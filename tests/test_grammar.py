"""Grammar compiler units: JSON schema → char DFA → token FSM.

Pure-CPU tests for diagnosis/grammar.py: the regex-AST construction,
determinization + dead-end pruning, the byte-tokenizer lift, and the
Verdict grammar's render/parse round trip.  The engine-level property
(every *sampled* sequence parses) lives in test_diagnosis.py.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from k8s_llm_monitor_tpu.diagnosis.grammar import (
    VERDICT_SCHEMA, CharDFA, GrammarError, TokenFSM, compile_schema,
    parse_verdict, render_verdict, token_fsm, verdict_dfa, verdict_fsm)
from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer


def encode_chars(text: str) -> list[int]:
    """The ByteTokenizer char→token lift the FSM is built against."""
    return [ord(c) + 3 for c in text]


# -- schema → char DFA -------------------------------------------------------


def test_enum_dfa_matches_exactly():
    dfa = compile_schema({"enum": ["info", "warning", "critical"]})
    assert dfa.matches('"info"')
    assert dfa.matches('"critical"')
    assert not dfa.matches('"INFO"')
    assert not dfa.matches('"inf"')
    assert not dfa.matches('"info" ')
    assert not dfa.matches("info")


def test_string_dfa_enforces_length_and_charset():
    dfa = compile_schema({"type": "string", "minLength": 2, "maxLength": 4})
    assert dfa.matches('"ab"')
    assert dfa.matches('"abcd"')
    assert not dfa.matches('"a"')        # below minLength
    assert not dfa.matches('"abcde"')    # above maxLength
    assert not dfa.matches('"a\\"b"')    # escapes are outside the charset
    assert not dfa.matches('"a\nb"')


def test_number_dfa_bounded_decimal():
    dfa = compile_schema({"type": "number"})
    for good in ("0", "7", "123456", "-3", "0.25", "-12.3456"):
        assert dfa.matches(good), good
    for bad in ("00", "1.", ".5", "1e3", "-", "1.23456", "1234567"):
        assert not dfa.matches(bad), bad


def test_boolean_integer_array_dfas():
    assert compile_schema({"type": "boolean"}).matches("true")
    assert not compile_schema({"type": "boolean"}).matches("True")
    ints = compile_schema({"type": "integer"})
    assert ints.matches("-42") and not ints.matches("007")
    arr = compile_schema({"type": "array",
                          "items": {"type": "integer"}, "maxItems": 2})
    assert arr.matches("[]") and arr.matches("[1,2]")
    assert not arr.matches("[1,2,3]") and not arr.matches("[1,]")


def test_object_dfa_fixed_key_order():
    dfa = compile_schema({
        "type": "object",
        "properties": {"a": {"type": "integer"},
                       "b": {"enum": ["x", "y"]}},
        "required": ["a", "b"],
    })
    assert dfa.matches('{"a":1,"b":"x"}')
    # Canonical form: no whitespace, declared key order, no omissions.
    assert not dfa.matches('{"b":"x","a":1}')
    assert not dfa.matches('{"a": 1,"b":"x"}')
    assert not dfa.matches('{"a":1}')


def test_unsupported_schemas_raise():
    with pytest.raises(GrammarError):
        compile_schema({"type": "object", "properties": {}})
    with pytest.raises(GrammarError):
        compile_schema({"type": "null"})
    with pytest.raises(GrammarError):
        compile_schema({"enum": [1, 2]})
    with pytest.raises(GrammarError):
        compile_schema({"type": "string", "maxLength": 0})


def test_max_path_len_bounded_and_unbounded():
    dfa = compile_schema({"enum": ["no", "yes"]})
    assert dfa.max_path_len() == len('"yes"')
    looped = CharDFA(trans=[{"a": 0}], accept=[True])
    assert looped.max_path_len() == -1


# -- token lift --------------------------------------------------------------


def test_token_fsm_free_row_and_start():
    fsm = token_fsm(compile_schema({"enum": ["ok"]}))
    assert fsm.start == 1
    assert np.all(fsm.trans[0] == 0)          # FREE state allows everything
    assert fsm.step(0, 123) == 0              # ... and self-loops
    assert fsm.max_len == len('"ok"') + 1     # chars + EOS


def test_token_fsm_walk_accepts_and_rejects():
    fsm = token_fsm(compile_schema({"enum": ["ok", "bad"]}))
    state = fsm.walk(encode_chars('"ok"'))
    assert state >= 1 and fsm.accept[state]
    # Accept state: only EOS self-loops; any other token is disallowed.
    assert fsm.step(state, fsm.eos_id) == state
    allowed = fsm.allowed(state)
    assert allowed[fsm.eos_id] and allowed.sum() == 1
    assert fsm.walk(encode_chars('"nope"')) == -1
    # walk resumes from an explicit state (preemption re-admission path).
    mid = fsm.walk(encode_chars('"o'))
    assert fsm.walk(encode_chars('k"'), state=mid) == state


def test_token_fsm_rejects_out_of_vocab():
    fsm = token_fsm(compile_schema({"enum": ["ok"]}))
    assert fsm.step(fsm.start, fsm.vocab_size + 5) == -1
    with pytest.raises(GrammarError):
        token_fsm(compile_schema({"enum": ["ok"]}), vocab_size=10)


def test_from_table_validates_shape_and_free_row():
    trans = np.zeros((3, 8), dtype=np.int32)
    trans[1:] = -1
    trans[1, 2] = 2
    fsm = TokenFSM.from_table(trans, start=1,
                              accept=np.array([False, False, True]),
                              eos_id=2)
    assert fsm.n_states == 3
    with pytest.raises(GrammarError):
        TokenFSM.from_table(trans, start=0, accept=[True] * 3, eos_id=2)
    bad = trans.copy()
    bad[0, 3] = -1
    with pytest.raises(GrammarError):
        TokenFSM.from_table(bad, start=1, accept=[False] * 3, eos_id=2)


# -- the Verdict grammar -----------------------------------------------------


def test_render_verdict_round_trips():
    text = render_verdict("critical", "default/web",
                          "container OOMKilled under memory pressure",
                          "raise the memory limit", 0.87)
    v = parse_verdict(text)
    assert v["severity"] == "critical"
    assert v["component"] == "default/web"
    assert v["confidence"] == 0.87


def test_render_verdict_clamps_hostile_fields():
    text = render_verdict("catastrophic", 'x" * 99', "a\nb\"c\\d" + "e" * 500,
                          "", 7.5)
    v = parse_verdict(text)
    assert v["severity"] == "warning"          # invalid severity coerced
    assert '"' not in v["component"]
    assert len(v["root_cause"]) <= 160
    assert v["recommendation"] == "n/a"        # empty field backfilled
    assert v["confidence"] == 1.0              # clamped into [0, 1]


def test_parse_verdict_rejects_almost_json():
    good = render_verdict("info", "c", "r", "fix", 0.5)
    for bad in (good[:-1], good.replace(":", ": ", 1),
                '{"severity":"info"}', "not json at all",
                good.replace('"info"', '"urgent"')):
        with pytest.raises(GrammarError):
            parse_verdict(bad)
    # Leading/trailing whitespace is stripped before validation.
    assert parse_verdict("  " + good + "\n")["severity"] == "info"


def test_verdict_fsm_cached_and_sized_for_byte_vocab():
    tok = ByteTokenizer()
    fsm = verdict_fsm(eos_id=tok.eos_id)
    assert fsm is verdict_fsm(eos_id=tok.eos_id)     # cache hit
    assert fsm.vocab_size == ByteTokenizer.vocab_size
    assert fsm.max_len == verdict_dfa().max_path_len() + 1
    # Every canonical rendering must thread the token FSM to acceptance.
    text = render_verdict("warning", "kube-system/dns", "lookup timeouts",
                          "restart coredns", 0.4)
    state = fsm.walk(encode_chars(text))
    assert state >= 1 and fsm.accept[state]
    assert len(text) + 1 <= fsm.max_len


def test_verdict_grammar_fuzz_renderings_always_parse():
    rng = np.random.default_rng(0)
    alphabet = np.array(list(
        "abc XYZ123/.-_:\"\\\n\t{}[]üé" + chr(7)))
    severities = ["info", "warning", "critical", "fatal", ""]
    for i in range(200):
        fields = ["".join(rng.choice(alphabet, size=rng.integers(0, 80)))
                  for _ in range(3)]
        text = render_verdict(severities[i % len(severities)], fields[0],
                              fields[1], fields[2],
                              float(rng.normal(0.5, 2.0)))
        v = parse_verdict(text)  # must never raise
        assert set(v) == {"severity", "component", "root_cause",
                          "recommendation", "confidence"}
        assert json.loads(text) == v
