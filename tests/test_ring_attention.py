"""Ring attention (seq-axis sequence parallelism) vs dense causal attention,
on the virtual 8-CPU mesh, incl. GQA, full-model forward, and the train step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.ops.attention import causal_attention
from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh
from k8s_llm_monitor_tpu.parallel.ring_attention import make_ring_attention

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")


def _mesh(data=2, seq=2, model=2):
    return create_mesh(MeshConfig(data=data, seq=seq, model=model),
                       devices=jax.devices()[: data * seq * model])


@pytest.mark.parametrize("H,KVH", [(4, 4), (8, 2)])
def test_ring_matches_dense(H, KVH):
    mesh = _mesh()
    rng = np.random.default_rng(H * 10 + KVH)
    B, S, D = 4, 32, 16
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, KVH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, KVH, D)), jnp.float32)

    want = causal_attention(q, k, v)
    ring = make_ring_attention(mesh)
    spec = NamedSharding(mesh, P("data", "seq", "model"))
    kv_spec = NamedSharding(mesh, P("data", "seq", None))
    got = jax.jit(ring)(jax.device_put(q, spec), jax.device_put(k, kv_spec),
                        jax.device_put(v, kv_spec))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_kv_len_mask():
    mesh = _mesh()
    rng = np.random.default_rng(3)
    B, S, H, D = 4, 16, 4, 8
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    kv_len = jnp.asarray([16, 9, 5, 12], jnp.int32)

    want = causal_attention(q, k, v, kv_len=kv_len)
    got = jax.jit(make_ring_attention(mesh))(q, k, v, kv_len=kv_len)
    # positions past kv_len have no valid keys in `want` either only when
    # q_pos < kv_len; compare the valid region.
    for b in range(B):
        n = int(kv_len[b])
        np.testing.assert_allclose(np.asarray(got)[b, :n],
                                   np.asarray(want)[b, :n],
                                   rtol=2e-5, atol=2e-5)


def test_full_model_forward_with_ring():
    cfg = ModelConfig(name="t", vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_layers=2, num_heads=4,
                      num_kv_heads=2, dtype="float32", rope_theta=1e4)
    mesh = _mesh()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 128, (4, 32)), jnp.int32)

    want = llama.forward_full(params, cfg, tokens)
    ring = make_ring_attention(mesh)
    got = jax.jit(
        lambda p, t: llama.forward_full(p, cfg, t, attn_fn=ring)
    )(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-4, atol=5e-4)


def test_train_step_with_ring_attention():
    from k8s_llm_monitor_tpu.training import (
        TrainConfig,
        create_train_state,
        make_train_step,
        shard_train_state,
    )
    from k8s_llm_monitor_tpu.training.train import data_spec

    cfg = ModelConfig(name="t", vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_layers=2, num_heads=4,
                      num_kv_heads=2, dtype="float32", rope_theta=1e4)
    mesh = _mesh()
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 128, (4, 32)), jnp.int32)

    def run(tc, use_mesh):
        state = create_train_state(jax.random.PRNGKey(0), cfg, tc)
        state = shard_train_state(state, mesh)
        step = make_train_step(cfg, tc, mesh=mesh if use_mesh else None)
        toks = jax.device_put(tokens, NamedSharding(mesh, data_spec()))
        _, _, loss = step(state.params, state.opt_state, toks)
        return float(loss)

    dense = run(TrainConfig(), False)
    ring = run(TrainConfig(ring_attention=True), True)
    assert np.isfinite(ring)
    np.testing.assert_allclose(ring, dense, rtol=1e-4)
