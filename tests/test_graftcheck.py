"""graftcheck suite: AST rules, trace-time guards, lock discipline.

Three layers, mirroring k8s_llm_monitor_tpu/devtools/:

  * astlint — every rule gets a seeded-violation positive and a clean
    negative, plus suppression and parse-error behavior;
  * traceguard — the recompile guard proves zero new compilations across
    same-bucket re-invocations on both decode paths, and (the control)
    that a deliberate bucket miss IS counted;
  * lockcheck — cycle detection, long-hold flagging, guarded-write
    tracking, and the disabled-mode fast path.
"""

from __future__ import annotations

import textwrap
import threading
import time

import pytest

from k8s_llm_monitor_tpu.devtools import astlint, lockcheck


def lint(src: str, rule: str | None = None):
    findings = astlint.lint_source(textwrap.dedent(src), path="snippet.py")
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


# -- astlint: jit-host-read --------------------------------------------------


def test_jit_host_read_flags_time_in_jit_body():
    src = """
    import jax, time

    @jax.jit
    def step(x):
        t = time.time()
        return x + t
    """
    assert len(lint(src, "jit-host-read")) == 1


def test_jit_host_read_flags_env_and_rng_seed():
    src = """
    import jax, os, random

    @jax.jit
    def step(x):
        flag = os.environ["K8SLLM_DEBUG"]
        random.seed(0)
        return x
    """
    assert len(lint(src, "jit-host-read")) == 2


def test_jit_host_read_sees_functools_partial_and_wrapping():
    src = """
    import functools, jax, time

    @functools.partial(jax.jit, donate_argnums=(0,))
    def a(x):
        return x + time.monotonic()

    def b(x):
        return x + time.perf_counter()

    b = jax.jit(b)
    """
    assert len(lint(src, "jit-host-read")) == 2


def test_jit_host_read_clean_outside_jit():
    src = """
    import time

    def host_loop():
        return time.time()
    """
    assert lint(src, "jit-host-read") == []


# -- astlint: lock-blocking-call ---------------------------------------------


def test_lock_blocking_call_flags_sleep_under_lock():
    src = """
    import time

    def f(self):
        with self._lock:
            time.sleep(1.0)
    """
    assert len(lint(src, "lock-blocking-call")) == 1


def test_lock_blocking_call_flags_device_get_and_join():
    src = """
    import jax

    def f(self, t):
        with self._handles_lock:
            x = jax.device_get(t)
            self._thread.join()
        return x
    """
    assert len(lint(src, "lock-blocking-call")) == 2


def test_lock_blocking_call_ignores_nested_defs_and_no_lock():
    src = """
    import time

    def f(self):
        with self._lock:
            def later():
                time.sleep(1.0)   # runs after the lock is gone
            self.cb = later
        time.sleep(0.1)           # not under a lock
    """
    assert lint(src, "lock-blocking-call") == []


# -- astlint: bare-except ----------------------------------------------------


def test_bare_except_flags_bare_and_swallowed_base_exception():
    src = """
    def f():
        try:
            g()
        except:
            pass

    def h():
        try:
            g()
        except BaseException:
            log()
    """
    assert len(lint(src, "bare-except")) == 2


def test_bare_except_allows_reraise_and_narrow():
    src = """
    def f():
        try:
            g()
        except BaseException:
            cleanup()
            raise

    def h():
        try:
            g()
        except Exception:
            pass
    """
    assert lint(src, "bare-except") == []


# -- astlint: mutable-default ------------------------------------------------


def test_mutable_default_flags_literals_and_constructors():
    src = """
    import collections

    def f(a=[], b={}, c=set(), d=collections.defaultdict(list)):
        return a, b, c, d
    """
    assert len(lint(src, "mutable-default")) == 4


def test_mutable_default_allows_none_and_tuples():
    src = """
    def f(a=None, b=(), c="x", d=frozenset()):
        return a, b, c, d
    """
    assert lint(src, "mutable-default") == []


# -- astlint: fault-point ----------------------------------------------------


def test_fault_point_flags_unknown_name():
    src = """
    def f(self):
        self._faults.maybe_raise("decode_dispach")  # typo'd point
    """
    assert len(lint(src, "fault-point")) == 1


def test_fault_point_allows_registered_names():
    src = """
    def f(self, injector):
        self._faults.maybe_raise("decode_dispatch")
        if injector.should_fire("kube_http_5xx"):
            return
        injector.delay_s("slow_host_callback")
    """
    assert lint(src, "fault-point") == []


def test_fault_point_hinted_receivers_only():
    src = """
    def f(fault, parser):
        fault.arm("bogus_point")      # fault-ish receiver: checked
        parser.arm("not_a_fault")     # unrelated .arm(): ignored
    """
    assert len(lint(src, "fault-point")) == 1


# -- astlint: raw-lock -------------------------------------------------------


def test_raw_lock_flags_threading_lock_and_rlock():
    src = """
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self._reentrant = threading.RLock()
    """
    assert len(lint(src, "raw-lock")) == 2


def test_raw_lock_flags_from_imports_and_aliases():
    src = """
    from threading import Lock, RLock as RL

    a = Lock()
    b = RL()
    """
    assert len(lint(src, "raw-lock")) == 2


def test_raw_lock_clean_for_make_lock_and_other_primitives():
    src = """
    import threading

    from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock

    class S:
        def __init__(self):
            self._lock = make_lock("s")
            self._stop = threading.Event()
            self._cv = threading.Condition(self._lock)
    """
    assert lint(src, "raw-lock") == []


def test_raw_lock_exempts_the_lockcheck_factory_itself():
    src = textwrap.dedent("""
    import threading

    def make_lock(name):
        return threading.Lock()
    """)
    findings = astlint.lint_source(src, path="devtools/lockcheck.py")
    assert [f for f in findings if f.rule == "raw-lock"] == []
    findings = astlint.lint_source(src, path="somewhere/else.py")
    assert len([f for f in findings if f.rule == "raw-lock"]) == 1


def test_raw_lock_line_suppression():
    src = """
    import threading

    _probe = threading.Lock()  # graftcheck: disable=raw-lock -- boot probe
    """
    assert lint(src, "raw-lock") == []


# -- astlint: unconstrained-model-parse --------------------------------------


def test_unconstrained_parse_flags_backend_classes():
    src = """
    import json

    class MyBackend:
        def generate(self, prompt):
            raw = self._call(prompt)
            return json.loads(raw)
    """
    assert len(lint(src, "unconstrained-model-parse")) == 1


def test_unconstrained_parse_flags_model_output_markers():
    src = """
    from json import loads

    def handle(answer_text):
        verdict = loads(answer_text)
        return verdict
    """
    assert len(lint(src, "unconstrained-model-parse")) == 1


def test_unconstrained_parse_ignores_request_bodies_and_non_llm():
    src = """
    import json

    class KubeRestBackend:  # no generate(): not an LLM adapter
        def list_pods(self, raw):
            return json.loads(raw)

    def _read_json(handler):
        raw = handler.rfile.read(10)
        return json.loads(raw)
    """
    assert lint(src, "unconstrained-model-parse") == []


def test_unconstrained_parse_exempts_grammar_module():
    src = textwrap.dedent("""
    import json

    def parse_verdict(answer):
        return json.loads(answer)
    """)
    findings = astlint.lint_source(src, path="diagnosis/grammar.py")
    assert [f for f in findings
            if f.rule == "unconstrained-model-parse"] == []
    findings = astlint.lint_source(src, path="monitor/analysis.py")
    assert len([f for f in findings
                if f.rule == "unconstrained-model-parse"]) == 1


def test_unconstrained_parse_line_suppression():
    src = """
    import json

    class CompatBackend:
        def generate(self, prompt):
            data = json.loads(self._post(prompt))  # graftcheck: disable=unconstrained-model-parse -- envelope
            return data["choices"][0]
    """
    assert lint(src, "unconstrained-model-parse") == []


def test_unconstrained_parse_sees_through_strip_chains():
    src = """
    import json

    def f(completion):
        return json.loads(completion.strip())
    """
    assert len(lint(src, "unconstrained-model-parse")) == 1


# -- astlint: tenant-namespace -----------------------------------------------


def test_tenant_namespace_flags_bare_prefix_cache_calls():
    src = """
    def admit(pc, prompt, blocks):
        shared, toks = pc.lookup(prompt)
        pc.register(prompt, blocks)
        digests = self.prefix_cache.digest_chain(prompt, 3)
    """
    assert len(lint(src, "tenant-namespace")) == 3


def test_tenant_namespace_flags_tier_put_and_blob_moves():
    src = """
    def spill(tier, digest, rows, owner, target, blob, prompt):
        tier.put(digest, rows)
        b = owner.fetch_prefix(prompt)
        target.install_prefix(b)
        e = owner.export_prefix(prompt)
    """
    assert len(lint(src, "tenant-namespace")) == 4


def test_tenant_namespace_clean_with_tenant_kwargs():
    src = """
    def admit(pc, tier, owner, target, prompt, blocks, digest, rows, blob):
        shared, toks = pc.lookup(prompt, tenant="a")
        pc.register(prompt, blocks, tenant="a")
        tier.put(digest, rows, tenant="a")
        b = owner.fetch_prefix(prompt, tenant="a")
        target.install_prefix(b, expected_tenant="a")
        e = owner.export_prefix(prompt, tenant="a")
        target.install_prefix(e, **kw)  # splat: assumed threaded
    """
    assert lint(src, "tenant-namespace") == []


def test_tenant_namespace_ignores_unrelated_receivers():
    src = """
    import atexit

    def other(tracer, registry, q):
        trace = tracer.lookup(q)           # not a prefix cache
        atexit.register(close)             # not a prefix cache
        registry.put("k", 1)               # not a KV tier
    """
    assert lint(src, "tenant-namespace") == []


def test_tenant_namespace_exempts_defining_modules():
    src = """
    def digest_chain(self, prompt):
        return self._cache.lookup(prompt)
    """
    import textwrap

    from k8s_llm_monitor_tpu.devtools.astlint import lint_source
    findings = lint_source(textwrap.dedent(src),
                           path="k8s_llm_monitor_tpu/serving/kv_cache.py")
    assert [f for f in findings if f.rule == "tenant-namespace"] == []


def test_tenant_namespace_live_repo_clean_without_suppressions():
    """The privacy invariant's second enforcement layer: every prefix-KV
    call site in the live tree threads the tenant, and none of them hides
    behind a suppression comment."""
    import pathlib

    root = pathlib.Path(astlint.__file__).resolve().parents[2]
    rule = astlint.TenantNamespaceRule()
    offenders = []
    for sub in ("k8s_llm_monitor_tpu", "tests", "bench.py"):
        for p in astlint.iter_py_files(root / sub):
            src = p.read_text(encoding="utf-8")
            per_line, per_file = astlint._suppressions(src)
            suppressed = per_file | set().union(*per_line.values(), set())
            assert rule.name not in suppressed, \
                f"{p}: {rule.name} suppression is not allowed"
            offenders += astlint.lint_source(src, str(p), rules=[rule])
    assert offenders == [], [f.human() for f in offenders]


# -- astlint: raw-kube-write -------------------------------------------------


def test_raw_kube_write_flags_mutation_verbs():
    src = """
    def handler(backend):
        backend.delete_pod("ns", "pod-1")
        backend.cordon_node("node-a")
        backend.rollout_restart("ns", "web")
        backend.scale_statefulset("ns", "db", 3)
        backend.list_pods("ns")  # read: clean
    """
    findings = lint(src, "raw-kube-write")
    assert len(findings) == 4
    assert all("sanctioned" in f.message or "guard" in f.message
               or "RemediationEngine" in f.message for f in findings)


def test_raw_kube_write_flags_raw_rest_writes():
    src = """
    def poke(self):
        self._request("/api/v1/pods/x", None, method="DELETE")
        self._request("/apis/apps/v1/d", None, method="PATCH", body=b"{}")
        self._request("/api/v1/pods", None)           # GET: clean
        self._request("/version", None, method="GET")  # read: clean
    """
    assert len(lint(src, "raw-kube-write")) == 2


def test_raw_kube_write_exempts_executors_backends_and_tests():
    src = textwrap.dedent("""
    def act(backend):
        backend.delete_pod("ns", "p")
    """)
    for path in ("k8s_llm_monitor_tpu/remediation/executor.py",
                 "k8s_llm_monitor_tpu/fleet/autoscaler.py",
                 "k8s_llm_monitor_tpu/monitor/kube_rest.py",
                 "k8s_llm_monitor_tpu/monitor/cluster.py",
                 "tests/test_remediation.py"):
        findings = astlint.lint_source(src, path=path)
        assert [f for f in findings if f.rule == "raw-kube-write"] == [], path
    findings = astlint.lint_source(src, path="monitor/server.py")
    assert [f for f in findings if f.rule == "raw-kube-write"]


def test_raw_kube_write_live_repo_clean_without_suppressions():
    """Satellite acceptance: every cluster mutation in the live tree flows
    through the sanctioned executors, and none hides behind a suppression
    comment."""
    import pathlib

    root = pathlib.Path(astlint.__file__).resolve().parents[2]
    rule = astlint.RawKubeWriteRule()
    offenders = []
    for sub in ("k8s_llm_monitor_tpu", "tests", "bench.py"):
        for p in astlint.iter_py_files(root / sub):
            src = p.read_text(encoding="utf-8")
            per_line, per_file = astlint._suppressions(src)
            suppressed = per_file | set().union(*per_line.values(), set())
            assert rule.name not in suppressed, \
                f"{p}: {rule.name} suppression is not allowed"
            offenders += astlint.lint_source(src, str(p), rules=[rule])
    assert offenders == [], [f.human() for f in offenders]


# -- astlint: suppressions + parse errors ------------------------------------


def test_line_suppression_silences_one_rule():
    src = """
    def f(a=[]):  # graftcheck: disable=mutable-default -- frozen at import
        return a
    """
    assert lint(src) == []


def test_file_suppression_silences_everything():
    src = """
    # graftcheck: disable-file=all
    def f(a=[]):
        try:
            return a
        except:
            pass
    """
    assert lint(src) == []


def test_suppression_of_other_rule_does_not_silence():
    src = """
    def f(a=[]):  # graftcheck: disable=bare-except
        return a
    """
    assert len(lint(src, "mutable-default")) == 1


def test_syntax_error_becomes_parse_error_finding():
    findings = lint("def f(:\n    pass\n")
    assert [f.rule for f in findings] == ["parse-error"]


# -- graftcheck CLI ----------------------------------------------------------


def test_cli_exit_codes(tmp_path, capsys):
    from k8s_llm_monitor_tpu.devtools import graftcheck

    bad = tmp_path / "bad.py"
    bad.write_text("def f(a=[]):\n    return a\n")
    good = tmp_path / "good.py"
    good.write_text("def f(a=None):\n    return a\n")

    assert graftcheck.main([str(good)]) == 0
    assert graftcheck.main([str(bad)]) == 1
    assert graftcheck.main([str(bad), "--json"]) == 1
    out = capsys.readouterr().out
    assert '"mutable-default"' in out
    assert graftcheck.main(["--list-rules"]) == 0


# -- traceguard: recompile guard ---------------------------------------------


@pytest.mark.slow  # builds a real engine (~15s); tier-1 is within ~40s of
# its timeout budget, so the trace gates run via `make lint-trace` + `make test`
@pytest.mark.parametrize("decode_path", ["gather", "fused", "mesh", "quant",
                                         "grammar_swap"])
def test_same_bucket_reinvocation_compiles_nothing(decode_path):
    """The acceptance gate: warm both prefill programs + the decode ladder,
    then rerun same-shaped requests with different content — the program
    caches must not grow and no backend compile may fire.  The "mesh" path
    runs the same gate on a GSPMD TP-8 engine over the forced 8-host-device
    mesh (sharded weights + head-sharded KV pages), proving zero recompiles
    and donated page-pool/token-state rebinding survive sharding.  The
    "quant" path runs it on the int8-KV engine, where the donation set also
    carries the per-page scale leaves."""
    from k8s_llm_monitor_tpu.devtools import traceguard

    report = traceguard.check_path(decode_path)
    assert report.warm_compiles > 0          # warm-up really compiled
    assert report.repeat_compiles == 0, report.as_dict()
    assert not any(report.forbidden.values()), report.forbidden
    assert report.donated_pages_rebound and report.donated_tokens_rebound
    assert report.donated_scales_rebound
    if decode_path == "quant":
        assert report.kv_quant == "int8"
    assert report.ok


@pytest.mark.slow  # builds a real engine; see note above
def test_bucket_miss_is_counted():
    """Control for the zero above: a prompt that lands in the NEXT prefill
    bucket must register as new compilation — proving the counter can see
    compiles at all, so its zero on the repeat pass means something."""
    from k8s_llm_monitor_tpu.devtools import traceguard

    engine = traceguard.build_engine("gather")
    warm_c, _ = traceguard.count_new_compiles(
        engine, lambda: traceguard._drive(engine, 12, greedy=True, tag=1))
    assert warm_c > 0
    miss_c, _ = traceguard.count_new_compiles(
        engine, lambda: traceguard._drive(engine, 20, greedy=True, tag=2))
    assert miss_c > 0, "bucket-32 prefill should have compiled a new program"


def test_forbidden_ops_detects_host_callbacks():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from k8s_llm_monitor_tpu.devtools import traceguard

    def leaky(x):
        return jax.pure_callback(
            np.sin, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    jaxpr = jax.make_jaxpr(jax.jit(leaky))(jnp.ones((4,), jnp.float32))
    hits = traceguard.forbidden_ops(jaxpr)
    assert any("pure_callback" in h for h in hits)

    jaxpr_clean = jax.make_jaxpr(jax.jit(lambda x: x * 2))(
        jnp.ones((4,), jnp.float32))
    assert traceguard.forbidden_ops(jaxpr_clean) == []


# -- lockcheck ---------------------------------------------------------------


@pytest.fixture
def armed_lockcheck(monkeypatch):
    """Enable instrumentation and hand the test a private registry so the
    session-level gate (conftest.pytest_sessionfinish) never sees the
    violations these tests provoke on purpose."""
    monkeypatch.setenv(lockcheck.ENV_FLAG, "1")
    reg = lockcheck.Registry()

    def make(name, reentrant=False):
        return lockcheck.InstrumentedLock(name, reentrant=reentrant, reg=reg)

    yield make, reg


def test_lock_order_cycle_detected(armed_lockcheck):
    make, reg = armed_lockcheck
    a, b = make("A"), make("B")
    with a:
        with b:
            pass
    with b:
        with a:     # opposite order: the A->B + B->A edges close a cycle
            pass
    assert reg.cycles() == [["A", "B"]]
    assert not reg.report()["ok"]
    with pytest.raises(AssertionError, match="cycle"):
        reg.assert_clean()


def test_consistent_order_is_clean(armed_lockcheck):
    make, reg = armed_lockcheck
    a, b = make("A"), make("B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert reg.cycles() == []
    assert reg.report()["ok"]


def test_long_hold_flagged(armed_lockcheck, monkeypatch):
    make, reg = armed_lockcheck
    monkeypatch.setenv(lockcheck.ENV_HOLD_MS, "1")
    lk = make("slowpoke")
    with lk:
        time.sleep(0.01)
    assert reg.long_holds and reg.long_holds[0].lock == "slowpoke"
    # long holds are advisory: they do not flip ok
    assert reg.report()["ok"]


def test_rlock_reentry_records_no_self_edge(armed_lockcheck):
    make, reg = armed_lockcheck
    lk = make("R", reentrant=True)
    with lk:
        with lk:
            pass
    assert reg.cycles() == []
    assert all(a != b for (a, b) in reg.edges)


def test_release_by_non_owner_raises(armed_lockcheck):
    make, _ = armed_lockcheck
    lk = make("owned")
    lk.acquire()
    err: list[BaseException] = []

    def rogue():
        try:
            lk.release()
        except RuntimeError as e:
            err.append(e)

    t = threading.Thread(target=rogue)
    t.start()
    t.join()
    lk.release()
    assert err and "non-owner" in str(err[0])


def test_guarded_by_catches_unlocked_write(monkeypatch):
    monkeypatch.setenv(lockcheck.ENV_FLAG, "1")
    reg = lockcheck.Registry()

    @lockcheck.guarded_by("_lock", "count")
    class Box:
        def __init__(self):
            self.count = 0  # pre-lock: construction, exempt
            self._lock = lockcheck.InstrumentedLock("box", reg=reg)

        def good(self):
            with self._lock:
                self.count += 1

        def bad(self):
            self.count += 1

    # guarded_by records into the global registry; point it at ours.
    monkeypatch.setattr(lockcheck, "_registry", reg)
    box = Box()
    box.good()
    assert reg.report()["ok"]
    box.bad()
    writes = reg.report()["unguarded_writes"]
    assert writes and writes[0]["attr"] == "count" and writes[0]["cls"] == "Box"


def test_disabled_mode_is_plain_locks(monkeypatch):
    monkeypatch.delenv(lockcheck.ENV_FLAG, raising=False)
    assert not lockcheck.enabled()
    lk = lockcheck.make_lock("plain")
    assert not isinstance(lk, lockcheck.InstrumentedLock)

    @lockcheck.guarded_by("_lock", "x")
    class C:
        pass

    # decorator is an identity when disabled: no __setattr__ wrapper
    assert "__setattr__" not in C.__dict__
