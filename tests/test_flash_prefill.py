"""Flash paged prefill: tiled online-softmax kernel vs the dense oracle.

Three layers of parity, every one greedy-token- or numerically-exact:

  * op level — ``flash_prefill_attention`` (interpret mode) against
    ``paged_verify_attention`` (gather + dense causal attention, the XLA
    oracle) across ragged start/length grids, quantized pools, and
    causal-mask fuzz pinned to the query-tile boundaries;
  * engine level — a flash engine and a dense engine decode the same
    prompts to identical token ids across all three KV tiers
    (fp32 pool, int8, fp8), covering fresh prefill AND chunked
    continuation (prompts longer than the top bucket);
  * mesh level — TP-8 on the virtual CPU mesh, flash vs dense, same ids.

Plus the selection-oracle semantics (``select_prefill_impl``) and the
flash-only bucket-ladder extension.  Runs in tier-1 (CPU, not slow) and
in ``make tier1-mesh``.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import PRESETS, ModelConfig
from k8s_llm_monitor_tpu.ops.attention import (
    paged_verify_attention,
    select_prefill_impl,
)
from k8s_llm_monitor_tpu.ops.pallas_attention import flash_prefill_attention
from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    SamplingParams,
)

# vocab 32, not 256: greedy argmax margins in a random-weight toy scale
# inversely with vocab, and the quantized-tier parity test needs margins
# comfortably above int8 pool noise (~0.4%) to be seed-robust.
CFG = ModelConfig(name="t", vocab_size=32, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)

# KV heads = TP degree so pages shard without replication on the 8-device
# mesh (the same reason test_sharding.py uses 8/8 heads).
MESH_CFG = ModelConfig(name="t8", vocab_size=256, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=8,
                       num_kv_heads=8, dtype="float32", rope_theta=10_000.0)


# ---------------------------------------------------------------- op level

def _paged_case(seed, B, S, KVH, D, qpk, bs, max_blocks, num_blocks,
                starts, lengths):
    """Random pool + distinct-block tables + queries for one geometry."""
    rng = np.random.default_rng(seed)
    H = KVH * qpk
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((num_blocks, bs, KVH * D)),
                    jnp.float32)
    v = jnp.asarray(rng.standard_normal((num_blocks, bs, KVH * D)),
                    jnp.float32)
    # Distinct non-null blocks per lane: parity must hold for arbitrary
    # (non-contiguous) page placement, exactly like the real allocator's.
    tables = np.stack([
        rng.permutation(np.arange(1, num_blocks))[:max_blocks]
        for _ in range(B)
    ]).astype(np.int32)
    return (q, k, v, jnp.asarray(tables),
            jnp.asarray(starts, jnp.int32), jnp.asarray(lengths, jnp.int32))


def _assert_close(flash, oracle, lengths, S):
    # Only rows inside each lane's valid query range are defined output.
    for b, n in enumerate(np.asarray(lengths)):
        if n == 0:
            continue
        np.testing.assert_allclose(np.asarray(flash)[b, :n],
                                   np.asarray(oracle)[b, :n],
                                   rtol=2e-5, atol=2e-5)


def test_flash_matches_oracle_ragged_mixed_geometries():
    # One batch covering every serving geometry at once: fresh prefill
    # (start=0, full bucket), a continuation chunk (start=17), an inactive
    # lane (length 0), and a lane ending one token below block alignment
    # (start 15 + len 16 = 31 = 4*8 - 1).
    q, k, v, tables, starts, lengths = _paged_case(
        0, B=4, S=40, KVH=2, D=16, qpk=2, bs=8, max_blocks=12,
        num_blocks=40, starts=[0, 17, 33, 15], lengths=[40, 23, 0, 16])
    out = flash_prefill_attention(q, k, v, tables, starts, lengths,
                                  interpret=True)
    ref = paged_verify_attention(q, k, v, tables, starts, lengths)
    _assert_close(out, ref, lengths, S=40)


def test_flash_verify_geometry():
    # spec_k+1-token scoring pass: tiny S, nonzero starts.
    q, k, v, tables, starts, lengths = _paged_case(
        1, B=3, S=8, KVH=2, D=16, qpk=2, bs=8, max_blocks=8,
        num_blocks=24, starts=[0, 9, 31], lengths=[5, 8, 3])
    out = flash_prefill_attention(q, k, v, tables, starts, lengths,
                                  interpret=True)
    ref = paged_verify_attention(q, k, v, tables, starts, lengths)
    _assert_close(out, ref, lengths, S=8)


@pytest.mark.parametrize("S", [16, 32, 64])
def test_flash_causal_mask_fuzz_at_tile_boundaries(S):
    # Lengths pinned to +-1 around the TQ tile edges, where an off-by-one
    # in the causal bound or the dead-tile guard would first show up.
    tq = next(t for t in (128, 64, 32, 16, 8, 4, 2, 1) if S % t == 0)
    edges = sorted({max(ln, 0) for ln in
                    (tq - 1, tq, tq + 1, S - 1, S, 1, 0) if ln <= S})
    B = len(edges)
    q, k, v, tables, starts, lengths = _paged_case(
        S, B=B, S=S, KVH=2, D=8, qpk=1, bs=8, max_blocks=(S + 40) // 8,
        num_blocks=64, starts=[7 * i for i in range(B)], lengths=edges)
    out = flash_prefill_attention(q, k, v, tables, starts, lengths,
                                  interpret=True)
    ref = paged_verify_attention(q, k, v, tables, starts, lengths)
    _assert_close(out, ref, lengths, S=S)


def _quantize_pool(x, dtype):
    """Per-(token, kv-head) symmetric quantization of a fused-lane pool."""
    nb, bs, F = x.shape
    kvh = F // 8  # D=8 in the quant tests below
    xs = np.asarray(x).reshape(nb, bs, kvh, 8)
    amax = np.abs(xs).max(axis=-1)
    if dtype == "int8":
        scale = np.maximum(amax / 127.0, 1e-8)
        qs = np.clip(np.rint(xs / scale[..., None]), -127, 127)
        quant = jnp.asarray(qs.reshape(nb, bs, F), jnp.int8)
        deq = qs * scale[..., None]
    else:
        scale = np.maximum(amax / 448.0, 1e-8)
        qs = jnp.asarray((xs / scale[..., None]).reshape(nb, bs, F),
                         jnp.float32).astype(jnp.float8_e4m3fn)
        quant = qs
        deq = np.asarray(qs.astype(jnp.float32)).reshape(
            nb, bs, kvh, 8) * scale[..., None]
    return (quant, jnp.asarray(scale, jnp.float32),
            jnp.asarray(deq.reshape(nb, bs, F), jnp.float32))


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
def test_flash_quant_dequantizes_in_kernel(kv_dtype):
    if kv_dtype == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("jax build has no float8_e4m3fn")
    q, k, v, tables, starts, lengths = _paged_case(
        3, B=3, S=24, KVH=2, D=8, qpk=2, bs=8, max_blocks=8,
        num_blocks=32, starts=[0, 11, 27], lengths=[24, 13, 5])
    kq, ks, kd = _quantize_pool(k, kv_dtype)
    vq, vs, vd = _quantize_pool(v, kv_dtype)
    out = flash_prefill_attention(q, kq, vq, tables, starts, lengths,
                                  k_scale=ks, v_scale=vs, interpret=True)
    # Oracle: the same attention over the DEQUANTIZED pool — the kernel's
    # in-kernel scale application must be exact, not approximate.
    ref = paged_verify_attention(q, kd, vd, tables, starts, lengths)
    _assert_close(out, ref, lengths, S=24)


# ------------------------------------------------------------ engine level

ENGINE_KW = dict(max_slots=4, num_blocks=64, block_size=8,
                 max_blocks_per_seq=8, prefill_buckets=(16, 32),
                 max_prefills_per_step=2, max_admission_rounds=2,
                 decode_steps_per_iter=4, spec_k=0, prefix_cache_entries=0)

# 40 > the 32-token top bucket: lane 2 exercises chunked continuation
# prefill; 7 and 23 exercise intra-bucket padding; 12 the small bucket.
PROMPT_LENS = (12, 40, 7, 23)


def _greedy_ids(cfg, params, prefill_path, kv_dtype="auto", mesh=None):
    ecfg = EngineConfig(prefill_path=prefill_path, kv_dtype=kv_dtype,
                        **ENGINE_KW)
    eng = InferenceEngine(cfg, params, ecfg, eos_id=-1, mesh=mesh)
    rng = np.random.default_rng(5)
    prompts = [[int(t) for t in rng.integers(4, cfg.vocab_size - 4, size=n)]
               for n in PROMPT_LENS]
    res = eng.generate(prompts, SamplingParams(max_tokens=8, temperature=0.0))
    assert all(r.finish_reason != "error" for r in res)
    return [r.token_ids for r in res], eng


# The engine-level legs each build 2+ engines (~20 s of CPU compiles
# apiece), so they carry the slow marker: excluded from tier-1's
# `-m 'not slow'` budget, enforced by `make tier1-mesh` and the CI mesh
# job (neither filters markers).  The op-level parity and selection
# tests above stay in tier-1.
@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_engine_flash_matches_dense_greedy(kv_dtype):
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    flash_ids, eng = _greedy_ids(CFG, params, "flash", kv_dtype)
    assert eng.prefill_path == "flash"
    # The admission/chunk paths actually took flash rounds per bucket.
    assert eng.prefill_bucket_rounds and all(
        b in ENGINE_KW["prefill_buckets"] for b in eng.prefill_bucket_rounds)
    del eng
    dense_ids, eng_d = _greedy_ids(CFG, params, "dense", kv_dtype)
    assert eng_d.prefill_path == "dense"
    assert flash_ids == dense_ids


@pytest.mark.slow
def test_engine_fp8_flash_runs_clean_and_deterministic():
    # fp8 e4m3 pool noise (~5% relative) is ABOVE this toy model's greedy
    # margins, and the dense engine legitimately attends over the fresh
    # chunk's unquantized in-flight K/V while flash reads the quantized
    # pages (the pool never widens in HBM) — so token-exactness vs dense
    # is not an invariant for fp8.  Exact fp8 parity is proven at op
    # level against the dequantized-pool oracle above; here we pin the
    # engine plumbing: scale planes thread through all prefill
    # geometries, the flash path is the one taken, and the output is
    # bit-deterministic across engine rebuilds.
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    ids_a, eng = _greedy_ids(CFG, params, "flash", "fp8")
    assert eng.prefill_path == "flash"
    assert eng.prefill_bucket_rounds
    del eng
    ids_b, _ = _greedy_ids(CFG, params, "flash", "fp8")
    assert ids_a == ids_b
    assert all(len(t) == 8 for t in ids_a)


@pytest.mark.slow
def test_engine_tp8_flash_matches_dense(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(model=8))
    params = llama.init_params(jax.random.PRNGKey(0), MESH_CFG)
    flash_ids, eng = _greedy_ids(MESH_CFG, params, "flash", mesh=mesh)
    assert eng.prefill_path == "flash"
    del eng
    dense_ids, _ = _greedy_ids(MESH_CFG, params, "dense", mesh=mesh)
    assert flash_ids == dense_ids


# -------------------------------------------------------- selection oracle

def test_select_dense_returns_none_and_unknown_raises():
    assert select_prefill_impl(platform="cpu", cfg=CFG, mode="dense") is None
    with pytest.raises(ValueError, match="unknown prefill_path"):
        select_prefill_impl(platform="cpu", cfg=CFG, mode="wat")


def test_select_auto_stays_dense_off_tpu():
    # The interpreter is a de-optimization; auto only picks flash on TPU.
    assert select_prefill_impl(platform="cpu", cfg=CFG, mode="auto") is None


def test_select_forced_flash_off_tpu_interprets():
    impl = select_prefill_impl(platform="cpu", cfg=CFG, mode="flash")
    assert llama.is_flash_prefill_impl(impl)
    assert impl.keywords.get("interpret") is True


def test_select_forced_flash_rejects_attn_extras():
    g2 = PRESETS["gemma2-2b"]
    assert g2.has_attn_extras
    with pytest.raises(ValueError, match="can't take the flash kernel"):
        select_prefill_impl(platform="cpu", cfg=g2, mode="flash")


def test_select_flash_rejects_tp_not_dividing_kv_heads(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(model=8))
    assert CFG.num_kv_heads % 8 != 0
    with pytest.raises(ValueError, match="can't take the flash kernel"):
        select_prefill_impl(platform="cpu", cfg=CFG, mesh=mesh, mode="flash")
    assert select_prefill_impl(platform="cpu", cfg=CFG, mesh=mesh,
                               mode="auto") is None


def test_select_auto_on_tpu_gates_on_head_dim():
    # Simulated TPU platform: geometry decides without touching hardware.
    cfg128 = dataclasses.replace(CFG, num_heads=4, num_kv_heads=2,
                                 head_dim=128)
    assert select_prefill_impl(platform="tpu", cfg=cfg128,
                               mode="auto") is not None
    assert select_prefill_impl(platform="tpu", cfg=CFG, mode="auto") is None
    with pytest.raises(ValueError, match="can't take the flash kernel"):
        select_prefill_impl(platform="tpu", cfg=CFG, mode="flash")


def test_env_overrides_config_prefill_path(monkeypatch):
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    monkeypatch.setenv("K8SLLM_PREFILL_PATH", "dense")
    eng = InferenceEngine(CFG, params,
                          EngineConfig(prefill_path="flash", **ENGINE_KW),
                          eos_id=-1)
    assert eng.prefill_path == "dense"


@pytest.mark.slow
def test_flash_extends_bucket_ladder_capacity_capped():
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    kw = dict(ENGINE_KW, num_blocks=560, max_blocks_per_seq=520)
    # Capacity 520*8 = 4160 tokens: room for the 4096 bucket, not 8192.
    eng = InferenceEngine(CFG, params,
                          EngineConfig(prefill_path="flash", **kw),
                          eos_id=-1)
    assert eng.prefill_path == "flash"
    assert eng.ecfg.prefill_buckets == (16, 32, 4096)
    del eng
    # Dense keeps the caller's ladder; so does a flash engine whose pool
    # can't hold a 4096-token sequence (the default ENGINE_KW geometry).
    eng_d = InferenceEngine(CFG, params,
                            EngineConfig(prefill_path="dense", **kw),
                            eos_id=-1)
    assert eng_d.ecfg.prefill_buckets == (16, 32)
    del eng_d
    eng_s = InferenceEngine(CFG, params,
                            EngineConfig(prefill_path="flash", **ENGINE_KW),
                            eos_id=-1)
    assert eng_s.ecfg.prefill_buckets == (16, 32)
