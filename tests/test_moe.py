"""Mixture-of-experts (models/llama.py:_moe_mlp + expert-parallel specs).

The exactness anchor: with capacity high enough that nothing drops, the
GShard einsum dispatch must equal a brute-force per-token loop over the
selected experts.  Then: capacity drops pass the residual through, the
serving engine decodes MoE configs, training (CE + aux) learns, expert
specs shard over TP-8, and the Mixtral HF key map loads.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig

CFG = ModelConfig(name="tm", vocab_size=200, hidden_size=32,
                  intermediate_size=48, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0,
                  num_experts=4, num_experts_per_tok=2,
                  capacity_factor=8.0)   # no drops at test sizes


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _moe_reference(layer, cfg, x):
    """Per-token loop: softmax router, top-k renormalized, full SwiGLU per
    selected expert — no capacity, no einsums."""
    B, S, H = x.shape
    xt = np.asarray(x, np.float64).reshape(-1, H)
    router = np.asarray(layer["router"]["kernel"], np.float64)
    gk = np.asarray(layer["gate_e"]["kernel"], np.float64)
    uk = np.asarray(layer["up_e"]["kernel"], np.float64)
    dk = np.asarray(layer["down_e"]["kernel"], np.float64)
    out = np.zeros_like(xt)
    for t in range(xt.shape[0]):
        logits = xt[t] @ router
        p = np.exp(logits - logits.max())
        p /= p.sum()
        top = np.argsort(-p)[: cfg.num_experts_per_tok]
        w = p[top] / p[top].sum()
        for e, wi in zip(top, w):
            g = xt[t] @ gk[e]
            u = xt[t] @ uk[e]
            silu = g / (1.0 + np.exp(-g))
            out[t] += wi * ((silu * u) @ dk[e])
    return out.reshape(B, S, H)


def test_moe_mlp_matches_per_token_reference(params):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 5, 32)) * 0.5, jnp.float32)
    layer = params["layers"][0]
    got, aux = llama._moe_mlp(layer, CFG, x)
    want = _moe_reference(layer, CFG, x)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)
    assert float(aux) >= 1.0  # E * sum(f_i * p_i) >= 1 by Cauchy-Schwarz


def test_moe_capacity_drop_passes_residual():
    """capacity_factor ~ 0 forces drops in the TRAINING dispatch: the MLP
    contribution for dropped tokens must be exactly zero (the residual
    path carries them).  Identical input rows all route identically, so
    with C=1 only one token per (rank, expert) survives."""
    cfg = dataclasses.replace(CFG, capacity_factor=0.01)  # C = 1 per group
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(2)
    row = rng.standard_normal((1, 1, 32))
    x = jnp.asarray(np.repeat(row, 4, axis=1), jnp.float32)  # 4 equal toks
    y, _ = llama._moe_mlp(params["layers"][0], cfg, x)
    y = np.asarray(y).reshape(-1, 32)
    zero_rows = np.sum(np.all(y == 0.0, axis=-1))
    # One token kept per rank (same expert chain for all four): <= 2
    # nonzero rows, and at least one token must have been dropped.
    assert zero_rows >= 2
    assert zero_rows < 4


def test_moe_dropless_is_batch_independent():
    """The inference path must give a token the same MLP output regardless
    of co-batched tokens (no capacity coupling) and match the per-token
    reference exactly."""
    cfg = dataclasses.replace(CFG, capacity_factor=0.01)  # would drop hard
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((1, 8, 32)) * 0.5, jnp.float32)
    full = llama._moe_mlp_dropless(params["layers"][0], cfg, x)
    solo = llama._moe_mlp_dropless(params["layers"][0], cfg, x[:, 3:4])
    np.testing.assert_allclose(np.asarray(full[:, 3]), np.asarray(solo[:, 0]),
                               rtol=1e-5, atol=1e-6)
    want = _moe_reference(params["layers"][0], cfg, x)
    np.testing.assert_allclose(np.asarray(full), want, rtol=2e-4, atol=2e-5)


def test_moe_forward_aux_and_dense_consistency(params):
    """forward_full with and without return_aux must produce identical
    logits; aux is finite and positive."""
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(2, 200, size=(2, 8)), jnp.int32)
    a = llama.forward_full(params, CFG, tokens)
    b, aux = llama.forward_full(params, CFG, tokens, return_aux=True)
    # Training dispatch (capacity, nothing drops at cf=8) vs dropless
    # inference path: same math, different einsum orders.
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-4, atol=2e-4)
    assert np.isfinite(float(aux)) and float(aux) >= 1.0


def test_moe_engine_greedy_matches_naive(params):
    """The serving paths (prefill + paged decode + speculation) run the
    MoE MLP per layer; greedy engine output must equal naive forward."""
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )

    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,),
                     spec_k=4, spec_rounds_per_iter=2),
        eos_id=-1,
    )
    rng = np.random.default_rng(4)
    prompts = [list(rng.integers(2, 200, size=6)) for _ in range(2)]
    res = eng.generate(prompts, SamplingParams(max_tokens=8, temperature=0.0))
    for p, r in zip(prompts, res):
        seq = list(p)
        want = []
        for _ in range(8):
            lg = llama.forward_full(params, CFG,
                                    jnp.asarray([seq], jnp.int32))
            t = int(jnp.argmax(lg[0, -1]))
            seq.append(t)
            want.append(t)
        assert r.token_ids == want


def test_moe_train_step_learns():
    """CE + 0.01*aux trains end-to-end on the data mesh and the loss
    drops; aux keeps the router load-balanced enough to stay finite."""
    from jax.sharding import NamedSharding
    from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh
    from k8s_llm_monitor_tpu.training import (
        TrainConfig,
        create_train_state,
        make_train_step,
        shard_train_state,
    )
    from k8s_llm_monitor_tpu.training.train import data_spec

    mesh = create_mesh(MeshConfig(data=2, seq=1, model=4))
    tc = TrainConfig(learning_rate=3e-3)
    state = create_train_state(jax.random.PRNGKey(0), CFG, tc)
    state = shard_train_state(state, mesh)
    step = make_train_step(CFG, tc, mesh=mesh)
    rng = np.random.default_rng(5)
    tokens = jax.device_put(
        jnp.asarray(rng.integers(2, 200, size=(4, 16)), jnp.int32),
        NamedSharding(mesh, data_spec()))
    params, opt_state = state.params, state.opt_state
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.95, losses


def test_moe_expert_specs_shard_tp8():
    """Expert stacks shard their E axis over ``model``; TP-8 divides the
    8-expert production preset (eval_shape, no weights)."""
    from k8s_llm_monitor_tpu.models.config import PRESETS
    from k8s_llm_monitor_tpu.parallel.sharding import param_partition_specs

    cfg = PRESETS["mixtral-8x7b"]
    shapes = jax.eval_shape(lambda r: llama.init_params(r, cfg),
                            jax.random.PRNGKey(0))
    specs = param_partition_specs(shapes)
    lyr = specs["layers"][0]
    assert lyr["gate_e"]["kernel"][0] == "model"
    assert "model" not in tuple(lyr["router"]["kernel"])   # replicated
    for name in ("gate_e", "up_e", "down_e"):
        E = shapes["layers"][0][name]["kernel"].shape[0]
        assert E % 8 == 0


def test_moe_int8_expert_parity(params):
    """quantize_params int8 expert stacks: logits track the bf16 MoE model
    closely (same contract as the dense int8 parity tests) and the
    quantized tree serves through the engine."""
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.utils.quantize import quantize_params

    qp = quantize_params(params)
    lyr = qp["layers"][0]
    assert lyr["gate_e"]["kernel_q"].dtype == jnp.int8
    assert lyr["gate_e"]["scale"].shape == (CFG.num_experts,
                                            CFG.intermediate_size)
    assert "kernel" in lyr["router"]          # router stays bf16

    rng = np.random.default_rng(8)
    tokens = jnp.asarray(rng.integers(2, 200, size=(2, 8)), jnp.int32)
    a = llama.forward_full(params, CFG, tokens)
    b = llama.forward_full(qp, CFG, tokens)
    af, bf = np.asarray(a).reshape(-1), np.asarray(b).reshape(-1)
    cos = float(af @ bf / (np.linalg.norm(af) * np.linalg.norm(bf)))
    assert cos > 0.999, f"int8 MoE logits diverged (cosine {cos})"

    eng = InferenceEngine(
        CFG, qp,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,)),
        eos_id=-1)
    res = eng.generate([list(rng.integers(2, 200, size=6))],
                       SamplingParams(max_tokens=6, temperature=0.0))
    assert res[0].finish_reason == "length"


def test_moe_w8a8_expert_parity(params):
    """act_quant on int8 experts routes the MLP through the s8 x s8 einsum
    path; logits must track the bf16 model (same cosine contract as the
    dense W8A8 parity test)."""
    from k8s_llm_monitor_tpu.utils.quantize import quantize_params

    qp = quantize_params(params)
    cfg_aq = dataclasses.replace(CFG, act_quant=True)
    rng = np.random.default_rng(9)
    tokens = jnp.asarray(rng.integers(2, 200, size=(2, 8)), jnp.int32)
    a = llama.forward_full(params, CFG, tokens)
    b = llama.forward_full(qp, cfg_aq, tokens)
    af, bf = np.asarray(a).reshape(-1), np.asarray(b).reshape(-1)
    cos = float(af @ bf / (np.linalg.norm(af) * np.linalg.norm(bf)))
    assert cos > 0.995, f"W8A8 MoE logits diverged (cosine {cos})"


def test_moe_int8_specs_shard_tp8():
    """Quantized expert leaves (kernel_q [E,in,out], scale [E,out]) shard
    their expert axis over ``model``."""
    from k8s_llm_monitor_tpu.parallel.sharding import param_partition_specs
    from k8s_llm_monitor_tpu.utils.quantize import quantize_params

    cfg = dataclasses.replace(CFG, num_experts=8)
    p = llama.init_params(jax.random.PRNGKey(2), cfg)
    specs = param_partition_specs(quantize_params(p))
    lyr = specs["layers"][0]
    assert lyr["gate_e"]["kernel_q"] == jax.sharding.PartitionSpec(
        "model", None, None)
    assert lyr["gate_e"]["scale"] == jax.sharding.PartitionSpec(
        "model", None)


def test_mixtral_hf_key_map_loads():
    """convert_hf_state_dict maps block_sparse_moe.{gate,experts.N.w1/w2/w3}
    into router/gate_e/up_e/down_e stacks."""
    from k8s_llm_monitor_tpu.utils.checkpoint import (
        config_from_hf,
        convert_hf_state_dict,
    )

    hf_cfg = {
        "vocab_size": 64, "hidden_size": 16, "intermediate_size": 24,
        "num_hidden_layers": 1, "num_attention_heads": 4,
        "num_key_value_heads": 2, "rope_theta": 1e6,
        "model_type": "mixtral", "num_local_experts": 4,
        "num_experts_per_tok": 2,
    }
    cfg = config_from_hf(hf_cfg, "mixtral-test")
    assert cfg.num_experts == 4 and cfg.num_experts_per_tok == 2

    rng = np.random.default_rng(7)
    state = {
        "model.embed_tokens.weight": rng.standard_normal((64, 16)),
        "model.norm.weight": np.ones(16),
        "lm_head.weight": rng.standard_normal((64, 16)),
    }
    pre = "model.layers.0."
    state[pre + "input_layernorm.weight"] = np.ones(16)
    state[pre + "post_attention_layernorm.weight"] = np.ones(16)
    for ours, theirs in (("q", "self_attn.q_proj"), ("k", "self_attn.k_proj"),
                         ("v", "self_attn.v_proj"), ("o", "self_attn.o_proj")):
        d = 8 if ours in ("k", "v") else 16
        state[f"{pre}{theirs}.weight"] = rng.standard_normal((d, 16))
    state[pre + "block_sparse_moe.gate.weight"] = rng.standard_normal((4, 16))
    for e in range(4):
        state[f"{pre}block_sparse_moe.experts.{e}.w1.weight"] = \
            rng.standard_normal((24, 16))
        state[f"{pre}block_sparse_moe.experts.{e}.w3.weight"] = \
            rng.standard_normal((24, 16))
        state[f"{pre}block_sparse_moe.experts.{e}.w2.weight"] = \
            rng.standard_normal((16, 24))

    params = convert_hf_state_dict(state, cfg)
    lyr = params["layers"][0]
    assert lyr["router"]["kernel"].shape == (16, 4)
    assert lyr["gate_e"]["kernel"].shape == (4, 16, 24)
    assert lyr["down_e"]["kernel"].shape == (4, 24, 16)
    # Stacking preserved per-expert values (w1 of expert 2, transposed).
    np.testing.assert_allclose(
        np.asarray(lyr["gate_e"]["kernel"][2], np.float32),
        state[f"{pre}block_sparse_moe.experts.2.w1.weight"].T,
        rtol=8e-3)  # stored at the config dtype (bf16)
    # And the MoE forward runs on the loaded tree.
    toks = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    logits = llama.forward_full(params, cfg, toks)
    assert np.isfinite(np.asarray(logits)).all()
