"""KubeRestBackend against a stub HTTP server speaking the Kubernetes wire
format: core lists, logs, metrics.k8s.io, chunked-JSON watch streams, CR
CRUD + /status, error mapping, kubeconfig parsing, and pods/exec over the
WebSocket upgrade (v4.channel.k8s.io).
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from k8s_llm_monitor_tpu.monitor.cluster import Conflict, NotFound
from k8s_llm_monitor_tpu.monitor.kube_rest import (
    KubeRestBackend,
    ws_accept_key,
    ws_encode_frame,
)

NODES = [{"metadata": {"name": "node-a"},
          "status": {"capacity": {"cpu": "4", "memory": "8Gi"}}}]
PODS = [{"metadata": {"name": "web", "namespace": "default"},
         "status": {"phase": "Running"}}]


class _Stub(BaseHTTPRequestHandler):
    server_version = "StubK8s/1.0"
    protocol_version = "HTTP/1.1"   # chunked watch responses need 1.1
    crs: dict = {}          # (ns, name) -> body, shared per server instance
    watch_events: list = []

    def log_message(self, *a):  # silence
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _watch(self):
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        for evt in self.watch_events:
            line = (json.dumps(evt) + "\n").encode()
            self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()
        self.wfile.write(b"0\r\n\r\n")

    def _exec_ws(self):
        key = self.headers["Sec-WebSocket-Key"]
        self.send_response(101, "Switching Protocols")
        self.send_header("Upgrade", "websocket")
        self.send_header("Connection", "Upgrade")
        self.send_header("Sec-WebSocket-Accept", ws_accept_key(key))
        self.send_header("Sec-WebSocket-Protocol", "v4.channel.k8s.io")
        self.end_headers()
        q = parse_qs(urlparse(self.path).query)
        out = f"ran: {' '.join(q['command'])}\n".encode()
        conn = self.connection
        if "frag" in q.get("command", []):
            # Fragmented stdout: FIN=0 first frame (channel byte + half the
            # data), opcode-0 continuation with the rest — exercises the
            # client's message reassembly (a naive reader would misread the
            # continuation's first byte as a channel id).
            half = len(out) // 2
            conn.sendall(ws_encode_frame(0x2, b"\x01" + out[:half],
                                         mask=False, fin=False))
            conn.sendall(ws_encode_frame(0x0, out[half:], mask=False))
        else:
            conn.sendall(ws_encode_frame(0x2, b"\x01" + out, mask=False))
        conn.sendall(ws_encode_frame(0x2, b"\x02" + b"warn\n", mask=False))
        status = json.dumps({"status": "Failure", "details": {
            "causes": [{"reason": "ExitCode", "message": "3"}]}}).encode()
        conn.sendall(ws_encode_frame(0x2, b"\x03" + status, mask=False))
        conn.sendall(ws_encode_frame(0x8, b"", mask=False))

    def do_GET(self):  # noqa: N802
        url = urlparse(self.path)
        q = parse_qs(url.query)
        path = url.path
        if path == "/version":
            return self._json({"gitVersion": "v1.29.0-stub"})
        if path == "/api/v1/nodes":
            return self._json({"items": NODES})
        if path == "/api/v1/namespaces/default/pods":
            if q.get("watch"):
                return self._watch()
            return self._json({"items": PODS})
        if path == "/api/v1/namespaces/default/events":
            limit = int(q.get("limit", ["0"])[0])
            items = [{"reason": f"r{i}"} for i in range(10)]
            return self._json({"items": items[:limit] if limit else items})
        if path == "/api/v1/namespaces/default/pods/web/log":
            body = "line1\nline2\n".encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return None
        if path == "/apis/metrics.k8s.io/v1beta1/nodes":
            return self._json({"items": [
                {"metadata": {"name": "node-a"},
                 "usage": {"cpu": "250m", "memory": "1Gi"}}]})
        if path.startswith("/apis/monitoring.io/v1/"):
            name = path.rsplit("/", 1)[-1]
            if path.endswith("/uavmetrics"):
                return self._json({"items": list(self.crs.values())})
            if ("default", name) in self.crs:
                return self._json(self.crs[("default", name)])
            return self._json({"message": "not found"}, code=404)
        return self._json({"message": f"no route {path}"}, code=404)

    def do_POST(self):  # noqa: N802
        path = urlparse(self.path).path
        body = json.loads(self.rfile.read(
            int(self.headers.get("Content-Length", 0))))
        if path == "/apis/monitoring.io/v1/namespaces/default/uavmetrics":
            name = body["metadata"]["name"]
            if ("default", name) in self.crs:
                return self._json({"message": "exists"}, code=409)
            self.crs[("default", name)] = body
            return self._json(body, code=201)
        return self._json({"message": "bad route"}, code=404)

    def do_PUT(self):  # noqa: N802
        path = urlparse(self.path).path
        body = json.loads(self.rfile.read(
            int(self.headers.get("Content-Length", 0))))
        parts = path.split("/")
        if parts[-1] == "status":
            name = parts[-2]
            cur = self.crs.get(("default", name))
            if cur is None:
                return self._json({"message": "nf"}, code=404)
            cur["status"] = body.get("status", {})
            return self._json(cur)
        name = parts[-1]
        if ("default", name) not in self.crs:
            return self._json({"message": "nf"}, code=404)
        self.crs[("default", name)] = body
        return self._json(body)


class _ExecStub(_Stub):
    def do_GET(self):  # noqa: N802
        if urlparse(self.path).path.endswith("/exec"):
            return self._exec_ws()
        return super().do_GET()


@pytest.fixture()
def server():
    handler = type("H", (_ExecStub,), {"crs": {}, "watch_events": [
        {"type": "ADDED", "object": {"metadata": {"name": "web"}}},
        {"type": "MODIFIED", "object": {"metadata": {"name": "web"}}},
        {"type": "BOOKMARK", "object": {}},
    ]})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


@pytest.fixture()
def backend(server):
    return KubeRestBackend(f"http://127.0.0.1:{server.server_address[1]}",
                           token="tok-123", timeout=5.0, watch_timeout=5.0)


def test_core_reads(backend):
    assert backend.server_version() == "v1.29.0-stub"
    assert backend.list_nodes()[0]["metadata"]["name"] == "node-a"
    assert backend.list_pods("default")[0]["metadata"]["name"] == "web"
    assert len(backend.list_events("default", limit=3)) == 3
    assert backend.pod_logs("default", "web") == "line1\nline2\n"
    usage = backend.node_usage()
    assert usage[0]["usage"]["cpu"] == "250m"


def test_watch_stream_and_close(backend):
    stream = backend.watch("pods", "default")
    events = list(stream)  # server closes after 3 events (BOOKMARK dropped)
    assert [e[0] for e in events] == ["ADDED", "MODIFIED"]
    assert stream.closed

    stream2 = backend.watch("pods", "default")
    stream2.close()  # client-side close must end iteration promptly
    assert len(list(stream2)) <= 2


def test_cr_crud_and_errors(backend):
    g, v, p = "monitoring.io", "v1", "uavmetrics"
    body = {"metadata": {"name": "uavmetric-node-a"},
            "spec": {"battery": {"remaining_percent": 88}}}
    created = backend.create_custom_resource(g, v, p, "default", body)
    assert created["spec"]["battery"]["remaining_percent"] == 88

    with pytest.raises(Conflict):
        backend.create_custom_resource(g, v, p, "default", body)

    got = backend.get_custom_resource(g, v, p, "default", "uavmetric-node-a")
    assert got["metadata"]["name"] == "uavmetric-node-a"

    with pytest.raises(NotFound):
        backend.get_custom_resource(g, v, p, "default", "missing")

    body["spec"]["battery"]["remaining_percent"] = 70
    backend.update_custom_resource(g, v, p, "default", body)
    assert backend.list_custom_resources(g, v, p, "default")[0][
        "spec"]["battery"]["remaining_percent"] == 70

    backend.update_custom_resource_status(
        g, v, p, "default",
        {"metadata": {"name": "uavmetric-node-a"},
         "status": {"collection_status": "active"}})
    got = backend.get_custom_resource(g, v, p, "default", "uavmetric-node-a")
    assert got["status"]["collection_status"] == "active"


def test_exec_websocket(backend):
    out, err, code = backend.exec_in_pod(
        "default", "web", ["ping", "-c", "3", "10.0.0.1"])
    assert out == "ran: ping -c 3 10.0.0.1\n"
    assert err == "warn\n"
    assert code == 3


def test_exec_websocket_fragmented_frames(backend):
    """A stdout message split across FIN=0 + continuation frames reassembles
    to the same bytes (advisor r3: a continuation's first payload byte must
    not be misread as a channel id)."""
    out, err, code = backend.exec_in_pod("default", "web", ["frag", "hello"])
    assert out == "ran: frag hello\n"
    assert err == "warn\n"
    assert code == 3


def test_from_kubeconfig(tmp_path, server):
    port = server.server_address[1]
    cfg = {
        "current-context": "stub",
        "contexts": [{"name": "stub",
                      "context": {"cluster": "c1", "user": "u1"}}],
        "clusters": [{"name": "c1",
                      "cluster": {"server": f"http://127.0.0.1:{port}"}}],
        "users": [{"name": "u1", "user": {"token": "secret-token"}}],
    }
    import yaml as _yaml

    path = tmp_path / "kubeconfig"
    path.write_text(_yaml.safe_dump(cfg))
    b = KubeRestBackend.from_kubeconfig(str(path))
    assert b.token == "secret-token"
    assert b.server_version() == "v1.29.0-stub"


def test_missing_kubeconfig_raises(tmp_path):
    from k8s_llm_monitor_tpu.monitor.cluster import ClusterError

    with pytest.raises(ClusterError):
        KubeRestBackend.from_kubeconfig(str(tmp_path / "nope"))
