"""Control-plane foundation + cluster access tests.

Covers config loading (defaults / YAML / env precedence like ref
internal/config/config.go:105-182), model JSON serialization, the fake
cluster backend, client conversions (ref internal/k8s/converter.go), the
UAVMetric CRD upsert contract (ref client.go:316-450), and the
reconnecting watchers (ref watcher.go, crd_watcher.go).
"""

import threading
import time

import pytest

from k8s_llm_monitor_tpu.monitor.client import Client, sanitize_resource_name
from k8s_llm_monitor_tpu.monitor.cluster import (
    FakeCluster,
    NotFound,
    parse_cpu_millis,
    parse_mem_bytes,
    seed_demo_cluster,
)
from k8s_llm_monitor_tpu.monitor.config import load_config
from k8s_llm_monitor_tpu.monitor.models import (
    NetworkPolicyRule,
    PeerRule,
    UAVReport,
    rfc3339,
    to_jsonable,
    utcnow,
)
from k8s_llm_monitor_tpu.monitor.watcher import CRDWatcher, EventHandler, Watcher


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


def test_config_defaults():
    cfg = load_config(None)
    assert cfg.server.port == 8080
    assert cfg.server.host == "0.0.0.0"
    assert cfg.metrics.collect_interval == 30
    assert cfg.analysis.max_context_events == 100
    assert cfg.llm.max_tokens == 2000
    assert cfg.storage.type == "memory"


def test_config_yaml_and_env(tmp_path, monkeypatch):
    p = tmp_path / "config.yaml"
    p.write_text(
        """
server:
  port: 9999
  debug: true
k8s:
  watch_namespaces: [default, kube-system]
llm:
  provider: tpu
  tpu:
    model: llama-8b
metrics:
  enable_network: true
"""
    )
    monkeypatch.setenv("SERVER_PORT", "7777")  # env beats file
    monkeypatch.setenv("OPENAI_API_KEY", "sk-test")
    cfg = load_config(str(p))
    assert cfg.server.port == 7777
    assert cfg.server.debug is True
    assert cfg.k8s.watch_namespaces == ["default", "kube-system"]
    assert cfg.metrics.namespaces == ["default", "kube-system"]
    assert cfg.llm.provider == "tpu"
    assert cfg.llm.tpu.model == "llama-8b"
    assert cfg.llm.api_key == "sk-test"  # OPENAI_API_KEY alias
    assert cfg.metrics.enable_network is True


def test_config_missing_explicit_file_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_config(str(tmp_path / "nope.yaml"))


# ---------------------------------------------------------------------------
# models / serialization
# ---------------------------------------------------------------------------


def test_to_jsonable_omitempty_and_from_key():
    rule = NetworkPolicyRule(from_=[PeerRule(pod_selector={"app": "a"})])
    d = to_jsonable(rule)
    assert "from" in d and "from_" not in d
    assert d["from"][0]["pod_selector"] == {"app": "a"}

    report = UAVReport(node_name="n1", uav_id="uav-n1")
    d = to_jsonable(report)
    assert "node_ip" not in d  # omitempty drops zero values
    assert "state" not in d
    assert d["node_name"] == "n1"
    assert d["timestamp"].endswith("Z")


def test_rfc3339_format():
    from datetime import datetime, timezone

    ts = datetime(2026, 7, 29, 12, 0, 5, tzinfo=timezone.utc)
    assert rfc3339(ts) == "2026-07-29T12:00:05Z"


def test_rfc3339_round_trip_any_fraction_width():
    # rfc3339 strips trailing fraction zeros (Go marshaling), so the wire
    # carries 1-6 digit fractions; fromisoformat on Python < 3.11 only
    # accepts 3 or 6.  A parse failure here is not cosmetic: the scheduler
    # skips the staleness check for CRs whose last_update doesn't parse.
    from datetime import datetime, timezone

    from k8s_llm_monitor_tpu.monitor.models import parse_rfc3339

    base = datetime(2026, 7, 29, 12, 0, 5, tzinfo=timezone.utc)
    for us in (0, 1, 100, 1000, 400000, 447710, 447711, 999999):
        ts = base.replace(microsecond=us)
        assert parse_rfc3339(rfc3339(ts)) == ts, us
    # k8s-style nanosecond fractions truncate instead of failing
    assert parse_rfc3339("2026-07-29T12:00:05.123456789Z") == base.replace(
        microsecond=123456
    )
    assert parse_rfc3339("not-a-timestamp") is None
    assert parse_rfc3339("") is None


def test_quantity_parsing():
    assert parse_cpu_millis("250m") == 250
    assert parse_cpu_millis("2") == 2000
    assert parse_cpu_millis("1.5") == 1500
    assert parse_cpu_millis("1500000n") == 1
    assert parse_mem_bytes("128Mi") == 128 * 1024**2
    assert parse_mem_bytes("1Gi") == 1024**3
    assert parse_mem_bytes("1000") == 1000


# ---------------------------------------------------------------------------
# fake cluster + client
# ---------------------------------------------------------------------------


@pytest.fixture
def demo():
    fake = seed_demo_cluster(FakeCluster())
    client = Client(fake, namespaces=["default", "kube-system"])
    return fake, client


def test_cluster_info(demo):
    fake, client = demo
    info = client.get_cluster_info()
    assert info["nodes"] == 3
    assert info["pods"] == 3
    assert info["namespaces"] == ["default", "kube-system"]
    assert client.test_connection() == "v1.29.0-fake"


def test_pod_conversion(demo):
    fake, client = demo
    pods = client.get_pods("default")
    assert len(pods) == 2
    web = next(p for p in pods if p.name.startswith("web-frontend"))
    assert web.status == "Running"
    assert web.node_name == "k3d-demo-agent-0"
    assert web.ip.startswith("10.244.")
    assert web.containers[0].state == "running"
    assert web.containers[0].ready is True


def test_env_secret_filtering():
    fake = FakeCluster()
    fake.add_pod(
        "p1",
        env={"APP_MODE": "prod", "DB_PASSWORD": "hunter2", "API_TOKEN": "t"},
    )
    client = Client(fake)
    pod = client.get_pod("default", "p1")
    env = pod.containers[0].env
    assert env == {"APP_MODE": "prod"}  # secret-looking names dropped


def test_services_events_logs(demo):
    fake, client = demo
    svcs = client.get_services("default")
    assert svcs[0].name == "api-backend"
    assert svcs[0].ports[0].port == 8080
    evs = client.get_events("default", limit=10)
    assert evs and evs[0].reason == "Scheduled"
    logs = client.get_pod_logs("default", "api-backend-6f5d8b7c9-k3k2m")
    assert "listening on :8080" in logs
    with pytest.raises(NotFound):
        client.get_pod_logs("default", "ghost")


def test_event_limit():
    fake = FakeCluster()
    for i in range(20):
        fake.add_event(reason=f"r{i}", message="m")
    client = Client(fake)
    evs = client.get_events("default", limit=5)
    assert len(evs) == 5
    assert evs[-1].reason == "r19"  # most recent kept


def test_sanitize_resource_name():
    assert sanitize_resource_name("Node_A.local") == "node-a-local"
    assert sanitize_resource_name("") == "unknown"


def test_uav_metric_upsert_create_then_update(demo):
    fake, client = demo
    report = UAVReport(
        node_name="k3d-demo-agent-0",
        node_ip="172.18.0.3",
        uav_id="uav-agent-0",
        status="active",
        state={
            "gps": {"latitude": 39.9, "longitude": 116.4, "altitude": 50.0},
            "battery": {"voltage": 22.2, "remaining_percent": 87.5},
            "flight": {"mode": "AUTO", "armed": True},
            "health": {"system_status": "OK"},
        },
    )
    client.upsert_uav_metric("", report)
    crs = client.list_uav_metrics_crd()
    assert len(crs) == 1
    cr = crs[0]
    assert cr.name == "uavmetric-k3d-demo-agent-0"
    assert cr.spec["battery"]["remaining_percent"] == 87.5
    assert cr.status["collection_status"] == "active"
    assert cr.generation == 1

    # update path bumps generation, merges labels, swaps spec
    report.state["battery"]["remaining_percent"] = 42.0
    client.upsert_uav_metric("", report)
    cr = client.list_uav_metrics_crd()[0]
    assert cr.spec["battery"]["remaining_percent"] == 42.0
    assert cr.generation == 2


def test_failure_injection(demo):
    fake, client = demo
    fake.fail_next("list_pods", times=1)
    info = client.get_cluster_info()  # pod listing degrades, nodes still there
    assert info["nodes"] == 3
    assert info["pods"] == 1  # only kube-system listed successfully


# ---------------------------------------------------------------------------
# watchers
# ---------------------------------------------------------------------------


class RecordingHandler(EventHandler):
    def __init__(self):
        self.pods = []
        self.services = []
        self.events = []
        self.crd_events = []
        self.got = threading.Event()

    def on_pod_update(self, event_type, pod):
        self.pods.append((event_type, pod.name))
        self.got.set()

    def on_service_update(self, event_type, service):
        self.services.append((event_type, service.name))

    def on_event(self, event):
        self.events.append(event.reason)

    def on_crd_event(self, event):
        self.crd_events.append((event.type, event.name))
        self.got.set()


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def test_watcher_delivers_and_reconnects():
    fake = FakeCluster()
    client = Client(fake)
    handler = RecordingHandler()
    w = Watcher(client, handler, reconnect_delay=0.05)
    w.start()
    try:
        assert _wait(lambda: fake._watchers)  # streams registered
        fake.add_pod("p1")
        assert _wait(lambda: ("ADDED", "p1") in handler.pods)

        # sever every stream; the watcher must reconnect and keep delivering
        fake.close_watches()
        assert _wait(lambda: fake._watchers)
        fake.add_pod("p2")
        assert _wait(lambda: ("ADDED", "p2") in handler.pods)

        fake.update_pod("default", "p2", phase="Failed")
        assert _wait(lambda: ("MODIFIED", "p2") in handler.pods)

        fake.add_event(reason="BackOff", message="restarting")
        assert _wait(lambda: "BackOff" in handler.events)
    finally:
        w.stop()
    assert not any(t.is_alive() for t in w._threads)


def test_crd_watcher_cache_and_events():
    fake = FakeCluster()
    fake.define_crd("monitoring.io", "UAVMetric", "uavmetrics")
    client = Client(fake)
    handler = RecordingHandler()
    cw = CRDWatcher(client, handler, reconnect_delay=0.05)
    cw.start()
    try:
        assert _wait(lambda: len(cw.get_crds()) == 1)
        assert _wait(lambda: ("cr", "monitoring.io", "uavmetrics", "") in fake._watchers)
        fake.create_custom_resource(
            "monitoring.io",
            "v1",
            "uavmetrics",
            "default",
            {"metadata": {"name": "uavmetric-n1"}, "spec": {"uav_id": "u1"}},
        )
        assert _wait(lambda: ("Added", "uavmetric-n1") in handler.crd_events)
        cache = cw.get_custom_resources()
        assert "monitoring.io/UAVMetric/default" in cache
        assert cache["monitoring.io/UAVMetric/default"][0].spec["uav_id"] == "u1"

        # a CRD defined later gets its CR watch spawned from the CRD stream
        fake.define_crd("scheduler.io", "SchedulingRequest", "schedulingrequests")
        assert _wait(
            lambda: ("cr", "scheduler.io", "schedulingrequests", "") in fake._watchers
        )
    finally:
        cw.stop()
