"""Fused decode fast-path (ops/pallas_attention.py:paged_decode_attention_fused).

Covers the three tentpole layers:

  * kernel numerics in Pallas interpreter mode against the gather oracle
    (apply_rope -> _scatter_pages -> paged_decode_attention), including
    page boundaries, ragged lanes, the null-block inactive encoding, past-
    table redirect, and bf16;
  * path selection (ops/attention.py:select_decode_impl mode gating) and
    greedy token-stream identity fused-vs-gather through
    models/llama.py:decode_step;
  * bounded on-device sampling (ops/sampling.py:sample_tokens_bounded)
    against the full-vocab distribution, and the pipelined engine
    (dispatch-ahead step()) preserving per-request streams under
    cancel/preemption.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.ops.attention import (
    paged_decode_attention,
    select_decode_impl,
)
from k8s_llm_monitor_tpu.ops.pallas_attention import (
    paged_decode_attention_fused,
)
from k8s_llm_monitor_tpu.ops.rope import apply_rope, rope_angles
from k8s_llm_monitor_tpu.ops.sampling import (
    filtered_scaled_logits,
    sample_tokens_bounded,
)
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)

THETA = 10_000.0

# Tiny engine config (head_dim 8: rope-compatible but fails the Mosaic
# 128-lane gate) and a fused-eligible one (KVH * D = 2 * 64 = 128).
CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=THETA)
CFG_FUSED_OK = ModelConfig(name="g", vocab_size=128, hidden_size=256,
                           intermediate_size=256, num_layers=1, num_heads=4,
                           num_kv_heads=2, dtype="float32", rope_theta=THETA)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _naive_greedy(params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    return seq[len(prompt):]


# ---------------------------------------------------------------------------
# Kernel numerics vs the gather oracle
# ---------------------------------------------------------------------------


def _fused_case(rng, B, H, KVH, D, bs, max_blocks, positions,
                dtype=jnp.float32):
    """Random decode state with explicit per-lane positions.

    Lanes with position 0 are inactive (all-zero table row, the engine's
    encoding); active lanes get distinct non-null blocks covering their
    append target (mirrors serving/kv_cache.py).
    """
    num_blocks = B * max_blocks + 2
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), dtype)
    k_new = jnp.asarray(rng.standard_normal((B, 1, KVH, D)), dtype)
    v_new = jnp.asarray(rng.standard_normal((B, 1, KVH, D)), dtype)
    k_pages = jnp.asarray(
        rng.standard_normal((num_blocks, bs, KVH * D)), dtype)
    v_pages = jnp.asarray(
        rng.standard_normal((num_blocks, bs, KVH * D)), dtype)
    table = np.zeros((B, max_blocks), np.int32)
    next_free = 1
    for b in range(B):
        used = min(int(positions[b]) // bs + 1, max_blocks)
        if positions[b] > 0:
            table[b, :used] = np.arange(next_free, next_free + used)
            next_free += used
    assert next_free <= num_blocks, "test sized the pool too small"
    return (q, k_new, v_new, k_pages, v_pages, jnp.asarray(table),
            jnp.asarray(np.asarray(positions, np.int32)))


def _gather_reference(q, k_new, v_new, k_pages, v_pages, table, positions):
    """The split path exactly as models/llama.py:decode_step runs it."""
    D = q.shape[-1]
    pos = positions[:, None]
    active = (positions > 0)[:, None]
    cos, sin = rope_angles(pos, D, THETA)
    q_r = apply_rope(q, cos, sin)
    k_r = apply_rope(k_new, cos, sin)
    pk = llama._scatter_pages(k_pages, k_r, table, pos, active)
    pv = llama._scatter_pages(v_pages, v_new, table, pos, active)
    attn = paged_decode_attention(q_r, pk, pv, table, positions + 1)
    return attn, pk, pv


def _run_fused(q, k_new, v_new, k_pages, v_pages, table, positions):
    D = q.shape[-1]
    cos, sin = rope_angles(positions[:, None], D, THETA)
    return paged_decode_attention_fused(
        q, k_new, v_new, cos, sin, k_pages, v_pages, table, positions,
        interpret=True)


@pytest.mark.parametrize("B,H,KVH,D,bs,max_blocks", [
    (4, 8, 8, 64, 16, 4),     # MHA
    (4, 8, 2, 64, 16, 4),     # GQA 4:1
    (2, 16, 4, 128, 8, 6),    # GQA, D=128
    (1, 4, 1, 32, 4, 3),      # MQA-ish, tiny
])
def test_fused_matches_gather_reference(B, H, KVH, D, bs, max_blocks):
    rng = np.random.default_rng(B * 1000 + H + KVH + D)
    positions = rng.integers(1, max_blocks * bs - 1, size=(B,))
    if B >= 4:
        positions[1] = 0                       # one inactive lane
    case = _fused_case(rng, B, H, KVH, D, bs, max_blocks, positions)

    want, wk, wv = _gather_reference(*case)
    got, gk, gv = _run_fused(*case)

    act = np.asarray(positions) > 0
    np.testing.assert_allclose(np.asarray(got)[act], np.asarray(want)[act],
                               rtol=2e-5, atol=2e-5)
    # The append must land identically everywhere — including the
    # inactive lane's null-block redirect.
    np.testing.assert_allclose(np.asarray(gk), np.asarray(wk),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(wv),
                               rtol=2e-5, atol=2e-5)


def test_fused_page_boundaries_and_null_redirect():
    """Positions straddling every block edge, the inactive encoding, and a
    past-table lane whose append must redirect to the null block."""
    B, H, KVH, D, bs, max_blocks = 8, 8, 4, 64, 8, 4
    rng = np.random.default_rng(7)
    #            inactive | first | block edges      | last row | past table
    positions = np.array([0, 1, 7, 8, 15, 16, bs * max_blocks - 1,
                          bs * max_blocks])
    case = _fused_case(rng, B, H, KVH, D, bs, max_blocks, positions)
    # Give the past-table lane a full table (its append overflows it).
    table = np.asarray(case[5]).copy()
    table[7, :] = np.arange(40, 40 + max_blocks)
    case = case[:5] + (jnp.asarray(table), case[6])

    want, wk, wv = _gather_reference(*case)
    got, gk, gv = _run_fused(*case)

    # Attention: active, table-covered lanes (the past-table lane's gather
    # reference would read beyond its table).
    cmp = (positions > 0) & (positions < bs * max_blocks)
    assert not np.any(np.isnan(np.asarray(got)[positions > 0]))
    np.testing.assert_allclose(np.asarray(got)[cmp], np.asarray(want)[cmp],
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(wk),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(wv),
                               rtol=2e-5, atol=2e-5)


def test_fused_bf16():
    B, H, KVH, D, bs, max_blocks = 4, 8, 2, 64, 16, 4
    rng = np.random.default_rng(3)
    positions = rng.integers(1, max_blocks * bs - 1, size=(B,))
    case = _fused_case(rng, B, H, KVH, D, bs, max_blocks, positions,
                       dtype=jnp.bfloat16)

    want, wk, wv = _gather_reference(*case)
    got, gk, gv = _run_fused(*case)

    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(
        np.asarray(gk, np.float32), np.asarray(wk, np.float32),
        rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# Path selection + decode_step stream identity
# ---------------------------------------------------------------------------


def test_select_decode_impl_modes():
    assert select_decode_impl(cfg=CFG_FUSED_OK, mode="gather") \
        is paged_decode_attention
    fused = select_decode_impl(cfg=CFG_FUSED_OK, mode="fused")
    assert llama.is_fused_decode_impl(fused)
    # auto on the CPU backend never picks fused (interpret in a scan).
    auto = select_decode_impl(cfg=CFG_FUSED_OK, mode="auto")
    assert not llama.is_fused_decode_impl(auto)
    with pytest.raises(ValueError):
        select_decode_impl(cfg=CFG, mode="fused")        # lane misalignment
    with pytest.raises(ValueError):
        select_decode_impl(cfg=CFG_FUSED_OK, mesh=object(), mode="fused")
    with pytest.raises(ValueError):
        select_decode_impl(cfg=CFG_FUSED_OK, mode="nope")


def test_greedy_stream_identity_fused_vs_gather(params):
    """decode_step over several steps (crossing a page boundary, reading
    back rows the kernel itself appended) must emit the same greedy stream
    on both paths — the ISSUE's acceptance assertion."""
    B, bs, width, n_steps = 4, 4, 6, 8
    fused_impl = functools.partial(paged_decode_attention_fused,
                                   interpret=True)
    assert llama.is_fused_decode_impl(fused_impl)

    rng = np.random.default_rng(5)
    table = jnp.asarray(
        np.arange(1, 1 + B * width).reshape(B, width).astype(np.int32))
    tokens0 = jnp.asarray(rng.integers(3, 300, size=(B,)), jnp.int32)

    streams, finals = {}, {}
    for name, impl in (("fused", fused_impl),
                       ("gather", paged_decode_attention)):
        pages = llama.init_kv_pages(CFG, 1 + B * width + 1, bs)
        ctx = jnp.ones((B,), jnp.int32)
        tokens = tokens0
        out = []
        for _ in range(n_steps):
            logits, pages = llama.decode_step(
                params, CFG, tokens, ctx, pages, table, attn_impl=impl)
            tokens = jnp.argmax(logits, -1).astype(jnp.int32)
            ctx = ctx + 1
            out.append(np.asarray(tokens))
        streams[name] = np.stack(out)
        finals[name] = pages

    np.testing.assert_array_equal(streams["fused"], streams["gather"])
    for fk, gk in zip(finals["fused"].k, finals["gather"].k):
        np.testing.assert_allclose(np.asarray(fk), np.asarray(gk),
                                   rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# Bounded on-device sampling
# ---------------------------------------------------------------------------


def test_sample_tokens_bounded_matches_full_distribution():
    """Empirical frequencies of the k_cap-bounded sampler must match the
    full-vocab filtered distribution; greedy lanes stay exact argmax."""
    B, V, cap, n_draws = 3, 64, 8, 4000
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((B, V)) * 3.0, jnp.float32)
    temp = jnp.asarray([0.7, 1.3, 0.0], jnp.float32)
    topk = jnp.asarray([5, 8, 4], jnp.int32)
    topp = jnp.asarray([0.8, 1.0, 0.9], jnp.float32)

    want = jax.nn.softmax(filtered_scaled_logits(
        logits, temperature=temp, top_k=topk, top_p=topp), axis=-1)
    keys = jax.random.split(jax.random.PRNGKey(0), n_draws)
    draws = np.asarray(jax.vmap(
        lambda k: sample_tokens_bounded(
            k, logits, temperature=temp, top_k=topk, top_p=topp, k_cap=cap)
    )(keys))

    assert (draws[:, 2] == int(jnp.argmax(logits[2]))).all()
    for b in (0, 1):
        counts = np.bincount(draws[:, b], minlength=V) / n_draws
        wp = np.asarray(want[b])
        # Support containment: the bounded sampler can never emit a token
        # the full filter assigns zero mass.
        assert set(np.nonzero(counts)[0]) <= set(np.nonzero(wp > 0)[0])
        np.testing.assert_allclose(counts, wp, atol=0.03)


def test_engine_bounded_sampling_reproducible(params):
    """top_k within sample_topk_cap routes decode through the bounded
    program; two engines with the same seed must emit identical streams."""
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(3, 300, size=6)) for _ in range(2)]
    sp = SamplingParams(max_tokens=6, temperature=0.8, top_k=5, top_p=0.9)
    outs = []
    for _ in range(2):
        eng = InferenceEngine(
            CFG, params,
            EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                         max_blocks_per_seq=16, prefill_buckets=(16,),
                         sample_topk_cap=8),
            eos_id=-1, seed=7)
        res = eng.generate(prompts, sp)
        assert all(0 <= t < CFG.vocab_size
                   for r in res for t in r.token_ids)
        # White-box: the bounded variant actually compiled.  Decode keys
        # are (n_steps, sampled, bounded, constrained); spec programs use
        # ("spec", ...) keys.
        assert any(key[1] and key[2]
                   for key in eng._decode_cache if key[0] != "spec")
        outs.append([r.token_ids for r in res])
    assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# Pipelined engine: streams survive cancel + preemption
# ---------------------------------------------------------------------------


def test_pipelined_step_preserves_streams_under_cancel_and_preemption(params):
    """Dispatch-ahead step() (max_inflight=2, opportunistic ready-drain)
    with a page pool tight enough to force preemption and a mid-flight
    cancel: every surviving request's stream must equal naive greedy, and
    the cancelled request's partial stream must be a prefix of it."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=3, num_blocks=14, block_size=4,
                     max_blocks_per_seq=16, prefill_buckets=(16,),
                     max_inflight=2,
                     # No prefix cache: retained prefixes would make the
                     # final no-leak accounting non-strict.
                     prefix_cache_entries=0),
        eos_id=-1)
    assert eng.ecfg.max_inflight >= 2
    rng = np.random.default_rng(2)
    prompts = [list(rng.integers(3, 300, size=7)) for _ in range(5)]
    n_gen = 24
    for i, p in enumerate(prompts):
        eng.submit(GenerationRequest(
            request_id=f"r{i}", prompt_ids=p,
            sampling=SamplingParams(max_tokens=n_gen)))

    def _slot(rid):
        return next((s for s in eng._slots
                     if s is not None and s.req.request_id == rid), None)

    # Step until r1 is mid-decode (some tokens reconciled, not finished),
    # then cancel it while decode calls for it may still be in flight.
    for _ in range(50):
        eng.step()
        s = _slot("r1")
        if s is not None and len(s.generated) >= 1:
            break
    assert eng.cancel("r1")
    while eng.has_work:
        eng.step()

    assert eng.preemptions > 0, "pool was not tight enough to preempt"
    for i, p in enumerate(prompts):
        res = eng.poll(f"r{i}")
        assert res is not None
        naive = _naive_greedy(params, p, n_gen)
        if i == 1:
            assert res.finish_reason != "error" or res.token_ids == []
            assert res.token_ids == naive[:len(res.token_ids)], \
                "cancelled stream is not a naive-greedy prefix"
        else:
            assert res.finish_reason == "length"
            assert res.token_ids == naive, f"r{i} diverged from naive"
    assert eng.allocator.free_blocks == eng.allocator.num_blocks - 1
