"""Paged-KV prefill + decode must reproduce the dense forward pass.

The invariant: for any prompt, running prefill() then decode_step() token by
token yields the same greedy continuation and (numerically close) logits as
forward_full() over the growing sequence.
"""

import numpy as np

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig

CFG = ModelConfig(name="t", vocab_size=97, hidden_size=32, intermediate_size=64,
                  num_layers=2, num_heads=4, num_kv_heads=2, dtype="float32",
                  rope_theta=10_000.0)

BLOCK = 8
NBLOCKS = 32  # block 0 reserved as null


def _setup(prompt_lens):
    rng = jax.random.PRNGKey(0)
    params = llama.init_params(rng, CFG)
    pages = llama.init_kv_pages(CFG, NBLOCKS, BLOCK)
    B = len(prompt_lens)
    S = max(prompt_lens)
    gen = np.random.default_rng(1)
    tokens = np.zeros((B, S), np.int32)
    for b, L in enumerate(prompt_lens):
        tokens[b, :L] = gen.integers(1, CFG.vocab_size, L)
    # allocate blocks: sequential, skipping block 0
    max_blocks = 8
    table = np.zeros((B, max_blocks), np.int32)
    nxt = 1
    for b in range(B):
        need = (prompt_lens[b] + 16 + BLOCK - 1) // BLOCK
        for j in range(need):
            table[b, j] = nxt
            nxt += 1
    return params, pages, jnp.asarray(tokens), jnp.asarray(table)


def test_prefill_matches_full_forward():
    lens = [13, 5, 8]
    params, pages, tokens, table = _setup(lens)
    lengths = jnp.asarray(lens, jnp.int32)
    last_logits, pages = llama.prefill(params, CFG, tokens, lengths, pages, table)

    for b, L in enumerate(lens):
        full = llama.forward_full(params, CFG, tokens[b : b + 1, :L])
        np.testing.assert_allclose(
            np.asarray(last_logits[b]), np.asarray(full[0, -1]), rtol=1e-4, atol=1e-4
        )


def test_decode_matches_full_forward():
    lens = [9, 4]
    params, pages, tokens, table = _setup(lens)
    lengths = jnp.asarray(lens, jnp.int32)
    logits, pages = llama.prefill(params, CFG, tokens, lengths, pages, table)

    seqs = [list(np.asarray(tokens[b, : lens[b]])) for b in range(len(lens))]
    ctx = np.asarray(lens, np.int32)
    for step in range(6):
        nxt = np.asarray(jnp.argmax(logits, axis=-1), np.int32)
        for b in range(len(lens)):
            seqs[b].append(int(nxt[b]))
        logits, pages = llama.decode_step(
            params, CFG, jnp.asarray(nxt), jnp.asarray(ctx), pages, table
        )
        ctx = ctx + 1
        for b in range(len(lens)):
            full = llama.forward_full(
                params, CFG, jnp.asarray(np.asarray(seqs[b])[None, :])
            )
            np.testing.assert_allclose(
                np.asarray(logits[b]), np.asarray(full[0, -1]),
                rtol=2e-4, atol=2e-4,
            )


def test_null_block_isolation():
    """Inactive lanes (context_len=0) must not corrupt live sequences."""
    lens = [9, 4]
    params, pages, tokens, table = _setup(lens)
    lengths = jnp.asarray(lens, jnp.int32)
    logits, pages = llama.prefill(params, CFG, tokens, lengths, pages, table)

    # run a decode step where lane 1 is inactive (ctx 0 -> writes to null blk)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    dead_table = table.at[1].set(0)
    logits2, pages = llama.decode_step(
        params, CFG, nxt, jnp.asarray([lens[0], 0], jnp.int32), pages, dead_table
    )
    seq0 = list(np.asarray(tokens[0, : lens[0]])) + [int(nxt[0])]
    full = llama.forward_full(params, CFG, jnp.asarray(np.asarray(seq0)[None, :]))
    np.testing.assert_allclose(
        np.asarray(logits2[0]), np.asarray(full[0, -1]), rtol=2e-4, atol=2e-4
    )
