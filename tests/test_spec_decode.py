"""Prompt-lookup speculative decoding (serving/spec.py + engine spec path).

The load-bearing guarantee is bit-identity: greedy speculation must emit
exactly the sequential greedy chain no matter what the proposer drafts.
The equivalence tests therefore compare against naive forward_full greedy —
they hold whether acceptance is 0% or 100%, exercising the accept/ctx/quota
bookkeeping either way.  Unit tests pin the proposer and acceptance rules
directly (multi-accept, EOS truncation, quota clamp, no-match fallback).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.spec import (
    accept_greedy,
    accept_sampled,
    propose_drafts,
)

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _naive_greedy(params, prompt, n, eos=-1):
    seq = list(prompt)
    out = []
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        t = int(jnp.argmax(logits[0, -1]))
        seq.append(t)
        out.append(t)
        if t == eos:
            break
    return out


# ---------------------------------------------------------------------------
# proposer
# ---------------------------------------------------------------------------


def test_propose_drafts_trigram_match():
    # History: ... 7 8 9 4 5 ... 7 8 9 <cur=9 at ctx>; the latest (7,8,9)
    # occurrence mid-history is at p=8, so drafts continue with 4 5 6 0.
    row = [1, 2, 7, 8, 9, 4, 5, 6, 0, 3, 7, 8, 9]
    ctx = len(row) - 1                       # position of cur token (9)
    hist = np.full((1, 32), -1, np.int32)
    hist[0, :len(row)] = row
    drafts = propose_drafts(jnp.asarray(hist), jnp.asarray([ctx], jnp.int32),
                            jnp.asarray([9], jnp.int32), 4)
    assert drafts.tolist() == [[4, 5, 6, 0]]


def test_propose_drafts_bigram_fallback():
    # No trigram (x,8,9) elsewhere, but bigram (8,9) appears at p=3.
    row = [5, 1, 8, 9, 6, 2, 4, 8, 9]
    ctx = len(row) - 1
    hist = np.full((1, 32), -1, np.int32)
    hist[0, :len(row)] = row
    drafts = propose_drafts(jnp.asarray(hist), jnp.asarray([ctx], jnp.int32),
                            jnp.asarray([9], jnp.int32), 3)
    assert drafts.tolist() == [[6, 2, 4]]


def test_propose_drafts_recency_wins():
    # Two trigram matches; the later one (continuing with 40) must win.
    row = [1, 2, 3, 30, 9, 1, 2, 3, 40, 8, 1, 2, 3]
    ctx = len(row) - 1
    hist = np.full((1, 32), -1, np.int32)
    hist[0, :len(row)] = row
    drafts = propose_drafts(jnp.asarray(hist), jnp.asarray([ctx], jnp.int32),
                            jnp.asarray([3], jnp.int32), 2)
    assert drafts.tolist() == [[40, 8]]


# ---------------------------------------------------------------------------
# acceptance
# ---------------------------------------------------------------------------


def _acc(greedy, drafts, quota, active, eos):
    emit, out = accept_greedy(
        jnp.asarray(greedy, jnp.int32), jnp.asarray(drafts, jnp.int32),
        jnp.asarray(quota, jnp.int32), jnp.asarray(active),
        jnp.asarray(eos, jnp.int32))
    return np.asarray(emit).tolist(), np.asarray(out).tolist()


def test_accept_full_partial_none():
    greedy = [[10, 11, 12, 13],   # full accept: 3 drafts + bonus
              [10, 99, 12, 13],   # mismatch at draft[1]: emit 10, 99
              [77, 11, 12, 13]]   # mismatch at draft[0]: emit 77 only
    drafts = [[10, 11, 12], [10, 11, 12], [10, 11, 12]]
    emit, out = _acc(greedy, drafts, [64, 64, 64], [True] * 3, -1)
    assert emit == [4, 2, 1]
    assert out[0] == [10, 11, 12, 13]
    assert out[1] == [10, 99, -1, -1]
    assert out[2] == [77, -1, -1, -1]


def test_accept_eos_truncates():
    greedy = [[10, 5, 12, 13]]            # eos=5 emitted at index 1
    drafts = [[10, 5, 12]]
    emit, out = _acc(greedy, drafts, [64], [True], 5)
    assert emit == [2]
    assert out[0] == [10, 5, -1, -1]


def test_accept_quota_and_inactive():
    greedy = [[10, 11, 12, 13], [10, 11, 12, 13]]
    drafts = [[10, 11, 12], [10, 11, 12]]
    emit, out = _acc(greedy, drafts, [2, 64], [True, False], -1)
    assert emit == [2, 0]
    assert out[0] == [10, 11, -1, -1]
    assert out[1] == [-1, -1, -1, -1]


def test_accept_neg_eos_never_matches_padding():
    # Engine uses eos_id=-1 when unset; out's -1 padding must not register
    # as EOS anywhere downstream (accept_greedy compares greedy, which is
    # argmax output and always >= 0).
    greedy = [[10, 11, 12, 13]]
    drafts = [[99, 11, 12]]
    emit, out = _acc(greedy, drafts, [64], [True], -1)
    assert emit == [1]
    assert out[0] == [10, -1, -1, -1]


def test_accept_sampled_marginal_distribution():
    """The delta-draft rule must leave the first emitted token distributed
    exactly as the target softmax, whatever the draft is: accept draft x
    w.p. p(x), else resample from p with x zeroed/renormalized.  Checked by
    Monte Carlo over keys against the analytic marginal."""
    V = 6
    logits_row = np.array([2.0, 0.5, 1.0, -1.0, 0.0, 1.5], np.float32)
    temp = 0.7
    p = np.exp(logits_row / temp) / np.exp(logits_row / temp).sum()
    draft0 = 2                                    # fed draft at position 0
    logits = jnp.asarray(np.tile(logits_row, (1, 3, 1)))   # [B=1, K+1=3, V]
    drafts = jnp.asarray([[draft0, 1]], jnp.int32)
    N = 4000
    keys = jax.random.split(jax.random.PRNGKey(0), N)
    _, outs = jax.vmap(lambda k: accept_sampled(
        k, logits, drafts,
        jnp.asarray([64], jnp.int32), jnp.asarray([True]),
        jnp.asarray(-1, jnp.int32), jnp.asarray([temp], jnp.float32)))(keys)
    first = np.asarray(outs)[:, 0, 0]             # [N] first emitted token
    freq = np.bincount(first, minlength=V) / N
    np.testing.assert_allclose(freq, p, atol=4.0 / np.sqrt(N),
                               err_msg=f"marginal {freq} != target {p}")


def test_accept_sampled_greedy_lanes_use_argmax():
    """temperature <= 0 lanes in a sampled-accept call must follow the
    argmax rule exactly (mixed batches share one program)."""
    V = 5
    logits = np.zeros((2, 3, V), np.float32)
    logits[:, 0, 3] = 9.0                        # argmax after fed token = 3
    logits[:, 1, 4] = 9.0                        # after draft0 = 4
    logits[:, 2, 1] = 9.0
    drafts = jnp.asarray([[3, 4], [0, 0]], jnp.int32)  # lane0 matches argmax
    emit, out = accept_sampled(
        jax.random.PRNGKey(0), jnp.asarray(logits), drafts,
        jnp.asarray([64, 64], jnp.int32), jnp.asarray([True, True]),
        jnp.asarray(-1, jnp.int32), jnp.asarray([0.0, 0.0], jnp.float32))
    assert np.asarray(emit).tolist() == [3, 1]
    assert np.asarray(out)[0].tolist() == [3, 4, 1]
    assert np.asarray(out)[1].tolist() == [3, -1, -1]


def test_spec_sampled_engine_completes(params):
    """The diagnosis sampling config (temperature 0.1, no top-k/p) must
    engage sampled speculation and complete with valid tokens.  (Exact
    distribution preservation is pinned by the Monte-Carlo unit test;
    near-tied logits mean even tiny temperatures may legitimately diverge
    from the argmax chain, so no greedy bit-compare here.)"""
    eng = _spec_engine(params, spec_k=4, rounds=4)
    rng = np.random.default_rng(19)
    prompts = [list(rng.integers(3, 300, size=6)) for _ in range(3)]
    results = eng.generate(
        prompts, SamplingParams(max_tokens=24, temperature=0.1))
    assert eng.spec_verify_steps > 0, "sampled speculation never engaged"
    for r in results:
        assert len(r.token_ids) == 24
        assert all(0 <= t < CFG.vocab_size for t in r.token_ids), \
            "sampled speculation emitted an out-of-vocab token"


def test_spec_topp_topk_lanes_speculate(params):
    """Nucleus/top-k lanes speculate too: acceptance runs against the
    filtered distribution sequential decode samples from."""
    eng = _spec_engine(params, spec_k=4, rounds=4)
    rng = np.random.default_rng(21)
    eng.submit(GenerationRequest(
        "p0", list(rng.integers(3, 300, size=6)),
        SamplingParams(max_tokens=12, temperature=0.8, top_p=0.9)))
    eng.submit(GenerationRequest(
        "p1", list(rng.integers(3, 300, size=6)),
        SamplingParams(max_tokens=12, temperature=0.8, top_k=5)))
    while eng.has_work:
        eng.step()
    assert len(eng.poll("p0").token_ids) == 12
    assert len(eng.poll("p1").token_ids) == 12
    assert eng.spec_verify_steps > 0


def test_accept_sampled_topk_marginal():
    """With top_k=2 the emitted-token marginal must equal the renormalized
    top-2 distribution (zero mass outside the filter, exact inside)."""
    V = 6
    logits_row = np.array([2.0, 0.5, 1.0, -1.0, 0.0, 1.5], np.float32)
    temp = 0.9
    scaled = logits_row / temp
    top2 = np.argsort(-scaled)[:2]
    p_ref = np.zeros(V)
    ex = np.exp(scaled[top2] - scaled[top2].max())
    p_ref[top2] = ex / ex.sum()
    logits = jnp.asarray(np.tile(logits_row, (1, 3, 1)))
    drafts = jnp.asarray([[int(top2[1]), 1]], jnp.int32)
    N = 4000
    keys = jax.random.split(jax.random.PRNGKey(1), N)
    _, outs = jax.vmap(lambda k: accept_sampled(
        k, logits, drafts,
        jnp.asarray([64], jnp.int32), jnp.asarray([True]),
        jnp.asarray(-1, jnp.int32), jnp.asarray([temp], jnp.float32),
        top_k=jnp.asarray([2], jnp.int32),
        top_p=jnp.asarray([1.0], jnp.float32)))(keys)
    first = np.asarray(outs)[:, 0, 0]
    freq = np.bincount(first, minlength=V) / N
    np.testing.assert_allclose(freq, p_ref, atol=4.0 / np.sqrt(N),
                               err_msg=f"filtered marginal {freq} != {p_ref}")
    # Nothing outside the top-2 filter is ever emitted at position 0.
    assert freq[[i for i in range(V) if i not in top2]].sum() == 0.0


# ---------------------------------------------------------------------------
# verify_step vs sequential decode
# ---------------------------------------------------------------------------


def test_verify_step_matches_sequential_decode(params):
    """Logits at every verify position equal the sequential decode logits
    for the same fed tokens (same paged cache semantics)."""
    ec = EngineConfig(max_slots=2, num_blocks=32, block_size=8,
                      max_blocks_per_seq=8, prefill_buckets=(16,))
    pages = llama.init_kv_pages(CFG, ec.num_blocks, ec.block_size)
    rng = np.random.default_rng(1)
    prompt = list(rng.integers(3, 300, size=9))
    blocks = [1, 2, 3, 4]
    tables = np.zeros((1, ec.max_blocks_per_seq), np.int32)
    tables[0, :4] = blocks

    toks = np.zeros((1, 16), np.int32)
    toks[0, :9] = prompt
    _, pages = llama.prefill(params, CFG, jnp.asarray(toks),
                             jnp.asarray([9], jnp.int32), pages,
                             jnp.asarray(tables))

    fed = list(rng.integers(3, 300, size=4))      # arbitrary draft chain
    # Sequential: feed one by one, collecting logits.
    seq_pages = pages
    seq_logits = []
    for i, t in enumerate(fed):
        lg, seq_pages = llama.decode_step(
            params, CFG, jnp.asarray([t], jnp.int32),
            jnp.asarray([9 + i], jnp.int32), seq_pages, jnp.asarray(tables))
        seq_logits.append(np.asarray(lg[0]))

    ver_logits, _ = llama.verify_step(
        params, CFG, jnp.asarray([fed], jnp.int32),
        jnp.asarray([9], jnp.int32), jnp.asarray([4], jnp.int32),
        pages, jnp.asarray(tables))
    ver = np.asarray(ver_logits[0])
    for i in range(4):
        np.testing.assert_allclose(ver[i], seq_logits[i], rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# engine equivalence
# ---------------------------------------------------------------------------


def _spec_engine(params, spec_k=4, rounds=2, eos=-1, **kw):
    base = dict(max_slots=4, num_blocks=64, block_size=8,
                max_blocks_per_seq=16, prefill_buckets=(16, 32),
                spec_k=spec_k, spec_rounds_per_iter=rounds)
    base.update(kw)
    return InferenceEngine(CFG, params, EngineConfig(**base), eos_id=eos)


def test_spec_greedy_matches_naive(params):
    eng = _spec_engine(params)
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(3, 300, size=n)) for n in (5, 11, 3, 8)]
    results = eng.generate(prompts,
                           SamplingParams(max_tokens=12, temperature=0.0))
    for p, r in zip(prompts, results):
        assert r.finish_reason == "length"
        assert r.token_ids == _naive_greedy(params, p, 12), \
            "speculative decode diverged from sequential greedy"
    assert eng.spec_verify_steps > 0


def test_spec_repetitive_prompt_accepts(params):
    """A prompt whose greedy continuation enters a cycle gives the n-gram
    proposer real matches; outputs must still be bit-identical and some
    round must accept more than the mandatory one token."""
    eng = _spec_engine(params, spec_k=4, rounds=4)
    rng = np.random.default_rng(7)
    prompts = [list(rng.integers(3, 300, size=6)) for _ in range(3)]
    results = eng.generate(prompts,
                           SamplingParams(max_tokens=48, temperature=0.0))
    for p, r in zip(prompts, results):
        assert r.token_ids == _naive_greedy(params, p, 48)
    # Random-init tiny models settle into argmax cycles quickly; once they
    # do, history matching predicts the cycle and acceptance goes >1/round.
    assert eng.spec_tokens > eng.spec_verify_steps, (
        f"no multi-token round in {eng.spec_tokens} tokens over "
        f"{eng.spec_verify_steps} verify steps")


def test_spec_eos_termination(params):
    """EOS inside an accepted draft run terminates exactly where the
    sequential chain would."""
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(3, 300, size=7)) for _ in range(3)]
    # Pick the eos from a reference run so at least one lane hits it.
    ref = _naive_greedy(params, prompts[0], 24)
    eos = ref[len(ref) // 2]
    eng = _spec_engine(params, eos=eos)
    results = eng.generate(prompts,
                           SamplingParams(max_tokens=24, temperature=0.0))
    hit_eos = 0
    for p, r in zip(prompts, results):
        want = _naive_greedy(params, p, 24, eos=eos)
        if want and want[-1] == eos:
            hit_eos += 1
            # The engine strips the terminal EOS from token_ids (_retire).
            assert r.finish_reason == "eos"
            assert r.token_ids == want[:-1]
        else:
            assert r.token_ids == want
    assert hit_eos >= 1


def test_spec_mixed_greedy_and_sampled_lanes(params):
    """Greedy and pure-temperature lanes share one sampled-accept spec
    program; nucleus lanes speculate with the filtered distribution.  Both
    mixes must complete with full budgets.  (spec_probe_every=1 keeps the
    adaptive controller speculating despite low random-prompt acceptance —
    this test is about program variants, not the controller.)"""
    eng = _spec_engine(params, spec_probe_every=1)
    rng = np.random.default_rng(5)
    for j in range(4):
        temp = 0.0 if j % 2 == 0 else 0.8
        eng.submit(GenerationRequest(
            f"r{j}", list(rng.integers(3, 300, size=6)),
            SamplingParams(max_tokens=10, temperature=temp)))
    while eng.has_work:
        eng.step()
    for j in range(4):
        res = eng.poll(f"r{j}")
        assert res is not None and len(res.token_ids) == 10
    assert eng.spec_verify_steps > 0   # pure-temp mix is spec-eligible
    # Nucleus lanes speculate too (filtered-distribution acceptance).
    before = eng.spec_verify_steps
    for j in range(2):
        eng.submit(GenerationRequest(
            f"n{j}", list(rng.integers(3, 300, size=6)),
            SamplingParams(max_tokens=10, temperature=0.8,
                           top_p=0.9 if j == 0 else 1.0)))
    while eng.has_work:
        eng.step()
    for j in range(2):
        assert len(eng.poll(f"n{j}").token_ids) == 10
    assert eng.spec_verify_steps > before


def test_spec_inflight_then_sampled_admission(params):
    """A sampled request arriving while a spec call is in flight flips the
    next dispatch to the fused path; that dispatch must first reconcile the
    spec call or it would run greedy lanes at overestimated ctx (reading
    rejected-draft KV).  The greedy lanes' outputs must stay bit-exact."""
    eng = _spec_engine(params, spec_k=4, rounds=4)
    rng = np.random.default_rng(17)
    gp = [list(rng.integers(3, 300, size=6)) for _ in range(2)]
    for j, p in enumerate(gp):
        eng.submit(GenerationRequest(
            f"g{j}", p, SamplingParams(max_tokens=40, temperature=0.0)))
    # Step until a spec call is actually in flight, then inject the
    # sampled request mid-stream.  step()'s opportunistic ready-drain is a
    # latency optimization, not a correctness requirement — hold it off so
    # the in-flight call stays observable even when CPU execution
    # completes before step() returns (machine-speed-dependent otherwise).
    eng._call_ready = lambda call: False
    for _ in range(50):
        eng.step()
        if any(c.kind == "spec" for c in eng._inflight):
            break
    assert any(c.kind == "spec" for c in eng._inflight), \
        "test setup: no spec call went in flight"
    # A sampled (nucleus) admission flips the batch from the greedy spec
    # program to the sampled one mid-flight; greedy lanes must stay
    # bit-exact through the transition (argmax rule inside accept_sampled).
    eng.submit(GenerationRequest(
        "s0", list(rng.integers(3, 300, size=5)),
        SamplingParams(max_tokens=8, temperature=0.9, top_p=0.9)))
    del eng._call_ready
    while eng.has_work:
        eng.step()
    for j, p in enumerate(gp):
        res = eng.poll(f"g{j}")
        assert res.token_ids == _naive_greedy(params, p, 40), \
            "greedy lane corrupted by dispatch against unreconciled spec ctx"
    assert len(eng.poll("s0").token_ids) == 8


def test_spec_under_page_pressure(params):
    """Preemption + re-admission (history row rewrite) keeps bit-identity."""
    eng = _spec_engine(params, num_blocks=14, prefix_cache_entries=0)
    rng = np.random.default_rng(11)
    prompts = [list(rng.integers(3, 300, size=9)) for _ in range(4)]
    results = eng.generate(prompts,
                           SamplingParams(max_tokens=16, temperature=0.0))
    for p, r in zip(prompts, results):
        assert r.finish_reason == "length"
        assert r.token_ids == _naive_greedy(params, p, 16)


def test_scatter_beyond_table_hits_null_block():
    """K/V writes at positions past the block table's reach must land in
    the null block 0, NOT clip into the lane's last real block: the spec
    verify pass at the capacity boundary writes rejected-draft K/V there,
    and a clip would overwrite live cache (silent logit corruption)."""
    bs, width = 4, 2
    pages = jnp.full((6, bs, 8), 7.0, jnp.float32)   # sentinel everywhere
    table = jnp.asarray([[1, 2]], jnp.int32)         # capacity = 8 positions
    vals = jnp.ones((1, 4, 2, 4), jnp.float32)       # [B, S, KVH, D]
    positions = jnp.asarray([[6, 7, 8, 9]], jnp.int32)  # 8, 9 overflow
    valid = jnp.ones((1, 4), bool)
    out = llama._scatter_pages(pages, vals, table, positions, valid)
    out = np.asarray(out)
    # In-range writes land in block 2 (positions 6, 7 -> offsets 2, 3).
    assert (out[2, 2:] == 1.0).all()
    # Overflow went to the null block, and block 2's offsets 0-1 (where a
    # clip of positions 8, 9 would land) still hold the sentinel.
    assert (out[0, :2] == 1.0).all()
    assert (out[2, :2] == 7.0).all(), "overflow clipped into a real block"
    assert (out[1] == 7.0).all()


def test_spec_at_capacity_boundary(params):
    """A request whose prompt+max_tokens exactly fills its per-seq capacity
    makes the verify pass write rejected drafts past the last block; those
    writes must fall into the null block, not clip back into the lane's
    real cache (which silently corrupts live KV and breaks bit-identity)."""
    eng = _spec_engine(params, spec_k=4, rounds=2,
                       max_blocks_per_seq=4, num_blocks=32,
                       prefill_buckets=(16,))
    cap = eng.capacity_tokens                     # 4 blocks x 8 = 32 tokens
    rng = np.random.default_rng(23)
    n_gen = 12
    prompt = list(rng.integers(3, 300, size=cap - n_gen))
    results = eng.generate([prompt],
                           SamplingParams(max_tokens=n_gen, temperature=0.0))
    assert results[0].token_ids == _naive_greedy(params, prompt, n_gen), \
        "KV corrupted by out-of-capacity draft writes"


def test_spec_long_prompt_chunked_admission(params):
    """Prompts beyond the largest bucket stream through chunked prefill;
    their generation must still match under speculation."""
    eng = _spec_engine(params, num_blocks=96, max_blocks_per_seq=24)
    rng = np.random.default_rng(13)
    prompts = [list(rng.integers(3, 300, size=75)),
               list(rng.integers(3, 300, size=6))]
    results = eng.generate(prompts,
                           SamplingParams(max_tokens=10, temperature=0.0))
    for p, r in zip(prompts, results):
        assert r.token_ids == _naive_greedy(params, p, 10)


def test_spec_adapts_off_at_low_acceptance(params):
    """Random prompts give ~1.0 acceptance, where the fused path wins; the
    engine must measure that and stop speculating (except probes)."""
    eng = _spec_engine(params, spec_k=4, rounds=2, spec_probe_every=6)
    rng = np.random.default_rng(29)
    prompts = [list(rng.integers(3, 300, size=6)) for _ in range(4)]
    results = eng.generate(prompts,
                           SamplingParams(max_tokens=60, temperature=0.0))
    for p, r in zip(prompts, results):
        assert r.token_ids == _naive_greedy(params, p, 60)
    assert eng.spec_verify_steps > 0, "first dispatch must probe"
    # Most decode work must have run on the fused path: verify rounds stay
    # well below the total device steps.
    assert eng.spec_verify_steps < eng.steps / 2, (
        eng.spec_verify_steps, eng.steps)
    assert eng._spec_ema is not None and eng._spec_ema < 1.2
    # Per-request-class bookkeeping: greedy-only traffic populates only the
    # "greedy" class, and the exporter snapshot mirrors it.
    snap = eng.spec_accept_ema()
    assert set(snap) == {"greedy"}
    assert snap["greedy"] < 1.2


def test_acceptance_ema_flat_acceptance_flips_kill_switch():
    """Satellite gate: a class whose accepted-length EMA sits flat under
    the floor must have drafting auto-disabled, re-enabled only as a
    periodic probe; a healthy class on the same tracker stays drafting."""
    from k8s_llm_monitor_tpu.serving.spec import AcceptanceEMA

    ema = AcceptanceEMA(floor=1.2, probe_every=4)
    assert ema.should_draft("greedy")          # no measurement yet: draft
    assert ema.ema("greedy") is None

    # Flat 1.0 acceptance (1 accepted token per lane-round): EMA converges
    # below the 1.2 floor and the kill-switch flips.
    for _ in range(20):
        ema.update("greedy", accepted=4, lane_rounds=4)
    assert ema.drafting_disabled("greedy")
    assert ema.ema("greedy") < 1.2

    # Disabled class: exactly one probe per probe_every dispatches.
    draws = [ema.should_draft("greedy") for _ in range(8)]
    assert draws.count(True) == 2 and draws[3] and draws[7]

    # An independent healthy class is untouched by greedy's kill-switch.
    for _ in range(20):
        ema.update("sampled", accepted=12, lane_rounds=4)
    assert not ema.drafting_disabled("sampled")
    assert all(ema.should_draft("sampled") for _ in range(8))
    assert ema.drafting_disabled("greedy")

    snap = ema.snapshot()
    assert snap["greedy"] < 1.2 < snap["sampled"]


def test_spec_min_accept_config_plumbs_to_engine():
    """monitor config -> EngineConfig -> AcceptanceEMA floor."""
    from k8s_llm_monitor_tpu.monitor.config import TPULLMConfig
    from k8s_llm_monitor_tpu.serving.engine import EngineConfig

    tpu_cfg = TPULLMConfig()
    assert tpu_cfg.spec_min_accept == EngineConfig().spec_min_accept == 1.2
