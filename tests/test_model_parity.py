"""Logit parity of our JAX Llama/Qwen2 against HuggingFace transformers.

A tiny random-weight HF model is instantiated on CPU (torch), its state dict
converted through utils/checkpoint.convert_hf_state_dict, and full-sequence
logits compared.  This pins the whole stack: embedding, RoPE convention,
GQA, SwiGLU, RMSNorm, and the load-time transpose.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.utils.checkpoint import config_from_hf, convert_hf_state_dict


def _hf_tiny(model_type: str):
    import torch
    import transformers

    kwargs = dict(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=500000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    if model_type == "llama":
        cfg = transformers.LlamaConfig(**kwargs)
        model = transformers.LlamaForCausalLM(cfg)
    else:
        cfg = transformers.Qwen2Config(**kwargs)
        model = transformers.Qwen2ForCausalLM(cfg)
    model.eval()
    torch.manual_seed(0)
    for p in model.parameters():
        with torch.no_grad():
            p.copy_(torch.randn_like(p) * 0.05)
    return cfg, model


@pytest.mark.parametrize("model_type", ["llama", "qwen2"])
def test_logits_match_hf(model_type):
    import torch

    hf_cfg, hf_model = _hf_tiny(model_type)
    cfg = config_from_hf(hf_cfg.to_dict(), name=model_type)
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": "float32"})
    assert cfg.qkv_bias == (model_type == "qwen2")

    state = {k: v.numpy() for k, v in hf_model.state_dict().items()}
    params = convert_hf_state_dict(state, cfg)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 17), dtype=np.int32)

    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens).long()).logits.numpy()

    ours = np.asarray(llama.forward_full(params, cfg, jnp.asarray(tokens)))
    np.testing.assert_allclose(ours, ref, rtol=5e-4, atol=5e-4)


def test_qwen2_bias_actually_loads():
    """Qwen2 QKV biases must land in the params (regression guard)."""
    hf_cfg, hf_model = _hf_tiny("qwen2")
    cfg = config_from_hf(hf_cfg.to_dict(), name="qwen2")
    state = {k: v.numpy() for k, v in hf_model.state_dict().items()}
    params = convert_hf_state_dict(state, cfg, dtype="float32")
    assert "bias" in params["layers"][0]["q"]
    assert "bias" not in params["layers"][0]["o"]
