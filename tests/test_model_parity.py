"""Logit parity of our JAX Llama/Qwen2 against HuggingFace transformers.

A tiny random-weight HF model is instantiated on CPU (torch), its state dict
converted through utils/checkpoint.convert_hf_state_dict, and full-sequence
logits compared.  This pins the whole stack: embedding, RoPE convention,
GQA, SwiGLU, RMSNorm, and the load-time transpose.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.utils.checkpoint import config_from_hf, convert_hf_state_dict


def _hf_tiny(model_type: str):
    import torch
    import transformers

    kwargs = dict(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=500000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    if model_type == "llama":
        cfg = transformers.LlamaConfig(**kwargs)
        model = transformers.LlamaForCausalLM(cfg)
    elif model_type == "gemma2":
        # Gemma-2: sandwich norms, GeGLU, (1+w) RMSNorm, embed scaling,
        # query_pre_attn_scalar, attn/final softcaps, and a sliding window
        # SMALLER than the test sequence so the alternating local/global
        # mask pattern actually bites.
        kwargs.update(head_dim=16, query_pre_attn_scalar=24.0,
                      attn_logit_softcapping=50.0,
                      final_logit_softcapping=30.0,
                      sliding_window=8, tie_word_embeddings=True)
        cfg = transformers.Gemma2Config(**kwargs)
        model = transformers.Gemma2ForCausalLM(cfg)
    else:
        cfg = transformers.Qwen2Config(**kwargs)
        model = transformers.Qwen2ForCausalLM(cfg)
    model.eval()
    torch.manual_seed(0)
    for p in model.parameters():
        with torch.no_grad():
            p.copy_(torch.randn_like(p) * 0.05)
    return cfg, model


@pytest.mark.parametrize("model_type", ["llama", "qwen2", "gemma2"])
def test_logits_match_hf(model_type):
    import torch

    hf_cfg, hf_model = _hf_tiny(model_type)
    cfg = config_from_hf(hf_cfg.to_dict(), name=model_type)
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": "float32"})
    assert cfg.qkv_bias == (model_type == "qwen2")

    state = {k: v.numpy() for k, v in hf_model.state_dict().items()}
    params = convert_hf_state_dict(state, cfg)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 17), dtype=np.int32)

    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens).long()).logits.numpy()

    ours = np.asarray(llama.forward_full(params, cfg, jnp.asarray(tokens)))
    np.testing.assert_allclose(ours, ref, rtol=5e-4, atol=5e-4)


def test_qwen2_bias_actually_loads():
    """Qwen2 QKV biases must land in the params (regression guard)."""
    hf_cfg, hf_model = _hf_tiny("qwen2")
    cfg = config_from_hf(hf_cfg.to_dict(), name="qwen2")
    state = {k: v.numpy() for k, v in hf_model.state_dict().items()}
    params = convert_hf_state_dict(state, cfg, dtype="float32")
    assert "bias" in params["layers"][0]["q"]
    assert "bias" not in params["layers"][0]["o"]


def test_gemma2_engine_matches_naive():
    """The serving paths (prefill scatter + paged gather decode +
    speculation) thread Gemma-2's per-layer sliding windows, softcaps, and
    query scale; greedy engine output must equal the dense forward.  The
    window (8) is smaller than prompt+generation so local layers really
    mask, and generation crosses block boundaries."""
    import jax

    from k8s_llm_monitor_tpu.models.config import ModelConfig
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )

    cfg = ModelConfig(
        name="tiny-gemma", vocab_size=160, hidden_size=32,
        intermediate_size=64, num_layers=4, num_heads=4, num_kv_heads=2,
        head_dim=8, dtype="float32", rope_theta=10_000.0,
        tie_embeddings=True, mlp_activation="gelu_tanh",
        sandwich_norms=True, rmsnorm_unit_offset=True,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        query_pre_attn_scalar=12.0, embed_scale=True,
        sliding_window=8,
        layer_types=("sliding_attention", "full_attention") * 2,
    )
    params = llama.init_params(jax.random.PRNGKey(3), cfg)
    eng = InferenceEngine(
        cfg, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=4,
                     max_blocks_per_seq=16, prefill_buckets=(16,),
                     spec_k=4, spec_rounds_per_iter=2),
        eos_id=-1,
    )
    rng = np.random.default_rng(5)
    prompts = [list(rng.integers(2, 160, size=n)) for n in (6, 11)]
    res = eng.generate(prompts, SamplingParams(max_tokens=12, temperature=0.0))
    for p, r in zip(prompts, res):
        seq = list(p)
        want = []
        for _ in range(12):
            lg = llama.forward_full(params, cfg, jnp.asarray([seq], jnp.int32))
            t = int(jnp.argmax(lg[0, -1]))
            seq.append(t)
            want.append(t)
        assert r.token_ids == want, \
            "gemma serving paths diverged from dense forward"


def test_config_from_hf_family_defaults():
    """Saved HF configs omit keys equal to class defaults; the translation
    must reproduce family defaults instead of neutral fallbacks."""
    from k8s_llm_monitor_tpu.utils.checkpoint import config_from_hf

    base = dict(vocab_size=64, hidden_size=16, intermediate_size=24,
                num_hidden_layers=4, num_attention_heads=4,
                num_key_value_heads=2)
    # Gemma-2 config.json as released (no layer_types, no
    # tie_word_embeddings, no softcap keys): tied embeddings, alternating
    # sliding/full windows, default softcaps and query scalar.
    g = config_from_hf({**base, "model_type": "gemma2",
                        "sliding_window": 8}, "g")
    assert g.tie_embeddings
    # sliding_window itself is a Gemma-2 class default (4096) that
    # re-saved configs omit — absence must not disable windows.
    g2 = config_from_hf({**base, "model_type": "gemma2"}, "g2")
    assert g2.sliding_window == 4096
    assert g2.layer_types is not None and len(g2.layer_types) == 4
    assert g.attn_logit_softcap == 50.0 and g.final_logit_softcap == 30.0
    assert g.query_pre_attn_scalar == 256.0
    assert g.layer_types == ("sliding_attention", "full_attention") * 2
    assert [g.layer_window(i) for i in range(4)] == [8, 0, 8, 0]
    # Qwen2 ships sliding_window=131072 with use_sliding_window=false —
    # must not enable windows (that would force gather attention and
    # reject pipeline/ring training for a windowless model).
    q = config_from_hf({**base, "model_type": "qwen2",
                        "sliding_window": 131072,
                        "use_sliding_window": False}, "q")
    assert q.sliding_window == 0 and not q.has_attn_extras
