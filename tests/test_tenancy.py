"""Multi-tenant hardening acceptance suite (docs/resilience.md, Tenancy).

Covers the three tenancy legs end to end on the CPU mesh:

  * **identity** — ``normalize_tenant`` at the trust boundary, header-wins
    HTTP parsing, tenant-tagged 429s, the ``/api/v1/stats`` tenants block;
  * **admission** — ``TokenBucket`` / ``TenantGovernor`` reservation
    protocol: refusal tagging, the request-token refund on token-quota
    refusal, settle idempotence, warm-start debt, accounting-only mode,
    the ``K8SLLM_TENANT_ENFORCE`` runtime flip, noisy-neighbor isolation,
    and the exact "charged tokens == delivered tokens" invariant across
    hedges, failovers, and a real mid-stream replica kill;
  * **KV isolation** — tenant-namespaced prefix caching on a live engine
    (cross-tenant lookups structurally miss, byte-exact output), the
    ``tenant_mismatch`` install outcome, and per-tenant block accounting,
    including under seeded ``lane_eviction`` faults.

``make chaos-tenant`` runs this module under K8SLLM_LOCKCHECK=1; the
flooding-tenant scenario is the acceptance gate: a tenant blasting 10x its
quota collects tenant-tagged 429s while a within-quota tenant's requests
all admit and complete byte-exactly.
"""

import json
import time
from http.client import HTTPConnection

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.fleet import (
    FleetRouter,
    HedgeConfig,
    LocalReplica,
    ReplicaRegistry,
    ReplicaStats,
)
from k8s_llm_monitor_tpu.fleet.replica import Replica
from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.monitor.config import Config
from k8s_llm_monitor_tpu.monitor.exporter import render_prometheus
from k8s_llm_monitor_tpu.monitor.models import AnalysisResponse
from k8s_llm_monitor_tpu.monitor.server import MonitorServer
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.resilience.tenancy import (
    DEFAULT_TENANT,
    TenantGovernor,
    TokenBucket,
    normalize_tenant,
    tenant_seed,
)
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    GenerationResult,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.service import EngineService, RequestHandle

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)
ECFG = dict(max_slots=4, num_blocks=64, block_size=8, max_blocks_per_seq=16,
            prefill_buckets=(16,), max_prefills_per_step=4,
            decode_steps_per_iter=4)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _mk_engine(params, **overrides):
    cfg = dict(ECFG)
    cfg.update(overrides)
    return InferenceEngine(CFG, params, EngineConfig(**cfg), eos_id=-1)


def _run(eng, max_steps=500):
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < max_steps, "engine wedged: work left after step budget"


def _naive_greedy(params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    return seq[len(prompt):]


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


# -- identity: normalize_tenant / tenant_seed ---------------------------------


def test_normalize_tenant_defaults_and_canonicalizes():
    assert normalize_tenant("") == DEFAULT_TENANT
    assert normalize_tenant(None) == DEFAULT_TENANT
    assert normalize_tenant("", default="fallback") == "fallback"
    # The slo_class idiom: strip + casefold once at the trust boundary.
    assert normalize_tenant("  Team-A ") == "team-a"
    assert normalize_tenant("a1_b.c-d") == "a1_b.c-d"


def test_normalize_tenant_env_default(monkeypatch):
    monkeypatch.setenv("K8SLLM_TENANT_DEFAULT", "acme")
    assert normalize_tenant("") == "acme"
    assert normalize_tenant(None) == "acme"
    # An explicit default still wins over the env fallback.
    assert normalize_tenant("", default="x") == "x"


@pytest.mark.parametrize("bad", ["two words", "-leading", ".dot", "a" * 65,
                                 "ünïcode", "semi;colon"])
def test_normalize_tenant_rejects_malformed(bad):
    with pytest.raises(ValueError):
        normalize_tenant(bad)


def test_tenant_seed_is_stable_and_disjoint():
    a, b = tenant_seed("team-a"), tenant_seed("team-b")
    assert len(a) == 32 and len(b) == 32
    assert a != b
    assert tenant_seed("team-a") == a
    # The default namespace is a seed like any other, never b"".
    assert tenant_seed(DEFAULT_TENANT) != b""


# -- TokenBucket --------------------------------------------------------------


def test_token_bucket_disabled_when_rate_zero():
    b = TokenBucket(0.0, 10.0)
    assert b.available() == float("inf")
    assert b.try_take(10 ** 9) == 0.0


def test_token_bucket_take_refuse_retry_hint_refill():
    now = [0.0]
    b = TokenBucket(2.0, 4.0, clock=lambda: now[0])
    assert b.try_take(4.0) == 0.0
    # Empty: the hint is the exact time for 2 tokens to refill at 2/s.
    assert b.try_take(2.0) == pytest.approx(1.0)
    assert b.refusals == 1
    now[0] += 1.0
    assert b.try_take(2.0) == 0.0
    assert b.takes == 2


def test_token_bucket_debt_and_refund_clamp():
    now = [0.0]
    b = TokenBucket(1.0, 5.0, clock=lambda: now[0])
    b.force_take(8.0)
    assert b.available() == pytest.approx(-3.0)
    # Refills pay the debt down before admissions succeed again.
    assert b.try_take(1.0) > 0.0
    b.give(100.0)
    assert b.available() == pytest.approx(5.0)  # clamped at burst


# -- TenantGovernor: the reservation protocol ---------------------------------


def _gov(**kw):
    now = [0.0]
    kw.setdefault("clock", lambda: now[0])
    return TenantGovernor(**kw), now


def test_governor_rate_refusal_is_tenant_tagged():
    gov, _ = _gov(requests_per_s=1.0, request_burst=1.0)
    gov.admit("team-a", "r0", max_tokens=4)
    with pytest.raises(OverloadedError) as ei:
        gov.admit("team-a", "r1", max_tokens=4)
    exc = ei.value
    assert exc.tenant == "team-a"
    assert exc.retriable is True
    assert exc.retry_after_s > 0.0
    snap = gov.snapshot()["team-a"]
    assert snap["admitted"] == 1
    assert snap["quota_refusals"] == 1 and snap["sheds"] == 1
    assert snap["inflight"] == 1


def test_governor_token_refusal_refunds_the_request_token():
    gov, _ = _gov(requests_per_s=1.0, request_burst=1.0,
                  tokens_per_s=0.001, token_burst=10.0)
    # The oversized request is refused on token quota — and must hand its
    # request-rate token back, or this refusal would starve the tenant's
    # next (within-quota) request on the rate dimension.
    with pytest.raises(OverloadedError) as ei:
        gov.admit("team-a", "big", max_tokens=50)
    assert "token quota" in str(ei.value)
    gov.admit("team-a", "small", max_tokens=5)  # must not raise
    snap = gov.snapshot()["team-a"]
    assert snap["admitted"] == 1 and snap["quota_refusals"] == 1


def test_governor_settle_refunds_and_is_idempotent():
    gov, _ = _gov(tokens_per_s=0.001, token_burst=100.0)
    gov.admit("team-a", "r0", max_tokens=10, prompt_bytes=33)
    assert gov.quota_remaining("team-a") == pytest.approx(90.0)
    gov.note_delivered("r0", 3)
    gov.note_delivered("r0", 1)
    assert gov.settle("r0") == 4
    assert gov.charged_tokens("team-a") == 4
    # Only delivered tokens stay charged; the reservation's unused 6 refund.
    assert gov.quota_remaining("team-a") == pytest.approx(96.0)
    assert gov.settle("r0") == 0  # idempotent: no double refund, no recharge
    assert gov.charged_tokens("team-a") == 4
    snap = gov.snapshot()["team-a"]
    assert snap["inflight"] == 0 and snap["admitted_bytes"] == 33


def test_governor_restore_re_reserves_into_debt():
    gov, _ = _gov(tokens_per_s=0.001, token_burst=10.0)
    # Warm start: 3 of 8 tokens were already delivered pre-crash; only the
    # remaining 5 are force-taken (the dead process charged the rest).
    gov.restore("wal-0", "team-a", max_tokens=8, delivered=3)
    assert gov.quota_remaining("team-a") == pytest.approx(5.0)
    gov.restore("wal-0", "team-a", max_tokens=8, delivered=3)  # idempotent
    assert gov.quota_remaining("team-a") == pytest.approx(5.0)
    gov.note_delivered("wal-0", 5)  # replay finishes the other 5
    assert gov.settle("wal-0") == 8
    assert gov.charged_tokens("team-a") == 8
    assert gov.quota_remaining("team-a") == pytest.approx(5.0)


def test_governor_accounting_only_mode_never_refuses():
    gov, _ = _gov(tokens_per_s=0.001, token_burst=4.0, enforce=False)
    for i in range(3):
        gov.admit("team-a", f"r{i}", max_tokens=4)  # 12 >> burst 4: no raise
    snap = gov.snapshot()["team-a"]
    assert snap["admitted"] == 3 and snap["quota_refusals"] == 0
    assert snap["quota_remaining"] < 0  # the debt is still visible


def test_governor_env_flips_enforcement_on(monkeypatch):
    gov, _ = _gov(requests_per_s=1.0, request_burst=1.0, enforce=False)
    monkeypatch.setenv("K8SLLM_TENANT_ENFORCE", "1")
    gov.admit("team-a", "r0", max_tokens=1)
    with pytest.raises(OverloadedError):
        gov.admit("team-a", "r1", max_tokens=1)
    monkeypatch.setenv("K8SLLM_TENANT_ENFORCE", "0")  # "0" means off
    gov.admit("team-a", "r2", max_tokens=1)


def test_governor_buckets_are_per_tenant():
    gov, _ = _gov(requests_per_s=0.001, request_burst=2.0)
    gov.admit("noisy", "n0", max_tokens=1)
    gov.admit("noisy", "n1", max_tokens=1)
    with pytest.raises(OverloadedError):
        gov.admit("noisy", "n2", max_tokens=1)
    # The quiet tenant's bucket is untouched by the noisy tenant's flood.
    gov.admit("quiet", "q0", max_tokens=1)
    gov.admit("quiet", "q1", max_tokens=1)
    snap = gov.snapshot()
    assert snap["quiet"]["quota_refusals"] == 0
    assert snap["noisy"]["quota_refusals"] == 1


def test_governor_evicts_idle_tenant_at_cap():
    gov, _ = _gov(max_tenants=2)
    gov.admit("t-idle", "r0", max_tokens=0)
    gov.settle("r0")                          # idle: nothing in flight
    gov.admit("t-busy", "r1", max_tokens=0)   # keeps an open reservation
    gov.admit("t-new", "r2", max_tokens=0)
    snap = gov.snapshot()
    assert set(snap) == {"t-busy", "t-new"}   # LRU-idle evicted, busy kept
    assert snap["t-busy"]["inflight"] == 1


# -- HTTP trust boundary ------------------------------------------------------


class _CaptureAnalysis:
    backend = None

    def __init__(self):
        self.tenants = []

    def query(self, question, slo_class="interactive", tenant=""):
        self.tenants.append(tenant)
        return AnalysisResponse(request_id="t", status="success",
                                result={"answer": "ok"})


class _RefusingAnalysis:
    backend = None

    def __init__(self, exc):
        self._exc = exc

    def query(self, question, slo_class="interactive", tenant=""):
        raise self._exc


def _post_query(srv, body=None, headers=None):
    conn = HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        payload = json.dumps({"question": "why?", **(body or {})})
        conn.request("POST", "/api/v1/query", body=payload,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp, resp.read()
    finally:
        conn.close()


def _get(srv, path):
    conn = HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp, resp.read()
    finally:
        conn.close()


def test_http_header_wins_over_body_then_defaults():
    analysis = _CaptureAnalysis()
    srv = MonitorServer(analysis=analysis, host="127.0.0.1", port=0)
    srv.start()
    try:
        resp, _ = _post_query(srv, body={"tenant": "team-b"},
                              headers={"X-Tenant-Id": " Team-A "})
        assert resp.status == 200
        resp, _ = _post_query(srv, body={"tenant": "team-b"})
        assert resp.status == 200
        resp, _ = _post_query(srv)
        assert resp.status == 200
    finally:
        srv.stop()
    assert analysis.tenants == ["team-a", "team-b", DEFAULT_TENANT]


def test_http_malformed_tenant_is_400_before_engine_work():
    analysis = _CaptureAnalysis()
    srv = MonitorServer(analysis=analysis, host="127.0.0.1", port=0)
    srv.start()
    try:
        resp, body = _post_query(srv, headers={"X-Tenant-Id": "no spaces"})
        assert resp.status == 400
        assert b"tenant" in body
    finally:
        srv.stop()
    assert analysis.tenants == []  # the backend never saw the request


def test_http_quota_429_names_the_tenant():
    exc = OverloadedError("tenant 'team-a' over token quota",
                          retriable=True, retry_after_s=1.2,
                          tenant="team-a")
    srv = MonitorServer(analysis=_RefusingAnalysis(exc),
                        host="127.0.0.1", port=0)
    srv.start()
    try:
        resp, body = _post_query(srv)
        assert resp.status == 429
        assert resp.getheader("Retry-After") == "2"  # ceil(1.2)
        payload = json.loads(body)
        assert payload["error_kind"] == "overloaded"
        assert payload["tenant"] == "team-a"
    finally:
        srv.stop()


def test_http_stats_exposes_tenant_accounting():
    gov = TenantGovernor()
    gov.admit("team-a", "r0", max_tokens=8)
    gov.note_delivered("r0", 8)
    gov.settle("r0")
    srv = MonitorServer(analysis=_CaptureAnalysis(),
                        host="127.0.0.1", port=0)
    srv.governor = gov
    srv.start()
    try:
        resp, body = _get(srv, "/api/v1/stats")
        assert resp.status == 200
        block = json.loads(body)["tenants"]["team-a"]
        assert block["admitted"] == 1
        assert block["charged_tokens"] == 8
        assert block["inflight"] == 0
    finally:
        srv.stop()


# -- exporter cardinality discipline ------------------------------------------


def test_exporter_caps_tenant_label_at_top_k_plus_other():
    gov = TenantGovernor()
    # t0..t5 admit 1..6 requests; with top_k=3 only t5,t4,t3 get rows.
    for i in range(6):
        for j in range(i + 1):
            rid = f"t{i}-{j}"
            gov.admit(f"t{i}", rid, max_tokens=0)
            gov.settle(rid)
    cfg = Config()
    cfg.tenancy.top_k_metrics = 3
    srv = MonitorServer(config=cfg, analysis=_CaptureAnalysis())
    srv.governor = gov
    text = render_prometheus(srv)

    for family in ("tenant_requests_total", "tenant_shed_total",
                   "tenant_kv_blocks", "tenant_quota_remaining"):
        rows = [ln for ln in text.splitlines()
                if ln.startswith(f"k8s_llm_monitor_{family}{{")]
        # Exactly K named tenants + the aggregate bucket: an abusive
        # client minting fresh ids grows the scrape by exactly nothing.
        assert len(rows) == 4, (family, rows)
        assert any('tenant="other"' in ln for ln in rows), family

    assert 'k8s_llm_monitor_tenant_requests_total{tenant="t5"} 6' in text
    # The spilled tail (t2,t1,t0 = 3+2+1) aggregates, it does not vanish.
    assert 'k8s_llm_monitor_tenant_requests_total{tenant="other"} 6' in text
    # Bucket levels don't sum across tenants: the aggregate is NaN.
    assert ('k8s_llm_monitor_tenant_quota_remaining{tenant="other"} NaN'
            in text)
    # The render passes its own exposition lint.
    assert "k8s_llm_monitor_exposition_lint_errors 0" in text


def test_exporter_tenant_families_absent_without_governor():
    srv = MonitorServer(analysis=_CaptureAnalysis())
    text = render_prometheus(srv)
    assert "tenant_requests_total" not in text
    assert "k8s_llm_monitor_exposition_lint_errors 0" in text


# -- fleet charge placement: scripted fakes (deterministic, fast) -------------


class _TokReplica(Replica):
    """Token-level fake (next = last + 1 mod 997): the replay contract is
    checkable token by token.  ``fail_after=n`` emits n tokens then dies
    (the router's failover trigger); ``stall`` never emits (hedge bait)."""

    supports_tokens = True

    def __init__(self, rid, fail_after=None, stall=False):
        self.replica_id = rid
        self.fail_after = fail_after
        self.stall = stall
        self.calls = []
        self.cancelled = []

    def readyz(self):
        return True

    def stats(self):
        return ReplicaStats(total_slots=4)

    def generate(self, prompt_ids, sampling=None, request_id=None,
                 deadline_s=0.0, slo_class="standard", tenant="public"):
        sampling = sampling or SamplingParams()
        self.calls.append((list(prompt_ids), tenant))
        h = RequestHandle(request_id or "r", eos_id=-1,
                          cancel_fn=lambda rid: self.cancelled.append(rid))
        if self.stall:
            return h
        start = prompt_ids[-1] if prompt_ids else 0
        toks = [(start + 1 + i) % 997 for i in range(sampling.max_tokens)]
        if self.fail_after is not None:
            emit = toks[: self.fail_after]
            for t in emit:
                h._push([t], None)
            h._push([], GenerationResult(
                request_id=h.request_id, token_ids=list(emit),
                finish_reason="error", ttft_s=0.0, latency_s=0.0,
                error="injected death"))
        else:
            for t in toks:
                h._push([t], None)
            h._push([], GenerationResult(
                request_id=h.request_id, token_ids=list(toks),
                finish_reason="length", ttft_s=0.0, latency_s=0.0))
        return h


def _scripted_fleet(*reps):
    reg = ReplicaRegistry()
    for r in reps:
        reg.add(r)
    reg.refresh()
    return reg


def test_hedge_loser_never_double_charges():
    gov = TenantGovernor(tokens_per_s=0.001, token_burst=100.0)
    a = _TokReplica("a", stall=True)
    b = _TokReplica("b")
    router = FleetRouter(_scripted_fleet(a, b), policy="round_robin",
                         hedge=HedgeConfig(enabled=True, fixed_delay_s=0.05),
                         governor=gov)
    h = router.submit([5, 6, 7], SamplingParams(max_tokens=6),
                      tenant="team-a")
    res = h.result(timeout=10)
    assert res.finish_reason == "length" and len(res.token_ids) == 6
    assert _wait(lambda: router.counters()["hedges_fired"] == 1)
    assert _wait(lambda: gov.snapshot()["team-a"]["inflight"] == 0)
    # One logical request, two dispatches, one charge.
    assert gov.charged_tokens("team-a") == 6
    assert gov.quota_remaining("team-a") == pytest.approx(94.0, abs=0.5)


def test_failover_replay_charges_delivered_exactly_once():
    gov = TenantGovernor(tokens_per_s=0.001, token_burst=100.0)
    a = _TokReplica("a", fail_after=2)
    b = _TokReplica("b")
    router = FleetRouter(_scripted_fleet(a, b), policy="round_robin",
                         max_failovers=2, governor=gov)
    h = router.submit([10, 11, 12], SamplingParams(max_tokens=6),
                      tenant="team-a")
    toks = list(h.stream(timeout=10))
    res = h.result(timeout=10)
    assert res.finish_reason == "length"
    assert toks == [(13 + i) % 997 for i in range(6)]  # no dup, no gap
    assert _wait(lambda: router.counters()["failovers"] == 1)
    assert _wait(lambda: gov.snapshot()["team-a"]["inflight"] == 0)
    # 2 tokens died with replica a, then the replay delivered all 6: the
    # tenant is charged 6, not 8 — the replay rode the same reservation.
    assert gov.charged_tokens("team-a") == 6


def test_router_quota_refusal_precedes_any_dispatch():
    gov = TenantGovernor(requests_per_s=0.001, request_burst=1.0)
    a = _TokReplica("a")
    b = _TokReplica("b")
    router = FleetRouter(_scripted_fleet(a, b), policy="round_robin",
                         governor=gov)
    router.submit([1, 2], SamplingParams(max_tokens=2),
                  tenant="team-a").result(timeout=10)
    with pytest.raises(OverloadedError) as ei:
        router.submit([3, 4], SamplingParams(max_tokens=2), tenant="team-a")
    assert ei.value.tenant == "team-a"
    # The refused request never reached a replica.
    assert len(a.calls) + len(b.calls) == 1


# -- engine-level acceptance (live engines; make chaos-tenant) ----------------


@pytest.mark.chaos
@pytest.mark.slow  # live engine + greedy oracle; covered by make chaos-tenant
def test_flooding_tenant_rate_limited_quiet_tenant_unharmed(params):
    """The acceptance gate: a tenant blasting far past its request-rate
    quota collects tenant-tagged 429s, while a within-quota tenant's
    interactive requests all admit and complete byte-exactly — per-tenant
    buckets mean the flood cannot consume the quiet tenant's budget."""
    gov = TenantGovernor(requests_per_s=0.5, request_burst=4.0)
    svc = EngineService(_mk_engine(params), governor=gov)
    rng = np.random.default_rng(41)
    try:
        flood, refused = [], 0
        for i in range(20):
            p = [int(t) for t in rng.integers(3, 300, size=8)]
            try:
                flood.append(svc.submit(
                    p, SamplingParams(max_tokens=4),
                    request_id=f"noisy{i}", tenant="noisy",
                    slo_class="standard"))
            except OverloadedError as exc:
                refused += 1
                assert exc.tenant == "noisy"
                assert exc.retriable and exc.retry_after_s > 0
        assert refused >= 15  # burst 4 (+ epsilon refill) admitted, rest 429

        for i in range(4):
            p = [int(t) for t in rng.integers(3, 300, size=8)]
            h = svc.submit(p, SamplingParams(max_tokens=4),
                           request_id=f"quiet{i}", tenant="quiet",
                           slo_class="interactive")
            res = h.result(timeout=60)
            assert res.finish_reason == "length"
            assert res.token_ids == _naive_greedy(params, p, 4)
        for h in flood:
            h.result(timeout=60)

        snap = gov.snapshot()
        assert snap["noisy"]["quota_refusals"] == refused
        assert snap["quiet"]["quota_refusals"] == 0
        assert snap["quiet"]["sheds"] == 0
        assert snap["noisy"]["inflight"] == 0 and snap["quiet"]["inflight"] == 0
    finally:
        svc.stop(timeout=10)


@pytest.mark.chaos
@pytest.mark.slow  # 2 live engines + mid-stream kill; make chaos-tenant
def test_chaos_replica_kill_charged_equals_delivered(params):
    """The quota-exactness regression gate (fleet edition): a replica dies
    while actively decoding tenant streams; every stream completes on the
    survivor and the governor's settled charge equals the tokens the
    callers actually received — failover replays ride the original
    reservation, never a second charge."""
    gov = TenantGovernor(tokens_per_s=0.001, token_burst=10_000.0)
    reps = [LocalReplica(f"r{i}", service=EngineService(_mk_engine(params)))
            for i in range(2)]
    reg = ReplicaRegistry()
    for r in reps:
        reg.add(r)
    reg.refresh()
    router = FleetRouter(reg, policy="affinity", max_failovers=2,
                         governor=gov)
    rng = np.random.default_rng(33)
    n_tok = 16
    prompts = [[int(t) for t in rng.integers(3, 300, size=4)]
               for _ in range(16)]
    try:
        handles = [router.submit(p, SamplingParams(max_tokens=n_tok),
                                 tenant="team-a")
                   for p in prompts]
        victim = reps[0]
        assert _wait(lambda: victim.service.engine.active_slots > 0,
                     timeout=60), "victim never received work"
        victim.kill()

        delivered = 0
        for p, h in zip(prompts, handles):
            toks = list(h.stream(timeout=120))
            res = h.result(timeout=120)
            assert res.finish_reason == "length", (res.finish_reason,
                                                   res.error)
            assert toks == res.token_ids
            assert toks == _naive_greedy(params, p, n_tok), \
                "failover duplicated or lost tokens"
            delivered += len(toks)

        assert _wait(lambda: gov.snapshot()["team-a"]["inflight"] == 0)
        assert gov.charged_tokens("team-a") == delivered  # == 16 * 16
        remaining = gov.quota_remaining("team-a")
        assert remaining == pytest.approx(10_000.0 - delivered, abs=1.0)
        assert router.counters()["failovers"] >= 1
    finally:
        for r in reps:
            r.close()


@pytest.mark.slow  # live engine prefix caching; covered by make chaos-tenant
def test_engine_kv_namespace_blocks_cross_tenant_reuse(params):
    """Two tenants submit the identical prompt: the second tenant's lookup
    must structurally miss (disjoint digest chains), both outputs stay
    byte-exact, and the per-tenant block accounting sees both namespaces."""
    eng = _mk_engine(params)
    prompt = [(7 * i) % 290 + 3 for i in range(17)]  # crosses 2 full blocks
    oracle = _naive_greedy(params, prompt, 4)

    def run(rid, tenant):
        eng.submit(GenerationRequest(
            request_id=rid, prompt_ids=list(prompt),
            sampling=SamplingParams(max_tokens=4), tenant=tenant))
        _run(eng)
        return eng._results[rid].token_ids

    assert run("a1", "team-a") == oracle
    misses_after_a = eng.prefix_cache.misses
    assert eng.prefix_cache.hits == 0

    # Same tokens, different tenant: no cross-tenant hit, ever.
    assert run("b1", "team-b") == oracle
    assert eng.prefix_cache.hits == 0
    assert eng.prefix_cache.misses > misses_after_a

    # Same tenant does hit its own namespace.
    assert run("a2", "team-a") == oracle
    assert eng.prefix_cache.hits >= 1

    blocks = eng.kv_tier_stats()["tenant_blocks"]
    assert blocks.get("team-a", 0) > 0 and blocks.get("team-b", 0) > 0


@pytest.mark.slow  # 2 live engines; covered by make chaos-tenant
def test_install_prefix_refuses_tenant_mismatch(params):
    """KVX1 blobs carry their namespace: a receiver expecting another
    tenant refuses the install as a distinct outcome (no silent
    cross-tenant cache pollution on migration paths)."""
    src = _mk_engine(params)
    prompt = [(11 * i) % 290 + 3 for i in range(24)]
    src.submit(GenerationRequest(
        request_id="warm", prompt_ids=list(prompt),
        sampling=SamplingParams(max_tokens=2), tenant="team-a"))
    _run(src)
    blob = src.export_prefix(list(prompt), tenant="team-a")
    assert blob is not None

    dst = _mk_engine(params)
    assert dst.install_prefix(blob, expected_tenant="team-b") == \
        "tenant_mismatch"
    assert dst.prefix_cache.misses == 0 and dst.prefix_cache.hits == 0
    assert dst.install_prefix(blob, expected_tenant="team-a") == "installed"
    # expected_tenant=None: an unpinned install trusts the blob's header.
    assert dst.install_prefix(blob, expected_tenant=None) == "cached"


@pytest.mark.chaos
@pytest.mark.slow  # seeded faults + greedy oracle; make chaos-tenant
def test_mixed_tenant_burst_byte_exact_under_lane_eviction_faults(params):
    """Tenant isolation holds on the failure path too: a slot-starved
    mixed-tenant burst forces a class preemption whose seeded
    ``lane_eviction`` fault fires mid-eviction — every tenant's output
    stays byte-exact and the per-tenant block accounting stays sane."""
    eng = _mk_engine(params, max_slots=2)
    get_injector().reset(seed=1234)
    get_injector().arm("lane_eviction", rate=1.0, times=1)
    try:
        reqs = [("a-b0", "team-a", "batch", [5, 6, 7], 60),
                ("b-b1", "team-b", "batch", [8, 9, 10], 60),
                ("a-i0", "team-a", "interactive", [11, 12, 13], 6)]
        for rid, tenant, cls, p, n in reqs[:2]:
            eng.submit(GenerationRequest(
                request_id=rid, prompt_ids=list(p),
                sampling=SamplingParams(max_tokens=n),
                tenant=tenant, slo_class=cls))
        eng.step()
        eng.step()
        # The interactive arrival preempts a running batch lane; the
        # armed fault fails that eviction mid-flight and the retry
        # (injector exhausted) completes it.
        rid, tenant, cls, p, n = reqs[2]
        eng.submit(GenerationRequest(
            request_id=rid, prompt_ids=list(p),
            sampling=SamplingParams(max_tokens=n),
            tenant=tenant, slo_class=cls))
        _run(eng, max_steps=2000)
        assert get_injector().fired("lane_eviction") == 1
        for rid, tenant, cls, p, n in reqs:
            res = eng._results[rid]
            assert res.finish_reason == "length", (rid, res.finish_reason)
            assert res.token_ids == _naive_greedy(params, p, n), rid
        blocks = eng.kv_tier_stats()["tenant_blocks"]
        assert set(blocks) <= {"team-a", "team-b", DEFAULT_TENANT}
    finally:
        get_injector().reset()
