"""Fleet tier: routing policies, registry health/breakers, hedged dispatch,
and mid-stream failover.

Unit tests run on scripted fake replicas (a deterministic "model" whose next
token is last-token+1, so the failover replay contract is checkable without
an engine).  Acceptance tests run real in-process fleets: affinity must beat
round-robin on prefix-cache hit rate, and killing a replica under >= 32
concurrent streams must lose zero tokens (``make chaos-fleet``).
"""

import hashlib
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.fleet import (
    Candidate,
    FleetRouter,
    HedgeConfig,
    LeastLoadedPolicy,
    LocalReplica,
    PrefixAffinityPolicy,
    ReplicaRegistry,
    ReplicaStats,
    RoundRobinPolicy,
)
from k8s_llm_monitor_tpu.fleet.frontend import build_router_server
from k8s_llm_monitor_tpu.fleet.replica import Replica
from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.monitor.analysis import AnalysisEngine, LocalEngineBackend
from k8s_llm_monitor_tpu.monitor.config import Config, LLMConfig
from k8s_llm_monitor_tpu.monitor.server import MonitorServer
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationResult,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.service import EngineService, RequestHandle
from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)
ECFG = dict(max_slots=4, num_blocks=64, block_size=8, max_blocks_per_seq=16,
            prefill_buckets=(16,), max_prefills_per_step=4,
            decode_steps_per_iter=4)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _naive_greedy(params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    return seq[len(prompt):]


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


# ---------------------------------------------------------------------------
# Scripted fakes
# ---------------------------------------------------------------------------


class ScriptedReplica(Replica):
    """Token-level fake.  Its "model" is next = last + 1 (mod 997), so
    folding emitted tokens into the prompt continues the sequence exactly
    like a deterministic LM — the replay contract is checkable token by
    token.  ``fail_after=n`` emits n tokens then resolves with an error
    result (the router's failover trigger); ``stall`` never emits."""

    supports_tokens = True

    def __init__(self, rid, fail_after=None, stall=False, ready=True):
        self.replica_id = rid
        self.fail_after = fail_after
        self.stall = stall
        self.ready = ready
        self.calls = []
        self.cancelled = []

    def readyz(self):
        return self.ready

    def stats(self):
        return ReplicaStats(total_slots=4)

    def generate(self, prompt_ids, sampling=None, request_id=None,
                 deadline_s=0.0, slo_class="standard", tenant="public"):
        sampling = sampling or SamplingParams()
        self.calls.append((list(prompt_ids), sampling, request_id))
        h = RequestHandle(request_id or "r", eos_id=-1,
                          cancel_fn=lambda rid: self.cancelled.append(rid))
        if self.stall:
            return h
        start = prompt_ids[-1] if prompt_ids else 0
        toks = [(start + 1 + i) % 997 for i in range(sampling.max_tokens)]
        if self.fail_after is not None:
            emit = toks[: self.fail_after]
            for t in emit:
                h._push([t], None)
            h._push([], GenerationResult(
                request_id=h.request_id, token_ids=list(emit),
                finish_reason="error", ttft_s=0.0, latency_s=0.0,
                error="injected death"))
        else:
            for t in toks:
                h._push([t], None)
            h._push([], GenerationResult(
                request_id=h.request_id, token_ids=list(toks),
                finish_reason="length", ttft_s=0.0, latency_s=0.0))
        return h


class ScriptedQueryReplica(Replica):
    """Text-level fake for the query/stream routing path."""

    supports_query = True

    def __init__(self, rid, answer="hello world", fail_stream_after=None,
                 ready=True):
        self.replica_id = rid
        self.answer = answer
        self.fail_stream_after = fail_stream_after
        self.ready = ready
        self.queries = []

    def readyz(self):
        return self.ready

    def stats(self):
        return ReplicaStats(total_slots=4)

    def query(self, question, slo_class="interactive", tenant="public"):
        self.queries.append(question)
        return {"status": "success", "served_by": self.replica_id}

    def query_stream(self, question, slo_class="interactive",
                     tenant="public"):
        def chunks():
            for i, ch in enumerate(self.answer):
                if (self.fail_stream_after is not None
                        and i >= self.fail_stream_after):
                    raise OSError("stream died")
                yield ch
        return f"{self.replica_id}-q", "tiny", chunks()


def _registry(*reps, **kw):
    reg = ReplicaRegistry(**kw)
    for r in reps:
        reg.add(r)
    reg.refresh()
    return reg


def _cand(rid, busy=0, total=4, qtok=0, inflight=0):
    return Candidate(rid, None,
                     ReplicaStats(busy_slots=busy, total_slots=total,
                                  queue_tokens=qtok), inflight)


# ---------------------------------------------------------------------------
# Routing policies
# ---------------------------------------------------------------------------


def test_round_robin_rotates():
    pol = RoundRobinPolicy()
    cands = [_cand("a"), _cand("b"), _cand("c")]
    firsts = [pol.rank(list(cands), b"x")[0].replica_id for _ in range(4)]
    assert firsts == ["a", "b", "c", "a"]


def test_least_loaded_orders_by_score():
    pol = LeastLoadedPolicy()
    ranked = pol.rank([_cand("a", qtok=100), _cand("b", busy=4), _cand("c")],
                      b"")
    assert [c.replica_id for c in ranked] == ["c", "b", "a"]


def test_affinity_is_deterministic_and_remap_stable():
    pol = PrefixAffinityPolicy()
    cands = [_cand(r) for r in ("a", "b", "c")]
    digests = [hashlib.sha256(bytes([i])).digest() for i in range(24)]
    winners = {d: pol.rank(list(cands), d)[0].replica_id for d in digests}
    assert all(pol.rank(list(cands), d)[0].replica_id == winners[d]
               for d in digests)
    assert len(set(winners.values())) > 1   # keys spread over the fleet
    # Consistent hashing: dropping one replica only remaps its own keys.
    subset = [c for c in cands if c.replica_id != "c"]
    for d in digests:
        if winners[d] != "c":
            assert pol.rank(list(subset), d)[0].replica_id == winners[d]


def test_affinity_saturated_winner_spills_but_stays_preferred():
    pol = PrefixAffinityPolicy()
    digest = b""
    for i in range(64):
        digest = hashlib.sha256(bytes([i])).digest()
        if pol.rank([_cand("a"), _cand("b")], digest)[0].replica_id == "a":
            break
    sat = [_cand("a", busy=4, total=4, qtok=50), _cand("b")]
    assert pol.rank(sat, digest)[0].replica_id == "b"   # spilled
    assert pol.preferred(sat, digest) == "a"            # accounting target


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_probe_failure_feeds_breaker():
    good, bad = ScriptedReplica("good"), ScriptedReplica("bad")
    reg = _registry(good, bad, breaker_failures=2, breaker_cooldown_s=60.0)
    assert {c.replica_id for c in reg.candidates()} == {"good", "bad"}
    bad.ready = False
    reg.refresh()
    assert {c.replica_id for c in reg.candidates()} == {"good"}
    reg.refresh()                           # second failure trips the breaker
    snap = reg.snapshot()["bad"]
    assert snap["ready"] is False and snap["breaker_state"] == "open"


def test_registry_contains_probe_exceptions():
    class Exploding(Replica):
        replica_id = "boom"
        supports_tokens = True

        def readyz(self):
            raise OSError("connection refused")

    reg = _registry(Exploding())
    assert reg.candidates() == []
    assert "probe failed" in reg.snapshot()["boom"]["reason"]


def test_registry_inflight_and_failure_accounting():
    reg = _registry(ScriptedReplica("a"))
    reg.note_dispatch("a")
    reg.note_dispatch("a")
    assert reg.snapshot()["a"]["inflight"] == 2
    reg.note_done("a", ok=True)
    reg.note_done("a", ok=False)
    snap = reg.snapshot()["a"]
    assert snap["inflight"] == 0 and snap["failures"] == 1


def test_mark_unready_takes_effect_before_next_probe():
    reg = _registry(ScriptedReplica("a"), ScriptedReplica("b"))
    reg.mark_unready("a", "observed dead")
    assert [c.replica_id for c in reg.candidates()] == ["b"]


# ---------------------------------------------------------------------------
# Router: dispatch, failover, hedging (scripted replicas)
# ---------------------------------------------------------------------------


def test_submit_streams_through_replica():
    a = ScriptedReplica("a")
    reg = _registry(a)
    router = FleetRouter(reg, policy="round_robin")
    h = router.submit([5], SamplingParams(max_tokens=4))
    toks = list(h.stream(timeout=10))
    res = h.result(timeout=10)
    assert toks == [6, 7, 8, 9] == res.token_ids
    assert res.finish_reason == "length"
    assert _wait(lambda: router.counters()["completed"] == 1)
    assert _wait(lambda: reg.snapshot()["a"]["inflight"] == 0)
    assert router.counters()["dispatches"] == 1


def test_empty_fleet_sheds():
    router = FleetRouter(ReplicaRegistry())
    with pytest.raises(OverloadedError):
        router.submit([1], SamplingParams(max_tokens=2))
    assert router.counters()["sheds"] == 1


def test_midstream_failover_replays_remainder_exactly():
    a = ScriptedReplica("a", fail_after=3)
    b = ScriptedReplica("b")
    reg = _registry(a, b)
    router = FleetRouter(reg, policy="round_robin", max_failovers=2)
    h = router.submit([5], SamplingParams(max_tokens=8))
    toks = list(h.stream(timeout=10))
    res = h.result(timeout=10)
    assert res.finish_reason == "length"
    assert toks == res.token_ids == [6, 7, 8, 9, 10, 11, 12, 13]
    # Replay contract: prompt + emitted folded in, budget trimmed, fresh
    # attempt id, dead replica excluded.
    prompt, sampling, rid = b.calls[0]
    assert prompt == [5, 6, 7, 8]
    assert sampling.max_tokens == 5
    assert rid.endswith("-a1")
    assert _wait(lambda: router.counters()["failovers"] == 1)
    c = router.counters()
    assert c["completed"] == 1 and c["failed"] == 0
    assert reg.snapshot()["a"]["ready"] is False


def test_failover_budget_exhausted_fails_with_partial_tokens():
    a = ScriptedReplica("a", fail_after=2)
    b = ScriptedReplica("b", fail_after=2)
    router = FleetRouter(_registry(a, b), policy="round_robin",
                         max_failovers=1)
    h = router.submit([5], SamplingParams(max_tokens=8))
    toks = list(h.stream(timeout=10))
    res = h.result(timeout=10)
    assert res.finish_reason == "error"
    assert "failover budget exhausted" in res.error
    assert toks == [6, 7, 8, 9]           # both incarnations' tokens, no dup
    assert router.counters()["failed"] == 1


def test_death_after_full_budget_completes_trimmed():
    a = ScriptedReplica("a", fail_after=4)   # whole budget, then dies
    b = ScriptedReplica("b")
    router = FleetRouter(_registry(a, b), policy="round_robin")
    h = router.submit([5], SamplingParams(max_tokens=4))
    res = h.result(timeout=10)
    assert res.finish_reason == "length" and res.token_ids == [6, 7, 8, 9]
    assert b.calls == []                  # nothing left to regenerate


def test_hedge_fires_and_second_replica_wins():
    a = ScriptedReplica("a", stall=True)
    b = ScriptedReplica("b")
    reg = _registry(a, b)
    router = FleetRouter(reg, policy="round_robin",
                         hedge=HedgeConfig(enabled=True, fixed_delay_s=0.05))
    h = router.submit([5], SamplingParams(max_tokens=4))
    toks = list(h.stream(timeout=10))
    res = h.result(timeout=10)
    assert toks == [6, 7, 8, 9] and res.finish_reason == "length"
    c = router.counters()
    assert c["hedges_fired"] == 1 and c["hedges_won"] == 1
    assert b.calls[0][2].endswith("-h")
    assert _wait(lambda: a.cancelled)     # loser cancelled
    assert _wait(lambda: reg.snapshot()["a"]["inflight"] == 0
                 and reg.snapshot()["b"]["inflight"] == 0)


def test_fast_primary_suppresses_hedge():
    a, b = ScriptedReplica("a"), ScriptedReplica("b")
    router = FleetRouter(_registry(a, b), policy="round_robin",
                         hedge=HedgeConfig(enabled=True, fixed_delay_s=0.5))
    res = router.submit([5], SamplingParams(max_tokens=3)).result(timeout=10)
    assert res.token_ids == [6, 7, 8]
    assert router.counters()["hedges_fired"] == 0
    assert b.calls == []


def test_hedge_delay_tracks_ttft_ema():
    router = FleetRouter(_registry(ScriptedReplica("a")),
                         hedge=HedgeConfig(enabled=True, min_delay_s=0.05,
                                           cold_delay_s=0.4))
    assert router.hedge_delay_s() == 0.4          # no TTFT sample yet
    for _ in range(8):
        router._note_ttft(0.1)
    delay = router.hedge_delay_s()
    assert delay == pytest.approx(0.1 + 3.0 * router._ttft_dev)
    assert delay >= 0.05
    router.hedge.fixed_delay_s = 0.123
    assert router.hedge_delay_s() == 0.123


def test_text_query_routes_and_sheds_when_empty():
    a = ScriptedQueryReplica("a")
    router = FleetRouter(_registry(a), policy="least_loaded")
    assert router.query("why")["served_by"] == "a"
    a.ready = False
    router.registry.refresh()
    with pytest.raises(OverloadedError):
        router.query("again")


def test_text_stream_failover_suppresses_delivered_prefix():
    a = ScriptedQueryReplica("a", fail_stream_after=4)
    b = ScriptedQueryReplica("b")
    router = FleetRouter(_registry(a, b), policy="round_robin",
                         max_failovers=2)
    _rid, _model, deltas = router.query_stream("q")
    assert "".join(deltas) == "hello world"       # no dup, no gap
    assert _wait(lambda: router.counters()["failovers"] == 1)


# ---------------------------------------------------------------------------
# Acceptance: real in-process fleets
# ---------------------------------------------------------------------------


def _local_fleet(params, n=2):
    reps = []
    for i in range(n):
        eng = InferenceEngine(CFG, params, EngineConfig(**ECFG), eos_id=-1)
        reps.append(LocalReplica(f"r{i}", service=EngineService(eng)))
    reg = ReplicaRegistry()
    for r in reps:
        reg.add(r)
    reg.refresh()
    return reg, reps


def _prefix_workload(params, policy):
    """3 prefix groups x 5 rounds, submitted sequentially so each round can
    hit the pages the previous one published.  3 groups over 2 replicas
    breaks round-robin's periodicity, so RR smears every group across both
    caches while affinity pins each group to one."""
    reg, reps = _local_fleet(params)
    router = FleetRouter(reg, policy=policy, affinity_prefix_tokens=16)
    rng = np.random.default_rng(21)
    groups = [list(rng.integers(3, 300, size=16)) for _ in range(3)]
    try:
        for _ in range(5):
            for g in groups:
                p = g + list(rng.integers(3, 300, size=3))
                res = router.submit(
                    p, SamplingParams(max_tokens=4)).result(timeout=60)
                assert res.finish_reason == "length"
            reg.refresh()
        hits = misses = 0
        for r in reps:
            s = r.stats()
            hits += s.prefix_hits
            misses += s.prefix_misses
    finally:
        for r in reps:
            r.close()
    return hits / max(1, hits + misses), router.counters()


@pytest.mark.slow  # boots 4 live engines; covered by make chaos-fleet
def test_affinity_beats_round_robin_on_prefix_hit_rate(params):
    affinity_rate, affinity_counters = _prefix_workload(params, "affinity")
    rr_rate, _ = _prefix_workload(params, "round_robin")
    assert affinity_rate > rr_rate, (affinity_rate, rr_rate)
    assert affinity_counters["affinity_hits"] == 15   # every dispatch on home


@pytest.mark.chaos
@pytest.mark.slow  # 32 streams + greedy oracle; covered by make chaos-fleet
def test_chaos_replica_kill_midstream_loses_no_tokens(params):
    """The ISSUE acceptance gate: 2-replica fleet, 32 concurrent streaming
    requests, one replica killed while actively decoding — every request
    completes on the survivor with zero duplicated and zero lost tokens,
    and the failover/affinity gauges reflect it."""
    reg, reps = _local_fleet(params)
    router = FleetRouter(reg, policy="affinity", max_failovers=2)
    rng = np.random.default_rng(33)
    n_tok = 16
    prompts = [list(rng.integers(3, 300, size=4)) for _ in range(32)]
    try:
        handles = [router.submit(p, SamplingParams(max_tokens=n_tok))
                   for p in prompts]
        victim = reps[0]
        assert _wait(lambda: victim.service.engine.active_slots > 0,
                     timeout=60), "victim never received work"
        victim.kill()

        streams = []
        for h in handles:
            toks = list(h.stream(timeout=120))
            res = h.result(timeout=120)
            assert res.finish_reason == "length", (res.finish_reason,
                                                   res.error)
            assert toks == res.token_ids, "stream/result token mismatch"
            streams.append(toks)
        for p, toks in zip(prompts, streams):
            assert toks == _naive_greedy(params, p, n_tok), \
                "failover duplicated or lost tokens"

        c = router.counters()
        assert c["completed"] == 32 and c["failed"] == 0
        assert c["failovers"] >= 1
        assert c["affinity_hits"] + c["affinity_spills"] == 32
        snap = reg.snapshot()
        assert snap["r0"]["ready"] is False
        assert snap["r0"]["failures"] >= 1
    finally:
        for r in reps:
            r.close()


# ---------------------------------------------------------------------------
# HTTP fleet: stats route, router role, exporter gauges, SSE failover
# ---------------------------------------------------------------------------


def _boot_http_replica(params, max_tokens=24):
    tok = ByteTokenizer()
    engine = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=512, block_size=16,
                     max_blocks_per_seq=128, prefill_buckets=(128, 512, 2048),
                     decode_steps_per_iter=4),
        tokenizer=tok)
    backend = LocalEngineBackend(engine, tok)
    analysis = AnalysisEngine(backend, llm_cfg=LLMConfig(max_tokens=max_tokens))
    srv = MonitorServer(config=Config(), analysis=analysis, port=0)
    srv.start()
    return srv, backend


def _boot_http_fleet(params, max_tokens=24):
    reps = [_boot_http_replica(params, max_tokens) for _ in range(2)]
    cfg = Config()
    cfg.server.port = 0
    cfg.fleet.replicas = [f"http://127.0.0.1:{srv.port}" for srv, _ in reps]
    cfg.fleet.probe_interval_s = 0.5
    router_srv = build_router_server(cfg)
    router_srv.start()
    return router_srv, reps


def _shutdown_http_fleet(router_srv, reps):
    router_srv.analysis.close()
    router_srv.stop()
    for srv, backend in reps:
        srv.stop()
        try:
            backend.service.stop(timeout=5.0)
        except Exception:
            pass


def _get_json(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def http_fleet(params):
    router_srv, reps = _boot_http_fleet(params)
    yield router_srv, reps
    _shutdown_http_fleet(router_srv, reps)


@pytest.mark.slow  # shares the live 2-engine HTTP fleet; make chaos-fleet
def test_stats_route_reports_engine_load(http_fleet):
    _router_srv, reps = http_fleet
    stats = _get_json(reps[0][0].port, "/api/v1/stats")
    eng = stats["engine"]
    assert eng["total_slots"] == 2
    assert eng["prefix_cache"] is not None
    for key in ("queue_depth", "queue_tokens", "busy_slots"):
        assert key in eng


@pytest.mark.slow  # shares the live 2-engine HTTP fleet; make chaos-fleet
def test_router_role_serves_replica_api(http_fleet):
    router_srv, _reps = http_fleet
    rstats = _get_json(router_srv.port, "/api/v1/stats")
    assert set(rstats["fleet"]["replicas"]) == {"replica-0", "replica-1"}
    assert "dispatches" in rstats["fleet"]["counters"]

    req = urllib.request.Request(
        f"http://127.0.0.1:{router_srv.port}/api/v1/query",
        data=json.dumps({"question": "why is my pod crashlooping"}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        body = json.loads(r.read())
    assert body["status"] == "success"
    assert body["result"].get("answer")

    health = _get_json(router_srv.port, "/health")
    assert "fleet" in health


@pytest.mark.slow  # shares the live 2-engine HTTP fleet; make chaos-fleet
def test_router_metrics_export_fleet_gauges(http_fleet):
    router_srv, _reps = http_fleet
    with urllib.request.urlopen(
            f"http://127.0.0.1:{router_srv.port}/metrics", timeout=30) as r:
        text = r.read().decode()
    for gauge in ("k8s_llm_monitor_fleet_replica_ready",
                  "k8s_llm_monitor_fleet_replica_inflight",
                  "k8s_llm_monitor_fleet_affinity_hits_total",
                  "k8s_llm_monitor_fleet_hedges_fired_total",
                  "k8s_llm_monitor_fleet_failovers_total",
                  "k8s_llm_monitor_fleet_hedge_delay_seconds"):
        assert gauge in text, gauge
    assert 'replica="replica-0"' in text and 'replica="replica-1"' in text


@pytest.mark.chaos
@pytest.mark.slow  # boots its own 2-replica HTTP fleet; make chaos-fleet
def test_http_stream_fails_over_when_replica_dies(params):
    router_srv, reps = _boot_http_fleet(params, max_tokens=96)
    router = router_srv.analysis.router
    killed = {}

    def _assassin():
        # Kill the serving replica the moment its engine starts decoding —
        # waiting for client-side SSE events loses the race on a tiny model
        # (the whole answer can be generated and buffered before the first
        # event reaches the client).
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            for i, (_srv, backend) in enumerate(reps):
                if backend.service.engine.active_slots > 0:
                    backend.service.stop(timeout=5.0)
                    killed["idx"] = i
                    return
            time.sleep(0.002)

    assassin = threading.Thread(target=_assassin, daemon=True)
    assassin.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{router_srv.port}/api/v1/query",
            data=json.dumps({"question": "tell me everything",
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        deltas, done = [], None
        with urllib.request.urlopen(req, timeout=120) as r:
            for raw in r:
                line = raw.decode().strip()
                if not line.startswith("data: "):
                    continue
                ev = json.loads(line[6:])
                if ev.get("done"):
                    done = ev
                elif ev.get("delta"):
                    deltas.append(ev["delta"])
        assassin.join(timeout=60)
        assert killed, "no replica ever started decoding"
        assert done is not None, "stream never completed after replica death"
        assert deltas
        assert _wait(lambda: router.counters()["failovers"] >= 1)
        assert router.counters()["failed"] == 0
    finally:
        _shutdown_http_fleet(router_srv, reps)
