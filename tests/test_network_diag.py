"""Network analyzer + RTT tester tests (ref internal/k8s/network.go,
rtt_tester.go) against the fake cluster's exec simulator."""

import pytest

from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster
from k8s_llm_monitor_tpu.monitor.network import NetworkAnalyzer
from k8s_llm_monitor_tpu.monitor.rtt import (
    RTTTester,
    assess_latency,
    is_http_service,
    parse_ping_output,
    parse_pod_ref,
)


@pytest.fixture
def cluster():
    fake = FakeCluster()
    fake.add_node("node-a")
    fake.add_node("node-b")
    fake.add_pod(
        "app-a", node="node-a", labels={"app": "app-a"}, image="busybox:1.36"
    )
    fake.add_pod("web-b", node="node-b", labels={"app": "web-b"}, image="nginx:1.25")
    fake.add_pod(
        "coredns-abc",
        namespace="kube-system",
        node="node-a",
        labels={"k8s-app": "kube-dns"},
    )
    fake.add_service("web-b-svc", selector={"app": "web-b"})
    client = Client(fake, namespaces=["default", "kube-system"])
    return fake, client


def test_parse_pod_ref():
    assert parse_pod_ref("ns1/p1") == ("ns1", "p1")
    assert parse_pod_ref("p1") == ("default", "p1")


def test_parse_ping_output():
    out = (
        "PING 10.0.0.1 (10.0.0.1): 56 data bytes\n"
        "64 bytes from 10.0.0.1: icmp_seq=0 ttl=64 time=0.5 ms\n"
        "64 bytes from 10.0.0.1: icmp_seq=1 ttl=64 time=1.5 ms\n"
        "--- 10.0.0.1 ping statistics ---\n"
        "3 packets transmitted, 2 packets received, 33% packet loss\n"
    )
    avg, count, loss = parse_ping_output(out)
    assert avg == 1.0
    assert count == 2
    assert loss == 33.0


def test_assess_latency_bands():
    assert assess_latency(0) == "unknown"
    assert assess_latency(0.5) == "excellent"
    assert assess_latency(3) == "good"
    assert assess_latency(30) == "fair"
    assert assess_latency(70) == "poor"
    assert assess_latency(200) == "very_poor"


def test_rtt_cross_node_probe(cluster):
    fake, client = cluster
    tester = RTTTester(client)
    result = tester.test_pod_connectivity("app-a", "web-b")
    # ping + ping_reverse + http (web-b is nginx)
    assert result.test_count == 3
    methods = [r.method for r in result.rtt_results]
    assert methods == ["ping", "ping_reverse", "http"]
    assert all(r.success for r in result.rtt_results)
    assert result.success_rate == 100.0
    # cross-node synthetic RTT is 2.5ms → "good"
    assert result.latency_assessment == "good"


def test_rtt_same_node_faster(cluster):
    fake, client = cluster
    fake.add_pod("app-c", node="node-a", labels={"app": "app-c"})
    tester = RTTTester(client)
    result = tester.test_pod_connectivity("app-a", "app-c")
    assert result.average_rtt_ms < 1.0  # same-node → excellent band
    assert result.latency_assessment == "excellent"


def test_is_http_service(cluster):
    fake, client = cluster
    assert is_http_service(client.get_pod("default", "web-b"))
    assert not is_http_service(client.get_pod("default", "app-a"))


def test_analyze_healthy_pair_connected(cluster):
    fake, client = cluster
    analyzer = NetworkAnalyzer(client)
    a = analyzer.analyze_pod_communication("app-a", "web-b")
    assert a.status == "connected"
    assert a.confidence == 0.9
    assert a.issues == []
    assert "No obvious issues detected" in a.solutions


def test_analyze_not_running_pod(cluster):
    fake, client = cluster
    fake.update_pod("default", "web-b", phase="CrashLoopBackOff")
    analyzer = NetworkAnalyzer(client)
    a = analyzer.analyze_pod_communication("app-a", "web-b")
    assert a.status == "disconnected"
    assert a.confidence == 0.7
    assert any("is not running" in i for i in a.issues)


def test_analyze_netpol_flagged(cluster):
    fake, client = cluster
    fake.add_network_policy("deny-web", pod_selector={"app": "web-b"})
    analyzer = NetworkAnalyzer(client)
    a = analyzer.analyze_pod_communication("app-a", "web-b")
    assert any("deny-web" in i for i in a.issues)
    assert any("Review network policy" in s for s in a.solutions)


def test_analyze_no_service_and_no_dns(cluster):
    fake, client = cluster
    fake.add_pod("lonely", node="node-b", labels={"app": "lonely"})
    fake.update_pod("kube-system", "coredns-abc", phase="Pending")
    analyzer = NetworkAnalyzer(client)
    a = analyzer.analyze_pod_communication("app-a", "lonely")
    assert any("No service found targeting" in i for i in a.issues)
    assert any("CoreDNS is not running" in i for i in a.issues)


def test_analyze_rtt_exec_failure_degrades(cluster):
    fake, client = cluster
    fake.fail_next("exec_in_pod", times=10)
    analyzer = NetworkAnalyzer(client)
    a = analyzer.analyze_pod_communication("app-a", "web-b")
    # probes failed → success rate 0 → connectivity issue reported
    assert any("success rate" in i.lower() for i in a.issues)
    assert a.status == "disconnected"
