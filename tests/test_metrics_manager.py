"""Metrics sources + manager tests (ref internal/metrics/)."""

import pytest

from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster
from k8s_llm_monitor_tpu.monitor.config import MetricsConfig
from k8s_llm_monitor_tpu.monitor.manager import CollectError, Manager
from k8s_llm_monitor_tpu.monitor.models import UAVReport
from k8s_llm_monitor_tpu.monitor.sources import (
    NetworkMetricsSource,
    NodeMetricsSource,
    PodMetricsSource,
    UAVMetricsSource,
)


@pytest.fixture
def cluster():
    fake = FakeCluster()
    fake.add_node("n1", cpu="4", memory="8Gi")
    fake.add_node("n2", cpu="8", memory="16Gi", tpu_chips=4)
    fake.set_node_usage("n1", cpu="1000m", memory="4Gi")
    fake.set_node_usage("n2", cpu="2000m", memory="4Gi")
    fake.add_pod(
        "app-1",
        node="n1",
        labels={"app": "app"},
        requests={"cpu": "100m", "memory": "128Mi"},
        limits={"cpu": "200m", "memory": "256Mi"},
        image="busybox:1.36",
    )
    fake.add_pod("web-1", node="n2", labels={"app": "web"}, image="nginx:1.25")
    fake.set_pod_usage("default", "app-1", cpu="150m", memory="128Mi")
    client = Client(fake, namespaces=["default"])
    return fake, client


def test_node_source(cluster):
    fake, client = cluster
    nodes = NodeMetricsSource(client).collect()
    assert set(nodes) == {"n1", "n2"}
    n1 = nodes["n1"]
    assert n1.cpu_capacity == 4000
    assert n1.cpu_usage == 1000
    assert n1.cpu_usage_rate == 25.0
    assert n1.memory_usage_rate == 50.0
    assert n1.healthy
    # disk estimated as capacity - allocatable (5% in the fake)
    assert 0 < n1.disk_usage_rate < 10
    # TPU chips surface through accelerator fields
    n2 = nodes["n2"]
    assert n2.gpu_count == 4
    assert n2.custom_metrics["accelerator_type"] == "tpu"


def test_node_source_degrades_without_metrics_server(cluster):
    fake, client = cluster
    fake.metrics_server_available = False
    nodes = NodeMetricsSource(client).collect()
    assert nodes["n1"].cpu_capacity == 4000  # capacity-only
    assert nodes["n1"].cpu_usage == 0


def test_node_unhealthy_conditions(cluster):
    fake, client = cluster
    fake.add_node("bad", ready=False, pressure=["MemoryPressure"])
    nodes = NodeMetricsSource(client).collect()
    bad = nodes["bad"]
    assert not bad.healthy
    assert "MemoryPressure" in bad.conditions
    assert "NotReady" in bad.conditions


def test_pod_source(cluster):
    fake, client = cluster
    pods = PodMetricsSource(client, ["default"]).collect()
    pm = pods["default/app-1"]
    assert pm.cpu_request == 100
    assert pm.cpu_limit == 200
    assert pm.cpu_usage == 150
    assert pm.cpu_usage_rate == 75.0  # vs limit
    assert pm.memory_usage_rate == 50.0
    assert pm.ready
    assert pm.phase == "Running"
    assert len(pm.containers) == 1


def test_network_source_pairs_prefer_cross_node(cluster):
    fake, client = cluster
    fake.add_pod("app-2", node="n1", labels={"app": "app2"}, image="busybox:1.36")
    src = NetworkMetricsSource(client, ["default"], max_pairs=2)
    pairs = src.select_pod_pairs()
    assert len(pairs) == 2
    # both selected pairs should be cross-node (app-1/n1 x web-1/n2 etc.)
    assert ("default/app-1", "default/web-1") in pairs


def test_network_source_collect(cluster):
    fake, client = cluster
    metrics = NetworkMetricsSource(client, ["default"], max_pairs=3).collect()
    assert metrics
    m = metrics[0]
    assert m.connected
    assert m.rtt_ms > 0
    # web-1 is nginx → http preferred for pairs targeting it
    methods = {x.test_method for x in metrics}
    assert "http" in methods or "ping" in methods


def test_uav_source_pull(cluster):
    fake, client = cluster
    fake.add_pod(
        "uav-agent-abc",
        node="n1",
        labels={"app": "uav-agent"},
        image="uav-agent:dev",
    )
    calls = []

    def fetcher(url):
        calls.append(url)
        return {"uav_id": "uav-n1", "battery": {"remaining_percent": 80.0}}

    src = UAVMetricsSource(client, "default", fetcher=fetcher)
    out = src.collect()
    assert list(out) == ["n1"]
    assert out["n1"]["uav_id"] == "uav-n1"
    assert calls and ":9090/api/v1/state" in calls[0]


def test_uav_source_send_command(cluster):
    """Command push to a node's agent (ref SendCommandToUAV — whose body
    marshaling was an unfinished TODO; ours must actually send params)."""
    fake, client = cluster
    fake.add_pod(
        "uav-agent-cmd",
        node="n2",
        labels={"app": "uav-agent"},
        image="uav-agent:dev",
    )
    posts = []

    def poster(url, payload):
        posts.append((url, payload))
        return {"status": "armed"}

    src = UAVMetricsSource(client, "default", poster=poster)
    res = src.send_command("n2", "takeoff", {"altitude": 30})
    assert res == {"status": "armed"}
    url, payload = posts[0]
    assert url.endswith(":9090/api/v1/command/takeoff")
    assert payload == {"altitude": 30}

    import pytest as _pytest

    with _pytest.raises(ValueError):
        src.send_command("missing-node", "arm")


def test_manager_collect_and_rollup(cluster):
    fake, client = cluster
    mgr = Manager(client, MetricsConfig(namespaces=["default"], enable_network=True))
    snap = mgr.collect()
    assert snap.cluster_metrics.total_nodes == 2
    assert snap.cluster_metrics.healthy_nodes == 2
    assert snap.cluster_metrics.total_pods == 2
    assert snap.cluster_metrics.running_pods == 2
    assert snap.cluster_metrics.total_cpu == 12000
    assert snap.cluster_metrics.used_cpu == 3000
    assert snap.cluster_metrics.total_gpus == 4
    assert snap.cluster_metrics.health_status == "healthy"
    assert snap.network_metrics  # network probes ran
    assert mgr.get_node_metrics("n1").cpu_capacity == 4000
    with pytest.raises(KeyError):
        mgr.get_node_metrics("ghost")


def test_manager_health_warning_and_critical(cluster):
    fake, client = cluster
    fake.set_node_usage("n1", cpu="3500m", memory="7Gi")
    fake.set_node_usage("n2", cpu="7000m", memory="14Gi")
    mgr = Manager(client, MetricsConfig(namespaces=["default"]))
    snap = mgr.collect()
    assert snap.cluster_metrics.cpu_usage_rate > 80
    assert snap.cluster_metrics.health_status in ("warning", "critical")

    fake.set_node_usage("n1", cpu="3900m", memory="7.9Gi")
    fake.set_node_usage("n2", cpu="7900m", memory="15.8Gi")
    snap = mgr.collect()
    assert snap.cluster_metrics.health_status == "critical"


def test_manager_node_error_propagates(cluster):
    fake, client = cluster
    fake.fail_next("list_nodes", times=1)
    mgr = Manager(client, MetricsConfig(namespaces=["default"]))
    with pytest.raises(CollectError):
        mgr.collect()
    # network errors must NOT propagate (log-only policy)
    fake.fail_next("exec_in_pod", times=100)
    mgr2 = Manager(client, MetricsConfig(namespaces=["default"], enable_network=True))
    mgr2.collect()  # no raise


def test_manager_uav_push_beats_pull(cluster):
    fake, client = cluster
    mgr = Manager(client, MetricsConfig(namespaces=["default"]))
    mgr.update_uav_report(
        UAVReport(
            node_name="n1",
            uav_id="uav-n1",
            source="agent",
            heartbeat_interval_seconds=10,
            state={"battery": {"remaining_percent": 55.0}},
        )
    )
    uavs = mgr.get_uav_metrics()
    assert uavs["n1"]["source"] == "agent"
    assert uavs["n1"]["heartbeat_interval_seconds"] == 10
    single = mgr.get_single_uav_metrics("n1")
    assert single["uav_id"] == "uav-n1"
    assert mgr.get_single_uav_metrics("ghost") is None

    # a collect cycle (no agent pods → empty pull) must not clobber a fresh
    # agent-push entry
    mgr.collect()
    assert mgr.get_uav_metrics()["n1"]["source"] == "agent"


def test_manager_start_stop_loop(cluster):
    fake, client = cluster
    mgr = Manager(client, MetricsConfig(namespaces=["default"], collect_interval=3600))
    mgr.start()
    import time

    deadline = time.monotonic() + 5
    while mgr.collect_count == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    mgr.stop()
    assert mgr.collect_count >= 1
    assert mgr.get_latest_snapshot().cluster_metrics.total_nodes == 2
