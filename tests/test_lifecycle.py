"""Crash-safe serving lifecycle: journal (WAL), supervisor, handover.

The acceptance contract (ROADMAP PR 4): a step-loop death mid-decode is
survivable — the supervisor rebuilds the engine, replays every incomplete
request with already-streamed tokens trimmed (zero duplicates, zero
losses), and the KV allocator lands back on its baseline because the
rebuilt engine starts fresh.  A SIGTERM handover drains within the grace
window, seals the journal, and leaves nothing for the next process to
replay; a SIGKILL (journal closed without a seal) leaves exactly the
incomplete requests, which a warm start replays before serving traffic.

Run standalone with ``make chaos-lifecycle``; deterministic (seeded
injector, greedy sampling).  The journal/HTTP/exporter tests are
CPU-fast and ride in tier-1; the end-to-end rebuild scenarios are
marked ``slow`` (every engine rebuild recompiles on CPU) and run in the
chaos suites only.
"""

import logging
import threading
import time
from http.client import HTTPConnection

import pytest

import jax

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.resilience.journal import (
    ADMIT,
    COMPLETE,
    PROGRESS,
    RequestJournal,
    _pack,
    scan_journal,
)
from k8s_llm_monitor_tpu.resilience.retry import Backoff
from k8s_llm_monitor_tpu.resilience.tenancy import TenantGovernor
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.supervisor import EngineSupervisor

pytestmark = pytest.mark.chaos

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32, intermediate_size=64,
                  num_layers=2, num_heads=4, num_kv_heads=2, dtype="float32",
                  rope_theta=10_000.0)

# Same shapes as tests/test_resilience.py so the jit cache is shared across
# the chaos modules; prefix cache off so the allocator baseline is exact.
ECFG = dict(max_slots=4, num_blocks=64, block_size=8,
            max_blocks_per_seq=16, prefill_buckets=(16,),
            max_prefills_per_step=4, decode_steps_per_iter=4,
            prefix_cache_entries=0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _fault_isolation():
    get_injector().reset(seed=1234)
    yield
    get_injector().reset()


def _mk_engine(params, **overrides):
    cfg = dict(ECFG)
    cfg.update(overrides)
    return InferenceEngine(CFG, params, EngineConfig(**cfg), eos_id=-1)


def _wait(predicate, timeout=30.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _mk_supervisor(params, tmp_path=None, **overrides):
    journal = None
    if tmp_path is not None:
        journal = RequestJournal(tmp_path / "wal", fsync="never")
    kw = dict(journal=journal, max_restarts=4,
              backoff=Backoff(base_s=0.01, cap_s=0.05, jitter=0.0),
              heartbeat_timeout_s=30.0, poll_interval_s=0.02)
    kw.update(overrides)
    return EngineSupervisor(lambda: _mk_engine(params), **kw)


# -- journal units -----------------------------------------------------------


def test_journal_roundtrip_and_seal(tmp_path):
    j = RequestJournal(tmp_path, fsync="always")
    j.log_admit("r1", [1, 2, 3], SamplingParams(max_tokens=5), 2.5, 1000.0)
    j.log_progress("r1", [10, 11])
    j.log_progress("r1", [])  # no-op, must not write a record
    j.log_admit("r2", [4], {"max_tokens": 7, "temperature": 0.3})
    j.log_complete("r2")
    j.seal()

    reqs, sealed = scan_journal(tmp_path)
    assert sealed
    assert set(reqs) == {"r1", "r2"}
    r1 = reqs["r1"]
    assert not r1.completed
    assert r1.prompt_ids == [1, 2, 3]
    assert r1.emitted == [10, 11]
    assert r1.sampling["max_tokens"] == 5
    assert r1.deadline_s == 2.5 and r1.arrival_unix == 1000.0
    assert reqs["r2"].completed

    # A fresh journal over the same dir exposes the incomplete survivor and
    # reports the clean close.
    j2 = RequestJournal(tmp_path, fsync="never")
    assert j2.recovered_sealed
    assert [r.request_id for r in j2.incomplete_recovered] == ["r1"]
    j2.close()


def test_journal_rotation_and_compaction(tmp_path):
    j = RequestJournal(tmp_path, segment_max_bytes=1024, fsync="never")
    for i in range(50):
        j.log_admit(f"r{i}", list(range(20)), {"max_tokens": 4})
        j.log_complete(f"r{i}")
    # Everything is tombstoned: all rolled-over segments hold only history
    # and must have been deleted; only the active segment remains.
    assert j.compacted_segments > 0
    live = sorted(p.name for p in tmp_path.glob("wal-*.log"))
    assert len(live) == 1
    assert j.size_bytes <= 1024 + 256  # active segment only, near-empty
    j.close()

    # An incomplete request pins its segments across rotation.
    j2 = RequestJournal(tmp_path, segment_max_bytes=1024, fsync="never")
    j2.log_admit("pinned", list(range(20)), {"max_tokens": 4})
    for i in range(50):
        j2.log_admit(f"s{i}", list(range(20)), {"max_tokens": 4})
        j2.log_complete(f"s{i}")
    assert any(req.request_id == "pinned" and not req.completed
               for req in scan_journal(tmp_path)[0].values())
    j2.log_complete("pinned")
    j2.close()


def test_journal_torn_tail_fuzzer(tmp_path):
    """Truncate the segment at every byte offset inside the final record:
    the scanner must never raise and never resurrect the torn record."""
    recs = [
        _pack(ADMIT, {"id": "keep", "prompt": [1, 2], "sampling": {},
                      "deadline_s": 0.0, "arrival": 0.0}),
        _pack(PROGRESS, {"id": "keep", "tokens": [5, 6, 7]}),
        _pack(COMPLETE, {"id": "done"}),
        _pack(ADMIT, {"id": "torn", "prompt": list(range(40)),
                      "sampling": {"max_tokens": 9}, "deadline_s": 0.0,
                      "arrival": 0.0}),
    ]
    data = b"".join(recs)
    base = len(data) - len(recs[-1])
    seg = tmp_path / "wal-00000000.log"
    for cut in range(base, len(data)):
        seg.write_bytes(data[:cut])
        reqs, sealed = scan_journal(tmp_path)  # must not raise
        assert not sealed
        assert "torn" not in reqs, f"torn record resurrected at cut={cut}"
        assert reqs["keep"].emitted == [5, 6, 7]
        assert not reqs["keep"].completed
    # The full file scans clean.
    seg.write_bytes(data)
    reqs, _ = scan_journal(tmp_path)
    assert reqs["torn"].prompt_ids == list(range(40))


def test_journal_crc_corruption_drops_rest_of_segment(tmp_path):
    recs = [
        _pack(ADMIT, {"id": "a", "prompt": [1], "sampling": {},
                      "deadline_s": 0.0, "arrival": 0.0}),
        _pack(ADMIT, {"id": "b", "prompt": [2], "sampling": {},
                      "deadline_s": 0.0, "arrival": 0.0}),
        _pack(ADMIT, {"id": "c", "prompt": [3], "sampling": {},
                      "deadline_s": 0.0, "arrival": 0.0}),
    ]
    data = bytearray(b"".join(recs))
    flip = len(recs[0]) + 12  # a payload byte inside record "b"
    data[flip] ^= 0xFF
    (tmp_path / "wal-00000000.log").write_bytes(bytes(data))
    reqs, _ = scan_journal(tmp_path)
    # Everything before the corrupt record applies; nothing after it can be
    # trusted (the framing itself may be gone).
    assert set(reqs) == {"a"}


def test_journal_rejects_unknown_fsync_policy(tmp_path):
    with pytest.raises(ValueError, match="fsync policy"):
        RequestJournal(tmp_path, fsync="sometimes")


# -- supervisor: rebuild-and-replay ------------------------------------------


@pytest.mark.slow  # rebuild recompiles: seconds on CPU; covered by make chaos-lifecycle
def test_double_kill_under_load_replays_without_duplicates(params, tmp_path):
    """The PR acceptance scenario: kill the step loop twice during a
    32-request mixed load.  Zero hangs, zero lost requests, zero duplicated
    tokens, allocator back to baseline, counters consistent."""
    sup = _mk_supervisor(params, tmp_path)
    try:
        baseline = sup.engine.allocator.free_blocks
        n = 32
        budgets = [3 + (i % 6) for i in range(n)]
        handles = [
            sup.submit([(7 * i + j) % 300 for j in range(5 + i % 4)],
                       SamplingParams(max_tokens=budgets[i], temperature=0.0))
            for i in range(n)
        ]
        streamed: list[list[int]] = [[] for _ in range(n)]

        def consume(i):
            for tok in handles[i].stream(timeout=60.0):
                streamed[i].append(tok)

        threads = [threading.Thread(target=consume, args=(i,), daemon=True)
                   for i in range(n)]
        for t in threads:
            t.start()

        for kill in (1, 2):
            get_injector().arm("step_loop_crash", rate=1.0, times=1)
            assert _wait(lambda: sup.restarts == kill), f"kill {kill} missed"
            assert _wait(lambda: sup.state == "serving"), \
                f"rebuild {kill} never finished"

        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "stream hung after rebuild"
        results = [h.result(timeout=60.0) for h in handles]

        for i, res in enumerate(results):
            assert res.finish_reason != "error", (i, res.error)
            assert len(res.token_ids) == budgets[i], \
                f"request {i}: lost or duplicated tokens"
            # Stream == final result: replay never re-delivers a token.
            assert streamed[i] == list(res.token_ids), f"request {i}"

        assert sup.restarts == 2
        assert sup.replayed_total >= 1
        assert sup.health.snapshot()["ready"]
        snap = sup.snapshot()
        assert snap["tracked"] == 0 and snap["journal_bytes"] > 0
        assert _wait(lambda: not sup.engine.has_work, timeout=5.0)
        assert sup.engine.allocator.free_blocks == baseline
        # Every journaled request is tombstoned.
        reqs, _ = scan_journal(tmp_path / "wal")
        assert reqs and all(r.completed for r in reqs.values())
    finally:
        sup.shutdown(grace_s=1.0)
    assert scan_journal(tmp_path / "wal")[1], "shutdown must seal the journal"


@pytest.mark.slow  # rebuild recompiles: seconds on CPU; covered by make chaos-lifecycle
def test_wedged_loop_detected_by_stale_heartbeat(params):
    """A step() that never returns (no exception) must still trigger a
    rebuild: heartbeat goes stale while work is pending."""
    gate = threading.Event()
    wedge = threading.Event()

    class _Wedgeable:
        """Engine proxy whose step() can be made to block."""

        def __init__(self, inner):
            object.__setattr__(self, "_inner", inner)

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def __setattr__(self, name, value):  # token_sink/health assignment
            setattr(self._inner, name, value)

        def step(self):
            if wedge.is_set():
                gate.wait(timeout=60.0)
            return self._inner.step()

    built = []

    def factory():
        eng = _mk_engine(params)
        built.append(eng)
        return _Wedgeable(eng) if len(built) == 1 else eng

    # Warm the jit cache first: a legitimate (compiling) first step must not
    # read as a wedge once the tight heartbeat timeout is in force.
    from k8s_llm_monitor_tpu.serving.engine import GenerationRequest

    warm = _mk_engine(params)
    warm.submit(GenerationRequest(request_id="warm", prompt_ids=[1, 2, 3],
                                  sampling=SamplingParams(max_tokens=4)))
    while warm.has_work:
        warm.step()

    sup = EngineSupervisor(
        factory, max_restarts=3,
        backoff=Backoff(base_s=0.01, cap_s=0.05, jitter=0.0),
        heartbeat_timeout_s=0.3, poll_interval_s=0.05)
    try:
        wedge.set()
        h = sup.submit([1, 2, 3], SamplingParams(max_tokens=4))
        assert _wait(lambda: sup.restarts >= 1, timeout=10.0), \
            "stale heartbeat never detected"
        # Only the first wedge is under test; don't let scheduler hiccups on
        # the rebuilt loop read as further wedges.
        sup.heartbeat_timeout_s = 60.0
        res = h.result(timeout=30.0)
        assert res.finish_reason != "error", res.error
        assert len(res.token_ids) == 4
        assert len(built) >= 2, "factory must have been called for a rebuild"
    finally:
        gate.set()  # release the wedged thread so it can observe _stop
        sup.shutdown(grace_s=1.0)


def test_restart_budget_exhaustion_fails_survivors_with_cause(params):
    sup = _mk_supervisor(params, max_restarts=0)
    try:
        h = sup.submit([1, 2, 3], SamplingParams(max_tokens=50))
        get_injector().arm("step_loop_crash", rate=1.0, times=1)
        res = h.result(timeout=30.0)
        assert res.finish_reason == "error"
        assert "restart budget exhausted" in res.error
        assert _wait(lambda: sup.state == "failed", timeout=5.0)
        assert not sup.health.snapshot()["ready"]
        with pytest.raises(OverloadedError) as exc_info:
            sup.submit([1], SamplingParams(max_tokens=2))
        assert not exc_info.value.retriable
    finally:
        sup.close()


@pytest.mark.slow  # rebuild recompiles: seconds on CPU; covered by make chaos-lifecycle
def test_admission_refused_while_rebuilding(params):
    release = threading.Event()
    calls = []

    def factory():
        calls.append(1)
        if len(calls) > 1:
            assert release.wait(timeout=30.0)
        return _mk_engine(params)

    sup = EngineSupervisor(
        factory, max_restarts=2,
        backoff=Backoff(base_s=0.01, cap_s=0.05, jitter=0.0),
        poll_interval_s=0.02)
    try:
        get_injector().arm("step_loop_crash", rate=1.0, times=1)
        assert _wait(lambda: sup.state == "rebuilding", timeout=10.0)
        with pytest.raises(OverloadedError) as exc_info:
            sup.submit([1, 2], SamplingParams(max_tokens=2))
        assert exc_info.value.retriable
        assert exc_info.value.retry_after_s > 0
        release.set()
        assert _wait(lambda: sup.state == "serving", timeout=10.0)
        # Back to serving: admission works again, end to end.
        res = sup.submit([1, 2], SamplingParams(max_tokens=2)).result(
            timeout=30.0)
        assert res.finish_reason != "error"
    finally:
        release.set()
        sup.close()


# -- warm start (cross-process replay) ---------------------------------------


def test_warm_start_replays_unsealed_journal(params, tmp_path):
    wal = tmp_path / "wal"
    # Process #1 accepts two requests, streams two tokens of the first,
    # finishes the second, then dies without sealing (SIGKILL shape).
    j = RequestJournal(wal, fsync="never")
    j.log_admit("w1", [1, 2, 3], {"max_tokens": 5, "temperature": 0.0})
    j.log_progress("w1", [7, 8])
    j.log_admit("w2", [4, 5], {"max_tokens": 3})
    j.log_complete("w2")
    j.close()

    # Process #2 warm-starts: w1 is replayed (budget trimmed by the two
    # already-delivered tokens) before any fresh traffic, then tombstoned.
    sup = _mk_supervisor(params, journal=RequestJournal(wal, fsync="never"))
    try:
        assert sup.replayed_total == 1
        assert _wait(lambda: sup.snapshot()["tracked"] == 0, timeout=30.0)
    finally:
        sup.shutdown(grace_s=5.0)
    reqs, sealed = scan_journal(wal)
    assert sealed
    assert all(r.completed for r in reqs.values())
    # Process #3 has nothing to replay.
    j3 = RequestJournal(wal, fsync="never")
    assert j3.incomplete_recovered == []
    j3.close()


# -- tenancy through the WAL --------------------------------------------------


def test_journal_admit_records_carry_tenant(tmp_path):
    j = RequestJournal(tmp_path, fsync="never")
    j.log_admit("t1", [1, 2], {"max_tokens": 4}, slo_class="interactive",
                tenant="team-a")
    j.log_admit("t2", [3], {"max_tokens": 2})       # unlabeled request
    j.close()
    reqs, _ = scan_journal(tmp_path)
    assert reqs["t1"].tenant == "team-a"
    assert reqs["t1"].slo_class == "interactive"
    assert reqs["t2"].tenant == "public"            # pre-tenancy default


def test_torn_tail_never_corrupts_another_tenants_accounting(tmp_path):
    """Tenant B's torn ADMIT vanishes without touching tenant A's
    replayable state: WAL records are per-request and tenant-tagged, so
    the scanner's drop-the-tail rule doubles as accounting isolation —
    quota rebuilt from the scan charges A exactly its own emitted tokens
    and B nothing, at every possible tear offset."""
    recs = [
        _pack(ADMIT, {"id": "a1", "prompt": [1, 2],
                      "sampling": {"max_tokens": 6}, "deadline_s": 0.0,
                      "arrival": 0.0, "tenant": "team-a"}),
        _pack(PROGRESS, {"id": "a1", "tokens": [5, 6]}),
        _pack(ADMIT, {"id": "b1", "prompt": [3, 4],
                      "sampling": {"max_tokens": 9}, "deadline_s": 0.0,
                      "arrival": 0.0, "tenant": "team-b"}),
    ]
    data = b"".join(recs)
    base = len(data) - len(recs[-1])
    seg = tmp_path / "wal-00000000.log"
    for cut in range(base, len(data)):
        seg.write_bytes(data[:cut])
        reqs, _ = scan_journal(tmp_path)            # must not raise
        assert "b1" not in reqs, f"torn admit resurrected at cut={cut}"
        a1 = reqs["a1"]
        assert a1.tenant == "team-a" and a1.emitted == [5, 6]
        gov = TenantGovernor(tokens_per_s=0.001, token_burst=100.0,
                             clock=lambda: 0.0)
        for rec in reqs.values():
            if not rec.completed:
                gov.restore(rec.request_id, rec.tenant,
                            max_tokens=int(rec.sampling.get("max_tokens", 0)),
                            delivered=len(rec.emitted))
        snap = gov.snapshot()
        assert set(snap) == {"team-a"}
        assert snap["team-a"]["inflight"] == 1
        # 6-token budget, 2 already streamed: 4 remain reserved.
        assert snap["team-a"]["quota_remaining"] == 96.0


@pytest.mark.slow  # rebuilds an engine; covered by make chaos-tenant
def test_warm_start_restores_per_tenant_quota(params, tmp_path):
    """A supervisor warm start rebuilds per-tenant quota state from the
    WAL: the incomplete request's remaining budget is re-reserved under
    its recorded tenant, the replay streams the rest, and settlement
    charges exactly the delivered tokens — a crash cannot launder quota."""
    wal = tmp_path / "wal"
    j = RequestJournal(wal, fsync="never")
    j.log_admit("wa", [1, 2, 3], {"max_tokens": 5, "temperature": 0.0},
                tenant="team-a")
    j.log_progress("wa", [7, 8])                    # 2 of 5 streamed
    j.log_admit("wb", [4, 5], {"max_tokens": 3}, tenant="team-b")
    j.log_complete("wb")                            # nothing to replay
    j.close()

    gov = TenantGovernor(tokens_per_s=0.001, token_burst=100.0)
    sup = _mk_supervisor(params, journal=RequestJournal(wal, fsync="never"),
                         governor=gov)
    try:
        assert sup.replayed_total == 1
        assert _wait(lambda: sup.snapshot()["tracked"] == 0, timeout=30.0)
    finally:
        sup.shutdown(grace_s=5.0)
    snap = gov.snapshot()
    assert set(snap) == {"team-a"}                  # completed b never restored
    st = snap["team-a"]
    # Replay regenerated the 3 remaining tokens; with the 2 pre-crash
    # tokens the caller saw 5, and exactly 5 are charged.
    assert st["charged_tokens"] == 5
    assert st["inflight"] == 0
    # The new process's bucket paid only for the replayed remainder (the
    # pre-crash 2 were charged to the dead process's bucket).
    assert 96.0 <= st["quota_remaining"] <= 98.0


# -- SIGTERM graceful handover ------------------------------------------------


class _StubBackend:
    def __init__(self, supervisor=None, service=None):
        self.supervisor = supervisor
        self._service = service

    @property
    def service(self):
        if self.supervisor is not None:
            return self.supervisor.service
        return self._service

    @property
    def engine(self):
        svc = self.service
        return svc.engine if svc is not None else None


class _StubAnalysis:
    def __init__(self, backend=None):
        self.backend = backend


@pytest.mark.slow  # rebuild recompiles: seconds on CPU; covered by make chaos-lifecycle
def test_graceful_shutdown_drains_seals_and_flips_readiness(params, tmp_path):
    from k8s_llm_monitor_tpu.cmd.server import _graceful_shutdown
    from k8s_llm_monitor_tpu.monitor.server import MonitorServer

    sup = _mk_supervisor(params, tmp_path)
    srv = MonitorServer(analysis=_StubAnalysis(_StubBackend(supervisor=sup)))
    assert srv.health_snapshot()["ready"]
    h = sup.submit([1, 2, 3, 4], SamplingParams(max_tokens=6))

    _graceful_shutdown(srv, grace_s=20.0, log=logging.getLogger("test"))

    # The inflight generation finished inside the grace window...
    res = h.result(timeout=1.0)
    assert res.finish_reason != "error"
    assert len(res.token_ids) == 6
    # ...the journal is sealed with nothing left to replay...
    reqs, sealed = scan_journal(tmp_path / "wal")
    assert sealed
    assert all(r.completed for r in reqs.values())
    # ...and readiness reports 503-shape (not ready, with cause).
    snap = srv.health_snapshot()
    assert not snap["ready"]
    assert snap["lifecycle"]["state"] == "stopped"
    assert sup.state == "stopped"
    # Terminating is terminal: no new admissions.
    with pytest.raises(OverloadedError):
        sup.submit([1], SamplingParams(max_tokens=1))


# -- HTTP mapping of OverloadedError ------------------------------------------


class _OverloadedAnalysis:
    backend = None

    def __init__(self, exc):
        self._exc = exc

    def query(self, question, slo_class="interactive", tenant=""):
        raise self._exc


def _post_query(srv):
    conn = HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        conn.request("POST", "/api/v1/query", body='{"question": "why?"}',
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        return resp, body
    finally:
        conn.close()


def test_http_maps_overload_to_429_with_retry_after():
    import json as _json

    from k8s_llm_monitor_tpu.monitor.server import MonitorServer

    exc = OverloadedError("queue depth over limit", queue_depth=9,
                          queue_tokens=1234, retriable=True,
                          retry_after_s=2.2)
    srv = MonitorServer(analysis=_OverloadedAnalysis(exc),
                        host="127.0.0.1", port=0)
    srv.start()
    try:
        resp, body = _post_query(srv)
        assert resp.status == 429
        assert resp.getheader("Retry-After") == "3"  # ceil(2.2)
        payload = _json.loads(body)
        assert payload["error_kind"] == "overloaded"
        assert payload["queue_depth"] == 9
        assert payload["queue_tokens"] == 1234
        assert payload["retriable"] is True
        assert "queue depth over limit" in payload["error"]
    finally:
        srv.stop()


def test_http_maps_nonretriable_overload_to_503():
    from k8s_llm_monitor_tpu.monitor.server import MonitorServer

    exc = OverloadedError("draining", retriable=False, retry_after_s=0.4)
    srv = MonitorServer(analysis=_OverloadedAnalysis(exc),
                        host="127.0.0.1", port=0)
    srv.start()
    try:
        resp, _ = _post_query(srv)
        assert resp.status == 503
        assert resp.getheader("Retry-After") == "1"  # floor of 1s
    finally:
        srv.stop()


# -- observability -------------------------------------------------------------


class _FakeSupervisor:
    def snapshot(self):
        return {"state": "rebuilding", "restarts": 3, "max_restarts": 4,
                "replayed_total": 7, "tracked": 2, "journal_bytes": 4096}


def test_health_snapshot_reports_lifecycle_not_ready():
    from k8s_llm_monitor_tpu.monitor.server import MonitorServer

    backend = _StubBackend()
    backend.supervisor = _FakeSupervisor()
    srv = MonitorServer(analysis=_StubAnalysis(backend))
    snap = srv.health_snapshot()
    assert snap["ready"] is False
    assert "rebuilding" in snap["reason"]
    assert snap["lifecycle"]["restarts"] == 3


def test_exporter_emits_lifecycle_metrics():
    from k8s_llm_monitor_tpu.monitor.exporter import render_prometheus
    from k8s_llm_monitor_tpu.monitor.server import MonitorServer

    backend = _StubBackend()
    backend.supervisor = _FakeSupervisor()
    srv = MonitorServer(analysis=_StubAnalysis(backend))
    text = render_prometheus(srv)
    assert 'k8s_llm_monitor_lifecycle_state{state="rebuilding"} 1' in text
    assert 'k8s_llm_monitor_lifecycle_state{state="serving"} 0' in text
    assert "k8s_llm_monitor_engine_restarts_total 3" in text
    assert "k8s_llm_monitor_journal_replayed_total 7" in text
    assert "k8s_llm_monitor_journal_bytes 4096" in text
