"""Telemetry plane (observability/timeseries.py + signals.py): ring-store
math under a fake clock, NaN marker discipline, the signal scraper's
local-engine and fleet sampling paths, derived scale hints, the anomaly →
diagnosis feed with its cooldown, exposition round-trips through the
exporter self-lint, flight-recorder v2 signal windows, and the live
2-replica flood → scale-up → anomaly → decay acceptance loop.

Unit tests drive ``scrape_once()`` synchronously against fake engines and
scripted fleet rows — no threads, no sleeps, a shared fake clock.  The
acceptance test boots a real HTTP router fleet and is marked ``slow``;
``make chaos-signals`` runs the whole file under ``K8SLLM_LOCKCHECK=1``.
"""

import json
import math
import threading
import time
import types
import urllib.request

import pytest

import jax

from k8s_llm_monitor_tpu.diagnosis.pipeline import DiagnosisPipeline
from k8s_llm_monitor_tpu.fleet.frontend import build_router_server
from k8s_llm_monitor_tpu.fleet.registry import ReplicaRegistry, ReplicaStats
from k8s_llm_monitor_tpu.fleet.router import FleetRouter
from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.monitor.analysis import (
    AnalysisEngine,
    LocalEngineBackend,
)
from k8s_llm_monitor_tpu.monitor.config import (
    Config,
    DiagnosisConfig,
    LLMConfig,
    TelemetryConfig,
)
from k8s_llm_monitor_tpu.monitor.exporter import (
    lint_exposition,
    render_prometheus,
)
from k8s_llm_monitor_tpu.monitor.server import MonitorServer
from k8s_llm_monitor_tpu.observability.flight import FlightRecorder
from k8s_llm_monitor_tpu.observability.signals import (
    LOCAL_TARGET,
    SignalScraper,
)
from k8s_llm_monitor_tpu.observability.timeseries import TimeSeriesStore
from k8s_llm_monitor_tpu.resilience.slo import SLO_CLASSES
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


# ---------------------------------------------------------------------------
# TimeSeriesStore: ring bounds, windowed math, NaN discipline
# ---------------------------------------------------------------------------


def test_ring_evicts_oldest_at_capacity():
    clock = FakeClock()
    st = TimeSeriesStore(capacity=4, clock=clock)
    for i in range(10):
        st.record("q", float(i), {"r": "a"}, t=float(i))
    pts = st.points("q", {"r": "a"})
    assert [v for _, v in pts] == [6.0, 7.0, 8.0, 9.0]
    assert st.totals() == {"series": 1, "points_total": 10,
                           "dropped_series_total": 0}


def test_rate_delta_and_quantile_exact_math():
    st = TimeSeriesStore(clock=FakeClock())
    for t, v in [(0, 0), (1, 10), (2, 20), (3, 30)]:
        st.record("q", v, t=float(t))
    assert st.last("q") == 30.0
    assert st.delta("q") == 30.0
    assert st.rate("q") == pytest.approx(10.0)
    assert st.quantile("q", 0.5) == pytest.approx(15.0)
    assert st.quantile("q", 0.99) == pytest.approx(29.7)
    assert st.quantile("q", 0.0) == 0.0
    assert st.quantile("q", 1.0) == 30.0
    # Degenerate windows read as NaN, never raise.
    st2 = TimeSeriesStore(clock=FakeClock())
    st2.record("one", 5.0, t=1.0)
    assert math.isnan(st2.rate("one"))
    assert math.isnan(st2.delta("one"))
    assert math.isnan(st2.rate("missing"))


def test_window_clips_to_trailing_seconds():
    st = TimeSeriesStore(clock=FakeClock())
    for t in range(10):
        st.record("q", float(t), t=float(t))
    pts = st.points("q", window_s=3.5, now=9.0)
    assert [t for t, _ in pts] == [6.0, 7.0, 8.0, 9.0]
    assert st.rate("q", window_s=3.5, now=9.0) == pytest.approx(1.0)
    assert math.isnan(st.last("q", window_s=0.5, now=100.0))


def test_ema_is_deterministic_hand_math():
    def build():
        st = TimeSeriesStore(clock=FakeClock())
        for t, v in [(0, 0), (10, 10), (20, 20)]:
            st.record("q", float(v), t=float(t))
        return st

    # Half-life 10 with 10 s steps halves the weight each step:
    # 0 -> .5*0+.5*10 = 5 -> .5*5+.5*20 = 12.5.
    assert build().ema("q", half_life_s=10.0) == pytest.approx(12.5)
    assert build().ema("q", half_life_s=10.0) == \
        build().ema("q", half_life_s=10.0)
    assert math.isnan(build().ema("missing"))


def test_nan_markers_pass_last_but_skip_window_math():
    st = TimeSeriesStore(clock=FakeClock())
    st.record("q", 1.0, t=0.0)
    st.record("q", float("nan"), t=1.0)
    st.record("q", 3.0, t=2.0)
    assert st.last("q") == 3.0
    st.record("q", float("nan"), t=3.0)
    assert math.isnan(st.last("q"))            # marker passes through
    assert st.rate("q") == pytest.approx(1.0)  # finite points only
    assert st.delta("q") == pytest.approx(2.0)
    assert st.quantile("q", 0.5) == pytest.approx(2.0)
    # A junk value is recorded as the NaN marker, not an exception.
    st.record("q", "garbage", t=4.0)
    assert math.isnan(st.last("q"))


def test_max_series_cap_drops_new_series_counted():
    st = TimeSeriesStore(max_series=2, clock=FakeClock())
    st.record("q", 1.0, {"r": "a"}, t=0.0)
    st.record("q", 1.0, {"r": "b"}, t=0.0)
    st.record("q", 1.0, {"r": "c"}, t=0.0)     # refused at the cap
    st.record("q", 2.0, {"r": "a"}, t=1.0)     # existing series still fine
    assert st.series_count() == 2
    assert st.dropped_series_total == 1
    assert st.last("q", {"r": "a"}) == 2.0
    assert st.points("q", {"r": "c"}) == []


def test_export_and_window_snapshot_are_json_safe():
    st = TimeSeriesStore(clock=FakeClock(10.0))
    st.record("q", 1.5, {"replica": "a", "class": "batch"}, t=1.0)
    st.record("q", float("nan"), {"replica": "a", "class": "batch"}, t=2.0)
    st.record("q", 7.0, {"replica": "b"}, t=2.0)
    out = st.export("q", label_filter={"replica": "a"})
    assert len(out) == 1
    assert out[0]["labels"] == {"replica": "a", "class": "batch"}
    assert out[0]["points"] == [[1.0, 1.5], [2.0, None]]
    snap = st.window_snapshot(30.0)
    assert snap["window_s"] == 30.0 and snap["t_mono"] == 10.0
    assert len(snap["series"]) == 2
    json.dumps(snap, allow_nan=False)          # strict-JSON clean


# ---------------------------------------------------------------------------
# SignalScraper: sampling fakes
# ---------------------------------------------------------------------------


class _FakeEngine:
    """The attribute surface ``_sample_engine`` reads, all mutable."""

    def __init__(self):
        self.queue = {c: 0 for c in SLO_CLASSES}
        self.queue_tokens = 0
        self.ttft_ema_by_class = {}
        self.preemptions_by_class = {"batch": 1}
        self.active_slots = 0
        self.headroom = 100.0
        self.host_kv_tier = None
        self.rung = 0

    def queue_tokens_by_class(self):
        return dict(self.queue)

    def brownout(self):
        return self.rung

    def admission_headroom_tokens(self):
        return self.headroom

    def kv_tier_stats(self):
        return {"device_bytes": 4096, "host_bytes": 0,
                "spills": 2, "restores": 1}


class _StubPipeline:
    def __init__(self):
        self.offered = []

    def offer(self, event):
        self.offered.append(event)


def _local_scraper(eng, cfg=None, pipeline=None, clock=None):
    clock = clock or FakeClock()
    svc = types.SimpleNamespace(engine=eng, shed_count_by_class={"batch": 2})
    scraper = SignalScraper(cfg=cfg or TelemetryConfig(),
                            pipeline=pipeline, clock=clock)
    scraper.attach(types.SimpleNamespace(engine_service=lambda: svc,
                                         fleet_router=lambda: None))
    return scraper, clock


def test_scraper_samples_local_engine_catalog():
    eng = _FakeEngine()
    eng.queue["batch"] = 7
    eng.queue_tokens = 7
    eng.ttft_ema_by_class = {"interactive": 0.2}
    eng.active_slots = 3
    scraper, _ = _local_scraper(eng)
    scraper.scrape_once()
    st, lab = scraper.store, {"replica": LOCAL_TARGET}
    assert st.last("queue_tokens",
                   {"replica": LOCAL_TARGET, "class": "batch"}) == 7.0
    assert st.last("queue_tokens_total", lab) == 7.0
    assert st.last("ttft_ema_s",
                   {"replica": LOCAL_TARGET,
                    "class": "interactive"}) == pytest.approx(0.2)
    assert math.isnan(st.last("ttft_ema_s",
                              {"replica": LOCAL_TARGET, "class": "batch"}))
    assert st.last("headroom_tokens", lab) == 100.0
    assert st.last("busy_slots", lab) == 3.0
    assert st.last("kv_bytes",
                   {"replica": LOCAL_TARGET, "tier": "device"}) == 4096.0
    # No host tier wired: occupancy is unmeasured, not zero.
    assert math.isnan(st.last("kv_bytes",
                              {"replica": LOCAL_TARGET, "tier": "host"}))
    assert st.last("kv_spills_total", lab) == 2.0
    assert st.last("sheds_total",
                   {"replica": LOCAL_TARGET, "class": "batch"}) == 2.0
    assert st.last("preemptions_total",
                   {"replica": LOCAL_TARGET, "class": "batch"}) == 1.0
    assert scraper.counters()["scrapes_total"] == 1
    assert scraper.role() == "replica"


def test_scrape_failure_is_a_counter_not_an_outage():
    def boom():
        raise RuntimeError("engine gone")

    scraper = SignalScraper(cfg=TelemetryConfig(), clock=FakeClock())
    scraper.attach(types.SimpleNamespace(engine_service=boom,
                                         fleet_router=lambda: None))
    scraper.scrape_once()                      # must not raise
    c = scraper.counters()
    assert c["scrape_errors_total"] == 1 and c["scrapes_total"] == 0


def _fleet_rows(**ages):
    """Scripted registry snapshot rows, one per replica id -> probe age."""
    rows = {}
    for rid, age in ages.items():
        rows[rid] = {
            "probe_age_s": age,
            "queue_tokens": 40,
            "queue_by_class": {"batch": 40},
            "ttft_ema_by_class": {"interactive": 0.1},
            "preemptions_by_class": {},
            "shed_by_class": {},
            "brownout": 0,
            "busy_slots": 2,
            "headroom_tokens": 64.0,
            "kv_tier": {"device_bytes": 1024, "host_bytes": 0,
                        "spills": 0, "restores": 0},
        }
    return rows


def test_stale_fleet_rows_record_nan_never_frozen_values():
    clock = FakeClock()
    pipe = _StubPipeline()
    cfg = TelemetryConfig(stale_after_probes=3.0, anomaly_cooldown_s=30.0)
    scraper = SignalScraper(cfg=cfg, pipeline=pipe, clock=clock)
    rows = _fleet_rows(r0=0.1, r1=10.0, r2=None)   # fresh / stale / never
    router = types.SimpleNamespace(telemetry_sample=lambda: {
        "replicas": rows, "probe_interval_s": 0.5, "counters": {}})
    scraper.attach(types.SimpleNamespace(engine_service=lambda: None,
                                         fleet_router=lambda: router))
    scraper.scrape_once()
    st = scraper.store
    assert st.last("queue_tokens_total", {"replica": "r0"}) == 40.0
    for rid in ("r1", "r2"):
        assert math.isnan(st.last("queue_tokens_total", {"replica": rid}))
        assert math.isnan(st.last("headroom_tokens", {"replica": rid}))
        assert math.isnan(st.last(
            "queue_tokens", {"replica": rid, "class": "batch"}))
    assert math.isnan(st.last("scrape_age_s", {"replica": "r2"}))
    assert st.last("scrape_age_s", {"replica": "r1"}) == 10.0

    payload = scraper.signals()
    assert payload["role"] == "router"
    assert payload["targets"]["r0"]["stale"] is False
    for rid in ("r1", "r2"):
        blk = payload["targets"][rid]
        assert blk["stale"] is True
        assert blk["scale_hint"] == "steady"   # never scale on no evidence
        assert "scrape_stale" in blk["anomalies"]
        assert blk["queue_tokens_total"] is None
    json.dumps(payload, allow_nan=False)
    # Both stale targets fed the diagnosis ring as self_monitor Warnings.
    reasons = {(e.reason, e.type, e.source) for e in pipe.offered}
    assert ("SelfMonitor:scrape_stale", "Warning", "self_monitor") in reasons
    assert len([e for e in pipe.offered
                if e.reason == "SelfMonitor:scrape_stale"]) == 2


# ---------------------------------------------------------------------------
# Derived signals: scale hints + anomaly feed
# ---------------------------------------------------------------------------


def test_queue_growth_drives_scale_up_and_anomaly_with_cooldown():
    eng = _FakeEngine()
    pipe = _StubPipeline()
    cfg = TelemetryConfig(queue_growth_up_tok_s=5.0, anomaly_cooldown_s=30.0)
    scraper, clock = _local_scraper(eng, cfg=cfg, pipeline=pipe)
    for q in (0, 100, 200, 300):
        eng.queue["batch"] = q
        eng.queue_tokens = q
        scraper.scrape_once()
        clock.advance(1.0)
    blk = scraper.signals()["targets"][LOCAL_TARGET]
    assert blk["scale_hint"] == "up"
    assert "queue_growth" in blk["anomalies"]
    assert blk["queue_growth_tok_per_s"]["batch"] == pytest.approx(100.0)
    assert blk["queue_growth_total_tok_per_s"] == pytest.approx(100.0)

    growth = [e for e in pipe.offered
              if e.reason == "SelfMonitor:queue_growth"]
    assert len(growth) == 1                    # edge-triggered once
    assert growth[0].type == "Warning"
    assert growth[0].source == "self_monitor"
    assert "tok/s" in growth[0].message

    # Still growing inside the cooldown: suppressed.
    for q in (400, 500):
        eng.queue["batch"] = q
        eng.queue_tokens = q
        scraper.scrape_once()
        clock.advance(1.0)
    assert len([e for e in pipe.offered
                if e.reason == "SelfMonitor:queue_growth"]) == 1
    # Past the cooldown with growth persisting: re-emitted.
    clock.advance(31.0)
    for q in (600, 700, 800):
        eng.queue["batch"] = q
        eng.queue_tokens = q
        scraper.scrape_once()
        clock.advance(1.0)
    assert len([e for e in pipe.offered
                if e.reason == "SelfMonitor:queue_growth"]) == 2
    by_flag = scraper.counters()["anomalies_by_flag"]
    assert by_flag["queue_growth"] == 2
    assert any(a["flag"] == "queue_growth"
               for a in scraper.signals()["recent_anomalies"])


def test_idle_window_with_headroom_reads_scale_down():
    eng = _FakeEngine()                        # all-zero queues, rung 0
    scraper, clock = _local_scraper(eng)
    for _ in range(4):
        scraper.scrape_once()
        clock.advance(1.0)
    blk = scraper.signals()["targets"][LOCAL_TARGET]
    assert blk["scale_hint"] == "down"
    assert blk["anomalies"] == []
    assert blk["brownout_dwell"] == 0.0


def test_sustained_ttft_breach_flags_and_scales_up():
    eng = _FakeEngine()
    eng.ttft_ema_by_class = {"interactive": 2.0}   # budget is 1.0 s
    pipe = _StubPipeline()
    scraper, clock = _local_scraper(eng, pipeline=pipe)
    for _ in range(3):
        scraper.scrape_once()
        clock.advance(1.0)
    blk = scraper.signals()["targets"][LOCAL_TARGET]
    assert blk["scale_hint"] == "up"
    assert "ttft_breach" in blk["anomalies"]
    assert blk["ttft_budget_breach"]["interactive"] is True
    assert any(e.reason == "SelfMonitor:ttft_breach" for e in pipe.offered)
    # A falling EMA is recovery, not a sustained breach.
    eng2 = _FakeEngine()
    scraper2, clock2 = _local_scraper(eng2)
    for v in (3.0, 2.0, 1.2):
        eng2.ttft_ema_by_class = {"interactive": v}
        scraper2.scrape_once()
        clock2.advance(1.0)
    blk2 = scraper2.signals()["targets"][LOCAL_TARGET]
    assert blk2["ttft_budget_breach"]["interactive"] is False
    assert "ttft_breach" not in blk2["anomalies"]


def test_brownout_dwell_drives_scale_up():
    eng = _FakeEngine()
    scraper, clock = _local_scraper(eng)
    for rung in (1, 1, 1, 0):                  # 75% of window at >= degraded
        eng.rung = rung
        scraper.scrape_once()
        clock.advance(1.0)
    blk = scraper.signals()["targets"][LOCAL_TARGET]
    assert blk["brownout_dwell"] == pytest.approx(0.75)
    assert blk["scale_hint"] == "up"


# ---------------------------------------------------------------------------
# Wire formats: stats payload, exposition, flight artifact
# ---------------------------------------------------------------------------


def test_replica_stats_from_payload_round_trips_enriched_block():
    payload = {"engine": {
        "queue_depth": 3, "queue_tokens": 120, "busy_slots": 2,
        "total_slots": 4, "brownout": 1,
        "queue_tokens_by_class": {"batch": 120},
        "prefix_cache": {"hits": 5, "misses": 1},
        "kv_tier": {"device_bytes": 2048, "spills": 7},
        "admission_headroom_tokens": 88.5,
        "shed_by_class": {"batch": 9},
        "ttft_ema_by_class": {"interactive": 0.125},
        "preemptions_by_class": {"standard": 2},
    }}
    s = ReplicaStats.from_payload(payload)
    assert s.queue_tokens == 120 and s.brownout == 1
    assert s.headroom_tokens == pytest.approx(88.5)
    assert s.shed_by_class == {"batch": 9}
    assert s.ttft_ema_by_class == {"interactive": 0.125}
    assert s.preemptions_by_class == {"standard": 2}
    assert s.kv_tier["spills"] == 7
    # Absent enrichment stays None/empty — never invented zeros that
    # would read as measurements.
    bare = ReplicaStats.from_payload({"engine": {"total_slots": 4}})
    assert bare.headroom_tokens is None
    assert bare.shed_by_class == {} and bare.ttft_ema_by_class == {}


class _ProbeReplica:
    replica_id = "a"

    def readyz(self):
        return True

    def stats(self):
        return ReplicaStats(total_slots=4, queue_tokens=10)

    def close(self):
        pass


def test_exposition_carries_fleet_age_and_telemetry_families():
    reg = ReplicaRegistry()
    reg.add(_ProbeReplica())
    reg.refresh()
    router = FleetRouter(reg)
    scraper = SignalScraper(cfg=TelemetryConfig(), clock=FakeClock())
    scraper.attach(types.SimpleNamespace(
        engine_service=lambda: None,
        fleet_router=lambda: types.SimpleNamespace(
            telemetry_sample=lambda: {"replicas": reg.snapshot(),
                                      "probe_interval_s": 5.0,
                                      "counters": {}})))
    scraper.scrape_once()
    srv = types.SimpleNamespace(
        analysis=types.SimpleNamespace(router=router, backend=None),
        client=None, manager=None, diagnosis=None, signals=scraper)
    text = render_prometheus(srv)
    assert lint_exposition(text) == []
    assert 'k8s_llm_monitor_fleet_scrape_age_s{replica="a"}' in text
    for fam in ("telemetry_scrapes_total", "telemetry_scrape_errors_total",
                "telemetry_anomalies_total", "telemetry_series",
                "telemetry_points_total", "telemetry_dropped_series_total"):
        assert f"k8s_llm_monitor_{fam}" in text, fam


def test_flight_recorder_v2_carries_signal_window(tmp_path):
    clock = FakeClock(50.0)
    store = TimeSeriesStore(clock=clock)
    store.record("queue_tokens_total", 5.0, {"replica": "local"}, t=49.0)
    store.record("queue_tokens_total", float("nan"),
                 {"replica": "local"}, t=50.0)
    rec = FlightRecorder(capacity=8, dirpath=str(tmp_path))
    rec.signal_source = lambda: store.window_snapshot(30.0)
    rec.note("tick")
    art = json.loads(open(rec.dump("telemetry window")).read())
    assert art["version"] == 2
    series = art["signals"]["series"]
    assert len(series) == 1
    assert series[0]["name"] == "queue_tokens_total"
    assert series[0]["points"] == [[49.0, 5.0], [50.0, None]]


# ---------------------------------------------------------------------------
# Acceptance: live 2-replica fleet, flood -> scale-up -> anomaly -> decay
# ---------------------------------------------------------------------------


class _NullAnalysis:
    def diagnose(self, question, context=""):
        return {"verdict": {}}


def _boot_replica(params):
    tok = ByteTokenizer()
    engine = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=4, num_blocks=256, block_size=16,
                     max_blocks_per_seq=8, prefill_buckets=(32,),
                     max_prefills_per_step=4, decode_steps_per_iter=4,
                     prefix_cache_entries=0),
        tokenizer=tok)
    backend = LocalEngineBackend(engine, tok)
    analysis = AnalysisEngine(backend, llm_cfg=LLMConfig(max_tokens=16))
    srv = MonitorServer(config=Config(), analysis=analysis, port=0)
    srv.start()
    return srv, backend


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.chaos
@pytest.mark.slow  # boots a 2-engine HTTP fleet; covered by chaos-signals
def test_live_fleet_flood_scale_up_anomaly_then_decay(params):
    """The ISSUE acceptance gate: flood one replica with batch traffic;
    within a scrape interval or two the router's /api/v1/signals must show
    positive queue-token growth and a scale-up hint for that replica, a
    self_monitor anomaly must land in the diagnosis pipeline (trigger
    counter), and after the backlog drains the hint decays off "up"."""
    reps = [_boot_replica(params) for _ in range(2)]
    cfg = Config()
    cfg.server.port = 0
    cfg.fleet.replicas = [f"http://127.0.0.1:{srv.port}" for srv, _ in reps]
    cfg.fleet.probe_interval_s = 0.25
    cfg.telemetry.scrape_interval_s = 0.25
    cfg.telemetry.window_s = 6.0
    cfg.telemetry.queue_growth_up_tok_s = 5.0
    cfg.telemetry.anomaly_cooldown_s = 600.0
    # Generous staleness budget: a loaded CI box can starve the probe
    # thread, and a spurious stale flag would force hint=steady.
    cfg.telemetry.stale_after_probes = 60.0
    router_srv = build_router_server(cfg)
    # Router-role self-diagnosis: the builder leaves the pipeline to the
    # caller (see build_router_server); one Warning = one trigger here.
    pipe = DiagnosisPipeline(
        _NullAnalysis(),
        DiagnosisConfig(burst_threshold=1, cooldown_s=0.0))
    router_srv.signals.pipeline = pipe
    router_srv.start()
    base = f"http://127.0.0.1:{router_srv.port}"

    victim_svc = reps[0][1].service
    handles, stop_feed = [], threading.Event()

    def _feeder():
        i = 0
        while not stop_feed.is_set() and len(handles) < 900:
            for _ in range(4):
                prompt = [(i * 7 + j) % 290 + 3 for j in range(16)]
                handles.append(victim_svc.submit(
                    prompt, SamplingParams(max_tokens=2),
                    force=True, slo_class="batch"))
                i += 1
            time.sleep(0.04)

    feeder = threading.Thread(target=_feeder, daemon=True)
    feeder.start()
    try:
        def _victim_block():
            payload = _get_json(f"{base}/api/v1/signals")
            return payload["targets"].get("replica-0")

        def _scaled_up():
            blk = _victim_block()
            return (blk is not None and blk["scale_hint"] == "up"
                    and (blk["queue_growth_total_tok_per_s"] or 0) > 0)

        assert _wait(_scaled_up, timeout=30), _victim_block()
        # The monotonic-growth anomaly fired and reached the diagnosis
        # pipeline as a self_monitor Warning -> one burst trigger.
        assert _wait(lambda: router_srv.signals.counters()
                     ["anomalies_by_flag"].get("queue_growth", 0) >= 1,
                     timeout=30)
        assert _wait(lambda: pipe.triggers_total >= 1, timeout=15)
        assert any(a["flag"] == "queue_growth" and a["target"] == "replica-0"
                   for a in router_srv.signals.signals()["recent_anomalies"])

        # Satellite 1: the replica's enriched /api/v1/stats block — the
        # registry probe rows the router-side series were built from.
        eng_blk = _get_json(
            f"http://127.0.0.1:{reps[0][0].port}/api/v1/stats")["engine"]
        for key in ("admission_headroom_tokens", "kv_tier", "shed_by_class",
                    "ttft_ema_by_class", "preemptions_by_class",
                    "queue_tokens_by_class"):
            assert key in eng_blk, key

        # Raw points behind the hint, filtered by replica label.
        ts = _get_json(f"{base}/api/v1/timeseries"
                       "?name=queue_tokens_total&replica=replica-0")
        assert ts["n_series"] == 1
        assert len(ts["series"][0]["points"]) >= 2

        stop_feed.set()
        feeder.join(timeout=10)
        for h in list(handles):
            res = h.result(timeout=180)
            assert res.finish_reason in ("length", "eos"), res.error

        # Drained: over a short fresh window the hint decays off "up".
        def _decayed():
            payload = _get_json(f"{base}/api/v1/signals?window=3")
            blk = payload["targets"].get("replica-0")
            return (blk is not None and blk["scale_hint"] != "up"
                    and (blk["queue_tokens_total"] or 0) == 0)

        assert _wait(_decayed, timeout=60)
    finally:
        stop_feed.set()
        router_srv.analysis.close()
        router_srv.stop()
        for srv, backend in reps:
            srv.stop()
            try:
                backend.service.stop(timeout=5.0)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
