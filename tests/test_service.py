"""EngineService: concurrent submissions share the engine's continuous batch;
token sink emits incrementally; streaming handles deliver tokens.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.service import EngineService

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32, intermediate_size=64,
                  num_layers=2, num_heads=4, num_kv_heads=2, dtype="float32",
                  rope_theta=10_000.0)

ECFG = dict(max_slots=4, num_blocks=64, block_size=8,
            max_blocks_per_seq=16, prefill_buckets=(16,),
            max_prefills_per_step=4, decode_steps_per_iter=4)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _naive_greedy(params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    return seq[len(prompt):]


def test_token_sink_emits_incrementally(params):
    """The engine delivers tokens in waves (prefill first-token, then one
    batch per fused decode call) before the final result."""
    eng = InferenceEngine(CFG, params, EngineConfig(**ECFG), eos_id=-1)
    calls = []
    eng.token_sink = lambda rid, toks, res: calls.append((rid, list(toks), res))

    eng.submit(GenerationRequest("a", [5, 6, 7], SamplingParams(max_tokens=10)))
    while eng.has_work:
        eng.step()

    token_calls = [c for c in calls if c[1]]
    result_calls = [c for c in calls if c[2] is not None]
    assert len(result_calls) == 1 and result_calls[0][2].finish_reason == "length"
    # prefill emits 1 token, then fused waves of <= decode_steps_per_iter.
    assert len(token_calls) >= 3
    assert token_calls[0][1] != [] and len(token_calls[0][1]) == 1
    streamed = [t for _, toks, _ in token_calls for t in toks]
    assert streamed == _naive_greedy(params, [5, 6, 7], 10)
    # result arrives after every token was emitted
    assert calls.index(result_calls[0]) == len(calls) - 1


def test_concurrent_callers_share_batch(params):
    """N threads blocking on generate() must share decode steps: the engine
    executes far fewer steps than serial generation would."""
    eng = InferenceEngine(CFG, params, EngineConfig(**ECFG), eos_id=-1)
    svc = EngineService(eng)
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(3, 300, size=n)) for n in (5, 9, 3, 7)]
    want = [_naive_greedy(params, p, 8) for p in prompts]

    results = [None] * len(prompts)

    def worker(i):
        handle = svc.submit(prompts[i], SamplingParams(max_tokens=8))
        results[i] = handle.result(timeout=120)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    svc.stop()

    for r, w in zip(results, want):
        assert r is not None and r.finish_reason == "length"
        assert r.token_ids == w
    # 4 requests x 8 tokens serially = 32+ decode steps; shared continuous
    # batch does it in ~8 (one lane each).  Allow slack for ragged admission.
    assert eng.steps <= 20, f"engine did not share decode steps: {eng.steps}"


def test_stream_yields_tokens(params):
    eng = InferenceEngine(CFG, params, EngineConfig(**ECFG), eos_id=-1)
    svc = EngineService(eng)
    handle = svc.submit([5, 6, 7], SamplingParams(max_tokens=10))
    toks = list(handle.stream(timeout=120))
    assert toks == _naive_greedy(params, [5, 6, 7], 10)
    assert handle.result(timeout=5).finish_reason == "length"
    svc.stop()


def test_cancel_stops_generation(params):
    """Cancelling a handle mid-stream retires the request early instead of
    decoding to max_tokens for a dead client."""
    eng = InferenceEngine(CFG, params, EngineConfig(**ECFG), eos_id=-1)
    svc = EngineService(eng)
    handle = svc.submit([5, 6, 7], SamplingParams(max_tokens=400))
    stream = handle.stream(timeout=120)
    got = [next(stream), next(stream)]
    handle.cancel()
    res = handle.result(timeout=120)
    assert len(got) == 2
    assert len(res.token_ids) < 400, "cancel did not stop generation"
    svc.stop()


def test_eos_not_streamed(params):
    eng = InferenceEngine(CFG, params, EngineConfig(**ECFG), eos_id=-1)
    svc = EngineService(eng)
    free = _naive_greedy(params, [5, 6, 7], 20)
    idx = next(i for i in range(3, len(free)) if free[i] not in free[:i])
    eng.eos_id = free[idx]
    # handle built after eos change so the filter sees the right id
    handle = svc.submit([5, 6, 7], SamplingParams(max_tokens=20))
    toks = list(handle.stream(timeout=120))
    res = handle.result(timeout=5)
    assert res.finish_reason == "eos"
    assert toks == res.token_ids == free[:idx]
    svc.stop()
