"""Continuous-batching engine: correctness against naive generation,
preemption under page pressure, mixed sampling configs, text round-trip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32, intermediate_size=64,
                  num_layers=2, num_heads=4, num_kv_heads=2, dtype="float32",
                  rope_theta=10_000.0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _naive_greedy(params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    return seq[len(prompt):]


def test_greedy_matches_naive(params):
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=4, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16, 32)),
        eos_id=-1,
    )
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(3, 300, size=n)) for n in (5, 11, 3)]
    results = eng.generate(prompts, SamplingParams(max_tokens=8, temperature=0.0))
    for p, r in zip(prompts, results):
        assert r.finish_reason == "length"
        assert r.token_ids == _naive_greedy(params, p, 8), "continuous batch != naive"
        assert r.ttft_s >= 0 and r.latency_s >= r.ttft_s


def test_staggered_admission(params):
    """More requests than slots: later requests admitted as slots free up."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,)),
        eos_id=-1,
    )
    rng = np.random.default_rng(1)
    prompts = [list(rng.integers(3, 300, size=6)) for _ in range(5)]
    results = eng.generate(prompts, SamplingParams(max_tokens=5))
    assert len(results) == 5
    for p, r in zip(prompts, results):
        assert r.token_ids == _naive_greedy(params, p, 5)


def test_preemption_under_page_pressure(params):
    """Tiny pool forces eviction; outputs must still match naive decoding."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=3, num_blocks=14, block_size=4,
                     max_blocks_per_seq=16, prefill_buckets=(16,)),
        eos_id=-1,
    )
    rng = np.random.default_rng(2)
    prompts = [list(rng.integers(3, 300, size=7)) for _ in range(3)]
    results = eng.generate(prompts, SamplingParams(max_tokens=12))
    for p, r in zip(prompts, results):
        assert r.token_ids == _naive_greedy(params, p, 12)
    assert eng.preemptions > 0, "test did not actually exercise preemption"


def test_chunked_prefill_long_prompt(params):
    """Prompts longer than the largest bucket split into chunks; the
    continuation chunks attend to the paged prefix and must match naive."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,)),
        eos_id=-1,
    )
    rng = np.random.default_rng(5)
    prompt = list(rng.integers(3, 300, size=45))  # 45 > 16 -> 3 chunks
    [r] = eng.generate([prompt], SamplingParams(max_tokens=6))
    assert r.token_ids == _naive_greedy(params, prompt, 6)


def test_oversized_prompt_truncates_to_tail(params):
    """Prompt + budget beyond cache capacity keeps the prompt *tail*."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16, 32, 64, 128)),
        eos_id=-1,
    )
    rng = np.random.default_rng(6)
    huge = list(rng.integers(3, 300, size=400))   # capacity is 128
    [r] = eng.generate([huge], SamplingParams(max_tokens=10))
    assert r.finish_reason == "length"
    assert r.token_ids == _naive_greedy(params, huge[-(128 - 10):], 10)


def test_eos_stops_generation(params):
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,)),
        eos_id=-1,
    )
    prompt = list(range(3, 10))
    free = _naive_greedy(params, prompt, 20)
    # Pick an EOS token at its *first* occurrence in the stream — choosing a
    # token that repeats earlier would legitimately stop generation early.
    idx = next(i for i in range(3, len(free)) if free[i] not in free[:i])
    eng.eos_id = free[idx]
    [r] = eng.generate([prompt], SamplingParams(max_tokens=20))
    assert r.finish_reason == "eos"
    assert r.token_ids == free[:idx]


def test_sampling_with_seed_is_reproducible(params):
    def run(seed):
        eng = InferenceEngine(
            CFG, params,
            EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                         max_blocks_per_seq=16, prefill_buckets=(16,)),
            eos_id=-1, seed=seed,
        )
        [r] = eng.generate([[5, 6, 7, 8]],
                           SamplingParams(max_tokens=10, temperature=0.8, top_k=40))
        return r.token_ids

    assert run(7) == run(7)
    # Not a hard requirement, but with temp 0.8 two seeds matching for all 10
    # tokens would indicate sampling ignores the rng.
    assert run(7) != run(8)


def test_text_roundtrip(params):
    tok = ByteTokenizer()
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16, 32)),
        tokenizer=tok,
    )
    out = eng.generate_text("pod crashloop", SamplingParams(max_tokens=6))
    assert isinstance(out, str)


def test_submit_poll_async_api(params):
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,)),
        eos_id=-1,
    )
    eng.submit(GenerationRequest("a", [5, 6, 7], SamplingParams(max_tokens=4)))
    eng.submit(GenerationRequest("b", [9, 10], SamplingParams(max_tokens=4)))
    assert eng.poll("a") is None
    while eng.has_work:
        eng.step()
    ra, rb = eng.poll("a"), eng.poll("b")
    assert ra is not None and rb is not None
    assert len(ra.token_ids) == 4 and len(rb.token_ids) == 4


def test_long_prompts_stream_and_batch_chunks(params):
    """Several long prompts admitted together stream their chunks in
    batched rounds (depth-first) while a short prompt co-admits and
    decodes between rounds; every output must still match naive decoding."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=4, num_blocks=128, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,),
                     max_prefills_per_step=4),
        eos_id=-1,
    )
    rng = np.random.default_rng(7)
    longs = [list(rng.integers(3, 300, size=n)) for n in (50, 60, 44)]
    short = list(rng.integers(3, 300, size=6))
    prompts = longs + [short]
    results = eng.generate(prompts, SamplingParams(max_tokens=5))
    for p, r in zip(prompts, results):
        assert r.finish_reason == "length"
        assert r.token_ids == _naive_greedy(params, p, 5)


def test_mixed_progress_chunk_rounds_match_naive(params):
    """Staggered long prompts of different lengths put lanes at different
    prefill depths in the SAME chunk round, exercising the narrowed block
    table (width = deepest lane's coverage) with shallower lanes' tables
    truncated; outputs must still match naive decoding exactly."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=4, num_blocks=128, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,),
                     max_prefills_per_step=4),
        eos_id=-1,
    )
    rng = np.random.default_rng(11)
    first = list(rng.integers(3, 300, size=90))   # deep lane
    later = [list(rng.integers(3, 300, size=n)) for n in (34, 70)]
    eng.submit(GenerationRequest("deep", first,
                                 SamplingParams(max_tokens=4)))
    eng.step()  # admit + first chunk round for the deep lane
    for i, p in enumerate(later):
        eng.submit(GenerationRequest(f"late-{i}", p,
                                     SamplingParams(max_tokens=4)))
    while eng.has_work:
        eng.step()
    for rid, p in [("deep", first)] + [
            (f"late-{i}", p) for i, p in enumerate(later)]:
        r = eng.poll(rid)
        assert r is not None and r.finish_reason == "length"
        assert r.token_ids == _naive_greedy(params, p, 4), rid


def test_cancel_mid_prefill_settles_cleanly(params):
    """Cancelling a long prompt while its chunks are still streaming must
    retire the slot, free its pages, and report an eos/length-free result
    without a first token."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,)),
        eos_id=-1,
    )
    rng = np.random.default_rng(8)
    long_prompt = list(rng.integers(3, 300, size=60))
    from k8s_llm_monitor_tpu.serving.engine import GenerationRequest
    eng.submit(GenerationRequest("lp", long_prompt,
                                 SamplingParams(max_tokens=5)))
    eng.step()                       # admit + first chunk round
    assert any(s is not None and s.prefilling for s in eng._slots)
    assert eng.cancel("lp")
    while eng.has_work:
        eng.step()
    res = eng.poll("lp")
    assert res is not None and res.token_ids == []
    assert res.ttft_s == 0.0
    assert eng.allocator.free_blocks == eng.allocator.num_blocks - 1


def test_qwen2_family_through_engine():
    """The Qwen2 skeleton (QKV biases) runs the full serving stack —
    batched prefill, paged decode — and matches naive decoding."""
    qcfg = ModelConfig(name="tq", vocab_size=300, hidden_size=32,
                       intermediate_size=64, num_layers=2, num_heads=4,
                       num_kv_heads=2, dtype="float32", rope_theta=1e4,
                       qkv_bias=True)
    qparams = llama.init_params(jax.random.PRNGKey(3), qcfg)
    assert "bias" in qparams["layers"][0]["q"]
    eng = InferenceEngine(
        qcfg, qparams,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,)),
        eos_id=-1,
    )
    rng = np.random.default_rng(4)
    prompts = [list(rng.integers(3, 300, size=n)) for n in (5, 9)]
    results = eng.generate(prompts, SamplingParams(max_tokens=5))

    def naive(prompt, n):
        seq = list(prompt)
        for _ in range(n):
            logits = llama.forward_full(qparams, qcfg,
                                        jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    for p, r in zip(prompts, results):
        assert r.token_ids == naive(p, 5)
