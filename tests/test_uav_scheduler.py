"""UAV simulator/agent + scheduler controller tests
(ref pkg/uav/mavlink_simulator.go, cmd/uav-agent/main.go,
internal/scheduler/controller.go)."""

import json
import urllib.request

import pytest

from k8s_llm_monitor_tpu.monitor.agent import UAVAgent
from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster
from k8s_llm_monitor_tpu.monitor.models import UAVReport
from k8s_llm_monitor_tpu.monitor.scheduler import SchedulerConfig, SchedulerController
from k8s_llm_monitor_tpu.monitor.uav import MAVLinkSimulator


# ---------------------------------------------------------------------------
# simulator
# ---------------------------------------------------------------------------


def test_simulator_initial_state():
    sim = MAVLinkSimulator("uav-1", "node-1", seed=42)
    s = sim.get_state()
    assert s["uav_id"] == "uav-1"
    assert s["gps"]["fix_type"] == 3
    assert s["battery"]["remaining_percent"] == 100.0
    assert s["battery"]["cell_count"] == 6
    assert s["flight"]["mode"] == "STABILIZE"
    assert not s["flight"]["armed"]
    assert s["health"]["system_status"] == "OK"
    assert s["health"]["sensors_health"]["gps"] is True


def test_simulator_flight_dynamics():
    sim = MAVLinkSimulator("uav-1", "node-1", seed=42)
    assert sim.arm()
    assert sim.take_off(60.0)
    s0 = sim.get_state()
    for _ in range(50):  # 5 simulated seconds
        sim.tick(0.1)
    s1 = sim.get_state()
    assert s1["flight"]["mode"] == "AUTO"
    assert s1["flight"]["armed"]
    # circular path moves GPS, battery discharges ~0.1%/s
    assert s1["gps"]["latitude"] != s0["gps"]["latitude"]
    assert s1["gps"]["ground_speed"] > 4.5
    assert 99.0 < s1["battery"]["remaining_percent"] < 100.0
    assert s1["battery"]["voltage"] < 22.2
    assert s1["flight"]["throttle_percent"] > 0


def test_simulator_battery_health_transitions():
    sim = MAVLinkSimulator("uav-1", "node-1", seed=1)
    sim.arm()
    sim.take_off()
    sim.set_battery_percent(19.0)
    sim.tick(0.1)
    s = sim.get_state()
    assert s["health"]["system_status"] == "WARNING"
    assert s["health"]["warning_count"] == 1
    assert any("Low battery" in m for m in s["health"]["messages"])

    sim.set_battery_percent(9.0)
    sim.tick(0.1)
    s = sim.get_state()
    assert s["health"]["system_status"] == "CRITICAL"
    assert any("Critical battery" in m for m in s["health"]["messages"])


def test_simulator_arm_requires_gps_fix():
    sim = MAVLinkSimulator("uav-1", "node-1", seed=1)
    sim._state.gps.fix_type = 0
    assert not sim.arm()
    assert not sim.get_state()["flight"]["armed"]
    # takeoff refused while disarmed
    assert not sim.take_off()


def test_simulator_message_ring_bounded():
    sim = MAVLinkSimulator("uav-1", "node-1")
    for i in range(25):
        sim.set_flight_mode(f"MODE{i}")
    assert len(sim.get_state()["health"]["messages"]) == 10


# ---------------------------------------------------------------------------
# agent
# ---------------------------------------------------------------------------


@pytest.fixture
def agent():
    posted = []
    a = UAVAgent(
        node_name="node-1",
        node_ip="10.0.0.1",
        port=0,
        master_url="http://master:8081",
        report_interval=3600,
        poster=lambda url, payload: posted.append((url, payload)),
    )
    a.start()
    yield a, posted
    a.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, json.loads(r.read())


def _post(port, path, payload=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def test_agent_http_surface(agent):
    a, _ = agent
    _, health = _get(a.port, "/health")
    assert health["status"] == "healthy"
    assert health["uav_id"] == "uav-node-1"

    _, state = _get(a.port, "/api/v1/state")
    assert state["node_name"] == "node-1"
    for sub in ("gps", "attitude", "battery", "flight"):
        _, part = _get(a.port, f"/api/v1/{sub}")
        assert part == state[sub] or set(part) == set(state[sub])


def test_agent_command_endpoints(agent):
    a, _ = agent
    _, res = _post(a.port, "/api/v1/command/arm")
    assert res["status"] == "success"
    _, res = _post(a.port, "/api/v1/command/takeoff", {"altitude": 80})
    assert res["status"] == "success"
    assert a.simulator.get_state()["flight"]["mode"] == "AUTO"
    _, res = _post(a.port, "/api/v1/command/mode", {"mode": "LOITER"})
    assert a.simulator.get_state()["flight"]["mode"] == "LOITER"
    _, res = _post(a.port, "/api/v1/command/rtl")
    assert a.simulator.get_state()["flight"]["mode"] == "RTL"
    _, res = _post(a.port, "/api/v1/command/land")
    assert a.simulator.get_state()["flight"]["mode"] == "LAND"
    _, res = _post(a.port, "/api/v1/command/disarm")
    assert not a.simulator.get_state()["flight"]["armed"]

    with pytest.raises(urllib.error.HTTPError) as err:
        _post(a.port, "/api/v1/command/explode")
    assert err.value.code == 404


def test_agent_report_push(agent):
    a, posted = agent
    # first report fires immediately on start
    import time

    deadline = time.monotonic() + 5
    while not posted and time.monotonic() < deadline:
        time.sleep(0.02)
    assert posted
    url, payload = posted[0]
    assert url == "http://master:8081/api/v1/uav/report"
    assert payload["node_name"] == "node-1"
    assert payload["node_ip"] == "10.0.0.1"
    assert payload["uav_id"] == "uav-node-1"
    assert payload["source"] == "agent"
    assert payload["heartbeat_interval_seconds"] == 3600
    assert payload["state"]["battery"]["remaining_percent"] == 100.0


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------


@pytest.fixture
def sched_world():
    fake = FakeCluster()
    fake.add_node("node-a")
    fake.add_node("node-b")
    fake.add_node("node-tpu", tpu_chips=4)
    fake.define_crd("monitoring.io", "UAVMetric", "uavmetrics")
    fake.define_crd("scheduler.io", "SchedulingRequest", "schedulingrequests")
    client = Client(fake, namespaces=["default"])
    return fake, client


def _push_uav(client, node, battery, status="active"):
    client.upsert_uav_metric(
        "",
        UAVReport(
            node_name=node,
            uav_id=f"uav-{node}",
            status=status,
            state={
                "gps": {"latitude": 1.0},
                "battery": {"remaining_percent": battery},
                "flight": {"mode": "AUTO"},
                "health": {"system_status": "OK"},
            },
        ),
    )


def _make_request(fake, name, workload="job-1", min_battery=None, preferred=None):
    spec = {"workload": {"name": workload, "namespace": "default"}}
    if min_battery is not None:
        spec["minBatteryPercent"] = min_battery
    if preferred:
        spec["preferredNodes"] = preferred
    return fake.create_custom_resource(
        "scheduler.io",
        "v1",
        "schedulingrequests",
        "default",
        {"metadata": {"name": name}, "spec": spec},
    )


def _get_request(fake, name):
    return fake.get_custom_resource(
        "scheduler.io", "v1", "schedulingrequests", "default", name
    )


def test_scheduler_assigns_best_battery(sched_world):
    fake, client = sched_world
    _push_uav(client, "node-a", 90.0)
    _push_uav(client, "node-b", 60.0)
    _make_request(fake, "req-1")
    ctrl = SchedulerController(client, SchedulerConfig(tpu_node_bonus=0))
    assert ctrl.reconcile() == 1
    req = _get_request(fake, "req-1")
    assert req["status"]["phase"] == "Assigned"
    assert req["status"]["assignedNode"] == "node-a"
    assert req["status"]["assignedUAV"] == "uav-node-a"
    assert req["status"]["score"] == 90.0


def test_scheduler_preferred_node_bonus(sched_world):
    fake, client = sched_world
    _push_uav(client, "node-a", 90.0)
    _push_uav(client, "node-b", 85.0)
    _make_request(fake, "req-2", preferred=["node-b"])
    ctrl = SchedulerController(client, SchedulerConfig(tpu_node_bonus=0))
    ctrl.reconcile()
    req = _get_request(fake, "req-2")
    # 85 + 10 bonus beats 90
    assert req["status"]["assignedNode"] == "node-b"
    assert req["status"]["score"] == 95.0


def test_scheduler_tpu_node_bonus(sched_world):
    fake, client = sched_world
    _push_uav(client, "node-a", 88.0)
    _push_uav(client, "node-tpu", 85.0)
    _make_request(fake, "req-tpu")
    ctrl = SchedulerController(client, SchedulerConfig(tpu_node_bonus=5.0))
    ctrl.reconcile()
    req = _get_request(fake, "req-tpu")
    assert req["status"]["assignedNode"] == "node-tpu"  # 85+5 > 88


def test_scheduler_filters(sched_world):
    fake, client = sched_world
    _push_uav(client, "node-a", 25.0)  # below requested min battery
    _push_uav(client, "node-b", 80.0, status="stale")  # explicit inactive
    _make_request(fake, "req-3", min_battery=30)
    ctrl = SchedulerController(client)
    ctrl.reconcile()
    req = _get_request(fake, "req-3")
    assert req["status"]["phase"] == "Failed"
    assert "no active UAV" in req["status"]["message"]


def test_scheduler_no_battery_filter_when_unset(sched_world):
    """Ref controller.go:174-221: minBatteryPercent absent/0 = no filter."""
    fake, client = sched_world
    _push_uav(client, "node-a", 5.0)
    _make_request(fake, "req-nofilter")
    ctrl = SchedulerController(client, SchedulerConfig(tpu_node_bonus=0))
    ctrl.reconcile()
    req = _get_request(fake, "req-nofilter")
    assert req["status"]["phase"] == "Assigned"
    assert req["status"]["assignedNode"] == "node-a"


def test_scheduler_accepts_empty_collection_status_and_case(sched_world):
    """Empty collection_status is accepted; "Active" compares lowercased;
    preferred-node matching is case-insensitive (ref :198-208)."""
    fake, client = sched_world
    _push_uav(client, "node-a", 70.0, status="")  # empty -> accepted
    _push_uav(client, "node-b", 70.0, status="Active")  # case-insensitive
    _make_request(fake, "req-ci", preferred=["NODE-B"])
    ctrl = SchedulerController(client, SchedulerConfig(tpu_node_bonus=0))
    ctrl.reconcile()
    req = _get_request(fake, "req-ci")
    assert req["status"]["phase"] == "Assigned"
    assert req["status"]["assignedNode"] == "node-b"  # 70+10 beats 70


def test_scheduler_invalid_workload(sched_world):
    fake, client = sched_world
    fake.create_custom_resource(
        "scheduler.io",
        "v1",
        "schedulingrequests",
        "default",
        {"metadata": {"name": "bad"}, "spec": {"workload": {"name": ""}}},
    )
    ctrl = SchedulerController(client)
    ctrl.reconcile()
    req = _get_request(fake, "bad")
    assert req["status"]["phase"] == "Failed"
    assert "required" in req["status"]["message"]


def test_scheduler_skips_settled_requests(sched_world):
    fake, client = sched_world
    _push_uav(client, "node-a", 90.0)
    _make_request(fake, "req-4")
    ctrl = SchedulerController(client, SchedulerConfig(tpu_node_bonus=0))
    assert ctrl.reconcile() == 1
    # second pass must not reprocess the Assigned request
    assert ctrl.reconcile() == 0


def test_agent_to_scheduler_end_to_end(sched_world):
    """Simulator-fed report → CRD upsert → scheduling request → Assigned."""
    fake, client = sched_world
    agent = UAVAgent(
        node_name="node-a",
        port=0,
        master_url="http://master",
        report_interval=3600,
        poster=lambda url, payload: client.upsert_uav_metric(
            "", UAVReport(**{
                k: v for k, v in payload.items()
                if k in ("node_name", "uav_id", "source", "status", "state")
            })
        ),
    )
    agent.start()
    try:
        import time

        deadline = time.monotonic() + 5
        while agent.reports_sent == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        _make_request(fake, "req-e2e")
        ctrl = SchedulerController(client)
        ctrl.reconcile()
        req = _get_request(fake, "req-e2e")
        assert req["status"]["phase"] == "Assigned"
        assert req["status"]["assignedNode"] == "node-a"
    finally:
        agent.stop()


def test_scheduler_excludes_stale_heartbeat(sched_world):
    """A dead UAV with a fresh-looking "active" CR must not win placement:
    last_update older than 3x the advertised heartbeat interval is excluded
    (the reference parses the heartbeat but never uses it —
    controller.go:202-203, the SURVEY §2.7 soft spot)."""
    import datetime

    from k8s_llm_monitor_tpu.monitor.models import utcnow

    fake, client = sched_world
    old = utcnow() - datetime.timedelta(seconds=60)
    client.upsert_uav_metric("", UAVReport(
        node_name="node-a", uav_id="uav-node-a", status="active",
        timestamp=old, heartbeat_interval_seconds=10,   # 60s >> 3*10s
        state={"battery": {"remaining_percent": 95.0}},
    ))
    _push_uav(client, "node-b", 40.0)                   # fresh, lower battery
    _make_request(fake, "req-stale")
    ctrl = SchedulerController(client, SchedulerConfig(tpu_node_bonus=0))
    ctrl.reconcile()
    req = _get_request(fake, "req-stale")
    assert req["status"]["phase"] == "Assigned"
    assert req["status"]["assignedNode"] == "node-b"    # stale 95% excluded


def test_scheduler_stale_default_cap_without_advertised_heartbeat(sched_world):
    """No advertised heartbeat: the absolute stale_after_seconds cap
    applies; a within-cap CR is still eligible."""
    import datetime

    from k8s_llm_monitor_tpu.monitor.models import utcnow

    fake, client = sched_world
    very_old = utcnow() - datetime.timedelta(seconds=600)
    client.upsert_uav_metric("", UAVReport(
        node_name="node-a", uav_id="uav-node-a", status="active",
        timestamp=very_old,
        state={"battery": {"remaining_percent": 95.0}},
    ))
    recent = utcnow() - datetime.timedelta(seconds=30)
    client.upsert_uav_metric("", UAVReport(
        node_name="node-b", uav_id="uav-node-b", status="active",
        timestamp=recent,
        state={"battery": {"remaining_percent": 50.0}},
    ))
    _make_request(fake, "req-cap")
    ctrl = SchedulerController(client, SchedulerConfig(tpu_node_bonus=0))
    ctrl.reconcile()
    req = _get_request(fake, "req-cap")
    assert req["status"]["assignedNode"] == "node-b"
