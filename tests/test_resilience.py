"""Chaos suite: fault injection against the serving + monitor planes.

Every scenario arms the process-global FaultInjector (resilience/faults.py)
instead of waiting for real hardware to misbehave, then asserts the
*recovery contract*: zero hangs, every request ends in exactly one terminal
state (finished | failed-with-cause | shed-retriable), the KV allocator's
free count returns to its idle baseline, and health transitions
HEALTHY -> DEGRADED -> HEALTHY around the fault window.

Run standalone with ``make chaos``; the suite is deterministic (seeded
injector, CPU mesh) and fast enough to ride in tier-1.
"""

import threading
import time

import pytest

import jax

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.resilience.faults import (
    FaultError,
    FaultInjector,
    get_injector,
)
from k8s_llm_monitor_tpu.resilience.health import (
    DEGRADED,
    DRAINING,
    HEALTHY,
    UNHEALTHY,
    HealthMonitor,
)
from k8s_llm_monitor_tpu.resilience.retry import (
    Backoff,
    CircuitBreaker,
    CircuitOpen,
)
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.service import EngineService, OverloadedError

pytestmark = pytest.mark.chaos

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32, intermediate_size=64,
                  num_layers=2, num_heads=4, num_kv_heads=2, dtype="float32",
                  rope_theta=10_000.0)

# Same shapes as tests/test_service.py so the jit cache is shared across the
# modules; prefix cache off so the allocator baseline is exact (cached
# prefixes intentionally pin pages).
ECFG = dict(max_slots=4, num_blocks=64, block_size=8,
            max_blocks_per_seq=16, prefill_buckets=(16,),
            max_prefills_per_step=4, decode_steps_per_iter=4,
            prefix_cache_entries=0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _fault_isolation():
    """Every test starts and ends with the global injector disarmed."""
    get_injector().reset(seed=1234)
    yield
    get_injector().reset()


def _mk_engine(params, **overrides):
    cfg = dict(ECFG)
    cfg.update(overrides)
    return InferenceEngine(CFG, params, EngineConfig(**cfg), eos_id=-1)


def _run(eng, max_steps=500):
    """Step the engine to completion with a wedge guard (zero-hang proof)."""
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < max_steps, "engine wedged: work left after step budget"


def _wait(predicate, timeout=10.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# -- injector / retry / health units ----------------------------------------


def test_injector_rate_times_after_determinism():
    inj = FaultInjector(seed=7)
    inj.arm("decode_dispatch", rate=1.0, times=2, after=3)
    fires = [inj.should_fire("decode_dispatch") for _ in range(10)]
    # 3 warm-up evaluations, then exactly `times` firings, then silent.
    assert fires == [False] * 3 + [True, True] + [False] * 5
    assert inj.fired("decode_dispatch") == 2

    # Same seed -> identical probabilistic draw sequence.
    draws = []
    for _ in range(2):
        inj.reset(seed=99)
        inj.arm("kube_http_5xx", rate=0.3)
        draws.append([inj.should_fire("kube_http_5xx") for _ in range(50)])
    assert draws[0] == draws[1]
    assert 0 < sum(draws[0]) < 50

    with pytest.raises(FaultError, match="injected fault: decode_dispatch"):
        inj.arm("decode_dispatch")
        inj.maybe_raise("decode_dispatch")


def test_injector_rejects_unknown_point_and_bad_env(monkeypatch):
    inj = FaultInjector()
    with pytest.raises(ValueError, match="unknown fault point"):
        inj.arm("no_such_point")  # graftcheck: disable=fault-point -- deliberately unknown (tests the registry guard)

    monkeypatch.setenv("K8SLLM_FAULTS", "decode_dispatch:0.5,kube_http_5xx")
    env_inj = FaultInjector()
    assert set(env_inj.armed) == {"decode_dispatch", "kube_http_5xx"}

    monkeypatch.setenv("K8SLLM_FAULTS", "decode_dispatch:lots")
    with pytest.raises(ValueError, match="K8SLLM_FAULTS"):
        FaultInjector()


def test_backoff_delays_bounded_and_jittered():
    import random

    bo = Backoff(base_s=0.2, cap_s=1.0, mult=2.0, jitter=0.2, attempts=6,
                 rng=random.Random(0))
    ds = list(bo.delays())
    assert len(ds) == 5  # attempts counts tries; delays sit between them
    # Each delay stays inside [nominal*(1-j), nominal*(1+j)] with the
    # exponential curve capped at cap_s.
    for i, d in enumerate(ds):
        nominal = min(0.2 * 2.0 ** i, 1.0)
        assert nominal * 0.8 <= d <= nominal * 1.2
    assert max(ds) <= 1.2


def test_breaker_trips_probes_and_recovers():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=3, cooldown_s=5.0,
                        clock=lambda: t[0])
    assert br.state == "closed"
    for _ in range(3):
        br.before_call()
        br.record_failure()
    assert br.state == "open" and br.trips == 1
    with pytest.raises(CircuitOpen):
        br.before_call()
    assert br.rejections == 1

    t[0] = 5.1  # cooldown over: half-open grants exactly one probe
    assert br.state == "half-open"
    br.before_call()
    with pytest.raises(CircuitOpen):
        br.before_call()  # second caller must wait for the probe's verdict
    br.record_failure()  # probe failed -> back to open
    assert br.state == "open" and br.trips == 2

    t[0] = 10.3
    br.before_call()
    br.record_success()  # probe succeeded -> closed
    assert br.state == "closed"
    br.before_call()  # and calls flow again


def test_health_state_machine_transitions():
    t = [0.0]
    h = HealthMonitor(window_s=10.0, degraded_shed_rate=0.5,
                      unhealthy_failures=3, clock=lambda: t[0])
    assert h.state() == HEALTHY

    h.record_dispatch_failure()
    snap = h.snapshot()
    assert snap["state"] == DEGRADED and "dispatch failure" in snap["reason"]
    h.record_dispatch_ok()
    t[0] = 11.0  # event ages out of the window
    assert h.state() == HEALTHY

    # Shed rate crosses the degraded threshold.
    h.record_admit()
    h.record_shed()
    h.record_shed()
    assert h.state() == DEGRADED
    t[0] = 22.0
    assert h.state() == HEALTHY

    h.set_draining(True)
    assert h.state() == DRAINING and not h.snapshot()["ready"]
    h.set_draining(False)

    for _ in range(3):
        h.record_dispatch_failure()
    assert h.state() == UNHEALTHY
    h.record_dispatch_ok()
    t[0] = 33.0
    assert h.state() == HEALTHY

    h.set_dead("step loop exploded")
    snap = h.snapshot()
    assert snap["state"] == UNHEALTHY and "exploded" in snap["reason"]


def test_health_endpoints_report_state_and_503():
    """/health carries the real state + counters; both probes flip to 503
    when the health monitor leaves a ready state."""
    import json
    import urllib.error
    import urllib.request

    from k8s_llm_monitor_tpu.monitor.config import Config
    from k8s_llm_monitor_tpu.monitor.server import MonitorServer

    class _EngStub:
        queue_depth = 2
        active_slots = 1
        watchdog_trips = 1
        dispatch_failures = 3
        consecutive_dispatch_failures = 0
        deadline_expired = 0
        requeues = 1

    class _SvcStub:
        def __init__(self):
            self.health = HealthMonitor()
            self.engine = _EngStub()

    class _Backend:
        def __init__(self):
            self.service = _SvcStub()

    class _Analysis:
        def __init__(self):
            self.backend = _Backend()

    analysis = _Analysis()
    srv = MonitorServer(config=Config(), analysis=analysis, port=0)
    srv.start()
    try:
        def get(path):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}{path}") as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as exc:
                return exc.code, json.loads(exc.read())

        status, body = get("/health")
        assert status == 200 and body["status"] == "healthy"
        assert body["engine"]["watchdog_trips"] == 1
        assert body["engine"]["dispatch_failures"] == 3

        status, body = get("/readyz")
        assert status == 200 and body["ready"] is True

        analysis.backend.service.health.set_dead("chaos")
        status, body = get("/health")
        assert status == 503 and body["status"] == "unhealthy"
        status, body = get("/readyz")
        assert status == 503 and body["ready"] is False
        assert body["reason"] == "chaos"
    finally:
        srv.stop()


# -- engine-level chaos ------------------------------------------------------


def test_decode_dispatch_failure_midstream_recovers(params):
    """One injected decode-dispatch failure mid-stream: the engine rolls the
    dispatch back, keeps serving, and every request still finishes."""
    eng = _mk_engine(params)
    get_injector().arm("decode_dispatch", rate=1.0, times=1, after=1)

    baseline = eng.allocator.free_blocks
    prompts = [[5, 6, 7], [9, 10, 11, 12]]
    results = eng.generate(prompts, SamplingParams(max_tokens=10))

    assert get_injector().fired("decode_dispatch") == 1
    assert eng.dispatch_failures == 1
    assert eng.consecutive_dispatch_failures == 0  # cleared by next success
    for res in results:
        assert res.finish_reason == "length" and len(res.token_ids) == 10
    assert eng.allocator.free_blocks == baseline


def test_prefill_dispatch_failure_exhausts_budget_then_serves(params):
    """A deterministic prefill-dispatch failure burns the requeue budget and
    surfaces to the caller with a cause — then the engine serves normally."""
    eng = _mk_engine(params)
    baseline = eng.allocator.free_blocks
    get_injector().arm("prefill_dispatch", rate=1.0)

    [res] = eng.generate([[3, 4, 5]], SamplingParams(max_tokens=4))
    assert res.finish_reason == "error"
    assert "prefill dispatch failed" in res.error
    assert "gave up after" in res.error
    assert eng.requeues == eng.ecfg.max_requeues
    assert eng.allocator.free_blocks == baseline

    get_injector().disarm("prefill_dispatch")
    [res] = eng.generate([[3, 4, 5]], SamplingParams(max_tokens=4))
    assert res.finish_reason == "length"
    assert eng.allocator.free_blocks == baseline


def test_watchdog_resets_stuck_decode(params):
    """A decode payload that never becomes ready trips the dispatch
    watchdog; the pipeline resets and the requests recompute to
    completion — no hang, no KV leak."""
    eng = _mk_engine(params, dispatch_timeout_s=0.05)
    baseline = eng.allocator.free_blocks
    get_injector().arm("decode_stuck", rate=1.0, times=1)

    results = eng.generate([[5, 6, 7], [8, 9]], SamplingParams(max_tokens=8))

    assert eng.watchdog_trips == 1
    assert eng.requeues >= 1
    for res in results:
        assert res.finish_reason in ("length", "eos")
    assert eng.allocator.free_blocks == baseline


def test_deadline_queue_ttl_and_running(params):
    """Expired queued requests fail at admission (queue TTL); a running
    request with its own deadline is aborted and retires with the cause."""
    eng = _mk_engine(params, queue_ttl_s=0.02)
    baseline = eng.allocator.free_blocks

    eng.submit(GenerationRequest("q1", [5, 6], SamplingParams(max_tokens=4)))
    eng.submit(GenerationRequest("q2", [7, 8], SamplingParams(max_tokens=4)))
    time.sleep(0.05)
    eng.step()
    for rid in ("q1", "q2"):
        res = eng.poll(rid)
        assert res is not None and res.finish_reason == "error"
        assert "deadline exceeded" in res.error and "in queue" in res.error
    assert eng.deadline_expired == 2
    assert not eng.has_work
    assert eng.allocator.free_blocks == baseline

    # Running deadline: generous enough to be admitted and produce tokens,
    # far too small for 300 of them.
    eng.submit(GenerationRequest("r1", [5, 6, 7],
                                 SamplingParams(max_tokens=300),
                                 deadline_s=0.2))
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < 500, "deadline abort never retired the slot"
    res = eng.poll("r1")
    assert res.finish_reason == "error"
    assert "deadline exceeded" in res.error and "tokens generated" in res.error
    assert eng.deadline_expired == 3
    assert eng.allocator.free_blocks == baseline


def test_alloc_exhaustion_preempts_then_recovers(params):
    """Injected OutOfBlocks on a decode-time extend forces the recompute
    preemption path; everything still completes and the pool refills."""
    eng = _mk_engine(params)
    baseline = eng.allocator.free_blocks
    # Skip the two admission allocs; fire on the first extend and on its
    # post-release retry so the engine must preempt a victim.
    get_injector().arm("alloc_exhaustion", rate=1.0, times=2, after=2)

    prompts = [[3, 4, 5, 6, 7, 8], [9, 10, 11, 12, 13, 14]]
    results = eng.generate(prompts, SamplingParams(max_tokens=12))

    assert get_injector().fired("alloc_exhaustion") == 2
    assert eng.preemptions >= 1
    for res in results:
        assert res.finish_reason in ("length", "eos")
    assert eng.allocator.free_blocks == baseline


# -- service-level chaos -----------------------------------------------------


def test_load_shedding_and_drain(params):
    """Backlog beyond shed_queue_tokens sheds with a retriable
    OverloadedError carrying the queue evidence; drain stops admission,
    finishes inflight, and flushes every stream."""
    eng = _mk_engine(params, shed_queue_tokens=6, max_admission_rounds=1)
    assert eng.should_shed() == ""
    svc = EngineService(eng)
    try:
        # Fill every slot with slow work, then build queue backlog.
        handles = [svc.submit([3 + i, 4, 5], SamplingParams(max_tokens=40))
                   for i in range(4)]
        handles += [svc.submit([20 + i, 21, 22],
                               SamplingParams(max_tokens=40))
                    for i in range(2)]
        assert _wait(lambda: eng.queue_tokens >= 6), "backlog never built"

        with pytest.raises(OverloadedError) as exc:
            svc.submit([1, 2, 3], SamplingParams(max_tokens=4))
        assert exc.value.retriable
        assert exc.value.queue_depth >= 2 and exc.value.queue_tokens >= 6
        assert svc.shed_count == 1 and svc.health.sheds == 1

        assert svc.drain(timeout=60.0), "drain did not complete"
        assert svc.health.state() == DRAINING
        for h in handles:
            res = h.result(timeout=1.0)  # already flushed by the drain
            assert res.finish_reason in ("length", "eos")

        with pytest.raises(OverloadedError) as exc:
            svc.submit([1, 2], SamplingParams(max_tokens=2))
        assert not exc.value.retriable and "draining" in str(exc.value)
    finally:
        svc.stop()


def test_cancel_while_queued_releases_immediately(params):
    """Cancelling a request that never won a slot must resolve its handle
    right away — not after the running work finishes."""
    eng = _mk_engine(params)
    svc = EngineService(eng)
    try:
        # Occupy all four slots with long generations.
        busy = [svc.submit([3 + i, 4, 5], SamplingParams(max_tokens=60))
                for i in range(4)]
        assert _wait(lambda: eng.active_slots == 4), "slots never filled"

        queued = svc.submit([9, 9, 9], SamplingParams(max_tokens=60))
        queued.cancel()
        res = queued.result(timeout=2.0)
        assert res.finish_reason == "error"
        assert "cancel" in res.error
        # The running work is untouched and still completes.
        assert not busy[0].done or busy[0].result().finish_reason != "error"
        for h in busy:
            assert h.result(timeout=60.0).finish_reason in ("length", "eos")
    finally:
        svc.stop()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_step_loop_death_fails_all_with_cause(params):
    """When the step loop dies, every outstanding handle resolves with the
    cause (no client blocks forever) and the service reports UNHEALTHY."""
    eng = _mk_engine(params)
    svc = EngineService(eng)
    try:
        boom = threading.Event()
        real_step = eng.step

        def step():
            if boom.is_set():
                raise RuntimeError("boom: chaos killed the loop")
            real_step()

        eng.step = step
        h1 = svc.submit([5, 6, 7], SamplingParams(max_tokens=50))
        boom.set()
        res = h1.result(timeout=10.0)
        assert res.finish_reason == "error" and "boom" in res.error
        assert svc.health.state() == UNHEALTHY
        with pytest.raises(RuntimeError, match="dead"):
            svc.submit([1, 2], SamplingParams(max_tokens=2))
    finally:
        svc.stop()


# -- kube client chaos -------------------------------------------------------


def test_kube_5xx_storm_trips_and_recovers_breaker():
    """An apiserver 5xx storm exhausts the retry budget, trips the circuit
    breaker (later calls fail fast), and a half-open probe closes it once
    the storm passes."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from k8s_llm_monitor_tpu.monitor.cluster import ClusterError
    from k8s_llm_monitor_tpu.monitor.kube_rest import KubeRestBackend

    class _Stub(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):  # noqa: N802
            body = json.dumps({"items": []}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = ThreadingHTTPServer(("127.0.0.1", 0), _Stub)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    t = [0.0]
    backend = KubeRestBackend(
        f"http://127.0.0.1:{server.server_address[1]}",
        backoff=Backoff(base_s=0.01, cap_s=0.01, attempts=4),
        breaker=CircuitBreaker(failure_threshold=5, cooldown_s=10.0,
                               clock=lambda: t[0]),
    )
    backend._sleep = lambda s: None  # no real backoff sleeps in tests
    try:
        get_injector().arm("kube_http_5xx", rate=1.0)

        # Storm: the full retry budget fails, the error carries the 503.
        with pytest.raises(ClusterError, match="503"):
            backend.list_nodes()
        assert backend.breaker.state == "closed"  # 4 failures, threshold 5

        # Fifth failure trips the breaker; the remaining attempts are
        # rejected without touching the (injected) apiserver.
        with pytest.raises(ClusterError, match="circuit open"):
            backend.list_nodes()
        assert backend.breaker.state == "open"
        assert backend.breaker.trips == 1
        assert backend.breaker.rejections >= 1

        # While open: fail fast, no retries burned.
        with pytest.raises(ClusterError, match="circuit open"):
            backend.list_nodes()

        # Storm over + cooldown elapsed: half-open probe hits the real
        # stub, succeeds, and the breaker closes.
        get_injector().disarm("kube_http_5xx")
        t[0] = 10.5
        assert backend.list_nodes() == []
        assert backend.breaker.state == "closed"
        assert backend.list_nodes() == []
    finally:
        backend.close()
        server.shutdown()
        server.server_close()


# -- acceptance: mixed chaos workload ---------------------------------------


def test_mixed_chaos_workload(params):
    """ISSUE acceptance: 64 mixed requests under a 5% decode-dispatch fault
    rate plus an allocator-exhaustion burst.  Zero hangs; every request
    ends in exactly one of {finished, failed-with-cause, shed-retriable};
    the allocator returns to baseline; health degrades during the fault
    window and recovers after it."""
    import random

    eng = _mk_engine(params, shed_queue_tokens=160, queue_ttl_s=30.0)
    baseline = eng.allocator.free_blocks
    health = HealthMonitor(window_s=1.5)
    svc = EngineService(eng, health=health)
    rng = random.Random(42)

    get_injector().arm("decode_dispatch", rate=0.05)
    get_injector().arm("alloc_exhaustion", rate=1.0, times=3, after=30)

    outcomes = {"finished": 0, "failed": 0, "shed": 0}
    handles = []
    states_seen = set()
    try:
        for i in range(64):
            prompt = [rng.randrange(3, 300)
                      for _ in range(rng.randrange(3, 11))]
            sampling = SamplingParams(max_tokens=rng.randrange(4, 9))
            deadline = 60.0 if i % 4 == 0 else 0.0
            try:
                handles.append(svc.submit(prompt, sampling,
                                          deadline_s=deadline))
            except OverloadedError as exc:
                assert exc.retriable and exc.queue_tokens > 0
                outcomes["shed"] += 1
            states_seen.add(health.state())

        for h in handles:
            res = h.result(timeout=120.0)  # zero hangs: every handle resolves
            states_seen.add(health.state())
            if res.finish_reason in ("length", "eos"):
                outcomes["finished"] += 1
            else:
                assert res.finish_reason == "error" and res.error
                outcomes["failed"] += 1

        assert sum(outcomes.values()) == 64
        assert outcomes["finished"] >= 32, outcomes
        # The storm actually happened and the state machine saw it.
        assert health.dispatch_failures >= 1
        assert get_injector().fired("alloc_exhaustion") >= 1
        assert DEGRADED in states_seen, states_seen

        # Fault window over: events age out and the state recovers.
        get_injector().reset()
        assert _wait(lambda: health.state() == HEALTHY, timeout=5.0)

        # No KV page leaks across faults, preemptions, and resets.
        assert _wait(lambda: eng.allocator.free_blocks == baseline,
                     timeout=5.0), (
            f"leaked pages: {eng.allocator.free_blocks} != {baseline}")
    finally:
        svc.stop()
