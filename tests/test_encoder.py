"""BERT-family encoder: parity vs transformers' BertModel, mask invariance,
pooling contracts, and the embedding anomaly detector.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.analysis.anomaly import (
    EmbeddingAnomalyDetector,
    HashingTokenizer,
)
from k8s_llm_monitor_tpu.models import encoder
from k8s_llm_monitor_tpu.models.config import EncoderConfig

CFG = EncoderConfig(name="t", vocab_size=120, hidden_size=32,
                    intermediate_size=64, num_layers=2, num_heads=4,
                    max_position_embeddings=64)


@pytest.fixture(scope="module")
def params():
    return encoder.init_params(jax.random.PRNGKey(0), CFG)


def test_parity_with_hf_bert():
    """Convert a randomly-initialized transformers BertModel's weights and
    check our forward reproduces its last_hidden_state."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.BertConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.hidden_size,
        num_hidden_layers=CFG.num_layers, num_attention_heads=CFG.num_heads,
        intermediate_size=CFG.intermediate_size,
        max_position_embeddings=CFG.max_position_embeddings,
        hidden_act="gelu", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    model = transformers.BertModel(hf_cfg, add_pooling_layer=False).eval()
    state = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    params = encoder.params_from_hf_state(state, CFG)

    rng = np.random.default_rng(0)
    tokens = rng.integers(1, CFG.vocab_size, size=(3, 12))
    mask = np.ones((3, 12), np.int64)
    mask[1, 8:] = 0
    mask[2, 5:] = 0
    tokens = tokens * mask  # zero out padding ids like a real tokenizer

    with torch.no_grad():
        want = model(
            input_ids=torch.tensor(tokens),
            attention_mask=torch.tensor(mask),
        ).last_hidden_state.numpy()

    got = np.asarray(encoder.forward(
        params, CFG, jnp.asarray(tokens, jnp.int32),
        jnp.asarray(mask, jnp.int32)))
    # only valid positions are comparable (padding rows are garbage/masked)
    m = mask.astype(bool)
    np.testing.assert_allclose(got[m], want[m], rtol=2e-4, atol=2e-4)


def test_mask_invariance(params):
    """Padding length must not change a sequence's embedding."""
    ids = [1, 7, 9, 22, 5]
    t1 = np.zeros((1, 8), np.int32)
    t1[0, :5] = ids
    m1 = np.zeros((1, 8), np.int32)
    m1[0, :5] = 1
    t2 = np.zeros((1, 16), np.int32)
    t2[0, :5] = ids
    t2[0, 10] = 99  # garbage beyond the mask
    m2 = np.zeros((1, 16), np.int32)
    m2[0, :5] = 1

    e1 = encoder.encode(params, CFG, jnp.asarray(t1), jnp.asarray(m1))
    e2 = encoder.encode(params, CFG, jnp.asarray(t2), jnp.asarray(m2))
    np.testing.assert_allclose(np.asarray(e1), np.asarray(e2),
                               rtol=1e-5, atol=1e-5)


def test_encode_pooling_and_norm(params):
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(1, 100, (4, 10)), jnp.int32)
    mask = jnp.ones((4, 10), jnp.int32)
    for pooling in ("cls", "mean"):
        emb = np.asarray(encoder.encode(params, CFG, tokens, mask,
                                        pooling=pooling))
        assert emb.shape == (4, CFG.hidden_size)
        np.testing.assert_allclose(np.linalg.norm(emb, axis=-1), 1.0,
                                   rtol=1e-5)
    with pytest.raises(ValueError):
        encoder.encode(params, CFG, tokens, mask, pooling="max")


def test_hashing_tokenizer_deterministic():
    tok = HashingTokenizer(500)
    a = tok.encode("Pod failed: OOMKilled in container web", 64)
    b = tok.encode("Pod failed: OOMKilled in container web", 64)
    assert a == b
    assert a[0] == 1 and a[-1] == 2
    assert all(0 <= t < 500 for t in a)


def test_anomaly_detector_flags_planted_outlier():
    det = EmbeddingAnomalyDetector(CFG)
    texts = ["BackOff: restarting failed container web"] * 6 + [
        "NodeHasDiskPressure: node worker-2 status is now NodeHasDiskPressure"
    ]
    flagged = det.flag_outliers(texts)
    assert any(i == 6 for i, _ in flagged), flagged
    # the repeated texts must not be flagged
    assert all(i == 6 for i, _ in flagged)


def test_anomaly_detector_small_batches_and_empty():
    det = EmbeddingAnomalyDetector(CFG)
    assert det.flag_outliers([]) == []
    assert det.flag_outliers(["a", "b", "c"]) == []
    assert det.score([]) == []
    scores = det.score(["same text"] * 5)
    assert max(scores) < 1e-3  # identical texts sit at the centroid


def test_bf16_encoder_tracks_f32():
    """The bf16 serving variant's embeddings stay close to f32 (pooling
    and normalization are f32 either way) — the contract behind the
    bge-large-bf16 bench preset."""
    import dataclasses as _dc

    import numpy as np

    from k8s_llm_monitor_tpu.analysis.anomaly import EmbeddingAnomalyDetector
    from k8s_llm_monitor_tpu.models.config import TINY_ENCODER

    docs = [f"container web-{i} OOMKilled exit 137" for i in range(8)]
    docs[5] = "scheduler assigned uav survey job to node-b"
    det32 = EmbeddingAnomalyDetector(TINY_ENCODER)
    det16 = EmbeddingAnomalyDetector(
        _dc.replace(TINY_ENCODER, name="tiny-bf16", dtype="bfloat16"))
    e32 = np.asarray(det32.embed(docs))
    e16 = np.asarray(det16.embed(docs))
    cos = (e32 * e16).sum(-1)  # both L2-normalized
    assert cos.min() > 0.99, cos


def test_from_checkpoint_disk_bert_with_hf_tokenizer(tmp_path):
    """The production seam behind ``analysis.embedding_model: <path>``
    (monitor/server.py boot): a BertModel checkpoint directory ON DISK plus
    its saved tokenizer -> ``EmbeddingAnomalyDetector.from_checkpoint`` ->
    embeddings that match transformers' CLS output over the HF-tokenized
    ids.  Every other encoder test converts an in-memory state dict; this
    one proves the disk + AutoTokenizer branch (anomaly.py from_checkpoint)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from tokenizers import Tokenizer, models, pre_tokenizers, processors

    words = ("pod node oom killed restart dns network error warning "
             "battery uav scheduler image pull ready probe the a is").split()
    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3}
    for w in words:
        vocab.setdefault(w, len(vocab))
    tok = Tokenizer(models.WordLevel(vocab, unk_token="[UNK]"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    tok.post_processor = processors.TemplateProcessing(
        single="[CLS] $A [SEP]",
        special_tokens=[("[CLS]", 2), ("[SEP]", 3)])
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, pad_token="[PAD]", unk_token="[UNK]",
        cls_token="[CLS]", sep_token="[SEP]")

    hf_cfg = transformers.BertConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.hidden_size,
        num_hidden_layers=CFG.num_layers, num_attention_heads=CFG.num_heads,
        intermediate_size=CFG.intermediate_size,
        max_position_embeddings=CFG.max_position_embeddings,
        hidden_act="gelu", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    model = transformers.BertModel(hf_cfg, add_pooling_layer=False).eval()

    ckpt = tmp_path / "bert-ckpt"
    model.save_pretrained(ckpt, safe_serialization=True)
    fast.save_pretrained(ckpt)
    assert (ckpt / "model.safetensors").exists()

    det = EmbeddingAnomalyDetector.from_checkpoint(str(ckpt))
    # The HF tokenizer branch must be taken, not the hashing fallback.
    assert not isinstance(det.tokenizer, HashingTokenizer)
    assert det.tokenizer.encode("pod oom killed", 16)[0] == 2  # [CLS]

    texts = ["pod oom killed restart", "dns error warning",
             "uav battery scheduler", "image pull error"]
    got = det.embed(texts)
    assert got.shape == (4, CFG.hidden_size)

    batch = fast(texts, padding=True, return_tensors="pt")
    with torch.no_grad():
        hidden = model(**{k: batch[k] for k in
                          ("input_ids", "attention_mask")}).last_hidden_state
    cls = hidden[:, 0, :].numpy()
    want = cls / np.maximum(
        np.linalg.norm(cls, axis=-1, keepdims=True), 1e-9)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    # The detector built from disk drives the scoring surface end-to-end.
    assert len(det.score(texts)) == 4
