"""OpenAI-compatible fallback backend: retry on transient failures,
error-body surfacing on permanent ones (VERDICT r3 weak #5)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k8s_llm_monitor_tpu.monitor.analysis import OpenAICompatBackend
from k8s_llm_monitor_tpu.monitor.config import LLMConfig


class _StubLLM(BaseHTTPRequestHandler):
    fail_times = 0          # 502s to serve before succeeding
    fail_status = 502
    calls = 0

    def log_message(self, *a):  # noqa: D102
        pass

    def do_POST(self):  # noqa: N802
        cls = type(self)
        cls.calls += 1
        n = int(self.headers.get("Content-Length", 0))
        self.rfile.read(n)
        if cls.calls <= cls.fail_times:
            body = json.dumps({"error": "upstream exploded"}).encode()
            self.send_response(cls.fail_status)
        else:
            body = json.dumps({"choices": [
                {"message": {"content": "the pod is OOMKilled"}}]}).encode()
            self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture
def stub():
    _StubLLM.calls = 0
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StubLLM)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _backend(srv) -> OpenAICompatBackend:
    cfg = LLMConfig(provider="openai", api_key="k", model="m",
                    base_url=f"http://127.0.0.1:{srv.server_address[1]}/v1",
                    timeout=5)
    b = OpenAICompatBackend(cfg)
    b.backoff_s = 0.01  # fast tests
    return b


def test_retries_transient_502(stub):
    _StubLLM.fail_times = 2
    _StubLLM.fail_status = 502
    out = _backend(stub).generate("why crashloop?")
    assert out == "the pod is OOMKilled"
    assert _StubLLM.calls == 3


def test_permanent_error_surfaces_body(stub):
    _StubLLM.fail_times = 99
    _StubLLM.fail_status = 401
    with pytest.raises(RuntimeError) as err:
        _backend(stub).generate("q")
    assert "401" in str(err.value) and "upstream exploded" in str(err.value)
    assert _StubLLM.calls == 1  # 401 is not retried


def test_exhausted_retries_raise(stub):
    _StubLLM.fail_times = 99
    _StubLLM.fail_status = 503
    b = _backend(stub)
    with pytest.raises(RuntimeError) as err:
        b.generate("q")
    assert "503" in str(err.value)
    assert _StubLLM.calls == b.max_retries + 1


def test_non_json_200_is_retried(stub):
    """200 + HTML error page (LB/proxy) is as transient as a 502 and must
    not escape as a raw JSONDecodeError."""
    class _HTML(_StubLLM):
        def do_POST(self):  # noqa: N802
            cls = _StubLLM
            cls.calls += 1
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            if cls.calls <= cls.fail_times:
                body = b"<html>503 Service Unavailable</html>"
                self.send_response(200)
            else:
                body = json.dumps({"choices": [
                    {"message": {"content": "ok"}}]}).encode()
                self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    stub.RequestHandlerClass = _HTML
    _StubLLM.fail_times = 1
    out = _backend(stub).generate("q")
    assert out == "ok" and _StubLLM.calls == 2
