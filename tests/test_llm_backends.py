"""OpenAI-compatible fallback backend: retry on transient failures,
error-body surfacing on permanent ones (VERDICT r3 weak #5)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k8s_llm_monitor_tpu.monitor.analysis import OpenAICompatBackend
from k8s_llm_monitor_tpu.monitor.config import LLMConfig


class _StubLLM(BaseHTTPRequestHandler):
    fail_times = 0          # 502s to serve before succeeding
    fail_status = 502
    calls = 0

    def log_message(self, *a):  # noqa: D102
        pass

    def do_POST(self):  # noqa: N802
        cls = type(self)
        cls.calls += 1
        n = int(self.headers.get("Content-Length", 0))
        self.rfile.read(n)
        if cls.calls <= cls.fail_times:
            body = json.dumps({"error": "upstream exploded"}).encode()
            self.send_response(cls.fail_status)
        else:
            body = json.dumps({"choices": [
                {"message": {"content": "the pod is OOMKilled"}}]}).encode()
            self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture
def stub():
    _StubLLM.calls = 0
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StubLLM)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _backend(srv) -> OpenAICompatBackend:
    cfg = LLMConfig(provider="openai", api_key="k", model="m",
                    base_url=f"http://127.0.0.1:{srv.server_address[1]}/v1",
                    timeout=5)
    b = OpenAICompatBackend(cfg)
    b.backoff_s = 0.01  # fast tests
    return b


def test_retries_transient_502(stub):
    _StubLLM.fail_times = 2
    _StubLLM.fail_status = 502
    out = _backend(stub).generate("why crashloop?")
    assert out == "the pod is OOMKilled"
    assert _StubLLM.calls == 3


def test_permanent_error_surfaces_body(stub):
    _StubLLM.fail_times = 99
    _StubLLM.fail_status = 401
    with pytest.raises(RuntimeError) as err:
        _backend(stub).generate("q")
    assert "401" in str(err.value) and "upstream exploded" in str(err.value)
    assert _StubLLM.calls == 1  # 401 is not retried


def test_exhausted_retries_raise(stub):
    _StubLLM.fail_times = 99
    _StubLLM.fail_status = 503
    b = _backend(stub)
    with pytest.raises(RuntimeError) as err:
        b.generate("q")
    assert "503" in str(err.value)
    assert _StubLLM.calls == b.max_retries + 1


def test_non_json_200_is_retried(stub):
    """200 + HTML error page (LB/proxy) is as transient as a 502 and must
    not escape as a raw JSONDecodeError."""
    class _HTML(_StubLLM):
        def do_POST(self):  # noqa: N802
            cls = _StubLLM
            cls.calls += 1
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            if cls.calls <= cls.fail_times:
                body = b"<html>503 Service Unavailable</html>"
                self.send_response(200)
            else:
                body = json.dumps({"choices": [
                    {"message": {"content": "ok"}}]}).encode()
                self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    stub.RequestHandlerClass = _HTML
    _StubLLM.fail_times = 1
    out = _backend(stub).generate("q")
    assert out == "ok" and _StubLLM.calls == 2


def test_tpu_backend_boot_preflight_warns_on_unfittable_config(caplog):
    """An over-budget llm.tpu config logs the preflight verdict at boot
    (before the weight build could OOM a real chip) and still boots —
    warn-only by contract."""
    import logging

    from k8s_llm_monitor_tpu.monitor.analysis import LocalEngineBackend
    from k8s_llm_monitor_tpu.monitor.config import TPULLMConfig

    cfg = TPULLMConfig(model="tiny", quantize="", kv_blocks=8)
    with caplog.at_level(logging.WARNING):
        backend = LocalEngineBackend.from_config(cfg)
    try:
        assert any("preflight FAILED" in m for m in caplog.messages)
        assert any("raise --kv-blocks" in m for m in caplog.messages)
        assert backend.engine is not None  # boot proceeded regardless
    finally:
        backend.service.stop()


def test_tpu_backend_boot_preflight_tolerates_bogus_quantize(caplog):
    """An unknown llm.tpu.quantize value must neither crash boot (argparse
    SystemExit is contained) nor silently size the wrong dtype: it maps to
    bf16 exactly like the engine build does."""
    import logging

    from k8s_llm_monitor_tpu.monitor.analysis import LocalEngineBackend
    from k8s_llm_monitor_tpu.monitor.config import TPULLMConfig

    cfg = TPULLMConfig(model="tiny", quantize="fp8-bogus", kv_blocks=8)
    with caplog.at_level(logging.WARNING):
        backend = LocalEngineBackend.from_config(cfg)
    try:
        assert any("preflight FAILED" in m for m in caplog.messages)
        # bf16 engine (unknown quantize falls back, matching from_config)
        import jax.numpy as jnp

        q0 = backend.engine.params["layers"][0]["q"]
        assert "kernel_q" not in q0 and q0["kernel"].dtype == jnp.bfloat16
    finally:
        backend.service.stop()
