"""Dataflow lint suite: call graph, reachability, and the three
interprocedural rules (graftcheck --dataflow).

Every rule gets a seeded-violation positive on a fixture package and a
clean negative that mirrors the *real* exclusions in the repo (watchdog-
guarded sleep, fault-injector-tainted delay, timeout-bounded HTTP,
sanctioned WAL IO) — so the exclusions are provably load-bearing, not
accidents of the checker.  The final test pins the live package clean.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from k8s_llm_monitor_tpu.devtools import dataflow
from k8s_llm_monitor_tpu.devtools.dataflow import (
    analyze_paths, build_index, reachable_from)

REPO_ROOT = Path(__file__).resolve().parents[1]
PKG_ROOT = REPO_ROOT / "k8s_llm_monitor_tpu"

ENTRIES = (("engine.py", "Engine.step"),)


def write_pkg(tmp_path: Path, files: dict[str, str]) -> Path:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    return tmp_path


def run(tmp_path: Path, files: dict[str, str], rule: str,
        entries=ENTRIES):
    root = write_pkg(tmp_path, files)
    return analyze_paths([root], rules=[rule], entries=entries)


# -- call graph --------------------------------------------------------------


def test_call_graph_resolves_methods_functions_and_imports(tmp_path):
    root = write_pkg(tmp_path, {
        "engine.py": """
            from journal import append_wal

            def helper():
                append_wal(b"x")

            class Engine:
                def step(self):
                    self._drain()
                    helper()

                def _drain(self):
                    def flush():
                        pass
                    flush()
            """,
        "journal.py": """
            def append_wal(rec):
                pass
            """,
    })
    idx = build_index([root])
    roots = [fi for fi in idx.funcs.values() if fi.qual == "Engine.step"]
    assert len(roots) == 1
    pred = reachable_from(idx, roots)
    names = {idx.funcs[q].display for q in pred}
    # self-method, module function, cross-module import, nested def
    assert names == {"engine.Engine.step", "engine.Engine._drain",
                     "engine.helper", "journal.append_wal",
                     "engine.Engine._drain.<locals>.flush"}


def test_call_graph_follows_base_class_methods(tmp_path):
    root = write_pkg(tmp_path, {
        "base.py": """
            class Base:
                def run(self):
                    pass
            """,
        "engine.py": """
            from base import Base

            class Engine(Base):
                def step(self):
                    self.run()
            """,
    })
    idx = build_index([root])
    pred = reachable_from(
        idx, [fi for fi in idx.funcs.values() if fi.qual == "Engine.step"])
    assert any(idx.funcs[q].display == "base.Base.run" for q in pred)


# -- blocking-in-hot-path ----------------------------------------------------


def test_blocking_flags_sleep_two_calls_from_entry(tmp_path):
    findings = run(tmp_path, {
        "engine.py": """
            import time

            def backoff():
                time.sleep(0.5)

            def reconcile():
                backoff()

            class Engine:
                def step(self):
                    reconcile()
            """,
    }, "blocking-in-hot-path")
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "blocking-in-hot-path"
    assert "time.sleep" in f.message
    # witness chain walks back to the entry
    assert "Engine.step" in f.message and "backoff" in f.message


def test_blocking_flags_file_io_and_subprocess(tmp_path):
    findings = run(tmp_path, {
        "engine.py": """
            import subprocess

            class Engine:
                def step(self):
                    cfg = open("/etc/cfg").read()
                    subprocess.run(["kubectl", "get", "pods"])
                    return cfg
            """,
    }, "blocking-in-hot-path")
    assert {m for m in (f.message.split("'")[1] for f in findings)} == {
        "open (file IO)", "subprocess.run (subprocess)"}


def test_blocking_ignores_watchdog_guarded_function(tmp_path):
    findings = run(tmp_path, {
        "engine.py": """
            import time

            class Engine:
                def step(self):
                    self._reconcile()

                def _reconcile(self):
                    if self.watchdog_trips > 0:
                        time.sleep(0.01)
            """,
    }, "blocking-in-hot-path")
    assert findings == []


def test_blocking_ignores_fault_injector_tainted_sleep(tmp_path):
    findings = run(tmp_path, {
        "engine.py": """
            import time

            class Engine:
                def step(self):
                    d = self._inj.delay_s("decode.step")
                    time.sleep(d)
            """,
    }, "blocking-in-hot-path")
    assert findings == []


def test_blocking_ignores_timeout_bounded_calls(tmp_path):
    findings = run(tmp_path, {
        "engine.py": """
            from urllib.request import urlopen

            class Engine:
                def step(self):
                    urlopen("http://replica/generate", timeout=2.0)
            """,
    }, "blocking-in-hot-path")
    assert findings == []


def test_blocking_ignores_sanctioned_wal_module(tmp_path):
    findings = run(tmp_path, {
        "engine.py": """
            from journal import append_wal

            class Engine:
                def step(self):
                    append_wal(b"rec")
            """,
        "resilience/journal.py": """
            def append_wal(rec):
                with open("/tmp/wal", "ab") as fh:
                    fh.write(rec)
            """,
    }, "blocking-in-hot-path")
    assert findings == []


def test_blocking_cold_path_not_flagged(tmp_path):
    findings = run(tmp_path, {
        "engine.py": """
            import time

            class Engine:
                def step(self):
                    pass

                def shutdown(self):
                    time.sleep(1.0)
            """,
    }, "blocking-in-hot-path")
    assert findings == []


def test_suppression_comment_silences_dataflow_finding(tmp_path):
    findings = run(tmp_path, {
        "engine.py": """
            import time

            class Engine:
                def step(self):
                    time.sleep(1.0)  # graftcheck: disable=blocking-in-hot-path
            """,
    }, "blocking-in-hot-path")
    assert findings == []


# -- recompile-hazard --------------------------------------------------------


def test_recompile_flags_host_read_in_jit_callee(tmp_path):
    findings = run(tmp_path, {
        "kernels.py": """
            import jax, time

            def scaled(x):
                return x * time.time()

            @jax.jit
            def kernel(x):
                return scaled(x)
            """,
    }, "recompile-hazard")
    assert len(findings) == 1
    assert "time.time" in findings[0].message
    assert "kernel" in findings[0].message  # traced-via chain


def test_recompile_flags_device_sync_anywhere_in_traced_flow(tmp_path):
    findings = run(tmp_path, {
        "kernels.py": """
            import jax

            @jax.jit
            def kernel(x):
                y = x + 1
                return float(y.item())
            """,
    }, "recompile-hazard")
    assert len(findings) == 1
    assert "device->host sync" in findings[0].message


def test_recompile_flags_mutable_closure_capture(tmp_path):
    findings = run(tmp_path, {
        "kernels.py": """
            import jax

            def build(scale):
                table = [1.0, 2.0, 4.0]

                def f(x):
                    return x * table[0]

                return jax.jit(f)
            """,
    }, "recompile-hazard")
    assert len(findings) == 1
    assert "captures 'table'" in findings[0].message


def test_recompile_root_host_read_left_to_astlint(tmp_path):
    # the direct read in the jit root is astlint's jit-host-read;
    # the dataflow rule only adds the interprocedural cases
    findings = run(tmp_path, {
        "kernels.py": """
            import jax, time

            @jax.jit
            def kernel(x):
                return x * time.time()
            """,
    }, "recompile-hazard")
    assert findings == []


def test_recompile_untraced_function_clean(tmp_path):
    findings = run(tmp_path, {
        "host.py": """
            import time

            def collect():
                return time.time()
            """,
    }, "recompile-hazard")
    assert findings == []


# -- lock-order-static -------------------------------------------------------


def test_lock_order_flags_nested_with_cycle(tmp_path):
    findings = run(tmp_path, {
        "a.py": """
            from locks import make_lock

            pool_lock = make_lock("pool")
            sched_lock = make_lock("sched")

            def alloc():
                with pool_lock:
                    with sched_lock:
                        pass

            def evict():
                with sched_lock:
                    with pool_lock:
                        pass
            """,
        "locks.py": """
            def make_lock(name):
                return object()
            """,
    }, "lock-order-static")
    assert len(findings) == 1
    assert "pool" in findings[0].message and "sched" in findings[0].message


def test_lock_order_flags_cycle_through_call_graph(tmp_path):
    findings = run(tmp_path, {
        "a.py": """
            from locks import make_lock

            pool_lock = make_lock("pool")
            sched_lock = make_lock("sched")

            def grab_pool():
                with pool_lock:
                    pass

            def alloc():
                with pool_lock:
                    with sched_lock:
                        pass

            def evict():
                with sched_lock:
                    grab_pool()
            """,
        "locks.py": """
            def make_lock(name):
                return object()
            """,
    }, "lock-order-static")
    assert len(findings) == 1
    assert "call into" in findings[0].message


def test_lock_order_consistent_order_clean(tmp_path):
    findings = run(tmp_path, {
        "a.py": """
            from locks import make_lock

            pool_lock = make_lock("pool")
            sched_lock = make_lock("sched")

            def alloc():
                with pool_lock:
                    with sched_lock:
                        pass

            def evict():
                with pool_lock:
                    with sched_lock:
                        pass
            """,
        "locks.py": """
            def make_lock(name):
                return object()
            """,
    }, "lock-order-static")
    assert findings == []


def test_lock_identity_is_scoped_not_textual(tmp_path):
    # self._lock in two different classes must never unify into one lock
    findings = run(tmp_path, {
        "a.py": """
            from locks import make_lock

            class Pool:
                def __init__(self):
                    self._lock = make_lock("pool")

                def use(self, sched):
                    with self._lock:
                        sched.use_raw()

            class Sched:
                def __init__(self):
                    self._lock = make_lock("sched")

                def use_raw(self):
                    with self._lock:
                        pass
            """,
        "locks.py": """
            def make_lock(name):
                return object()
            """,
    }, "lock-order-static")
    assert findings == []


# -- the live repo -----------------------------------------------------------


def test_live_package_passes_all_dataflow_rules():
    findings = analyze_paths([PKG_ROOT])
    assert findings == [], dataflow.render(findings)


def test_hot_entries_exist_and_reach_real_code():
    idx = build_index([PKG_ROOT])
    roots = [fi for fi in idx.funcs.values()
             for (sfx, qual) in dataflow.HOT_ENTRIES
             if fi.qual == qual
             and fi.path.replace("\\", "/").endswith(sfx)]
    # every configured entry resolves to exactly one real function
    assert len(roots) == len(dataflow.HOT_ENTRIES)
    pred = reachable_from(idx, roots)
    # the hot set is a real interprocedural closure, not just the roots
    assert len(pred) > 10 * len(roots)
