"""KV prefix caching: allocator refcounts, PrefixCache semantics, and
engine-level reuse — N same-prefix requests prefill the prefix once, reuse
is exact (greedy outputs unchanged), and cache eviction relieves page
pressure before preemption.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.resilience.tenancy import DEFAULT_TENANT as TEN
from k8s_llm_monitor_tpu.serving.kv_cache import BlockAllocator, PrefixCache

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _naive_greedy(params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    return seq[len(prompt):]


# ---------------------------------------------------------------------------
# Allocator refcounts
# ---------------------------------------------------------------------------


def test_allocator_refcount_lifecycle():
    a = BlockAllocator(num_blocks=8, block_size=4)
    blocks = a.alloc(10)                 # 3 blocks
    assert a.free_blocks == 4
    shared = blocks[:2]
    a.incref(shared)
    assert a.ref_count(blocks[0]) == 2
    mine = list(blocks)
    a.free(mine)                         # drops to 1 ref on shared, 0 on last
    assert mine == []
    assert a.free_blocks == 5            # only the unshared block returned
    still = list(shared)
    a.free(still)
    assert a.free_blocks == 7


def test_allocator_rejects_null_block_ops():
    a = BlockAllocator(num_blocks=4, block_size=4)
    with pytest.raises(ValueError):
        a.incref([0])
    with pytest.raises(ValueError):
        a.free([0])


# ---------------------------------------------------------------------------
# PrefixCache unit semantics
# ---------------------------------------------------------------------------


def test_prefix_cache_lookup_longest_and_refcounts():
    a = BlockAllocator(num_blocks=32, block_size=4)
    pc = PrefixCache(a, max_entries=8)
    prompt = list(range(100, 118))                 # 18 tokens -> 4 full blocks
    blocks = a.alloc(len(prompt) + 1)
    pc.register(prompt, blocks, tenant=TEN)
    assert len(pc) == 4                            # one entry per prefix length
    # Block i is held by its slot plus every entry covering it (lengths > i).
    assert a.ref_count(blocks[0]) == 1 + 4
    assert a.ref_count(blocks[3]) == 1 + 1

    # Identical prompt: all 4 full blocks reused.
    shared, toks = pc.lookup(list(prompt), tenant=TEN)
    assert toks == 16 and shared == blocks[:4]
    assert a.ref_count(shared[0]) == 1 + 4 + 1
    a.free(shared)

    # Prompt diverging inside block 3: only 2 blocks reused.
    div = prompt[:10] + [9, 9, 9, 9, 9, 9, 9, 9]
    shared, toks = pc.lookup(div, tenant=TEN)
    assert toks == 8 and shared == blocks[:2]
    a.free(shared)

    # Fully different prompt: miss.
    shared, toks = pc.lookup([7] * 18, tenant=TEN)
    assert shared == [] and toks == 0


def test_prefix_cache_never_shares_whole_prompt():
    """At least one prompt token must stay unshared (its logits produce the
    first generated token)."""
    a = BlockAllocator(num_blocks=16, block_size=4)
    pc = PrefixCache(a)
    prompt = list(range(8))                        # exactly 2 blocks
    blocks = a.alloc(len(prompt) + 1)
    pc.register(prompt, blocks, tenant=TEN)
    shared, toks = pc.lookup(list(prompt), tenant=TEN)
    assert toks == 4 and len(shared) == 1          # only the first block
    a.free(shared)


def test_prefix_cache_tenant_namespace_blocks_cross_tenant_hits():
    """The same prompt registered by tenant A must be invisible to tenant
    B — digests are seeded per tenant, so a cross-tenant lookup is a
    structural miss, not a policy decision.  Resident-block accounting
    attributes the entry to its owner."""
    a = BlockAllocator(num_blocks=32, block_size=4)
    pc = PrefixCache(a, max_entries=8)
    prompt = list(range(100, 118))                 # 4 full blocks
    blocks = a.alloc(len(prompt) + 1)
    pc.register(prompt, blocks, tenant="team-a")
    shared, toks = pc.lookup(list(prompt), tenant="team-b")
    assert shared == [] and toks == 0              # structurally impossible
    shared, toks = pc.lookup(list(prompt), tenant="team-a")
    assert toks == 16 and shared == blocks[:4]
    a.free(shared)
    per = pc.blocks_by_tenant()
    assert per.get("team-a", 0) > 0 and "team-b" not in per


def test_eviction_with_live_follower_does_not_free_shared_pages():
    """LRU eviction while a follower holds lookup refs on the entry's
    blocks must drop only the cache's refs — the follower's pages stay
    allocated (and intact) until the follower releases them."""
    a = BlockAllocator(num_blocks=32, block_size=4)
    pc = PrefixCache(a, max_entries=2)
    prompt = list(range(100, 109))                 # 9 tokens -> 2 full blocks
    blocks = a.alloc(10)
    pc.register(prompt, blocks, tenant=TEN)
    a.free(blocks)                                 # slot done; cache holds on

    shared, toks = pc.lookup(list(prompt), tenant=TEN)  # follower attaches
    assert toks == 8 and len(shared) == 2

    # Displace the entry while the follower is still attached.
    p2 = [7] * 9
    b2 = a.alloc(10)
    pc.register(p2, b2, tenant=TEN)
    a.free(b2)
    assert pc.evictions >= 1

    # Cache refs dropped, follower refs intact: exactly one holder each,
    # and the pages are NOT back in the free pool.
    assert a.ref_count(shared[0]) == 1
    assert a.ref_count(shared[1]) == 1
    assert a.free_blocks == 27     # 31 usable - 2 follower - 2 new entry
    follower = list(shared)
    a.free(follower)
    assert a.free_blocks == 29
    pc.clear()
    assert a.free_blocks == 31


def test_prefix_cache_eviction_returns_blocks():
    a = BlockAllocator(num_blocks=32, block_size=4)
    pc = PrefixCache(a, max_entries=4)
    prompts = [[i] * 9 for i in range(3)]          # 2 full blocks each
    for p in prompts:
        blocks = a.alloc(10)
        pc.register(p, blocks, tenant=TEN)
        a.free(blocks)                             # slot done; cache holds on
    assert len(pc) <= 4 and pc.evictions >= 1      # LRU entries displaced
    free0 = a.free_blocks
    pc.clear()
    assert a.free_blocks == 31 and a.free_blocks > free0  # everything back


# ---------------------------------------------------------------------------
# Engine-level reuse
# ---------------------------------------------------------------------------


def _engine(params, **over):
    kw = dict(max_slots=4, num_blocks=64, block_size=8,
              max_blocks_per_seq=16, prefill_buckets=(16, 32))
    kw.update(over)
    return InferenceEngine(CFG, params, EngineConfig(**kw), eos_id=-1)


def test_same_prefix_requests_allocate_prefix_once(params):
    eng = _engine(params)
    rng = np.random.default_rng(0)
    prefix = list(rng.integers(3, 300, size=24))   # 3 full blocks at bs=8
    p1 = prefix + list(rng.integers(3, 300, size=4))
    p2 = prefix + list(rng.integers(3, 300, size=5))

    r1 = eng.generate([p1], SamplingParams(max_tokens=6))[0]
    hits0 = eng.prefix_cache.hits
    free_before = eng.allocator.free_blocks
    eng.submit(GenerationRequest("p2", list(p2), SamplingParams(max_tokens=6)))
    # Admission happens on the first step; snapshot allocation right after.
    eng.step()
    allocated = free_before - eng.allocator.free_blocks
    assert eng.prefix_cache.hits == hits0 + 1
    # p2 needs blocks for 29+1 tokens = 4 blocks total; 3 are shared, so at
    # most 1-2 fresh blocks (decode extension may add one more).
    assert allocated <= 2
    while eng.has_work:
        eng.step()
    r2 = eng.poll("p2")
    assert r1.token_ids == _naive_greedy(params, p1, 6)
    assert r2.token_ids == _naive_greedy(params, p2, 6)


def test_batched_mixed_hit_miss_round_is_exact(params):
    """One admission round mixing prefix hits and misses (the chunked
    batched program with per-lane start) must reproduce naive outputs."""
    eng = _engine(params, max_prefills_per_step=4)
    rng = np.random.default_rng(1)
    prefix = list(rng.integers(3, 300, size=17))   # 2 full blocks
    seed_prompt = prefix + [7, 8]
    eng.generate([seed_prompt], SamplingParams(max_tokens=2))  # seeds cache

    prompts = [
        prefix + list(rng.integers(3, 300, size=3)),   # hit
        list(rng.integers(3, 300, size=12)),           # miss
        prefix + list(rng.integers(3, 300, size=6)),   # hit
    ]
    results = eng.generate(prompts, SamplingParams(max_tokens=5))
    for p, r in zip(prompts, results):
        assert r.token_ids == _naive_greedy(params, p, 5), "prefix reuse changed output"
    assert eng.prefix_cache.hits >= 2


def test_long_prompt_prefix_hit_shortens_chunk_loop(params):
    """A long prompt whose prefix is cached admits via suffix-only chunks
    (or even the batched path when the suffix fits a bucket)."""
    eng = _engine(params, num_blocks=128, max_blocks_per_seq=16,
                  prefill_buckets=(16,))
    rng = np.random.default_rng(2)
    long_prompt = list(rng.integers(3, 300, size=60))  # >> bucket 16
    r1 = eng.generate([long_prompt], SamplingParams(max_tokens=4))[0]
    prefills0 = eng.prefills
    hits0 = eng.prefix_cache.hits
    # Same prompt + divergent tail: shares 56 tokens (7 blocks), suffix 8.
    p2 = long_prompt[:56] + list(rng.integers(3, 300, size=4))
    r2 = eng.generate([p2], SamplingParams(max_tokens=4))[0]
    assert eng.prefix_cache.hits == hits0 + 1
    assert r2.token_ids == _naive_greedy(params, p2, 4)
    assert r1.token_ids == _naive_greedy(params, long_prompt, 4)


def test_cache_eviction_relieves_pressure_before_preemption(params):
    """With the pool nearly exhausted by cached prefixes, new work evicts
    cache entries instead of preempting or failing."""
    eng = _engine(params, max_slots=2, num_blocks=16, block_size=8,
                  prefill_buckets=(16, 32))
    rng = np.random.default_rng(3)
    # Fill the cache with distinct prompts (each leaves a 2-3 block entry).
    for i in range(4):
        p = list(rng.integers(3, 300, size=20))
        eng.generate([p], SamplingParams(max_tokens=2))
    assert len(eng.prefix_cache) >= 2
    # A burst that needs most of the pool: must succeed via eviction.
    prompts = [list(rng.integers(3, 300, size=24)) for _ in range(2)]
    results = eng.generate(prompts, SamplingParams(max_tokens=8))
    for p, r in zip(prompts, results):
        assert r.finish_reason == "length"
        assert r.token_ids == _naive_greedy(params, p, 8)
    assert eng.prefix_cache.evictions > 0


def test_prefix_cache_disabled(params):
    eng = _engine(params, prefix_cache_entries=0)
    assert eng.prefix_cache is None
    p = list(np.random.default_rng(4).integers(3, 300, size=20))
    r = eng.generate([p, list(p)], SamplingParams(max_tokens=4))
    assert all(x.token_ids == _naive_greedy(params, p, 4) for x in r)


def test_cold_burst_prefills_shared_prefix_once(params):
    """A simultaneous burst of same-prefix requests with a COLD cache (the
    /api/v1/query shape right after a new snapshot) computes the prefix in
    one lane: every other candidate is deferred one admission round and
    admits as a suffix-only hit.  Outputs stay exactly greedy."""
    eng = _engine(params, max_slots=8, max_prefills_per_step=8)
    rng = np.random.default_rng(7)
    prefix = list(rng.integers(3, 300, size=24))   # 3 full blocks at bs=8
    prompts = [prefix + list(rng.integers(3, 300, size=4)) for _ in range(5)]
    for i, p in enumerate(prompts):
        eng.submit(GenerationRequest(f"c{i}", list(p),
                                     SamplingParams(max_tokens=5)))
    while eng.has_work:
        eng.step()
    assert eng.prefix_deferrals == 4
    assert eng.prefix_cache.hits >= 4      # the deferred lanes all hit
    assert eng.prefix_cache.misses <= 1    # only the publishing lane missed
    for i, p in enumerate(prompts):
        res = eng.poll(f"c{i}")
        assert res is not None and res.finish_reason == "length"
        assert res.token_ids == _naive_greedy(params, p, 5)


def test_cold_burst_defers_per_distinct_prefix(params):
    """Two prefix groups plus an unrelated prompt in one cold burst: one
    publisher per group, one deferral per duplicate, nothing deferred
    twice, and nothing deferred for the unrelated prompt."""
    eng = _engine(params, max_slots=8, max_prefills_per_step=8,
                  num_blocks=128)
    rng = np.random.default_rng(8)
    pre_a = list(rng.integers(3, 300, size=24))
    pre_b = list(rng.integers(3, 300, size=24))
    prompts = [
        pre_a + [11, 12, 13],
        pre_a + [14, 15],
        pre_b + [16, 17, 18],
        pre_b + [19, 20],
        list(rng.integers(3, 300, size=20)),  # unrelated
    ]
    for i, p in enumerate(prompts):
        eng.submit(GenerationRequest(f"g{i}", list(p),
                                     SamplingParams(max_tokens=4)))
    while eng.has_work:
        eng.step()
    assert eng.prefix_deferrals == 2       # one per duplicate, once each
    for i, p in enumerate(prompts):
        res = eng.poll(f"g{i}")
        assert res is not None
        assert res.token_ids == _naive_greedy(params, p, 4)


def test_tiny_shared_prefix_not_worth_deferring(params):
    """Deferral is gated on the published prefix covering >= half the
    candidate's remaining prefill work — a 1-block prefix on a 28-token
    prompt admits immediately instead of waiting a round."""
    eng = _engine(params, max_slots=8, max_prefills_per_step=8)
    rng = np.random.default_rng(9)
    prefix = list(rng.integers(3, 300, size=8))    # 1 block of 28 tokens
    prompts = [prefix + list(rng.integers(3, 300, size=20))
               for _ in range(3)]
    for i, p in enumerate(prompts):
        eng.submit(GenerationRequest(f"t{i}", list(p),
                                     SamplingParams(max_tokens=3)))
    while eng.has_work:
        eng.step()
    assert eng.prefix_deferrals == 0
    for i, p in enumerate(prompts):
        res = eng.poll(f"t{i}")
        assert res is not None
        assert res.token_ids == _naive_greedy(params, p, 3)


def test_long_cold_burst_waits_for_streaming_publisher(params):
    """Two long same-prefix prompts submitted together with a COLD cache:
    the first streams its chunks; the second (chunk-path) waits until the
    publisher's final chunk registers the pages, then admits suffix-only
    as a hit — the shared prefix is ingested once."""
    eng = _engine(params, max_slots=4, num_blocks=128, max_blocks_per_seq=16,
                  prefill_buckets=(16,), max_prefills_per_step=4)
    rng = np.random.default_rng(11)
    prefix = list(rng.integers(3, 300, size=48))   # 6 blocks, 3 chunk rounds
    p1 = prefix + list(rng.integers(3, 300, size=20))  # suffix 20 > bucket 16
    p2 = prefix + list(rng.integers(3, 300, size=21))
    eng.submit(GenerationRequest("l1", list(p1), SamplingParams(max_tokens=4)))
    eng.submit(GenerationRequest("l2", list(p2), SamplingParams(max_tokens=4)))
    while eng.has_work:
        eng.step()
    assert eng.prefix_deferrals == 1
    assert eng.prefix_cache.hits >= 1
    r1, r2 = eng.poll("l1"), eng.poll("l2")
    assert r1.token_ids == _naive_greedy(params, p1, 4)
    assert r2.token_ids == _naive_greedy(params, p2, 4)


def test_publisher_cancel_releases_waiting_candidate(params):
    """A chunk-path candidate waiting on a streaming publisher admits
    normally once the publisher is cancelled mid-stream — the wait rule
    must not strand the queue."""
    eng = _engine(params, max_slots=4, num_blocks=128, max_blocks_per_seq=16,
                  prefill_buckets=(16,), max_prefills_per_step=4)
    rng = np.random.default_rng(12)
    prefix = list(rng.integers(3, 300, size=48))
    p1 = prefix + list(rng.integers(3, 300, size=20))
    p2 = prefix + list(rng.integers(3, 300, size=21))
    eng.submit(GenerationRequest("c1", list(p1), SamplingParams(max_tokens=4)))
    eng.submit(GenerationRequest("c2", list(p2), SamplingParams(max_tokens=4)))
    eng.step()                 # admits c1 (streaming), defers c2
    assert eng.prefix_deferrals == 1
    eng.cancel("c1")
    while eng.has_work:
        eng.step()
    r2 = eng.poll("c2")
    assert r2 is not None and r2.finish_reason == "length"
    assert r2.token_ids == _naive_greedy(params, p2, 4)


def test_defer_budget_bounds_round_scan(params):
    """A cold same-prefix queue deeper than the per-round deferral budget
    (4 x max_prefills_per_step) stops the admission scan at the budget —
    the overflow stays pending, hits the cache next round, and the prefix
    is still prefilled exactly once."""
    eng = _engine(params, max_slots=16, num_blocks=256,
                  max_prefills_per_step=2)   # defer budget = 8
    rng = np.random.default_rng(13)
    prefix = list(rng.integers(3, 300, size=24))
    prompts = [prefix + list(rng.integers(3, 300, size=4))
               for _ in range(12)]
    for i, p in enumerate(prompts):
        eng.submit(GenerationRequest(f"d{i}", list(p),
                                     SamplingParams(max_tokens=3)))
    while eng.has_work:
        eng.step()
    assert eng.prefix_cache.misses == 1     # one publisher, ever
    assert eng.prefix_deferrals == 8        # capped at the round budget
    for i, p in enumerate(prompts):
        res = eng.poll(f"d{i}")
        assert res is not None
        assert res.token_ids == _naive_greedy(params, p, 3)


def test_concurrent_cold_admission_publishes_once(params):
    """Two same-prefix requests racing through the thread-safe service
    submit path onto a COLD cache (the fleet router's affinity shape):
    whatever round each lands in, the prefix is published exactly once,
    outputs stay greedy-exact, and every page comes back."""
    from k8s_llm_monitor_tpu.serving.service import EngineService

    eng = _engine(params, max_slots=4, max_prefills_per_step=4)
    svc = EngineService(eng)
    rng = np.random.default_rng(17)
    prefix = list(rng.integers(3, 300, size=24))   # 3 full blocks at bs=8
    prompts = [prefix + list(rng.integers(3, 300, size=4)) for _ in range(2)]
    handles = [None, None]
    barrier = threading.Barrier(2)

    def submit(i):
        barrier.wait()
        handles[i] = svc.submit(list(prompts[i]),
                                SamplingParams(max_tokens=5),
                                request_id=f"race{i}")

    threads = [threading.Thread(target=submit, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = [h.result(timeout=60) for h in handles]
    svc.stop(timeout=10.0)
    for p, r in zip(prompts, results):
        assert r.finish_reason == "length"
        assert r.token_ids == _naive_greedy(params, p, 5)
    assert eng.prefix_cache.misses <= 1            # no double-publish
    assert eng.prefix_cache.hits >= 1              # the loser reused it
    eng.prefix_cache.clear()
    assert eng.allocator.free_blocks == 63         # nothing leaked
