"""End-to-end real-artifact seam: sharded safetensors ON DISK -> streamed
load (bf16 / int8) -> HF tokenizer dir -> text -> InferenceEngine -> text.

Every other checkpoint test converts an in-memory state dict
(tests/test_quantize.py) or compares logits (tests/test_model_parity.py);
this one exercises the exact production path a user of BASELINE.md config
#2 hits: ``utils/checkpoint.load_hf_checkpoint`` over a *sharded*
``model.safetensors.index.json`` directory written by
``transformers.save_pretrained``, plus ``utils/tokenizer.HFTokenizer`` over
a saved tokenizer directory, driven through ``InferenceEngine`` text APIs,
with greedy token-identity against ``transformers.generate``.

(The reference has no counterpart: its LLM layer is config keys only,
reference internal/config/config.go:141-145.)
"""

import json

import numpy as np
import pytest

from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.utils.checkpoint import load_hf_checkpoint
from k8s_llm_monitor_tpu.utils.tokenizer import HFTokenizer

WORDS = (
    "pod service node event warning error restart backoff oom killed "
    "pending running failed ready probe liveness readiness image pull "
    "dns resolve network policy deny allow traffic latency high low "
    "battery uav drone scheduler assign score memory cpu disk pressure "
    "the a is was not can cannot reach because of on in to from and "
    "web db cache api frontend backend default kube system container "
    "crashloop evicted taint toleration affinity replica deployment"
).split()


@pytest.fixture(scope="module")
def artifact_dirs(tmp_path_factory):
    """Write a tiny Llama as SHARDED safetensors + a real tokenizer dir."""
    import torch
    import transformers
    from tokenizers import Tokenizer, models, pre_tokenizers

    root = tmp_path_factory.mktemp("artifact")
    model_dir, tok_dir = root / "model", root / "tokenizer"

    # -- tokenizer: word-level over a diagnosis-ish vocabulary ----------
    vocab = {"<unk>": 0, "<s>": 1, "</s>": 2}
    for w in WORDS:
        vocab.setdefault(w, len(vocab))
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, unk_token="<unk>", bos_token="<s>",
        eos_token="</s>")
    fast.save_pretrained(tok_dir)

    # -- model: tiny Llama, vocab covering the tokenizer ----------------
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=500000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
        bos_token_id=1,
        eos_token_id=2,
    )
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    torch.manual_seed(0)
    for p in model.parameters():
        with torch.no_grad():
            p.copy_(torch.randn_like(p) * 0.05)
    # ~360 KB of f32 params; 50 KB shards force the index-sharded layout.
    model.save_pretrained(model_dir, max_shard_size="50KB",
                          safe_serialization=True)
    assert (model_dir / "model.safetensors.index.json").exists(), (
        "artifact must exercise the sharded-index path")
    n_shards = len(set(json.loads(
        (model_dir / "model.safetensors.index.json").read_text()
    )["weight_map"].values()))
    assert n_shards > 1, "expected multiple safetensors shards"
    return model_dir, tok_dir, model


def _engine_cfg() -> EngineConfig:
    return EngineConfig(
        max_slots=4, num_blocks=32, block_size=16, max_blocks_per_seq=8,
        prefill_buckets=(16, 32), max_prefills_per_step=2,
        max_admission_rounds=2, decode_steps_per_iter=4,
        prefix_cache_entries=0)


PROMPT = ("the web pod is not ready because the image pull failed "
          "and the dns resolve")


def test_disk_to_text_greedy_matches_transformers(artifact_dirs):
    import torch

    model_dir, tok_dir, hf_model = artifact_dirs
    cfg, params = load_hf_checkpoint(model_dir, dtype="float32")
    tok = HFTokenizer(str(tok_dir))
    assert tok.bos_id == 1 and tok.eos_id == 2

    eng = InferenceEngine(cfg, params, _engine_cfg(), tokenizer=tok,
                          eos_id=tok.eos_id)
    eng.submit_text("q1", PROMPT, SamplingParams(max_tokens=24))
    while eng.has_work:
        eng.step()
    res = eng.poll("q1")
    assert res is not None and res.finish_reason in ("eos", "length")

    ids = tok.encode(PROMPT)
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.tensor([ids]), max_new_tokens=24, do_sample=False,
            eos_token_id=tok.eos_id, pad_token_id=0)
    hf_new = hf_out[0, len(ids):].tolist()
    if hf_new and hf_new[-1] == tok.eos_id:
        hf_new = hf_new[:-1]  # engine results exclude the trailing EOS
    assert res.token_ids == hf_new, (
        f"greedy divergence: engine {res.token_ids} vs hf {hf_new}")

    # The text seam decodes to real vocabulary words.
    text = tok.decode(res.token_ids)
    assert isinstance(text, str)
    for w in text.split():
        assert w in WORDS or w == "<unk>"


def test_disk_streamed_int8_serves_text(artifact_dirs):
    """The production 8B path: quantize=True streams each shard tensor
    through host-side int8; the engine serves text from the result."""
    model_dir, tok_dir, _ = artifact_dirs
    cfg, params = load_hf_checkpoint(model_dir, quantize=True)
    import jax.numpy as jnp

    # Spot-check the streamed quantization actually produced int8 kernels.
    q0 = params["layers"][0]["q"]
    assert q0["kernel_q"].dtype == jnp.int8 and "scale" in q0

    tok = HFTokenizer(str(tok_dir))
    eng = InferenceEngine(cfg, params, _engine_cfg(), tokenizer=tok,
                          eos_id=tok.eos_id)
    out = eng.generate_text(PROMPT, SamplingParams(max_tokens=16))
    assert isinstance(out, str)
    res_ids = [i for i in tok.encode(out, add_bos=False)]
    assert all(0 <= i < cfg.vocab_size for i in res_ids)


def test_hf_config_translation_roundtrip(artifact_dirs):
    """config.json written by save_pretrained translates to our geometry."""
    model_dir, _, hf_model = artifact_dirs
    cfg, _ = load_hf_checkpoint(model_dir)
    hf = hf_model.config
    assert cfg.vocab_size == hf.vocab_size
    assert cfg.hidden_size == hf.hidden_size
    assert cfg.num_layers == hf.num_hidden_layers
    assert cfg.num_heads == hf.num_attention_heads
    assert cfg.num_kv_heads == hf.num_key_value_heads
    assert cfg.rope_theta == hf.rope_theta
    assert not cfg.tie_embeddings
