"""Sampling op: greedy/temperature/top-k/top-p semantics."""

import numpy as np

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.ops.sampling import sample_tokens


def _sample(logits, temperature, top_k, top_p, seed=0):
    B = logits.shape[0]
    return np.asarray(sample_tokens(
        jax.random.PRNGKey(seed), jnp.asarray(logits, jnp.float32),
        temperature=jnp.full((B,), temperature, jnp.float32),
        top_k=jnp.full((B,), top_k, jnp.int32),
        top_p=jnp.full((B,), top_p, jnp.float32),
    ))


def test_greedy():
    logits = np.array([[0.1, 3.0, -1.0], [2.0, 0.0, 1.9]])
    out = _sample(logits, temperature=0.0, top_k=0, top_p=1.0)
    assert out.tolist() == [1, 0]


def test_top_k_restricts_support():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(1, 50)).astype(np.float32)
    top2 = set(np.argsort(logits[0])[-2:].tolist())
    seen = set()
    for seed in range(50):
        seen.add(int(_sample(logits, 1.0, 2, 1.0, seed=seed)[0]))
    assert seen <= top2
    assert len(seen) == 2  # both top-2 tokens reachable


def test_top_p_restricts_support():
    # one dominant token (p ~ .97) -> top_p=0.9 keeps only it
    logits = np.zeros((1, 10), np.float32)
    logits[0, 3] = 5.0
    for seed in range(30):
        assert int(_sample(logits, 1.0, 0, 0.9, seed=seed)[0]) == 3


def test_top_p_keeps_minimum_one_token():
    logits = np.zeros((1, 4), np.float32)  # uniform: every token has mass .25
    outs = {int(_sample(logits, 1.0, 0, 0.1, seed=s)[0]) for s in range(20)}
    # cum-before < 0.1 keeps exactly the single largest-sorted entry
    assert len(outs) == 1


def test_mixed_batch_greedy_and_sampled():
    logits = np.array([[0.0, 4.0, 0.0, 0.0]] * 2, np.float32)
    out = np.asarray(sample_tokens(
        jax.random.PRNGKey(0), jnp.asarray(logits),
        temperature=jnp.asarray([0.0, 1.0], jnp.float32),
        top_k=jnp.asarray([0, 0], jnp.int32),
        top_p=jnp.asarray([1.0, 1.0], jnp.float32),
    ))
    assert out[0] == 1  # greedy lane
    assert 0 <= out[1] < 4


def test_temperature_sharpens():
    # At temp 0.01 the top-1 margin (~0.08 for this rng draw) scales to ~8
    # nats, so honest sampling picks argmax with p > 0.999 — 20 seeds must
    # all agree.  (Temp 0.05 only scales the margin to ~1.7 nats, where a
    # correct sampler legitimately misses argmax ~20% of the time.)
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(1, 20)).astype(np.float32)
    best = int(np.argmax(logits[0]))
    cold = [int(_sample(logits, 0.01, 0, 1.0, seed=s)[0]) for s in range(20)]
    assert all(t == best for t in cold)
    warm = {int(_sample(logits, 2.0, 0, 1.0, seed=s)[0]) for s in range(20)}
    assert len(warm) > 1  # hot sampling actually spreads
