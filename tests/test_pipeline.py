"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch schedule
over a data x pipe mesh must reproduce the dense model exactly — forward
hiddens, loss, and gradients (GPipe is an exact-gradient schedule) — and
train end-to-end with AdamW.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.parallel.pipeline import (
    create_pp_mesh,
    make_pipeline_forward,
    make_pipeline_train_step,
    pipeline_loss,
    place_pipeline_params,
    stack_pipeline_params,
)

CFG = ModelConfig(name="t", vocab_size=128, hidden_size=32,
                  intermediate_size=64, num_layers=4, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _dense_loss(params, tokens):
    logits = llama.forward_full(params, CFG, tokens)
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


@pytest.mark.parametrize("dp,pp,n_micro", [(2, 4, 4), (1, 2, 8), (4, 2, 2)])
def test_pipeline_loss_matches_dense(params, cpu_mesh_devices, dp, pp, n_micro):
    mesh = create_pp_mesh(dp, pp, cpu_mesh_devices[: dp * pp])
    staged = place_pipeline_params(stack_pipeline_params(params, pp), mesh)
    rng = np.random.default_rng(0)
    B, S = 8, 12
    tokens = jnp.asarray(rng.integers(2, 128, size=(B, S)), jnp.int32)
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))

    pipe_fwd = make_pipeline_forward(mesh, CFG)
    got = pipeline_loss(CFG, pipe_fwd, staged, tokens, n_micro)
    want = _dense_loss(params, tokens)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_dense(params, cpu_mesh_devices):
    """GPipe is exact: grads of the pipelined loss equal the dense grads
    (compare the per-layer blocks after unstacking)."""
    pp, n_micro = 4, 4
    mesh = create_pp_mesh(2, pp, cpu_mesh_devices)
    staged = place_pipeline_params(stack_pipeline_params(params, pp), mesh)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(2, 128, size=(8, 10)), jnp.int32)
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))

    pipe_fwd = make_pipeline_forward(mesh, CFG)
    g_staged = jax.grad(
        lambda st, t: pipeline_loss(CFG, pipe_fwd, st, t, n_micro)
    )(staged, tokens)
    g_dense = jax.grad(_dense_loss)(params, tokens)

    # Layer blocks: unstack [pp, Lp, ...] back to the per-layer list.
    Lp = CFG.num_layers // pp
    for li in range(CFG.num_layers):
        s, j = li // Lp, li % Lp
        got = jax.tree.map(lambda x: np.asarray(x[s, j]), g_staged["layers"])
        want = jax.tree.map(np.asarray, g_dense["layers"][li])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4,
                                                    atol=2e-5),
            got, want)
    # Replicated leaves (embed / final_norm / lm_head).
    for key in ("embed", "final_norm", "lm_head"):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            g_staged[key], g_dense[key])


def test_pipeline_train_step_learns(params, cpu_mesh_devices):
    """A few AdamW steps on a fixed batch must reduce the loss (end-to-end
    through jit + shard_map + ppermute backward)."""
    import optax

    pp, n_micro = 2, 4
    mesh = create_pp_mesh(4, pp, cpu_mesh_devices)
    staged = place_pipeline_params(stack_pipeline_params(params, pp), mesh)
    opt = optax.adamw(3e-3)
    opt_state = opt.init(staged)
    step = make_pipeline_train_step(mesh, CFG, opt, n_micro)

    rng = np.random.default_rng(2)
    # Per-microbatch batch (16/4 = 4) must divide the data axis (4).
    tokens = jnp.asarray(rng.integers(2, 128, size=(16, 16)), jnp.int32)
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
    losses = []
    for _ in range(6):
        staged, opt_state, loss = step(staged, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9, losses


def test_stack_rejects_uneven_layers(params):
    with pytest.raises(ValueError):
        stack_pipeline_params(params, 3)
