"""Tracing subsystem (observability/): span ring bounds, deterministic
sampling, W3C traceparent round-trips, cross-replica trace merging over a
live router fleet with hedging and a forced mid-stream failover, the
flight recorder's dump-on-failure edges, per-class histogram bucket math,
and the exporter's exposition self-lint.

Unit tests run on scripted fake replicas and bare Tracer instances (no
engines).  Acceptance tests boot real in-process fleets and are marked
``slow`` — ``make chaos-trace`` runs the whole file under
``K8SLLM_LOCKCHECK=1``.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

import jax

from k8s_llm_monitor_tpu.fleet import (
    FleetRouter,
    HedgeConfig,
    LocalReplica,
    ReplicaRegistry,
)
from k8s_llm_monitor_tpu.fleet.frontend import build_router_server
from k8s_llm_monitor_tpu.fleet.replica import Replica
from k8s_llm_monitor_tpu.fleet.registry import ReplicaStats
from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.monitor.analysis import (
    AnalysisEngine,
    LocalEngineBackend,
)
from k8s_llm_monitor_tpu.monitor.config import Config, LLMConfig
from k8s_llm_monitor_tpu.monitor.exporter import lint_exposition
from k8s_llm_monitor_tpu.monitor.server import MonitorServer
from k8s_llm_monitor_tpu.observability.flight import (
    FlightRecorder,
    get_flight_recorder,
    set_flight_recorder,
)
from k8s_llm_monitor_tpu.observability.metrics import ClassHistogram
from k8s_llm_monitor_tpu.observability.tracing import (
    TraceContext,
    Tracer,
    format_traceparent,
    get_tracer,
    parse_traceparent,
    set_tracer,
)
from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationResult,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.service import EngineService, RequestHandle
from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)
# Same shapes as tests/test_service.py / test_resilience.py so the jit
# cache is shared across the modules.
ECFG = dict(max_slots=4, num_blocks=64, block_size=8, max_blocks_per_seq=16,
            prefill_buckets=(16,), max_prefills_per_step=4,
            decode_steps_per_iter=4, prefix_cache_entries=0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Every test gets its own fully-sampled tracer (and leaves the
    process singleton as it found it)."""
    import k8s_llm_monitor_tpu.observability.tracing as tr

    prev = tr._TRACER
    set_tracer(Tracer(sample=1.0, seed=1234))
    yield
    set_tracer(prev)


@pytest.fixture(autouse=True)
def _fault_isolation():
    get_injector().reset(seed=1234)
    yield
    get_injector().reset()


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


def _assert_no_orphans(spans):
    """Every non-root parent_id must be a span id present in the trace."""
    ids = {s["span_id"] for s in spans}
    orphans = [s for s in spans
               if s["parent_id"] and s["parent_id"] not in ids]
    assert not orphans, [(s["name"], s["parent_id"]) for s in orphans]


# ---------------------------------------------------------------------------
# traceparent / identity
# ---------------------------------------------------------------------------


def test_traceparent_round_trip():
    ctx = TraceContext("ab" * 16, "cd" * 8, True)
    parsed = parse_traceparent(format_traceparent(ctx))
    assert parsed == ctx
    unsampled = TraceContext("ab" * 16, "cd" * 8, False)
    assert format_traceparent(unsampled).endswith("-00")
    assert parse_traceparent(format_traceparent(unsampled)).sampled is False


def test_traceparent_rejects_malformed():
    good = f"00-{'ab' * 16}-{'cd' * 8}-01"
    assert parse_traceparent(good) is not None
    for bad in ("", "garbage", good[:-1], good + "0",
                f"ff-{'ab' * 16}-{'cd' * 8}-01",      # reserved version
                f"00-{'0' * 32}-{'cd' * 8}-01",       # zero trace id
                f"00-{'ab' * 16}-{'0' * 16}-01",      # zero span id
                f"00-{'AB' * 16}-{'cd' * 8}"):        # missing flags
        assert parse_traceparent(bad) is None, bad


def test_child_context_keeps_trace_and_links_parent():
    t = get_tracer()
    root = t.new_trace()
    child = Tracer.child(root)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    assert child.sampled == root.sampled


def test_bind_and_lookup_by_request_or_trace_id():
    t = get_tracer()
    ctx = t.new_trace()
    t.bind("req-1", ctx)
    assert t.lookup("req-1") == ctx.trace_id
    assert t.lookup(ctx.trace_id) == ctx.trace_id       # literal hex
    assert t.lookup(ctx.trace_id.upper()) == ctx.trace_id
    assert t.lookup("nonexistent") is None
    # Bounded FIFO: old bindings evict once past capacity.
    for i in range(t._rid_cap + 8):
        t.bind(f"spam-{i}", ctx)
    assert t.lookup("req-1") is None


# ---------------------------------------------------------------------------
# Ring + sampling
# ---------------------------------------------------------------------------


def test_span_ring_is_bounded():
    t = Tracer(ring_size=64, sample=1.0, seed=1)
    ctx = t.new_trace()
    for i in range(500):
        t.record(f"s{i}", 0.0, 1.0, ctx)
    assert t.recorded == 500
    spans = t.snapshot()
    assert len(spans) == 64                     # oldest overwritten
    names = {s["name"] for s in spans}
    assert "s499" in names and "s0" not in names


def test_sampling_is_deterministic_in_trace_id():
    a = Tracer(sample=0.5, seed=1)
    b = Tracer(sample=0.5, seed=999)            # different RNG, same rule
    ids = [a._new_trace_id() for _ in range(400)]
    decisions = [a.sampled(tid) for tid in ids]
    assert decisions == [b.sampled(tid) for tid in ids]
    rate = sum(decisions) / len(decisions)
    assert 0.35 < rate < 0.65                   # rough mass check
    # Seeded tracers replay identical id sequences (test determinism).
    s1 = Tracer(sample=1.0, seed=7)
    s2 = Tracer(sample=1.0, seed=7)
    assert [s1.new_trace() for _ in range(8)] == \
           [s2.new_trace() for _ in range(8)]


def test_sampling_off_records_nothing():
    t = Tracer(sample=0.0, seed=1)
    assert t.new_trace() is None
    with t.span("noop"):
        pass
    assert t.recorded == 0 and t.snapshot() == []


def test_unsampled_trace_counts_attempts_not_spans():
    t = Tracer(sample=0.5, seed=1)
    ctx = TraceContext("f" * 32, "1" * 16, False)
    t.record("x", 0.0, 1.0, ctx)
    assert t.recorded == 0 and t.unsampled == 1


def test_span_scope_sets_thread_local_and_marks_errors():
    t = get_tracer()
    with t.span("outer") as outer:
        assert t.current_traceparent().startswith("00-")
        with t.span("inner"):
            pass
    assert t.current() is None
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("x")
    spans = {s["name"]: s for s in t.snapshot()}
    assert spans["inner"]["parent_id"] == outer.span_id
    assert spans["boom"]["status"] == "error"


# ---------------------------------------------------------------------------
# Per-class histograms
# ---------------------------------------------------------------------------


def test_class_histogram_bucket_math_and_units():
    h = ClassHistogram((0.025, 0.1, 0.5))
    h.observe(0.01, "interactive", trace_id="t1")   # le=0.025
    h.observe(0.1, "interactive")                    # le=0.1 (boundary: <=)
    h.observe(0.3, "interactive")                    # le=0.5
    h.observe(9.0, "interactive", trace_id="t2")     # +Inf
    cum, total, count, ex = h.series("interactive")
    assert cum == [1, 2, 3, 4]                       # cumulative le series
    assert count == 4 and total == pytest.approx(9.41)
    assert ex[0][0] == "t1" and ex[3][0] == "t2"
    assert ex[0][1] == pytest.approx(0.01)
    # Classes are independent; unknown class reads as empty.
    h.observe(0.2, "batch")
    assert h.classes() == ["batch", "interactive"]
    assert h.series("standard")[2] == 0
    assert h.total_count() == 5
    q = h.quantile("interactive", 0.5)
    assert 0.025 <= q <= 0.5


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_artifact_format(tmp_path):
    rec = FlightRecorder(capacity=32, dirpath=str(tmp_path))
    for i in range(40):
        rec.note("tick", i=i)
    t = get_tracer()
    with t.span("something"):
        pass
    path = rec.dump("watchdog: decode stuck!", extra={"k": "v"})
    assert path and rec.dumps == 1 and rec.last_dump_path == path
    assert "watchdog" in path and "!" not in path    # reason sanitized
    art = json.loads(open(path).read())
    assert art["version"] == 2
    assert art["signals"] is None    # no telemetry source wired here
    assert art["reason"] == "watchdog: decode stuck!"
    assert art["extra"] == {"k": "v"}
    assert len(art["events"]) == 32                  # ring bounded
    assert art["events"][-1]["i"] == 39
    assert any(s["name"] == "something" for s in art["spans"])


def test_flight_recorder_dump_failure_is_swallowed(tmp_path):
    blocker = tmp_path / "blocked"
    blocker.write_text("a file, not a dir")
    rec = FlightRecorder(dirpath=str(blocker / "sub"))
    assert rec.dump("x") == ""
    assert rec.dump_errors == 1 and rec.dumps == 0


@pytest.mark.chaos
def test_flight_recorder_dumps_on_watchdog_fault(params, tmp_path):
    """A seeded stuck-decode fault trips the dispatch watchdog; the
    pipeline reset dumps a flight artifact carrying both the engine event
    ring and the span ring."""
    rec = FlightRecorder(dirpath=str(tmp_path))
    prev = get_flight_recorder()
    set_flight_recorder(rec)
    try:
        eng = InferenceEngine(CFG, params,
                              EngineConfig(dispatch_timeout_s=0.05, **ECFG),
                              eos_id=-1)
        get_injector().arm("decode_stuck", rate=1.0, times=1)
        results = eng.generate([[5, 6, 7], [8, 9]],
                               SamplingParams(max_tokens=8))
        assert eng.watchdog_trips == 1
        for res in results:
            assert res.finish_reason in ("length", "eos")
    finally:
        set_flight_recorder(prev)
    assert rec.dumps >= 1
    art = json.loads(open(rec.last_dump_path).read())
    assert art["reason"] == "pipeline_reset"
    assert "watchdog" in art["extra"]["cause"]
    assert any(s["name"].startswith("engine.") for s in art["spans"])


# ---------------------------------------------------------------------------
# Scripted fleet: trace threading through hedge and failover (no engines)
# ---------------------------------------------------------------------------


class _ScriptedReplica(Replica):
    """Token-level fake (next = last + 1): emits ``fail_after`` tokens
    then an error result, or stalls forever (hedge bait)."""

    supports_tokens = True

    def __init__(self, rid, fail_after=None, stall=False):
        self.replica_id = rid
        self.fail_after = fail_after
        self.stall = stall
        self.cancelled = []

    def readyz(self):
        return True

    def stats(self):
        return ReplicaStats(total_slots=4)

    def generate(self, prompt_ids, sampling=None, request_id=None,
                 deadline_s=0.0, slo_class="standard", tenant="public"):
        sampling = sampling or SamplingParams()
        h = RequestHandle(request_id or "r", eos_id=-1,
                          cancel_fn=lambda rid: self.cancelled.append(rid))
        if self.stall:
            return h
        start = prompt_ids[-1] if prompt_ids else 0
        toks = [(start + 1 + i) % 997 for i in range(sampling.max_tokens)]
        if self.fail_after is not None:
            emit = toks[: self.fail_after]
            for t in emit:
                h._push([t], None)
            h._push([], GenerationResult(
                request_id=h.request_id, token_ids=list(emit),
                finish_reason="error", ttft_s=0.0, latency_s=0.0,
                error="injected death"))
        else:
            for t in toks:
                h._push([t], None)
            h._push([], GenerationResult(
                request_id=h.request_id, token_ids=list(toks),
                finish_reason="length", ttft_s=0.0, latency_s=0.0))
        return h


def _registry(*reps):
    reg = ReplicaRegistry()
    for r in reps:
        reg.add(r)
    reg.refresh()
    return reg


def test_router_failover_stays_in_one_trace():
    a = _ScriptedReplica("a", fail_after=3)
    b = _ScriptedReplica("b")
    router = FleetRouter(_registry(a, b), policy="round_robin",
                         max_failovers=2)
    h = router.submit([5], SamplingParams(max_tokens=8))
    res = h.result(timeout=10)
    assert res.finish_reason == "length"
    assert _wait(lambda: router.counters()["completed"] == 1)
    t = get_tracer()
    tid = t.lookup(h.request_id)
    assert tid is not None
    spans = t.spans_for(tid)
    names = [s["name"] for s in spans]
    assert "router.dispatch" in names
    assert "router.failover" in names
    assert "router.request" in names
    assert all(s["trace_id"] == tid for s in spans)
    _assert_no_orphans(spans)
    fo = next(s for s in spans if s["name"] == "router.failover")
    assert fo["attrs"]["from"] == "a" and fo["attrs"]["to"] == "b"
    root = next(s for s in spans if s["name"] == "router.request")
    assert root["parent_id"] == ""
    assert root["attrs"]["attempts"] == 1


def test_router_hedge_joins_same_trace():
    a = _ScriptedReplica("a", stall=True)
    b = _ScriptedReplica("b")
    router = FleetRouter(_registry(a, b), policy="round_robin",
                         hedge=HedgeConfig(enabled=True, fixed_delay_s=0.02))
    h = router.submit([5], SamplingParams(max_tokens=4))
    res = h.result(timeout=10)
    assert res.finish_reason == "length"
    assert _wait(lambda: router.counters()["completed"] == 1)
    t = get_tracer()
    tid = t.lookup(h.request_id)
    spans = t.spans_for(tid)
    _assert_no_orphans(spans)
    hedge = next(s for s in spans if s["name"] == "router.hedge")
    assert hedge["attrs"]["winner"] == "b"
    assert hedge["trace_id"] == tid


def test_router_shed_records_terminal_span():
    router = FleetRouter(ReplicaRegistry())        # empty fleet
    from k8s_llm_monitor_tpu.resilience.errors import OverloadedError

    with pytest.raises(OverloadedError) as exc:
        router.submit([1], SamplingParams(max_tokens=2))
    rid = exc.value.request_id
    assert rid
    t = get_tracer()
    tid = t.lookup(rid)
    spans = t.spans_for(tid)
    _assert_no_orphans(spans)
    root = next(s for s in spans if s["name"] == "router.request")
    assert root["status"] == "error"
    assert root["attrs"]["outcome"] == "shed"


def test_router_joins_incoming_traceparent():
    """A caller-established context (the HTTP layer's ``traceparent``
    parse) becomes the parent of the router's request span."""
    a = _ScriptedReplica("a")
    router = FleetRouter(_registry(a), policy="round_robin")
    t = get_tracer()
    with t.span("http.server") as server_span:
        h = router.submit([5], SamplingParams(max_tokens=2))
    res = h.result(timeout=10)
    assert res.finish_reason == "length"
    assert _wait(lambda: router.counters()["completed"] == 1)
    spans = t.spans_for(server_span.trace_id)
    _assert_no_orphans(spans)
    root = next(s for s in spans if s["name"] == "router.request")
    assert root["parent_id"] == server_span.span_id


# ---------------------------------------------------------------------------
# Acceptance: live fleets
# ---------------------------------------------------------------------------


def _local_fleet(params, n=2):
    reps = []
    for i in range(n):
        eng = InferenceEngine(CFG, params, EngineConfig(**ECFG), eos_id=-1)
        reps.append(LocalReplica(f"r{i}", service=EngineService(eng)))
    reg = ReplicaRegistry()
    for r in reps:
        reg.add(r)
    reg.refresh()
    return reg, reps


@pytest.mark.chaos
@pytest.mark.slow  # boots 2 live engines; covered by make chaos-trace
def test_live_fleet_failover_yields_one_merged_trace(params):
    """The ISSUE acceptance gate: live router + 2 replicas with hedging
    enabled and a replica killed mid-decode — every request's spans form
    ONE trace with no orphan parents, covering >= 95% of the measured
    request wall time."""
    reg, reps = _local_fleet(params)
    router = FleetRouter(
        reg, policy="affinity", max_failovers=2,
        hedge=HedgeConfig(enabled=True, fixed_delay_s=0.02))
    rng = np.random.default_rng(33)
    n_req, n_tok = 8, 12
    prompts = [list(rng.integers(3, 300, size=4)) for _ in range(n_req)]
    import threading

    try:
        handles, walls, errors = [], [None] * n_req, []

        def _awaiter(i, h, t0):
            try:
                res = h.result(timeout=120)
                if res.finish_reason != "length":
                    errors.append((i, res.finish_reason, res.error))
                walls[i] = time.monotonic() - t0
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append((i, "exception", repr(exc)))

        waiters = []
        for i, p in enumerate(prompts):
            t0 = time.monotonic()
            h = router.submit(p, SamplingParams(max_tokens=n_tok))
            handles.append(h)
            th = threading.Thread(target=_awaiter, args=(i, h, t0),
                                  daemon=True)
            th.start()
            waiters.append(th)
        victim = reps[0]
        assert _wait(lambda: victim.service.engine.active_slots > 0,
                     timeout=60), "victim never received work"
        victim.kill()
        for th in waiters:
            th.join(timeout=120)
        assert not errors, errors
        assert all(w is not None for w in walls)
        assert _wait(lambda: router.counters()["completed"] == n_req,
                     timeout=60)
        assert router.counters()["failovers"] >= 1

        t = get_tracer()

        def _all_roots_landed():
            return all(
                any(s["name"] == "router.request"
                    for s in t.spans_for(t.lookup(h.request_id) or ""))
                for h in handles)

        assert _wait(_all_roots_landed, timeout=30)
        for h, wall in zip(handles, walls):
            tid = t.lookup(h.request_id)
            assert tid is not None, h.request_id
            spans = t.spans_for(tid)
            assert all(s["trace_id"] == tid for s in spans)
            _assert_no_orphans(spans)
            names = {s["name"] for s in spans}
            assert "router.request" in names
            assert "engine.request" in names        # replica layer joined
            lo = min(s["start_mono"] for s in spans)
            hi = max(s["start_mono"] + s["duration_s"] for s in spans)
            assert (hi - lo) >= 0.95 * wall, \
                (h.request_id, hi - lo, wall, sorted(names))
    finally:
        for r in reps:
            r.close()


@pytest.mark.slow  # boots a 2-engine HTTP fleet; covered by make chaos-trace
def test_http_traceparent_round_trip_and_merged_trace_endpoint(params):
    """W3C propagation over real HTTP: a caller-minted traceparent rides
    client -> router -> replica, and the router's /api/v1/trace/<id>
    returns the stitched timeline."""
    def boot_replica():
        tok = ByteTokenizer()
        engine = InferenceEngine(
            CFG, params,
            EngineConfig(max_slots=2, num_blocks=512, block_size=16,
                         max_blocks_per_seq=128,
                         prefill_buckets=(128, 512, 2048),
                         decode_steps_per_iter=4),
            tokenizer=tok)
        backend = LocalEngineBackend(engine, tok)
        analysis = AnalysisEngine(backend, llm_cfg=LLMConfig(max_tokens=16))
        srv = MonitorServer(config=Config(), analysis=analysis, port=0)
        srv.start()
        return srv, backend

    reps = [boot_replica() for _ in range(2)]
    cfg = Config()
    cfg.server.port = 0
    cfg.fleet.replicas = [f"http://127.0.0.1:{srv.port}" for srv, _ in reps]
    cfg.fleet.probe_interval_s = 0.5
    router_srv = build_router_server(cfg)
    router_srv.start()
    try:
        tid, sid = "ab" * 16, "cd" * 8
        req = urllib.request.Request(
            f"http://127.0.0.1:{router_srv.port}/api/v1/query",
            data=json.dumps({"question": "why"}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{tid}-{sid}-01"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert json.loads(r.read())["status"] == "success"

        with urllib.request.urlopen(
                f"http://127.0.0.1:{router_srv.port}/api/v1/trace/{tid}",
                timeout=30) as r:
            payload = json.loads(r.read())
        spans = payload["spans"]
        assert payload["trace_id"] == tid and spans
        assert all(s["trace_id"] == tid for s in spans)
        names = {s["name"] for s in spans}
        # Cross-layer stitch: the router's HTTP ingress, the routing span,
        # and the replica hop's HTTP ingress all joined the caller's trace
        # — the replica one can only be there via the traceparent header.
        assert "http.server" in names
        assert "router.query" in names
        rq = next(s for s in spans if s["name"] == "router.query")
        assert any(s["name"] == "http.server"
                   and s["parent_id"] == rq["span_id"] for s in spans), \
            "replica ingress did not join via the outbound traceparent"
        ids = {s["span_id"] for s in spans}
        orphans = [s for s in spans
                   if s["parent_id"] and s["parent_id"] not in ids
                   and s["parent_id"] != sid]        # caller's own span
        assert not orphans, [(s["name"], s["parent_id"]) for s in orphans]

        with urllib.request.urlopen(
                f"http://127.0.0.1:{router_srv.port}/api/v1/trace?limit=5",
                timeout=30) as r:
            recent = json.loads(r.read())
        assert any(row["trace_id"] == tid for row in recent["traces"])
    finally:
        router_srv.analysis.close()
        router_srv.stop()
        for srv, backend in reps:
            srv.stop()
            try:
                backend.service.stop(timeout=5.0)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


# ---------------------------------------------------------------------------
# Exposition self-lint (unit; the live render is linted at render time)
# ---------------------------------------------------------------------------

_GOOD = """\
# HELP k8s_llm_monitor_up is the server up
# TYPE k8s_llm_monitor_up gauge
k8s_llm_monitor_up 1
# HELP k8s_llm_monitor_ttft_seconds ttft
# TYPE k8s_llm_monitor_ttft_seconds histogram
k8s_llm_monitor_ttft_seconds_bucket{class="interactive",le="0.1"} 3
k8s_llm_monitor_ttft_seconds_bucket{class="interactive",le="+Inf"} 4
k8s_llm_monitor_ttft_seconds_sum{class="interactive"} 0.5
k8s_llm_monitor_ttft_seconds_count{class="interactive"} 4
# HELP k8s_llm_monitor_overhead_ms overhead
# TYPE k8s_llm_monitor_overhead_ms gauge
k8s_llm_monitor_overhead_ms NaN
"""


def _with_meta(sample, fam="k8s_llm_monitor_x"):
    return f"# HELP {fam} h\n# TYPE {fam} gauge\n{sample}\n"


def test_lint_accepts_clean_exposition():
    assert lint_exposition(_GOOD) == []


def test_lint_flags_duplicate_family():
    text = _GOOD + "# HELP k8s_llm_monitor_up again\n" \
                   "# TYPE k8s_llm_monitor_up gauge\n"
    errs = lint_exposition(text)
    assert any("duplicate" in e for e in errs)


def test_lint_flags_bad_names_values_and_markers():
    assert lint_exposition(_with_meta("9bad_name 1"))
    errs = lint_exposition(_with_meta("k8s_llm_monitor_x not_a_number"))
    assert any("value" in e for e in errs)
    # Non-canonical NaN/Inf spellings are inconsistent across parsers.
    errs = lint_exposition(_with_meta("k8s_llm_monitor_x nan"))
    assert any("marker" in e for e in errs)
    assert lint_exposition(_with_meta("k8s_llm_monitor_x NaN")) == []


def test_lint_flags_orphan_type_and_help():
    errs = lint_exposition("# TYPE k8s_llm_monitor_x gauge\n")
    assert any("HELP" in e for e in errs)
    errs = lint_exposition("# HELP k8s_llm_monitor_y some help\n")
    assert any("TYPE" in e for e in errs)


def test_lint_flags_bad_label_block():
    errs = lint_exposition(
        _with_meta('k8s_llm_monitor_x{class=interactive} 1'))
    assert any("label" in e for e in errs)
