"""Pallas paged-decode-attention kernel vs the XLA gather reference.

Runs the kernel in Pallas interpreter mode (tests run on the CPU backend);
on real TPU the same kernel is compiled by Mosaic and selected by
ops.attention.select_attn_impl.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.ops.attention import (
    paged_decode_attention,
    select_attn_impl,
)
from k8s_llm_monitor_tpu.ops.pallas_attention import (
    paged_decode_attention_pallas,
)


def _random_paged_case(rng, B, H, KVH, D, num_blocks, bs, max_blocks):
    """Build a random paged-cache decode case with ragged lengths."""
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    # Fused lane layout [num_blocks, bs, KVH*D] — models/llama.py:KVPages.
    k_pages = jnp.asarray(
        rng.standard_normal((num_blocks, bs, KVH * D)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((num_blocks, bs, KVH * D)), jnp.float32)

    lengths = rng.integers(1, max_blocks * bs, size=(B,)).astype(np.int32)
    table = np.zeros((B, max_blocks), np.int32)
    # Hand out distinct non-null blocks per sequence, zeros past the end
    # (mirrors serving/kv_cache.py).
    next_free = 1
    for b in range(B):
        used = -(-int(lengths[b]) // bs)
        for j in range(used):
            table[b, j] = next_free
            next_free += 1
    assert next_free <= num_blocks, "test sized the pool too small"
    return q, k_pages, v_pages, jnp.asarray(table), jnp.asarray(lengths)


@pytest.mark.parametrize("B,H,KVH,D,bs,max_blocks", [
    (4, 8, 8, 64, 16, 4),     # MHA
    (4, 8, 2, 64, 16, 4),     # GQA 4:1
    (2, 16, 4, 128, 8, 6),    # GQA, D=128
    (1, 4, 1, 32, 4, 3),      # MQA-ish, tiny
])
def test_kernel_matches_xla_reference(B, H, KVH, D, bs, max_blocks):
    rng = np.random.default_rng(B * 1000 + H + KVH + D)
    num_blocks = B * max_blocks + 2
    q, kp, vp, table, lens = _random_paged_case(
        rng, B, H, KVH, D, num_blocks, bs, max_blocks)

    want = paged_decode_attention(q, kp, vp, table, lens)
    got = paged_decode_attention_pallas(q, kp, vp, table, lens,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_kernel_inactive_lane_null_block():
    """Lanes with length 1 and an all-zero table (the engine's inactive-lane
    encoding) must not produce NaNs."""
    rng = np.random.default_rng(0)
    B, H, KVH, D, bs, max_blocks = 2, 8, 4, 64, 8, 4
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((10, bs, KVH * D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((10, bs, KVH * D)), jnp.float32)
    table = jnp.zeros((B, max_blocks), jnp.int32)
    lens = jnp.ones((B,), jnp.int32)

    want = paged_decode_attention(q, kp, vp, table, lens)
    got = paged_decode_attention_pallas(q, kp, vp, table, lens,
                                        interpret=True)
    assert not np.any(np.isnan(np.asarray(got)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_select_attn_impl():
    assert select_attn_impl("cpu") is paged_decode_attention
    # On TPU the Pallas kernel is selected (import guarded).
    impl = select_attn_impl("tpu")
    assert impl.__name__ in ("paged_decode_attention_pallas",
                             "paged_decode_attention")


def test_bf16_parity():
    rng = np.random.default_rng(7)
    B, H, KVH, D, bs, max_blocks = 3, 8, 2, 64, 16, 4
    num_blocks = B * max_blocks + 2
    q, kp, vp, table, lens = _random_paged_case(
        rng, B, H, KVH, D, num_blocks, bs, max_blocks)
    q = q.astype(jnp.bfloat16)
    kp = kp.astype(jnp.bfloat16)
    vp = vp.astype(jnp.bfloat16)

    want = paged_decode_attention(q, kp, vp, table, lens)
    got = paged_decode_attention_pallas(q, kp, vp, table, lens,
                                        interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# Multi-query verify kernel
# ---------------------------------------------------------------------------


def _random_verify_case(rng, B, S, H, KVH, D, bs, max_blocks):
    """Random verify case: per-lane cached prefix of ``start`` tokens plus
    an S-token chunk already scattered into the pages."""
    num_blocks = B * max_blocks + 2
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k_pages = jnp.asarray(
        rng.standard_normal((num_blocks, bs, KVH * D)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((num_blocks, bs, KVH * D)), jnp.float32)
    start = rng.integers(0, max_blocks * bs - S, size=(B,)).astype(np.int32)
    lengths = np.full((B,), S, np.int32)
    table = np.zeros((B, max_blocks), np.int32)
    next_free = 1
    for b in range(B):
        used = -(-int(start[b] + S) // bs)
        for j in range(used):
            table[b, j] = next_free
            next_free += 1
    assert next_free <= num_blocks
    return (q, k_pages, v_pages, jnp.asarray(table),
            jnp.asarray(start), jnp.asarray(lengths))


@pytest.mark.parametrize("B,S,H,KVH,D,bs,max_blocks", [
    (4, 5, 8, 8, 64, 16, 4),    # MHA, spec_k=4 shape
    (4, 5, 8, 2, 64, 16, 4),    # GQA 4:1
    (2, 4, 16, 4, 128, 8, 6),   # GQA, D=128
    (1, 2, 4, 1, 32, 4, 3),     # MQA-ish, tiny
    (4, 1, 8, 2, 64, 16, 4),    # S=1 degenerates to decode semantics
])
def test_verify_kernel_matches_xla_reference(B, S, H, KVH, D, bs, max_blocks):
    from k8s_llm_monitor_tpu.ops.attention import paged_verify_attention
    from k8s_llm_monitor_tpu.ops.pallas_attention import (
        paged_verify_attention_pallas,
    )

    rng = np.random.default_rng(B * 7919 + S * 131 + H + KVH + D)
    q, kp, vp, table, start, lens = _random_verify_case(
        rng, B, S, H, KVH, D, bs, max_blocks)
    want = paged_verify_attention(q, kp, vp, table, start, lens)
    got = paged_verify_attention_pallas(q, kp, vp, table, start, lens,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_verify_kernel_inactive_and_start_zero():
    """Inactive lanes (length 0, null table) and start=0 lanes (first
    tokens of a fresh sequence) must be NaN-free and match the reference
    on active rows."""
    from k8s_llm_monitor_tpu.ops.attention import paged_verify_attention
    from k8s_llm_monitor_tpu.ops.pallas_attention import (
        paged_verify_attention_pallas,
    )

    rng = np.random.default_rng(3)
    B, S, H, KVH, D, bs, max_blocks = 3, 4, 8, 4, 64, 8, 4
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((16, bs, KVH * D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((16, bs, KVH * D)), jnp.float32)
    table = np.zeros((B, max_blocks), np.int32)
    table[0, :1] = [1]            # start=0 lane: chunk only
    table[2, :2] = [2, 3]         # start>0 lane
    start = jnp.asarray([0, 0, 9], jnp.int32)
    lens = jnp.asarray([S, 0, S], jnp.int32)   # lane 1 inactive
    want = paged_verify_attention(q, kp, vp, jnp.asarray(table), start, lens)
    got = paged_verify_attention_pallas(q, kp, vp, jnp.asarray(table),
                                        start, lens, interpret=True)
    assert not np.any(np.isnan(np.asarray(got)))
    for b in (0, 2):
        np.testing.assert_allclose(np.asarray(got)[b], np.asarray(want)[b],
                                   rtol=2e-5, atol=2e-5)


def test_verify_vs_sequential_decode_kernel():
    """S staggered decode-kernel calls must equal one verify call: query i
    with context start+i+1."""
    from k8s_llm_monitor_tpu.ops.pallas_attention import (
        paged_verify_attention_pallas,
    )

    rng = np.random.default_rng(11)
    B, S, H, KVH, D, bs, max_blocks = 2, 3, 8, 2, 64, 8, 6
    q, kp, vp, table, start, lens = _random_verify_case(
        rng, B, S, H, KVH, D, bs, max_blocks)
    ver = paged_verify_attention_pallas(q, kp, vp, table, start, lens,
                                        interpret=True)
    for i in range(S):
        dec = paged_decode_attention_pallas(
            q[:, i:i + 1], kp, vp, table, start + i + 1, interpret=True)
        np.testing.assert_allclose(np.asarray(ver[:, i:i + 1]),
                                   np.asarray(dec), rtol=2e-5, atol=2e-5)


def test_select_verify_impl_gate():
    from k8s_llm_monitor_tpu.ops.attention import (
        VERIFY_KERNEL_MIN_TABLE_TOKENS,
        paged_verify_attention,
        select_verify_impl,
    )

    # CPU always gets the gather reference.
    assert select_verify_impl("cpu") is paged_verify_attention
    # Short tables stay on the gather even on TPU.
    assert select_verify_impl(
        "tpu", max_table_tokens=VERIFY_KERNEL_MIN_TABLE_TOKENS - 1,
    ) is paged_verify_attention
    # Long tables select the kernel (import-guarded).
    impl = select_verify_impl(
        "tpu", max_table_tokens=VERIFY_KERNEL_MIN_TABLE_TOKENS)
    assert impl.__name__ in ("paged_verify_attention_pallas",
                             "paged_verify_attention")
