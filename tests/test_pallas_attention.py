"""Pallas paged-decode-attention kernel vs the XLA gather reference.

Runs the kernel in Pallas interpreter mode (tests run on the CPU backend);
on real TPU the same kernel is compiled by Mosaic and selected by
ops.attention.select_attn_impl.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.ops.attention import (
    paged_decode_attention,
    select_attn_impl,
)
from k8s_llm_monitor_tpu.ops.pallas_attention import (
    paged_decode_attention_pallas,
)


def _random_paged_case(rng, B, H, KVH, D, num_blocks, bs, max_blocks):
    """Build a random paged-cache decode case with ragged lengths."""
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    # Fused lane layout [num_blocks, bs, KVH*D] — models/llama.py:KVPages.
    k_pages = jnp.asarray(
        rng.standard_normal((num_blocks, bs, KVH * D)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((num_blocks, bs, KVH * D)), jnp.float32)

    lengths = rng.integers(1, max_blocks * bs, size=(B,)).astype(np.int32)
    table = np.zeros((B, max_blocks), np.int32)
    # Hand out distinct non-null blocks per sequence, zeros past the end
    # (mirrors serving/kv_cache.py).
    next_free = 1
    for b in range(B):
        used = -(-int(lengths[b]) // bs)
        for j in range(used):
            table[b, j] = next_free
            next_free += 1
    assert next_free <= num_blocks, "test sized the pool too small"
    return q, k_pages, v_pages, jnp.asarray(table), jnp.asarray(lengths)


@pytest.mark.parametrize("B,H,KVH,D,bs,max_blocks", [
    (4, 8, 8, 64, 16, 4),     # MHA
    (4, 8, 2, 64, 16, 4),     # GQA 4:1
    (2, 16, 4, 128, 8, 6),    # GQA, D=128
    (1, 4, 1, 32, 4, 3),      # MQA-ish, tiny
])
def test_kernel_matches_xla_reference(B, H, KVH, D, bs, max_blocks):
    rng = np.random.default_rng(B * 1000 + H + KVH + D)
    num_blocks = B * max_blocks + 2
    q, kp, vp, table, lens = _random_paged_case(
        rng, B, H, KVH, D, num_blocks, bs, max_blocks)

    want = paged_decode_attention(q, kp, vp, table, lens)
    got = paged_decode_attention_pallas(q, kp, vp, table, lens,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_kernel_inactive_lane_null_block():
    """Lanes with length 1 and an all-zero table (the engine's inactive-lane
    encoding) must not produce NaNs."""
    rng = np.random.default_rng(0)
    B, H, KVH, D, bs, max_blocks = 2, 8, 4, 64, 8, 4
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((10, bs, KVH * D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((10, bs, KVH * D)), jnp.float32)
    table = jnp.zeros((B, max_blocks), jnp.int32)
    lens = jnp.ones((B,), jnp.int32)

    want = paged_decode_attention(q, kp, vp, table, lens)
    got = paged_decode_attention_pallas(q, kp, vp, table, lens,
                                        interpret=True)
    assert not np.any(np.isnan(np.asarray(got)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_select_attn_impl():
    assert select_attn_impl("cpu") is paged_decode_attention
    # On TPU the Pallas kernel is selected (import guarded).
    impl = select_attn_impl("tpu")
    assert impl.__name__ in ("paged_decode_attention_pallas",
                             "paged_decode_attention")


def test_bf16_parity():
    rng = np.random.default_rng(7)
    B, H, KVH, D, bs, max_blocks = 3, 8, 2, 64, 16, 4
    num_blocks = B * max_blocks + 2
    q, kp, vp, table, lens = _random_paged_case(
        rng, B, H, KVH, D, num_blocks, bs, max_blocks)
    q = q.astype(jnp.bfloat16)
    kp = kp.astype(jnp.bfloat16)
    vp = vp.astype(jnp.bfloat16)

    want = paged_decode_attention(q, kp, vp, table, lens)
    got = paged_decode_attention_pallas(q, kp, vp, table, lens,
                                        interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2)
