"""Contract-drift suite (graftcheck --contracts).

Each checker runs against deliberately drifted fixture sources/docs to
prove both directions fire, against reconciled fixtures to prove it goes
quiet, and finally against the live repo — the assertion that every
route, metric family, bench key and env key the docs promise actually
exists (and vice versa), with zero suppressions.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from k8s_llm_monitor_tpu.devtools import contracts
from k8s_llm_monitor_tpu.devtools.contracts import (
    _norm_route, check_env, check_metrics, check_routes, derived_env_keys,
    extract_agent_routes, extract_bench_keys, extract_exporter_metrics,
    extract_server_routes, run_contracts)

REPO_ROOT = Path(__file__).resolve().parents[1]


def dedent(s: str) -> str:
    return textwrap.dedent(s)


# -- fixture sources ---------------------------------------------------------

SERVER_SRC = dedent("""
    class Handler:
        _ROUTES: dict = {
            ("GET", "/health"): "h_health",
            ("POST", "/api/v1/query"): "h_query",
            ("GET", "/api/v1/metrics/cluster"): "h_cluster",
        }

        def _dispatch(self, method, path):
            if path.startswith("/api/v1/metrics/nodes/"):
                return "h_node"
            if path.startswith("/api/v1/remediations/"):
                if method != "POST":
                    return "err405"
                return "h_remediation_action"
    """)

AGENT_SRC = dedent("""
    class AgentHandler:
        def do_GET(self):
            routes = {
                "/health": self.h_health,
                "/api/v1/state": self.h_state,
            }

        def do_POST(self):
            if self.path.startswith("/api/v1/command/"):
                command = self.path.rsplit("/", 1)[-1]
                if command == "arm":
                    pass
                elif command == "land":
                    pass
    """)

GOOD_ROUTE_DOCS = {
    "README.md": dedent("""
        - `GET /health`
        - `POST /api/v1/query`
        - `GET /api/v1/metrics/cluster`
        - `GET /api/v1/metrics/nodes/{name}`
        - `POST /api/v1/remediations/{id}/approve`
        - GET :9090/health
        - GET :9090/api/v1/state
        - POST :9090/api/v1/command/{arm,land}
        """),
}


# -- route normalization -----------------------------------------------------


def test_norm_route_wildcards_and_alternation():
    assert _norm_route("/api/v1/metrics/nodes/{name}") == \
        ["/api/v1/metrics/nodes/*"]
    assert _norm_route("/api/v1/command/{arm,land}") == \
        ["/api/v1/command/arm", "/api/v1/command/land"]
    assert _norm_route("/api/v1/trace/<id>?fmt=json") == ["/api/v1/trace/*"]


def test_extract_server_routes_reads_annassign_table_and_prefixes():
    routes = extract_server_routes(SERVER_SRC)
    assert ("POST", "/api/v1/query") in routes
    assert ("GET", "/api/v1/metrics/nodes/*") in routes  # _dispatch prefix
    # a prefix route's inline `method != "POST"` guard sets its method
    assert ("POST", "/api/v1/remediations/*") in routes
    assert ("GET", "/api/v1/remediations/*") not in routes


def test_extract_agent_routes_reads_get_dict_and_post_commands():
    routes = extract_agent_routes(AGENT_SRC)
    assert ("GET", "/api/v1/state") in routes
    assert ("POST", "/api/v1/command/arm") in routes
    assert ("POST", "/api/v1/command/*") in routes


# -- route-contract ----------------------------------------------------------


def test_routes_reconciled_fixture_is_clean():
    assert check_routes(SERVER_SRC, AGENT_SRC, GOOD_ROUTE_DOCS) == []


def test_routes_flags_documented_but_unregistered():
    docs = {"README.md":
            GOOD_ROUTE_DOCS["README.md"] + "- `POST /api/v1/export`\n"}
    findings = check_routes(SERVER_SRC, AGENT_SRC, docs)
    assert len(findings) == 1
    assert findings[0].rule == "route-contract"
    assert "POST /api/v1/export" in findings[0].message
    assert "not registered" in findings[0].message


def test_routes_flags_registered_but_undocumented():
    docs = {"README.md": GOOD_ROUTE_DOCS["README.md"].replace(
        "- `POST /api/v1/query`\n", "")}
    findings = check_routes(SERVER_SRC, AGENT_SRC, docs)
    assert len(findings) == 1
    assert "POST /api/v1/query" in findings[0].message
    assert "not documented" in findings[0].message


def test_routes_attributes_port_9090_to_agent():
    # the same path exists only on the agent; a bare doc mention without
    # the :9090 marker claims it on the monitor server and must fail
    docs = {"README.md": GOOD_ROUTE_DOCS["README.md"].replace(
        "GET :9090/api/v1/state", "`GET /api/v1/state`")}
    findings = check_routes(SERVER_SRC, AGENT_SRC, docs)
    assert any("'GET /api/v1/state' (monitor server)" in f.message
               for f in findings)


# -- metrics-contract --------------------------------------------------------

EXPORTER_SRC = dedent("""
    _PREFIX = "k8s_llm_monitor"

    def export(w, hist):
        w.metric("engine_queue_depth", "gauge", "depth", [(1.0, {})])
        w.histogram("request_ttft_seconds", "ttft", hist)
        w.lines.append(f"{_PREFIX}_engine_ttft_seconds_sum 1.0")
        hists = (
            ("decode_step_seconds", "per-step decode latency", hist),
        )
        for name, help_, h in hists:
            w.histogram(name, help_, h)
    """)

GOOD_OBS = dedent("""
    | metric | type | meaning |
    |---|---|---|
    | `k8s_llm_monitor_engine_queue_depth` | gauge | queue depth |
    | `k8s_llm_monitor_request_ttft_seconds` | histogram | ttft |
    | `k8s_llm_monitor_engine_ttft_seconds` | histogram | engine ttft |
    | `k8s_llm_monitor_decode_step_seconds` | histogram | decode step |
    """)

BENCH_SRC = dedent("""
    def main():
        doc = {"decode_tok_s": 1.0, "ttft_p50_ms": 2.0}
        doc["prefill_speedup_8k"] = 3.0
        for n in (2, 8, 32):
            doc[f"prefill_ttft_{n}k_ms"] = 4.0
        print(doc)
    """)


def check_m(obs=GOOD_OBS, extra_docs=None):
    docs = {"docs/observability.md": obs}
    docs.update(extra_docs or {})
    return check_metrics(EXPORTER_SRC, obs, BENCH_SRC, docs)


def test_exporter_extraction_covers_all_emission_styles():
    fams = set(extract_exporter_metrics(EXPORTER_SRC))
    # literal metric(), literal histogram(), manual f-string sample
    # (collapsed to the family), and the tuple-table rows
    assert fams == {"engine_queue_depth", "request_ttft_seconds",
                    "engine_ttft_seconds", "decode_step_seconds"}


def test_metrics_reconciled_fixture_is_clean():
    assert check_m() == []


def test_metrics_flags_emitted_but_not_inventoried():
    obs = GOOD_OBS.replace(
        "| `k8s_llm_monitor_decode_step_seconds` | histogram | decode step |\n",
        "")
    findings = check_m(obs=obs)
    assert len(findings) == 1
    assert "decode_step_seconds" in findings[0].message
    assert "does not list it" in findings[0].message


def test_metrics_flags_inventoried_but_never_emitted():
    obs = GOOD_OBS + \
        "| `k8s_llm_monitor_phantom_total` | counter | ghost |\n"
    findings = check_m(obs=obs)
    assert len(findings) >= 1
    assert any("phantom_total" in f.message
               and "never emits" in f.message for f in findings)


def test_metrics_flags_stale_doc_mention():
    # the real drift this rule caught: a doc citing a pre-rename family
    findings = check_m(extra_docs={"docs/usage.md": dedent("""
        Watch `k8s_llm_monitor_ttft_seconds_bucket` for tail latency.
        """)})
    assert len(findings) == 1
    assert findings[0].path == "docs/usage.md"
    assert "never emits" in findings[0].message


def test_bench_key_extraction_and_claims():
    exact, prefixes = extract_bench_keys(BENCH_SRC)
    assert "prefill_speedup_8k" in exact
    assert "prefill_ttft_" in prefixes  # f-string key -> prefix wildcard
    # a cited key bench.py never emits
    findings = check_m(extra_docs={
        "README.md": "reports `decode_tok_s_avg` per run\n"})
    assert len(findings) == 1
    assert "decode_tok_s_avg" in findings[0].message
    # valid exact + wildcard + f-string-prefix claims stay quiet
    assert check_m(extra_docs={"README.md": dedent("""
        reports `decode_tok_s`, the `prefill_ttft_*` ladder and
        `prefill_speedup_8k`
        """)}) == []


# -- env-contract ------------------------------------------------------------

CONFIG_SRC = dedent("""
    ENV_KEYS = {
        "K8SLLM_KV_DTYPE": "EngineConfig.kv_dtype",
        "K8SLLM_FAULTS": "runtime:resilience/faults.py",
    }

    class FleetConfig:
        role: str = "combined"

    class Config:
        fleet: FleetConfig = None
    """)

PY_SOURCES = {
    "k8s_llm_monitor_tpu/serving/engine.py": dedent("""
        import os

        class EngineConfig:
            kv_dtype: str = "bf16"

        def load():
            return os.environ.get("K8SLLM_KV_DTYPE", "bf16")
        """),
    "k8s_llm_monitor_tpu/resilience/faults.py": dedent("""
        import os

        spec = os.getenv("K8SLLM_FAULTS", "")
        """),
}

ENV_DOCS = {"README.md":
            "`K8SLLM_KV_DTYPE` picks the dtype; `K8SLLM_FAULTS` arms "
            "the injector.\n"}


def test_env_reconciled_fixture_is_clean():
    assert check_env(PY_SOURCES, CONFIG_SRC, ENV_DOCS) == []


def test_env_flags_unregistered_read():
    srcs = dict(PY_SOURCES)
    srcs["k8s_llm_monitor_tpu/x.py"] = \
        'import os\nv = os.environ.get("K8SLLM_ROGUE")\n'
    findings = check_env(srcs, CONFIG_SRC, ENV_DOCS)
    assert len(findings) == 1
    assert "K8SLLM_ROGUE" in findings[0].message
    assert findings[0].path == "k8s_llm_monitor_tpu/x.py"


def test_env_flags_dead_and_mismapped_registry_entries():
    cfg = CONFIG_SRC.replace(
        '"K8SLLM_KV_DTYPE": "EngineConfig.kv_dtype",',
        '"K8SLLM_KV_DTYPE": "EngineConfig.kv_dtype",\n'
        '    "K8SLLM_UNUSED": "EngineConfig.nonexistent",')
    docs = {"README.md": ENV_DOCS["README.md"] + "`K8SLLM_UNUSED`\n"}
    msgs = [f.message for f in check_env(PY_SOURCES, cfg, docs)]
    assert any("not a dataclass field" in m for m in msgs)
    assert any("no module reads it" in m for m in msgs)


def test_env_flags_runtime_owner_that_never_reads():
    srcs = {k: v for k, v in PY_SOURCES.items()
            if not k.endswith("faults.py")}
    srcs["k8s_llm_monitor_tpu/resilience/faults.py"] = "spec = ''\n"
    msgs = [f.message for f in check_env(srcs, CONFIG_SRC, ENV_DOCS)]
    assert any("never reads it" in m for m in msgs)


def test_env_flags_undocumented_and_ghost_doc_keys():
    msgs = [f.message for f in check_env(
        PY_SOURCES, CONFIG_SRC,
        {"README.md": "`K8SLLM_KV_DTYPE` and the ghost `K8SLLM_GHOST`\n"})]
    assert any("'K8SLLM_FAULTS' is undocumented" in m for m in msgs)
    assert any("'K8SLLM_GHOST'" in m and "neither in ENV_KEYS" in m
               for m in msgs)


def test_env_derived_keys_walk_the_config_tree():
    assert "FLEET_ROLE" in derived_env_keys(CONFIG_SRC)


# -- run_contracts end-to-end on a mini repo --------------------------------


def mini_repo(tmp_path: Path, readme_extra: str = "") -> Path:
    pkg = tmp_path / "k8s_llm_monitor_tpu" / "monitor"
    pkg.mkdir(parents=True)
    (pkg / "server.py").write_text(SERVER_SRC, encoding="utf-8")
    (pkg / "agent.py").write_text(AGENT_SRC, encoding="utf-8")
    (pkg / "exporter.py").write_text(EXPORTER_SRC, encoding="utf-8")
    (pkg / "config.py").write_text(CONFIG_SRC, encoding="utf-8")
    serving = tmp_path / "k8s_llm_monitor_tpu" / "serving"
    serving.mkdir()
    (serving / "engine.py").write_text(
        PY_SOURCES["k8s_llm_monitor_tpu/serving/engine.py"],
        encoding="utf-8")
    res = tmp_path / "k8s_llm_monitor_tpu" / "resilience"
    res.mkdir()
    (res / "faults.py").write_text(
        PY_SOURCES["k8s_llm_monitor_tpu/resilience/faults.py"],
        encoding="utf-8")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(
        GOOD_OBS, encoding="utf-8")
    (tmp_path / "README.md").write_text(
        GOOD_ROUTE_DOCS["README.md"] + ENV_DOCS["README.md"]
        + readme_extra, encoding="utf-8")
    (tmp_path / "bench.py").write_text(BENCH_SRC, encoding="utf-8")
    return tmp_path


def test_run_contracts_clean_mini_repo(tmp_path):
    assert run_contracts(mini_repo(tmp_path)) == []


def test_run_contracts_reports_drift_across_all_rules(tmp_path):
    root = mini_repo(
        tmp_path,
        "- `POST /api/v1/export`\n"
        "Watch `k8s_llm_monitor_phantom_total`.\n"
        "Set `K8SLLM_GHOST=1` to enable.\n")
    rules = {f.rule for f in run_contracts(root)}
    assert rules == {"route-contract", "metrics-contract", "env-contract"}


def test_run_contracts_honors_suppression_on_anchor_line(tmp_path):
    line = ("- `POST /api/v1/export` "
            "<!-- # graftcheck: disable=route-contract -->\n")
    assert run_contracts(mini_repo(tmp_path, line)) == []


# -- the live repo -----------------------------------------------------------


def test_live_repo_contracts_are_clean():
    findings = run_contracts(REPO_ROOT)
    assert findings == [], contracts.render(findings)


def test_live_repo_has_zero_contract_suppressions():
    # the acceptance bar: drift is reconciled, never suppressed
    hits = []
    for p in [REPO_ROOT / "README.md", REPO_ROOT / "Makefile",
              *sorted((REPO_ROOT / "docs").glob("*.md")),
              *sorted((REPO_ROOT / "k8s_llm_monitor_tpu").rglob("*.py"))]:
        if not p.is_file() or "__pycache__" in p.parts:
            continue
        text = p.read_text(encoding="utf-8")
        for rule in (*contracts.CONTRACT_RULE_NAMES,
                     "blocking-in-hot-path", "recompile-hazard",
                     "lock-order-static"):
            if f"disable={rule}" in text or f"disable-file={rule}" in text:
                hits.append((str(p), rule))
    # the devtools sources and this test mention the rule names, but no
    # real suppression comment may exist outside the fixtures
    assert not [h for h in hits
                if "devtools" not in h[0] and "tests" not in h[0]], hits
