"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before the first ``import jax`` anywhere in the test session, which
pytest guarantees by importing conftest first.  All sharding tests target this
virtual mesh; the driver separately validates the multi-chip path via
__graft_entry__.dryrun_multichip.
"""

import os

# Force, don't setdefault: the session environment pins JAX_PLATFORMS to the
# real TPU tunnel, and tests must never contend for that single chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's sitecustomize (a .pth hook on PYTHONPATH) registers the
# tunneled-TPU PJRT plugin at interpreter startup and calls
# jax.config.update("jax_platforms", "axon,cpu"), which OVERRIDES the env var
# above — a plain `pytest` would then run every test against the single real
# chip over the tunnel (slow enough to look like a hang, and test_sharding
# needs 8 devices).  Re-pin the config here, before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# slow/chaos markers are registered in pyproject.toml [tool.pytest.ini_options].


def pytest_sessionfinish(session, exitstatus):
    # Lock-discipline gate: when the suite ran with K8SLLM_LOCKCHECK=1
    # (e.g. `K8SLLM_LOCKCHECK=1 make chaos`), a dirty lockcheck registry
    # (cycles in the acquisition-order graph, unguarded writes to
    # guarded_by fields, release-by-non-owner) fails the whole session
    # even if every individual test passed.
    from k8s_llm_monitor_tpu.devtools import lockcheck

    if not lockcheck.enabled():
        return
    report = lockcheck.registry().report()
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    if tr is not None:
        tr.write_line(
            f"lockcheck: {len(report['locks'])} instrumented lock(s), "
            f"{len(report['order_edges'])} order edge(s), "
            f"{len(report['cycles'])} cycle(s), "
            f"{len(report['unguarded_writes'])} unguarded write(s), "
            f"{len(report['long_holds'])} long hold(s)")
    if not report["ok"]:
        import json

        print(json.dumps(report, indent=2, default=str))
        session.exitstatus = 1


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
