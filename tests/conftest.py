"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before the first ``import jax`` anywhere in the test session, which
pytest guarantees by importing conftest first.  All sharding tests target this
virtual mesh; the driver separately validates the multi-chip path via
__graft_entry__.dryrun_multichip.
"""

import os

# Force, don't setdefault: the session environment pins JAX_PLATFORMS to the
# real TPU tunnel, and tests must never contend for that single chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's sitecustomize (a .pth hook on PYTHONPATH) registers the
# tunneled-TPU PJRT plugin at interpreter startup and calls
# jax.config.update("jax_platforms", "axon,cpu"), which OVERRIDES the env var
# above — a plain `pytest` would then run every test against the single real
# chip over the tunnel (slow enough to look like a hang, and test_sharding
# needs 8 devices).  Re-pin the config here, before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    # No pytest.ini/pyproject config in this repo: register the markers the
    # suite selects on so `-m 'not slow'` (tier-1) and `-m chaos` run
    # without unknown-marker warnings.
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers", "chaos: fault-injection resilience tests "
                   "(tests/test_resilience.py; `make chaos`)")


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
