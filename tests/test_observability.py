"""Self-observability (/metrics Prometheus exporter, /debug/profile gate)
and SSE streaming of /api/v1/query.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

import jax

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.monitor.analysis import AnalysisEngine, LocalEngineBackend
from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster, seed_demo_cluster
from k8s_llm_monitor_tpu.monitor.config import Config, LLMConfig, MetricsConfig
from k8s_llm_monitor_tpu.monitor.manager import Manager
from k8s_llm_monitor_tpu.monitor.server import MonitorServer
from k8s_llm_monitor_tpu.serving.engine import EngineConfig, InferenceEngine
from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

CFG = ModelConfig(name="tiny", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=1e4)


@pytest.fixture(scope="module")
def engine_server():
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    tok = ByteTokenizer()
    engine = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=512, block_size=16,
                     max_blocks_per_seq=128, prefill_buckets=(128, 512, 2048),
                     decode_steps_per_iter=4),
        tokenizer=tok,
    )
    backend = LocalEngineBackend(engine, tok)
    fake = seed_demo_cluster(FakeCluster())
    client = Client(fake, namespaces=["default"])
    manager = Manager(client, MetricsConfig(namespaces=["default"]))
    manager.collect()
    analysis = AnalysisEngine(backend, client=client, manager=manager,
                              llm_cfg=LLMConfig(max_tokens=40))
    srv = MonitorServer(config=Config(), client=client, manager=manager,
                        analysis=analysis, port=0)
    srv.start()
    yield srv, engine
    srv.stop()
    backend.service.stop()


def _metrics_text(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        return r.read().decode()


def _parse(text):
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, value = line.rsplit(" ", 1)
        out[name] = float(value)
    return out


def test_prometheus_exporter_gauges(engine_server):
    srv, engine = engine_server
    text = _metrics_text(srv.port)
    vals = _parse(text)
    assert vals['k8s_llm_monitor_build_info{version="1.0.0"}'] == 1
    assert vals["k8s_llm_monitor_collections_total"] >= 1
    assert vals["k8s_llm_monitor_snapshot_nodes"] > 0
    assert vals["k8s_llm_monitor_engine_slots_total"] == 2
    assert vals["k8s_llm_monitor_engine_kv_blocks_total"] == 512
    assert (vals["k8s_llm_monitor_engine_free_kv_blocks"]
            <= vals["k8s_llm_monitor_engine_kv_blocks_total"])


def test_spec_accept_and_overhead_gauges(engine_server):
    """Per-class spec-acceptance gauge appears once a class has a
    measurement; the constrained-decode overhead gauge is ALWAYS present
    on a local-engine backend (0.0 until both decode classes observed);
    an off-mesh engine emits no mesh topology gauges."""
    srv, engine = engine_server
    text = _metrics_text(srv.port)
    assert "k8s_llm_monitor_constrained_decode_overhead_ms" in text
    assert "mesh_axes" not in text                 # single-device engine
    assert "spec_accept_ema" not in text           # no measurement yet
    engine._spec_accept.update("greedy", accepted=4, lane_rounds=4)
    text = _metrics_text(srv.port)
    assert 'k8s_llm_monitor_spec_accept_ema{class="greedy"} 1.0' in text


def test_overhead_gauge_emits_nan_marker_for_nonlocal_backend():
    """Satellite 6: backends that don't measure the constrained-decode tax
    (remote/openai/template) must still emit the gauge — as an explicit
    NaN — so the router's proxied /metrics never silently mixes a
    population that has the series with one that lacks it."""
    from k8s_llm_monitor_tpu.monitor.exporter import (
        _diagnosis_metrics,
        _Writer,
    )

    w = _Writer()
    _diagnosis_metrics(w, None, object())   # backend without the EMA attr
    assert ("k8s_llm_monitor_constrained_decode_overhead_ms NaN"
            in w.render())


def test_mesh_topology_gauges_on_tp_engine():
    """A mesh-native engine exports its axis sizes and the collective-share
    estimate, so dashboards can tell a TP-8 slice from a single chip."""
    from k8s_llm_monitor_tpu.monitor.exporter import _engine_metrics, _Writer
    from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh

    n_dev = len(jax.devices())
    mesh = create_mesh(MeshConfig(model=n_dev))
    # TP-shardable geometry (the module CFG's 300-row vocab doesn't divide
    # the vocab-parallel embedding 8 ways).
    tp_cfg = ModelConfig(name="tp-t", vocab_size=512, hidden_size=64,
                         intermediate_size=128, num_layers=2, num_heads=8,
                         num_kv_heads=8, dtype="float32", rope_theta=1e4)
    params = llama.init_params(jax.random.PRNGKey(1), tp_cfg)
    eng = InferenceEngine(
        tp_cfg, params,
        EngineConfig(max_slots=2, num_blocks=32, block_size=16,
                     max_blocks_per_seq=8, prefill_buckets=(64,)),
        mesh=mesh)
    w = _Writer()
    _engine_metrics(w, eng)
    text = w.render()
    assert f'k8s_llm_monitor_mesh_axes{{axis="model"}} {n_dev}' in text
    assert 'k8s_llm_monitor_mesh_axes{axis="data"} 1' in text
    assert "k8s_llm_monitor_engine_decode_collective_share 0.0" in text
    assert eng.mesh_axes()["model"] == n_dev


def test_ttft_histogram_counts_queries(engine_server):
    srv, engine = engine_server
    before = engine.ttft_count
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/api/v1/query",
        data=json.dumps({"question": "what is wrong?"}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as r:
        assert json.loads(r.read())["status"] == "success"
    vals = _parse(_metrics_text(srv.port))
    assert vals["k8s_llm_monitor_engine_ttft_seconds_count"] >= before + 1
    assert vals['k8s_llm_monitor_engine_ttft_seconds_bucket{le="+Inf"}'] == (
        vals["k8s_llm_monitor_engine_ttft_seconds_count"])


def test_sse_streaming_query(engine_server):
    """stream=true must deliver the answer as multiple SSE deltas that
    arrive incrementally (first chunk before generation completes), then a
    done event."""
    srv, engine = engine_server
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/api/v1/query",
        data=json.dumps({"question": "why crashloop?", "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    events = []
    arrivals = []
    with urllib.request.urlopen(req, timeout=300) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                events.append(json.loads(line[len("data: "):]))
                arrivals.append(time.monotonic())

    assert events[-1].get("done") is True
    deltas = [e["delta"] for e in events if "delta" in e]
    # 40 tokens at <=4 fused steps per wave -> several waves of deltas: the
    # client observably received chunks spread over time, not one blob.
    assert len(deltas) >= 3
    assert "".join(deltas)  # non-empty answer text
    assert arrivals[-1] - arrivals[0] > 0.0
    assert all(e["request_id"] == events[0]["request_id"] for e in events)


def test_profile_endpoint_gated_by_debug(engine_server):
    srv, _ = engine_server
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/debug/profile",
        data=json.dumps({"seconds": 0.1}).encode(),
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=30)
    assert exc.value.code == 403


def test_live_metrics_pass_self_lint(engine_server):
    """The real exporter output — both Prometheus text and OpenMetrics —
    must pass the in-tree exposition linter, and the render-time check
    must report zero errors for itself."""
    from k8s_llm_monitor_tpu.monitor.exporter import lint_exposition

    srv, _ = engine_server
    text = _metrics_text(srv.port)
    assert lint_exposition(text) == []
    assert "k8s_llm_monitor_exposition_lint_errors 0" in text

    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/metrics",
        headers={"Accept": "application/openmetrics-text"})
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.headers["Content-Type"].startswith(
            "application/openmetrics-text")
        om = r.read().decode()
    assert lint_exposition(om) == []
