"""cmd.preflight: capacity/mesh preflight math against known geometries.

All checks run via jax.eval_shape — no weights are materialized, so even
70B-class configs preflight in seconds on the CPU test mesh.  (The
reference's preflight surface is cluster-only, cmd/test-k8s/main.go; the
TPU plane is this system's addition.)
"""

from k8s_llm_monitor_tpu.cmd.preflight import main


def test_8b_w8a8_tp8_fits_v5e(capsys):
    rc = main(["--model", "llama3-8b", "--quantize", "w8a8",
               "--mesh", "1,1,8", "--per-chip-hbm-gib", "16",
               "--kv-blocks", "2200"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "w8a8 weights 7.49 GiB total" in out   # matches the measured chip
    assert "kv_heads=8 shard 8-way" in out
    assert "preflight: PASS" in out


def test_70b_bf16_single_chip_fails(capsys):
    rc = main(["--model", "llama3-70b", "--quantize", "none",
               "--mesh", "1,1,1", "--per-chip-hbm-gib", "16"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "does not fit" in out


def test_70b_int8_tp16_fits_v5p(capsys):
    rc = main(["--model", "llama3-70b", "--quantize", "int8",
               "--mesh", "1,1,16", "--per-chip-hbm-gib", "95"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "GiB/chip at TP-16" in out


def test_qwen2_72b_int8_tp8_fits_v5e(capsys):
    """The ISSUE's unlock gate: Qwen2-72B (80L, 64q/8kv heads, 152k vocab)
    must pass the fit preflight on an 8-chip v5e mesh spec with int8
    weights — 8.47 GiB/chip weights + head-sharded KV under the 16 GiB
    budget, every sharded axis dividing TP-8."""
    rc = main(["--model", "qwen2-72b", "--quantize", "int8",
               "--mesh", "1,1,8", "--per-chip-hbm-gib", "16",
               "--kv-blocks", "1024"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "heads=64/8kv" in out
    assert "q-heads/FFN/vocab all divide model=8" in out
    assert "kv_heads=8 shard 8-way" in out
    assert "8.47 GiB/chip at TP-8" in out
    assert "preflight: PASS" in out


def test_indivisible_tp_fails(capsys):
    rc = main(["--model", "llama3-8b", "--mesh", "1,1,3"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "not divisible by model=3" in out
    assert "KV pages replicate" in out            # warn, not fail


def test_moe_estimated_bytes(capsys):
    rc = main(["--model", "mixtral-8x7b", "--quantize", "int8",
               "--mesh", "1,1,4", "--per-chip-hbm-gib", "95"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "estimated" in out
    assert "experts=8" in out


def test_kv_capacity_too_small_fails(capsys):
    rc = main(["--model", "llama3-8b", "--quantize", "int8",
               "--mesh", "1,1,1", "--per-chip-hbm-gib", "16",
               "--kv-blocks", "8", "--prompt-len", "192",
               "--max-tokens", "256"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "raise --kv-blocks" in out


def test_cli_flags_beat_config(tmp_path, capsys):
    """--config fills only unset flags; an explicit flag wins over YAML."""
    cfg = tmp_path / "server.yaml"
    cfg.write_text(
        "llm:\n  tpu:\n    model: llama3-70b\n    quantize: int8\n"
        "    mesh_shape: \"1,1,1\"\n    kv_blocks: 64\n")
    rc = main(["--config", str(cfg), "--model", "llama3-8b",
               "--mesh", "1,1,8", "--per-chip-hbm-gib", "16",
               "--kv-blocks", "2200"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "heads=32/8kv" in out            # 8B geometry, not 70B's 64/8
    assert "2200 blocks" in out             # CLI kv-blocks, not YAML's 64
    assert "int8 weights" in out            # quantize came from the YAML


def test_zero_mesh_dim_fails_cleanly(capsys):
    rc = main(["--model", "llama3-8b", "--mesh", "1,1,0"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "bad --mesh" in out


def test_check_returns_structured_lists(capsys):
    """check() (the boot-path API) returns (rc, fail_msgs, warn_msgs) as
    structured lists — monitor/analysis.py consumes these, not scraped
    stdout."""
    rc, fails, warns = __import__(
        "k8s_llm_monitor_tpu.cmd.preflight", fromlist=["check"]).check(
        ["--model", "llama3-8b", "--quantize", "none",
         "--mesh", "1,1,1", "--per-chip-hbm-gib", "16"])
    capsys.readouterr()               # discard the printed human report
    assert rc == 1
    assert any("does not fit" in m for m in fails)
    assert all(isinstance(m, str) for m in fails + warns)
