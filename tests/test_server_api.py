"""HTTP API + Analysis Engine tests.

Exercises the 14 reference routes' envelopes (ref cmd/server/main.go:97-141)
against the fake cluster, the degraded dev mode, and the /api/v1/query
endpoint end-to-end through a tiny TPU-path model (the reference documents
the route, README.md:89-95, but never implemented it)."""

import json
import urllib.error
import urllib.request

import pytest

from k8s_llm_monitor_tpu.monitor.analysis import (
    AnalysisEngine,
    EvidenceCollector,
    LocalEngineBackend,
    TemplateBackend,
)
from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster, seed_demo_cluster
from k8s_llm_monitor_tpu.monitor.config import Config, MetricsConfig
from k8s_llm_monitor_tpu.monitor.manager import Manager
from k8s_llm_monitor_tpu.monitor.models import AnalysisRequest
from k8s_llm_monitor_tpu.monitor.server import MonitorServer


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, json.loads(r.read())


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


@pytest.fixture(scope="module")
def live_server():
    fake = seed_demo_cluster(FakeCluster())
    client = Client(fake, namespaces=["default", "kube-system"])
    manager = Manager(
        client, MetricsConfig(namespaces=["default"], enable_network=True)
    )
    manager.collect()
    analysis = AnalysisEngine(TemplateBackend(), client=client, manager=manager)
    srv = MonitorServer(
        config=Config(), client=client, manager=manager, analysis=analysis, port=0
    )
    srv.start()
    yield fake, srv
    srv.stop()


@pytest.fixture(scope="module")
def dev_server():
    srv = MonitorServer(config=Config(), port=0)  # no client/manager/analysis
    srv.start()
    yield srv
    srv.stop()


# -- live mode ---------------------------------------------------------------


def test_health(live_server):
    _, srv = live_server
    status, body = _get(srv.port, "/health")
    assert status == 200
    assert body["status"] == "healthy"
    assert body["version"] == "1.0.0"


def test_cluster_status(live_server):
    _, srv = live_server
    _, body = _get(srv.port, "/api/v1/cluster/status")
    assert body["status"] == "success"
    assert body["cluster_info"]["nodes"] == 3
    assert body["cluster_info"]["namespaces"] == ["default", "kube-system"]


def test_pods_route(live_server):
    _, srv = live_server
    _, body = _get(srv.port, "/api/v1/pods")
    assert body["status"] == "success"
    assert body["count"] == 3  # default(2) + kube-system(1)
    names = {p["name"] for p in body["pods"]}
    assert any(n.startswith("coredns") for n in names)


def test_metrics_routes(live_server):
    _, srv = live_server
    _, cluster = _get(srv.port, "/api/v1/metrics/cluster")
    assert cluster["data"]["total_nodes"] == 3
    assert cluster["data"]["health_status"] == "healthy"

    _, nodes = _get(srv.port, "/api/v1/metrics/nodes")
    assert nodes["count"] == 3
    assert "k3d-demo-agent-1" in nodes["data"]

    _, node = _get(srv.port, "/api/v1/metrics/nodes/k3d-demo-agent-1")
    assert node["data"]["node_name"] == "k3d-demo-agent-1"
    assert node["data"]["gpu_count"] == 8  # TPU chips via accelerator fields

    _, pods = _get(srv.port, "/api/v1/metrics/pods")
    assert pods["count"] == 2

    _, snap = _get(srv.port, "/api/v1/metrics/snapshot")
    assert set(snap["data"]) >= {
        "timestamp",
        "node_metrics",
        "pod_metrics",
        "network_metrics",
        "cluster_metrics",
    }

    _, net = _get(srv.port, "/api/v1/metrics/network")
    assert net["count"] >= 1
    assert net["data"][0]["connected"] is True


def test_metrics_node_not_found(live_server):
    _, srv = live_server
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(srv.port, "/api/v1/metrics/nodes/ghost")
    assert err.value.code == 404


def test_method_not_allowed(live_server):
    _, srv = live_server
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(srv.port, "/api/v1/pods", {})
    assert err.value.code == 405


def test_cors_header_on_metrics(live_server):
    _, srv = live_server
    with urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}/api/v1/metrics/cluster"
    ) as r:
        assert r.headers["Access-Control-Allow-Origin"] == "*"


def test_uav_report_roundtrip(live_server):
    fake, srv = live_server
    payload = {
        "node_name": "k3d-demo-agent-0",
        "node_ip": "172.18.0.3",
        "state": {
            "gps": {"latitude": 39.9, "longitude": 116.4},
            "battery": {"remaining_percent": 66.0},
            "flight": {"mode": "AUTO", "armed": True},
            "health": {"system_status": "OK"},
        },
        "heartbeat_interval_seconds": 10,
    }
    _, body = _post(srv.port, "/api/v1/uav/report", payload)
    assert body["status"] == "success"
    assert body["uav_id"] == "uav-k3d-demo-agent-0"  # defaulted
    assert body["crd_status"] == "updated"
    assert body["heartbeat_interval_seconds"] == 10

    _, uavs = _get(srv.port, "/api/v1/metrics/uav")
    assert uavs["count"] == 1
    assert uavs["data"]["k3d-demo-agent-0"]["source"] == "agent"

    _, single = _get(srv.port, "/api/v1/metrics/uav/k3d-demo-agent-0")
    assert single["data"]["state"]["battery"]["remaining_percent"] == 66.0

    _, crd = _get(srv.port, "/api/v1/crd/uav")
    assert crd["count"] == 1
    assert crd["data"][0]["name"] == "uavmetric-k3d-demo-agent-0"
    assert crd["data"][0]["spec"]["battery"]["remaining_percent"] == 66.0


def test_uav_report_missing_node(live_server):
    _, srv = live_server
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(srv.port, "/api/v1/uav/report", {"uav_id": "x"})
    assert err.value.code == 400


def test_pod_communication_route(live_server):
    _, srv = live_server
    _, body = _post(
        srv.port,
        "/api/v1/analyze/pod-communication",
        {
            "pod_a": "web-frontend-7d4b9c6f5-x2x1p",
            "pod_b": "api-backend-6f5d8b7c9-k3k2m",
        },
    )
    assert body["status"] == "success"
    assert body["analysis"]["status"] in ("connected", "disconnected")
    assert body["analysis"]["confidence"] > 0
    assert "Diagnosis" in body["llm_diagnosis"]


def test_query_route_with_template_backend(live_server):
    _, srv = live_server
    _, body = _post(srv.port, "/api/v1/query", {"question": "Is my cluster healthy?"})
    assert body["status"] == "success"
    assert "Diagnosis" in body["result"]["answer"]
    assert body["result"]["model"] == "template"
    assert "cluster" in body["result"]["evidence"]


def test_analyze_route_anomaly_and_root_cause(live_server):
    fake, srv = live_server
    fake.add_pod("crashy", phase="CrashLoopBackOff", labels={"app": "crashy"})
    fake.add_event(
        type_="Warning",
        reason="BackOff",
        message="Back-off restarting failed container",
        involved_object="crashy",
    )
    srv.manager.collect()
    _, body = _post(srv.port, "/api/v1/analyze", {"type": "anomaly_detection"})
    assert body["status"] == "success"
    assert body["result"]["anomaly_count"] >= 1
    assert any("crashy" in a for a in body["result"]["anomalies"])

    _, rc = _post(
        srv.port,
        "/api/v1/analyze",
        {
            "type": "root_cause",
            "parameters": {
                "namespace": "default",
                "pod": "crashy",
                "symptom": "pod keeps restarting",
            },
        },
    )
    assert rc["status"] == "success"
    assert rc["result"]["target"] == "pod default/crashy"
    assert rc["result"]["root_cause_analysis"]

    with pytest.raises(urllib.error.HTTPError) as err:
        _post(srv.port, "/api/v1/analyze", {"type": "nonsense"})
    assert err.value.code == 400


def test_static_web(live_server, tmp_path_factory):
    _, srv = live_server
    # the default web dir ships index.html; 404s must not leak paths
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(srv.port, "/../etc/passwd")
    assert err.value.code == 404


# -- dev mode ----------------------------------------------------------------


def test_dev_mode_degradation(dev_server):
    srv = dev_server
    _, status = _get(srv.port, "/api/v1/cluster/status")
    assert status["status"] == "warning"
    assert "development mode" in status["message"]

    _, pods = _get(srv.port, "/api/v1/pods")
    assert pods["status"] == "warning"
    assert pods["pods"] == []

    for path in (
        "/api/v1/metrics/cluster",
        "/api/v1/metrics/nodes",
        "/api/v1/metrics/snapshot",
    ):
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(srv.port, path)
        assert err.value.code == 503

    with pytest.raises(urllib.error.HTTPError) as err:
        _post(srv.port, "/api/v1/analyze/pod-communication", {"pod_a": "a", "pod_b": "b"})
    assert err.value.code == 503

    with pytest.raises(urllib.error.HTTPError) as err:
        _get(srv.port, "/api/v1/crd/uav")
    assert err.value.code == 503

    # uav report still accepted (cache skipped, CRD unavailable)
    _, body = _post(srv.port, "/api/v1/uav/report", {"node_name": "n1"})
    assert body["status"] == "success"
    assert body["crd_status"] == "unavailable"


# -- the TPU inference path end-to-end ---------------------------------------


def test_query_through_tiny_tpu_engine():
    """NL question → evidence prompt → continuous-batching engine with a
    tiny random-init model → generated answer. Zero external API calls."""
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import ModelConfig
    from k8s_llm_monitor_tpu.serving.engine import EngineConfig, InferenceEngine
    from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

    cfg = ModelConfig(
        name="tiny",
        vocab_size=300,
        hidden_size=32,
        intermediate_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        dtype="float32",
        rope_theta=1e4,
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tok = ByteTokenizer()
    engine = InferenceEngine(
        cfg,
        params,
        EngineConfig(
            max_slots=2,
            num_blocks=512,
            block_size=16,
            max_blocks_per_seq=128,
            prefill_buckets=(128, 512, 2048),
        ),
        tokenizer=tok,
    )
    backend = LocalEngineBackend(engine, tok)

    fake = seed_demo_cluster(FakeCluster())
    client = Client(fake, namespaces=["default"])
    manager = Manager(client, MetricsConfig(namespaces=["default"]))
    manager.collect()
    analysis = AnalysisEngine(backend, client=client, manager=manager)
    resp = analysis.query("why is my pod slow?")
    assert resp.status == "success"
    assert resp.result["model"] == "tpu-local"
    assert isinstance(resp.result["answer"], str)
    # random weights → gibberish, but the pipe must produce tokens
    assert len(resp.result["answer"]) > 0


def test_evidence_collector_bounds_events():
    fake = seed_demo_cluster(FakeCluster())
    for i in range(150):
        fake.add_event(type_="Warning", reason=f"W{i}", message="x")
    client = Client(fake, namespaces=["default"])
    from k8s_llm_monitor_tpu.monitor.config import AnalysisConfig

    coll = EvidenceCollector(client, None, AnalysisConfig(max_context_events=10))
    ev = coll.collect()
    assert len(ev["recent_warning_events"]) == 10
    prompt = coll.format_prompt(ev)
    assert "Recent warning events" in prompt


def test_concurrent_queries_share_evidence_prefix():
    """The production query path builds prompts as preamble + evidence +
    question, so concurrent diagnosis queries against the same snapshot
    reuse the evidence prefix through the engine's KV prefix cache —
    the mechanism behind the shared-prefix bench leg."""
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import ModelConfig
    from k8s_llm_monitor_tpu.serving.engine import EngineConfig, InferenceEngine
    from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

    cfg = ModelConfig(name="tiny", vocab_size=300, hidden_size=32,
                      intermediate_size=64, num_layers=2, num_heads=4,
                      num_kv_heads=2, dtype="float32", rope_theta=1e4)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tok = ByteTokenizer()
    engine = InferenceEngine(
        cfg, params,
        EngineConfig(max_slots=2, num_blocks=768, block_size=16,
                     max_blocks_per_seq=192,
                     prefill_buckets=(128, 512, 2048)),
        tokenizer=tok,
    )
    backend = LocalEngineBackend(engine, tok)
    fake = seed_demo_cluster(FakeCluster())
    client = Client(fake, namespaces=["default"])
    manager = Manager(client, MetricsConfig(namespaces=["default"]))
    manager.collect()
    analysis = AnalysisEngine(backend, client=client, manager=manager)

    for q in ("why is web-frontend slow?",
              "is the uav fleet healthy today?",
              "which node is under memory pressure?"):
        resp = analysis.query(q)
        assert resp.status == "success"
    pc = engine.prefix_cache
    assert pc is not None and pc.hits >= 2, (pc.hits, pc.misses)
