"""Latency-hiding TP decode (parallel/overlap.py) + tier-aware admission.

The overlap schedule replaces GSPMD's auto-inserted post-o/post-down psum
with a hand-staged reduce-scatter -> all-gather pair interleaved with the
next column-parallel matmuls.  Its whole value rests on EXACT parity: the
staged collectives must reproduce the GSPMD reference byte-for-byte
(greedy argmax over identical float math), or the flag is a silent
quality regression.  These tests are that gate, plus the admission /
spec-default satellites that ride the same PR.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig, PRESETS
from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh
from k8s_llm_monitor_tpu.parallel.overlap import overlap_supported
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)

# Overlap-compatible geometry: 8 heads / 8 KV heads / even hidden and
# intermediate splits under TP-8 (test_sharding.py's CFG, reused so the
# two parity suites gate the same model).
CFG = ModelConfig(name="t", vocab_size=512, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=8, num_kv_heads=8, dtype="float32",
                  rope_theta=10_000.0)

ECFG = EngineConfig(max_slots=4, num_blocks=128, block_size=8,
                    max_blocks_per_seq=32, prefill_buckets=(16,),
                    decode_steps_per_iter=4)


def _engine(params, tp_overlap, mesh, **kw):
    ecfg = dataclasses.replace(ECFG, tp_overlap=tp_overlap, **kw)
    return InferenceEngine(CFG, params, ecfg, eos_id=-1, mesh=mesh)


# -- support gates ------------------------------------------------------------


def test_overlap_supported_gates(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(model=8))
    assert overlap_supported(CFG, mesh) == ""
    assert "mesh" in overlap_supported(CFG, None)
    # tiny preset: 4 heads / 2 KV heads do not divide TP-8 -> pages
    # would replicate and the per-shard attention contract breaks.
    assert overlap_supported(PRESETS["tiny"], mesh) != ""
    moe = dataclasses.replace(CFG, num_experts=8, num_experts_per_tok=2)
    assert "expert" in overlap_supported(moe, mesh)
    odd = dataclasses.replace(CFG, intermediate_size=129)
    assert overlap_supported(odd, mesh) != ""


def test_auto_mode_falls_back_and_on_mode_raises(cpu_mesh_devices):
    """`auto` silently keeps GSPMD on unsupported geometry; `on` refuses
    to build rather than serve a schedule it cannot honour."""
    tiny = PRESETS["tiny"]
    params = llama.init_params(jax.random.PRNGKey(0), tiny)
    mesh = create_mesh(MeshConfig(model=8))
    ecfg = dataclasses.replace(ECFG, tp_overlap="auto")
    eng = InferenceEngine(tiny, params, ecfg, eos_id=-1, mesh=mesh)
    assert not eng.tp_overlap
    with pytest.raises(ValueError, match="tp_overlap"):
        InferenceEngine(tiny, params,
                        dataclasses.replace(ECFG, tp_overlap="on"),
                        eos_id=-1, mesh=mesh)


def test_env_flag_overrides_config(cpu_mesh_devices, monkeypatch):
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    mesh = create_mesh(MeshConfig(model=8))
    monkeypatch.setenv("K8SLLM_TP_OVERLAP", "off")
    eng = _engine(params, "on", mesh)   # env wins over the config field
    assert not eng.tp_overlap
    monkeypatch.setenv("K8SLLM_TP_OVERLAP", "bogus")
    with pytest.raises(ValueError, match="K8SLLM_TP_OVERLAP|tp_overlap"):
        _engine(params, "auto", mesh)


# -- parity: the tentpole gate ------------------------------------------------


@pytest.mark.slow  # three full engines; runs in CI via `make tier1-mesh`
def test_overlap_mixed_traffic_parity_incl_constrained(cpu_mesh_devices):
    """Byte-identical greedy streams: overlap vs GSPMD vs 1-device over
    one mixed wave — chunked long prompt, dense short prefills, uneven
    decode drain, and a grammar-constrained verdict lane in the batch."""
    from k8s_llm_monitor_tpu.diagnosis.grammar import verdict_fsm
    from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    params = llama.init_params(jax.random.PRNGKey(4), CFG)
    rng = np.random.default_rng(5)
    reqs = [
        ("long", [int(t) for t in rng.integers(2, 250, size=40)],
         SamplingParams(max_tokens=8)),                    # 40 > 16: chunked
        ("short-a", [int(t) for t in rng.integers(2, 250, size=7)],
         SamplingParams(max_tokens=8)),
        ("short-b", [int(t) for t in rng.integers(2, 250, size=5)],
         SamplingParams(max_tokens=12)),                   # uneven drain
        ("verdict", tok.encode("why is default/web crashlooping?"),
         SamplingParams(max_tokens=1, constrained=True)),  # grammar lane
    ]

    def run(mesh, tp_overlap):
        ecfg = dataclasses.replace(ECFG, tp_overlap=tp_overlap)
        eng = InferenceEngine(CFG, params, ecfg, tokenizer=tok, mesh=mesh)
        assert eng.tp_overlap == (tp_overlap == "on")
        eng.set_grammar(verdict_fsm(eos_id=tok.eos_id))
        for rid, prompt, sp in reqs:
            eng.submit(GenerationRequest(
                request_id=rid, prompt_ids=list(prompt), sampling=sp))
        while eng.has_work:
            eng.step()
        out = {}
        for rid, _, _ in reqs:
            res = eng.poll(rid)
            assert res is not None and res.finish_reason != "error", res
            out[rid] = res.token_ids
        return out

    mesh = create_mesh(MeshConfig(model=8))
    overlap = run(mesh, "on")
    gspmd = run(mesh, "off")
    plain = run(None, "auto")
    assert overlap == gspmd == plain
    assert len(overlap["verdict"]) > 0


@pytest.mark.slow  # four engines (two quant variants x on/off)
def test_overlap_quant_parity(cpu_mesh_devices):
    """Quantized pools keep exactness: int8 KV (per-page scales travel
    through the shard_map) and W8A8 (global pmax amax + int32 partial
    reduced BEFORE the float scales, matching GSPMD's multiply order)."""
    from k8s_llm_monitor_tpu.utils.quantize import quantize_params

    params = llama.init_params(jax.random.PRNGKey(6), CFG)
    qparams = quantize_params(params)
    mesh = create_mesh(MeshConfig(model=8))
    prompts = [[5, 6, 7, 8, 9, 10, 11], [9, 8, 7, 6, 5], [11, 12, 13]]
    sp = SamplingParams(max_tokens=10)

    def run(cfg, p, tp_overlap, **kw):
        ecfg = dataclasses.replace(ECFG, tp_overlap=tp_overlap, **kw)
        eng = InferenceEngine(cfg, p, ecfg, eos_id=-1, mesh=mesh)
        assert eng.tp_overlap == (tp_overlap == "on")
        return [r.token_ids for r in eng.generate(prompts, sp)]

    # int8 KV pages
    assert (run(CFG, params, "on", kv_dtype="int8")
            == run(CFG, params, "off", kv_dtype="int8"))
    # W8A8: int8 weights + dynamic int8 activations
    cfg_aq = dataclasses.replace(CFG, act_quant=True)
    assert run(cfg_aq, qparams, "on") == run(cfg_aq, qparams, "off")


# -- traceguard: zero recompiles with overlap on ------------------------------


@pytest.mark.slow  # builds a real engine; also runs via `make lint-trace`
def test_traceguard_overlap_path_zero_recompiles():
    """Warm the overlap engine, rerun same-shaped traffic: program caches
    must not grow, no forbidden host-sync ops, and the donated page-pool /
    token-state buffers must rebind across the shard_map'd decode step."""
    from k8s_llm_monitor_tpu.devtools import traceguard

    report = traceguard.check_path("overlap")
    assert report.warm_compiles > 0
    assert report.repeat_compiles == 0, report.as_dict()
    assert not any(report.forbidden.values()), report.forbidden
    assert report.donated_pages_rebound and report.donated_tokens_rebound
    assert report.ok


# -- hidden-share model -------------------------------------------------------


def test_hidden_share_dryrun_floor(cpu_mesh_devices):
    """Off-TPU the share is the analytic weight-streaming window (column
    weight bytes / shard over HBM bandwidth vs the per-layer ring wire
    time).  The ISSUE's floor: >= 0.5 of the analytic ring time."""
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    mesh = create_mesh(MeshConfig(model=8))
    eng = _engine(params, "on", mesh)
    share = eng.estimate_hidden_share()
    assert 0.5 <= share <= 1.0
    assert eng.decode_collective_hidden_share == share
    off = _engine(params, "off", mesh)
    assert off.estimate_hidden_share() == 0.0


# -- tier-aware admission -----------------------------------------------------

BS = 16
SEED_LEN = 64            # publishes shareable_blocks(64,16)=3 blocks each
A_LEN, A_GEN = 120, 8    # burst lane: needs 121 tokens of headroom


def _admission_engine(kv_admission: str, host_bytes: int = 64 << 20):
    """Device pool of 17 usable blocks with 12 pinned by published seed
    prefixes -> 5 free blocks = 80 tokens of device-only headroom."""
    params = llama.init_params(jax.random.PRNGKey(7), CFG)
    ecfg = EngineConfig(
        max_slots=4, num_blocks=18, block_size=BS,
        max_blocks_per_seq=(A_LEN + A_GEN + 1 + BS - 1) // BS,
        prefill_buckets=(64, 128), max_prefills_per_step=2,
        decode_steps_per_iter=4, prefix_cache_entries=64,
        host_spill_bytes=host_bytes, kv_admission=kv_admission)
    eng = InferenceEngine(CFG, params, ecfg, eos_id=-1)
    rng = np.random.default_rng(23)
    for _ in range(4):
        eng.generate([[int(t) for t in rng.integers(4, 500, size=SEED_LEN)]],
                     SamplingParams(max_tokens=1))
    return eng, rng


def test_tier_admission_admits_where_device_only_sheds():
    tier, _ = _admission_engine("tier")
    dev, _ = _admission_engine("device")
    assert tier.allocator.free_blocks == dev.allocator.free_blocks == 5
    need = A_LEN + 1
    # device-only headroom: 5 * 16 = 80 < 121 -> shed
    assert "kv capacity" in dev.should_shed(need_tokens=need)
    # tier headroom adds the 12 spillable blocks: (5 + 12) * 16 = 272
    assert tier.admission_headroom_tokens() == 272
    assert tier.should_shed(need_tokens=need) == ""


def test_tier_admission_sheds_when_host_also_full():
    """A host tier too small for even one block buys no headroom: the
    tier policy degrades to device-only arithmetic, not wishful math."""
    eng, _ = _admission_engine("tier", host_bytes=1024)
    assert eng.admission_headroom_tokens() == 5 * BS
    assert "kv capacity" in eng.should_shed(need_tokens=A_LEN + 1)


def test_tier_mode_without_host_tier_is_legacy():
    """kv_admission="tier" with no host tier configured must not arm the
    capacity clause — there is nothing to spill to, so admission relies
    on the queue + OutOfBlocks pushback exactly as before this PR."""
    params = llama.init_params(jax.random.PRNGKey(7), CFG)
    ecfg = dataclasses.replace(ECFG, kv_admission="tier", host_spill_bytes=0)
    eng = InferenceEngine(CFG, params, ecfg, eos_id=-1)
    assert eng.host_kv_tier is None
    assert eng.should_shed(need_tokens=10**6) == ""


def test_tier_admitted_lanes_lose_zero_tokens_under_eviction_faults():
    """The admitted burst must finish clean with its full token budget
    while lane_eviction faults fire mid-drain: spill/restore through the
    host tier is lossless, so admission-by-spill never costs output."""
    from k8s_llm_monitor_tpu.resilience.faults import get_injector

    eng, rng = _admission_engine("tier")
    admitted = []
    get_injector().reset(seed=1234)
    get_injector().arm("lane_eviction", rate=0.25, times=2)
    try:
        for i in range(4):
            prompt = [int(t) for t in rng.integers(4, 500, size=A_LEN)]
            assert eng.should_shed(need_tokens=len(prompt) + 1) == ""
            eng.submit(GenerationRequest(
                request_id=f"burst-{i}", prompt_ids=prompt,
                sampling=SamplingParams(max_tokens=A_GEN)))
            admitted.append(f"burst-{i}")
        while eng.has_work:
            eng.step()
    finally:
        get_injector().reset()
    for rid in admitted:
        res = eng.poll(rid)
        assert res is not None and res.finish_reason != "error", res
        assert len(res.token_ids) == A_GEN, (rid, res.token_ids)


# -- spec decode default-on ---------------------------------------------------


def test_spec_decode_default_on_with_kill_switch():
    """Monitor presets now draft by default; the AcceptanceEMA floor and
    explicit spec_k=0 opt-out both remain live kill-switches."""
    from k8s_llm_monitor_tpu.monitor.config import TPULLMConfig

    cfg = TPULLMConfig()
    assert cfg.spec_k > 0                    # default-on
    assert cfg.spec_min_accept > 1.0         # EMA floor still armed
    assert TPULLMConfig(spec_k=0).spec_k == 0  # opt-out respected

    # The engine honours the floor: an engine built with drafting on
    # arms the acceptance EMA with the config's floor, and the analysis
    # factory threads the monitor defaults straight into EngineConfig.
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    ecfg = dataclasses.replace(ECFG, spec_k=cfg.spec_k,
                               spec_min_accept=cfg.spec_min_accept)
    eng = InferenceEngine(CFG, params, ecfg, eos_id=-1)
    assert eng._spec_accept.floor == cfg.spec_min_accept
    assert eng.ecfg.spec_k == cfg.spec_k > 0
