"""Closed-loop remediation: plan grammar, executor gates, chaos e2e.

Layers, mirroring k8s_llm_monitor_tpu/remediation/:

  * plan grammar — snapshot enumeration, render→parse round-trips, the
    fixed-shape FSM contract, and a fuzz proving the deterministic
    planner's output always lands inside the grammar;
  * engine fuzz (slow) — FSM-constrained samples on a real tiny engine
    parse as valid plans, and swapping snapshot grammars mid-run
    triggers zero recompiles;
  * executor gates — dry-run-first ordering, approval, rate limits,
    breaker trips, idempotent replay, verification + escalation, all on
    injected fake clocks;
  * chaos e2e — four scenarios (crash loop, OOM, stale scheduler, node
    pressure) through a real MonitorServer: inject → detect → plan →
    execute → verified recovery, plus the HTTP routes and /metrics
    families.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request

import pytest

from k8s_llm_monitor_tpu.diagnosis.grammar import GrammarError
from k8s_llm_monitor_tpu.diagnosis.session import SessionManager
from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster, seed_demo_cluster
from k8s_llm_monitor_tpu.monitor.config import Config, RemediationConfig
from k8s_llm_monitor_tpu.monitor.models import EventInfo
from k8s_llm_monitor_tpu.monitor.server import build_server
from k8s_llm_monitor_tpu.remediation import (
    DESTRUCTIVE_VERBS, PLAN_STATE_CAP, PLAN_VERBS, RemediationEngine,
    TargetSnapshot, parse_plan, plan_fsm, propose_plan, render_plan)
from k8s_llm_monitor_tpu.remediation.plans import (
    MAX_PODS, MAX_REPLICAS, workload_of)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


def _cluster() -> FakeCluster:
    fake = FakeCluster()
    fake.add_node("node-a")
    fake.add_node("node-b")
    fake.add_pod("web-frontend-7d4b9c6f5-x2x1p", node="node-a")
    fake.add_pod("api-backend-6f5d8b7c9-k3k2m", node="node-b")
    fake.add_statefulset("engine-decode", replicas=2)
    return fake


class StubAnalysis:
    """Just enough of AnalysisEngine for the executor machinery."""

    class backend:
        name = "stub-model"
        supports_grammar = False

    def __init__(self, severity: str = "warning"):
        self.severity = severity
        self.questions: list[str] = []

    def diagnose(self, question, context=None, slo_class="standard",
                 tenant=""):
        self.questions.append(question)
        return {"severity": self.severity, "component": "c",
                "root_cause": "r", "recommendation": "f", "confidence": 0.5}


class StubPipeline:
    def __init__(self):
        self.events: list[EventInfo] = []

    def offer(self, event: EventInfo) -> None:
        self.events.append(event)


def _engine(backend, *, analysis=None, clock=None, pipeline=None,
            **overrides) -> RemediationEngine:
    kw = dict(enabled=True, execute=False, dry_run_first=True, verify=False,
              verb_interval_s=0.0, target_interval_s=0.0)
    kw.update(overrides)
    return RemediationEngine(
        backend, analysis or StubAnalysis(), RemediationConfig(**kw),
        namespaces=("default",), pipeline=pipeline,
        clock=clock or FakeClock())


def _verdict(sev="warning"):
    return {"severity": sev, "component": "c", "root_cause": "r",
            "recommendation": "f", "confidence": 0.5}


# -- plan grammar ------------------------------------------------------------


def test_workload_of_strips_hash_segments():
    assert workload_of("web-frontend-7d4b9c6f5-x2x1p") == "web-frontend"
    assert workload_of("api-backend-6f5d8b7c9-k3k2m") == "api-backend"
    assert workload_of("engine-decode-0") == "engine-decode-0"  # no hash
    assert workload_of("solo") == "solo"


def test_snapshot_from_backend_enumerates_all_kinds():
    snap = TargetSnapshot.from_backend(_cluster(), ["default"])
    assert "default/web-frontend-7d4b9c6f5-x2x1p" in snap.pods
    assert "default/web-frontend" in snap.workloads
    assert "default/api-backend" in snap.workloads
    assert snap.nodes == ("node-a", "node-b")
    assert snap.statefulsets == ("default/engine-decode",)
    assert snap.statefulset_replicas["default/engine-decode"] == 2


def test_snapshot_caps_keep_unhealthy_pods_first():
    fake = _cluster()
    for i in range(MAX_PODS + 10):
        fake.add_pod(f"bulk-{i:03d}", node="node-a")
    fake.add_pod("stuck-worker-1a2b3", phase="Pending", node="")
    snap = TargetSnapshot.from_backend(fake, ["default"])
    assert len(snap.pods) == MAX_PODS
    assert "default/stuck-worker-1a2b3" in snap.pods  # incident survives cap


def test_snapshot_degrades_per_kind_on_backend_faults():
    fake = _cluster()
    fake.fail_next("list_statefulsets")
    snap = TargetSnapshot.from_backend(fake, ["default"])
    assert snap.statefulsets == ()          # that arm drops out
    assert snap.pods and snap.nodes         # others unaffected
    plan = parse_plan(render_plan("noop", reason="nothing safe"), snap)
    assert plan["verb"] == "noop"
    with pytest.raises(GrammarError):       # scale arm gone with its targets
        parse_plan(render_plan(
            "scale", target="default/engine-decode", replicas=3,
            reason="x"), snap)


def test_render_parse_roundtrip_every_verb():
    snap = TargetSnapshot.from_backend(_cluster(), ["default"])
    cases = [
        ("scale", "default/engine-decode", 3),
        ("rollout_restart", "default/web-frontend", None),
        ("cordon", "node-a", None),
        ("delete_pod", "default/web-frontend-7d4b9c6f5-x2x1p", None),
        ("noop", "", None),
    ]
    for verb, target, replicas in cases:
        text = render_plan(verb, target=target, reason="because tests",
                           replicas=replicas)
        plan = parse_plan(text, snap)
        assert plan["verb"] == verb
        if verb == "cordon":
            assert plan["namespace"] == "" and plan["name"] == target
        elif verb != "noop":
            assert f"{plan['namespace']}/{plan['name']}" == target
        if verb == "scale":
            assert plan["replicas"] == replicas


def test_parse_rejects_ghosts_free_text_and_oversized_replicas():
    snap = TargetSnapshot.from_backend(_cluster(), ["default"])
    with pytest.raises(GrammarError):
        parse_plan(render_plan("delete_pod", target="default/ghost-pod",
                               reason="x"), snap)
    with pytest.raises(GrammarError):
        parse_plan("please restart the web frontend", snap)
    with pytest.raises(GrammarError):   # grammar-level: 17 > MAX_REPLICAS
        parse_plan('{"verb":"scale","target":"default/engine-decode",'
                   f'"replicas":{MAX_REPLICAS + 1},"reason":"x"}}', snap)
    with pytest.raises(GrammarError):   # verbs can't cross target kinds
        parse_plan('{"verb":"cordon","target":"default/engine-decode",'
                   '"reason":"x"}', snap)


def test_plan_fsm_fixed_shape_and_cache():
    snap_a = TargetSnapshot.from_backend(_cluster(), ["default"])
    other = _cluster()
    other.add_pod("extra-worker-9z8y7", node="node-b")
    snap_b = TargetSnapshot.from_backend(other, ["default"])
    fsm_a, fsm_b = plan_fsm(snap_a), plan_fsm(snap_b)
    assert fsm_a.trans.shape == fsm_b.trans.shape \
        == (PLAN_STATE_CAP + 1, 259)
    assert plan_fsm(snap_a) is fsm_a        # cache hit on identical key
    assert fsm_a is not fsm_b


def test_empty_snapshot_admits_only_noop():
    snap = TargetSnapshot()
    assert parse_plan(render_plan("noop", reason="idle"), snap)["verb"] \
        == "noop"
    with pytest.raises(GrammarError):
        parse_plan('{"verb":"delete_pod","target":"a/b","reason":"x"}', snap)


def test_propose_plan_output_always_parses_fuzz():
    """The grammar property for the deterministic planner: whatever junk
    lands in the verdict/trigger text — unicode, quotes, oversized
    strings — the rendered plan parses and names a live target."""
    snap = TargetSnapshot.from_backend(_cluster(), ["default"])
    words = ["oomkilling", "backoff", "failedscheduling", "pressure",
             "web-frontend", "api-backend-6f5d8b7c9-k3k2m", "node-a",
             "engine-decode", "overload", "queue", "weird-λ-unicode",
             '"quotes" and \\backslashes\\', "x" * 300, "", "NotReady"]
    rng = random.Random(20)
    for _ in range(200):
        trigger = " ".join(rng.sample(words, rng.randint(1, 5)))
        verdict = {"severity": "critical",
                   "component": rng.choice(words),
                   "root_cause": rng.choice(words),
                   "recommendation": rng.choice(words), "confidence": 0.5}
        plan = parse_plan(propose_plan(snap, verdict, trigger), snap)
        assert plan["verb"] in PLAN_VERBS
        if plan["verb"] == "scale":
            assert 0 <= plan["replicas"] <= MAX_REPLICAS


def test_propose_plan_keyword_ladder():
    snap = TargetSnapshot.from_backend(_cluster(), ["default"])
    cases = [
        ("FailedScheduling pod web-frontend-7d4b9c6f5-x2x1p stuck",
         "delete_pod", "web-frontend-7d4b9c6f5-x2x1p"),
        ("memory pressure on node-b", "cordon", "node-b"),
        ("BackOff restarting web-frontend", "rollout_restart",
         "web-frontend"),
        ("queue depth high, scale up engine-decode", "scale",
         "engine-decode"),
        ("nothing recognizable here", "noop", ""),
    ]
    for trigger, verb, name in cases:
        plan = parse_plan(propose_plan(snap, _verdict(), trigger), snap)
        assert (plan["verb"], plan["name"]) == (verb, name), trigger
    # scale proposes current+1 from the snapshot's observed replicas
    plan = parse_plan(propose_plan(snap, _verdict(), "overload"), snap)
    assert plan["replicas"] == 3


# -- engine fuzz: constrained samples parse, swaps don't recompile -----------


@pytest.fixture(scope="module")
def plan_engine():
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import ModelConfig
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig, InferenceEngine)
    from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

    cfg = ModelConfig(name="tiny", vocab_size=300, hidden_size=32,
                      intermediate_size=64, num_layers=2, num_heads=4,
                      num_kv_heads=2, dtype="float32", rope_theta=1e4)
    tok = ByteTokenizer()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(
        cfg, params,
        EngineConfig(max_slots=2, num_blocks=512, block_size=16,
                     max_blocks_per_seq=128, prefill_buckets=(64, 128, 512),
                     decode_steps_per_iter=4),
        tokenizer=tok)
    return engine, tok


@pytest.mark.slow  # real-engine compile; `make chaos-remediate` runs these
@pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (0.9, 20), (1.5, 5)])
def test_constrained_plan_samples_always_parse(plan_engine, temperature,
                                               top_k):
    """The 100%-schema-valid property for plans: whatever the sampler
    draws under the snapshot FSM must parse and name a live target."""
    from k8s_llm_monitor_tpu.serving.engine import SamplingParams

    engine, tok = plan_engine
    snap = TargetSnapshot.from_backend(_cluster(), ["default"])
    engine.set_grammar(plan_fsm(snap, eos_id=tok.eos_id))
    prompt = tok.encode("## Plan\nchoose one action:\n")
    results = engine.generate(
        [prompt, prompt],
        SamplingParams(max_tokens=1, temperature=temperature, top_k=top_k,
                       constrained=True))
    for res in results:
        assert res.finish_reason in ("eos", "stop", "length"), res
        plan = parse_plan(tok.decode(res.token_ids), snap)
        assert plan["verb"] in PLAN_VERBS
        if plan["verb"] == "delete_pod":
            assert f"{plan['namespace']}/{plan['name']}" in snap.pods


@pytest.mark.slow  # shares the real-engine fixture above
def test_snapshot_grammar_swap_is_recompile_free(plan_engine):
    """The traceguard claim on real plan grammars: swapping one
    snapshot's padded FSM for another's (different cluster, same fixed
    shape) triggers zero new compiles after warm-up."""
    from k8s_llm_monitor_tpu.devtools.traceguard import count_new_compiles
    from k8s_llm_monitor_tpu.serving.engine import SamplingParams

    engine, tok = plan_engine
    snap_a = TargetSnapshot.from_backend(_cluster(), ["default"])
    other = _cluster()
    other.add_pod("drainer-4c5d6", node="node-b", phase="Pending")
    other.add_statefulset("engine-prefill", replicas=1)
    snap_b = TargetSnapshot.from_backend(other, ["default"])
    prompt = tok.encode("## Plan\n")
    sampling = SamplingParams(max_tokens=1, constrained=True)

    engine.set_grammar(plan_fsm(snap_a, eos_id=tok.eos_id))
    [warm] = engine.generate([prompt], sampling)   # warm the FSM programs
    parse_plan(tok.decode(warm.token_ids), snap_a)

    def swapped():
        engine.set_grammar(plan_fsm(snap_b, eos_id=tok.eos_id))
        [res] = engine.generate([prompt], sampling)
        parse_plan(tok.decode(res.token_ids), snap_b)

    new_compiles, _ = count_new_compiles(engine, swapped)
    assert new_compiles == 0


# -- executor gates ----------------------------------------------------------


class RecordingBackend:
    """Delegating wrapper logging every mutation verb with its dry_run."""

    _VERBS = ("scale_statefulset", "rollout_restart", "cordon_node",
              "delete_pod")

    def __init__(self, inner):
        self._inner = inner
        self.calls: list[tuple[str, bool]] = []

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in self._VERBS and callable(attr):
            def wrapper(*args, **kwargs):
                self.calls.append((name, bool(kwargs.get("dry_run", False))))
                return attr(*args, **kwargs)
            return wrapper
        return attr


def test_on_verdict_gates_severity_and_enabled():
    eng = _engine(_cluster())
    assert eng.on_verdict(_verdict("info"), trigger="BackOff web-frontend") \
        is None
    off = _engine(_cluster(), enabled=False)
    assert off.on_verdict(_verdict("critical"), trigger="x") is None


def test_observe_only_default_proposes_without_touching_cluster():
    backend = RecordingBackend(_cluster())
    eng = _engine(backend)                  # execute=False: observe-only
    rec = eng.on_verdict(_verdict(), trigger="BackOff web-frontend crash")
    assert rec["status"] == "proposed" and rec["outcome"] == "proposed"
    assert rec["plan"]["verb"] == "rollout_restart"
    assert rec["planner"] == "heuristic"    # stub backend: no grammar path
    assert backend.calls == []              # nothing executed
    assert eng.counters()["plans_total"][("rollout_restart", "proposed")] == 1


def test_execute_is_dry_run_first():
    backend = RecordingBackend(_cluster())
    eng = _engine(backend, execute=True)
    rec = eng.on_verdict(_verdict(), trigger="BackOff web-frontend crash")
    assert rec["status"] == "executed"
    assert rec["detail"] == "dry-run validated"
    assert backend.calls == [("rollout_restart", True),
                             ("rollout_restart", False)]


def test_destructive_verbs_refuse_without_approval(monkeypatch):
    monkeypatch.delenv("K8SLLM_REMEDIATE_APPROVE", raising=False)
    fake = _cluster()
    fake.add_pod("stuck-worker-1a2b3", phase="Pending", node="")
    eng = _engine(fake, execute=True)
    rec = eng.on_verdict(
        _verdict(), trigger="FailedScheduling pod stuck-worker-1a2b3")
    assert rec["plan"]["verb"] == "delete_pod"
    assert rec["plan"]["verb"] in DESTRUCTIVE_VERBS
    assert rec["status"] == "awaiting_approval"
    assert rec["outcome"] == "refused_approval"
    # env-wide operator approval opens the gate immediately
    monkeypatch.setenv("K8SLLM_REMEDIATE_APPROVE", "1")
    assert eng.execute(rec["id"]) == "executed"
    assert all((p["metadata"] or {}).get("name") != "stuck-worker-1a2b3"
               for p in fake.list_pods("default"))


def test_per_plan_approve_executes_even_in_observe_only(monkeypatch):
    monkeypatch.delenv("K8SLLM_REMEDIATE_APPROVE", raising=False)
    fake = _cluster()
    eng = _engine(fake)                     # observe-only
    rec = eng.on_verdict(_verdict(), trigger="node-a memory pressure")
    assert rec["plan"]["verb"] == "cordon" and rec["status"] == "proposed"
    out = eng.approve(rec["id"])
    assert out["approved"] and out["status"] == "executed"
    node = next(n for n in fake.list_nodes()
                if n["metadata"]["name"] == "node-a")
    assert node["spec"]["unschedulable"] is True


def test_reject_parks_the_record():
    eng = _engine(_cluster())
    rec = eng.on_verdict(_verdict(), trigger="BackOff web-frontend")
    assert eng.reject(rec["id"])["status"] == "rejected"
    assert eng.execute(rec["id"]) == "refused_replay"   # terminal state
    assert eng.reject("rem-99999") is None


def test_idempotent_replay_refused_within_window():
    clk = FakeClock()
    eng = _engine(_cluster(), execute=True, clock=clk,
                  replay_window_s=300.0)
    rec1 = eng.on_verdict(_verdict(), trigger="BackOff web-frontend")
    assert rec1["status"] == "executed"
    # supervisor replay: same verdict, same trigger → same idempotency key
    rec2 = eng.on_verdict(_verdict(), trigger="BackOff web-frontend")
    assert rec2["idempotency_key"] == rec1["idempotency_key"]
    assert rec2["outcome"] == "refused_replay"
    assert eng.execute(rec1["id"]) == "refused_replay"  # terminal record
    clk.tick(301)                           # window expires
    assert eng.execute(rec2["id"]) == "executed"


def test_rate_limits_per_verb_and_per_target():
    clk = FakeClock()
    eng = _engine(_cluster(), clock=clk, verb_interval_s=5.0,
                  target_interval_s=60.0)
    rec_a = eng.on_verdict(_verdict(), trigger="BackOff web-frontend")
    rec_b = eng.on_verdict(_verdict(), trigger="crash api-backend")
    assert eng.execute(rec_a["id"]) == "executed"
    assert eng.execute(rec_b["id"]) == "refused_rate"   # verb cooldown
    clk.tick(6)
    assert eng.execute(rec_b["id"]) == "executed"
    clk.tick(6)                              # verb open, target still cold
    rec_a2 = eng.on_verdict(_verdict(), trigger="BackOff web-frontend again")
    assert eng.execute(rec_a2["id"]) == "refused_rate"
    clk.tick(60)
    assert eng.execute(rec_a2["id"]) == "executed"


def test_breaker_trips_after_failures_and_cools_down():
    clk = FakeClock()
    fake = _cluster()
    eng = _engine(fake, clock=clk, breaker_failures=2,
                  breaker_cooldown_s=30.0)
    rec = eng.on_verdict(_verdict(), trigger="BackOff web-frontend")
    fake.fail_next("rollout_restart", 2)
    assert eng.execute(rec["id"]) == "error"            # failure 1
    assert eng.execute(rec["id"]) == "error"            # failure 2: opens
    assert eng.execute(rec["id"]) == "refused_breaker"
    assert eng.counters()["breaker_open"]["rollout_restart"] == 1
    clk.tick(31)                             # cooldown: half-open probe
    assert eng.execute(rec["id"]) == "executed"
    assert eng.counters()["breaker_open"]["rollout_restart"] == 0


def test_verify_resolved_marks_record_verified():
    analysis = StubAnalysis(severity="warning")
    analysis.sessions = SessionManager()
    eng = _engine(_cluster(), analysis=analysis, execute=True, verify=True)
    rec = eng.on_verdict(_verdict(), trigger="BackOff web-frontend")
    assert rec["status"] == "verified"
    assert rec["verify"]["result"] == "resolved"
    assert rec["verify"]["condition_cleared"] is True
    assert eng.counters()["verify_total"]["resolved"] == 1
    # the verification turn ran on a session pinned to post-action context
    session = analysis.sessions.get(f"remediation-{rec['id']}")
    assert session is not None
    assert "Cluster state (post-action)" in session.context
    assert "Is the triggering condition cleared?" in analysis.questions[-1]


def test_unresolved_escalates_then_parks(monkeypatch):
    analysis = StubAnalysis(severity="critical")   # verdict never clears
    pipeline = StubPipeline()
    eng = _engine(_cluster(), analysis=analysis, execute=True, verify=True,
                  pipeline=pipeline, max_retries=1)
    rec = eng.on_verdict(_verdict(), trigger="BackOff web-frontend")
    assert rec["status"] == "unresolved" and rec["escalation"] == 1
    assert len(pipeline.events) == 1        # re-entered the pipeline
    event = pipeline.events[0]
    assert event.reason == "RemediationUnresolved:rollout_restart"
    assert event.source == "remediation"
    assert eng.verify(rec["id"]) == "unresolved"   # attempt 2 > max_retries
    assert eng.get(rec["id"])["status"] == "escalated"
    assert len(pipeline.events) == 1        # parked: no more re-entry


def test_condition_cleared_predicates():
    fake = _cluster()
    eng = _engine(fake)
    assert eng._condition_cleared({"verb": "noop", "namespace": "",
                                   "name": "", "reason": ""})
    fake.scale_statefulset("default", "engine-decode", 3)
    assert eng._condition_cleared(
        {"verb": "scale", "namespace": "default", "name": "engine-decode",
         "replicas": 3, "reason": ""})
    assert not eng._condition_cleared(
        {"verb": "scale", "namespace": "default", "name": "engine-decode",
         "replicas": 5, "reason": ""})
    assert not eng._condition_cleared(
        {"verb": "cordon", "namespace": "", "name": "node-b", "reason": ""})
    fake.cordon_node("node-b")
    assert eng._condition_cleared(
        {"verb": "cordon", "namespace": "", "name": "node-b", "reason": ""})
    assert not eng._condition_cleared(
        {"verb": "delete_pod", "namespace": "default",
         "name": "api-backend-6f5d8b7c9-k3k2m", "reason": ""})
    fake.delete_pod("default", "api-backend-6f5d8b7c9-k3k2m")
    assert eng._condition_cleared(
        {"verb": "delete_pod", "namespace": "default",
         "name": "api-backend-6f5d8b7c9-k3k2m", "reason": ""})


def test_snapshot_and_counters_are_json_safe():
    eng = _engine(_cluster(), execute=True)
    eng.on_verdict(_verdict(), trigger="BackOff web-frontend")
    snap = eng.snapshot()
    json.dumps(snap)                        # must serialize for /api/v1/stats
    assert snap["enabled"] and snap["execute"]
    assert snap["plans_total"]["rollout_restart/executed"] == 1
    assert snap["breakers"]["rollout_restart"] == "closed"
    assert eng.records(limit=1)[0]["id"] == "rem-00001"


# -- chaos e2e: four scenarios through a real server -------------------------


@pytest.fixture(scope="module")
def remediation_server():
    cfg = Config()
    cfg.llm.provider = "template"
    cfg.diagnosis.burst_threshold = 3
    cfg.diagnosis.window_s = 60.0
    cfg.diagnosis.cooldown_s = 0.0
    cfg.remediation.execute = True
    cfg.remediation.verify = True
    cfg.remediation.verb_interval_s = 0.0
    cfg.remediation.target_interval_s = 0.0
    backend = seed_demo_cluster(FakeCluster())
    backend.add_statefulset("engine-decode", replicas=2)
    srv = build_server(cfg, backend=backend)
    srv.start()
    yield srv, backend
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=30) as r:
        body = r.read().decode()
        return (json.loads(body) if r.headers["Content-Type"].startswith(
            "application/json") else body)


def _post(srv, path, payload=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _drive_scenario(srv, reason, message, want_verb, want_name, want_status,
                    timeout=20.0):
    """Inject a warning burst and wait for a matching remediation record
    (verb + target name, so earlier scenarios' records never match) to
    reach ``want_status``."""
    for i in range(4):
        srv.diagnosis.handler.on_event(EventInfo(
            type="Warning", reason=reason, message=f"{message} (try {i})",
            source="chaos"))
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for rec in srv.remediation.records():
            if rec["plan"]["verb"] == want_verb \
                    and rec["plan"]["name"] == want_name \
                    and rec["status"] == want_status:
                return rec
        time.sleep(0.05)
    raise AssertionError(
        f"no {want_verb}/{want_name} record reached {want_status}; have "
        f"{[(r['plan']['verb'], r['plan']['name'], r['status']) for r in srv.remediation.records()]}")


def test_chaos_crash_loop_verified_recovery(remediation_server):
    """Scenario 1: crash loop → rollout_restart → pods Running again."""
    srv, backend = remediation_server
    backend.update_pod("default", "web-frontend-7d4b9c6f5-x2x1p",
                       phase="CrashLoopBackOff")
    rec = _drive_scenario(
        srv, "BackOff",
        "Back-off restarting failed container in web-frontend",
        "rollout_restart", "web-frontend", "verified")
    assert rec["outcome"] == "executed"
    assert rec["detail"] == "dry-run validated"
    assert rec["plan"]["name"] == "web-frontend"
    assert rec["verify"]["result"] == "resolved"
    assert rec["verify"]["condition_cleared"] is True
    pod = next(p for p in backend.list_pods("default")
               if p["metadata"]["name"].startswith("web-frontend"))
    assert pod["status"]["phase"] == "Running"


def test_chaos_oom_verified_recovery(remediation_server):
    """Scenario 2: OOM kill → rollout_restart of the OOMing workload."""
    srv, backend = remediation_server
    backend.update_pod("default", "api-backend-6f5d8b7c9-k3k2m",
                       phase="OOMKilled")
    rec = _drive_scenario(
        srv, "OOMKilling", "Memory cgroup out of memory: api-backend",
        "rollout_restart", "api-backend", "verified")
    assert rec["plan"]["name"] == "api-backend"
    assert rec["verify"]["result"] == "resolved"
    pod = next(p for p in backend.list_pods("default")
               if p["metadata"]["name"].startswith("api-backend"))
    assert pod["status"]["phase"] == "Running"


def test_chaos_stale_scheduler_needs_approval(remediation_server,
                                              monkeypatch):
    """Scenario 3: stale scheduler → delete_pod, which must refuse until
    the operator approves over HTTP — then executes and verifies."""
    monkeypatch.delenv("K8SLLM_REMEDIATE_APPROVE", raising=False)
    srv, backend = remediation_server
    backend.add_pod("batch-runner-5f7d8", phase="Pending", node="")
    rec = _drive_scenario(
        srv, "FailedScheduling",
        "pod batch-runner-5f7d8 unschedulable: stale scheduler assignment",
        "delete_pod", "batch-runner-5f7d8", "awaiting_approval")
    assert rec["outcome"] == "refused_approval"
    assert [p for p in backend.list_pods("default")
            if p["metadata"]["name"] == "batch-runner-5f7d8"]  # still there
    resp = _post(srv, f"/api/v1/remediations/{rec['id']}/approve")
    assert resp["action"] == "approve"
    assert resp["remediation"]["status"] == "verified"
    assert resp["remediation"]["verify"]["result"] == "resolved"
    assert not [p for p in backend.list_pods("default")
                if p["metadata"]["name"] == "batch-runner-5f7d8"]


def test_chaos_node_pressure_env_approval(remediation_server, monkeypatch):
    """Scenario 4: node memory pressure → cordon, gated until the blanket
    env approval is set — the second approval path."""
    srv, backend = remediation_server
    monkeypatch.delenv("K8SLLM_REMEDIATE_APPROVE", raising=False)
    rec = _drive_scenario(
        srv, "NodeHasMemoryPressure",
        "node k3d-demo-agent-1 under memory pressure, evicting",
        "cordon", "k3d-demo-agent-1", "awaiting_approval")
    monkeypatch.setenv("K8SLLM_REMEDIATE_APPROVE", "1")
    assert srv.remediation.execute(rec["id"]) == "executed"
    rec = srv.remediation.get(rec["id"])
    assert rec["status"] == "verified"
    assert rec["plan"]["name"] == "k3d-demo-agent-1"
    node = next(n for n in backend.list_nodes()
                if n["metadata"]["name"] == "k3d-demo-agent-1")
    assert node["spec"]["unschedulable"] is True


def test_remediations_api_and_metrics(remediation_server):
    """Runs after the four scenarios: routes, limits, error edges, and
    the three exporter families with their contractual labels."""
    srv, _ = remediation_server
    payload = _get(srv, "/api/v1/remediations")
    assert payload["status"] == "success"
    assert len(payload["remediations"]) >= 4
    assert payload["counters"]["plans_total"]
    assert len(_get(srv, "/api/v1/remediations?limit=1")["remediations"]) == 1
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(srv, "/api/v1/remediations?limit=abc")
    assert err.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(srv, "/api/v1/remediations/rem-00001/approve")   # GET: no
    assert err.value.code == 405
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(srv, "/api/v1/remediations/rem-99999/approve")
    assert err.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(srv, "/api/v1/remediations/rem-00001/frobnicate")
    assert err.value.code == 404

    metrics = _get(srv, "/metrics")
    assert ('k8s_llm_monitor_remediation_plans_total{'
            'verb="rollout_restart",outcome="executed"}') in metrics
    assert ('k8s_llm_monitor_remediation_plans_total{'
            'verb="delete_pod",outcome="refused_approval"}') in metrics
    assert ('k8s_llm_monitor_remediation_breaker_open{verb="cordon"}'
            in metrics)
    assert ('k8s_llm_monitor_remediation_verify_total{result="resolved"}'
            in metrics)

    stats = _get(srv, "/api/v1/stats")
    assert "remediation" in json.dumps(stats)
