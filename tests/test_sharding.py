"""GSPMD tensor-parallel execution on the virtual 8-device CPU mesh.

TP-sharded forward/prefill/decode must match single-device results bit-for-
nearly-bit (same program, XLA inserts collectives from the annotations).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh
from k8s_llm_monitor_tpu.parallel.sharding import (
    param_partition_specs,
    shard_params,
)
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    SamplingParams,
)

CFG = ModelConfig(name="t", vocab_size=512, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=8, num_kv_heads=8, dtype="float32",
                  rope_theta=10_000.0)


def test_partition_specs_cover_param_tree():
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    specs = param_partition_specs(params)
    flat_p = jax.tree_util.tree_leaves(params)
    flat_s = jax.tree_util.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
    assert len(flat_p) == len(flat_s)
    # column-parallel q kernel shards axis 1; row-parallel o shards axis 0
    assert specs["layers"][0]["q"]["kernel"] == P(None, "model")
    assert specs["layers"][0]["o"]["kernel"] == P("model", None)
    assert specs["embed"]["weight"] == P("model", None)
    assert specs["final_norm"] == P(None)


def test_tp_forward_matches_single_device(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(model=8))
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 512, size=(2, 12), dtype=np.int32)
    )

    ref = llama.forward_full(params, CFG, tokens)

    sharded = shard_params(params, mesh)
    fwd = jax.jit(lambda p, t: llama.forward_full(p, CFG, t))
    out = fwd(sharded, jax.device_put(tokens, NamedSharding(mesh, P(None, None))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_tp_engine_generation_matches_unsharded(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(model=8))
    params = llama.init_params(jax.random.PRNGKey(1), CFG)
    ecfg = EngineConfig(max_slots=2, num_blocks=32, block_size=8,
                        max_blocks_per_seq=8, prefill_buckets=(16,))
    prompts = [[5, 6, 7, 8, 9], [11, 12, 13]]
    sp = SamplingParams(max_tokens=6)

    plain = InferenceEngine(CFG, params, ecfg, eos_id=-1).generate(prompts, sp)
    tp = InferenceEngine(CFG, params, ecfg, eos_id=-1, mesh=mesh).generate(prompts, sp)
    for a, b in zip(plain, tp):
        assert a.token_ids == b.token_ids


def test_seq_sharded_prefill_engine_matches_unsharded(cpu_mesh_devices):
    """Sequence-parallel serve prefill (SURVEY §7 step 5): a mesh with a
    nontrivial ``seq`` axis shards chunked-prefill token batches over it
    (engine._tokens_to_device), splitting one long prompt's ingestion
    FLOPs across chips.  Long prompts (> top bucket) force the chunk-round
    path; output must be token-identical to the unsharded engine."""
    mesh = create_mesh(MeshConfig(data=1, seq=2, model=4))
    params = llama.init_params(jax.random.PRNGKey(2), CFG)
    ecfg = EngineConfig(max_slots=2, num_blocks=32, block_size=8,
                        max_blocks_per_seq=8, prefill_buckets=(16,))
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(2, 500, size=40)),   # 40 > 16: chunked
               list(rng.integers(2, 500, size=12))]   # dense admission
    sp = SamplingParams(max_tokens=5)

    plain = InferenceEngine(CFG, params, ecfg, eos_id=-1).generate(prompts, sp)
    sq = InferenceEngine(CFG, params, ecfg, eos_id=-1, mesh=mesh)
    assert sq._tok_sharding is not None
    seq = sq.generate(prompts, sp)
    for a, b in zip(plain, seq):
        assert a.token_ids == b.token_ids


def test_seq_mesh_rejects_indivisible_buckets(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(data=1, seq=2, model=4))
    params = llama.init_params(jax.random.PRNGKey(2), CFG)
    ecfg = EngineConfig(max_slots=2, num_blocks=32, block_size=8,
                        max_blocks_per_seq=8, prefill_buckets=(15,))
    with pytest.raises(ValueError, match="seq"):
        InferenceEngine(CFG, params, ecfg, eos_id=-1, mesh=mesh)


def test_all_presets_are_coherent_and_tp8_shardable():
    """Every serving preset must have integral GQA/head geometry and a
    parameter pytree whose model-sharded axes divide a TP-8 mesh (or fall
    back to replication) — checked via eval_shape, no weights built."""
    from k8s_llm_monitor_tpu.models.config import PRESETS

    for name, cfg in PRESETS.items():
        assert cfg.hidden_size % cfg.num_heads == 0 or cfg.head_dim, name
        assert cfg.num_heads % cfg.num_kv_heads == 0, name
        assert cfg.head_dim_ * cfg.num_heads <= 2 * cfg.hidden_size, name
        shapes = jax.eval_shape(
            lambda rng, c=cfg: llama.init_params(rng, c),
            jax.random.PRNGKey(0))
        specs = param_partition_specs(shapes)

        def check(path, leaf, spec):
            for dim, axis in enumerate(spec):
                if axis == "model":
                    assert leaf.shape[dim] % 8 == 0, (
                        f"{name}: {path} {leaf.shape} axis {dim} "
                        f"not divisible by TP-8")

        jax.tree_util.tree_map_with_path(
            lambda p, l, s: check(p, l, s), shapes, specs)


def test_70b_class_specs_divide_on_tp8_and_tp16():
    """BASELINE config #5 (70B-class GSPMD TP): every parameter's sharded
    axis must divide evenly on TP-8 and TP-16 meshes, and the KV pages fall
    back to replication when TP exceeds the 8 KV heads — checked via
    eval_shape so no 70B weights are materialized."""
    from k8s_llm_monitor_tpu.models.config import PRESETS
    from k8s_llm_monitor_tpu.parallel.sharding import kv_pages_partition_specs

    class _FakeMesh:
        def __init__(self, tp):
            self.shape = {"data": 1, "seq": 1, "model": tp}

    for name in ("llama3-70b", "qwen2-72b"):
        cfg = PRESETS[name]
        shapes = jax.eval_shape(
            lambda rng, c=cfg: llama.init_params(rng, c),
            jax.random.PRNGKey(0))
        specs = param_partition_specs(shapes)

        for tp in (8, 16):
            def check(path, leaf, spec):
                for dim, axis in enumerate(spec):
                    if axis == "model":
                        assert leaf.shape[dim] % tp == 0, (
                            f"{name} tp={tp}: {path} {leaf.shape} "
                            f"axis {dim} not divisible")

            jax.tree_util.tree_map_with_path(
                lambda p, l, s: check(p, l, s), shapes, specs)

        pages_shape = jax.eval_shape(
            lambda c=cfg: llama.init_kv_pages(c, 16, 16))
        kv8 = kv_pages_partition_specs(
            pages_shape, _FakeMesh(8), num_kv_heads=cfg.num_kv_heads)
        assert kv8.k[0] == P(None, None, "model")        # 8 kv heads / tp8
        kv16 = kv_pages_partition_specs(
            pages_shape, _FakeMesh(16), num_kv_heads=cfg.num_kv_heads)
        assert kv16.k[0] == P(None, None, None)          # tp16 > kv -> repl


def test_70b_dims_tp_forward_lowers(cpu_mesh_devices):
    """A 70B-dimensioned (2-layer) model must lower with the TP specs on the
    8-device mesh — catches partitioner rejections (uneven shards, bad
    specs) without allocating 70B weights."""
    from k8s_llm_monitor_tpu.models.config import LLAMA3_70B
    import dataclasses as _dc

    cfg = _dc.replace(LLAMA3_70B, num_layers=2)
    mesh = create_mesh(MeshConfig(model=8))
    shapes = jax.eval_shape(
        lambda rng: llama.init_params(rng, cfg), jax.random.PRNGKey(0))
    specs = param_partition_specs(shapes)
    shaped = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(
            l.shape, l.dtype, sharding=NamedSharding(mesh, s)),
        shapes, specs)
    tok_shape = jax.ShapeDtypeStruct(
        (1, 64), jnp.int32, sharding=NamedSharding(mesh, P(None, None)))
    lowered = jax.jit(
        lambda p, t: llama.forward_full(p, cfg, t)
    ).lower(shaped, tok_shape)
    assert "stablehlo" in lowered.as_text()[:4000].lower()


def test_tp_engine_selects_pallas_kernel_path(cpu_mesh_devices):
    """When TP divides the KV heads, the engine must run the shard_map-
    wrapped Pallas kernel (VERDICT r3 item 3), not the gather fallback;
    when it does not divide, it must fall back."""
    from k8s_llm_monitor_tpu.ops.attention import paged_decode_attention

    params = llama.init_params(jax.random.PRNGKey(1), CFG)
    ecfg = EngineConfig(max_slots=2, num_blocks=32, block_size=8,
                        max_blocks_per_seq=8, prefill_buckets=(16,))
    mesh = create_mesh(MeshConfig(model=8))          # 8 kv heads / tp8
    eng = InferenceEngine(CFG, params, ecfg, eos_id=-1, mesh=mesh)
    assert eng._attn_impl is not paged_decode_attention

    import dataclasses as _dc
    cfg3 = _dc.replace(CFG, num_kv_heads=2, num_heads=8)  # tp8 > 2 kv heads
    eng2 = InferenceEngine(
        cfg3, llama.init_params(jax.random.PRNGKey(1), cfg3),
        ecfg, eos_id=-1, mesh=mesh)
    assert eng2._attn_impl is paged_decode_attention


def test_spec_layout_roles_and_rules():
    """SpecLayout is the single source of the axis layout; the regex rules
    bind its role methods to param paths (first match wins, unmatched
    leaves replicate, list indices drop out of paths)."""
    from k8s_llm_monitor_tpu.parallel.sharding import (
        DEFAULT_LAYOUT,
        SpecLayout,
        match_partition_rules,
        partition_rules,
    )

    lay = DEFAULT_LAYOUT
    assert lay.column_kernel() == P(None, "model")
    assert lay.row_kernel() == P("model", None)
    assert lay.embedding() == P("model", None)
    assert lay.layer_norm() == P(None)
    # KV pages: head-slice only when tp divides the kv-head count; any
    # other degree must replicate (a mid-head lane split is wrong, not
    # just slow).
    assert lay.kv_pages(8, 8) == P(None, None, "model")
    assert lay.kv_pages(8, 16) == P(None, None, None)
    assert lay.kv_pages(8, 3) == P(None, None, None)
    assert lay.kv_pages(8, 1) == P(None, None, None)
    # Page tables never shard: block ids are global (kv_cache.py).
    assert lay.page_table() == P(None, None)

    params = {"layers": [{"q": {"kernel": 0}, "o": {"kernel": 0},
                          "up_e": {"kernel": 0}, "input_norm": 0}],
              "embed": {"weight": 0}, "final_norm": 0, "odd_leaf": 0}
    specs = match_partition_rules(partition_rules(lay), params)
    assert specs["layers"][0]["q"]["kernel"] == P(None, "model")
    assert specs["layers"][0]["o"]["kernel"] == P("model", None)
    assert specs["layers"][0]["up_e"]["kernel"] == P("model", None, None)
    assert specs["layers"][0]["input_norm"] == P(None)
    assert specs["embed"]["weight"] == P("model", None)
    assert specs["odd_leaf"] == P(None)          # unmatched -> replicate

    # Axis names flow from the layout, not from hardcoded strings.
    alt = SpecLayout(model_axis="tp")
    assert alt.column_kernel() == P(None, "tp")
    assert alt.kv_pages(8, 2) == P(None, None, "tp")


def test_page_slice_bytes_divides_heads_not_pages():
    from k8s_llm_monitor_tpu.serving.kv_cache import page_slice_bytes

    full = page_slice_bytes(8, 64, 16, 2, tp=1)
    assert full == 2 * 16 * 8 * 64 * 2
    assert page_slice_bytes(8, 64, 16, 2, tp=8) == full // 8
    # Indivisible/oversubscribed TP replicates: the full page per chip.
    assert page_slice_bytes(8, 64, 16, 2, tp=16) == full
    assert page_slice_bytes(8, 64, 16, 2, tp=3) == full


@pytest.mark.slow  # builds two full engines (~30s on one core); the gate
# still runs in CI via `make tier1-mesh`, which applies no marker filter
def test_tp_mixed_traffic_parity_incl_constrained(cpu_mesh_devices):
    """The ISSUE's parity gate: TP-8 and 1-device engines must produce
    byte-identical greedy token streams over one mixed submission wave —
    a chunked long-prompt admission (> top bucket), dense short prefills,
    multi-round decode, and a grammar-constrained verdict lane sharing
    the batch."""
    from k8s_llm_monitor_tpu.diagnosis.grammar import verdict_fsm
    from k8s_llm_monitor_tpu.serving.engine import GenerationRequest
    from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    params = llama.init_params(jax.random.PRNGKey(4), CFG)
    ecfg = EngineConfig(max_slots=4, num_blocks=128, block_size=8,
                        max_blocks_per_seq=32, prefill_buckets=(16,),
                        decode_steps_per_iter=4)
    rng = np.random.default_rng(5)
    reqs = [
        ("long", [int(t) for t in rng.integers(2, 250, size=40)],
         SamplingParams(max_tokens=8)),                  # 40 > 16: chunked
        ("short-a", [int(t) for t in rng.integers(2, 250, size=7)],
         SamplingParams(max_tokens=8)),                  # dense admission
        ("short-b", [int(t) for t in rng.integers(2, 250, size=5)],
         SamplingParams(max_tokens=12)),                 # uneven drain
        ("verdict", tok.encode("why is default/web crashlooping?"),
         SamplingParams(max_tokens=1, constrained=True)),  # grammar lane
    ]

    def run(mesh):
        eng = InferenceEngine(CFG, params, ecfg, tokenizer=tok, mesh=mesh)
        eng.set_grammar(verdict_fsm(eos_id=tok.eos_id))
        for rid, prompt, sp in reqs:
            eng.submit(GenerationRequest(
                request_id=rid, prompt_ids=list(prompt), sampling=sp))
        while eng.has_work:
            eng.step()
        out = {}
        for rid, _, _ in reqs:
            res = eng.poll(rid)
            assert res is not None and res.finish_reason != "error", res
            out[rid] = res.token_ids
        return out

    plain = run(None)
    tp = run(create_mesh(MeshConfig(model=8)))
    assert plain == tp
    assert len(tp["verdict"]) > 0


def test_init_multihost_single_host_noop(cpu_mesh_devices):
    """init_multihost on a single host is a safe no-op returning index 0."""
    from k8s_llm_monitor_tpu.parallel.mesh import init_multihost

    assert init_multihost() == 0
    assert init_multihost() == 0  # idempotent
