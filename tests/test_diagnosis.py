"""Diagnosis subsystem: constrained-decode property tests, the standing
watcher→LLM pipeline, sessions, and the synthetic crash-loop e2e.

Layers:
  * engine fuzz — every FSM-constrained sample on a real (tiny) engine
    parses as a schema-valid verdict, across temperature/top-k/top-p;
  * pipeline units — burst detector, context assembler, verdict store,
    sessions, all on injected fake clocks;
  * e2e — a fake watcher feeds a crash-loop burst through a real
    MonitorServer (template backend): the verdict must land in
    GET /api/v1/diagnoses AND the /metrics diagnosis gauges.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import pytest

import jax

from k8s_llm_monitor_tpu.diagnosis.grammar import (
    GrammarError, parse_verdict, verdict_fsm)
from k8s_llm_monitor_tpu.diagnosis.pipeline import (
    BurstDetector, ContextAssembler, DiagnosisEventHandler,
    DiagnosisPipeline, VerdictStore)
from k8s_llm_monitor_tpu.diagnosis.session import (
    MAX_TURNS, SessionManager)
from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.monitor.cluster import FakeCluster, seed_demo_cluster
from k8s_llm_monitor_tpu.monitor.config import Config, DiagnosisConfig
from k8s_llm_monitor_tpu.monitor.models import EventInfo
from k8s_llm_monitor_tpu.monitor.server import build_server
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig, InferenceEngine, SamplingParams)
from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

CFG = ModelConfig(name="tiny", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=1e4)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


# -- engine fuzz: every constrained sample parses ----------------------------


@pytest.fixture(scope="module")
def constrained_engine():
    tok = ByteTokenizer()
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    engine = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=2, num_blocks=512, block_size=16,
                     max_blocks_per_seq=128, prefill_buckets=(64, 128, 512),
                     decode_steps_per_iter=4),
        tokenizer=tok,
    )
    engine.set_grammar(verdict_fsm(eos_id=tok.eos_id))
    return engine, tok


@pytest.mark.slow  # real-engine compile; `make diagnose-e2e` runs these
@pytest.mark.parametrize("temperature,top_k,top_p", [
    (0.0, 0, 1.0),     # greedy
    (0.7, 0, 1.0),
    (1.0, 50, 1.0),    # top-k
    (1.3, 0, 0.9),     # top-p
    (0.9, 20, 0.95),   # both filters
    (2.0, 5, 0.8),     # hot + tight filters
])
def test_constrained_samples_always_parse(constrained_engine, temperature,
                                          top_k, top_p):
    """The 100%-schema-valid property: whatever the sampler draws under the
    FSM mask — any temperature, any top-k/top-p — must parse as a verdict."""
    engine, tok = constrained_engine
    prompt = tok.encode("## Question\nwhy is default/web crashlooping?\n")
    results = engine.generate(
        [prompt, prompt],
        SamplingParams(max_tokens=1, temperature=temperature,
                       top_k=top_k, top_p=top_p, constrained=True))
    for res in results:
        assert res.finish_reason in ("eos", "stop", "length"), res
        text = tok.decode(res.token_ids)
        verdict = parse_verdict(text)  # GrammarError == test failure
        assert verdict["severity"] in ("info", "warning", "critical")
        assert verdict["root_cause"]


@pytest.mark.slow  # shares the real-engine fixture above
def test_constrained_and_free_lanes_share_a_batch(constrained_engine):
    """Mixed batches: a FREE-state lane (state 0) must decode unconstrained
    in the same program that masks the constrained lane."""
    engine, tok = constrained_engine
    prompt = tok.encode("status?")
    [free] = engine.generate([prompt], SamplingParams(max_tokens=8))
    [forced] = engine.generate(
        [prompt], SamplingParams(max_tokens=1, constrained=True))
    assert len(free.token_ids) <= 8
    parse_verdict(tok.decode(forced.token_ids))
    with pytest.raises(GrammarError):
        parse_verdict(tok.decode(free.token_ids) or "x")


def test_constrained_submit_requires_grammar():
    tok = ByteTokenizer()
    params = llama.init_params(jax.random.PRNGKey(1), CFG)
    engine = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=1, num_blocks=64, block_size=16,
                     max_blocks_per_seq=32, prefill_buckets=(64,)),
        tokenizer=tok)
    with pytest.raises((ValueError, RuntimeError)):
        engine.generate([tok.encode("x")],
                        SamplingParams(max_tokens=1, constrained=True))


# -- burst detector ----------------------------------------------------------


def test_burst_detector_fires_at_threshold_once():
    clk = FakeClock()
    det = BurstDetector(threshold=3, window_s=60, cooldown_s=120, clock=clk)
    assert not det.observe()
    assert not det.observe()
    assert det.observe()          # third event inside the window fires
    assert det.pending() == 0     # window consumed by the firing
    assert not det.observe()      # needs 3 fresh events again


def test_burst_detector_window_expiry():
    clk = FakeClock()
    det = BurstDetector(threshold=3, window_s=10, cooldown_s=0, clock=clk)
    det.observe()
    clk.tick(11)                  # first event ages out of the window
    det.observe()
    assert not det.observe()      # only 2 inside the window
    assert det.pending() == 2


def test_burst_detector_cooldown_suppresses_refire():
    clk = FakeClock()
    det = BurstDetector(threshold=2, window_s=60, cooldown_s=30, clock=clk)
    det.observe()
    assert det.observe()
    clk.tick(5)
    det.observe()
    assert not det.observe()      # threshold met but inside cooldown
    clk.tick(30)
    # Suppressed events stayed in the window, so the first observation
    # after the cooldown elapses fires immediately.
    assert det.observe()


def test_burst_detector_rejects_bad_threshold():
    with pytest.raises(ValueError):
        BurstDetector(threshold=0)


# -- context assembler -------------------------------------------------------


def test_context_assembler_recency_fallback_and_budget():
    ctx = ContextAssembler(capacity=4, top_k=2, max_chars=200)
    for i in range(6):
        ctx.add(f"event {i}")
    assert len(ctx) == 4                       # ring capacity
    block = ctx.assemble("anything")
    assert "event 4" in block and "event 5" in block
    assert "event 2" not in block              # top_k=2, most recent win
    tight = ContextAssembler(capacity=4, top_k=4, max_chars=40)
    tight.add("x" * 30)
    tight.add("y" * 30)
    assert "y" not in tight.assemble()         # char budget stops the block


def test_context_assembler_empty():
    assert "none observed" in ContextAssembler().assemble("q")


def test_context_assembler_embedding_retrieval():
    import numpy as np

    class KeywordEmbedder:
        """Unit vectors: axis 0 iff 'oom' in text, axis 1 otherwise."""

        def embed(self, texts):
            return np.array([[1.0, 0.0] if "oom" in t else [0.0, 1.0]
                             for t in texts])

    ctx = ContextAssembler(capacity=8, top_k=2, embedder=KeywordEmbedder())
    for i in range(4):
        ctx.add(f"scheduling noise {i}")
    ctx.add("oom killed container web")
    ctx.add("oom killed container db")
    block = ctx.assemble("why the oom kills?")
    assert "oom killed container web" in block
    assert "oom killed container db" in block
    assert "noise" not in block


def test_context_assembler_broken_embedder_falls_back():
    class Boom:
        def embed(self, texts):
            raise RuntimeError("no encoder")

    ctx = ContextAssembler(capacity=8, top_k=1, embedder=Boom())
    ctx.add("old")
    ctx.add("new")
    assert "new" in ctx.assemble("q") and "old" not in ctx.assemble("q")


# -- verdict store -----------------------------------------------------------


def _verdict(sev="warning"):
    return {"severity": sev, "component": "c", "root_cause": "r",
            "recommendation": "f", "confidence": 0.5}


def test_verdict_store_counts_lag_and_order():
    store = VerdictStore(capacity=2)
    store.publish(_verdict("info"), trigger="a", lag_ms=10.0)
    store.publish(_verdict("critical"), trigger="b", lag_ms=20.0)
    store.publish(_verdict("critical"), trigger="c", lag_ms=5.0)
    assert len(store) == 2                      # ring trimmed
    snap = store.snapshot()
    assert [e["trigger"] for e in snap] == ["c", "b"]   # newest first
    assert store.snapshot(limit=1)[0]["trigger"] == "c"
    assert store.counts() == {"info": 1, "warning": 0, "critical": 2}
    assert store.lag_ms() == 5.0
    assert snap[0]["timestamp"]


# -- sessions ----------------------------------------------------------------


def test_session_manager_pins_context_and_mints_ids():
    clk = FakeClock()
    mgr = SessionManager(ttl_s=100, max_sessions=4, clock=clk)
    calls = []

    def ctx():
        calls.append(1)
        return f"CTX-{len(calls)}\n"

    s1, created = mgr.get_or_create("", ctx)
    assert created and len(s1.session_id) == 12
    s2, created = mgr.get_or_create(s1.session_id, ctx)
    assert s2 is s1 and not created
    assert calls == [1]                        # context_fn ran once: pinned
    p1 = s1.build_prompt("PRE\n", "q1")
    s1.record("q1", "a1")
    p2 = s1.build_prompt("PRE\n", "q2")
    assert p1.startswith("PRE\nCTX-1\n")       # byte-identical prefix
    assert p2.startswith(p1[: p1.rindex("## Question")])
    assert "a1" in p2 and p2.endswith("## Answer\n")


def test_session_turn_window_and_answer_truncation():
    clk = FakeClock()
    mgr = SessionManager(clock=clk)
    s, _ = mgr.get_or_create("", lambda: "C\n")
    for i in range(MAX_TURNS + 3):
        s.record(f"q{i}", "a" * 2000)
    prompt = s.build_prompt("P", "next")
    assert "q0" not in prompt and f"q{MAX_TURNS + 2}" in prompt
    assert "a" * 2000 not in prompt            # MAX_ANSWER_CHARS cap


def test_session_ttl_and_lru_eviction():
    clk = FakeClock()
    mgr = SessionManager(ttl_s=50, max_sessions=2, clock=clk)
    a, _ = mgr.get_or_create("a", lambda: "ctx")
    clk.tick(60)
    assert mgr.get("a") is None                # TTL eviction
    mgr.get_or_create("b", lambda: "ctx")
    clk.tick(1)
    mgr.get_or_create("c", lambda: "ctx")
    clk.tick(1)
    mgr.get_or_create("d", lambda: "ctx")      # over cap: LRU ("b") out
    assert mgr.get("b") is None
    assert mgr.get("c") is not None and mgr.get("d") is not None
    assert len(mgr) == 2


# -- pipeline ----------------------------------------------------------------


class StubAnalysis:
    """Just enough of AnalysisEngine for the pipeline machinery."""

    class backend:
        name = "stub-model"

    def __init__(self, fail=False):
        self.fail = fail
        self.questions: list[tuple[str, str]] = []

    def diagnose(self, question, context=None, slo_class="batch"):
        if self.fail:
            raise RuntimeError("engine down")
        self.questions.append((question, context))
        return _verdict("critical")


def test_pipeline_burst_to_verdict_with_coalescing():
    clk = FakeClock()
    analysis = StubAnalysis()
    pipe = DiagnosisPipeline(
        analysis,
        DiagnosisConfig(burst_threshold=2, window_s=60, cooldown_s=0),
        clock=clk)
    for reason in ("BackOff", "BackOff", "OOMKilling", "OOMKilling"):
        pipe.offer(EventInfo(type="Warning", reason=reason, message="m"))
        clk.tick(1)
    assert pipe.triggers_total == 2
    assert pipe.run_pending() == 1             # two triggers, ONE query
    assert pipe.queries_total == 1
    question, context = analysis.questions[0]
    assert "BackOff" in question and "OOMKilling" in question
    assert "BackOff: m" in context             # events reached the prompt
    entry = pipe.store.snapshot()[0]
    assert entry["verdict"]["severity"] == "critical"
    assert entry["model"] == "stub-model"
    assert entry["lag_ms"] >= 0


def test_pipeline_normal_events_feed_context_not_bursts():
    pipe = DiagnosisPipeline(
        StubAnalysis(), DiagnosisConfig(burst_threshold=1), clock=FakeClock())
    pipe.offer(EventInfo(type="Normal", reason="Pulled", message="image"))
    assert pipe.triggers_total == 0 and len(pipe.context) == 1


def test_pipeline_survives_diagnose_errors():
    clk = FakeClock()
    pipe = DiagnosisPipeline(
        StubAnalysis(fail=True),
        DiagnosisConfig(burst_threshold=1, cooldown_s=0), clock=clk)
    pipe.offer(EventInfo(type="Warning", reason="Failed", message="m"))
    assert pipe.run_pending() == 0
    assert pipe.errors_total == 1 and len(pipe.store) == 0


def test_event_handler_formats_and_counts():
    text = DiagnosisEventHandler.format_event(EventInfo(
        type="Warning", reason="BackOff", message="restarting",
        source="kubelet", count=4))
    assert text == "BackOff: restarting (source kubelet) x4"

    class Pod:
        namespace, name, phase = "default", "web-0", "CrashLoopBackOff"

    pipe = DiagnosisPipeline(StubAnalysis(), DiagnosisConfig(),
                             clock=FakeClock())
    pipe.handler.on_pod_update("MODIFIED", Pod())
    pipe.handler.on_pod_update("MODIFIED", type("P", (), {"phase": "Running"}))
    assert len(pipe.context) == 1
    assert "default/web-0 phase=CrashLoopBackOff" in pipe.context.assemble()


# -- synthetic crash-loop e2e ------------------------------------------------


@pytest.fixture(scope="module")
def diagnosis_server():
    cfg = Config()
    cfg.llm.provider = "template"
    cfg.diagnosis.burst_threshold = 3
    cfg.diagnosis.window_s = 60.0
    cfg.diagnosis.cooldown_s = 0.0
    srv = build_server(cfg, backend=seed_demo_cluster(FakeCluster()))
    srv.start()
    yield srv
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=30) as r:
        body = r.read().decode()
        return (json.loads(body) if r.headers["Content-Type"].startswith(
            "application/json") else body)


def _post(srv, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def test_crash_loop_burst_lands_in_api_and_metrics(diagnosis_server):
    """The acceptance path: fake watcher events → burst → constrained
    verdict → GET /api/v1/diagnoses + /metrics gauges."""
    srv = diagnosis_server
    for i in range(4):
        srv.diagnosis.handler.on_event(EventInfo(
            type="Warning", reason="BackOff",
            message=f"Back-off restarting failed container web (try {i})",
            source="kubelet"))
    deadline = time.monotonic() + 10
    payload = _get(srv, "/api/v1/diagnoses")
    while payload["count"] == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
        payload = _get(srv, "/api/v1/diagnoses")
    assert payload["status"] == "success" and payload["count"] >= 1
    entry = payload["diagnoses"][0]
    verdict = entry["verdict"]
    assert set(verdict) == {"severity", "component", "root_cause",
                            "recommendation", "confidence"}
    assert verdict["root_cause"]
    assert "BackOff" in entry["trigger"]
    assert payload["verdicts_total"][verdict["severity"]] >= 1
    assert payload["pipeline"]["queries"] >= 1

    metrics = _get(srv, "/metrics")
    sev = verdict["severity"]
    assert (f'k8s_llm_monitor_diagnosis_verdicts_total{{severity="{sev}"}}'
            in metrics)
    assert "k8s_llm_monitor_diagnosis_pipeline_lag_ms" in metrics
    assert "k8s_llm_monitor_diagnosis_triggers_total" in metrics


def test_diagnoses_limit_param_and_validation(diagnosis_server):
    srv = diagnosis_server
    payload = _get(srv, "/api/v1/diagnoses?limit=1")
    assert len(payload["diagnoses"]) <= 1
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(srv, "/api/v1/diagnoses?limit=abc")
    assert err.value.code == 400


def test_session_queries_over_http(diagnosis_server):
    srv = diagnosis_server
    p1 = _post(srv, "/api/v1/query",
               {"question": "what is wrong?", "session_id": ""})
    sid = p1["result"]["session_id"]
    assert p1["result"]["session_created"] and p1["result"]["turn"] == 1
    p2 = _post(srv, "/api/v1/query",
               {"question": "and the fix?", "session_id": sid})
    assert p2["result"]["session_id"] == sid
    assert p2["result"]["turn"] == 2 and not p2["result"]["session_created"]
    plain = _post(srv, "/api/v1/query", {"question": "ok?"})
    assert "session_id" not in plain["result"]


def test_analyze_root_cause_includes_verdict(diagnosis_server):
    srv = diagnosis_server
    resp = _post(srv, "/api/v1/analyze", {
        "type": "root_cause",
        "parameters": {"target": "default/web", "symptom": "crashloop"}})
    verdict = resp["result"]["verdict"]
    assert verdict["severity"] in ("info", "warning", "critical")
    assert verdict["root_cause"]
