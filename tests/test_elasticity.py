"""Disaggregated prefill/decode fleet + the chaos-hardened elasticity
controller (PR 14).

Unit tests run on scripted role-tagged fakes (next = last+1 mod 997, so
the handoff continuation contract is checkable token by token) and a fake
clock (so every hysteresis gate is provable without sleeping).  Acceptance
runs a real 2-prefill/2-decode in-process fleet through a mixed-class
burst with seeded faults, a scale-up, a drain-based scale-down, and a
role rebalance mid-burst — zero lost/duplicated tokens, interactive tail
bounded (``make chaos-elastic``).
"""

import math
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.fleet import (
    AutoscaleController,
    FleetRouter,
    KubeScaleExecutor,
    LocalPoolExecutor,
    LocalReplica,
    ReplicaRegistry,
    ReplicaStats,
)
from k8s_llm_monitor_tpu.fleet.replica import Replica, ReplicaUnavailable
from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.monitor.config import AutoscaleConfig
from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationResult,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.kv_tier import BlobError
from k8s_llm_monitor_tpu.serving.service import EngineService, RequestHandle

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)
ECFG = dict(max_slots=4, num_blocks=64, block_size=8, max_blocks_per_seq=16,
            prefill_buckets=(16,), max_prefills_per_step=4,
            decode_steps_per_iter=4)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _naive_greedy(params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    return seq[len(prompt):]


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


# ---------------------------------------------------------------------------
# Scripted fakes
# ---------------------------------------------------------------------------


class RoleReplica(Replica):
    """Token-level fake with a role tag and a scriptable KV-migration
    seam.  The "model" is next = last + 1 (mod 997): a continuation
    dispatched with prompt+emitted regenerates the exact sequence, so
    every handoff landing is byte-checkable."""

    supports_tokens = True
    supports_kv_migration = True

    def __init__(self, rid, role="unified", blob=b"KVX1-fake",
                 install_outcome="installed", fetch_exc=None,
                 install_exc=None, refuse_generate=False,
                 refuse_after=None):
        self.replica_id = rid
        self.role = role
        self.blob = blob
        self.install_outcome = install_outcome
        self.fetch_exc = fetch_exc
        self.install_exc = install_exc
        self.refuse_generate = refuse_generate
        self.refuse_after = refuse_after  # serve N calls, refuse the rest
        self.ready = True
        self._draining = False
        self.calls = []
        self.fetches = []
        self.installs = []
        self.closed = False

    def readyz(self):
        return self.ready

    def stats(self):
        return ReplicaStats(total_slots=4, role=self.role,
                            draining=self._draining)

    def drain(self):
        self._draining = True

    @property
    def draining(self):
        return self._draining

    def generate(self, prompt_ids, sampling=None, request_id=None,
                 deadline_s=0.0, slo_class="standard", tenant="public"):
        if self.refuse_generate or (self.refuse_after is not None
                                    and len(self.calls) >= self.refuse_after):
            raise ReplicaUnavailable(f"{self.replica_id}: refusing")
        sampling = sampling or SamplingParams()
        self.calls.append((list(prompt_ids), sampling, request_id))
        h = RequestHandle(request_id or "r", eos_id=-1)
        start = prompt_ids[-1] if prompt_ids else 0
        toks = [(start + 1 + i) % 997 for i in range(sampling.max_tokens)]
        for t in toks:
            h._push([t], None)
        h._push([], GenerationResult(
            request_id=h.request_id, token_ids=list(toks),
            finish_reason="length", ttft_s=0.0, latency_s=0.0))
        return h

    def fetch_prefix(self, token_ids, tenant="public"):
        self.fetches.append(list(token_ids))
        if self.fetch_exc is not None:
            raise self.fetch_exc
        return self.blob

    def install_prefix(self, blob, tenant="public"):
        self.installs.append(blob)
        if self.install_exc is not None:
            raise self.install_exc
        return self.install_outcome

    def close(self):
        self.closed = True


def _registry(*reps, **kw):
    reg = ReplicaRegistry(**kw)
    for r in reps:
        reg.add(r)
    reg.refresh()
    return reg


# ---------------------------------------------------------------------------
# Tentpole 1: role-aware dispatch + the handoff ladder
# ---------------------------------------------------------------------------


def test_disaggregated_dispatch_prefill_then_decode():
    """Happy path: the request prefills (1-token budget) on the prefill
    replica, the finished prefix moves to the decode replica, and the
    continuation streams from there — the caller sees one seamless
    stream."""
    p = RoleReplica("p", role="prefill")
    d = RoleReplica("d", role="decode")
    router = FleetRouter(_registry(p, d), policy="round_robin")
    h = router.submit([5], SamplingParams(max_tokens=6))
    toks = list(h.stream(timeout=10))
    res = h.result(timeout=10)
    assert toks == res.token_ids == [6, 7, 8, 9, 10, 11]
    assert res.finish_reason == "length"
    # Prefill leg: 1-token budget, attempt id -a0.
    prompt, sampling, rid = p.calls[0]
    assert prompt == [5] and sampling.max_tokens == 1
    assert rid.endswith("-a0")
    # Handoff: prefix fetched from P (prompt + first token), installed on
    # D, continuation carries the folded prompt and the remaining budget.
    assert p.fetches == [[5, 6]]
    assert d.installs == [b"KVX1-fake"]
    prompt, sampling, rid = d.calls[0]
    assert prompt == [5, 6] and sampling.max_tokens == 5
    assert rid.endswith("-d0")
    assert router.counters()["handoffs"] == {"decode": 1}


def test_single_token_request_skips_handoff():
    p = RoleReplica("p", role="prefill")
    d = RoleReplica("d", role="decode")
    router = FleetRouter(_registry(p, d), policy="round_robin")
    res = router.submit([5], SamplingParams(max_tokens=1)).result(timeout=10)
    assert res.token_ids == [6]
    assert router.counters()["handoffs"] == {}
    assert d.installs == []


def test_missing_role_dispatches_unified():
    """A fleet without decode replicas has nowhere to hand off: the full
    budget dispatches in one leg, exactly the pre-disaggregation path."""
    p0 = RoleReplica("p0", role="prefill")
    p1 = RoleReplica("p1", role="prefill")
    router = FleetRouter(_registry(p0, p1), policy="round_robin")
    res = router.submit([5], SamplingParams(max_tokens=4)).result(timeout=10)
    assert res.token_ids == [6, 7, 8, 9]
    assert router.counters()["handoffs"] == {}
    assert len(p0.calls) + len(p1.calls) == 1
    _, sampling, _ = (p0.calls or p1.calls)[0]
    assert sampling.max_tokens == 4


@pytest.mark.parametrize("cause,setup", [
    ("nospace", dict(install_outcome="nospace")),
    ("incompatible", dict(install_outcome="incompatible")),
    ("owner_down", dict(fetch_exc=ReplicaUnavailable("owner died"))),
    ("torn", dict(install_exc=BlobError("torn KVX1 frame"))),
    ("install_timeout", dict(install_exc=ReplicaUnavailable("timed out"))),
    ("miss", dict(blob=None)),  # owner's export comes back empty
    ("error", dict(install_exc=ValueError("unexpected"))),
])
def test_handoff_failure_degrades_to_local_decode(cause, setup):
    """Every handoff failure mode lands the continuation back on the
    prefill replica (its KV still holds the prefix) with the exact same
    tokens — a failed handoff is a perf event, never a dropped request."""
    # fetch_exc and blob describe the OWNER side of the transfer.
    p_kw = {k: setup.pop(k) for k in ("fetch_exc", "blob") if k in setup}
    p = RoleReplica("p", role="prefill", **p_kw)
    d = RoleReplica("d", role="decode", **setup)
    router = FleetRouter(_registry(p, d), policy="round_robin")
    h = router.submit([5], SamplingParams(max_tokens=6))
    toks = list(h.stream(timeout=10))
    res = h.result(timeout=10)
    assert toks == res.token_ids == [6, 7, 8, 9, 10, 11], cause
    assert res.finish_reason == "length"
    # Continuation landed locally on P with the folded prompt.
    assert len(p.calls) == 2
    prompt, sampling, rid = p.calls[1]
    assert prompt == [5, 6] and sampling.max_tokens == 5
    assert rid.endswith("-l0")
    assert d.calls == []
    hand = router.counters()["handoffs"]
    assert hand == {cause: 1, "local": 1}
    assert router.counters()["failed"] == 0


def test_owner_death_mid_transfer_replays_elsewhere():
    """Rung 3: the prefill replica dies between its leg and the handoff —
    local decode is impossible, so the continuation replays on whatever
    is left, still token-exact (the replay re-prefills)."""
    p = RoleReplica("p", role="prefill",
                    fetch_exc=ReplicaUnavailable("owner died"),
                    refuse_after=1)  # serves the prefill leg, then dies
    d = RoleReplica("d", role="decode")
    router = FleetRouter(_registry(p, d), policy="round_robin")
    h = router.submit([5], SamplingParams(max_tokens=6))
    res = h.result(timeout=10)
    assert res.token_ids == [6, 7, 8, 9, 10, 11]
    # P refused the local rung; the replay rung landed on D as a plain
    # re-prefill (no install — the handoff transfer already failed).
    prompt, sampling, rid = d.calls[0]
    assert prompt == [5, 6] and sampling.max_tokens == 5
    assert rid.endswith("-f0")
    hand = router.counters()["handoffs"]
    assert hand.get("owner_down") == 1 and hand.get("replay") == 1


def test_decode_dispatch_refused_degrades_local():
    """Install succeeds but D refuses the continuation dispatch: the blob
    landed for nothing, the stream still finishes locally on P."""
    p = RoleReplica("p", role="prefill")
    d = RoleReplica("d", role="decode", refuse_generate=True)
    router = FleetRouter(_registry(p, d), policy="round_robin")
    res = router.submit([5], SamplingParams(max_tokens=6)).result(timeout=10)
    assert res.token_ids == [6, 7, 8, 9, 10, 11]
    assert d.installs and not d.calls
    hand = router.counters()["handoffs"]
    assert hand.get("dispatch_failed") == 1 and hand.get("local") == 1


# ---------------------------------------------------------------------------
# Tentpole 2: membership lifecycle (drain, removal GC)
# ---------------------------------------------------------------------------


def test_draining_replica_takes_no_new_dispatches():
    a = RoleReplica("a")
    b = RoleReplica("b")
    reg = _registry(a, b)
    router = FleetRouter(reg, policy="round_robin")
    a.drain()
    reg.refresh()
    snap = reg.snapshot()
    assert snap["a"]["draining"] is True and snap["b"]["draining"] is False
    assert [c.replica_id for c in reg.candidates()] == ["b"]
    for _ in range(4):
        router.submit([5], SamplingParams(max_tokens=2)).result(timeout=10)
    assert len(a.calls) == 0 and len(b.calls) == 4


def test_draining_owner_loses_rendezvous_affinity():
    """A draining replica must not win the rendezvous hash: the prompt's
    home moves to a live replica the moment the drain is announced, not
    when the pod dies."""
    reps = [RoleReplica(f"r{i}") for i in range(3)]
    reg = _registry(*reps)
    router = FleetRouter(reg, policy="affinity", affinity_prefix_tokens=8)
    prompt = [11, 12, 13, 14]
    router.submit(prompt, SamplingParams(max_tokens=2)).result(timeout=10)
    owner = next(r for r in reps if r.calls)
    owner.drain()
    reg.refresh()
    router.submit(prompt, SamplingParams(max_tokens=2)).result(timeout=10)
    assert len(owner.calls) == 1, "draining owner won affinity again"
    new_owner = next(r for r in reps if r is not owner and r.calls)
    assert not new_owner.draining


def test_drain_sweep_exports_prefixes_within_budget():
    """Announcing a drain triggers ONE bounded sweep: up to
    drain_sweep_budget recently-served prefixes move from the draining
    owner to their new rendezvous owners, so the warm state survives the
    scale-down instead of dying with the pod."""
    a = RoleReplica("a")
    b = RoleReplica("b")
    reg = _registry(a, b)
    router = FleetRouter(reg, policy="affinity", affinity_prefix_tokens=8,
                         drain_sweep_budget=3)
    rng = np.random.default_rng(7)
    for _ in range(12):
        prompt = list(rng.integers(3, 300, size=6))
        router.submit(prompt, SamplingParams(max_tokens=2)).result(timeout=10)
    owned_by_a = len(a.calls)
    assert owned_by_a > 0 and len(b.calls) > 0  # both own some prefixes
    a.drain()
    reg.refresh()  # rising drain edge fires the sweep
    c = router.counters()
    moved = c["drain_sweeps"]
    assert 1 <= moved <= 3, "sweep ignored its budget"
    assert len(b.installs) == moved  # every move landed on the survivor
    assert len(a.fetches) == moved


def test_remove_gc_forgets_breaker_inflight_and_prefixes():
    a = RoleReplica("a")
    b = RoleReplica("b")
    reg = _registry(a, b)
    router = FleetRouter(reg, policy="affinity", affinity_prefix_tokens=8)
    rng = np.random.default_rng(3)
    for _ in range(6):
        prompt = list(rng.integers(3, 300, size=6))
        router.submit(prompt, SamplingParams(max_tokens=2)).result(timeout=10)
    assert any(rid == "a" for _, rid, _t in router._recent_prefixes.values())
    reg.remove("a")
    assert "a" not in reg.snapshot()
    assert reg.get("a") is None  # breaker + inflight died with the entry
    assert all(rid != "a" for _, rid, _t in router._recent_prefixes.values()), \
        "removed replica still owns prefix-memory entries"


def test_scraper_evicts_departed_target_series():
    """Probe-leak GC: when a replica leaves the fleet, its series leave
    the store — fleet_scrape_age_s for the dead replica goes silent
    instead of alarming as stale forever."""
    from k8s_llm_monitor_tpu.monitor.config import TelemetryConfig
    from k8s_llm_monitor_tpu.observability.signals import SignalScraper

    scraper = SignalScraper(cfg=TelemetryConfig())
    row = {"probe_age_s": 0.1, "queue_by_class": {}, "ttft_ema_by_class": {},
           "queue_tokens": 0, "brownout": 0, "busy_slots": 0}
    scraper._sample_fleet({"r0": dict(row), "r1": dict(row)}, 5.0, 100.0)
    assert {"r0", "r1"} <= set(scraper.signals()["targets"])
    age = scraper.store.last("scrape_age_s", {"replica": "r1"})
    assert math.isfinite(age)

    scraper._sample_fleet({"r0": dict(row)}, 5.0, 105.0)  # r1 departed
    assert "r1" not in scraper.signals()["targets"]
    assert not math.isfinite(
        scraper.store.last("scrape_age_s", {"replica": "r1"}))
    assert scraper.counters()["evicted_targets_total"] == 1
    # The survivor keeps its series untouched.
    assert math.isfinite(scraper.store.last("scrape_age_s",
                                            {"replica": "r0"}))


# ---------------------------------------------------------------------------
# Tentpole 3: AutoscaleController hysteresis gates (fake clock)
# ---------------------------------------------------------------------------


class StubSignals:
    def __init__(self, targets=None):
        self.targets = targets or {}

    def signals(self):
        return {"targets": self.targets}


def _derived(hint="steady", anomalies=(), stale=False):
    return {"scale_hint": hint, "anomalies": list(anomalies), "stale": stale}


class StubExecutor:
    def __init__(self, counts):
        self.counts = dict(counts)
        self.calls = []
        self.fail = False

    def current_replicas(self, role):
        return self.counts.get(role, 0)

    def scale(self, role, replicas, dry_run=False):
        self.calls.append((role, replicas, dry_run))
        if self.fail:
            raise RuntimeError("injected executor failure")
        if not dry_run:
            self.counts[role] = replicas


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _controller(signals, executor, clock, registry=None, **cfg_over):
    cfg = AutoscaleConfig(enabled=True, cooldown_s=30.0,
                          scale_down_dwell_s=60.0, flap_window_s=120.0,
                          flap_max_flips=3, min_decode=1, max_decode=4,
                          min_prefill=1, max_prefill=4,
                          min_unified=1, max_unified=4)
    for k, v in cfg_over.items():
        setattr(cfg, k, v)
    return AutoscaleController(signals, executor, cfg, registry=registry,
                               clock=clock)


def _role_registry():
    return _registry(RoleReplica("p0", role="prefill"),
                     RoleReplica("d0", role="decode"))


def test_scale_up_is_immediate_and_dry_run_first():
    clock = FakeClock()
    ex = StubExecutor({"decode": 2})
    ctl = _controller(StubSignals({"d0": _derived("up")}), ex, clock,
                      registry=_role_registry())
    ctl.tick()
    assert ex.calls == [("decode", 3, True), ("decode", 3, False)]
    assert ex.counts["decode"] == 3
    assert ctl.actions_total[("decode", "up", "applied")] == 1


def test_anomaly_flags_read_as_up():
    clock = FakeClock()
    ex = StubExecutor({"decode": 1})
    ctl = _controller(
        StubSignals({"d0": _derived("steady", anomalies=["ttft_breach"])}),
        ex, clock, registry=_role_registry())
    ctl.tick()
    assert ex.counts["decode"] == 2


def test_stale_targets_are_no_evidence():
    clock = FakeClock()
    ex = StubExecutor({"decode": 2})
    ctl = _controller(
        StubSignals({"d0": _derived("up", stale=True)}), ex, clock,
        registry=_role_registry())
    ctl.tick()
    assert ex.calls == []  # stale "up" must not scale anything


def test_cooldown_gate_blocks_back_to_back_actions():
    clock = FakeClock()
    ex = StubExecutor({"decode": 1})
    sig = StubSignals({"d0": _derived("up")})
    ctl = _controller(sig, ex, clock, registry=_role_registry())
    ctl.tick()
    assert ex.counts["decode"] == 2
    calls_after_first = len(ex.calls)
    clock.advance(5.0)  # inside the 30s cooldown
    ctl.tick()
    assert len(ex.calls) == calls_after_first, "acted during cooldown"
    assert ctl.actions_total[("decode", "up", "refused_cooldown")] == 1
    clock.advance(30.0)  # past cooldown
    ctl.tick()
    assert ex.counts["decode"] == 3


def test_scale_down_requires_continuous_dwell():
    clock = FakeClock()
    ex = StubExecutor({"decode": 3})
    sig = StubSignals({"d0": _derived("down")})
    ctl = _controller(sig, ex, clock, registry=_role_registry())
    ctl.tick()
    assert ex.calls == []  # dwell starts now, nothing happens yet
    assert ctl.actions_total[("decode", "down", "refused_dwell")] == 1
    clock.advance(30.0)  # half the 60s dwell
    ctl.tick()
    assert ex.calls == []
    # The hints wobble back to steady: the dwell must restart from zero.
    sig.targets = {"d0": _derived("steady")}
    clock.advance(10.0)
    ctl.tick()
    sig.targets = {"d0": _derived("down")}
    clock.advance(40.0)  # would have satisfied the ORIGINAL dwell
    ctl.tick()
    assert ex.calls == [], "dwell did not reset on interruption"
    clock.advance(61.0)  # full dwell, continuous this time
    ctl.tick()
    assert ex.counts["decode"] == 2
    assert ctl.actions_total[("decode", "down", "applied")] == 1


def test_minmax_clamps_refuse_at_bounds():
    clock = FakeClock()
    ex = StubExecutor({"decode": 4})
    ctl = _controller(StubSignals({"d0": _derived("up")}), ex, clock,
                      registry=_role_registry())
    ctl.tick()
    assert ex.calls == []  # at max already: no dry-run, no patch
    assert ctl.actions_total[("decode", "up", "refused_minmax")] == 1

    ex2 = StubExecutor({"decode": 1})
    ctl2 = _controller(StubSignals({"d0": _derived("down")}), ex2, clock,
                       registry=_role_registry(), scale_down_dwell_s=0.0)
    ctl2.tick()
    assert ex2.calls == []  # at min already
    assert ctl2.actions_total[("decode", "down", "refused_minmax")] == 1


def test_breaker_opens_on_executor_failure_then_refuses():
    clock = FakeClock()
    ex = StubExecutor({"decode": 1})
    ex.fail = True
    ctl = _controller(StubSignals({"d0": _derived("up")}), ex, clock,
                      registry=_role_registry(), cooldown_s=0.0,
                      breaker_failures=2, breaker_cooldown_s=300.0)
    ctl.tick()
    clock.advance(1.0)
    ctl.tick()
    assert ctl.actions_total[("decode", "up", "error")] == 2
    assert ctl.breaker.state == "open"
    calls_when_open = len(ex.calls)
    clock.advance(1.0)
    ctl.tick()
    # The refusal happened BEFORE any executor call — an open breaker
    # means the apiserver is already hurting; don't touch it.
    assert len(ex.calls) == calls_when_open
    assert ctl.actions_total[("decode", "up", "refused_breaker")] == 1


def test_flap_damping_freezes_oscillating_role():
    clock = FakeClock()
    ex = StubExecutor({"decode": 2})
    sig = StubSignals({"d0": _derived("up")})
    ctl = _controller(sig, ex, clock, registry=_role_registry(),
                      cooldown_s=0.0, flap_max_flips=2, flap_window_s=500.0)
    for i in range(6):  # up/down/up/down/... : a flapping signal
        sig.targets = {"d0": _derived("up" if i % 2 == 0 else "down")}
        ctl.tick()
        clock.advance(1.0)
    assert any(o == "refused_flap"
               for (_, _, o) in ctl.actions_total), ctl.actions_total
    frozen_at = ex.counts["decode"]
    sig.targets = {"d0": _derived("up")}
    ctl.tick()
    assert ex.counts["decode"] == frozen_at, "acted while flap-frozen"


def test_rebalance_moves_capacity_between_roles():
    clock = FakeClock()
    ex = StubExecutor({"prefill": 3, "decode": 1})
    sig = StubSignals({"p0": _derived("down"), "d0": _derived("up")})
    ctl = _controller(sig, ex, clock, registry=_role_registry(),
                      scale_down_dwell_s=10.0)
    ctl.tick()  # opposing desires detected; down-dwell still gates it
    assert ex.counts == {"prefill": 3, "decode": 1}
    clock.advance(11.0)
    ctl.tick()
    assert ex.counts == {"prefill": 2, "decode": 2}
    assert ctl.actions_total[("decode", "rebalance", "applied")] == 1
    assert ctl.actions_total[("prefill", "rebalance", "applied")] == 1


def test_tick_returns_cycle_events_and_snapshot_is_json_safe():
    import json

    clock = FakeClock()
    ex = StubExecutor({"decode": 1})
    ctl = _controller(StubSignals({"d0": _derived("up")}), ex, clock,
                      registry=_role_registry())
    events = ctl.tick()
    assert [e["outcome"] for e in events] == ["applied"]
    snap = ctl.snapshot()
    json.dumps(snap)
    assert snap["actions_total"] == {"decode/up/applied": 1}
    assert snap["breaker_state"] == "closed"


# ---------------------------------------------------------------------------
# Executors
# ---------------------------------------------------------------------------


def test_kube_executor_maps_roles_to_statefulsets():
    class FakeBackend:
        def __init__(self):
            self.calls = []

        def get_statefulset_scale(self, ns, name):
            self.calls.append(("get", ns, name))
            return {"spec": {"replicas": 2}}

        def scale_statefulset(self, ns, name, replicas, dry_run=False):
            self.calls.append(("scale", ns, name, replicas, dry_run))

    backend = FakeBackend()
    ex = KubeScaleExecutor(backend, AutoscaleConfig())
    assert ex.current_replicas("prefill") == 2
    ex.scale("decode", 3, dry_run=True)
    ex.scale("unified", 1)
    assert backend.calls == [
        ("get", "monitoring", "engine-prefill"),
        ("scale", "monitoring", "engine-decode", 3, True),
        ("scale", "monitoring", "engine", 1, False),
    ]


def test_kube_rest_scale_patches_scale_subresource(monkeypatch):
    from k8s_llm_monitor_tpu.monitor.kube_rest import KubeRestBackend

    backend = KubeRestBackend("http://apiserver:6443")
    seen = []

    def fake_request(path, params=None, **kw):
        seen.append((path, params, kw))
        return {"spec": {"replicas": 3}}

    monkeypatch.setattr(backend, "_request", fake_request)
    assert backend.get_statefulset_scale("ns", "engine-decode")[
        "spec"]["replicas"] == 3
    backend.scale_statefulset("ns", "engine-decode", 3, dry_run=True)
    backend.scale_statefulset("ns", "engine-decode", 3)
    path = "/apis/apps/v1/namespaces/ns/statefulsets/engine-decode/scale"
    assert seen[0][0] == path
    assert seen[1] == (path, {"dryRun": "All"}, dict(
        method="PATCH", body={"spec": {"replicas": 3}},
        content_type="application/merge-patch+json"))
    assert seen[2][1] is None  # the real patch carries no dryRun


def test_local_pool_executor_spawns_drains_and_reaps():
    reg = ReplicaRegistry()
    spawned = []

    def factory(role, rid):
        r = RoleReplica(rid, role=role)
        spawned.append(r)
        return r

    ex = LocalPoolExecutor(reg, factory)
    seed = RoleReplica("decode-0", role="decode")
    reg.add(seed)
    reg.refresh()
    ex.adopt("decode", seed)
    assert ex.current_replicas("decode") == 1

    ex.scale("decode", 2)  # up: spawn + register + probe
    assert len(spawned) == 1 and spawned[0].role == "decode"
    assert ex.current_replicas("decode") == 2
    assert spawned[0].replica_id in reg.snapshot()
    assert reg.snapshot()[spawned[0].replica_id]["ready"] is True

    ex.scale("decode", 1)  # down: newest drains, nothing is removed yet
    assert spawned[0].draining is True and not seed.draining
    assert ex.current_replicas("decode") == 1
    assert spawned[0].replica_id in reg.snapshot()

    removed = ex.reap()  # idle: safe to remove now
    assert removed == [spawned[0].replica_id]
    assert spawned[0].closed is True
    assert spawned[0].replica_id not in reg.snapshot()

    ex.scale("decode", 1, dry_run=True)  # dry-run never mutates the pool
    assert ex.current_replicas("decode") == 1


def test_reap_waits_for_inflight_streams():
    reg = ReplicaRegistry()
    ex = LocalPoolExecutor(reg, lambda role, rid: RoleReplica(rid, role=role))
    rep = RoleReplica("decode-0", role="decode")
    reg.add(rep)
    reg.refresh()
    ex.adopt("decode", rep)
    reg.note_dispatch("decode-0")  # a stream is mid-flight
    ex.scale("decode", 0)
    assert rep.draining
    assert ex.reap() == []  # refuses while inflight > 0
    assert "decode-0" in reg.snapshot()
    reg.note_done("decode-0", ok=True)
    assert ex.reap() == ["decode-0"]
    assert "decode-0" not in reg.snapshot()


# ---------------------------------------------------------------------------
# Acceptance: real engines (make chaos-elastic)
# ---------------------------------------------------------------------------


def _role_fleet(params, n_prefill=1, n_decode=1, prefix="", ecfg=None):
    reps = []
    for i in range(n_prefill):
        eng = InferenceEngine(CFG, params, EngineConfig(**(ecfg or ECFG)),
                              eos_id=-1)
        reps.append(LocalReplica(f"{prefix}prefill-{i}",
                                 service=EngineService(eng), role="prefill"))
    for i in range(n_decode):
        eng = InferenceEngine(CFG, params, EngineConfig(**(ecfg or ECFG)),
                              eos_id=-1)
        reps.append(LocalReplica(f"{prefix}decode-{i}",
                                 service=EngineService(eng), role="decode"))
    reg = ReplicaRegistry()
    for r in reps:
        reg.add(r)
    reg.refresh()
    return reg, reps


@pytest.mark.slow  # boots two live engines; covered by make chaos-elastic
def test_real_handoff_streams_byte_exact(params):
    """End-to-end disaggregation on live engines: prefill leg on P, blob
    export/install, decode continuation on D — greedy-byte-exact vs the
    single-model oracle, and the KV actually moved (D gets a prefix
    hit)."""
    reg, reps = _role_fleet(params)
    router = FleetRouter(reg, policy="affinity", affinity_prefix_tokens=16)
    rng = np.random.default_rng(17)
    prompt = list(rng.integers(3, 300, size=24))  # 3 full blocks: exportable
    try:
        h = router.submit(prompt, SamplingParams(max_tokens=8))
        toks = list(h.stream(timeout=120))
        res = h.result(timeout=120)
        assert res.finish_reason == "length"
        assert toks == res.token_ids == _naive_greedy(params, prompt, 8)
        hand = router.counters()["handoffs"]
        assert hand.get("decode") == 1, hand
        dec = next(r for r in reps if r.role == "decode")
        assert dec.service.engine.prefix_cache.hits >= 1, \
            "decode continuation never hit the installed prefix"
    finally:
        for r in reps:
            r.close()


@pytest.mark.slow
@pytest.mark.chaos  # covered by make chaos-elastic
@pytest.mark.parametrize("cause,breakage", [
    ("nospace", lambda p, d: ("install", lambda blob: "nospace")),
    ("incompatible", lambda p, d: ("install", lambda blob: "incompatible")),
    ("owner_down", lambda p, d: ("fetch", None)),
    ("torn", lambda p, d: ("truncate", None)),
    ("install_timeout",
     lambda p, d: ("install", None)),
])
def test_real_install_failure_degrades_local_no_leak(params, cause,
                                                     breakage):
    """Satellite (c) on live engines: break the install path each known
    way; the stream must degrade to local decode on the prefill replica
    with greedy-byte-exact output and no leaked KV blocks."""
    reg, reps = _role_fleet(params)
    p = next(r for r in reps if r.role == "prefill")
    d = next(r for r in reps if r.role == "decode")
    kind, fn = breakage(p, d)
    if kind == "install":
        if fn is None:
            d.install_prefix = lambda blob: (_ for _ in ()).throw(
                ReplicaUnavailable("install timed out"))
        else:
            d.install_prefix = fn
    elif kind == "fetch":
        p.fetch_prefix = lambda ids: (_ for _ in ()).throw(
            ReplicaUnavailable("owner died mid-transfer"))
    elif kind == "truncate":
        real_fetch = p.fetch_prefix
        p.fetch_prefix = lambda ids: (real_fetch(ids) or b"KVX1xxxx")[:-7]
    router = FleetRouter(reg, policy="affinity", affinity_prefix_tokens=16)
    rng = np.random.default_rng(23)
    prompt = list(rng.integers(3, 300, size=24))
    try:
        res = router.submit(prompt,
                            SamplingParams(max_tokens=8)).result(timeout=120)
        assert res.finish_reason == "length"
        assert res.token_ids == _naive_greedy(params, prompt, 8), cause
        hand = router.counters()["handoffs"]
        assert hand.get(cause) == 1 and hand.get("local") == 1, (cause, hand)
        assert _wait(lambda: p.service.engine.active_slots == 0, timeout=30)
        free_once = p.service.engine.allocator.free_blocks
        # Leak probe: the SAME degraded request again reaches the same
        # allocator steady state — a per-request block leak cannot.
        res2 = router.submit(prompt,
                             SamplingParams(max_tokens=8)).result(timeout=120)
        assert res2.token_ids == res.token_ids
        assert _wait(lambda: p.service.engine.active_slots == 0, timeout=30)
        assert p.service.engine.allocator.free_blocks == free_once, \
            f"{cause}: degraded handoff leaked KV blocks"
    finally:
        for r in reps:
            r.close()


@pytest.mark.slow
@pytest.mark.chaos  # THE acceptance gate: make chaos-elastic
def test_chaos_elastic_burst_scaleup_drain_rebalance(params):
    """2-prefill/2-decode fleet under a 3x mixed-class burst with seeded
    faults, while the elasticity controller scales UP, scales DOWN with a
    drain, and rebalances a role mid-burst.  Every stream finishes
    greedy-byte-exact (zero lost/dup tokens), no interactive request is
    shed, and the interactive tail stays bounded (p99 <= 2x p50)."""
    reg, reps = _role_fleet(params, n_prefill=2, n_decode=2)
    router = FleetRouter(reg, policy="affinity", affinity_prefix_tokens=16,
                         max_failovers=2)
    pool = {r: ("prefill" if r.role == "prefill" else "decode")
            for r in reps}

    warmed = set()

    def _warm(rep):
        # JIT-compile both prefill paths this burst exercises: a fresh
        # full prefill, and the suffix-only prefill a handoff continuation
        # runs against an installed/cached prefix (the second generate
        # continues one token past the now-cached warm prompt).
        w = list(range(3, 19))
        first = rep.generate(w, SamplingParams(max_tokens=2)).result(
            timeout=120)
        rep.generate(w + first.token_ids[:1],
                     SamplingParams(max_tokens=2)).result(timeout=120)
        warmed.add(rep.replica_id)

    def factory(role, rid):
        eng = InferenceEngine(CFG, params, EngineConfig(**ECFG), eos_id=-1)
        rep = LocalReplica(rid, service=EngineService(eng), role=role)
        # Spawn warm: compile before the registry ever offers this replica
        # a dispatch, so mid-burst elasticity never parks an interactive
        # continuation behind a compile.
        _warm(rep)
        pool[rep] = role
        return rep

    executor = LocalPoolExecutor(reg, factory)
    for rep, role in list(pool.items()):
        executor.adopt(role, rep)
    sig = StubSignals({})
    ctl = AutoscaleController(
        sig, executor,
        AutoscaleConfig(enabled=True, cooldown_s=0.05,
                        scale_down_dwell_s=0.2, min_prefill=1, max_prefill=3,
                        min_decode=1, max_decode=4, flap_max_flips=50),
        registry=reg)

    rng = np.random.default_rng(41)
    # Fresh prompts every round: each burst pays its own prefills and
    # handoffs, so the three rounds' latency samples are comparable (a
    # repeated prompt would ride the prefix cache and skew the tail gate).
    all_prompts = [list(rng.integers(3, 300, size=16)) for _ in range(36)]
    oracle = {tuple(p): _naive_greedy(params, p, 8) for p in all_prompts}
    classes = ["interactive", "standard", "batch"]
    lat = {c: [] for c in classes}
    results = []

    def warm_all():
        # First generate on a fresh engine pays JIT compile; keep that out
        # of the latency sample (and off the mid-burst critical path).
        for rep in list(pool):
            if rep.replica_id not in warmed and not rep.draining:
                _warm(rep)

    def submit_round(rnd):
        handles = []
        for i, p in enumerate(all_prompts[rnd * 12:(rnd + 1) * 12]):
            cls = classes[i % 3]
            t0 = time.monotonic()
            h = router.submit(list(p), SamplingParams(max_tokens=8),
                              slo_class=cls)
            handles.append((p, cls, t0, h))
        return handles

    def collect(tag, handles):
        # One reader thread per stream: a slow neighbour must not inflate
        # the recorded latency of a stream that finished early.
        rows, errors = [], []

        def consume(p, cls, t0, h):
            try:
                toks = list(h.stream(timeout=240))
                res = h.result(timeout=240)
                rows.append((p, cls, time.monotonic() - t0, toks, res))
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append((tag, cls, exc))

        threads = [threading.Thread(target=consume, args=hc, daemon=True)
                   for hc in handles]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        assert len(rows) == len(handles), f"{tag}: lost streams"
        for p, cls, dt, toks, res in rows:
            lat[cls].append(dt)
            assert res.finish_reason == "length", \
                (tag, res.finish_reason, res.error)
            assert toks == res.token_ids == oracle[tuple(p)], \
                f"{tag}: lost or duplicated tokens"
            results.append(res)

    try:
        # Burst 1: baseline, with seeded engine faults mid-stream.
        warm_all()
        get_injector().arm("lane_eviction", rate=0.2, times=3)
        collect("round1-faults", submit_round(0))

        # Burst 2 with a scale-up landing mid-burst: decode screams, the
        # controller spawns a (pre-warmed) replica while streams run.
        h2 = submit_round(1)
        sig.targets = {"decode-0": _derived("up",
                                            anomalies=["queue_growth"])}
        ctl.tick()
        new_decode = [r for r in pool if r.replica_id.startswith(
            "decode-auto-")]
        assert len(new_decode) == 1, "scale-up never spawned"
        assert ctl.actions_total[("decode", "up", "applied")] == 1
        collect("round2-scaled-up", h2)

        # Burst 3 with a drain-based scale-down AND a role rebalance
        # mid-burst.  Draining replicas finish their in-flight streams —
        # they just stop winning new dispatches.
        h3 = submit_round(2)
        sig.targets = {"decode-0": _derived("down")}
        deadline = time.monotonic() + 10.0
        while (("decode", "down", "applied") not in ctl.actions_total
               and time.monotonic() < deadline):
            ctl.tick()
            time.sleep(0.05)
        assert ctl.actions_total.get(("decode", "down", "applied")) == 1
        draining = [r for r in pool if r.role == "decode" and r.draining]
        assert len(draining) == 1
        assert all(c.replica_id != draining[0].replica_id
                   for c in reg.candidates())

        # Role rebalance while the same burst is still streaming.
        sig.targets = {"prefill-0": _derived("down"),
                       "decode-0": _derived("up")}
        deadline = time.monotonic() + 10.0
        while (("decode", "rebalance", "applied") not in ctl.actions_total
               and time.monotonic() < deadline):
            ctl.tick()
            time.sleep(0.05)
        assert ctl.actions_total.get(("decode", "rebalance", "applied")) == 1
        collect("round3-drain-rebalance", h3)

        # Drained replicas get reaped once their streams finished.
        assert _wait(lambda: bool(executor.reap()) or not any(
            r.draining and r.replica_id in reg.snapshot() for r in pool),
            timeout=30)

        # Zero lost requests, zero interactive sheds, handoffs happened.
        assert len(results) == 36
        assert router.counters()["sheds"] == 0
        hand = router.counters()["handoffs"]
        # Nearly every stream disaggregated (a fault-triggered failover
        # legitimately skips the handoff), and real handoffs landed.
        assert sum(hand.get(k, 0)
                   for k in ("decode", "local", "replay")) >= 30, hand
        assert hand.get("decode", 0) >= 1, hand
        # Tail discipline: interactive p99 within 2x median.
        inter = sorted(lat["interactive"])
        p50 = inter[len(inter) // 2]
        p99 = inter[min(len(inter) - 1, int(len(inter) * 0.99))]
        assert p99 <= 2.0 * p50, (p50, p99)
    finally:
        get_injector().reset()
        for r in list(pool):
            r.close()
