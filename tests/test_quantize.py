"""int8 weight-only quantization: roundtrip bounds, logits parity vs the
bf16/f32 model, engine generation on quantized params, and TP sharding of
the quantized pytree.

This is the path that serves the real Llama-3-8B target on a 16 GB chip
(VERDICT r3 item 1); the parity tolerances here are the "within tolerance"
contract for that claim.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.utils import quantize as qz

CFG = ModelConfig(name="t", vocab_size=256, hidden_size=64,
                  intermediate_size=128, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)

CFG_TIED = ModelConfig(name="t-tied", vocab_size=256, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, dtype="float32",
                       rope_theta=10_000.0, tie_embeddings=True)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = rng.normal(0, 0.05, size=(96, 48)).astype(np.float32)
    w_q, scale = qz.quantize_array(w, axis=0)
    assert w_q.dtype == np.int8 and scale.shape == (48,)
    deq = w_q.astype(np.float32) * scale[None, :]
    # Symmetric 8-bit: error per element <= scale/2 = amax/254.
    amax = np.abs(w).max(axis=0)
    assert np.all(np.abs(deq - w) <= amax[None, :] / 254 + 1e-7)


def test_quantized_linear_matches_dequantized(params):
    """(x @ w_q) * scale must equal x @ (w_q * scale) — the algebra the
    fused dequant relies on."""
    layer = params["layers"][0]["gate"]
    qp = qz.quantize_linear(layer)
    x = jax.random.normal(jax.random.PRNGKey(1), (3, CFG.hidden_size),
                          jnp.float32)
    fused = llama._linear(qp, x)
    explicit = x @ (qp["kernel_q"].astype(jnp.float32)
                    * qp["scale"][None, :])
    np.testing.assert_allclose(np.asarray(fused), np.asarray(explicit),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("cfg", [CFG, CFG_TIED], ids=["untied", "tied"])
def test_forward_logits_parity(cfg):
    """Full-model logits of the int8 pytree track the f32 reference."""
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    qparams = qz.quantize_params(params)
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 24), 0,
                              cfg.vocab_size)
    ref = np.asarray(llama.forward_full(params, cfg, toks))
    got = np.asarray(llama.forward_full(qparams, cfg, toks))
    # Per-position cosine similarity of the logit vectors.
    dot = (ref * got).sum(-1)
    cos = dot / (np.linalg.norm(ref, axis=-1)
                 * np.linalg.norm(got, axis=-1) + 1e-9)
    assert cos.min() > 0.99, f"min cosine {cos.min()}"
    # And the probability mass moved stays small.
    err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert err < 0.2, f"relative max logit error {err}"


def test_engine_generation_on_quantized_params(params):
    """prefill+paged-decode on the quantized pytree is self-consistent with
    dense forward of the same quantized weights (exercises _embed_lookup,
    _linear, and _unembed quantized branches through the whole stack)."""
    qparams = qz.quantize_params(params)
    eng = InferenceEngine(
        CFG, qparams,
        EngineConfig(max_slots=4, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16, 32)),
        eos_id=-1,
    )
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(3, 250, size=n)) for n in (5, 12)]
    results = eng.generate(prompts, SamplingParams(max_tokens=6))

    def naive(prompt, n):
        seq = list(prompt)
        for _ in range(n):
            logits = llama.forward_full(
                qparams, CFG, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    for p, r in zip(prompts, results):
        assert r.finish_reason == "length"
        assert r.token_ids == naive(p, 6)


def test_init_params_quantized_runs():
    """Direct quantized random init (the 8B bench path) generates."""
    qparams = qz.init_params_quantized(jax.random.PRNGKey(0), CFG)
    assert qparams["layers"][0]["q"]["kernel_q"].dtype == jnp.int8
    eng = InferenceEngine(
        CFG, qparams,
        EngineConfig(max_slots=2, num_blocks=32, block_size=8,
                     max_blocks_per_seq=8, prefill_buckets=(16,)),
        eos_id=-1,
    )
    res = eng.generate([[5, 6, 7]], SamplingParams(max_tokens=4))[0]
    assert res.finish_reason == "length" and len(res.token_ids) == 4


def test_quantized_param_bytes_halve(params):
    dense = qz.param_bytes(jax.tree.map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
        params))
    quant = qz.param_bytes(qz.quantize_params(params))
    assert quant < 0.75 * dense  # int8 kernels + small f32 scales


def test_quantized_pytree_shards_over_mesh(params):
    """TP partition specs cover kernel_q/scale; device_put succeeds on the
    virtual 8-device mesh (2-way model axis on the tiny shapes)."""
    from jax.sharding import Mesh, NamedSharding
    from k8s_llm_monitor_tpu.parallel.sharding import param_partition_specs

    qparams = qz.quantize_params(params)
    devs = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("model",))
    specs = param_partition_specs(qparams)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        qparams, specs)
    # Column-parallel scale must actually be split over the model axis.
    q_scale = sharded["layers"][0]["q"]["scale"]
    shard_shapes = {tuple(sh.data.shape) for sh in q_scale.addressable_shards}
    assert shard_shapes == {(q_scale.shape[0] // 2,)}


def test_hf_streaming_quantized_load():
    """convert_hf_state_dict(quantize=True) produces a quantized pytree whose
    logits track the unquantized load of the same state dict."""
    torch = pytest.importorskip("torch")
    import transformers

    from k8s_llm_monitor_tpu.utils.checkpoint import (
        config_from_hf,
        convert_hf_state_dict,
    )

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=500000.0,
        tie_word_embeddings=False, attn_implementation="eager")
    model = transformers.LlamaForCausalLM(hf_cfg)
    torch.manual_seed(0)
    for p in model.parameters():
        with torch.no_grad():
            p.copy_(torch.randn_like(p) * 0.05)
    state = {k: v.numpy() for k, v in model.state_dict().items()}
    cfg = config_from_hf(hf_cfg.to_dict(), name="tiny-hf")
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": "float32"})

    ref_params = convert_hf_state_dict(state, cfg)
    q_params = convert_hf_state_dict(state, cfg, quantize=True)
    assert "weight_q" in q_params["embed"]
    toks = jnp.asarray([[1, 5, 9, 80, 3, 44]], jnp.int32)
    ref = np.asarray(llama.forward_full(ref_params, cfg, toks))
    got = np.asarray(llama.forward_full(q_params, cfg, toks))
    cos = ((ref * got).sum(-1)
           / (np.linalg.norm(ref, axis=-1) * np.linalg.norm(got, axis=-1)
              + 1e-9))
    assert cos.min() > 0.99


def test_w8a8_forward_parity():
    """W8A8 (dynamic per-token activation int8 on top of int8 weights)
    logits track the f32 reference closely enough for serving."""
    import dataclasses as _dc

    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    qparams = qz.quantize_params(params)
    cfg_aq = _dc.replace(CFG, act_quant=True)
    toks = jax.random.randint(jax.random.PRNGKey(5), (2, 24), 0,
                              CFG.vocab_size)
    ref = np.asarray(llama.forward_full(params, CFG, toks))
    got = np.asarray(llama.forward_full(qparams, cfg_aq, toks))
    dot = (ref * got).sum(-1)
    cos = dot / (np.linalg.norm(ref, axis=-1)
                 * np.linalg.norm(got, axis=-1) + 1e-9)
    assert cos.min() > 0.98, f"min cosine {cos.min()}"


def test_w8a8_engine_self_consistent():
    """Engine generation under act_quant matches naive decoding of the
    same (act_quant) model — prefill, paged decode, and dense forward all
    run the s8 x s8 path consistently."""
    import dataclasses as _dc

    cfg_aq = _dc.replace(CFG, act_quant=True)
    qparams = qz.quantize_params(llama.init_params(jax.random.PRNGKey(0), CFG))
    eng = InferenceEngine(
        cfg_aq, qparams,
        EngineConfig(max_slots=2, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16, 32)),
        eos_id=-1,
    )
    rng = np.random.default_rng(6)
    prompts = [list(rng.integers(3, 250, size=n)) for n in (6, 11)]
    results = eng.generate(prompts, SamplingParams(max_tokens=5))

    def naive(prompt, n):
        seq = list(prompt)
        for _ in range(n):
            logits = llama.forward_full(
                qparams, cfg_aq, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    for p, r in zip(prompts, results):
        assert r.token_ids == naive(p, 5)


def test_70b_int8_specs_divide_on_tp8_and_tp16():
    """BASELINE config #5 with int8 weights: every sharded axis of the
    quantized 70B/72B pytrees divides TP-8 and TP-16 (checked via
    eval_shape — no 70B weights materialized)."""
    from k8s_llm_monitor_tpu.models.config import PRESETS
    from k8s_llm_monitor_tpu.parallel.sharding import param_partition_specs

    for name in ("llama3-70b", "qwen2-72b"):
        cfg = PRESETS[name]
        shapes = jax.eval_shape(
            lambda rng, c=cfg: qz.init_params_quantized(rng, c),
            jax.random.PRNGKey(0))
        specs = param_partition_specs(shapes)
        for tp in (8, 16):
            def check(path, leaf, spec):
                for dim, axis in enumerate(spec):
                    if axis == "model":
                        assert leaf.shape[dim] % tp == 0, (
                            f"{name} tp={tp}: {path} {leaf.shape}")
            jax.tree_util.tree_map_with_path(
                lambda p, l, s: check(p, l, s), shapes, specs)
        # int8 70B-class weights must fit a v5p-16's per-chip HBM budget.
        total = sum(l.size * l.dtype.itemsize
                    for l in jax.tree_util.tree_leaves(shapes))
        assert total < 80 * 2**30, f"{name}: {total/2**30:.1f} GiB int8"


def test_fp8_kv_cache_decode_parity():
    """kv_dtype=float8_e4m3fn: decode logits over fp8 pages track the
    bf16-KV model (capacity option; measured ~2x slower decode on v5e —
    f8 conversion is emulated — so it trades speed for 2x KV capacity)."""
    import dataclasses as _dc

    cfg8 = _dc.replace(CFG, kv_dtype="float8_e4m3fn")
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(9)
    prompt = list(rng.integers(3, 250, size=12))

    def decode_logits(cfg):
        pages = llama.init_kv_pages(cfg, 16, 8)
        table = jnp.asarray([[1, 2, 3, 0, 0, 0, 0, 0]], jnp.int32)
        toks = jnp.asarray([prompt], jnp.int32)
        _, pages = llama.prefill(
            params, cfg, toks, jnp.asarray([12], jnp.int32), pages, table)
        logits, _ = llama.decode_step(
            params, cfg, jnp.asarray([prompt[-1]], jnp.int32),
            jnp.asarray([12], jnp.int32), pages, table)
        return np.asarray(logits[0])

    ref = decode_logits(CFG)
    got = decode_logits(cfg8)
    cos = (ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got))
    assert cos > 0.98, cos


def test_gemma2_int8_roundtrip():
    """quantize_params keeps the Gemma sandwich norms and the quantized
    model still matches its own bf16 logits closely."""
    import numpy as np

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import ModelConfig
    from k8s_llm_monitor_tpu.utils.quantize import quantize_params

    cfg = ModelConfig(
        name="tiny-gemma-q", vocab_size=160, hidden_size=32,
        intermediate_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=8, dtype="float32", rope_theta=10_000.0,
        tie_embeddings=True, mlp_activation="gelu_tanh",
        sandwich_norms=True, rmsnorm_unit_offset=True,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        query_pre_attn_scalar=12.0, embed_scale=True)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    qp = quantize_params(params)
    assert "post_attn_norm" in qp["layers"][0]
    assert "post_mlp_norm" in qp["layers"][0]
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(2, 160, size=(2, 9)), jnp.int32)
    a = np.asarray(llama.forward_full(params, cfg, toks)).reshape(-1)
    b = np.asarray(llama.forward_full(qp, cfg, toks)).reshape(-1)
    cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
    assert cos > 0.995, cos
