"""Overload chaos suite: SLO classes, preemptive eviction, brownout ladder.

Exercises the three class-ordered pressure valves (resilience/slo.py +
docs/resilience.md) end to end on the CPU mesh:

  * class-aware admission — lowest class sheds first, per-class Retry-After
    streaks, ``interactive`` never refused while ``batch`` waits;
  * preemptive lane eviction — the lowest-class running lane is
    recompute-preempted for a higher-class arrival, byte-exactly, with the
    seeded ``lane_eviction`` fault proving the failure path recovers;
  * the brownout ladder — hysteretic DEGRADED/DRAINING rungs clamp batch
    budgets, pause diagnosis triggers, and gate router hedging.

``make chaos-overload`` runs this module under K8SLLM_LOCKCHECK=1; the
3x-capacity mixed-class burst is the acceptance scenario.
"""

import math
import threading
import time

import pytest

import jax

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.resilience.slo import (
    BROWNOUT_DEGRADED,
    BROWNOUT_DRAINING,
    BROWNOUT_NORMAL,
    BrownoutController,
    normalize_slo_class,
)
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.service import EngineService, OverloadedError

pytestmark = pytest.mark.chaos

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32, intermediate_size=64,
                  num_layers=2, num_heads=4, num_kv_heads=2, dtype="float32",
                  rope_theta=10_000.0)

# Same shapes as tests/test_resilience.py so the jit cache is shared across
# modules; prefix cache off so the allocator baseline is exact.
ECFG = dict(max_slots=4, num_blocks=64, block_size=8,
            max_blocks_per_seq=16, prefill_buckets=(16,),
            max_prefills_per_step=4, decode_steps_per_iter=4,
            prefix_cache_entries=0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _fault_isolation():
    get_injector().reset(seed=1234)
    yield
    get_injector().reset()


def _mk_engine(params, **overrides):
    cfg = dict(ECFG)
    cfg.update(overrides)
    return InferenceEngine(CFG, params, EngineConfig(**cfg), eos_id=-1)


def _run(eng, max_steps=500):
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < max_steps, "engine wedged: work left after step budget"


def _naive_greedy(params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jax.numpy.asarray([toks]))
        toks.append(int(jax.numpy.argmax(logits[0, -1])))
    return toks[len(prompt):]


# -- slo.py units ------------------------------------------------------------


def test_normalize_slo_class():
    assert normalize_slo_class("") == "standard"
    assert normalize_slo_class(None) == "standard"
    assert normalize_slo_class("", default="interactive") == "interactive"
    assert normalize_slo_class(" Batch ") == "batch"
    assert normalize_slo_class("interactive") == "interactive"
    with pytest.raises(ValueError, match="unknown slo_class"):
        normalize_slo_class("premium")


def test_brownout_ladder_hysteresis():
    state = {"v": "healthy"}
    clock = {"t": 0.0}
    b = BrownoutController(lambda: state["v"], recover_dwell_s=10.0,
                           clock=lambda: clock["t"])
    assert b.level() == BROWNOUT_NORMAL

    # Escalation is immediate, and can jump straight to the top rung.
    state["v"] = "draining"
    assert b.level() == BROWNOUT_DRAINING
    assert b.escalations == 1

    # Recovery needs a continuous dwell, one rung at a time.
    state["v"] = "healthy"
    assert b.level() == BROWNOUT_DRAINING          # dwell starts now
    clock["t"] = 9.9
    assert b.level() == BROWNOUT_DRAINING          # not dwelt long enough
    clock["t"] = 10.0
    assert b.level() == BROWNOUT_DEGRADED          # one rung, not straight home
    assert b.recoveries == 1
    clock["t"] = 19.9
    assert b.level() == BROWNOUT_DEGRADED
    clock["t"] = 20.0
    assert b.level() == BROWNOUT_NORMAL
    assert b.recoveries == 2

    # A flap inside the dwell resets the timer (hysteresis).
    state["v"] = "degraded"
    assert b.level() == BROWNOUT_DEGRADED
    state["v"] = "healthy"
    clock["t"] = 25.0
    assert b.level() == BROWNOUT_DEGRADED          # dwell starts at t=25
    state["v"] = "degraded"
    clock["t"] = 34.0
    assert b.level() == BROWNOUT_DEGRADED          # flap: timer reset
    state["v"] = "healthy"
    clock["t"] = 36.0
    assert b.level() == BROWNOUT_DEGRADED          # only 2s since the flap
    clock["t"] = 46.0
    assert b.level() == BROWNOUT_NORMAL

    snap = b.snapshot()
    assert snap["name"] == "normal" and snap["escalations"] == 2


# -- class-aware admission ---------------------------------------------------


def test_shedding_is_class_ordered(params):
    eng = _mk_engine(params, shed_queue_tokens=24)
    eng.submit(GenerationRequest("b0", list(range(12)),
                                 SamplingParams(max_tokens=4),
                                 slo_class="batch"))
    eng.submit(GenerationRequest("b1", list(range(12)),
                                 SamplingParams(max_tokens=4),
                                 slo_class="batch"))
    # 24 batch tokens queued: batch is over its own budget...
    assert "batch" in eng.should_shed("batch")
    # ...but higher classes are never refused while lower-class work waits
    # (it would be admitted after them anyway, and it evicts/sheds first).
    assert eng.should_shed("interactive") == ""
    assert eng.should_shed("standard") == ""

    # Single-class traffic reduces to the flat threshold.
    eng2 = _mk_engine(params, shed_queue_tokens=24)
    eng2.submit(GenerationRequest("s0", list(range(24)),
                                  SamplingParams(max_tokens=4)))
    assert eng2.should_shed("standard") != ""
    # A class is charged for backlog of its own class and above: batch
    # waits behind the 24 standard tokens, so it sheds too.
    assert eng2.should_shed("batch") != ""
    assert eng2.should_shed("interactive") == ""
    _run(eng)
    _run(eng2)


def test_service_per_class_retry_after_streaks(params):
    eng = _mk_engine(params)
    svc = EngineService(eng)
    try:
        real_shed = eng.should_shed
        eng.should_shed = lambda slo_class="standard", need_tokens=0: "forced overload"
        hints = {"batch": [], "interactive": []}
        for _ in range(5):
            with pytest.raises(OverloadedError) as ei:
                svc.submit([1, 2, 3], SamplingParams(max_tokens=2),
                           slo_class="batch")
            assert ei.value.slo_class == "batch"
            hints["batch"].append(ei.value.retry_after_s)
        with pytest.raises(OverloadedError) as ei:
            svc.submit([1, 2, 3], SamplingParams(max_tokens=2),
                       slo_class="interactive")
        hints["interactive"].append(ei.value.retry_after_s)

        # The shed backoff is deterministic (jitter=0, base 1s, cap 8s):
        # each class escalates its own streak; interactive's first shed is
        # not inflated by batch's five.
        assert hints["batch"] == [1.0, 2.0, 4.0, 8.0, 8.0]
        assert hints["interactive"] == [1.0]
        assert svc.shed_count_by_class == {"batch": 5, "interactive": 1}

        # A successful admit of the class resets its streak.
        eng.should_shed = real_shed
        svc.submit([1, 2, 3], SamplingParams(max_tokens=2),
                   slo_class="batch").result(timeout=30)
        eng.should_shed = lambda slo_class="standard", need_tokens=0: "forced overload"
        with pytest.raises(OverloadedError) as ei:
            svc.submit([1, 2, 3], SamplingParams(max_tokens=2),
                       slo_class="batch")
        assert ei.value.retry_after_s == 1.0
        eng.should_shed = real_shed
    finally:
        svc.stop(timeout=10.0)


# -- preemptive lane eviction ------------------------------------------------


def test_voluntary_eviction_is_byte_exact(params):
    eng = _mk_engine(params, max_slots=2)
    baseline = eng.allocator.free_blocks
    eng.submit(GenerationRequest("b0", [5, 6, 7],
                                 SamplingParams(max_tokens=60),
                                 slo_class="batch"))
    eng.submit(GenerationRequest("b1", [8, 9, 10],
                                 SamplingParams(max_tokens=60),
                                 slo_class="batch"))
    eng.step()
    eng.step()
    assert eng.active_slots == 2
    eng.submit(GenerationRequest("i0", [11, 12, 13],
                                 SamplingParams(max_tokens=6),
                                 slo_class="interactive"))
    _run(eng)
    # Exactly one batch lane paid for the interactive arrival — the
    # re-sorted queue prevents the victim from reclaiming its own slot
    # (which would re-evict it every step).
    assert eng.preemptions_by_class.get("batch", 0) == 1
    assert eng.preemptions_by_class.get("interactive", 0) == 0
    # Recompute-preemption is byte-exact: every request matches the
    # unpreempted greedy decode.
    for rid, prompt, n in (("b0", [5, 6, 7], 60), ("b1", [8, 9, 10], 60),
                           ("i0", [11, 12, 13], 6)):
        res = eng._results[rid]
        assert res.finish_reason == "length"
        assert res.token_ids == _naive_greedy(params, prompt, n), rid
    assert eng.allocator.free_blocks == baseline


def test_eviction_never_targets_equal_or_higher_class(params):
    eng = _mk_engine(params, max_slots=2)
    eng.submit(GenerationRequest("i0", [5, 6, 7],
                                 SamplingParams(max_tokens=40),
                                 slo_class="interactive"))
    eng.submit(GenerationRequest("s0", [8, 9, 10],
                                 SamplingParams(max_tokens=40)))
    eng.step()
    eng.step()
    # A standard arrival outranks nobody running: it must wait, not evict.
    eng.submit(GenerationRequest("s1", [11, 12, 13],
                                 SamplingParams(max_tokens=4)))
    _run(eng)
    assert eng.preemptions == 0
    assert eng._results["s1"].finish_reason == "length"


def test_lane_eviction_fault_recovers(params):
    eng = _mk_engine(params, max_slots=2)
    baseline = eng.allocator.free_blocks
    get_injector().arm("lane_eviction", rate=1.0, times=1)
    eng.submit(GenerationRequest("b0", [5, 6, 7],
                                 SamplingParams(max_tokens=60),
                                 slo_class="batch"))
    eng.submit(GenerationRequest("b1", [8, 9, 10],
                                 SamplingParams(max_tokens=60),
                                 slo_class="batch"))
    eng.step()
    eng.step()
    eng.submit(GenerationRequest("i0", [11, 12, 13],
                                 SamplingParams(max_tokens=6),
                                 slo_class="interactive"))
    _run(eng)
    # The injected eviction failure left running lanes untouched; the
    # next step's retry (injector exhausted) completed the preemption.
    assert get_injector().fired("lane_eviction") == 1
    assert eng.dispatch_failures >= 1
    for rid, prompt, n in (("b0", [5, 6, 7], 60), ("b1", [8, 9, 10], 60),
                           ("i0", [11, 12, 13], 6)):
        assert eng._results[rid].token_ids == _naive_greedy(params, prompt, n)
    assert eng.allocator.free_blocks == baseline


# -- chunked-prefill fairness (decode cadence under long-prompt backlog) -----


def test_decode_progresses_under_chunk_backlog(params):
    eng = _mk_engine(params, max_slots=2, decode_every_n_chunk_rounds=2)
    # d0 holds a decode lane for the whole test.
    eng.submit(GenerationRequest("d0", [5, 6, 7],
                                 SamplingParams(max_tokens=60),
                                 slo_class="interactive"))
    eng.step()
    eng.step()
    # Sustained long-prompt backlog: each 48-token prompt needs 3 chunk
    # rounds through the 16-token bucket.
    longs = {}
    for i in range(4):
        prompt = [(17 * i + j) % 290 + 2 for j in range(48)]
        longs[f"L{i}"] = prompt
        eng.submit(GenerationRequest(f"L{i}", prompt,
                                     SamplingParams(max_tokens=4),
                                     slo_class="batch"))

    def d0_progress():
        for s in eng._slots:
            if s is not None and s.req.request_id == "d0":
                return len(s.generated)
        return None

    start = d0_progress()
    assert start is not None
    finished_order = []
    submitted_mid = False
    steps = 0
    while eng.has_work and steps < 200:
        eng.step()
        steps += 1
        if steps == 4 and not submitted_mid:
            # A short interactive prompt arriving mid-backlog must not
            # queue behind the remaining chunk rounds.
            eng.submit(GenerationRequest("i1", [20, 21, 22],
                                         SamplingParams(max_tokens=4),
                                         slo_class="interactive"))
            submitted_mid = True
        for rid in list(longs) + ["i1", "d0"]:
            if rid in eng._results and rid not in finished_order:
                finished_order.append(rid)
        prog = d0_progress()
        if prog is not None and steps == 8:
            # Decode interleaved at the configured cadence instead of
            # starving behind the chunk-round stream.
            assert prog > start, "decode lane starved by chunk rounds"
    assert steps < 200
    # The mid-backlog interactive request finished before the batch tail.
    assert finished_order.index("i1") < finished_order.index("L3")
    for rid, prompt in longs.items():
        assert eng._results[rid].token_ids == _naive_greedy(params, prompt, 4)
    assert eng._results["i1"].token_ids == _naive_greedy(
        params, [20, 21, 22], 4)
    assert eng._results["d0"].token_ids == _naive_greedy(params, [5, 6, 7], 60)


@pytest.mark.slow  # unique engine shapes recompile; runs via make chaos-overload
def test_interactive_chunk_bucket_shrinks_rounds(params):
    """Deadline-aware chunk-round sizing (EngineConfig
    interactive_chunk_bucket): while an interactive request waits in the
    queue, a long prompt's chunk rounds drop to the small bucket so the
    interactive admission isn't head-of-line blocked behind full-bucket
    chunks.  Total ingest work is unchanged — outputs stay byte-exact —
    and without queued interactive work the rounds keep the full bucket."""
    long_prompt = [(3 * j) % 290 + 2 for j in range(48)]
    eng = _mk_engine(params, max_slots=1, prefill_buckets=(8, 16),
                     interactive_chunk_bucket=8)
    eng.submit(GenerationRequest("L0", list(long_prompt),
                                 SamplingParams(max_tokens=4),
                                 slo_class="interactive"))
    eng.step()
    # No interactive backlog yet: the round used the full top bucket.
    assert eng.last_chunk_bucket == 16
    assert eng.chunk_shrinks == 0
    # An interactive arrival has to queue (the slot is held): every
    # subsequent chunk round shrinks to the interactive bucket.
    eng.submit(GenerationRequest("i0", [5, 6, 7],
                                 SamplingParams(max_tokens=4),
                                 slo_class="interactive"))
    eng.step()
    assert eng.last_chunk_bucket == 8
    assert eng.chunk_shrinks >= 1
    _run(eng)
    assert eng._results["L0"].token_ids == _naive_greedy(
        params, long_prompt, 4)
    assert eng._results["i0"].token_ids == _naive_greedy(params, [5, 6, 7], 4)

    # Flag off (default): the same load never shrinks a round, and the
    # gauge still reports the bucket the last round used.
    eng2 = _mk_engine(params, max_slots=1, prefill_buckets=(8, 16))
    eng2.submit(GenerationRequest("L0", list(long_prompt),
                                  SamplingParams(max_tokens=4),
                                  slo_class="interactive"))
    eng2.step()
    eng2.submit(GenerationRequest("i0", [5, 6, 7],
                                  SamplingParams(max_tokens=4),
                                  slo_class="interactive"))
    _run(eng2)
    assert eng2.chunk_shrinks == 0
    assert eng2.last_chunk_bucket == 16


# -- brownout effects --------------------------------------------------------


def test_brownout_clamps_batch_budget_only(params):
    eng = _mk_engine(params, brownout_batch_max_tokens=8)
    eng.brownout = lambda: BROWNOUT_DEGRADED
    eng.submit(GenerationRequest("b0", [5, 6, 7],
                                 SamplingParams(max_tokens=40),
                                 slo_class="batch"))
    eng.submit(GenerationRequest("i0", [8, 9, 10],
                                 SamplingParams(max_tokens=12),
                                 slo_class="interactive"))
    _run(eng)
    assert len(eng._results["b0"].token_ids) == 8      # clamped at admission
    assert len(eng._results["i0"].token_ids) == 12     # untouched
    assert eng.brownout_clamps == 1

    # At normal, batch keeps its budget.
    eng2 = _mk_engine(params, brownout_batch_max_tokens=8)
    eng2.submit(GenerationRequest("b0", [5, 6, 7],
                                  SamplingParams(max_tokens=12),
                                  slo_class="batch"))
    _run(eng2)
    assert len(eng2._results["b0"].token_ids) == 12
    assert eng2.brownout_clamps == 0


def test_brownout_clamp_exempts_constrained(params):
    eng = _mk_engine(params, brownout_batch_max_tokens=8)
    eng.brownout = lambda: BROWNOUT_DEGRADED
    req = GenerationRequest("c0", [5, 6, 7],
                            SamplingParams(max_tokens=40, constrained=True),
                            slo_class="batch")
    eng._clamp_for_brownout(req)
    # The grammar's forced-EOS path needs its full budget reachable.
    assert req.sampling.max_tokens == 40
    assert eng.brownout_clamps == 0


def test_pipeline_triggers_pause_at_draining():
    from k8s_llm_monitor_tpu.diagnosis.pipeline import DiagnosisPipeline
    from k8s_llm_monitor_tpu.monitor.config import DiagnosisConfig
    from k8s_llm_monitor_tpu.monitor.models import EventInfo

    level = {"v": BROWNOUT_DRAINING}
    clock = {"t": 0.0}

    def tick():
        clock["t"] += 1.0
        return clock["t"]

    pipe = DiagnosisPipeline(
        analysis=None,
        cfg=DiagnosisConfig(burst_threshold=2, window_s=60.0, cooldown_s=0.0),
        brownout=lambda: level["v"],
        clock=tick)
    for _ in range(4):
        pipe.offer(EventInfo(type="Warning", reason="OOMKilled"))
    # Bursts were detected but every trigger was paused: the engine is
    # shedding real traffic, background diagnosis must not compete.
    assert pipe.triggers_total == 0
    assert pipe.paused_total >= 1

    level["v"] = BROWNOUT_DEGRADED  # degraded still diagnoses
    for _ in range(2):
        pipe.offer(EventInfo(type="Warning", reason="OOMKilled"))
    assert pipe.triggers_total == 1


# -- client retry hints (satellite: 429 decorrelated jitter) -----------------


def test_client_retry_hint_decorrelated_jitter():
    from k8s_llm_monitor_tpu.monitor.client import ApiClient

    cl = ApiClient("http://127.0.0.1:9")
    hints = [cl._retry_hint_s(2.0, "batch") for _ in range(8)]
    # Every delay honors the server hint as a floor and the cap as a
    # ceiling; consecutive 429s spread over a widening window instead of
    # all clients sleeping exactly the hinted value.
    assert all(2.0 <= h <= cl.retry_cap_s for h in hints)
    assert len(set(hints)) > 1
    for prev, cur in zip(hints, hints[1:]):
        assert cur <= max(2.0, prev * 3.0) + 1e-9

    # Per-class streaks are independent: a fresh class starts at its hint.
    first_interactive = cl._retry_hint_s(0.5, "interactive")
    assert 0.5 <= first_interactive <= 1.5
    # A successful POST clears the map (simulated here).
    cl._retry_prev_s.clear()
    assert 2.0 <= cl._retry_hint_s(2.0, "batch") <= 6.0


def test_client_maps_429_payload_to_overloaded():
    from k8s_llm_monitor_tpu.monitor.client import ApiClient

    class _Fake429:
        code = 429

        def read(self):
            return (b'{"error_kind": "overloaded", "reason": "queue full",'
                    b' "retry_after_s": 4.0, "slo_class": "batch",'
                    b' "queue_depth": 7, "queue_tokens": 120}')

    cl = ApiClient("http://127.0.0.1:9")
    err = cl._overloaded_from(_Fake429())
    assert isinstance(err, OverloadedError)
    assert err.slo_class == "batch"
    assert err.retriable
    assert err.queue_depth == 7 and err.queue_tokens == 120
    # The hint passed through the jitter schedule, not a flat fallback.
    assert 4.0 <= err.retry_after_s <= cl.retry_cap_s

    class _Fake500(_Fake429):
        code = 500

    assert cl._overloaded_from(_Fake500()) is None


# -- exporter per-class series (satellite: /metrics) -------------------------


def test_exporter_emits_per_class_series(params):
    from k8s_llm_monitor_tpu.monitor.exporter import (_resilience_metrics,
                                                      _Writer)

    class _StubHealth:
        sheds = 3

        def state(self):
            return "healthy"

    class _StubService:
        health = _StubHealth()
        shed_count_by_class = {"batch": 2}
        brownout = BrownoutController(lambda: "healthy")

    eng = _mk_engine(params)
    eng.preemptions_by_class["batch"] = 4
    eng.ttft_ema_by_class["interactive"] = 0.25
    w = _Writer()
    _resilience_metrics(w, eng, _StubService())
    text = w.render()
    assert 'k8s_llm_monitor_shed_total{class="batch"} 2' in text
    assert 'k8s_llm_monitor_shed_total{class="interactive"} 0' in text
    assert 'k8s_llm_monitor_preemptions_total{class="batch"} 4' in text
    assert 'k8s_llm_monitor_brownout_state{state="normal"} 1' in text
    assert 'k8s_llm_monitor_brownout_state{state="draining"} 0' in text
    # Unmeasured classes emit an explicit NaN marker, not 0.0 — the router
    # proxies replica /metrics, and a fake zero would pollute the
    # population; measured classes emit the EMA.
    assert ('k8s_llm_monitor_engine_ttft_ema_seconds{class="interactive"} '
            '0.25' in text)
    assert ('k8s_llm_monitor_engine_ttft_ema_seconds{class="batch"} NaN'
            in text)
    assert 'k8s_llm_monitor_queue_wait_ms{class="interactive"} NaN' in text
    assert math.isnan(float("NaN"))  # the marker parses as a float


# -- fleet: class routing + stats plumbing -----------------------------------


def _stat_replica(rid, **stats):
    from k8s_llm_monitor_tpu.fleet.registry import ReplicaStats
    from k8s_llm_monitor_tpu.fleet.replica import Replica

    class _R(Replica):
        supports_tokens = True
        supports_query = True

        def __init__(self):
            self.replica_id = rid

        def readyz(self):
            return True

        def stats(self):
            return ReplicaStats(**stats)

    return _R()


def _mk_router(*reps, **kw):
    from k8s_llm_monitor_tpu.fleet.registry import ReplicaRegistry
    from k8s_llm_monitor_tpu.fleet.router import FleetRouter

    reg = ReplicaRegistry()
    for r in reps:
        reg.add(r)
    reg.refresh()
    return FleetRouter(reg, **kw)


def test_interactive_routes_least_loaded_over_policy():
    # Round-robin would alternate heads; interactive always takes the
    # least-loaded replica so an operator query never queues behind a
    # backlog the rotation happens to point at.
    router = _mk_router(
        _stat_replica("a", queue_tokens=100, total_slots=4),
        _stat_replica("b", total_slots=4),
        policy="round_robin")
    for _ in range(4):
        ranked = router._ranked(b"x", need_tokens=True,
                                slo_class="interactive")
        assert ranked[0].replica_id == "b"
    # Standard traffic still follows the configured policy's rotation.
    heads = {router._ranked(b"x", True, "standard")[0].replica_id
             for _ in range(4)}
    assert heads == {"a", "b"}


def test_batch_spills_only_below_saturation():
    router = _mk_router(
        _stat_replica("a", total_slots=4),
        _stat_replica("b", busy_slots=3, total_slots=4),
        policy="least_loaded", batch_spill_threshold=0.75)
    ranked = router._ranked(b"x", need_tokens=True, slo_class="batch")
    # b sits exactly at the 0.75 saturation threshold: kept as the
    # affinity/policy head only, dropped as a spill target.
    assert [c.replica_id for c in ranked] == ["a"]
    ranked = router._ranked(b"x", need_tokens=True, slo_class="standard")
    assert [c.replica_id for c in ranked] == ["a", "b"]


def test_browned_out_replica_suppresses_hedge_and_stats_parse():
    from k8s_llm_monitor_tpu.fleet.registry import ReplicaStats

    router = _mk_router(
        _stat_replica("a", total_slots=4, brownout=1),
        _stat_replica("b", total_slots=4))
    assert router._replica_browned_out("a")
    assert not router._replica_browned_out("b")
    assert not router._replica_browned_out("missing")

    st = ReplicaStats.from_payload({"engine": {
        "queue_depth": 2, "queue_tokens": 30, "busy_slots": 1,
        "total_slots": 4, "brownout": 2,
        "queue_tokens_by_class": {"batch": 24, "interactive": 6},
    }})
    assert st.brownout == 2
    assert st.queue_by_class == {"batch": 24, "interactive": 6}
    # Pre-SLO replicas simply report empty class maps.
    old = ReplicaStats.from_payload({"engine": {"queue_depth": 1}})
    assert old.brownout == 0 and old.queue_by_class == {}


# -- acceptance: 3x-capacity mixed-class burst -------------------------------


def test_chaos_mixed_class_burst_protects_interactive(params):
    """The `make chaos-overload` acceptance scenario: a sustained burst at
    3x slot capacity across all three classes, with seeded eviction faults,
    must shed only the lower classes (interactive sheds stay zero), keep
    every accepted request byte-exact, and return the allocator to its
    idle baseline."""
    eng = _mk_engine(params, shed_queue_tokens=48, max_preemptions=2)
    baseline = eng.allocator.free_blocks
    svc = EngineService(eng)
    get_injector().arm("lane_eviction", rate=0.25, times=2)

    reqs = []  # (rid, prompt, max_tokens, slo_class)
    for i in range(4):
        reqs.append((f"i{i}", [(7 * i + j) % 290 + 2 for j in range(4)],
                     6, "interactive"))
        reqs.append((f"s{i}", [(11 * i + j) % 290 + 2 for j in range(8)],
                     10, "standard"))
        reqs.append((f"b{i}", [(13 * i + j) % 290 + 2 for j in range(12)],
                     16, "batch"))

    from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock

    handles = {}
    lock = make_lock("test.overload_burst")
    errors = []

    def submit_class(cls):
        for rid, prompt, mt, c in reqs:
            if c != cls:
                continue
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    h = svc.submit(prompt, SamplingParams(max_tokens=mt),
                                   request_id=rid, slo_class=c)
                    with lock:
                        handles[rid] = h
                    break
                except OverloadedError as exc:
                    if time.monotonic() > deadline:
                        with lock:
                            errors.append(f"{rid}: still shed ({exc})")
                        return
                    time.sleep(min(exc.retry_after_s, 0.05))

    threads = [threading.Thread(target=submit_class, args=(c,))
               for c in ("interactive", "standard", "batch")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90.0)
    assert errors == []
    assert len(handles) == len(reqs)

    results = {rid: h.result(timeout=60.0) for rid, h in handles.items()}
    for rid, prompt, mt, _ in reqs:
        res = results[rid]
        assert res.finish_reason == "length", (rid, res.error)
        assert res.token_ids == _naive_greedy(params, prompt, mt), rid

    # The interactive-only backlog (16 tokens) can never reach the shed
    # threshold, and the class discount shields it from everyone else's:
    # zero interactive sheds however hard standard/batch pushed.
    assert svc.shed_count_by_class.get("interactive", 0) == 0
    svc.stop(timeout=10.0)
    assert eng.allocator.free_blocks == baseline
