"""KV tiering: quantized resident pages, host-RAM spill/restore, and
cross-replica prefix migration (serving/kv_tier.py + the engine's tier
hooks + the fleet router's migration path).

Fast units (blob framing, quant roundtrip, host-tier LRU accounting) run
in tier-1; engine-level scenarios are slow/chaos-marked and run via
``make chaos-kvtier`` (K8SLLM_LOCKCHECK=1).
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.resilience.retry import Backoff
from k8s_llm_monitor_tpu.resilience.tenancy import DEFAULT_TENANT as TEN
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.kv_cache import (
    BlockAllocator,
    PrefixCache,
    page_slice_bytes,
)
from k8s_llm_monitor_tpu.serving.kv_tier import (
    BlobError,
    HostKVTier,
    SpilledPrefix,
    pack_prefix_blob,
    unpack_prefix_blob,
)
from k8s_llm_monitor_tpu.serving.supervisor import EngineSupervisor

# Same shapes as tests/test_prefix_cache.py so the jit cache is shared
# across the cache-focused modules.
CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32,
                  intermediate_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, dtype="float32", rope_theta=10_000.0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _fault_isolation():
    get_injector().reset(seed=1234)
    yield
    get_injector().reset()


def _naive_greedy(params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = llama.forward_full(params, CFG, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    return seq[len(prompt):]


def _engine(params, **over):
    kw = dict(max_slots=4, num_blocks=64, block_size=8,
              max_blocks_per_seq=16, prefill_buckets=(16, 32))
    kw.update(over)
    return InferenceEngine(CFG, params, EngineConfig(**kw), eos_id=-1)


def _wait(predicate, timeout=30.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# Fast units: page accounting, quantization numerics, blob framing, host LRU
# ---------------------------------------------------------------------------


def test_page_slice_bytes_quant_overhead():
    """int8 pages + f32 per-(token, head) scales vs bf16/f32 pages: the
    byte math the fit preflight and kv_tier_stats rely on."""
    kvh, d, bs = 8, 128, 16
    fp16 = page_slice_bytes(kvh, d, bs, 2)
    int8 = page_slice_bytes(kvh, d, bs, 1, scale_bytes=4)
    assert fp16 == 2 * bs * kvh * d * 2
    assert int8 == 2 * bs * kvh * d * 1 + 2 * bs * kvh * 4
    # The tentpole economics: ~1.94x more pages per byte at 8B geometry.
    assert fp16 / int8 > 1.9
    # Head sharding divides both the pages and the scale rows.
    assert page_slice_bytes(kvh, d, bs, 1, tp=4, scale_bytes=4) * 4 == int8


def test_quantize_dequantize_roundtrip():
    """Per-(token, head) symmetric int8: dequantize recovers rows within
    the one-LSB-of-scale bound, zero rows stay exactly zero, and the scale
    shape drops the head_dim axis."""
    rng = np.random.default_rng(0)
    kvh, d = 2, 16
    x = jnp.asarray(rng.normal(size=(3, 5, kvh * d)) * 4.0, jnp.float32)
    qdtype, qmax = llama.kv_quant_spec("int8")
    xq, scale = llama.quantize_kv(x, kvh, qdtype, qmax)
    assert xq.shape == x.shape and xq.dtype == jnp.int8
    assert scale.shape == (3, 5, kvh) and scale.dtype == jnp.float32
    back = llama.dequantize_kv(xq, scale)
    err = np.abs(np.asarray(back) - np.asarray(x))
    # Worst case is half an LSB of the per-head scale.
    bound = np.repeat(np.asarray(scale), d, axis=-1) * 0.51
    assert (err <= bound).all()
    zq, zs = llama.quantize_kv(jnp.zeros((1, 4, kvh * d)), kvh, qdtype, qmax)
    assert not np.asarray(llama.dequantize_kv(zq, zs)).any()


def test_blob_roundtrip_and_crc_rejection():
    meta = {"model": "t", "n_blocks": 2, "tokens": [1, 2, 3]}
    arrays = [np.arange(12, dtype=np.float32).reshape(2, 6),
              np.arange(8, dtype=np.int8)]
    blob = pack_prefix_blob(meta, arrays)
    out_meta, raw = unpack_prefix_blob(blob)
    assert out_meta["model"] == "t" and out_meta["version"] == 1
    assert np.frombuffer(raw[0], np.float32).tolist() == list(range(12))
    assert np.frombuffer(raw[1], np.int8).tolist() == list(range(8))

    # Any damaged byte must raise, never install garbage.
    for pos in (0, 5, len(blob) // 2, len(blob) - 1):
        bad = blob[:pos] + bytes([blob[pos] ^ 0xFF]) + blob[pos + 1:]
        with pytest.raises(BlobError):
            unpack_prefix_blob(bad)
    # Truncation at any point inside a record must raise too.
    with pytest.raises(BlobError):
        unpack_prefix_blob(blob[:-3])
    with pytest.raises(BlobError):
        unpack_prefix_blob(b"NOPE" + blob[4:])


def test_host_tier_lru_byte_cap_and_counters():
    def entry(nbytes):
        return SpilledPrefix(
            n_blocks=1, layers=[(np.zeros(nbytes, np.uint8),)])

    tier = HostKVTier(max_bytes=100)
    assert not tier.put(b"huge", entry(101), tenant=TEN)  # can never fit
    assert tier.put(b"a", entry(40), tenant=TEN)
    assert tier.put(b"b", entry(40), tenant=TEN)
    assert len(tier) == 2 and tier.bytes_used == 80
    # Third 40-byte entry displaces the LRU ("a") and counts it lost.
    assert tier.put(b"c", entry(40), tenant=TEN)
    assert tier.contains(b"b") and not tier.contains(b"a")
    assert tier.stats()["lost"] == 1

    assert tier.peek(b"b") is not None             # peek doesn't consume
    assert tier.take(b"b").n_blocks == 1           # take does
    assert tier.take(b"b") is None
    st = tier.stats()
    assert st["spills"] == 3 and st["restores"] == 1
    tier.clear()
    assert len(tier) == 0 and tier.bytes_used == 0
    assert tier.stats()["lost"] == 2               # "c" dropped unrestored


def test_host_tier_tenant_share_cap_and_byte_accounting():
    """Eviction fairness at the host tier: a tenant over its byte share
    (while another tenant is resident) evicts its OWN oldest entries —
    a flooding tenant can't push a quiet tenant's spills out of RAM."""
    def entry(nbytes):
        return SpilledPrefix(
            n_blocks=1, layers=[(np.zeros(nbytes, np.uint8),)])

    tier = HostKVTier(max_bytes=100, max_tenant_share=0.5)
    assert tier.put(b"a1", entry(30), tenant="team-a")
    assert tier.put(b"b1", entry(30), tenant="team-b")
    # team-a exceeds its 50-byte share with team-b resident: its own LRU
    # ("a1") pays; team-b's entry is untouched.
    assert tier.put(b"a2", entry(30), tenant="team-a")
    per = tier.bytes_by_tenant()
    assert per["team-a"] <= 50 and per["team-b"] == 30
    assert not tier.contains(b"a1") and tier.contains(b"b1")
    assert tier.contains(b"a2")                    # new entry never victim
    # Alone in the tier, the cap does not bind (no one to be unfair to).
    tier2 = HostKVTier(max_bytes=100, max_tenant_share=0.5)
    assert tier2.put(b"x1", entry(40), tenant="team-a")
    assert tier2.put(b"x2", entry(40), tenant="team-a")
    assert tier2.bytes_by_tenant()["team-a"] == 80


def test_peek_lru_does_not_evict_or_touch_refcounts():
    a = BlockAllocator(num_blocks=32, block_size=4)
    pc = PrefixCache(a, max_entries=8)
    prompt = list(range(100, 109))                 # 2 full blocks
    blocks = a.alloc(10)
    pc.register(prompt, blocks, tenant=TEN)
    refs = [a.ref_count(b) for b in blocks[:2]]
    peek = pc.peek_lru()
    assert peek is not None
    digest, victim_blocks = peek
    assert isinstance(digest, bytes) and victim_blocks
    assert len(pc) == 2                            # nothing evicted
    assert [a.ref_count(b) for b in blocks[:2]] == refs
    # peek's blocks are exactly what evict_lru would free next.
    assert pc.evict_lru()
    assert pc.peek_lru() != peek
    assert PrefixCache(a).peek_lru() is None       # empty cache -> None


# ---------------------------------------------------------------------------
# Engine level: quant parity, spill/restore, rebuild rehydration, migration
# ---------------------------------------------------------------------------


@pytest.mark.slow  # builds engines (jit compiles); runs via make chaos-kvtier
def test_int8_vs_fp_greedy_parity_budget(params):
    """Quantized-resident decode against the full-precision oracle on the
    same weights: greedy outputs must agree on a long prefix.  The budget
    is explicit — int8 KV is lossy, so divergence deep into a generation
    is tolerated (median agreement >= 75% of the budget, and the first
    token always matches); wholesale divergence is a kernel bug."""
    n_gen = 16
    eng_fp = _engine(params)
    eng_q = _engine(params, kv_dtype="int8")
    assert eng_q.kv_quant == "int8"
    assert np.dtype(eng_q.pages.k[0].dtype) == np.int8
    rng = np.random.default_rng(5)
    agree = []
    for _ in range(6):
        p = list(rng.integers(3, 300, size=24))
        r_fp = eng_fp.generate([list(p)], SamplingParams(max_tokens=n_gen))[0]
        r_q = eng_q.generate([list(p)], SamplingParams(max_tokens=n_gen))[0]
        assert r_fp.token_ids == _naive_greedy(params, p, n_gen)
        k = 0
        while (k < n_gen and r_fp.token_ids[k] == r_q.token_ids[k]):
            k += 1
        assert k >= 1, "first greedy token diverged under int8 KV"
        agree.append(k / n_gen)
    assert float(np.median(agree)) >= 0.75, agree


@pytest.mark.slow  # builds an engine; runs via make chaos-kvtier
def test_spill_restore_byte_exact(params):
    """Pressured evictions demote to the host tier and the next hit
    rehydrates: cycling more distinct prefixes than the pool holds must
    spill, restore, and keep every greedy output byte-stable."""
    eng = _engine(params, max_slots=2, num_blocks=14, block_size=8,
                  prefill_buckets=(32,), host_spill_bytes=64 << 20,
                  kv_dtype="int8")
    rng = np.random.default_rng(6)
    prompts = [list(rng.integers(3, 300, size=24)) for _ in range(6)]
    first: dict[int, list[int]] = {}
    for _ in range(2):
        for i, p in enumerate(prompts):
            r = eng.generate([list(p)], SamplingParams(max_tokens=4))[0]
            assert r.finish_reason == "length"
            if i in first:
                assert r.token_ids == first[i], \
                    f"prompt {i} diverged after spill/restore"
            else:
                first[i] = r.token_ids
    st = eng.kv_tier_stats()
    assert st["spills"] > 0, st
    assert st["restores"] > 0, st
    assert st["host_bytes"] == eng.host_kv_tier.bytes_used


@pytest.mark.slow
@pytest.mark.chaos  # kills the step loop; runs via make chaos-kvtier
def test_supervisor_rebuild_rehydrates_spilled_pages(params):
    """A supervisor whose factory shares one HostKVTier across rebuilds:
    pages spilled before a crash rehydrate into the REBUILT engine's fresh
    pool (restore counter moves, outputs byte-identical); once the tier is
    cleared too, the same prompt still completes exactly via tokens-to-
    prompt replay — a lost spill entry costs latency, never tokens."""
    tier = HostKVTier(max_bytes=64 << 20)
    ecfg = dict(max_slots=4, num_blocks=64, block_size=8,
                max_blocks_per_seq=16, prefill_buckets=(16, 32),
                max_prefills_per_step=4)

    def factory():
        return InferenceEngine(CFG, params, EngineConfig(**ecfg), eos_id=-1,
                               host_kv_tier=tier)

    sup = EngineSupervisor(factory, max_restarts=4,
                           backoff=Backoff(base_s=0.01, cap_s=0.05,
                                           jitter=0.0),
                           poll_interval_s=0.02)
    try:
        rng = np.random.default_rng(8)
        prompt = list(rng.integers(3, 300, size=24))
        r1 = sup.submit(prompt, SamplingParams(max_tokens=6)).result(
            timeout=60)
        assert r1.finish_reason == "length"

        # Demote every cached entry for the prompt to the host tier
        # (deterministic pressure: the engine's own spill hook).
        def spill_all(e):
            n = 0
            while e._evict_prefix_lru():
                n += 1
            return n
        assert sup.call(spill_all, timeout=30.0) > 0
        assert len(tier) > 0 and tier.spills > 0

        # Crash the step loop mid-flight; the monitor rebuilds the engine
        # around the SAME tier.
        get_injector().arm("step_loop_crash", rate=1.0, times=1)
        sup.submit(list(rng.integers(3, 300, size=12)),
                   SamplingParams(max_tokens=3)).result(timeout=60)
        assert _wait(lambda: sup.restarts == 1)
        assert _wait(lambda: sup.state == "serving")

        restores0 = tier.restores
        r2 = sup.submit(list(prompt), SamplingParams(max_tokens=6)).result(
            timeout=60)
        assert r2.token_ids == r1.token_ids, "rehydrated pages diverged"
        assert tier.restores > restores0, "rebuilt engine never restored"

        # Replay fallback: lose the spill buffer, crash again — the prompt
        # must still produce the exact tokens (plain re-prefill), with
        # zero duplicated or lost tokens.
        tier.clear()
        get_injector().arm("step_loop_crash", rate=1.0, times=1)
        sup.submit(list(rng.integers(3, 300, size=12)),
                   SamplingParams(max_tokens=3)).result(timeout=60)
        assert _wait(lambda: sup.restarts == 2)
        assert _wait(lambda: sup.state == "serving")
        r3 = sup.submit(list(prompt), SamplingParams(max_tokens=6)).result(
            timeout=60)
        assert r3.token_ids == r1.token_ids
        assert len(r3.token_ids) == 6
    finally:
        sup.shutdown(grace_s=1.0)


@pytest.mark.slow  # builds two engines; runs via make chaos-kvtier
@pytest.mark.parametrize("kv_dtype", ["", "int8"])
def test_export_install_byte_exact(params, kv_dtype):
    """Rung three at the engine seam: export the cached prefix from a warm
    engine, install into a cold one — the receiver hits its prefix cache
    and reproduces the owner's greedy tokens exactly.  Tampered geometry
    is refused; damaged framing raises."""
    over = {"kv_dtype": kv_dtype} if kv_dtype else {}
    src = _engine(params, **over)
    dst = _engine(params, **over)
    rng = np.random.default_rng(9)
    prompt = list(rng.integers(3, 300, size=24))
    r_src = src.generate([list(prompt)], SamplingParams(max_tokens=5))[0]

    assert dst.export_prefix(list(prompt), tenant=TEN) is None  # cold cache
    blob = src.export_prefix(list(prompt), tenant=TEN)
    assert blob is not None and blob[:4] == b"KVX1"

    assert dst.install_prefix(blob, expected_tenant=TEN) == "installed"
    assert dst.install_prefix(blob, expected_tenant=TEN) == "cached"

    hits0 = dst.prefix_cache.hits
    r_dst = dst.generate([list(prompt)], SamplingParams(max_tokens=5))[0]
    assert r_dst.token_ids == r_src.token_ids
    assert dst.prefix_cache.hits == hits0 + 1

    # Geometry tamper: same framing, wrong contract -> refused, no write.
    meta, raw = unpack_prefix_blob(blob)
    meta.pop("version")
    bad_meta = dict(meta, block_size=4)
    tampered = pack_prefix_blob(
        bad_meta, [np.frombuffer(b, np.uint8) for b in raw])
    assert dst.install_prefix(tampered,
                              expected_tenant=TEN) == "incompatible"

    # Torn transfer: must raise, never partially install.
    with pytest.raises(BlobError):
        dst.install_prefix(blob[:-7], expected_tenant=TEN)


@pytest.mark.slow
@pytest.mark.chaos  # kills a replica mid-migration; runs via make chaos-kvtier
def test_router_migration_outcomes_and_mid_migration_kill(params):
    """The fleet path: an affinity miss migrates the owner's pages to the
    dispatch target ("installed"), a re-migration is "cached", and killing
    the owner mid-migration degrades to "owner_down" — the request still
    completes exactly via re-prefill on the target."""
    from k8s_llm_monitor_tpu.fleet.registry import (
        Candidate,
        ReplicaRegistry,
        ReplicaStats,
    )
    from k8s_llm_monitor_tpu.fleet.replica import LocalReplica
    from k8s_llm_monitor_tpu.fleet.router import FleetRouter
    from k8s_llm_monitor_tpu.serving.service import EngineService

    ecfg = dict(max_slots=4, num_blocks=64, block_size=8,
                max_blocks_per_seq=16, prefill_buckets=(32,),
                max_prefills_per_step=4)
    reps = [LocalReplica(f"r{i}", service=EngineService(
        InferenceEngine(CFG, params, EngineConfig(**ecfg), eos_id=-1)))
        for i in range(2)]
    try:
        reg = ReplicaRegistry()
        for r in reps:
            reg.add(r)
        reg.refresh()
        router = FleetRouter(reg, policy="affinity",
                             affinity_prefix_tokens=16)
        rng = np.random.default_rng(10)
        prompt = list(int(t) for t in rng.integers(3, 300, size=17))

        # Warm the owner; then simulate the affinity miss the router sees
        # when the preferred replica has no free slots.
        r0 = reps[0].generate(prompt, SamplingParams(max_tokens=4)).result(
            timeout=60)
        digest = router._token_digest(prompt)
        router.policy.preferred = lambda cands, d: "r0"
        ranked = [Candidate("r1", reps[1], ReplicaStats(total_slots=4), 0),
                  Candidate("r0", reps[0], ReplicaStats(total_slots=4), 0)]

        router._maybe_migrate_prefix(digest, prompt, ranked)
        assert router.counters()["prefix_migrations"] == {"installed": 1}

        r1 = reps[1].generate(prompt, SamplingParams(max_tokens=4)).result(
            timeout=60)
        assert r1.token_ids == r0.token_ids
        assert reps[1].service.engine.prefix_cache.hits >= 1

        router._maybe_migrate_prefix(digest, prompt, ranked)
        assert router.counters()["prefix_migrations"]["cached"] == 1

        # Mid-migration owner death: the fetch fails, the outcome records
        # owner_down, and the target still serves the prompt exactly.
        reps[0].kill()
        router._maybe_migrate_prefix(digest, prompt, ranked)
        assert router.counters()["prefix_migrations"]["owner_down"] == 1
        fresh = list(rng.integers(3, 300, size=17))
        rd = reps[1].generate(fresh, SamplingParams(max_tokens=4)).result(
            timeout=60)
        assert rd.finish_reason == "length"
        assert rd.token_ids == _naive_greedy(params, fresh, 4)
    finally:
        for r in reps:
            r.close()
