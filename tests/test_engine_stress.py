"""Randomized stress for the async engine pipeline: mixed lengths, EOS,
preemption under a tiny KV pool, and cancellation must never deadlock, leak
blocks, or drop results.
"""

import numpy as np
import pytest

import jax

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    InferenceEngine,
    SamplingParams,
)

CFG = ModelConfig(name="t", vocab_size=300, hidden_size=32, intermediate_size=64,
                  num_layers=2, num_heads=4, num_kv_heads=2, dtype="float32",
                  rope_theta=10_000.0)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG)


@pytest.mark.parametrize("spec_k", [0, 4])
def test_stress_mixed_workload_under_pressure(params, spec_k):
    """40 requests with random prompts/budgets through a pool small enough
    to force preemptions, with EOS active and cancels injected mid-flight.
    The spec_k=4 variant mixes greedy, pure-temperature, and top-p lanes so
    dispatches alternate between greedy-spec, sampled-spec, and the fused
    fallback while preemption and cancels fire."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=4, num_blocks=40, block_size=4,
                     max_blocks_per_seq=24, prefill_buckets=(16, 32),
                     max_prefills_per_step=4, max_admission_rounds=2,
                     decode_steps_per_iter=4, max_inflight=2,
                     spec_k=spec_k, spec_rounds_per_iter=2),
        eos_id=7,  # a plausible token: some generations stop early
    )
    rng = np.random.default_rng(0)
    N = 40
    budgets = {}
    for i in range(N):
        L = int(rng.integers(3, 60))          # some prompts need chunking
        mt = int(rng.integers(1, 30))
        budgets[f"s{i}"] = mt
        if spec_k and i % 3 == 1:
            sp = SamplingParams(max_tokens=mt, temperature=0.8)
        elif spec_k and i % 3 == 2:
            sp = SamplingParams(max_tokens=mt, temperature=0.8, top_p=0.9)
        else:
            sp = SamplingParams(max_tokens=mt)
        eng.submit(GenerationRequest(
            request_id=f"s{i}",
            prompt_ids=list(rng.integers(8, 300, size=L)),  # avoid eos id
            sampling=sp,
        ))

    cancelled = set()
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        if steps == 5:
            for rid in ("s3", "s17", "s30"):
                if eng.cancel(rid):
                    cancelled.add(rid)
        assert steps < 10_000, "engine failed to drain (livelock)"

    results = {f"s{i}": eng.poll(f"s{i}") for i in range(N)}
    for rid, r in results.items():
        assert r is not None, f"{rid}: no result delivered"
        if r.finish_reason == "error":
            assert rid in cancelled, f"{rid} errored: {r.error}"
            continue
        assert r.finish_reason in ("eos", "length")
        assert len(r.token_ids) <= budgets[rid] + 1
        if r.finish_reason == "length" and rid not in cancelled:
            assert len(r.token_ids) == budgets[rid]
        assert all(t != 7 for t in r.token_ids), "eos token leaked into output"

    # No leaked KV blocks: everything returned to the pool.
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()   # cache-held refs are not leaks
    assert eng.allocator.free_blocks == 40 - 1  # block 0 reserved
    assert not eng._deferred_frees
    assert all(s is None for s in eng._slots)
    assert not eng._inflight


@pytest.mark.parametrize("spec_k", [0, 4])
def test_stress_cancel_storm(params, spec_k):
    """Cancel every request at staggered points; pool must fully recover and
    the engine must stay usable afterwards."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=4, num_blocks=64, block_size=8,
                     max_blocks_per_seq=16, prefill_buckets=(16,),
                     decode_steps_per_iter=4, max_inflight=2,
                     spec_k=spec_k, spec_rounds_per_iter=2),
        eos_id=-1,
    )
    rng = np.random.default_rng(1)
    N = 12
    for i in range(N):
        eng.submit(GenerationRequest(
            f"c{i}", list(rng.integers(3, 300, size=6)),
            SamplingParams(max_tokens=50)))

    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        if steps % 2 == 0:
            eng.cancel(f"c{steps % N}")
        if steps == 4:
            for i in range(N):
                eng.cancel(f"c{i}")
        assert steps < 5_000

    for i in range(N):
        r = eng.poll(f"c{i}")
        assert r is not None
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()   # cache-held refs are not leaks
    assert eng.allocator.free_blocks == 64 - 1

    # Engine still serves correctly after the storm.
    [r] = eng.generate([[5, 6, 7, 8]], SamplingParams(max_tokens=5))
    assert r.finish_reason == "length" and len(r.token_ids) == 5


@pytest.mark.parametrize("spec_k", [0, 4])
def test_stress_waves_of_submissions(params, spec_k):
    """Interleave submission waves with stepping so admission, retirement,
    and slot reuse all overlap in-flight decode calls."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=3, num_blocks=48, block_size=4,
                     max_blocks_per_seq=12, prefill_buckets=(16,),
                     max_prefills_per_step=2, decode_steps_per_iter=2,
                     max_inflight=2, spec_k=spec_k, spec_rounds_per_iter=2),
        eos_id=-1,
    )
    rng = np.random.default_rng(2)
    ids = []
    steps = 0
    for wave in range(6):
        for j in range(4):
            rid = f"w{wave}-{j}"
            ids.append(rid)
            eng.submit(GenerationRequest(
                rid, list(rng.integers(3, 300, size=int(rng.integers(2, 12)))),
                SamplingParams(max_tokens=int(rng.integers(1, 10)))))
        for _ in range(3):
            if eng.has_work:
                eng.step()
                steps += 1
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < 5_000

    for rid in ids:
        r = eng.poll(rid)
        assert r is not None and r.finish_reason == "length"
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()   # cache-held refs are not leaks
    assert eng.allocator.free_blocks == 48 - 1


@pytest.mark.parametrize("spec_k", [0, 4])
def test_stress_long_prompts_shared_prefixes_and_cancels(params, spec_k):
    """The round-4 machinery under randomized load: streaming chunked long
    prompts, prefix-cache hits at every length, cache eviction under a
    tiny pool, preemption, and cancels — must drain without deadlock,
    error results, or leaked blocks."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=4, num_blocks=56, block_size=4,
                     max_blocks_per_seq=32, prefill_buckets=(8, 16),
                     max_prefills_per_step=4, max_admission_rounds=2,
                     decode_steps_per_iter=4, max_inflight=2,
                     decode_every_n_chunk_rounds=2,
                     spec_k=spec_k, spec_rounds_per_iter=2),
        eos_id=7,
    )
    rng = np.random.default_rng(11)
    prefixes = [list(rng.integers(8, 300, size=n)) for n in (12, 24, 40)]
    ids, cancelled = [], set()
    steps = 0
    for wave in range(6):
        for j in range(5):
            rid = f"w{wave}-{j}"
            ids.append(rid)
            kind = rng.integers(0, 4)
            if kind == 0:                       # short, unique
                prompt = list(rng.integers(8, 300, size=int(rng.integers(3, 14))))
            elif kind == 1:                     # shared prefix + tail
                prompt = list(prefixes[int(rng.integers(0, len(prefixes)))]) \
                    + list(rng.integers(8, 300, size=int(rng.integers(1, 6))))
            elif kind == 2:                     # long (chunk-streamed)
                prompt = list(rng.integers(8, 300, size=int(rng.integers(20, 60))))
            else:                               # long + shared prefix
                prompt = prefixes[2] + \
                    list(rng.integers(8, 300, size=int(rng.integers(20, 40))))
            eng.submit(GenerationRequest(
                rid, prompt,
                SamplingParams(max_tokens=int(rng.integers(1, 10)))))
        for _ in range(int(rng.integers(1, 5))):
            if eng.has_work:
                eng.step()
                steps += 1
        if wave % 2 == 1:                       # cancel something random
            victim = ids[int(rng.integers(0, len(ids)))]
            if eng.cancel(victim):
                cancelled.add(victim)
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < 5_000
    for rid in ids:
        r = eng.poll(rid)
        assert r is not None, f"{rid} dropped"
        if rid in cancelled and r.finish_reason == "error":
            continue
        assert r.finish_reason in ("eos", "length"), (rid, r)
    assert eng.prefix_cache.hits > 0           # the shared tails actually hit
    eng.prefix_cache.clear()
    assert eng.allocator.free_blocks == 56 - 1  # no leaked blocks


def test_stress_seq_parallel_mesh_long_prompts(params, cpu_mesh_devices):
    """The seq-sharded prefill path (engine._tokens_to_device) under churn:
    a data=1 x seq=2 x model=2 mesh with chunk-streamed long prompts,
    prefix hits, preemption pressure, and a cancel — must drain cleanly
    and match the unsharded engine's greedy outputs."""
    from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(data=1, seq=2, model=2),
                       devices=cpu_mesh_devices[:4])
    ecfg = EngineConfig(max_slots=4, num_blocks=56, block_size=4,
                        max_blocks_per_seq=32, prefill_buckets=(8, 16),
                        max_prefills_per_step=4, max_admission_rounds=2,
                        decode_steps_per_iter=4, max_inflight=2,
                        decode_every_n_chunk_rounds=2)
    rng = np.random.default_rng(21)
    prefix = list(rng.integers(8, 300, size=20))
    prompts = {
        "long-a": list(rng.integers(8, 300, size=44)),
        "long-b": list(rng.integers(8, 300, size=37)),
        "hit": prefix + list(rng.integers(8, 300, size=4)),
        "short": list(rng.integers(8, 300, size=5)),
        "victim": list(rng.integers(8, 300, size=50)),
    }

    def drive(engine):
        engine.generate([prefix], SamplingParams(max_tokens=1))  # seed cache
        for rid, p in prompts.items():
            engine.submit(GenerationRequest(
                rid, list(p), SamplingParams(max_tokens=6)))
        steps = 0
        while engine.has_work:
            engine.step()
            steps += 1
            if steps == 3:
                engine.cancel("victim")
            assert steps < 5_000
        return {rid: engine.poll(rid) for rid in prompts}

    plain = drive(InferenceEngine(CFG, params, ecfg, eos_id=-1))
    sq_eng = InferenceEngine(CFG, params, ecfg, eos_id=-1, mesh=mesh)
    assert sq_eng._tok_sharding is not None
    sq = drive(sq_eng)
    for rid in prompts:
        assert sq[rid] is not None, f"{rid} dropped"
        if rid == "victim":
            continue  # cancel timing is scheduler-dependent
        assert sq[rid].finish_reason == plain[rid].finish_reason
        assert sq[rid].token_ids == plain[rid].token_ids, rid
    sq_eng.prefix_cache.clear()
    assert sq_eng.allocator.free_blocks == 56 - 1


@pytest.mark.parametrize("spec_k", [0, 4])
def test_stress_cold_burst_deferral_under_churn(params, spec_k):
    """The round-5 cold-burst dedup under randomized load: every wave
    submits a burst sharing a brand-new (never-cached) prefix — short
    dense publishers and chunk-streaming long publishers both — while a
    tiny pool forces preemptions and random cancels kill publishers that
    deferred candidates are waiting on.  Must drain with no drops, no
    deadlock, no leaked blocks, and the deferral machinery must have
    actually fired."""
    eng = InferenceEngine(
        CFG, params,
        EngineConfig(max_slots=4, num_blocks=56, block_size=4,
                     max_blocks_per_seq=32, prefill_buckets=(8, 16),
                     max_prefills_per_step=4, max_admission_rounds=2,
                     decode_steps_per_iter=4, max_inflight=2,
                     decode_every_n_chunk_rounds=2,
                     spec_k=spec_k, spec_rounds_per_iter=2),
        eos_id=7,
    )
    rng = np.random.default_rng(23)
    ids, cancelled = [], set()
    steps = 0
    for wave in range(8):
        # A fresh prefix every wave: the cache has never seen it, so the
        # wave's same-prefix burst exercises the deferral rules, not the
        # warm hit path.  Odd waves use a long prefix so the publisher
        # streams chunks (the bounded-wait rule); even waves stay dense.
        plen = int(rng.integers(24, 44)) if wave % 2 else int(
            rng.integers(12, 20))
        prefix = list(rng.integers(8, 300, size=plen))
        for j in range(4):
            rid = f"c{wave}-{j}"
            ids.append(rid)
            tail = list(rng.integers(8, 300, size=int(rng.integers(1, 8))))
            eng.submit(GenerationRequest(
                rid, prefix + tail,
                SamplingParams(max_tokens=int(rng.integers(1, 8)))))
        for _ in range(int(rng.integers(1, 4))):
            if eng.has_work:
                eng.step()
                steps += 1
        # Kill a random in-flight request — sometimes the publisher a
        # deferred candidate is waiting on.
        victim = ids[int(rng.integers(max(0, len(ids) - 8), len(ids)))]
        if eng.cancel(victim):
            cancelled.add(victim)
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < 5_000
    for rid in ids:
        r = eng.poll(rid)
        assert r is not None, f"{rid} dropped"
        if rid in cancelled and r.finish_reason == "error":
            continue
        assert r.finish_reason in ("eos", "length"), (rid, r)
    assert eng.prefix_deferrals > 0            # the dedup actually fired
    assert eng.prefix_cache.hits > 0
    eng.prefix_cache.clear()
    assert eng.allocator.free_blocks == 56 - 1  # no leaked blocks
