# Build/run targets (parity: /root/reference/Makefile, minus its broken
# cmd/agent reference — the agent entrypoint here is cmd.uav_agent).

PY ?= python
TEST_ENV = env PYTHONPATH= JAX_PLATFORMS=cpu
SHELL := /bin/bash    # tier1 uses pipefail/PIPESTATUS

.PHONY: run run-agent run-scheduler demo test test-fast tier1 tier1-mesh \
        chaos chaos-lifecycle chaos-fleet chaos-overload chaos-kvtier \
        chaos-trace chaos-signals chaos-elastic chaos-tenant \
        chaos-remediate \
        diagnose-e2e bench bench-decode \
        bench-fleet bench-mesh bench-signals bench-elastic bench-prefill \
        bench-tenant bench-remediate \
        dryrun smoke \
        preflight \
        deploy-agent docker \
        docker-agent docker-scheduler lint lint-contracts lint-trace clean

run:
	$(PY) -m k8s_llm_monitor_tpu.cmd.server --cluster fake --port 8081

run-agent:
	$(PY) -m k8s_llm_monitor_tpu.cmd.uav_agent --port 9090

run-scheduler:
	$(PY) -m k8s_llm_monitor_tpu.cmd.scheduler --cluster fake

demo:
	$(PY) -m k8s_llm_monitor_tpu.cmd.demo debug-test

test:
	$(TEST_ENV) $(PY) -m pytest tests/ -q

test-fast:          # monitor plane only (no jax compiles)
	$(TEST_ENV) $(PY) -m pytest tests/ -q \
	  --ignore=tests/test_model_parity.py \
	  --ignore=tests/test_engine.py \
	  --ignore=tests/test_sharding.py \
	  --ignore=tests/test_real_artifact_e2e.py

tier1:              # the driver's verify gate, verbatim (ROADMAP.md)
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 1350 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log \
	  | tr -cd . | wc -c); \
	exit $$rc

# Mesh acceptance: TP-8 parity + SpecLayout + traceguard mesh path on the
# simulated 8-device CPU mesh, with lock discipline checked.  (conftest.py
# forces the 8-device XLA flag; set here too so the leg is self-contained.)
tier1-mesh:
	$(TEST_ENV) XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_sharding.py tests/test_spec_decode.py \
	  tests/test_overlap.py tests/test_flash_prefill.py -q \
	  -p no:cacheprovider

chaos:              # fault-injection resilience suite (docs/resilience.md)
	$(TEST_ENV) $(PY) -m pytest tests/ -q -m chaos -p no:cacheprovider

# Crash-safe lifecycle acceptance: WAL + supervisor + handover, with lock
# discipline checked and journal fsync off (CI speed).
chaos-lifecycle:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 K8SLLM_JOURNAL_FSYNC=never \
	  $(PY) -m pytest tests/test_lifecycle.py -q -p no:cacheprovider

# Fleet tier acceptance: router policies, hedging, 32-stream mid-kill
# failover (docs/fleet.md), with lock discipline checked.
chaos-fleet:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_fleet.py -q -p no:cacheprovider

# SLO-class overload acceptance: class-ordered shedding, preemptive lane
# eviction (byte-exact, with seeded eviction faults), the brownout ladder,
# and the 3x-capacity mixed-class burst (docs/resilience.md) — with lock
# discipline checked.
chaos-overload:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_overload.py -q -p no:cacheprovider

# KV-tier acceptance (docs/serving.md "KV tiers & prefix migration"):
# quantized-KV greedy parity, host-RAM spill/restore byte-exactness,
# supervisor-rebuild rehydration (+ replay fallback with the spill buffer
# gone), and cross-replica migration with a mid-migration replica kill —
# with lock discipline checked.
chaos-kvtier:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_kv_tier.py -q -p no:cacheprovider

# Tracing acceptance (docs/observability.md): span-ring bounds, seeded
# sampling determinism, the live router→2-replica merged trace with a
# hedge + forced mid-stream failover, flight-recorder dump on a seeded
# watchdog fault, and exposition lint — with lock discipline checked.
chaos-trace:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_tracing.py -q -p no:cacheprovider

# Telemetry-plane acceptance (docs/observability.md "Signals & time
# series"): ring-store math under a fake clock, fleet staleness NaN
# discipline, derived scale hints, the anomaly→diagnosis feed, and the
# live 2-replica flood→scale-up→decay loop — with lock discipline checked.
chaos-signals:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_signals.py -q -p no:cacheprovider

# Disaggregated-fleet + elasticity acceptance (docs/fleet.md
# "Disaggregated roles & autoscaling"): the prefill→decode handoff ladder
# (every install failure degrades to local decode, byte-exact), drain
# lifecycle with the budget-bounded prefix sweep, AutoscaleController
# hysteresis gates under a fake clock, and the 2-prefill/2-decode
# chaos burst with scale-up + drain-down + rebalance mid-burst — with
# lock discipline checked.
chaos-elastic:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_elasticity.py -q -p no:cacheprovider

# Multi-tenant hardening acceptance (docs/resilience.md "Tenancy &
# quotas"): identity normalization at the trust boundary, the
# TenantGovernor reservation protocol (charged == delivered across
# hedges, failovers, and a mid-stream replica kill), tenant-namespaced
# KV isolation (cross-tenant lookups structurally miss, tenant_mismatch
# installs refused), exporter top-K cardinality, and the flooding-tenant
# burst with seeded lane_eviction faults — with lock discipline checked.
chaos-tenant:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_tenancy.py -q -p no:cacheprovider

# Closed-loop remediation acceptance (docs/remediation.md): plan-grammar
# property fuzz (every constrained sample parses and names a live
# target), executor gate units on a fake clock (dry-run-first ordering,
# breaker trip, approval required, idempotent replay), and the
# four-scenario chaos e2e — crash loop, OOM, stale scheduler, node
# pressure: inject → detect → plan → execute → verified recovery — with
# lock discipline checked.
chaos-remediate:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_remediation.py -q -p no:cacheprovider

# Diagnosis acceptance (docs/diagnosis.md): grammar compiler units, the
# constrained-sampling fuzz (every sample parses), and the synthetic
# crash-loop burst → verdict e2e — with lock discipline checked.
diagnose-e2e:
	$(TEST_ENV) K8SLLM_LOCKCHECK=1 \
	  $(PY) -m pytest tests/test_grammar.py tests/test_diagnosis.py -q \
	  -p no:cacheprovider

bench:
	$(PY) bench.py

bench-decode:       # fused-vs-fallback decode microbench + phase attribution
	env BENCH_CONCURRENCY=8 BENCH_MAX_TOKENS=16 $(PY) bench.py

bench-fleet:        # CPU fleet smoke: 1-vs-2 replicas, hedged tail latency
	$(TEST_ENV) BENCH_FLEET_ONLY=1 BENCH_MODEL=tiny \
	  $(PY) bench.py | tee fleet-bench.json

# TP-mesh serving dryrun: p50/p99 TTFT + tok/s through one tensor-parallel
# engine on a forced 8-host-device CPU mesh (JSON flagged mesh_dryrun).
# The measured leg runs inside plain `make bench` on real multi-chip
# hardware and supersedes the perchip_equiv_* arithmetic.
bench-mesh:
	$(TEST_ENV) XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  BENCH_MESH_ONLY=1 BENCH_MODEL=tiny BENCH_QUANT=none \
	  BENCH_MESH_CONCURRENCY=12 BENCH_MESH_PROMPT_LEN=48 \
	  BENCH_MESH_MAX_TOKENS=12 BENCH_MESH_SLOTS=8 \
	  $(PY) bench.py | tee mesh-bench.json

# Long-prefill smoke: flash-vs-dense TTFT ladder, the chunked-vs-single-
# bucket crossover, the int8-pool variant, and the dense-skip branch
# (analytic transient bytes over budget) on a tiny CPU engine.  The
# measured 2k/8k/32k leg runs on real TPU hardware with the defaults.
bench-prefill:
	$(TEST_ENV) BENCH_PREFILL_ONLY=1 BENCH_MODEL=tiny BENCH_QUANT=none \
	  $(PY) bench.py | tee prefill-bench.json

# Telemetry-plane overhead smoke: scraper-on vs scraper-off tok/s on a
# tiny CPU engine; asserts the < 1% budget and persists the derived
# signal snapshot with the artifact.
bench-signals:
	$(TEST_ENV) BENCH_SIGNALS_ONLY=1 BENCH_MODEL=tiny BENCH_QUANT=none \
	  $(PY) bench.py | tee signals-bench.json

# Elasticity reaction smoke: reaction time from hint to first scale-up,
# TTFT p99 churn-vs-steady ratio, and the handoff-vs-local-prefill TTFT
# ratio on a tiny CPU fleet.
bench-elastic:
	$(TEST_ENV) BENCH_ELASTIC_ONLY=1 BENCH_MODEL=tiny BENCH_QUANT=none \
	  $(PY) bench.py | tee elastic-bench.json

# Multi-tenant fairness smoke: flooding tenant rate-limited with
# tenant-tagged 429s while quiet Zipf tenants stay byte-exact within the
# 2x-solo interactive TTFT budget, charged tokens == delivered tokens.
bench-tenant:
	$(TEST_ENV) BENCH_TENANT_ONLY=1 BENCH_MODEL=tiny BENCH_QUANT=none \
	  $(PY) bench.py | tee tenant-bench.json

# Remediation smoke: inject→verified-recovery latency for each chaos
# scenario on the template backend, plus constrained-vs-free plan decode
# tok/s on a tiny CPU engine (asserts the < 10% overhead budget).
bench-remediate:
	$(TEST_ENV) BENCH_REMEDIATE_ONLY=1 BENCH_MODEL=tiny BENCH_QUANT=none \
	  $(PY) bench.py | tee remediation-bench.json

smoke:              # boot server + 20-check live API suite
	$(TEST_ENV) bash scripts/smoke.sh

preflight:          # will the model/quant/mesh fit? (no weights built)
	$(PY) -m k8s_llm_monitor_tpu.cmd.preflight --model llama3-8b \
	  --quantize w8a8 --mesh 1,1,8 --kv-blocks 2200 --per-chip-hbm-gib 16

deploy-agent:       # build agent image, k3d import, roll out DaemonSet
	bash scripts/build-and-deploy-uav-agent.sh

dryrun:
	env PYTHONPATH= $(PY) __graft_entry__.py 8

docker:
	docker build -t k8s-llm-monitor-tpu-server:dev -f Dockerfile .

docker-agent:
	docker build -t k8s-llm-monitor-tpu-agent:dev -f Dockerfile.agent .

docker-scheduler:
	docker build -t k8s-llm-monitor-tpu-scheduler:dev -f Dockerfile.scheduler .

LINT_PATHS = k8s_llm_monitor_tpu tests bench.py __graft_entry__.py

lint:               # compileall + graftcheck always; ruff/mypy when installed
	$(PY) -m compileall -q k8s_llm_monitor_tpu
	@if $(PY) -c "import ruff" >/dev/null 2>&1; then \
	  $(PY) -m ruff check $(LINT_PATHS); \
	else echo "lint: ruff not installed, skipping (config in pyproject.toml)"; fi
	@if $(PY) -c "import mypy" >/dev/null 2>&1; then \
	  $(PY) -m mypy --config-file pyproject.toml; \
	else echo "lint: mypy not installed, skipping (config in pyproject.toml)"; fi
	$(TEST_ENV) $(PY) -m k8s_llm_monitor_tpu.devtools.graftcheck \
	  --dataflow --contracts $(LINT_PATHS)

lint-contracts:     # fast path: contract-drift checks only (no package import)
	$(TEST_ENV) $(PY) -m k8s_llm_monitor_tpu.devtools.graftcheck \
	  --contracts k8s_llm_monitor_tpu/devtools/contracts.py

lint-trace:         # lint + trace-time guards (jit-compiles a tiny engine)
	$(TEST_ENV) $(PY) -m k8s_llm_monitor_tpu.devtools.graftcheck --trace $(LINT_PATHS)

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
