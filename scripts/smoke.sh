#!/usr/bin/env bash
# One-command live smoke: boot the monitor server against the in-memory
# demo cluster (template LLM — no model compile) and run the end-to-end
# API check suite against it.
# (Capability parity with the reference's root test_server.sh /
# test_web_interface.sh / test_with_mock_k8s.sh trio, consolidated.)
#
# Usage: ./scripts/smoke.sh [port]          (default 18230)
set -euo pipefail

PORT="${1:-18230}"
cd "$(dirname "$0")/.."

python3 -m k8s_llm_monitor_tpu.cmd.server \
  --cluster fake --llm template --port "$PORT" >/tmp/monitor-smoke.log 2>&1 &
SRV=$!
trap 'kill $SRV 2>/dev/null || true' EXIT

echo "==> waiting for server on :$PORT"
for _ in $(seq 1 30); do
  curl -sf "http://127.0.0.1:$PORT/health" >/dev/null 2>&1 && break
  sleep 1
done
curl -sf "http://127.0.0.1:$PORT/health" >/dev/null || {
  echo "server failed to boot; log tail:"; tail -20 /tmp/monitor-smoke.log
  exit 1
}

echo "==> dashboard reachable"
curl -sf "http://127.0.0.1:$PORT/" | grep -q "k8s-llm-monitor"

echo "==> API pipeline"
./scripts/test_uav_collection.sh "http://127.0.0.1:$PORT"
