#!/usr/bin/env bash
# Build the UAV agent image, import it into a k3d cluster, roll out the
# DaemonSet, and print per-node endpoints.
# (Capability parity: /root/reference/scripts/build-and-deploy-uav-agent.sh
# — build → k3d import → apply → rollout wait → endpoint listing — rebuilt
# for this repo's Python agent image, Dockerfile.agent.)
#
# Usage: ./scripts/build-and-deploy-uav-agent.sh [k3d-cluster-name]
set -euo pipefail

CLUSTER="${1:-k8s-llm-monitor}"
IMAGE="k8s-llm-monitor-tpu-agent:dev"   # must match uav-agent-daemonset.yaml
NS="monitoring"                          # the DaemonSet's namespace

if [ ! -f "Dockerfile.agent" ]; then
  echo "error: run from the repository root (Dockerfile.agent not found)" >&2
  exit 1
fi

echo "==> building $IMAGE"
docker build -f Dockerfile.agent -t "$IMAGE" .

if command -v k3d >/dev/null 2>&1; then
  echo "==> importing image into k3d cluster '$CLUSTER'"
  k3d image import "$IMAGE" -c "$CLUSTER"
else
  echo "==> k3d not found; assuming the cluster can pull $IMAGE"
fi

echo "==> applying CRDs + DaemonSet"
kubectl create namespace "$NS" --dry-run=client -o yaml | kubectl apply -f -
kubectl apply -f deployments/uav-metrics-crd.yaml
kubectl apply -f deployments/uav-agent-daemonset.yaml

echo "==> waiting for rollout"
kubectl rollout status daemonset/uav-agent -n "$NS" --timeout=120s

echo
echo "==> agents"
kubectl get pods -n "$NS" -l app=uav-agent -o wide

echo
echo "==> per-node endpoints"
kubectl get pods -n "$NS" -l app=uav-agent --no-headers \
  -o custom-columns=NAME:.metadata.name,NODE:.spec.nodeName,HOST:.status.hostIP \
  | while read -r name node host; do
      echo "  $name on $node:"
      echo "    http://$host:9090/health"
      echo "    http://$host:9090/api/v1/state"
    done

cat <<'EOF'

Try:
  curl http://<host>:9090/api/v1/state
  curl -X POST http://<host>:9090/api/v1/command/arm
  curl -X POST http://<host>:9090/api/v1/command/takeoff \
       -H 'Content-Type: application/json' -d '{"altitude": 50}'
EOF
