#!/usr/bin/env bash
# End-to-end UAV pipeline check against a running monitor server
# (parity: /root/reference/scripts/test_uav_collection.sh — curl/jq
# verification of report ingestion, cache reads, and the CRD record).
#
# Usage: ./scripts/test_uav_collection.sh [base-url]   (default :8081)
set -euo pipefail

BASE="${1:-http://127.0.0.1:8081}"
PASS=0; FAIL=0

check() {  # check <name> <cmd...>
  local name="$1"; shift
  if "$@" >/dev/null 2>&1; then
    echo "  PASS $name"; PASS=$((PASS+1))
  else
    echo "  FAIL $name"; FAIL=$((FAIL+1))
  fi
}

json() { curl -sf "$BASE$1"; }

echo "== 1. server health =="
check "/health" curl -sf "$BASE/health"

echo "== 2. report ingestion =="
REPORT='{"node_name":"script-node","node_ip":"10.0.0.9","uav_id":"uav-script",
  "heartbeat_interval_seconds":10,
  "state":{"gps":{"latitude":39.9,"longitude":116.4,"altitude":55},
  "battery":{"voltage":21.8,"remaining_percent":72.5},
  "flight":{"mode":"AUTO","armed":true},
  "health":{"system_status":"OK"}}}'
check "POST /api/v1/uav/report" \
  curl -sf -X POST -H 'Content-Type: application/json' -d "$REPORT" \
  "$BASE/api/v1/uav/report"

echo "== 3. cache reads =="
check "uav list contains node" \
   bash -c "curl -sf $BASE/api/v1/metrics/uav | grep -q script-node"
check "single uav entry" curl -sf "$BASE/api/v1/metrics/uav/script-node"
check "battery value present" \
   bash -c "curl -sf $BASE/api/v1/metrics/uav/script-node | grep -q 72.5"

echo "== 4. CRD record =="
check "uavmetric CR exists" \
   bash -c "curl -sf $BASE/api/v1/crd/uav | grep -q uavmetric-script-node"

echo "== 5. metrics plane =="
check "cluster metrics" curl -sf "$BASE/api/v1/metrics/cluster"
check "nodes metrics" curl -sf "$BASE/api/v1/metrics/nodes"
check "snapshot" curl -sf "$BASE/api/v1/metrics/snapshot"

echo "== 6. analysis engine =="
check "NL query" \
  curl -sf -X POST -H 'Content-Type: application/json' \
  -d '{"question":"is the uav fleet healthy?"}' "$BASE/api/v1/query"

echo "== 7. self-observability =="
check "/metrics exporter" \
  bash -c "curl -sf $BASE/metrics | grep -q k8s_llm_monitor_build_info"

echo "== 8. mock UAV agent (deployments/uav-configmap.yaml) =="
# Extract the embedded mock server, boot it locally, and verify it serves
# the same state shape the pull collector consumes.
MOCK_DIR="$(mktemp -d)"
trap 'rm -rf "$MOCK_DIR"; [ -n "${MOCK_PID:-}" ] && kill "$MOCK_PID" 2>/dev/null' EXIT
python3 - "$MOCK_DIR" <<'PY'
import sys, yaml
cm = yaml.safe_load(open("deployments/uav-configmap.yaml"))
open(sys.argv[1] + "/mock_server.py", "w").write(cm["data"]["mock_server.py"])
PY
UAV_ID=uav-mock-ci NODE_NAME=ci-node BATTERY=77 \
  python3 "$MOCK_DIR/mock_server.py" & MOCK_PID=$!
sleep 4
check "mock /health" curl -sf http://127.0.0.1:9090/health
check "mock state shape" \
  bash -c "curl -sf http://127.0.0.1:9090/api/v1/state | grep -q remaining_percent"
kill "$MOCK_PID" 2>/dev/null; MOCK_PID=""

echo
echo "passed $PASS, failed $FAIL"
[ "$FAIL" -eq 0 ]
