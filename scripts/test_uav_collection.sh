#!/usr/bin/env bash
# End-to-end UAV pipeline check against a running monitor server
# (parity: /root/reference/scripts/test_uav_collection.sh — curl/jq
# verification of report ingestion, cache reads, and the CRD record).
#
# Usage: ./scripts/test_uav_collection.sh [base-url]   (default :8081)
set -euo pipefail

BASE="${1:-http://127.0.0.1:8081}"
PASS=0; FAIL=0

check() {  # check <name> <cmd...>
  local name="$1"; shift
  if "$@" >/dev/null 2>&1; then
    echo "  PASS $name"; PASS=$((PASS+1))
  else
    echo "  FAIL $name"; FAIL=$((FAIL+1))
  fi
}

json() { curl -sf "$BASE$1"; }

echo "== 1. server health =="
check "/health" curl -sf "$BASE/health"

echo "== 2. report ingestion =="
REPORT='{"node_name":"script-node","node_ip":"10.0.0.9","uav_id":"uav-script",
  "heartbeat_interval_seconds":10,
  "state":{"gps":{"latitude":39.9,"longitude":116.4,"altitude":55},
  "battery":{"voltage":21.8,"remaining_percent":72.5},
  "flight":{"mode":"AUTO","armed":true},
  "health":{"system_status":"OK"}}}'
check "POST /api/v1/uav/report" \
  curl -sf -X POST -H 'Content-Type: application/json' -d "$REPORT" \
  "$BASE/api/v1/uav/report"

echo "== 3. cache reads =="
check "uav list contains node" \
   bash -c "curl -sf $BASE/api/v1/metrics/uav | grep -q script-node"
check "single uav entry" curl -sf "$BASE/api/v1/metrics/uav/script-node"
check "battery value present" \
   bash -c "curl -sf $BASE/api/v1/metrics/uav/script-node | grep -q 72.5"

echo "== 4. CRD record =="
check "uavmetric CR exists" \
   bash -c "curl -sf $BASE/api/v1/crd/uav | grep -q uavmetric-script-node"

echo "== 5. metrics plane =="
check "cluster metrics" curl -sf "$BASE/api/v1/metrics/cluster"
check "nodes metrics" curl -sf "$BASE/api/v1/metrics/nodes"
check "snapshot" curl -sf "$BASE/api/v1/metrics/snapshot"

echo "== 6. analysis engine =="
check "NL query" \
  curl -sf -X POST -H 'Content-Type: application/json' \
  -d '{"question":"is the uav fleet healthy?"}' "$BASE/api/v1/query"

echo "== 7. self-observability =="
check "/metrics exporter" \
  bash -c "curl -sf $BASE/metrics | grep -q k8s_llm_monitor_build_info"

echo "== 8. mock UAV agent (deployments/uav-configmap.yaml) =="
# Extract the embedded mock server, boot it locally, and verify it serves
# the same state shape the pull collector consumes.
MOCK_DIR="$(mktemp -d)"
trap 'rm -rf "$MOCK_DIR"; [ -n "${MOCK_PID:-}" ] && kill "$MOCK_PID" 2>/dev/null' EXIT
python3 - "$MOCK_DIR" <<'PY'
import sys, yaml
cm = yaml.safe_load(open("deployments/uav-configmap.yaml"))
open(sys.argv[1] + "/mock_server.py", "w").write(cm["data"]["mock_server.py"])
PY
UAV_ID=uav-mock-ci NODE_NAME=ci-node BATTERY=77 \
  python3 "$MOCK_DIR/mock_server.py" & MOCK_PID=$!
sleep 4
check "mock /health" curl -sf http://127.0.0.1:9090/health
check "mock state shape" \
  bash -c "curl -sf http://127.0.0.1:9090/api/v1/state | grep -q remaining_percent"
kill "$MOCK_PID" 2>/dev/null; MOCK_PID=""

echo "== 9. data integrity =="
STATE="$(json /api/v1/metrics/uav/script-node || true)"
for field in latitude remaining_percent mode system_status; do
  check "field $field" bash -c "echo '$STATE' | grep -q $field"
done

echo "== 10. low-battery visibility =="
LOWBAT='{"node_name":"lowbat-node","uav_id":"uav-low","heartbeat_interval_seconds":10,
  "state":{"battery":{"remaining_percent":12.0},"health":{"system_status":"WARNING"}}}'
check "low-battery report" \
  curl -sf -X POST -H 'Content-Type: application/json' -d "$LOWBAT" \
  "$BASE/api/v1/uav/report"
check "low battery visible" \
  bash -c "curl -sf $BASE/api/v1/metrics/uav/lowbat-node | grep -q '12'"

echo "== 11. response time =="
T0=$(date +%s%N)
for _ in 1 2 3 4 5; do curl -sf "$BASE/api/v1/metrics/uav" >/dev/null; done
T1=$(date +%s%N)
MS=$(( (T1 - T0) / 5000000 ))
if [ "$MS" -lt 1000 ]; then
  echo "  PASS avg response ${MS}ms"; PASS=$((PASS+1))
else
  echo "  FAIL avg response ${MS}ms (>= 1000ms)"; FAIL=$((FAIL+1))
fi

echo "== 12. scheduler assignment chain (kubectl; skipped without a cluster) =="
# Full pipeline: report (above) -> UAVMetric CR -> SchedulingRequest ->
# one-shot scheduler reconcile -> status verify.  Mirrors the reference's
# end-to-end check (scripts/test_uav_collection.sh:1-274) against the NEW
# scheduler, including the heartbeat-staleness gate.
if command -v kubectl >/dev/null 2>&1 && kubectl version --request-timeout=3s >/dev/null 2>&1; then
  kubectl apply -f deployments/uav-metrics-crd.yaml -f deployments/scheduling-crd.yaml >/dev/null
  cat <<'YAML' | kubectl apply -f - >/dev/null
apiVersion: scheduler.io/v1
kind: SchedulingRequest
metadata:
  name: smoke-request
  namespace: default
spec:
  workload: {name: smoke-job, namespace: default}
  minBatteryPercent: 30
YAML
  check "one-shot reconcile" \
    python3 -m k8s_llm_monitor_tpu.cmd.scheduler --once
  PHASE="$(kubectl get schedulingrequest smoke-request -n default \
           -o jsonpath='{.status.phase}' 2>/dev/null || true)"
  if [ "$PHASE" = "Assigned" ] || [ "$PHASE" = "Failed" ]; then
    echo "  PASS request processed (phase=$PHASE)"; PASS=$((PASS+1))
  else
    echo "  FAIL request phase '$PHASE'"; FAIL=$((FAIL+1))
  fi
  kubectl delete schedulingrequest smoke-request -n default >/dev/null 2>&1 || true
else
  echo "  SKIP (no reachable cluster)"
fi

echo
echo "passed $PASS, failed $FAIL"
[ "$FAIL" -eq 0 ]
