"""Closed-loop remediation: verdicts → gated execution → verified recovery.

``RemediationEngine`` is the plan stage the diagnosis pipeline calls after
publishing a verdict.  One pass through ``on_verdict``:

1. **Snapshot** live targets (``plans.TargetSnapshot``) — the plan
   grammar is compiled *from* this snapshot, so the model cannot name a
   resource that does not exist.
2. **Plan**: a grammar-constrained decode on the serving engine when the
   backend supports FSM swaps (``generate_with_grammar``), else the
   deterministic keyword planner (``plans.propose_plan``).  Either way the
   text goes through ``plans.parse_plan`` — the sanctioned parse — before
   anything else sees it.
3. **Execute** (only when ``RemediationConfig.execute`` is on, or a human
   approves the specific plan): idempotency-key replay guard, approval
   gate for destructive verbs (``K8SLLM_REMEDIATE_APPROVE=1`` or
   ``POST /api/v1/remediations/<id>/approve``), per-verb + per-target rate
   limits, per-verb circuit breaker, then dry-run-first through the
   cluster backend (server-side ``dryRun=All`` on the real client,
   simulated validation on the fake).
4. **Verify**: a follow-up diagnosis turn through the session machinery
   on freshly pinned post-action context, AND'd with a deterministic
   per-verb predicate over live state.  Unresolved records re-enter the
   pipeline as synthetic warnings with a capped escalation ladder.

The default posture is **observe-only** (``execute=False``): plans are
generated, stored, and exported, but nothing touches the cluster until an
operator flips the config or approves a specific plan.  Every action and
every refusal is a counted outcome (``remediation_plans_total``), a flight
-recorder note, and a tracer span — a remediator that silently does
nothing would be undiagnosable.
"""

from __future__ import annotations

import hashlib
import logging
import os
import time
from collections import deque
from typing import Any, Callable, Optional

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.diagnosis.grammar import GrammarError, render_verdict
from k8s_llm_monitor_tpu.observability.flight import get_flight_recorder
from k8s_llm_monitor_tpu.observability.tracing import get_tracer
from k8s_llm_monitor_tpu.remediation.plans import (
    DESTRUCTIVE_VERBS,
    PLAN_VERBS,
    TargetSnapshot,
    parse_plan,
    plan_fsm,
    propose_plan,
)
from k8s_llm_monitor_tpu.resilience.retry import CircuitBreaker, CircuitOpen

logger = logging.getLogger("remediation.executor")

__all__ = ["RemediationEngine", "OUTCOMES", "VERIFY_RESULTS"]

#: Execution outcomes pre-seeded in the exporter (extra dynamic outcomes
#: still render; these are the contractual families).
OUTCOMES = ("proposed", "executed", "refused_approval", "refused_breaker",
            "refused_rate", "refused_replay", "error")

VERIFY_RESULTS = ("resolved", "unresolved", "error")

_VERDICT_PREAMBLE = (
    "You are a Kubernetes SRE assistant verifying a remediation action "
    "against live cluster evidence.\n"
)


def _env_approved() -> bool:
    """Blanket operator approval for destructive verbs.  Read per call —
    flipping the env var mid-process takes effect immediately, and tests
    toggle it with monkeypatch."""
    return os.environ.get("K8SLLM_REMEDIATE_APPROVE", "").lower() in (
        "1", "true", "yes")


@guarded_by("_lock", "plans_total", "verify_total", "_records", "_order",
            "_last_verb_t", "_last_target_t", "_executed", "_escalations")
class RemediationEngine:
    """Verdict → plan → gated execution → verification, with counters.

    All time comes from an injectable clock; gate proofs in
    ``tests/test_remediation.py`` drive it with a fake clock.  Thread
    safety matters because ``on_verdict`` runs on the pipeline worker
    while approve/reject arrive on HTTP threads.
    """

    def __init__(self, backend, analysis, cfg=None, *,
                 namespaces: tuple[str, ...] | list[str] = ("default",),
                 pipeline: Any = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        from k8s_llm_monitor_tpu.monitor.config import RemediationConfig

        self.cfg = cfg or RemediationConfig()
        self.backend = backend
        self.analysis = analysis
        self.pipeline = pipeline
        self.namespaces = tuple(namespaces) or ("default",)
        self._clock = clock
        # One breaker per mutating verb: a broken scale path must not
        # stop an unrelated cordon.
        self.breakers: dict[str, CircuitBreaker] = {
            verb: CircuitBreaker(
                failure_threshold=self.cfg.breaker_failures,
                cooldown_s=self.cfg.breaker_cooldown_s,
                clock=clock)
            for verb in PLAN_VERBS if verb != "noop"
        }
        self._seq = 0
        # {(verb, outcome): count} → remediation_plans_total{verb,outcome}
        self.plans_total: dict[tuple[str, str], int] = {}
        # {result: count} → remediation_verify_total{result}
        self.verify_total: dict[str, int] = {}
        self._records: dict[str, dict] = {}
        self._order: deque[str] = deque(maxlen=max(8, self.cfg.history))
        self._last_verb_t: dict[str, float] = {}
        self._last_target_t: dict[tuple[str, str], float] = {}
        self._executed: dict[str, float] = {}  # idempotency key -> t
        self._escalations: dict[str, int] = {}
        # Created last (lockcheck construction rule).
        self._lock = make_lock("remediation.engine")

    # -- counting / recording --------------------------------------------

    def _count(self, verb: str, outcome: str) -> None:
        with self._lock:
            key = (verb, outcome)
            self.plans_total[key] = self.plans_total.get(key, 0) + 1

    def _note(self, rec: dict, outcome: str, detail: str = "") -> None:
        """Outcome bookkeeping shared by every gate: counter, record
        fields, flight-recorder note."""
        rec["outcome"] = outcome
        if detail:
            rec["detail"] = detail
        self._count(rec["plan"]["verb"], outcome)
        get_flight_recorder().note(
            "remediation", id=rec["id"], verb=rec["plan"]["verb"],
            target=rec["plan"].get("name", ""), outcome=outcome,
            detail=detail)

    def _store(self, rec: dict) -> None:
        with self._lock:
            if len(self._order) == self._order.maxlen:
                self._records.pop(self._order[0], None)
            self._records[rec["id"]] = rec
            self._order.append(rec["id"])

    # -- planning ---------------------------------------------------------

    def snapshot_targets(self) -> TargetSnapshot:
        return TargetSnapshot.from_backend(self.backend, self.namespaces)

    def _plan_prompt(self, snapshot: TargetSnapshot, verdict: dict,
                     trigger: str) -> str:
        lines = ["## Live targets"]
        lines += [f"- pod {p}" for p in snapshot.pods]
        lines += [f"- workload {w}" for w in snapshot.workloads]
        lines += [f"- node {n}" for n in snapshot.nodes]
        lines += [f"- statefulset {s}" for s in snapshot.statefulsets]
        return (
            "You are a Kubernetes SRE choosing ONE bounded remediation "
            "action against the live targets below.\n"
            + "\n".join(lines)
            + f"\n## Verdict\nseverity={verdict.get('severity')} "
            f"component={verdict.get('component')} "
            f"root_cause={verdict.get('root_cause')}\n"
            f"## Trigger\n{trigger}\n"
            "## Plan\nRespond with exactly one JSON action plan:\n"
        )

    def _plan_text(self, snapshot: TargetSnapshot, verdict: dict,
                   trigger: str, context: str) -> tuple[str, str]:
        """(plan text, planner name).  The constrained-engine path decodes
        under the snapshot's padded FSM; anything else — including an
        engine emitting an out-of-snapshot plan, which the FSM makes
        unreachable — falls back to the deterministic planner."""
        llm = getattr(self.analysis, "backend", None)
        if llm is not None and getattr(llm, "supports_grammar", False):
            try:
                text = llm.generate_with_grammar(
                    self._plan_prompt(snapshot, verdict, trigger),
                    plan_fsm(snapshot),
                    temperature=0.0, slo_class="batch")
                if text:
                    parse_plan(text, snapshot)  # raises if invalid
                    return text, "engine"
            except GrammarError as exc:
                logger.warning("engine plan rejected by grammar: %s", exc)
            except Exception:  # noqa: BLE001 — planner must degrade
                logger.exception("constrained plan decode failed")
        return propose_plan(snapshot, verdict, trigger, context), "heuristic"

    def on_verdict(self, verdict: dict, trigger: str = "",
                   context: str = "") -> Optional[dict]:
        """The pipeline's plan stage.  Returns the new record (or None
        when the verdict does not warrant one).  Never raises — a broken
        plan stage must not take the diagnosis worker down."""
        if not self.cfg.enabled:
            return None
        if verdict.get("severity") not in ("warning", "critical"):
            return None
        tracer = get_tracer()
        try:
            with tracer.span("remediation.plan",
                             attrs={"trigger": trigger[:120]}):
                snapshot = self.snapshot_targets()
                text, planner = self._plan_text(
                    snapshot, verdict, trigger, context)
                plan = parse_plan(text, snapshot)
        except GrammarError as exc:
            logger.warning("plan stage produced no valid plan: %s", exc)
            self._count("noop", "error")
            return None
        except Exception:  # noqa: BLE001 — plan stage is best-effort
            logger.exception("plan stage failed")
            self._count("noop", "error")
            return None
        with self._lock:
            self._seq += 1
            rec_id = f"rem-{self._seq:05d}"
        target_ref = (f"{plan['namespace']}/{plan['name']}"
                      if plan["namespace"] else plan["name"])
        rec = {
            "id": rec_id,
            "t_mono": round(self._clock(), 3),
            "plan": plan,
            "text": text,
            "planner": planner,
            "verdict": dict(verdict),
            "trigger": trigger,
            "status": "proposed",
            "outcome": "",
            "detail": "",
            "approved": False,
            "escalation": self._escalations.get(
                self._esc_key(plan), 0),
            "idempotency_key": self._idem_key(plan, trigger),
            "verify": None,
        }
        self._store(rec)
        self._note(rec, "proposed", f"planner={planner} target={target_ref}")
        if self.cfg.execute:
            self.execute(rec_id)
        return rec

    # -- gating / execution ----------------------------------------------

    @staticmethod
    def _idem_key(plan: dict, trigger: str) -> str:
        raw = "|".join([plan["verb"], plan["namespace"], plan["name"],
                        str(plan.get("replicas", "")), trigger])
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    @staticmethod
    def _esc_key(plan: dict) -> str:
        return f"{plan['verb']}|{plan['namespace']}/{plan['name']}"

    def _apply(self, plan: dict, dry_run: bool) -> None:
        verb = plan["verb"]
        if verb == "noop":
            return
        if verb == "scale":
            self.backend.scale_statefulset(
                plan["namespace"], plan["name"], plan["replicas"],
                dry_run=dry_run)
        elif verb == "rollout_restart":
            self.backend.rollout_restart(
                plan["namespace"], plan["name"], dry_run=dry_run)
        elif verb == "cordon":
            self.backend.cordon_node(plan["name"], dry_run=dry_run)
        elif verb == "delete_pod":
            self.backend.delete_pod(
                plan["namespace"], plan["name"], dry_run=dry_run)

    def _refusal(self, rec: dict, now: float) -> Optional[tuple[str, str]]:
        """The gate ladder; returns (outcome, detail) or None when every
        gate is open.  Order: replay guard (an already-done action makes
        every other question moot), approval, rate limits, breaker."""
        plan = rec["plan"]
        verb = plan["verb"]
        with self._lock:
            done_t = self._executed.get(rec["idempotency_key"])
        if done_t is not None and now - done_t < self.cfg.replay_window_s:
            return ("refused_replay",
                    f"identical action executed {now - done_t:.1f}s ago")
        if verb in DESTRUCTIVE_VERBS and not rec["approved"] \
                and not _env_approved():
            return ("refused_approval",
                    "destructive verb requires K8SLLM_REMEDIATE_APPROVE=1 "
                    "or POST .../approve")
        if verb == "noop":
            return None  # nothing below applies to a no-op
        with self._lock:
            last_v = self._last_verb_t.get(verb)
            last_t = self._last_target_t.get((verb, plan["name"]))
        if last_v is not None and now - last_v < self.cfg.verb_interval_s:
            return ("refused_rate", f"verb {verb} on cooldown")
        if last_t is not None and now - last_t < self.cfg.target_interval_s:
            return ("refused_rate",
                    f"target {plan['name']} on cooldown for {verb}")
        try:
            self.breakers[verb].before_call()
        except CircuitOpen as exc:
            return ("refused_breaker", str(exc))
        return None

    def execute(self, rec_id: str) -> str:
        """Run one stored plan through the full gate ladder.  Returns the
        outcome string; the record's status/outcome fields are updated in
        place."""
        with self._lock:
            rec = self._records.get(rec_id)
        if rec is None:
            return "not_found"
        if rec["status"] in ("executed", "verified", "rejected"):
            self._note(rec, "refused_replay",
                       f"record already {rec['status']}")
            return "refused_replay"
        plan = rec["plan"]
        verb = plan["verb"]
        now = self._clock()
        refusal = self._refusal(rec, now)
        if refusal is not None:
            outcome, detail = refusal
            if outcome == "refused_approval":
                rec["status"] = "awaiting_approval"
            self._note(rec, outcome, detail)
            return outcome
        tracer = get_tracer()
        breaker = self.breakers.get(verb)
        t0 = time.monotonic()
        try:
            with tracer.span("remediation.execute",
                             attrs={"verb": verb, "target": plan["name"]}):
                if self.cfg.dry_run_first:
                    self._apply(plan, dry_run=True)
                self._apply(plan, dry_run=False)
        except Exception as exc:  # noqa: BLE001 — cluster fault
            if breaker is not None:
                breaker.record_failure()
            rec["status"] = "error"
            self._note(rec, "error", f"{type(exc).__name__}: {exc}")
            logger.warning("remediation %s %s failed: %s",
                           verb, plan["name"], exc)
            return "error"
        if breaker is not None:
            breaker.record_success()
        with self._lock:
            self._last_verb_t[verb] = now
            self._last_target_t[(verb, plan["name"])] = now
            self._executed[rec["idempotency_key"]] = now
        rec["status"] = "executed"
        rec["execute_ms"] = round((time.monotonic() - t0) * 1e3, 3)
        self._note(rec, "executed", "dry-run validated"
                   if self.cfg.dry_run_first else "")
        if self.cfg.verify:
            self.verify(rec_id)
        return "executed"

    # -- approval (the human-in-the-loop path) ----------------------------

    def approve(self, rec_id: str) -> Optional[dict]:
        """Explicit per-plan approval.  Approving executes immediately,
        even in observe-only mode — this IS the operator saying "do it"."""
        with self._lock:
            rec = self._records.get(rec_id)
        if rec is None:
            return None
        rec["approved"] = True
        self.execute(rec_id)
        return rec

    def reject(self, rec_id: str) -> Optional[dict]:
        with self._lock:
            rec = self._records.get(rec_id)
        if rec is None:
            return None
        if rec["status"] in ("proposed", "awaiting_approval"):
            rec["status"] = "rejected"
            self._note(rec, "rejected", "operator rejection")
        return rec

    # -- verification ------------------------------------------------------

    def _cluster_context(self) -> str:
        """Post-action evidence block: live pods/nodes/statefulsets in the
        ``- `` line shape every backend's issue extractor understands.
        Deliberately NOT the pipeline's event ring — old warnings from the
        incident would poison a health check of the *current* state."""
        lines = ["## Cluster state (post-action)"]
        for ns in self.namespaces:
            try:
                pods = self.backend.list_pods(ns)
            except Exception:  # noqa: BLE001 — partial evidence is fine
                continue
            for pod in pods:
                meta = pod.get("metadata") or {}
                status = pod.get("status") or {}
                restarts = sum(
                    int(s.get("restartCount", 0))
                    for s in status.get("containerStatuses", []))
                lines.append(
                    f"- pod {ns}/{meta.get('name', '?')} "
                    f"phase={status.get('phase', '?')} restarts={restarts}")
        try:
            nodes = self.backend.list_nodes()
        except Exception:  # noqa: BLE001
            nodes = []
        for node in nodes:
            meta = node.get("metadata") or {}
            spec = node.get("spec") or {}
            conds = {c.get("type"): c.get("status")
                     for c in (node.get("status") or {}).get("conditions", [])}
            lines.append(
                f"- node {meta.get('name', '?')} "
                f"ready={conds.get('Ready', '?')} "
                f"unschedulable={bool(spec.get('unschedulable'))}")
        return "\n".join(lines) + "\n"

    def _condition_cleared(self, plan: dict) -> bool:
        """Deterministic per-verb recovery predicate over live state — the
        half of verification that cannot hallucinate."""
        verb, ns, name = plan["verb"], plan["namespace"], plan["name"]
        if verb == "noop":
            return True
        if verb == "scale":
            scale = self.backend.get_statefulset_scale(ns, name)
            observed = scale if isinstance(scale, int) else int(
                (scale.get("spec") or {}).get("replicas", -1))
            return observed == plan["replicas"]
        if verb == "delete_pod":
            pods = self.backend.list_pods(ns)
            return all((p.get("metadata") or {}).get("name") != name
                       for p in pods)
        if verb == "cordon":
            for node in self.backend.list_nodes():
                if (node.get("metadata") or {}).get("name") == name:
                    return bool((node.get("spec") or {}).get("unschedulable"))
            return False
        if verb == "rollout_restart":
            matched = [
                p for p in self.backend.list_pods(ns)
                if ((p.get("metadata") or {}).get("name") or ""
                    ).startswith(name)
            ]
            if not matched:
                return False
            for pod in matched:
                status = pod.get("status") or {}
                if status.get("phase") != "Running":
                    return False
                for s in status.get("containerStatuses", []):
                    if int(s.get("restartCount", 0)) > 0:
                        return False
            return True
        return False

    def verify(self, rec_id: str) -> str:
        """Post-action verification turn.  Result ∈ VERIFY_RESULTS; the
        record moves to ``verified`` / ``unresolved`` / ``escalated``."""
        with self._lock:
            rec = self._records.get(rec_id)
        if rec is None:
            return "error"
        plan = rec["plan"]
        tracer = get_tracer()
        try:
            with tracer.span("remediation.verify",
                             attrs={"verb": plan["verb"]}):
                cleared = self._condition_cleared(plan)
                verdict = self._verify_verdict(rec)
                resolved = cleared and verdict.get("severity") != "critical"
        except Exception as exc:  # noqa: BLE001 — verification fault
            with self._lock:
                self.verify_total["error"] = \
                    self.verify_total.get("error", 0) + 1
            rec["verify"] = {"result": "error", "detail": str(exc)}
            logger.exception("remediation verify failed")
            return "error"
        result = "resolved" if resolved else "unresolved"
        with self._lock:
            self.verify_total[result] = self.verify_total.get(result, 0) + 1
        rec["verify"] = {
            "result": result,
            "condition_cleared": cleared,
            "verdict": verdict,
        }
        get_flight_recorder().note(
            "remediation_verify", id=rec["id"], verb=plan["verb"],
            result=result)
        if resolved:
            rec["status"] = "verified"
            return result
        self._escalate(rec)
        return result

    def _verify_verdict(self, rec: dict) -> dict:
        """The LLM half of verification: a constrained diagnosis turn on a
        session pinned to freshly collected post-action context, so retry
        turns replay a cached prefix instead of re-prefilling."""
        plan = rec["plan"]
        question = (
            f"Remediation {plan['verb']} on "
            f"{plan['namespace'] + '/' if plan['namespace'] else ''}"
            f"{plan['name'] or 'cluster'} was executed for: "
            f"{rec['trigger'] or 'a diagnosis verdict'}. "
            "Is the triggering condition cleared?")
        sessions = getattr(self.analysis, "sessions", None)
        context = None
        if sessions is not None:
            session, _ = sessions.get_or_create(
                f"remediation-{rec['id']}",
                lambda: _VERDICT_PREAMBLE + self._cluster_context())
            context = session.context
        verdict = self.analysis.diagnose(
            question, context=context, slo_class="batch")
        if sessions is not None:
            session.record(question, render_verdict(
                verdict["severity"], verdict["component"],
                verdict["root_cause"], verdict["recommendation"],
                verdict["confidence"]))
        return verdict

    def _escalate(self, rec: dict) -> None:
        """Capped retry ladder: an unresolved record re-enters the
        pipeline as a synthetic warning (so the next burst re-plans with
        fresh state); past the cap it parks as ``escalated`` for a
        human."""
        key = self._esc_key(rec["plan"])
        with self._lock:
            n = self._escalations.get(key, 0) + 1
            self._escalations[key] = n
        rec["escalation"] = n
        if n > self.cfg.max_retries:
            rec["status"] = "escalated"
            logger.warning("remediation escalated after %d attempts: %s",
                           n, key)
            return
        rec["status"] = "unresolved"
        if self.pipeline is None:
            return
        from k8s_llm_monitor_tpu.monitor.models import EventInfo

        event = EventInfo(
            type="Warning",
            reason=f"RemediationUnresolved:{rec['plan']['verb']}",
            message=(f"plan {rec['id']} ({rec['plan']['verb']} "
                     f"{rec['plan']['name']}) did not clear: "
                     f"{rec['trigger']} (attempt {n})"),
            source="remediation",
        )
        try:
            self.pipeline.offer(event)
        except Exception:  # noqa: BLE001 — re-entry is best-effort
            logger.exception("remediation re-entry offer failed")

    # -- observability -----------------------------------------------------

    def records(self, limit: int = 0) -> list[dict]:
        """Newest-first JSON-safe record list for the HTTP API."""
        with self._lock:
            ids = list(self._order)
            out = [dict(self._records[i]) for i in reversed(ids)
                   if i in self._records]
        return out[:limit] if limit > 0 else out

    def get(self, rec_id: str) -> Optional[dict]:
        with self._lock:
            rec = self._records.get(rec_id)
            return dict(rec) if rec is not None else None

    def counters(self) -> dict:
        with self._lock:
            return {
                "plans_total": dict(self.plans_total),
                "verify_total": dict(self.verify_total),
                "breaker_open": {
                    verb: 1 if br.state == "open" else 0
                    for verb, br in sorted(self.breakers.items())},
            }

    def snapshot(self) -> dict:
        """JSON-safe block for /api/v1/stats."""
        with self._lock:
            plans = {f"{verb}/{outcome}": n
                     for (verb, outcome), n
                     in sorted(self.plans_total.items())}
            verify = dict(self.verify_total)
            n_records = len(self._records)
        return {
            "enabled": bool(self.cfg.enabled),
            "execute": bool(self.cfg.execute),
            "records": n_records,
            "plans_total": plans,
            "verify_total": verify,
            "breakers": {verb: br.state
                         for verb, br in sorted(self.breakers.items())},
        }
