"""Action-plan grammar: bounded kubectl verbs over live-state targets.

The diagnosis pipeline ends at a verdict; this module defines the *plan*
language that turns verdicts into executable actions.  The design point is
that the model is structurally unable to name anything that does not
exist: every target (pod, node, workload, statefulset) is enumerated from
a ``TargetSnapshot`` of live cluster state and baked into the schema as an
enum, so the compiled grammar only admits plans against real resources.

The schema is an ``anyOf`` of one object shape per verb — each verb only
admits its own target kind (a ``cordon`` cannot name a pod, a
``delete_pod`` cannot name a node) — compiled through the PR 6 grammar
compiler (``diagnosis/grammar.py``) into a char DFA and lifted to a token
FSM for on-device constrained decode.

Zero recompiles across snapshots: plan FSM transition tables are padded to
a fixed ``[PLAN_STATE_CAP + 1, vocab]`` shape (padding rows are
unreachable, so semantics are untouched).  The engine's decode program
treats the table as a runtime argument keyed by shape, so swapping one
snapshot's plan grammar for another's — or alternating with the verdict
grammar — never triggers a new XLA compile after first warm-up
(``devtools/traceguard.py`` ``grammar_swap`` path proves it).

``parse_plan`` funnels through ``grammar.parse_with_dfa`` — the one
sanctioned ``json.loads`` — then re-checks every target against the
snapshot, defense in depth for plans arriving from non-FSM backends.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from k8s_llm_monitor_tpu.diagnosis.grammar import (
    CharDFA,
    GrammarError,
    TokenFSM,
    compile_schema,
    parse_with_dfa,
    token_fsm,
)

__all__ = [
    "PLAN_VERBS",
    "DESTRUCTIVE_VERBS",
    "PLAN_STATE_CAP",
    "MAX_REPLICAS",
    "REASON_MAX_CHARS",
    "TargetSnapshot",
    "build_plan_schema",
    "plan_dfa",
    "plan_fsm",
    "parse_plan",
    "render_plan",
    "propose_plan",
    "workload_of",
]

#: The closed verb set.  Order matters only for docs; the grammar is an
#: alternation.  ``noop`` is always admissible — a planner that has nothing
#: safe to do must still be able to close the object.
PLAN_VERBS = ("scale", "rollout_restart", "cordon", "delete_pod", "noop")

#: Verbs that remove capacity or workloads: refused without an approval
#: (K8SLLM_REMEDIATE_APPROVE=1 or per-plan approve via the HTTP API).
DESTRUCTIVE_VERBS = frozenset({"cordon", "delete_pod"})

#: Fixed token-FSM row count every plan grammar is padded to.  One shape →
#: one compiled decode variant → snapshot-to-snapshot grammar swaps are
#: recompile-free.  Sized ~2x the largest grammar the default enumeration
#: caps can produce; ``plan_fsm`` raises before silently truncating.
PLAN_STATE_CAP = 4096

#: Bounded replica range for the ``scale`` verb (enumerated literals in
#: the grammar — the model cannot ask for 10^9 replicas).
MAX_REPLICAS = 16

REASON_MAX_CHARS = 96

# Enumeration caps: bound the DFA size no matter how big the cluster is.
# Selection under pressure keeps the *interesting* entries (non-Running
# pods first), so caps trim healthy bulk, not the incident.
MAX_PODS = 24
MAX_NODES = 12
MAX_WORKLOADS = 12
MAX_STATEFULSETS = 8
MAX_NAMESPACES = 8

_HASHY = re.compile(r"^[a-z0-9]{4,10}$")


def workload_of(pod_name: str) -> str:
    """Controller-ish workload name for a pod: strip up to two trailing
    hash-like segments (``web-frontend-7d4b9c6f5-x2x1p`` → ``web-frontend``).
    Heuristic by design — the snapshot only uses it to *enumerate* restart
    targets; execution matches pods back by prefix."""
    parts = pod_name.split("-")
    for _ in range(2):
        if len(parts) > 1 and _HASHY.match(parts[-1]) \
                and any(c.isdigit() for c in parts[-1]):
            parts.pop()
    return "-".join(parts)


@dataclass(frozen=True)
class TargetSnapshot:
    """Frozen enumeration of live targets a plan may name.

    Entries are ``"namespace/name"`` refs (pods, workloads, statefulsets)
    or bare node names, pre-joined so the grammar admits only valid
    namespace+name *pairs* — separate enums would let the model cross
    them.  ``statefulset_replicas`` carries observed replica counts for
    the deterministic planner's scale proposals.
    """

    pods: tuple[str, ...] = ()
    nodes: tuple[str, ...] = ()
    workloads: tuple[str, ...] = ()
    statefulsets: tuple[str, ...] = ()
    statefulset_replicas: dict[str, int] = field(default_factory=dict)

    def key(self) -> tuple:
        """Cache key for the compiled grammar (replica counts don't change
        the admitted language)."""
        return (self.pods, self.nodes, self.workloads, self.statefulsets)

    @classmethod
    def from_backend(cls, backend, namespaces: list[str] | tuple[str, ...],
                     ) -> "TargetSnapshot":
        """Enumerate targets through the ``ClusterBackend`` seam.  Reads
        are best-effort per kind: a failing list degrades that verb's
        target set to empty (its grammar arm drops out) instead of failing
        the plan stage outright."""
        namespaces = list(namespaces)[:MAX_NAMESPACES] or ["default"]
        pods: list[tuple[bool, str]] = []
        workloads: list[str] = []
        nodes: list[str] = []
        stss: list[str] = []
        replicas: dict[str, int] = {}
        for ns in namespaces:
            try:
                listed = backend.list_pods(ns)
            except Exception:  # noqa: BLE001 — degrade per kind
                listed = []
            for pod in listed:
                name = (pod.get("metadata") or {}).get("name", "")
                if not name or not _ref_ok(name):
                    continue
                phase = (pod.get("status") or {}).get("phase", "")
                # Unhealthy pods sort first so caps keep the incident.
                pods.append((phase == "Running", f"{ns}/{name}"))
                wl = f"{ns}/{workload_of(name)}"
                if wl not in workloads and _ref_ok(wl):
                    workloads.append(wl)
        try:
            listed_nodes = backend.list_nodes()
        except Exception:  # noqa: BLE001
            listed_nodes = []
        for node in listed_nodes:
            name = (node.get("metadata") or {}).get("name", "")
            if name and _ref_ok(name):
                nodes.append(name)
        lister = getattr(backend, "list_statefulsets", None)
        if callable(lister):
            for ns in namespaces:
                try:
                    listed_sts = lister(ns)
                except Exception:  # noqa: BLE001
                    listed_sts = []
                for sts in listed_sts:
                    name = (sts.get("metadata") or {}).get("name", "")
                    if not name or not _ref_ok(name):
                        continue
                    ref = f"{ns}/{name}"
                    stss.append(ref)
                    spec = sts.get("spec") or {}
                    replicas[ref] = int(spec.get("replicas", 0))
        pods.sort()  # False (non-Running) before True
        return cls(
            pods=tuple(ref for _, ref in pods[:MAX_PODS]),
            nodes=tuple(sorted(nodes)[:MAX_NODES]),
            workloads=tuple(sorted(workloads)[:MAX_WORKLOADS]),
            statefulsets=tuple(sorted(stss)[:MAX_STATEFULSETS]),
            statefulset_replicas=replicas,
        )


_REF_RE = re.compile(r"^[A-Za-z0-9._/-]+$")


def _ref_ok(ref: str) -> bool:
    """Targets must fit the grammar's JSON-safe charset; k8s DNS names
    always do — this guards against exotic CR names leaking in."""
    return bool(_REF_RE.match(ref)) and len(ref) <= 96


def build_plan_schema(snapshot: TargetSnapshot) -> dict[str, Any]:
    """The ``anyOf``-of-verbs schema for one snapshot.  Verb arms with no
    live targets drop out entirely (an empty enum is uncompilable and
    would be meaningless anyway); ``noop`` is always present."""
    reason = {"type": "string", "minLength": 1,
              "maxLength": REASON_MAX_CHARS}
    arms: list[dict[str, Any]] = []
    if snapshot.statefulsets:
        arms.append({"type": "object", "properties": {
            "verb": {"enum": ["scale"]},
            "target": {"enum": list(snapshot.statefulsets)},
            "replicas": {"type": "integer", "minimum": 0,
                         "maximum": MAX_REPLICAS},
            "reason": reason,
        }})
    if snapshot.workloads:
        arms.append({"type": "object", "properties": {
            "verb": {"enum": ["rollout_restart"]},
            "target": {"enum": list(snapshot.workloads)},
            "reason": reason,
        }})
    if snapshot.nodes:
        arms.append({"type": "object", "properties": {
            "verb": {"enum": ["cordon"]},
            "target": {"enum": list(snapshot.nodes)},
            "reason": reason,
        }})
    if snapshot.pods:
        arms.append({"type": "object", "properties": {
            "verb": {"enum": ["delete_pod"]},
            "target": {"enum": list(snapshot.pods)},
            "reason": reason,
        }})
    arms.append({"type": "object", "properties": {
        "verb": {"enum": ["noop"]},
        "reason": reason,
    }})
    return {"anyOf": arms}


# Compiled-grammar caches, keyed by snapshot content.  Bounded: plan
# grammars are per-incident, not per-request, and each padded FSM is a few
# MB — keep the last few snapshots warm, drop the oldest beyond that.
_DFA_CACHE: dict[tuple, CharDFA] = {}
_FSM_CACHE: dict[tuple, TokenFSM] = {}
_CACHE_CAP = 4


def _cache_put(cache: dict, key: tuple, value) -> None:
    if key not in cache and len(cache) >= _CACHE_CAP:
        cache.pop(next(iter(cache)))
    cache[key] = value


def plan_dfa(snapshot: TargetSnapshot) -> CharDFA:
    key = snapshot.key()
    dfa = _DFA_CACHE.get(key)
    if dfa is None:
        dfa = compile_schema(build_plan_schema(snapshot))
        _cache_put(_DFA_CACHE, key, dfa)
    return dfa


def plan_fsm(snapshot: TargetSnapshot, *, eos_id: int = 2,
             vocab_size: int = 259) -> TokenFSM:
    """Padded token FSM for one snapshot's plan grammar.

    Rows are padded to ``PLAN_STATE_CAP + 1`` with all-disallowed (-1)
    entries — unreachable from any live state, so the admitted language is
    exactly the unpadded grammar's.  The fixed shape is the recompile-free
    contract: every snapshot's plan FSM is the same ``[rows, vocab]``
    runtime argument to the decode program.
    """
    key = snapshot.key() + (eos_id, vocab_size)
    fsm = _FSM_CACHE.get(key)
    if fsm is not None:
        return fsm
    base = token_fsm(plan_dfa(snapshot), eos_id=eos_id,
                     vocab_size=vocab_size)
    rows = PLAN_STATE_CAP + 1
    if base.trans.shape[0] > rows:
        raise GrammarError(
            f"plan grammar needs {base.trans.shape[0]} states "
            f"(cap {rows}); lower the snapshot enumeration caps")
    trans = np.full((rows, vocab_size), -1, dtype=np.int32)
    trans[: base.trans.shape[0]] = base.trans
    accept = np.zeros(rows, dtype=bool)
    accept[: base.accept.shape[0]] = base.accept
    fsm = TokenFSM(trans=trans, start=base.start, accept=accept,
                   eos_id=eos_id, max_len=base.max_len)
    _cache_put(_FSM_CACHE, key, fsm)
    return fsm


def parse_plan(text: str, snapshot: TargetSnapshot) -> dict[str, Any]:
    """Grammar-validate, parse, and semantically check one plan.

    Returns ``{"verb", "namespace", "name", "replicas", "reason"}``
    (namespace empty for node targets and noop).  Raises ``GrammarError``
    for anything the constrained sampler could not have produced *or*
    whose target is not in the snapshot — the latter is unreachable for
    FSM-decoded plans and exists for render-path backends.
    """
    plan = parse_with_dfa(text, plan_dfa(snapshot))
    verb = plan.get("verb", "")
    if verb not in PLAN_VERBS:
        raise GrammarError(f"unknown plan verb {verb!r}")
    target = str(plan.get("target", ""))
    pools = {
        "scale": snapshot.statefulsets,
        "rollout_restart": snapshot.workloads,
        "cordon": snapshot.nodes,
        "delete_pod": snapshot.pods,
    }
    if verb != "noop":
        if target not in pools[verb]:
            raise GrammarError(
                f"plan target {target!r} not in the live snapshot")
    namespace, _, name = target.partition("/")
    if verb == "cordon":
        namespace, name = "", target
    out = {
        "verb": verb,
        "namespace": namespace,
        "name": name,
        "reason": str(plan.get("reason", "")),
    }
    if verb == "scale":
        replicas = int(plan["replicas"])
        if not 0 <= replicas <= MAX_REPLICAS:
            raise GrammarError(f"replicas {replicas} out of range")
        out["replicas"] = replicas
    return out


def render_plan(verb: str, *, target: str = "", reason: str = "",
                replicas: int | None = None) -> str:
    """Canonical plan serialization — the deterministic planner's path,
    mirroring ``grammar.render_verdict``: fields are filtered to the
    grammar's charset and clamped, so the output parses by construction
    (assuming the target is in the snapshot)."""
    def clean(s: str, max_len: int) -> str:
        out = "".join(
            ch for ch in s
            if 0x20 <= ord(ch) < 0x7F and ch not in ('"', "\\"))
        return out[:max_len] or "n/a"

    if verb not in PLAN_VERBS:
        raise GrammarError(f"unknown plan verb {verb!r}")
    parts = [f'"verb":"{verb}"']
    if verb != "noop":
        parts.append(f'"target":"{clean(target, 96)}"')
    if verb == "scale":
        r = min(max(int(replicas or 0), 0), MAX_REPLICAS)
        parts.append(f'"replicas":{r}')
    parts.append(f'"reason":"{clean(reason, REASON_MAX_CHARS)}"')
    return "{" + ",".join(parts) + "}"


def propose_plan(snapshot: TargetSnapshot, verdict: dict[str, Any],
                 trigger: str = "", context: str = "") -> str:
    """Deterministic scenario→verb planner (the template-backend path and
    the fallback when no constrained engine is wired).

    Keyword ladder over the verdict + trigger + context text, most
    specific first; a verb with no matching live target degrades to
    ``noop`` rather than guessing.
    """
    text = " ".join([
        trigger, str(verdict.get("component", "")),
        str(verdict.get("root_cause", "")),
        str(verdict.get("recommendation", "")), context,
    ]).lower()

    def find(pool: tuple[str, ...]) -> str:
        for ref in pool:
            name = ref.rsplit("/", 1)[-1]
            if name.lower() in text:
                return ref
        return ""

    if "failedscheduling" in text or "unschedulable pod" in text \
            or "stale scheduler" in text:
        target = find(snapshot.pods)
        if target:
            return render_plan("delete_pod", target=target,
                               reason=f"reschedule stale pod ({trigger})")
    if "pressure" in text or "notready" in text or "not ready" in text:
        target = find(snapshot.nodes)
        if target:
            return render_plan("cordon", target=target,
                               reason=f"fence pressured node ({trigger})")
    if "oom" in text or "crash" in text or "backoff" in text:
        target = find(snapshot.workloads)
        if target:
            return render_plan("rollout_restart", target=target,
                               reason=f"restart crashing workload ({trigger})")
    if ("queue" in text or "scale up" in text or "overload" in text) \
            and snapshot.statefulsets:
        target = find(snapshot.statefulsets) or snapshot.statefulsets[0]
        current = snapshot.statefulset_replicas.get(target, 1)
        return render_plan("scale", target=target,
                           replicas=min(current + 1, MAX_REPLICAS),
                           reason=f"add capacity ({trigger})")
    return render_plan("noop",
                       reason=f"no safe action for: {trigger or 'verdict'}")
