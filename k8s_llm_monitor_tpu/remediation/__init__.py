"""Closed-loop remediation: grammar-bounded action plans, gated execution,
verified recovery.

Two modules:

- ``plans`` — the action-plan grammar.  Verbs are a closed set
  (``scale``/``rollout_restart``/``cordon``/``delete_pod``/``noop``) and
  every target is enumerated from a live-state ``TargetSnapshot``, so the
  compiled token FSM structurally cannot name a nonexistent resource.
- ``executor`` — ``RemediationEngine``: dry-run-first execution behind
  per-verb circuit breakers, rate limits, an approval gate for destructive
  verbs, idempotent replay protection, and a post-action verification turn
  through the diagnosis session machinery.

See ``docs/remediation.md`` for the verb catalog and operational posture
(observe-only by default).
"""

from k8s_llm_monitor_tpu.remediation.executor import (
    OUTCOMES,
    VERIFY_RESULTS,
    RemediationEngine,
)
from k8s_llm_monitor_tpu.remediation.plans import (
    DESTRUCTIVE_VERBS,
    PLAN_STATE_CAP,
    PLAN_VERBS,
    TargetSnapshot,
    build_plan_schema,
    parse_plan,
    plan_dfa,
    plan_fsm,
    propose_plan,
    render_plan,
)

__all__ = [
    "RemediationEngine",
    "OUTCOMES",
    "VERIFY_RESULTS",
    "PLAN_VERBS",
    "DESTRUCTIVE_VERBS",
    "PLAN_STATE_CAP",
    "TargetSnapshot",
    "build_plan_schema",
    "plan_dfa",
    "plan_fsm",
    "parse_plan",
    "render_plan",
    "propose_plan",
]
