"""Inference serving: paged KV allocator, continuous-batching engine, and
the concurrent service front-end."""

from k8s_llm_monitor_tpu.serving.kv_cache import BlockAllocator
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    GenerationResult,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.service import EngineService, RequestHandle
from k8s_llm_monitor_tpu.serving.supervisor import EngineSupervisor

__all__ = [
    "BlockAllocator",
    "EngineConfig",
    "EngineService",
    "EngineSupervisor",
    "GenerationRequest",
    "GenerationResult",
    "InferenceEngine",
    "RequestHandle",
    "SamplingParams",
]
