"""Inference serving: paged KV allocator + continuous-batching engine."""

from k8s_llm_monitor_tpu.serving.kv_cache import BlockAllocator
from k8s_llm_monitor_tpu.serving.engine import (
    EngineConfig,
    GenerationRequest,
    GenerationResult,
    InferenceEngine,
    SamplingParams,
)

__all__ = [
    "BlockAllocator",
    "EngineConfig",
    "GenerationRequest",
    "GenerationResult",
    "InferenceEngine",
    "SamplingParams",
]
