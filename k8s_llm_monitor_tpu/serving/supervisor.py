"""Engine supervisor: rebuild-and-replay over a dead or wedged step loop.

PR 2 made the engine resilient *within* a healthy process; this module
survives the process-lifecycle failures Kubernetes actually deals out.
The :class:`EngineSupervisor` owns the :class:`EngineService` and watches
two death signals:

  * ``service._dead``          — the step loop raised and exited;
  * a stale step-loop heartbeat with work pending — the loop is wedged
    inside a dispatch that will never return.

On either, it tears the service down, rebuilds the engine through the
injected ``engine_factory`` (a fresh engine means a fresh KV allocator —
free count back to baseline by construction), and re-admits every
incomplete request — idempotent by request id, with already-streamed
tokens folded into the prompt and ``max_tokens`` trimmed so no token is
ever generated twice (the same recompute idiom as the engine's
``_requeue_or_fail``).  Restarts burn a ``max_restarts`` budget with
``Backoff`` between attempts; past the budget the supervisor gives up,
fails the survivors with cause, and pins UNHEALTHY.

Request durability spans processes through the optional
:class:`~k8s_llm_monitor_tpu.resilience.journal.RequestJournal`: admits
are journaled write-ahead, progress is checkpointed from the service's
observer hook (before tokens reach the caller), and a warm start replays
whatever the previous process never finished — before traffic is served.

States (exporter ``lifecycle_state`` gauge):

    serving -> rebuilding -> serving        (successful restart)
    serving -> rebuilding -> failed         (budget exhausted)
    serving -> terminating -> stopped       (SIGTERM graceful handover)

Admission is refused while rebuilding/terminating with a retriable
:class:`OverloadedError` carrying a backoff-derived Retry-After hint.
"""

from __future__ import annotations

import atexit
import dataclasses
import itertools
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.observability.flight import get_flight_recorder
from k8s_llm_monitor_tpu.observability.tracing import get_tracer
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.resilience.health import HealthMonitor
from k8s_llm_monitor_tpu.resilience.journal import (
    JournaledRequest,
    RequestJournal,
)
from k8s_llm_monitor_tpu.resilience.retry import Backoff
from k8s_llm_monitor_tpu.resilience.slo import DEFAULT_CLASS
from k8s_llm_monitor_tpu.resilience.tenancy import (
    DEFAULT_TENANT,
    TenantGovernor,
    normalize_tenant,
)
from k8s_llm_monitor_tpu.serving.engine import (
    GenerationResult,
    InferenceEngine,
    SamplingParams,
)
from k8s_llm_monitor_tpu.serving.service import EngineService, RequestHandle

logger = logging.getLogger("serving.supervisor")

SERVING = "serving"
REBUILDING = "rebuilding"
TERMINATING = "terminating"
STOPPED = "stopped"
FAILED = "failed"
LIFECYCLE_STATES = (SERVING, REBUILDING, TERMINATING, STOPPED, FAILED)


@dataclass
class _Tracked:
    """Everything needed to re-admit one in-flight request."""

    prompt_ids: list[int]
    sampling: SamplingParams
    deadline_s: float
    arrival_unix: float
    emitted: list[int] = field(default_factory=list)
    handle: Optional[RequestHandle] = None
    slo_class: str = DEFAULT_CLASS
    tenant: str = DEFAULT_TENANT


def _sampling_from_dict(data: dict) -> SamplingParams:
    fields = {f.name for f in dataclasses.fields(SamplingParams)}
    return SamplingParams(**{k: v for k, v in (data or {}).items()
                             if k in fields})


@guarded_by("_lock", "_state", "restarts", "replayed_total")
class EngineSupervisor:
    """Owns the EngineService; rebuilds the engine and replays survivors.

    ``engine_factory`` must return a *fresh* ``InferenceEngine`` each call
    (weights may be shared; KV pages and host state must not be).  With
    ``max_restarts=0`` a loop death is terminal — equivalent to the
    unsupervised service, plus journaling.

    KV tiering note: a factory that closes over one shared
    ``HostKVTier`` and passes it as the engine's ``host_kv_tier`` kwarg
    keeps *spilled* prefix pages alive across rebuilds — the rebuilt
    engine starts with a fresh device pool but rehydrates demoted
    prefixes from host RAM on their next hit.  If the tier was lost too
    (process restart), the replay machinery above is the fallback: the
    prompt re-prefills from tokens, so a lost spill entry can never lose
    tokens — only the latency win.
    """

    def __init__(
        self,
        engine_factory: Callable[[], InferenceEngine],
        *,
        journal: RequestJournal | None = None,
        health: HealthMonitor | None = None,
        max_restarts: int = 3,
        backoff: Backoff | None = None,
        heartbeat_timeout_s: float = 30.0,
        poll_interval_s: float = 0.1,
        clock=time.monotonic,
        governor: TenantGovernor | None = None,
    ):
        self.engine_factory = engine_factory
        self.journal = journal
        self.health = health or HealthMonitor()
        # Supervisor-owned so per-tenant reservations survive engine
        # rebuilds (the replacement EngineService gets the same instance)
        # and warm starts can restore quota state from the journal.
        self.governor = governor
        self.max_restarts = max_restarts
        self.backoff = backoff or Backoff(base_s=0.2, cap_s=5.0, jitter=0.0)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.poll_interval_s = poll_interval_s
        self._clock = clock
        self._ids = itertools.count()
        self._pid = os.getpid()

        self.restarts = 0        # engine rebuilds performed
        self.replayed_total = 0  # requests re-admitted (rebuild + warm start)
        self._tracked: dict[str, _Tracked] = {}
        self._state = SERVING
        self._death = threading.Event()   # woken by on_death for fast detect
        self._stop = threading.Event()

        self.service = self._build_service()
        # Created last (lockcheck: writes before the lock exists are
        # construction) — but before warm-start replay and the monitor
        # thread, which both take it.
        self._lock = make_lock("serving.supervisor")
        if journal is not None and journal.incomplete_recovered:
            self._replay_recovered(journal.incomplete_recovered)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="engine-supervisor", daemon=True)
        self._monitor.start()
        atexit.register(self.close)

    # -- accessors -------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def engine(self) -> InferenceEngine:
        return self.service.engine

    @property
    def journal_bytes(self) -> int:
        return self.journal.size_bytes if self.journal is not None else 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "restarts": self.restarts,
                "max_restarts": self.max_restarts,
                "replayed_total": self.replayed_total,
                "tracked": len(self._tracked),
                "journal_bytes": self.journal_bytes,
            }

    # -- construction ----------------------------------------------------

    def _build_service(self) -> EngineService:
        engine = self.engine_factory()
        svc = EngineService(engine, health=self.health,
                            on_death=self._on_service_death,
                            governor=self.governor)
        svc.observer = self._observe
        return svc

    def _on_service_death(self, reason: str) -> None:
        # Called from the dying step-loop thread: just wake the monitor —
        # the rebuild must not run on a thread that's about to re-raise.
        self._death.set()

    # -- submission ------------------------------------------------------

    def submit(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams | None = None,
        request_id: str | None = None,
        deadline_s: float = 0.0,
        slo_class: str = DEFAULT_CLASS,
        tenant: str = DEFAULT_TENANT,
    ) -> RequestHandle:
        """Journal (write-ahead), track, and admit one request."""
        # Normalized HERE so the journal never records a raw tenant string
        # (replay re-derives quota state from what the WAL says).
        tenant = normalize_tenant(tenant)
        if request_id is None:
            # Unique across process restarts sharing one journal dir.
            # Assigned BEFORE any refusal so every 429/503 body carries
            # the id (joinable with traces and journal records).
            request_id = f"req-{self._pid}-{next(self._ids)}"
        with self._lock:
            state = self._state
        if state == REBUILDING:
            raise OverloadedError(
                "engine rebuilding", retriable=True,
                retry_after_s=self.backoff.delay(0) + 0.5,
                slo_class=slo_class, request_id=request_id,
                tenant=tenant)
        if state != SERVING:
            raise OverloadedError(f"lifecycle state {state}",
                                  retriable=False, slo_class=slo_class,
                                  request_id=request_id, tenant=tenant)
        sampling = sampling or SamplingParams()
        tracked = _Tracked(list(prompt_ids), sampling, deadline_s,
                           time.time(), slo_class=slo_class, tenant=tenant)
        # Track before the engine can emit a single token for this id, and
        # journal before the engine can accept it (write-AHEAD).
        with self._lock:
            self._tracked[request_id] = tracked
        if self.journal is not None:
            self.journal.log_admit(request_id, prompt_ids, sampling,
                                   deadline_s, tracked.arrival_unix,
                                   slo_class=slo_class, tenant=tenant)
        try:
            handle = self.service.submit(
                prompt_ids, sampling, request_id=request_id,
                deadline_s=deadline_s, slo_class=slo_class, tenant=tenant)
        except BaseException as exc:
            # Refused (shed/dead): untrack and tombstone the admit record.
            with self._lock:
                self._tracked.pop(request_id, None)
            if self.journal is not None:
                self.journal.log_complete(request_id)
            if isinstance(exc, RuntimeError):
                # The service died between the state check and the submit:
                # a rebuild is imminent — tell the client to retry.
                raise OverloadedError(
                    "engine restarting", retriable=True,
                    retry_after_s=self.backoff.delay(0) + 0.5,
                    slo_class=slo_class, request_id=request_id) from exc
            raise
        tracked.handle = handle
        return handle

    # -- control plane ---------------------------------------------------

    def call(self, fn: Callable[[InferenceEngine], object],
             timeout: float = 30.0):
        """Run ``fn(engine)`` on the *current* service's step thread
        (serving/service.py ``EngineService.call``) — the seam the
        ``/api/v1/kv`` endpoints use for prefix export/install.  Refused
        with a retriable OverloadedError while rebuilding: the engine is
        mid-swap and a call could land on either incarnation."""
        with self._lock:
            state = self._state
        if state == REBUILDING:
            raise OverloadedError(
                "engine rebuilding", retriable=True,
                retry_after_s=self.backoff.delay(0) + 0.5)
        if state != SERVING:
            raise OverloadedError(f"lifecycle state {state}",
                                  retriable=False)
        try:
            return self.service.call(fn, timeout=timeout)
        except RuntimeError as exc:
            # Service died between the state check and the call: a
            # rebuild is imminent — same shape as the submit() race.
            raise OverloadedError(
                "engine restarting", retriable=True,
                retry_after_s=self.backoff.delay(0) + 0.5) from exc

    # -- progress observation (called from the step-loop thread) ---------

    def _observe(self, request_id: str, toks: list[int],
                 result: Optional[GenerationResult]) -> None:
        with self._lock:
            tracked = self._tracked.get(request_id)
            if tracked is not None and toks:
                tracked.emitted.extend(int(t) for t in toks)
            if result is not None:
                self._tracked.pop(request_id, None)
        if self.journal is not None:
            if toks:
                self.journal.log_progress(request_id, [int(t) for t in toks])
            if result is not None:
                self.journal.log_complete(request_id)

    # -- death detection -------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            self._death.wait(timeout=self.poll_interval_s)
            self._death.clear()
            if self._stop.is_set():
                return
            with self._lock:
                if self._state != SERVING:
                    continue
            svc = self.service
            with svc._handles_lock:
                dead = svc._dead
            reason = dead
            if reason is None and svc.engine.has_work:
                stale_s = self._clock() - svc.last_heartbeat
                if stale_s > self.heartbeat_timeout_s:
                    reason = (f"step loop wedged: no heartbeat for "
                              f"{stale_s:.1f}s with work pending")
            if reason is not None:
                try:
                    self._restart(reason)
                except Exception:  # noqa: BLE001 — monitor must survive
                    logger.exception("engine restart failed")

    # -- rebuild-and-replay ----------------------------------------------

    def _restart(self, reason: str) -> None:
        with self._lock:
            if self._state != SERVING:
                return
            self._state = REBUILDING
            self.restarts += 1
            attempt = self.restarts
        logger.warning("engine restart %d/%d: %s",
                       attempt, self.max_restarts, reason)
        # Dump the flight artifact before recovery mutates state: the span
        # ring and event log still describe the failing incarnation.
        rec = get_flight_recorder()
        rec.note("supervisor_rebuild", reason=reason, attempt=attempt)
        rec.dump("supervisor_rebuild",
                 extra={"reason": reason, "attempt": attempt})
        old = self.service
        handles = old.detach_handles()
        # A wedged loop may wake up long after the rebuild: its late tokens
        # are from a replaced engine incarnation and must not reach the
        # tracked state (they would duplicate what the new engine re-emits).
        old.observer = None
        # Close the dying incarnation's request spans: phase spans already
        # recorded parent them, and replay mints fresh contexts — without
        # this the old parents would never be emitted (orphan spans).
        tracer = get_tracer()
        t_now = time.monotonic()
        for rid, h in handles.items():
            ctx = getattr(h, "trace", None)
            if ctx is not None:
                tracer.record(
                    "engine.request", t_now, t_now, ctx, status="error",
                    span_id=ctx.span_id, parent_id=ctx.parent_id,
                    attrs={"request_id": rid, "outcome": "rebuild"})
        if attempt > self.max_restarts:
            self._give_up(f"restart budget exhausted after: {reason}",
                          handles)
            return
        time.sleep(self.backoff.delay(attempt - 1))
        try:
            old.stop(timeout=2.0)
        except Exception:  # noqa: BLE001 — the loop may be unjoinable (wedged)
            logger.exception("old service stop failed (continuing)")
        try:
            svc = self._build_service()
        except Exception as exc:  # noqa: BLE001 — factory failed: terminal
            logger.exception("engine factory failed during restart")
            self._give_up(f"engine rebuild failed: {exc!r}", handles)
            return
        # Fresh engine, fresh KV allocator: free count is back to its
        # baseline by construction.
        self.health.clear_dead()
        self.service = svc
        with self._lock:
            pending = list(self._tracked.items())
        replayed = 0
        for rid, tracked in pending:
            tracked.handle = handles.get(rid, tracked.handle)
            if self._replay_one(rid, tracked):
                replayed += 1
        with self._lock:
            self.replayed_total += replayed
            self._state = SERVING
        logger.info("engine rebuilt: %d request(s) replayed", replayed)

    def _replay_one(self, rid: str, tracked: _Tracked) -> bool:
        """Re-admit one tracked request on the current service.  Already-
        emitted tokens are folded into the prompt and trimmed from the
        budget — replay never re-generates a delivered token."""
        with self._lock:
            if rid not in self._tracked:
                return False  # resolved (or refused) while we snapshotted
        emitted = list(tracked.emitted)
        remaining = tracked.sampling.max_tokens - len(emitted)
        if remaining < 1:
            # Budget already delivered: finish the request as-is.
            self._finish_tracked(rid, tracked, GenerationResult(
                request_id=rid, token_ids=emitted, finish_reason="length",
                ttft_s=0.0, latency_s=0.0))
            return False
        deadline_s = tracked.deadline_s
        if deadline_s > 0:
            deadline_s -= time.time() - tracked.arrival_unix
            if deadline_s <= 0:
                self._finish_tracked(rid, tracked, GenerationResult(
                    request_id=rid, token_ids=emitted, finish_reason="error",
                    ttft_s=0.0, latency_s=0.0,
                    error="deadline exceeded during engine rebuild"))
                return False
        if tracked.handle is not None:
            # Streamed tokens stay streamed; the final result still carries
            # the complete output.
            tracked.handle._replay_prefix = emitted
        sampling = dataclasses.replace(tracked.sampling,
                                       max_tokens=remaining)
        try:
            tracked.handle = self.service.submit(
                tracked.prompt_ids + emitted, sampling, request_id=rid,
                deadline_s=deadline_s, force=True, handle=tracked.handle,
                slo_class=tracked.slo_class, tenant=tracked.tenant)
        except Exception as exc:  # noqa: BLE001 — replay refusal is terminal
            self._finish_tracked(rid, tracked, GenerationResult(
                request_id=rid, token_ids=emitted, finish_reason="error",
                ttft_s=0.0, latency_s=0.0,
                error=f"replay failed: {exc!r}"))
            return False
        return True

    def _finish_tracked(self, rid: str, tracked: _Tracked,
                        result: GenerationResult) -> None:
        with self._lock:
            self._tracked.pop(rid, None)
        if self.governor is not None:
            # Settle is idempotent; this covers terminal paths that never
            # re-reach the service (budget-done, deadline, replay refusal)
            # so the tenant is charged only for tokens actually emitted.
            self.governor.settle(rid)
        if self.journal is not None:
            self.journal.log_complete(rid)
        if tracked.handle is not None:
            tracked.handle._replay_prefix = []  # token_ids already complete
            tracked.handle._push([], result)

    def _give_up(self, reason: str, handles: dict[str, RequestHandle]) -> None:
        logger.error("supervisor giving up: %s", reason)
        get_flight_recorder().note("supervisor_give_up", reason=reason)
        with self._lock:
            self._state = FAILED
            pending = list(self._tracked.items())
        self.health.set_dead(reason)
        for rid, tracked in pending:
            tracked.handle = handles.get(rid, tracked.handle)
            self._finish_tracked(rid, tracked, GenerationResult(
                request_id=rid, token_ids=list(tracked.emitted),
                finish_reason="error", ttft_s=0.0, latency_s=0.0,
                error=reason))

    # -- warm start (previous process's journal) -------------------------

    def _replay_recovered(self, recovered: list[JournaledRequest]) -> None:
        """Re-admit requests a previous process accepted but never
        finished.  Runs during construction — strictly before the HTTP
        listener exists, so replay always precedes fresh traffic."""
        replayed = 0
        for rec in recovered:
            tracked = _Tracked(
                prompt_ids=list(rec.prompt_ids),
                sampling=_sampling_from_dict(rec.sampling),
                deadline_s=rec.deadline_s,
                arrival_unix=rec.arrival_unix or time.time(),
                emitted=list(rec.emitted),
                slo_class=rec.slo_class,
                tenant=rec.tenant,
            )
            with self._lock:
                self._tracked[rec.request_id] = tracked
            if self.governor is not None:
                # Rebuild the tenant's reservation exactly as the WAL
                # recorded it: tokens already streamed are pre-charged
                # (force-taken, possibly into debt) so the eventual
                # settle charges emitted tokens once — a crash can never
                # launder quota, and a torn tail for one tenant cannot
                # perturb another tenant's accounting (records are
                # per-request and tenant-tagged).
                self.governor.restore(
                    rec.request_id, rec.tenant,
                    max_tokens=tracked.sampling.max_tokens,
                    delivered=len(rec.emitted))
            if self._replay_one(rec.request_id, tracked):
                replayed += 1
        with self._lock:
            self.replayed_total += replayed
        if recovered:
            logger.info("warm start: %d journaled request(s) recovered, "
                        "%d replayed", len(recovered), replayed)

    # -- graceful handover (SIGTERM) -------------------------------------

    def shutdown(self, grace_s: float = 20.0) -> bool:
        """Terminating handover: refuse admission, flip readiness via
        DRAINING, drain inflight within ``grace_s``, stop the loop, seal
        the journal.  Returns True when fully drained in time (stragglers
        stay journaled for the next process to replay)."""
        with self._lock:
            if self._state in (TERMINATING, STOPPED):
                return True
            self._state = TERMINATING
        self._stop.set()
        self._death.set()
        self.health.set_draining(True)
        svc = self.service
        drained = svc.drain(timeout=grace_s) if grace_s > 0 else False
        try:
            svc.stop(timeout=5.0)
        except Exception:  # noqa: BLE001 — wedged loop: proceed to seal
            logger.exception("service stop failed during shutdown")
        if self.journal is not None:
            self.journal.seal()
        self._monitor.join(timeout=2.0)
        with self._lock:
            self._state = STOPPED
        atexit.unregister(self.close)
        return drained

    def close(self) -> None:
        """atexit / test teardown: immediate stop, journal kept replayable."""
        self.shutdown(grace_s=0.0)
