"""Prompt-lookup speculative decoding: device-side draft proposal + acceptance.

Two acceptance rules live here:

  * ``accept_greedy`` — argmax verification.  Bit-identical to sequential
    greedy decode for ANY draft (see below).
  * ``accept_sampled`` — exact speculative *sampling* for every sampled
    mode: the target p is the temperature-scaled, top-k/top-p-filtered,
    renormalized distribution sequential decode samples from (the shared
    ``ops/sampling.py:filtered_scaled_logits`` definition; plain softmax
    when no lane filters).  A prompt-lookup draft is a delta distribution
    q = 1{x}, so the canonical accept rule min(1, p(x)/q(x)) reduces to
    "accept x with probability p(x)", and the rejection residual
    norm((p-q)+) reduces to p with x zeroed, renormalized — for ANY
    target p, filtered or not.  Marginal check:
    P(t) = p(x)·1{t=x} + (1-p(x))·p(t)/(1-p(x))·1{t≠x} = p(t) — the output
    distribution is exactly the target's at every position, so sampled
    speculation changes the rng *stream* but not the statistics.

Diagnosis answers quote the evidence block that dominates their prompt
(pod names, event messages, metric lines), so the next tokens of the output
are very often a verbatim continuation of an n-gram that already appeared
in the context.  Prompt-lookup speculation (Saxena 2023; the technique
behind HF's ``prompt_lookup_num_tokens`` and vLLM's ``[ngram]`` speculator)
exploits that without a draft model: match the tail of the sequence against
its own history, propose the K tokens that followed the match, and verify
all K+1 positions in one forward pass.

Everything here is static-shaped jnp so the whole speculation loop — match,
propose, verify, accept — runs inside the engine's jitted program with no
host round-trip.  The TPU-friendly trick is that matching is a vectorized
compare over the [B, H] history buffer (one VPU sweep), not a hash-table
probe like the CPU implementations: H is a few thousand, so the sweep is
noise next to the verify matmuls.

Correctness does not depend on draft quality anywhere: greedy acceptance
(``accept_greedy``) emits the longest draft prefix that equals the argmax
chain, which is by construction exactly what one-token-at-a-time greedy
decode would have emitted — a garbage draft just means fewer accepted
tokens, never wrong ones.  (Reference counterpart: none — the reference's
LLM layer is config-only, internal/config/config.go:141-145; this is a
serving-throughput extension the TPU engine gets because verify FLOPs are
free under the decode weight-bandwidth ceiling.)
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class AcceptanceEMA:
    """Per-request-class accepted-length EMA with an auto-disable floor.

    Drafting only pays when verify forwards emit enough tokens to beat the
    fused pipelined decode path; below ``floor`` accepted tokens per
    lane-round, the draft overhead is pure loss (BENCH_r04/r05 measured a
    flat 1.00 on random-init weights).  The engine feeds every reconciled
    spec call's measured acceptance in here, keyed by request *class*
    (greedy vs sampled traffic accept at very different rates — a sampled
    class collapsing must not disable drafting for greedy quoting traffic),
    and asks ``should_draft`` before each dispatch.  A killed class still
    re-probes every ``probe_every`` fused dispatches so recovery (e.g. the
    workload starts quoting its context) is observed, not assumed.

    Host-side bookkeeping only — nothing here is traced.
    """

    floor: float = 1.2
    probe_every: int = 32
    alpha: float = 0.2  # EMA weight of the newest measurement

    _ema: dict = dataclasses.field(default_factory=dict)
    _since_probe: dict = dataclasses.field(default_factory=dict)

    def update(self, klass: str, accepted: int, lane_rounds: int) -> None:
        """Fold one reconciled spec call's acceptance into the class EMA."""
        if lane_rounds <= 0:
            return
        rate = float(accepted) / float(lane_rounds)
        prev = self._ema.get(klass)
        self._ema[klass] = (rate if prev is None
                            else (1.0 - self.alpha) * prev + self.alpha * rate)

    def ema(self, klass: str):
        """The class EMA, or None before any measurement."""
        return self._ema.get(klass)

    def drafting_disabled(self, klass: str) -> bool:
        """True when the kill-switch is engaged for this class (EMA
        measured and below the floor)."""
        ema = self._ema.get(klass)
        return ema is not None and ema < self.floor

    def should_draft(self, klass: str) -> bool:
        """Gate one dispatch: True while the class EMA is unmeasured or at/
        above the floor; once killed, True only for the periodic probe."""
        if not self.drafting_disabled(klass):
            self._since_probe[klass] = 0
            return True
        count = self._since_probe.get(klass, 0) + 1
        if count >= self.probe_every:
            self._since_probe[klass] = 0
            return True
        self._since_probe[klass] = count
        return False

    def snapshot(self) -> dict:
        """{class: ema} for the exporter's ``spec_accept_ema`` gauge."""
        return dict(self._ema)


def propose_drafts(
    hist: jnp.ndarray,
    ctx: jnp.ndarray,
    cur_tok: jnp.ndarray,
    k: int,
) -> jnp.ndarray:
    """Propose ``k`` draft tokens per lane by n-gram lookup over ``hist``.

    Args:
      hist: [B, H] int32 token history; positions ``0..ctx`` are valid
        (``hist[b, ctx[b]]`` must already hold ``cur_tok[b]``), the rest is
        stale garbage from earlier requests in the slot (harmless: matches
        are masked to ``p <= ctx``).
      ctx: [B] int32 position of the current (last known) token.
      cur_tok: [B] int32 the current token — the one the next forward feeds.
      k: draft length (static).

    Returns:
      [B, k] int32 draft tokens.  Lanes with no match get whatever follows
      position 0 — garbage-safe under greedy acceptance.

    A 3-gram match (last three tokens) is preferred over a 2-gram match:
    longer context keys have far better continuation precision, which is
    what sets the acceptance rate; the 2-gram fallback keeps short outputs
    speculating.  Both are computed in one pass and selected per lane.
    """
    B, H = hist.shape
    pos = jnp.arange(H, dtype=jnp.int32)[None, :]                  # [1, H]
    safe = lambda i: jnp.clip(i, 0, H - 1)
    prev1 = jnp.take_along_axis(hist, safe(ctx - 1)[:, None], 1)[:, 0]
    prev2 = jnp.take_along_axis(hist, safe(ctx - 2)[:, None], 1)[:, 0]

    # m2[b, p]: positions whose (p-1, p) tokens equal the lane's last two.
    # The match must end strictly before ctx so its continuation is history.
    in_range = (pos >= 1) & (pos < ctx[:, None])
    m2 = in_range & (hist == cur_tok[:, None])
    m2 = m2 & (jnp.roll(hist, 1, axis=1) == prev1[:, None])
    m3 = m2 & (pos >= 2) & (jnp.roll(hist, 2, axis=1) == prev2[:, None])
    m3 = m3 & (ctx[:, None] >= 2)

    # Latest match wins (recency beats earlier occurrences for code/log
    # text); 0 doubles as the no-match sentinel — its continuation is just
    # a garbage draft, which greedy acceptance scores as 0 accepted.
    p3 = jnp.max(jnp.where(m3, pos, 0), axis=1)
    p2 = jnp.max(jnp.where(m2, pos, 0), axis=1)
    p = jnp.where(p3 > 0, p3, p2)                                  # [B]

    gather_idx = safe(p[:, None] + 1 + jnp.arange(k, dtype=jnp.int32)[None, :])
    drafts = jnp.take_along_axis(hist, gather_idx, axis=1)         # [B, k]
    # The -1 history padding is not a token id: fed to the verify embed it
    # would wrap to vocab row V-1, and sampled acceptance could then accept
    # and emit -1 (the reconcile padding sentinel) with p(V-1) probability.
    # Token 0 is an ordinary (never-matching-argmax, low-p) vocab id.
    return jnp.maximum(drafts, 0)


def accept_greedy(
    greedy: jnp.ndarray,
    drafts: jnp.ndarray,
    quota: jnp.ndarray,
    active: jnp.ndarray,
    eos_id: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy acceptance over one verify pass.

    Args:
      greedy: [B, K+1] int32 argmax of the verify logits — ``greedy[:, i]``
        is the model's token *after* fed position ``i``.
      drafts: [B, K] int32 the proposed tokens that were fed at positions
        ``1..K`` of the verify chunk.
      quota: [B] int32 max tokens this lane may still emit (budget).
      active: [B] bool lanes participating this round.
      eos_id: scalar int32.

    Returns:
      (emit [B] int32 — number of tokens emitted, 0 for inactive lanes;
       out [B, K+1] int32 — emitted tokens left-packed, -1 elsewhere).

    The emitted sequence per lane is ``greedy[:, :emit]``: the accepted
    draft prefix (where ``greedy[:, i] == drafts[:, i]``) plus the model's
    one correction/bonus token, truncated to the quota and to the first
    EOS.  Every emitted token equals what sequential greedy decode would
    produce, so speculation is bit-identical to the non-speculative path.
    """
    B, K1 = greedy.shape
    K = K1 - 1
    iot = jnp.arange(K1, dtype=jnp.int32)[None, :]                 # [1, K+1]

    matched = greedy[:, :K] == drafts                              # [B, K]
    n_acc = jnp.sum(jnp.cumprod(matched.astype(jnp.int32), axis=1), axis=1)
    emit = jnp.minimum(n_acc + 1, quota)

    # Truncate after the first EOS that falls inside the emitted window.
    is_eos = (greedy == eos_id) & (iot < emit[:, None])
    any_eos = jnp.any(is_eos, axis=1)
    first_eos = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
    emit = jnp.where(any_eos, first_eos + 1, emit)
    emit = jnp.where(active, emit, 0)

    out = jnp.where((iot < emit[:, None]) & active[:, None], greedy, -1)
    return emit, out


def accept_sampled(
    rng: jax.Array,
    logits: jnp.ndarray,
    drafts: jnp.ndarray,
    quota: jnp.ndarray,
    active: jnp.ndarray,
    eos_id: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray | None = None,
    top_p: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Distribution-exact acceptance for sampled lanes (see module
    docstring for the delta-draft derivation), with greedy lanes
    (temperature <= 0) handled by the argmax rule in the same call so one
    program serves a mixed batch.

    The target distribution per position is EXACTLY the one sequential
    decode samples from — temperature-scaled, top-k/top-p-filtered,
    renormalized (ops/sampling.py:filtered_scaled_logits, the shared
    definition) — and the delta-draft accept/residual rule is exact for
    any target, so nucleus/top-k lanes speculate too.

    Args:
      rng: PRNG key (two subkeys consumed per call).
      logits: [B, K+1, V] float verify logits; position ``i`` is the
        distribution for the token after fed position ``i``.
      drafts: [B, K] int32 proposed tokens fed at verify positions 1..K.
      quota / active / eos_id: as in ``accept_greedy``.
      temperature: [B] float; <= 0 selects the greedy rule for that lane.
      top_k / top_p: [B] per-lane filters (None = disabled).

    Returns:
      (emit [B] int32, out [B, K+1] int32 emitted tokens, -1 padding).
    """
    from k8s_llm_monitor_tpu.ops.sampling import filtered_scaled_logits

    B, K1, V = logits.shape
    K = K1 - 1
    iot = jnp.arange(K1, dtype=jnp.int32)[None, :]
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # [B, K+1]
    is_greedy = temperature <= 0.0                               # [B]

    if top_k is None and top_p is None:
        # No filtered lane in the batch (the diagnosis default): skip the
        # full-vocab argsort the rank-cutoff filters need — a plain
        # temperature softmax is the same distribution with k=V, p=1.
        temp3 = jnp.maximum(temperature, 1e-6)[:, None, None]
        p = jax.nn.softmax(logits / temp3, axis=-1)
    else:
        if top_k is None:
            top_k = jnp.zeros((B,), jnp.int32)
        if top_p is None:
            top_p = jnp.ones((B,), jnp.float32)
        rep = lambda a: jnp.repeat(a, K1, axis=0)
        filtered = filtered_scaled_logits(
            logits.reshape(B * K1, V), temperature=rep(temperature),
            top_k=rep(top_k), top_p=rep(top_p))
        p = jax.nn.softmax(filtered, axis=-1).reshape(B, K1, V)

    # Accept draft_i with probability p_i(draft_i) (delta-draft rule);
    # greedy lanes accept on argmax match.
    p_draft = jnp.take_along_axis(
        p[:, :K, :], drafts[..., None], axis=-1)[..., 0]         # [B, K]
    rng_u, rng_c = jax.random.split(rng)
    u = jax.random.uniform(rng_u, (B, K))
    acc = jnp.where(is_greedy[:, None],
                    greedy[:, :K] == drafts,
                    u < p_draft)
    n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)

    # Boundary token at index n_acc: the model's correction (rejection:
    # resample from p with the rejected draft zeroed — the (p-q)+ residual)
    # or the bonus sample (n_acc == K: straight from p).  Greedy lanes take
    # the argmax.
    bnd = jnp.clip(n_acc, 0, K)[:, None]
    p_bnd = jnp.take_along_axis(p, bnd[..., None], axis=1)[:, 0, :]  # [B, V]
    draft_bnd = jnp.take_along_axis(
        drafts, jnp.clip(bnd, 0, K - 1), axis=1)[:, 0]           # [B]
    rejected = n_acc < K
    zero_mask = (jnp.arange(V, dtype=jnp.int32)[None, :]
                 == draft_bnd[:, None]) & rejected[:, None]
    p_res = jnp.where(zero_mask, 0.0, p_bnd)
    corr = jax.random.categorical(
        rng_c, jnp.where(p_res > 0, jnp.log(p_res), -jnp.inf), axis=-1
    ).astype(jnp.int32)
    greedy_bnd = jnp.take_along_axis(greedy, bnd, axis=1)[:, 0]
    boundary_tok = jnp.where(is_greedy, greedy_bnd, corr)

    # Emitted row: accepted drafts then the boundary token.
    base = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)          # [B, K+1]
    toks = jnp.where(iot < n_acc[:, None], base,
                     jnp.where(iot == n_acc[:, None],
                               boundary_tok[:, None], 0))

    emit = jnp.minimum(n_acc + 1, quota)
    is_eos = (toks == eos_id) & (toks >= 0) & (iot < emit[:, None])
    any_eos = jnp.any(is_eos, axis=1)
    first_eos = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
    emit = jnp.where(any_eos, first_eos + 1, emit)
    emit = jnp.where(active, emit, 0)

    out = jnp.where((iot < emit[:, None]) & active[:, None], toks, -1)
    return emit, out
