"""Continuous-batching inference engine.

The decode loop is slot-based: a fixed-width batch of ``max_slots`` lanes is
compiled exactly once (static shapes), and requests are admitted into / retired
from lanes between steps.  Inactive lanes run with context_len=0 and the null
KV block, so the compiled program never changes shape.  Prompts are prefilled
one at a time into length buckets (powers of two), bounding both compile-cache
size and decode-step starvation.

Preemption: if the allocator runs out of pages mid-decode, the youngest slot
is evicted and re-queued with its generated tokens folded into the prompt
(recompute-style preemption), so long-running requests always make progress.

This engine is the TPU replacement for the reference's never-implemented LLM
path (its entire integration is config keys, reference
internal/config/config.go:141-145); the north-star SLO it serves is 100
concurrent diagnosis queries at p50 TTFT < 500 ms on v5e-8 (BASELINE.md).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.ops.sampling import greedy_tokens, sample_tokens
from k8s_llm_monitor_tpu.serving.kv_cache import BlockAllocator, OutOfBlocks


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 256
    temperature: float = 0.0   # <= 0 -> greedy
    top_k: int = 0             # <= 0 -> disabled
    top_p: float = 1.0         # >= 1 -> disabled


@dataclasses.dataclass
class GenerationRequest:
    request_id: str
    prompt_ids: list[int]
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    submit_time: float = dataclasses.field(default_factory=time.monotonic)
    # Set on first admission; tokens past this index in prompt_ids are
    # generated output folded back in by preemption.
    orig_prompt_len: int = -1
    first_token_time: float = 0.0


@dataclasses.dataclass
class GenerationResult:
    request_id: str
    token_ids: list[int]
    finish_reason: str         # "eos" | "length" | "error"
    ttft_s: float              # submit -> first token
    latency_s: float           # submit -> completion
    error: str = ""            # set when finish_reason == "error"


@dataclasses.dataclass
class EngineConfig:
    max_slots: int = 8
    num_blocks: int = 512
    block_size: int = 16
    max_blocks_per_seq: int = 64
    prefill_buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048)
    max_prefills_per_step: int = 1


class _Slot:
    __slots__ = ("req", "blocks", "ctx_len", "pending_token", "generated",
                 "first_token_time")

    def __init__(self, req: GenerationRequest, blocks: list[int]):
        self.req = req
        self.blocks = blocks
        self.ctx_len = 0
        self.pending_token = 0
        self.generated: list[int] = []
        self.first_token_time = 0.0


class InferenceEngine:
    """Single-process engine over one jitted prefill + one jitted decode step.

    When ``mesh`` is given, params and KV pages are GSPMD-sharded (TP over the
    ``model`` axis) and the same jitted functions run multi-chip — XLA inserts
    the collectives from the sharding annotations.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Any,
        engine_cfg: EngineConfig | None = None,
        tokenizer=None,
        mesh=None,
        eos_id: Optional[int] = None,
        attn_impl=None,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.ecfg = engine_cfg or EngineConfig()
        self.tokenizer = tokenizer
        self.eos_id = eos_id if eos_id is not None else (
            tokenizer.eos_id if tokenizer is not None else -1
        )
        self.mesh = mesh

        ec = self.ecfg
        pages = llama.init_kv_pages(cfg, ec.num_blocks, ec.block_size)
        if mesh is not None:
            from jax.sharding import NamedSharding
            from k8s_llm_monitor_tpu.parallel.sharding import (
                kv_pages_partition_specs,
                param_partition_specs,
            )

            pspecs = param_partition_specs(params)
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                params, pspecs,
            )
            kvspecs = kv_pages_partition_specs(pages, mesh)
            pages = llama.KVPages(
                k=[jax.device_put(x, NamedSharding(mesh, s))
                   for x, s in zip(pages.k, kvspecs.k)],
                v=[jax.device_put(x, NamedSharding(mesh, s))
                   for x, s in zip(pages.v, kvspecs.v)],
            )
        self.params = params
        self.pages = pages
        self.allocator = BlockAllocator(ec.num_blocks, ec.block_size)

        if attn_impl is None:
            from k8s_llm_monitor_tpu.ops.attention import paged_decode_attention
            attn_impl = paged_decode_attention

        def _prefill_fn(params, tokens, lengths, pages, tables):
            return llama.prefill(params, cfg, tokens, lengths, pages, tables)

        def _prefill_chunk_fn(params, tokens, start, lengths, pages, tables):
            return llama.prefill_chunk(
                params, cfg, tokens, start, lengths, pages, tables
            )

        def _decode_fn(params, tokens, ctx, pages, tables, temp, topk, topp, rng):
            logits, pages = llama.decode_step(
                params, cfg, tokens, ctx, pages, tables, attn_impl=attn_impl
            )
            nxt = sample_tokens(rng, logits, temperature=temp, top_k=topk, top_p=topp)
            return nxt, pages

        def _decode_greedy_fn(params, tokens, ctx, pages, tables):
            # Sort-free fast path for all-greedy steps (the common diagnosis
            # workload: temperature 0) — skips the [B, V] argsort + rank
            # scatter sample_tokens needs for nucleus filtering.
            logits, pages = llama.decode_step(
                params, cfg, tokens, ctx, pages, tables, attn_impl=attn_impl
            )
            return greedy_tokens(logits), pages

        # pages are donated so the scatter-updates happen in place on device.
        self._prefill = jax.jit(_prefill_fn, donate_argnums=(3,))
        self._prefill_chunk = jax.jit(_prefill_chunk_fn, donate_argnums=(4,))
        self._decode = jax.jit(_decode_fn, donate_argnums=(3,))
        self._decode_greedy = jax.jit(_decode_greedy_fn, donate_argnums=(3,))
        self._sample = jax.jit(
            lambda rng, logits, t, k, p: sample_tokens(
                rng, logits, temperature=t, top_k=k, top_p=p
            )
        )

        self._rng = jax.random.PRNGKey(seed)
        self._pending: collections.deque[GenerationRequest] = collections.deque()
        self._slots: list[Optional[_Slot]] = [None] * ec.max_slots
        self._results: dict[str, GenerationResult] = {}
        self.steps = 0
        self.prefills = 0
        self.preemptions = 0

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @property
    def capacity_tokens(self) -> int:
        """Max cached tokens for one sequence (per-seq table cap and pool)."""
        ec = self.ecfg
        return min(ec.max_blocks_per_seq, ec.num_blocks - 1) * ec.block_size

    def _cap_request(self, req: GenerationRequest) -> None:
        """Enforce prompt_len + max_tokens <= capacity (reference ADVICE:
        submit-time truncation prevents the block-table overflow crash and
        the can_alloc livelock).  Keeps the prompt *tail* — diagnosis prompts
        front-load boilerplate — and never produces a degenerate slice."""
        cap = self.capacity_tokens
        sp = req.sampling
        if sp.max_tokens >= cap:
            req.sampling = dataclasses.replace(sp, max_tokens=cap - 1)
            sp = req.sampling
        overflow = len(req.prompt_ids) + sp.max_tokens - cap
        if overflow > 0:
            req.prompt_ids = req.prompt_ids[overflow:]
            if req.orig_prompt_len >= 0:
                # Preempted fold being re-capped: the dropped tokens come off
                # the original-prompt prefix, not the generated tail.
                req.orig_prompt_len = max(0, req.orig_prompt_len - overflow)

    def submit(self, req: GenerationRequest) -> None:
        if not req.prompt_ids:
            raise ValueError("empty prompt")
        if req.sampling.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        self._cap_request(req)
        self._pending.append(req)

    def submit_text(self, request_id: str, prompt: str,
                    sampling: SamplingParams | None = None) -> None:
        assert self.tokenizer is not None
        self.submit(GenerationRequest(
            request_id=request_id,
            prompt_ids=self.tokenizer.encode(prompt),
            sampling=sampling or SamplingParams(),
        ))

    def poll(self, request_id: str) -> Optional[GenerationResult]:
        return self._results.pop(request_id, None)

    @property
    def has_work(self) -> bool:
        return bool(self._pending) or any(s is not None for s in self._slots)

    def generate(self, prompts: list[list[int]],
                 sampling: SamplingParams | None = None) -> list[GenerationResult]:
        """Synchronous batch generation (runs the loop to completion)."""
        ids = [f"gen-{i}" for i in range(len(prompts))]
        for rid, p in zip(ids, prompts):
            self.submit(GenerationRequest(rid, list(p),
                                          sampling or SamplingParams()))
        while self.has_work:
            self.step()
        return [self._results.pop(rid) for rid in ids]

    def generate_text(self, prompt: str,
                      sampling: SamplingParams | None = None) -> str:
        assert self.tokenizer is not None
        res = self.generate([self.tokenizer.encode(prompt)], sampling)[0]
        return self.tokenizer.decode(res.token_ids)

    # ------------------------------------------------------------------
    # engine loop
    # ------------------------------------------------------------------

    def step(self) -> None:
        """One scheduler iteration: admit up to N prefills, then one decode."""
        admitted = 0
        while (admitted < self.ecfg.max_prefills_per_step
               and self._pending and self._try_admit()):
            admitted += 1
        if any(s is not None for s in self._slots):
            self._decode_once()
        self.steps += 1

    # -- admission ------------------------------------------------------

    def _bucket(self, n: int) -> int:
        """Smallest prefill bucket covering ``n`` tokens.

        ``n`` must not exceed the largest bucket — longer prompts go through
        chunked prefill (``_try_admit`` splits them), never silent clamping.
        """
        for b in self.ecfg.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"{n} tokens exceeds the largest prefill bucket "
            f"{self.ecfg.prefill_buckets[-1]} — chunk before bucketing"
        )

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _fail_request(self, req: GenerationRequest, msg: str) -> None:
        now = time.monotonic()
        self._results[req.request_id] = GenerationResult(
            request_id=req.request_id,
            token_ids=req.prompt_ids[req.orig_prompt_len:]
            if req.orig_prompt_len >= 0 else [],
            finish_reason="error",
            ttft_s=0.0,
            latency_s=now - req.submit_time,
            error=msg,
        )

    def _try_admit(self) -> bool:
        slot_idx = self._free_slot()
        if slot_idx is None:
            return False
        req = self._pending[0]
        L = len(req.prompt_ids)
        if L + 1 > self.capacity_tokens:
            # Defensive: submit() caps requests, so this only catches internal
            # misuse; fail loudly instead of livelocking in can_alloc forever.
            self._pending.popleft()
            self._fail_request(req, f"prompt of {L} tokens exceeds capacity "
                                    f"{self.capacity_tokens}")
            return True
        if not self.allocator.can_alloc(L + 1):
            return False
        self._pending.popleft()
        if req.orig_prompt_len < 0:
            req.orig_prompt_len = L
        blocks = self.allocator.alloc(L + 1)

        table = np.zeros((1, self.ecfg.max_blocks_per_seq), np.int32)
        table[0, : len(blocks)] = blocks
        table_j = jnp.asarray(table)

        # Chunked prefill: prompts longer than the largest bucket are split;
        # the first chunk runs the dense path, continuations attend to the
        # paged prefix (llama.prefill_chunk).
        top = self.ecfg.prefill_buckets[-1]
        first = min(L, top)
        bucket = self._bucket(first)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :first] = req.prompt_ids[:first]
        logits, self.pages = self._prefill(
            self.params, jnp.asarray(tokens),
            jnp.asarray([first], jnp.int32), self.pages, table_j,
        )
        pos = first
        while pos < L:
            n = min(L - pos, top)
            bucket = self._bucket(n)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :n] = req.prompt_ids[pos:pos + n]
            logits, self.pages = self._prefill_chunk(
                self.params, jnp.asarray(tokens),
                jnp.asarray([pos], jnp.int32), jnp.asarray([n], jnp.int32),
                self.pages, table_j,
            )
            pos += n
        self.prefills += 1

        sp = req.sampling
        self._rng, sub = jax.random.split(self._rng)
        first = self._sample(
            sub, logits,
            jnp.asarray([sp.temperature], jnp.float32),
            jnp.asarray([sp.top_k], jnp.int32),
            jnp.asarray([sp.top_p], jnp.float32),
        )
        first_id = int(np.asarray(first)[0])

        slot = _Slot(req, blocks)
        slot.ctx_len = L
        slot.pending_token = first_id
        slot.generated = [first_id]
        if req.first_token_time == 0.0:
            req.first_token_time = time.monotonic()
        slot.first_token_time = req.first_token_time
        self._slots[slot_idx] = slot
        if self._is_finished(slot):
            self._retire(slot_idx)
        return True

    # -- decode ---------------------------------------------------------

    def _decode_once(self) -> None:
        ec = self.ecfg
        B = ec.max_slots
        tokens = np.zeros((B,), np.int32)
        ctx = np.zeros((B,), np.int32)
        table = np.zeros((B, ec.max_blocks_per_seq), np.int32)
        temp = np.zeros((B,), np.float32)
        topk = np.zeros((B,), np.int32)
        topp = np.ones((B,), np.float32)

        # Ensure every active slot has a page for the incoming token.  On
        # pressure, evict the *youngest* active slot (recompute-preemption)
        # so the oldest requests always make progress — guarantees the loop
        # drains even when the pool is smaller than the working set.  The
        # youngest slot may be the one that failed, in which case it evicts
        # itself rather than stealing pages from an older request.
        def _youngest_active() -> int:
            return max(
                (j for j, sl in enumerate(self._slots) if sl is not None),
                key=lambda j: self._slots[j].req.submit_time,
            )

        for i in sorted(
            (i for i, s in enumerate(self._slots) if s is not None),
            key=lambda i: self._slots[i].req.submit_time,
        ):
            s = self._slots[i]
            if s is None:  # already evicted below
                continue
            while True:
                try:
                    self.allocator.extend(s.blocks, s.ctx_len + 1)
                    break
                except OutOfBlocks:
                    victim = _youngest_active()
                    self._preempt(victim)
                    if victim == i:
                        break

        active = [(i, s) for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return
        for i, s in active:
            tokens[i] = s.pending_token
            ctx[i] = s.ctx_len
            table[i, : len(s.blocks)] = s.blocks
            sp = s.req.sampling
            temp[i], topk[i], topp[i] = sp.temperature, sp.top_k, sp.top_p

        if all(s.req.sampling.temperature <= 0.0 for _, s in active):
            nxt, self.pages = self._decode_greedy(
                self.params, jnp.asarray(tokens), jnp.asarray(ctx),
                self.pages, jnp.asarray(table),
            )
        else:
            self._rng, sub = jax.random.split(self._rng)
            nxt, self.pages = self._decode(
                self.params, jnp.asarray(tokens), jnp.asarray(ctx), self.pages,
                jnp.asarray(table), jnp.asarray(temp), jnp.asarray(topk),
                jnp.asarray(topp), sub,
            )
        nxt = np.asarray(nxt)

        for i, s in active:
            s.ctx_len += 1          # pending token's KV is now in cache
            tok = int(nxt[i])
            s.pending_token = tok
            s.generated.append(tok)
            if self._is_finished(s):
                self._retire(i)

    def _is_finished(self, s: _Slot) -> bool:
        return (s.generated[-1] == self.eos_id
                or len(s.generated) >= s.req.sampling.max_tokens)

    def _retire(self, slot_idx: int) -> None:
        s = self._slots[slot_idx]
        assert s is not None
        now = time.monotonic()
        # Tokens generated before a preemption live in the folded prompt tail.
        toks = s.req.prompt_ids[s.req.orig_prompt_len:] + s.generated
        reason = "eos" if toks and toks[-1] == self.eos_id else "length"
        if reason == "eos":
            toks = toks[:-1]
        self._results[s.req.request_id] = GenerationResult(
            request_id=s.req.request_id,
            token_ids=toks,
            finish_reason=reason,
            ttft_s=s.first_token_time - s.req.submit_time,
            latency_s=now - s.req.submit_time,
        )
        self.allocator.free(s.blocks)
        self._slots[slot_idx] = None

    def _preempt(self, slot_idx: int) -> None:
        """Evict a slot, folding generated tokens into a new prompt."""
        s = self._slots[slot_idx]
        assert s is not None
        self.allocator.free(s.blocks)
        self._slots[slot_idx] = None
        req = s.req
        # Already-sampled tokens become prompt; budget shrinks accordingly.
        consumed = len(s.generated)
        req.prompt_ids = req.prompt_ids + s.generated
        req.sampling = dataclasses.replace(
            req.sampling, max_tokens=max(1, req.sampling.max_tokens - consumed)
        )
        self._cap_request(req)  # re-apply the submit-time capacity cap
        self._pending.appendleft(req)
        self.preemptions += 1
