"""Continuous-batching inference engine.

The decode loop is slot-based: a fixed-width batch of ``max_slots`` lanes is
compiled exactly once (static shapes), and requests are admitted into / retired
from lanes between steps.  Inactive lanes run with context_len=0 and the null
KV block, so the compiled program never changes shape.

Throughput design (the north-star SLO is p50 TTFT < 500 ms at 100 concurrent
diagnosis queries, BASELINE.md):

  * **Batched prefill** — up to ``max_prefills_per_step`` pending prompts are
    ingested in ONE ``[P, bucket]`` prefill call (padded lanes are inactive),
    and their first tokens are sampled inside the same compiled program, so an
    admission round costs one dispatch regardless of how many it admits.
  * **Fused multi-step decode** — ``decode_steps_per_iter`` decode steps run
    inside one compiled ``lax.scan`` with on-device token feedback; per-lane
    EOS detection and budget exhaustion are masked on device, so the host
    syncs once per K steps instead of once per token.
  * **Asynchronous reconciliation** — sampled tokens live in a device-resident
    ``[max_slots]`` buffer that feeds the next decode call directly, so the
    host never blocks on token values to keep the device busy.  Dispatched
    calls join an in-flight queue (depth ``max_inflight``); their results are
    fetched via ``copy_to_host_async`` and reconciled (emission, EOS/budget
    retirement, TTFT stamping) behind the dispatch front.  This hides the
    device->host latency that would otherwise serialize every step — on a
    remote-tunneled chip that latency is the dominant cost, and on a local
    chip it still buys dispatch/compute overlap.
  * Prompts longer than the largest bucket admit into *prefilling* slots:
    their chunks stream one batched round per scheduler step (depth-first —
    lanes closest to completion go first), so decode dispatches and
    short-prompt admissions interleave between chunk rounds instead of
    stalling behind a serial per-request chunk loop.  Continuation chunks
    attend to the paged prefix.

Speculation note: EOS is only learned at reconcile time, so up to
``max_inflight`` decode calls may keep stepping a finished lane.  Those
zombie steps are confined to the lane's own pre-extended pages and their
outputs are discarded at reconcile; pages of a retired lane are returned to
the pool only after the last in-flight call that references them completes.

Preemption: if the allocator runs out of pages, in-flight work is drained and
the youngest slot is evicted and re-queued with its generated tokens folded
into the prompt (recompute-style preemption), so long-running requests always
make progress.

This engine is the TPU replacement for the reference's never-implemented LLM
path (its entire integration is config keys, reference
internal/config/config.go:141-145).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from k8s_llm_monitor_tpu.models import llama
from k8s_llm_monitor_tpu.models.config import ModelConfig
from k8s_llm_monitor_tpu.observability.flight import get_flight_recorder
from k8s_llm_monitor_tpu.observability.metrics import ClassHistogram
from k8s_llm_monitor_tpu.observability.tracing import get_tracer
from k8s_llm_monitor_tpu.resilience.faults import FaultError, get_injector
from k8s_llm_monitor_tpu.resilience.slo import DEFAULT_CLASS, SLO_RANK
from k8s_llm_monitor_tpu.resilience.tenancy import (
    DEFAULT_TENANT,
    normalize_tenant,
)
from k8s_llm_monitor_tpu.ops.sampling import (
    fsm_advance,
    fsm_mask_logits,
    greedy_tokens,
    sample_tokens,
    sample_tokens_bounded,
)
from k8s_llm_monitor_tpu.serving.kv_cache import (
    BlockAllocator,
    OutOfBlocks,
    PrefixCache,
    page_slice_bytes,
    shareable_blocks,
)
from k8s_llm_monitor_tpu.serving.kv_tier import (
    BlobError,
    HostKVTier,
    SpilledPrefix,
    pack_prefix_blob,
    unpack_prefix_blob,
)
from k8s_llm_monitor_tpu.serving.spec import (
    AcceptanceEMA,
    accept_greedy,
    accept_sampled,
    propose_drafts,
)

logger = logging.getLogger("serving.engine")


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 256
    temperature: float = 0.0   # <= 0 -> greedy
    top_k: int = 0             # <= 0 -> disabled
    top_p: float = 1.0         # >= 1 -> disabled
    # Grammar-constrained decoding (diagnosis/grammar.py): every sampled
    # token is masked by the engine's installed TokenFSM so the output is
    # schema-valid by construction.  Requires ``set_grammar()`` before
    # submit; max_tokens is raised to the grammar's max_len so the forced
    # EOS is always reachable.
    constrained: bool = False


@dataclasses.dataclass
class GenerationRequest:
    request_id: str
    prompt_ids: list[int]
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    submit_time: float = dataclasses.field(default_factory=time.monotonic)
    # Set on first admission; tokens past this index in prompt_ids are
    # generated output folded back in by preemption.
    orig_prompt_len: int = -1
    first_token_time: float = 0.0
    # Cold-burst dedup: set the first time admission holds this request
    # back so a same-prefix lane can publish the shared pages first
    # (engine._admit_round); caps the dense-lane rule at one round and
    # keeps the deferral counter per-request.
    prefix_deferred: bool = False
    # Wall-clock budget from submit (seconds); 0 = none.  Enforced at
    # admission and per step(): an expired request fails with a
    # "deadline exceeded" cause instead of occupying KV pages forever.
    deadline_s: float = 0.0
    # Times this request was recompute-requeued by a pipeline reset
    # (watchdog trip / dispatch failure); bounded by
    # EngineConfig.max_requeues, then the request fails with the cause.
    requeues: int = 0
    # SLO class (resilience/slo.py): "interactive" | "standard" | "batch".
    # Host-side scheduling metadata only — orders admission, shedding, and
    # eviction; never enters a traced program (zero recompiles).
    slo_class: str = DEFAULT_CLASS
    # Tenant namespace (resilience/tenancy.py): seeds this request's
    # prefix-cache digest chain, so its KV reuse is confined to its own
    # tenant by construction.  Host-side scheduling metadata only, like
    # slo_class — never enters a traced program (zero recompiles).
    tenant: str = DEFAULT_TENANT
    # Trace context (observability/tracing.py TraceContext) captured at
    # EngineService.submit; the engine records phase spans against it.
    # Host-side metadata only, like slo_class — never enters a traced
    # program (zero recompiles).  None when the request is untraced.
    trace: Any = None


@dataclasses.dataclass
class GenerationResult:
    request_id: str
    token_ids: list[int]
    finish_reason: str         # "eos" | "length" | "error"
    ttft_s: float              # submit -> first token
    latency_s: float           # submit -> completion
    error: str = ""            # set when finish_reason == "error"


def prefill_bucket_for(n: int, buckets) -> int:
    """Smallest bucket in ``buckets`` covering ``n`` tokens — THE bucket
    rounding, shared by the engine's admission path (``_bucket``) and by
    bench.py's engine-sizing math, so the two can't silently disagree
    about which bucket a prompt lands in (they once computed it with
    independent formulas).  ``buckets`` must be ascending; ``n`` past the
    top bucket raises — longer prompts go through chunked prefill, never
    silent clamping."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(
        f"{n} tokens exceeds the largest prefill bucket "
        f"{buckets[-1]} — chunk before bucketing"
    )


@dataclasses.dataclass
class EngineConfig:
    max_slots: int = 16
    num_blocks: int = 512
    block_size: int = 16
    max_blocks_per_seq: int = 64
    prefill_buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048)
    # Requests ingested per batched-prefill call (the prefill lane count).
    max_prefills_per_step: int = 8
    # Batched-prefill admission rounds per scheduler step: a burst drains
    # into slots at up to rounds*lanes requests before each decode run,
    # which is TTFT-optimal for bursts while the cap bounds decode stall.
    max_admission_rounds: int = 4
    # Decode steps fused into one device call between host syncs.
    decode_steps_per_iter: int = 8
    # Dispatch-ahead depth: calls in flight before reconciling the oldest.
    max_inflight: int = 2
    # Decode attention path (ops/attention.py:select_decode_impl):
    # "auto" = the fused RoPE+append+attention Pallas kernel on a
    # compatible single TPU chip, split/gather otherwise; "fused",
    # "pallas", "gather" force a path.  K8SLLM_DECODE_PATH overrides.
    decode_path: str = "auto"
    # Prefill-family attention path (ops/attention.py:select_prefill_impl):
    # "auto" = the flash paged-prefill kernel (tiled online softmax reading
    # K/V straight from the pool) on a compatible TPU chip or mesh, the
    # dense XLA oracle otherwise; "flash"/"dense" force a path.  Serves
    # fresh prefill, continuation chunks, and spec verify alike.
    # K8SLLM_PREFILL_PATH overrides.
    prefill_path: str = "auto"
    # Resident KV representation (serving/kv_tier.py rung 1): "auto" keeps
    # the model-dtype pool (the flag-selectable fp16/bf16 oracle, same
    # pattern as decode_path); "int8"/"fp8" store pages in the narrow dtype
    # with per-(token, head) f32 dequant scales — roughly doubling resident
    # lanes on the same pool bytes (page_slice_bytes accounting).  fp8
    # falls back to int8 when this jax build lacks float8_e4m3fn.
    # K8SLLM_KV_DTYPE overrides.
    kv_dtype: str = "auto"
    # Host-RAM spill tier capacity in bytes (rung 2): pressured prefix-cache
    # evictions demote page rows to a HostKVTier of this size instead of
    # dropping them, and the next hit rehydrates without re-prefill.
    # 0 disables (pressured evictions drop, as before).
    host_spill_bytes: int = 0
    # On-device sampling: when every sampling lane of a dispatch has
    # 0 < top_k <= this cap, the decode program samples from the top
    # ``sample_topk_cap`` logits (one lax.top_k) instead of rank-sorting
    # the full vocab each scan step (V=128k on the 8B target).  The
    # bounded program is distribution-exact in that regime
    # (ops/sampling.py:sample_tokens_bounded); 0 disables.
    sample_topk_cap: int = 64
    # Prompt-prefix KV reuse (serving/kv_cache.py:PrefixCache): LRU entry
    # cap (one entry per cached prefix *length*; host-side tuples, cheap);
    # 0 disables.  Shared blocks are read-only by construction, so this is
    # refcounting, not copy-on-write.
    prefix_cache_entries: int = 1024
    # Multi-tenant KV fairness (resilience/tenancy.py): the fraction of
    # cached blocks (device prefix cache) / bytes (host tier) one tenant
    # may hold while another tenant is resident — over-share tenants
    # become the preferred eviction victims of THEIR OWN LRU entries.
    # 1.0 disables the cap (single-tenant default).
    kv_max_tenant_share: float = 1.0
    # Prefill-priority: while chunk rounds are pending, decode dispatches
    # only every Nth step — TTFT is completion-order-sensitive and a decode
    # dispatch between chunk rounds would steal ~half the bandwidth from
    # every waiting first token.  N bounds decode starvation for lanes
    # already generating.  1 = strict alternation, large = prefill-first.
    decode_every_n_chunk_rounds: int = 3
    # Deadline-aware chunk-round sizing: while any interactive-class
    # request waits in the pending queue, chunk rounds clamp their token
    # bucket to this size (rounded up to a prefill bucket) so the queued
    # interactive work reaches its admission dispatch sooner — a 2048-token
    # chunk round is a ~2048-token head-of-line block on every admission
    # behind it.  0 disables (full-bucket rounds, the historical cadence).
    interactive_chunk_bucket: int = 0
    # Prompt-lookup speculative decoding (serving/spec.py): draft length per
    # verify pass; 0 disables.  Every sampling mode speculates — greedy by
    # argmax match (bit-identical), sampled (incl. top-k/top-p) by the
    # distribution-exact delta-draft rule.  Decode throughput rises toward
    # (spec_k+1)x when outputs quote their context (the diagnosis
    # workload: answers cite pod names / events / metric lines verbatim)
    # because a verify pass costs the same weight traffic as one decode
    # step.  Tradeoff: emission per call is data-dependent, so spec
    # dispatches reconcile the pipeline first (no decode dispatch-ahead).
    spec_k: int = 0
    # Verify rounds fused into one spec dispatch (device-side scan) — the
    # host-sync amortization knob, the spec analogue of decode_steps_per_iter.
    spec_rounds_per_iter: int = 4
    # Adaptive speculation: a spec dispatch serializes the pipeline and a
    # verify forward costs more than a fused step, so near the acceptance
    # floor speculation loses to the fused path.  The engine tracks an EMA
    # of EMITTED tokens per lane-round — accepted drafts plus the one
    # correction/bonus token, so the metric's floor is 1.0 even with zero
    # drafts accepted — and falls back to the fused path below this
    # threshold, re-probing with one spec dispatch every spec_probe_every
    # decode dispatches in case the workload turned quotable again.  The
    # default sits above the 1.0 floor (where fused wins) with margin for
    # the verify forward's extra cost over a fused step.
    spec_min_accept: float = 1.2
    spec_probe_every: int = 32
    # History window for n-gram matching, per lane (tokens; rounded down to
    # the per-seq capacity).  [max_slots, cap] int32 is KBs, not MBs.
    spec_hist_cap: int = 4096
    # --- resilience (docs/resilience.md) ------------------------------
    # Default time-to-live for requests still waiting in the pending
    # queue (seconds; 0 = none).  A request with its own deadline_s uses
    # that instead.  Queued work past its TTL fails at the next step()
    # instead of occupying the queue (and later KV pages) for a caller
    # that has long since timed out.
    queue_ttl_s: float = 0.0
    # Inflight watchdog: wall-clock budget for the oldest dispatched call
    # to become ready at reconcile time (seconds; 0 = disabled, block
    # forever as before).  On expiry the engine performs a pipeline
    # reset: in-flight results are dropped, affected slots are
    # recompute-requeued (bounded by max_requeues) and the engine keeps
    # serving instead of wedging on a stuck device dispatch.
    dispatch_timeout_s: float = 0.0
    # Recompute-requeue budget per request across pipeline resets;
    # exceeded -> the request fails with the reset cause.
    max_requeues: int = 2
    # Load shedding thresholds (0 = disabled).  should_shed() reports a
    # reason when the pending-queue token backlog or the admission-wait
    # EMA crosses its threshold; EngineService turns that into a
    # retriable OverloadedError at submit time.
    shed_queue_tokens: int = 0
    shed_slot_wait_s: float = 0.0
    # --- SLO classes (resilience/slo.py) ------------------------------
    # Voluntary class-ordered preemptions per step(): with no free slot
    # and a strictly higher-class request queued, the engine evicts the
    # lowest-class running lane (recompute-requeue, byte-exact resumption)
    # up to this budget.  0 disables voluntary eviction; page-pressure
    # eviction inside the decode path still runs.
    max_preemptions: int = 2
    # Brownout clamp on batch-class max_tokens applied at admission while
    # the ladder sits at DEGRADED or worse; 0 disables the clamp.
    brownout_batch_max_tokens: int = 64
    # --- TP collective overlap (parallel/overlap.py) ------------------
    # Decode-step collective schedule under a TP mesh.  "auto" (default):
    # the hand-staged reduce-scatter/all-gather program whenever
    # overlap_supported() clears the (cfg, mesh) — byte-identical to the
    # GSPMD reference, with the per-layer wire time hidden under the next
    # sub-block's weight streaming.  "on": require it (ValueError when
    # unsupported).  "off": always the GSPMD-auto psum program.  Env
    # override: K8SLLM_TP_OVERLAP, same values.
    tp_overlap: str = "auto"
    # --- tier-aware admission (ROADMAP item 2 / PR 9 ladder) ----------
    # What counts as KV headroom in should_shed()'s capacity clause:
    # "tier" (default) counts free device blocks PLUS prefix-cache blocks
    # a lossless host spill could reclaim (bounded by HostKVTier free
    # bytes), so admission tracks the capacity the eviction path can
    # actually deliver; "device" counts free device blocks only; "off"
    # disables the clause (pre-PR-12: rely on OutOfBlocks pushback).
    kv_admission: str = "tier"


class _Slot:
    __slots__ = ("req", "blocks", "ctx_len", "generated", "pending_admit",
                 "inflight_decode", "first_token_time", "retired",
                 "cancel_requested", "prefill_pos", "prefilling",
                 "inflight_chunks", "abort_cause")

    def __init__(self, req: GenerationRequest, blocks: list[int]):
        self.req = req
        self.blocks = blocks
        self.ctx_len = 0          # reconciled tokens in the KV cache
        self.generated: list[int] = []   # reconciled sampled tokens
        self.pending_admit = True        # first token not yet reconciled
        self.inflight_decode = 0         # decode steps dispatched, unreconciled
        self.first_token_time = 0.0
        self.retired = False
        self.cancel_requested = False
        # When set, retirement produces an error result with this cause
        # (deadline expiry, pipeline-reset give-up) instead of eos/length.
        self.abort_cause = ""
        # Long-prompt streaming admission: tokens dispatched so far and
        # whether more chunks remain (decode skips prefilling slots).
        self.prefill_pos = 0
        self.prefilling = False
        self.inflight_chunks = 0         # chunk calls dispatched, unreconciled

    # -- predicted (dispatch-side) state --------------------------------

    @property
    def gen_pred(self) -> int:
        return (len(self.generated) + self.inflight_decode
                + (1 if self.pending_admit else 0))

    @property
    def ctx_pred(self) -> int:
        return self.ctx_len + self.inflight_decode

    @property
    def remaining_pred(self) -> int:
        return self.req.sampling.max_tokens - self.gen_pred


@dataclasses.dataclass
class _Inflight:
    kind: str                     # "admit" | "chunk" | "decode"
    call_id: int
    arr: Any                      # device array (async copy started)
    # admit: [(slot_idx, req)]; chunk: [(row, slot_idx, req)] final lanes;
    # decode: [(slot_idx, slot, steps_i)]
    lanes: list[tuple]
    # chunk: every slot touched by the call (inflight_chunks decrement).
    touched: list = dataclasses.field(default_factory=list)
    # Dispatch timestamp (monotonic) — phase spans cover dispatch ->
    # reconcile; host-side bookkeeping only.
    t0: float = 0.0
    # Per-call span attributes (chunk bucket, spec round count, ...).
    span_attrs: dict = dataclasses.field(default_factory=dict)


class _StuckPayload:
    """Wraps a dispatched device payload so it never reports ready — the
    deterministic CPU stand-in for a wedged device call (fault point
    ``decode_stuck``).  Conversion raises too, so a run with the watchdog
    disabled fails loudly through the reconcile-reset path instead of
    silently reading the real array."""

    def __init__(self, inner: Any):
        self.inner = inner

    def is_ready(self) -> bool:
        return False

    def __array__(self, *args, **kwargs):
        raise FaultError("decode_stuck")

    def __iter__(self):
        raise FaultError("decode_stuck")


# Sink signature: (request_id, new_token_ids, result_or_none).  ``result`` is
# set exactly once per request, when it completes (or errors); new tokens are
# delivered as they are reconciled, including the EOS token.
TokenSink = Callable[[str, list[int], Optional[GenerationResult]], None]


class InferenceEngine:
    """Single-process engine over jitted batched-prefill + fused-decode steps.

    When ``mesh`` is given, params and KV pages are GSPMD-sharded (TP over the
    ``model`` axis) and the same jitted functions run multi-chip — XLA inserts
    the collectives from the sharding annotations.

    Not thread-safe: one thread owns the engine (see serving/service.py for
    the concurrent front-end).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Any,
        engine_cfg: EngineConfig | None = None,
        tokenizer=None,
        mesh=None,
        eos_id: Optional[int] = None,
        attn_impl=None,
        seed: int = 0,
        host_kv_tier: Optional[HostKVTier] = None,
    ):
        self.cfg = cfg
        self.ecfg = engine_cfg or EngineConfig()
        self.tokenizer = tokenizer
        self.eos_id = eos_id if eos_id is not None else (
            tokenizer.eos_id if tokenizer is not None else -1
        )
        self.mesh = mesh
        self.token_sink: Optional[TokenSink] = None

        ec = self.ecfg
        # Resident-KV representation (kv_tier rung 1), resolved before any
        # pool allocation or program build: ``kv_quant`` is "" for the
        # model-dtype oracle pool and "int8"/"fp8" for the quantized tier.
        kvd = os.environ.get("K8SLLM_KV_DTYPE", ec.kv_dtype) or "auto"
        if kvd in ("auto", "fp16", "bf16", "none"):
            self.kv_quant = ""
        elif kvd in ("int8", "fp8"):
            self.kv_quant = kvd
            if kvd == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
                logger.warning(
                    "kv_dtype=fp8 requested but this jax build has no "
                    "float8_e4m3fn; falling back to int8 KV")
        else:
            raise ValueError(
                f"unknown kv_dtype {kvd!r} (auto | int8 | fp8)")
        # Prefill-family attention path, resolved before the bucket ladder
        # is frozen (and before the mesh seq-divisibility check below sees
        # it): the flash kernel's geometry gates live in
        # ops/attention.py:select_prefill_impl; None = dense XLA oracle.
        from k8s_llm_monitor_tpu.ops.attention import select_prefill_impl
        pmode = os.environ.get("K8SLLM_PREFILL_PATH",
                               ec.prefill_path) or "auto"
        self._prefill_attn = select_prefill_impl(
            cfg=cfg, mesh=mesh, mode=pmode, kv_quant=self.kv_quant)
        self.prefill_path = ("flash" if self._prefill_attn is not None
                             else "dense")
        if self._prefill_attn is not None:
            # Cash in the flash win: long prompts chunk in 4096/8192-token
            # rounds instead of 2048 — fewer chunk rounds per prompt at the
            # same pool bytes.  Flash-gated because the dense path would
            # materialize [B, H, S, T] float32 score tensors at these S;
            # capacity-capped so small engines (tests, traceguard) keep
            # their ladders byte-for-byte unchanged.
            cap = min(ec.max_blocks_per_seq,
                      ec.num_blocks - 1) * ec.block_size
            extra = tuple(b for b in (4096, 8192)
                          if b > max(ec.prefill_buckets) and b <= cap)
            if extra:
                ec = dataclasses.replace(
                    ec, prefill_buckets=tuple(ec.prefill_buckets) + extra)
                self.ecfg = ec
        pages = llama.init_kv_pages(cfg, ec.num_blocks, ec.block_size,
                                    kv_quant=self.kv_quant)
        # Sequence-sharded prefill (SURVEY §7 step 5): on a mesh with a
        # nontrivial ``seq`` axis, prefill/chunk token batches are placed
        # sharded over ``seq`` — GSPMD then splits the per-position matmul
        # FLOPs across the axis (each device embeds/projects its sequence
        # slice, all-gathers chunk K/V for attention, and the page scatter
        # reassembles) so ONE long prompt's ingestion spreads over chips,
        # e.g. mesh_shape "1,2,4" on a v5e-8.  Decode is untouched: its
        # [B, 1] queries have no sequence axis to split.
        self._tok_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from k8s_llm_monitor_tpu.parallel.sharding import (
                kv_pages_partition_specs,
                param_partition_specs,
            )

            seq_deg = mesh.shape.get("seq", 1)
            if seq_deg > 1:
                from jax.sharding import PartitionSpec

                for b in ec.prefill_buckets:
                    if b % seq_deg:
                        raise ValueError(
                            f"prefill bucket {b} is not divisible by the "
                            f"mesh seq axis ({seq_deg}); choose bucket "
                            f"sizes that split evenly")
                self._tok_sharding = NamedSharding(
                    mesh, PartitionSpec(None, "seq"))

            pspecs = param_partition_specs(params)
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                params, pspecs,
            )
            kvspecs = kv_pages_partition_specs(
                pages, mesh, num_kv_heads=cfg.num_kv_heads)
            pages = llama.KVPages(
                k=[jax.device_put(x, NamedSharding(mesh, s))
                   for x, s in zip(pages.k, kvspecs.k)],
                v=[jax.device_put(x, NamedSharding(mesh, s))
                   for x, s in zip(pages.v, kvspecs.v)],
                # Scale leaves shard their kv-heads axis exactly when the
                # pages' fused lane dim does (SpecLayout.kv_scales).  An
                # unquantized pool keeps the EMPTY-TUPLE containers from
                # init_kv_pages — an empty list here is a different
                # treedef from what prefill/decode return, so the first
                # dispatch would silently fork a second variant of every
                # program that takes pages.
                k_scale=[jax.device_put(x, NamedSharding(mesh, s))
                         for x, s in zip(pages.k_scale, kvspecs.k_scale)]
                if pages.quantized else (),
                v_scale=[jax.device_put(x, NamedSharding(mesh, s))
                         for x, s in zip(pages.v_scale, kvspecs.v_scale)]
                if pages.quantized else (),
            )
        self.params = params
        self.pages = pages
        self.allocator = BlockAllocator(ec.num_blocks, ec.block_size)
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.allocator, ec.prefix_cache_entries,
                        max_tenant_share=ec.kv_max_tenant_share)
            if ec.prefix_cache_entries > 0 else None)
        # Cold-burst shared-prefix dedup: requests whose admission waited
        # for an in-flight lane to publish their prefix.
        self.prefix_deferrals = 0
        # Host-RAM spill tier (kv_tier rung 2).  A caller-provided tier
        # (the supervisor's engine_factory closes over one) survives engine
        # rebuilds, so spilled prefixes outlive a crash-recovery cycle.
        if host_kv_tier is None and ec.host_spill_bytes > 0:
            host_kv_tier = HostKVTier(ec.host_spill_bytes,
                                      max_tenant_share=ec.kv_max_tenant_share)
        self.host_kv_tier = host_kv_tier
        # Rehydration scatter programs, one per (leaf dtype, padded row
        # count): leaf.at[idx].set(rows) with donated leaf, so a restore
        # rebinds page leaves in place without changing treedef/sharding.
        self._tier_write_cache: dict = {}

        if attn_impl is None:
            from k8s_llm_monitor_tpu.ops.attention import select_decode_impl
            # Decode path: the fused RoPE+append+attention kernel on a
            # compatible single TPU chip; under a GSPMD mesh the split
            # kernel runs per-shard via shard_map
            # (ops/attention.py:make_tp_paged_attention) when the KV heads
            # divide the TP degree; otherwise the XLA gather path
            # partitions automatically.  A quantized pool routes to the
            # fused-quant kernel or the gather/dequant reference
            # (select_decode_impl kv_quant gate).
            mode = os.environ.get("K8SLLM_DECODE_PATH", ec.decode_path)
            attn_impl = select_decode_impl(cfg=cfg, mesh=mesh, mode=mode,
                                           kv_quant=self.kv_quant)
        self._attn_impl = attn_impl
        # "fused" | "pallas" | "gather" — surfaced in /metrics and bench.
        if self.kv_quant and llama.is_fused_quant_decode_impl(attn_impl):
            self.decode_path = "fused"
        elif self.kv_quant:
            # Quantized pool without the quant kernel: decode_step runs its
            # gather/dequant branch regardless of the impl handed in.
            self.decode_path = "gather"
        elif llama.is_fused_decode_impl(attn_impl):
            self.decode_path = "fused"
        elif getattr(attn_impl, "__name__", "") == "paged_decode_attention":
            self.decode_path = "gather"
        else:
            self.decode_path = "pallas"
        # TP collective overlap: swap the GSPMD-auto decode program for the
        # hand-staged reduce-scatter/all-gather schedule
        # (parallel/overlap.py).  The step is built once here and captured
        # by _step_core, so the scan programs and their donation/caching
        # behavior are untouched — overlap-on vs overlap-off differ only
        # in the traced layer body.
        self._overlap_step = None
        self.tp_overlap = False
        overlap_mode = os.environ.get("K8SLLM_TP_OVERLAP",
                                      ec.tp_overlap) or "auto"
        if overlap_mode not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown tp_overlap {overlap_mode!r} (auto | on | off)")
        if overlap_mode != "off":
            from k8s_llm_monitor_tpu.parallel.overlap import (
                make_overlap_decode_step,
                overlap_supported,
            )

            why_not = overlap_supported(cfg, mesh, params=self.params)
            if not why_not:
                self._overlap_step = make_overlap_decode_step(
                    mesh, cfg, self.params, self.pages,
                    attn_path=self.decode_path)
                self.tp_overlap = True
            elif overlap_mode == "on":
                raise ValueError(
                    f"tp_overlap=on but the overlap schedule cannot serve "
                    f"this (cfg, mesh): {why_not}")
            elif mesh is not None and mesh.shape.get("model", 1) > 1:
                logger.warning("tp_overlap=auto: staying on the GSPMD "
                               "schedule (%s)", why_not)
        # Measured share of the per-step ring time the overlap schedule
        # hides; estimate_hidden_share() fills it from profile/bench runs
        # and the exporter publishes it.
        self.decode_collective_hidden_share = 0.0
        # Multi-query attention for the speculative verify pass (Pallas
        # kernel on compatible single-chip TPU; XLA gather otherwise).
        # Quantized pools drop the dedicated verify kernel: llama's
        # prefill/verify gather branch dequantizes in-program instead
        # (models/llama.py _prefill_impl quant gate).
        if self.ecfg.spec_k > 0 and self._prefill_attn is not None:
            # Flash prefill serves verify too (identical geometry contract,
            # all-positions unembed) — including quantized pools, whose
            # scale planes ride as kwargs.  This lifts the historical
            # "quant drops the verify kernel" restriction above.
            self._verify_impl = self._prefill_attn
        elif self.ecfg.spec_k > 0 and not self.kv_quant:
            from k8s_llm_monitor_tpu.ops.attention import select_verify_impl

            self._verify_impl = select_verify_impl(
                cfg=cfg, mesh=mesh,
                max_table_tokens=ec.max_blocks_per_seq * ec.block_size)
        else:
            self._verify_impl = None
        # Captured by the prefill closures below; None keeps llama's
        # dense branches (in-flight attention / gather_pages).
        prefill_attn = self._prefill_attn

        def _prefill_sample_fn(params, tokens, lengths, pages, tables,
                               temp, topk, topp, rng):
            logits, pages = llama.prefill(
                params, cfg, tokens, lengths, pages, tables,
                attn_impl=prefill_attn
            )
            first = sample_tokens(
                rng, logits, temperature=temp, top_k=topk, top_p=topp
            )
            return first, pages

        def _prefill_greedy_fn(params, tokens, lengths, pages, tables):
            # Sort-free fast path for all-greedy admission rounds: skips the
            # [P, V] argsort nucleus filtering needs (V is 128k on the 8B
            # target — the sort costs more than the unembed).
            logits, pages = llama.prefill(
                params, cfg, tokens, lengths, pages, tables,
                attn_impl=prefill_attn
            )
            return greedy_tokens(logits), pages

        def _prefill_chunk_sample_fn(params, tokens, start, lengths, pages,
                                     tables, temp, topk, topp, rng):
            # Batched admission over cached prefixes: each lane ingests only
            # its unshared suffix (start = shared tokens, 0 for misses) and
            # samples its first token in the same program.
            logits, pages = llama.prefill_chunk(
                params, cfg, tokens, start, lengths, pages, tables,
                attn_impl=prefill_attn
            )
            first = sample_tokens(
                rng, logits, temperature=temp, top_k=topk, top_p=topp
            )
            return first, pages

        def _prefill_chunk_greedy_fn(params, tokens, start, lengths, pages,
                                     tables):
            logits, pages = llama.prefill_chunk(
                params, cfg, tokens, start, lengths, pages, tables,
                attn_impl=prefill_attn
            )
            return greedy_tokens(logits), pages

        def _prefill_sample_fsm_fn(params, tokens, lengths, pages, tables,
                                   fstate, ftrans, temp, topk, topp, rng):
            # Grammar-constrained admission: mask the first-token logits by
            # each lane's FSM state (0 = FREE lane, unmasked) BEFORE the
            # shared sampler — greedy lanes take the argmax of the masked
            # logits inside sample_tokens, so constrained-greedy is exact.
            logits, pages = llama.prefill(
                params, cfg, tokens, lengths, pages, tables,
                attn_impl=prefill_attn
            )
            masked = fsm_mask_logits(logits, fstate, ftrans)
            first = sample_tokens(
                rng, masked, temperature=temp, top_k=topk, top_p=topp
            )
            return first, fsm_advance(fstate, ftrans, first), pages

        def _prefill_chunk_sample_fsm_fn(params, tokens, start, lengths,
                                         pages, tables, fstate, ftrans,
                                         temp, topk, topp, rng):
            logits, pages = llama.prefill_chunk(
                params, cfg, tokens, start, lengths, pages, tables,
                attn_impl=prefill_attn
            )
            masked = fsm_mask_logits(logits, fstate, ftrans)
            first = sample_tokens(
                rng, masked, temperature=temp, top_k=topk, top_p=topp
            )
            return first, fsm_advance(fstate, ftrans, first), pages

        def _place_fn(tok_state, first, idx):
            # Scatter freshly sampled first tokens into the device-resident
            # token buffer; padding lanes carry idx == max_slots and drop.
            return tok_state.at[idx].set(first, mode="drop")

        # pages are donated so the scatter-updates happen in place on device.
        self._prefill_sample = jax.jit(_prefill_sample_fn, donate_argnums=(3,))
        self._prefill_greedy = jax.jit(_prefill_greedy_fn, donate_argnums=(3,))
        self._prefill_chunk_sample = jax.jit(
            _prefill_chunk_sample_fn, donate_argnums=(4,))
        self._prefill_chunk_greedy = jax.jit(
            _prefill_chunk_greedy_fn, donate_argnums=(4,))
        self._prefill_sample_fsm = jax.jit(
            _prefill_sample_fsm_fn, donate_argnums=(3,))
        self._prefill_chunk_sample_fsm = jax.jit(
            _prefill_chunk_sample_fsm_fn, donate_argnums=(4,))
        self._place_tokens = jax.jit(_place_fn, donate_argnums=(0,))
        # Grammar-constrained decoding state (set_grammar): host TokenFSM,
        # its device transition table, and the device-resident per-lane FSM
        # state — data-dependent like _tok_state, so it must live on device
        # to survive dispatch-ahead.  Lane state 0 is FREE (unconstrained);
        # _place_fsm (re)writes lanes at admission, zeroing reused slots.
        self._grammar = None
        self._fsm_trans = None
        self._fsm_state = jnp.zeros((ec.max_slots,), jnp.int32)
        self._place_fsm = jax.jit(
            lambda f, v, idx: f.at[idx].set(v, mode="drop"),
            donate_argnums=(0,))
        # Fused-decode programs, built lazily per (n_steps, sampled).
        self._decode_cache: dict[tuple, Any] = {}

        # Speculative decoding state: per-lane token history for the n-gram
        # proposer.  Rows are (re)written whole at admission, then extended
        # in-program as tokens are accepted.
        if ec.spec_k > 0:
            H = min(self.capacity_tokens, ec.spec_hist_cap)
            self._hist = jnp.full((ec.max_slots, H), -1, jnp.int32)
            self._hist_place = jax.jit(
                lambda h, rows, idx: h.at[idx].set(rows, mode="drop"),
                donate_argnums=(0,))
        else:
            self._hist = None
            self._hist_place = None
        self.spec_tokens = 0         # tokens emitted by spec dispatches
        self.spec_verify_steps = 0   # verify forwards those tokens cost
        self.spec_lane_rounds = 0    # sum of active lanes over those forwards
        # Adaptive speculation state: per-request-class EMA of accepted
        # tokens per lane-round (serving/spec.py:AcceptanceEMA).  No
        # measurement yet -> speculate optimistically; a class whose EMA
        # stays under spec_min_accept has drafting auto-disabled (fused
        # path) except for a probe every spec_probe_every dispatches.
        self._spec_accept = AcceptanceEMA(floor=ec.spec_min_accept,
                                          probe_every=ec.spec_probe_every)

        self._rng = jax.random.PRNGKey(seed)
        self._tok_state = jnp.zeros((ec.max_slots,), jnp.int32)
        self._pending: collections.deque[GenerationRequest] = collections.deque()
        self._slots: list[Optional[_Slot]] = [None] * ec.max_slots
        self._results: dict[str, GenerationResult] = {}
        self._inflight: collections.deque[_Inflight] = collections.deque()
        self._next_call_id = 0
        # Blocks of retired slots still referenced by in-flight calls:
        # released once the tagged call reconciles.
        self._deferred_frees: list[tuple[int, list[int]]] = []
        self.steps = 0
        self.prefills = 0
        self.preemptions = 0
        self.preemptions_by_class: dict[str, int] = {}
        self.brownout_clamps = 0
        self._chunks_since_decode = 0
        # Deadline-aware chunk sizing (interactive_chunk_bucket): rounds
        # clamped because interactive work was queued, and the bucket the
        # most recent chunk round actually used (exporter gauge + tests).
        self.chunk_shrinks = 0
        self.last_chunk_bucket = 0
        # Resilience state (docs/resilience.md).  ``health`` is an optional
        # HealthMonitor attached by EngineService; the engine records
        # watchdog trips and dispatch outcomes into it directly so the
        # state machine sees events the moment they happen.
        self._faults = get_injector()
        self.health = None
        # Optional brownout-level source (callable -> int 0..2), attached
        # by EngineService; consulted host-side only, never traced.
        self.brownout = None
        self.dispatch_failures = 0
        self.consecutive_dispatch_failures = 0
        self.watchdog_trips = 0
        self.deadline_expired = 0
        self.requeues = 0
        self.constrained_requests = 0
        # EMA of submit->admission wait; a shed signal when slots churn
        # slower than the arrival rate.
        self.slot_wait_ema_s = 0.0
        # Per-class admission-wait and TTFT EMAs (exporter gauges).  Keys
        # appear on first observation, so the exporter can NaN-mark
        # classes that never carried traffic instead of mixing populations.
        self.slot_wait_ema_by_class: dict[str, float] = {}
        self.ttft_ema_by_class: dict[str, float] = {}
        # TTFT histogram (Prometheus semantics: cumulative le buckets +
        # sum/count), observed once per request at admission reconcile.
        self.ttft_buckets: tuple[float, ...] = (
            0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
        self.ttft_counts = [0] * (len(self.ttft_buckets) + 1)  # +Inf last
        self.ttft_sum = 0.0
        self.ttft_count = 0
        # Decode phase attribution (monitor/exporter.py gauges).
        # decode_host_gap_ms: EMA of host time blocked per decode/spec
        # reconcile — ~0 when dispatch-ahead fully hides device latency.
        # decode_attn_ms / decode_sample_ms: per-step attention / sampling
        # cost, populated by profile_decode_phases() (bench or an admin
        # probe); never computed on a /metrics scrape.
        self.decode_host_gap_ms = 0.0
        self.decode_attn_ms = 0.0
        self.decode_sample_ms = 0.0
        # Prefill fast-path attribution (exporter parity with the decode
        # trio): prefill_attn_ms is an EMA of per-prefill-call wall time
        # (dispatch -> reconcile, admission and chunk rounds alike);
        # prefill_bucket_rounds counts dispatched rounds per bucket size,
        # so the signals plane can see which buckets production actually
        # runs (the 4096/8192 rungs exist only on the flash path).
        self.prefill_attn_ms = 0.0
        self.prefill_bucket_rounds: dict[int, int] = {}
        # Per-step collective (ICI) share of the TP decode step, estimated
        # by profile_decode_phases() from the measured step time and the
        # ring-all-reduce byte model; 0.0 off-mesh or before profiling.
        self.decode_collective_share = 0.0
        # Request-lifecycle histograms (observability/metrics.py): per-SLO
        # class, with exemplar trace ids, observed on the step thread only.
        # The exporter renders these as real Prometheus histograms.
        _lat = (0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
        self.hist_ttft = ClassHistogram(_lat)
        self.hist_e2e = ClassHistogram(_lat)
        self.hist_queue_wait = ClassHistogram(_lat)
        # Per fused-decode-step seconds (call wall time / steps in call).
        self.hist_decode_step = ClassHistogram(
            (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0))
        # Tracing (observability/tracing.py): phase spans are recorded
        # host-side at dispatch/reconcile time against each request's
        # captured TraceContext — never inside a traced program.  Engine
        # maintenance work with no owning request (KV spill/restore)
        # records under a per-engine synthetic root span.
        self._tracer = get_tracer()
        self._flight = get_flight_recorder()
        self._maint_ctx = self._tracer.new_trace()
        if self._maint_ctx is not None and self._maint_ctx.sampled:
            t_now = time.monotonic()
            self._tracer.record(
                "engine.maintenance", t_now, t_now, self._maint_ctx,
                span_id=self._maint_ctx.span_id, parent_id="")

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @property
    def capacity_tokens(self) -> int:
        """Max cached tokens for one sequence (per-seq table cap and pool)."""
        ec = self.ecfg
        return min(ec.max_blocks_per_seq, ec.num_blocks - 1) * ec.block_size

    def _cap_request(self, req: GenerationRequest) -> None:
        """Enforce prompt_len + max_tokens <= capacity (submit-time truncation
        prevents the block-table overflow crash and the can_alloc livelock).
        Keeps the prompt *tail* — diagnosis prompts front-load boilerplate —
        and never produces a degenerate slice."""
        cap = self.capacity_tokens
        sp = req.sampling
        if sp.max_tokens >= cap:
            req.sampling = dataclasses.replace(sp, max_tokens=cap - 1)
            sp = req.sampling
        overflow = len(req.prompt_ids) + sp.max_tokens - cap
        if overflow > 0:
            req.prompt_ids = req.prompt_ids[overflow:]
            if req.orig_prompt_len >= 0:
                # Preempted fold being re-capped: the dropped tokens come off
                # the original-prompt prefix, not the generated tail.
                req.orig_prompt_len = max(0, req.orig_prompt_len - overflow)

    def set_grammar(self, fsm) -> None:
        """Install the :class:`~..diagnosis.grammar.TokenFSM` constrained
        requests decode against.  One grammar per engine (the verdict
        schema); the dense table moves to device once, and every program
        variant closes over nothing — the table is a runtime argument, so
        swapping grammars of the same shape costs no recompile."""
        if fsm.vocab_size > self.cfg.vocab_size:
            raise ValueError(
                f"grammar vocab {fsm.vocab_size} exceeds model vocab "
                f"{self.cfg.vocab_size}")
        if fsm.eos_id != self.eos_id:
            raise ValueError(
                f"grammar eos_id {fsm.eos_id} != engine eos_id {self.eos_id}")
        self._grammar = fsm
        self._fsm_trans = jnp.asarray(fsm.trans)

    def _fsm_entry(self, req: GenerationRequest) -> int:
        """FSM state for ``req``'s next sampled token: the grammar start
        state walked through any generated tokens folded back into the
        prompt by preemption / pipeline-reset requeue.  A fold that the
        grammar rejects (only possible if the grammar changed under a
        supervisor rebuild — a documented limitation) restarts from the
        grammar start state rather than silently dropping the constraint."""
        if not req.sampling.constrained or self._grammar is None:
            return 0
        gen = (req.prompt_ids[req.orig_prompt_len:]
               if req.orig_prompt_len >= 0 else [])
        state = self._grammar.walk(gen)
        return state if state > 0 else self._grammar.start

    def submit(self, req: GenerationRequest) -> None:
        if not req.prompt_ids:
            raise ValueError("empty prompt")
        if req.sampling.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        # Defense in depth: the trust boundary (service/HTTP) normalized
        # already, but a raw-engine caller must not smuggle an unvalidated
        # namespace into the digest seeds.
        req.tenant = normalize_tenant(req.tenant, default=DEFAULT_TENANT)
        if req.sampling.constrained:
            if self._grammar is None:
                raise ValueError(
                    "constrained sampling requires set_grammar() first")
            self.constrained_requests += 1
            # Guarantee the forced EOS is reachable within budget: the
            # grammar's longest accepted sequence bounds generation, so
            # raising max_tokens to it never produces more tokens — it only
            # prevents a mid-object "length" truncation.
            ml = self._grammar.max_len
            if ml > 0 and req.sampling.max_tokens < ml:
                req.sampling = dataclasses.replace(
                    req.sampling, max_tokens=ml)
        self._cap_request(req)
        self._pending.append(req)

    def submit_text(self, request_id: str, prompt: str,
                    sampling: SamplingParams | None = None) -> None:
        assert self.tokenizer is not None
        self.submit(GenerationRequest(
            request_id=request_id,
            prompt_ids=self.tokenizer.encode(prompt),
            sampling=sampling or SamplingParams(),
        ))

    def poll(self, request_id: str) -> Optional[GenerationResult]:
        return self._results.pop(request_id, None)

    def cancel(self, request_id: str) -> bool:
        """Stop generating for a request (client went away).

        Pending requests are failed immediately; an active slot is marked
        and retired at its next reconcile (its in-flight device steps finish
        but no new ones are dispatched).  Returns True if found."""
        for i, req in enumerate(self._pending):
            if req.request_id == request_id:
                del self._pending[i]
                self._fail_request(req, "cancelled")
                return True
        for s in self._slots:
            if s is not None and s.req.request_id == request_id:
                s.cancel_requested = True
                return True
        return False

    @property
    def has_work(self) -> bool:
        return (bool(self._pending) or bool(self._inflight)
                or any(s is not None for s in self._slots))

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    @property
    def queue_tokens(self) -> int:
        """Prompt-token backlog waiting for admission (shed signal)."""
        return sum(len(r.prompt_ids) for r in self._pending)

    def queue_tokens_by_class(self) -> dict[str, int]:
        """Prompt-token backlog per SLO class (fleet stats + class-aware
        shedding).  Only classes with queued work appear as keys."""
        out: dict[str, int] = {}
        for r in self._pending:
            out[r.slo_class] = out.get(r.slo_class, 0) + len(r.prompt_ids)
        return out

    @property
    def active_slots(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def admission_headroom_tokens(self) -> int:
        """KV capacity (tokens) admission may count on, per the
        ``kv_admission`` policy.

        ``device``/``off``: tokens the free device blocks cover.  ``tier``
        additionally counts prefix-cache blocks a LOSSLESS host spill
        could reclaim — ``evictable_blocks`` bounded by the host tier's
        free bytes — because that is exactly the capacity ``_ensure_free``
        can deliver without destroying cache content.  With no host tier
        configured there is nothing to spill to, so the tier bonus is 0
        (eviction would drop prefixes; the queue + OutOfBlocks pushback
        stay the arbiter, as before this knob existed).  Exported as the
        ``kv_admission_headroom_tokens`` gauge."""
        ec = self.ecfg
        free_blocks = self.allocator.free_blocks
        if (ec.kv_admission == "tier" and self.prefix_cache is not None
                and self.host_kv_tier is not None):
            evictable = self.prefix_cache.evictable_blocks()
            if evictable > 0:
                cfg = self.cfg
                pdtype = np.dtype(self.pages.k[0].dtype)
                blk_bytes = cfg.num_layers * page_slice_bytes(
                    cfg.num_kv_heads, cfg.head_dim_, ec.block_size,
                    pdtype.itemsize, scale_bytes=4 if self.kv_quant else 0)
                st = self.host_kv_tier.stats()
                host_free = max(st["max_bytes"] - st["bytes"], 0)
                free_blocks += min(evictable, host_free // max(blk_bytes, 1))
        return free_blocks * ec.block_size

    def should_shed(self, slo_class: str = DEFAULT_CLASS,
                    need_tokens: int = 0) -> str:
        """Non-empty reason when new work of ``slo_class`` should be shed
        (admission control): queue-token backlog or admission-wait EMA
        above the configured thresholds, or — when the caller passes the
        request's KV footprint as ``need_tokens`` — a footprint the
        tier-aware headroom cannot cover (``kv_admission`` policy).  The
        caller (EngineService.submit) turns this into a retriable
        ``OverloadedError``; the engine itself never rejects — by the time
        work reaches ``submit()`` the caller has already been told to back
        off.

        Shedding is class-ordered: a request is charged only for backlog
        of its own class and above (queued lower-class tokens would be
        admitted *after* it, so they are not load it waits behind), and no
        request is shed while strictly lower-class work is queued — that
        work sheds/evicts first, so ``interactive`` is never refused while
        ``batch`` waits.  With single-class traffic (everything at the
        default) this reduces exactly to the flat thresholds."""
        ec = self.ecfg
        rank = SLO_RANK.get(slo_class, SLO_RANK[DEFAULT_CLASS])
        by_class = self.queue_tokens_by_class()
        ahead = sum(t for c, t in by_class.items()
                    if SLO_RANK.get(c, SLO_RANK[DEFAULT_CLASS]) <= rank)
        lower_queued = any(
            t > 0 and SLO_RANK.get(c, SLO_RANK[DEFAULT_CLASS]) > rank
            for c, t in by_class.items())
        if lower_queued:
            return ""
        if 0 < ec.shed_queue_tokens <= ahead:
            return (f"queue token backlog {ahead} >= "
                    f"{ec.shed_queue_tokens} for class {slo_class}")
        if 0 < ec.shed_slot_wait_s <= self.slot_wait_ema_s:
            return (f"admission wait EMA {self.slot_wait_ema_s:.2f}s >= "
                    f"{ec.shed_slot_wait_s:.2f}s")
        # Capacity clause: checked after the class ordering above so that
        # queued-lower-class eviction/preemption gets first refusal — it
        # can free device blocks the headroom figure does not count.
        # "tier" only arms it when a host tier is actually configured:
        # without one the headroom figure would say nothing the legacy
        # queue + OutOfBlocks pushback does not already handle.
        capacity_armed = (ec.kv_admission == "device"
                          or (ec.kv_admission == "tier"
                              and self.host_kv_tier is not None))
        if need_tokens > 0 and capacity_armed:
            headroom = self.admission_headroom_tokens()
            if need_tokens > headroom:
                return (f"kv capacity: request needs {need_tokens} tokens, "
                        f"admission headroom is {headroom} "
                        f"(kv_admission={ec.kv_admission})")
        return ""

    def generate(self, prompts: list[list[int]],
                 sampling: SamplingParams | None = None) -> list[GenerationResult]:
        """Synchronous batch generation (runs the loop to completion)."""
        ids = [f"gen-{i}" for i in range(len(prompts))]
        for rid, p in zip(ids, prompts):
            self.submit(GenerationRequest(rid, list(p),
                                          sampling or SamplingParams()))
        while self.has_work:
            self.step()
        return [self._results.pop(rid) for rid in ids]

    def generate_text(self, prompt: str,
                      sampling: SamplingParams | None = None) -> str:
        assert self.tokenizer is not None
        res = self.generate([self.tokenizer.encode(prompt)], sampling)[0]
        return self.tokenizer.decode(res.token_ids)

    # ------------------------------------------------------------------
    # engine loop
    # ------------------------------------------------------------------

    def step(self) -> None:
        """One scheduler iteration: dispatch up to ``max_admission_rounds``
        batched prefills and one fused decode, then reconcile in-flight
        results down to the dispatch-ahead window (or fully, when there is
        nothing left to dispatch)."""
        self._enforce_deadlines()
        self._schedule_classes()
        dispatched = 0
        rounds = 0
        while rounds < self.ecfg.max_admission_rounds and self._admit_round():
            rounds += 1
            dispatched += 1
        chunked = self._dispatch_prefill_chunks()
        if chunked:
            dispatched += 1
            self._chunks_since_decode += 1
        if (not chunked or self._chunks_since_decode
                >= self.ecfg.decode_every_n_chunk_rounds):
            if self._dispatch_decode():
                dispatched += 1
                self._chunks_since_decode = 0
        # Opportunistic drain: results the device already finished cost no
        # host wait, and every reconcile here frees slots/pages one step
        # earlier — admission and chunk prep in the NEXT step() overlap
        # with whatever is still running on device.
        while self._inflight and self._call_ready(self._inflight[0]):
            self._reconcile_one()
        if dispatched:
            while len(self._inflight) > self.ecfg.max_inflight:
                self._reconcile_one()
        else:
            # Nothing dispatchable: drain so retirements/admissions unblock.
            if self._inflight:
                self._reconcile_one()

    @staticmethod
    def _call_ready(call: _Inflight) -> bool:
        """True when reconciling ``call`` would not block on the device."""
        arrs = call.arr if isinstance(call.arr, tuple) else (call.arr,)
        try:
            return all(a.is_ready() for a in arrs)
        except AttributeError:  # non-jax payloads (tests with stub arrays)
            return True

    def _reconcile_all(self) -> None:
        while self._inflight:
            self._reconcile_one()

    # -- deadlines / failure recovery -----------------------------------

    def _deadline_of(self, req: GenerationRequest, queued: bool) -> float:
        """Absolute monotonic deadline for ``req``; +inf when unbounded.
        A per-request deadline_s always applies; the config queue TTL only
        bounds time spent *waiting* (a running request already holds its
        pages — killing it at TTL would waste the work done)."""
        if req.deadline_s > 0:
            return req.submit_time + req.deadline_s
        if queued and self.ecfg.queue_ttl_s > 0:
            return req.submit_time + self.ecfg.queue_ttl_s
        return float("inf")

    def _enforce_deadlines(self) -> None:
        """Fail expired queued requests and abort expired running slots.
        Runs at the top of every step(); admission re-checks queued
        candidates so a request never spends KV pages after expiry."""
        now = time.monotonic()
        if self._pending:
            keep: collections.deque[GenerationRequest] = collections.deque()
            for req in self._pending:
                if now > self._deadline_of(req, queued=True):
                    self.deadline_expired += 1
                    self._fail_request(
                        req, f"deadline exceeded after "
                             f"{now - req.submit_time:.2f}s in queue")
                else:
                    keep.append(req)
            self._pending = keep
        for s in self._slots:
            if (s is not None and not s.retired and not s.cancel_requested
                    and now > self._deadline_of(s.req, queued=False)):
                self.deadline_expired += 1
                s.abort_cause = (f"deadline exceeded after "
                                 f"{now - s.req.submit_time:.2f}s "
                                 f"({len(s.generated)} tokens generated)")
                # Reuse the cancel path: no new dispatches; the slot
                # retires once its in-flight steps settle.
                s.cancel_requested = True

    def _record_dispatch_failure(self, exc: BaseException) -> None:
        self.dispatch_failures += 1
        self.consecutive_dispatch_failures += 1
        self._flight.note("dispatch_failure", error=repr(exc)[:200],
                          consecutive=self.consecutive_dispatch_failures)
        if self.health is not None:
            self.health.record_dispatch_failure()

    def _record_dispatch_ok(self) -> None:
        self.consecutive_dispatch_failures = 0
        if self.health is not None:
            self.health.record_dispatch_ok()

    def _note_admission_wait(self, req: GenerationRequest) -> None:
        """Track how long requests sit queued before winning a slot — the
        EMA backs the ``shed_slot_wait_s`` load-shedding signal; the
        per-class EMAs back the exporter's ``queue_wait_ms{class}``."""
        now = time.monotonic()
        wait = now - req.submit_time
        if self.slot_wait_ema_s == 0.0:
            self.slot_wait_ema_s = wait
        else:
            self.slot_wait_ema_s = (
                0.9 * self.slot_wait_ema_s + 0.1 * wait)
        prev = self.slot_wait_ema_by_class.get(req.slo_class)
        self.slot_wait_ema_by_class[req.slo_class] = (
            wait if prev is None else 0.9 * prev + 0.1 * wait)
        self.hist_queue_wait.observe(wait, req.slo_class, self._trace_id(req))
        self._span("engine.queue_wait", req.submit_time, now, req)

    # -- tracing helpers (observability/tracing.py) ----------------------

    @staticmethod
    def _trace_id(req: GenerationRequest) -> str:
        """Exemplar trace id for histograms ('' when untraced/unsampled)."""
        ctx = req.trace
        return ctx.trace_id if ctx is not None and ctx.sampled else ""

    def _span(self, name: str, t0: float, t1: float,
              req: GenerationRequest, status: str = "ok", **attrs) -> None:
        """Record one engine phase span under ``req``'s trace.  No-op for
        untraced or unsampled requests — the hot-path cost is one
        attribute check."""
        ctx = req.trace
        if ctx is None or not ctx.sampled:
            return
        attrs["request_id"] = req.request_id
        attrs["class"] = req.slo_class
        self._tracer.record(name, t0, t1, ctx, attrs=attrs, status=status)

    def _end_request_span(self, req: GenerationRequest, status: str,
                          **attrs) -> None:
        """Close the per-request root span (submit -> terminal outcome).
        Uses the context's own span/parent ids so the phase spans recorded
        along the way nest under it with no orphan parents."""
        ctx = req.trace
        if ctx is None or not ctx.sampled:
            return
        attrs["request_id"] = req.request_id
        attrs["class"] = req.slo_class
        self._tracer.record(
            "engine.request", req.submit_time, time.monotonic(), ctx,
            span_id=ctx.span_id, parent_id=ctx.parent_id,
            attrs=attrs, status=status)

    # -- SLO-class scheduling (resilience/slo.py) ------------------------

    def _brownout_level(self) -> int:
        """Current brownout ladder level; 0 when no controller attached.
        Host-side scheduling input only — never read inside a traced
        program."""
        if self.brownout is None:
            return 0
        try:
            return int(self.brownout())
        except Exception:  # noqa: BLE001 — a dying controller must not wedge the step loop
            return 0

    def _clamp_for_brownout(self, req: GenerationRequest) -> None:
        """At DEGRADED or worse, clamp batch-class generation budgets so
        bulk work stops monopolizing decode bandwidth.  Applied at
        admission — lanes already running keep their budget.  Constrained
        requests are exempt: the grammar's forced EOS needs its max
        accepting path reachable."""
        cap = self.ecfg.brownout_batch_max_tokens
        if (cap <= 0 or req.slo_class != "batch"
                or req.sampling.constrained
                or req.sampling.max_tokens <= cap
                or self._brownout_level() < 1):
            return
        req.sampling = dataclasses.replace(req.sampling, max_tokens=cap)
        self.brownout_clamps += 1

    def _eviction_victim(self, worse_than: int = -1) -> int:
        """Running lane to evict under pressure: lowest SLO class first,
        youngest within a class (so the oldest protected work always makes
        progress).  ``worse_than`` >= 0 restricts candidates to lanes
        strictly underranking it — voluntary preemption must only evict
        lanes a queued request outranks.  Cancelled lanes are skipped
        (preempting one would resurrect a request nobody is waiting for).
        Returns -1 when no lane qualifies."""
        best = -1
        best_key: tuple[int, float] | None = None
        for j, sl in enumerate(self._slots):
            if sl is None or sl.retired or sl.cancel_requested:
                continue
            r = SLO_RANK.get(sl.req.slo_class, SLO_RANK[DEFAULT_CLASS])
            if 0 <= worse_than < r or worse_than < 0:
                key = (r, sl.req.submit_time)
                if best_key is None or key > best_key:
                    best, best_key = j, key
        return best

    def _schedule_classes(self) -> None:
        """Class-priority scheduling, all host-side (nothing traced):
        stable-sort the pending queue by SLO rank (FIFO preserved within a
        class — preempted requests pushed to the queue head stay first in
        their class), then voluntarily evict lower-class running lanes
        while a strictly higher-class request waits with no free slot,
        bounded by ``max_preemptions`` per step."""
        self._sort_pending_by_class()
        budget = self.ecfg.max_preemptions
        preempted = 0
        while preempted < budget and self._pending:
            if any(s is None for s in self._slots):
                return  # a free slot exists; plain admission will fill it
            best = min(SLO_RANK.get(r.slo_class, SLO_RANK[DEFAULT_CLASS])
                       for r in self._pending)
            if self._eviction_victim(worse_than=best) < 0:
                return
            # Recompute-preemption requires reconciled lanes: the folded
            # prompt must contain every sampled token (byte-exactness).
            self._reconcile_all()
            if any(s is None for s in self._slots):
                continue  # the drain freed a slot; no eviction needed
            victim = self._eviction_victim(worse_than=best)
            if victim < 0:
                return
            try:
                self._faults.maybe_raise("lane_eviction")
            except FaultError as exc:
                # Eviction path died mid-ladder: running lanes are
                # untouched and every already-preempted request is safely
                # queued — record the failure and stop evicting this step.
                self._record_dispatch_failure(exc)
                return
            self._preempt(victim)
            # The victim was requeued at the queue head; re-sort so the
            # higher-class request it was evicted for is admitted first
            # (otherwise the victim reclaims its own slot and the next
            # step evicts it again — a preemption livelock).
            self._sort_pending_by_class()
            preempted += 1

    def _sort_pending_by_class(self) -> None:
        """Stable-sort the pending queue by SLO rank (FIFO preserved
        within a class).  Skipped for single-class traffic: order is
        already FIFO and the sort would be pure overhead."""
        if len(self._pending) > 1 and len(
                {r.slo_class for r in self._pending}) > 1:
            self._pending = collections.deque(sorted(
                self._pending,
                key=lambda r: SLO_RANK.get(
                    r.slo_class, SLO_RANK[DEFAULT_CLASS])))

    def _requeue_or_fail(self, slot_idx: int, cause: str) -> None:
        """Recovery path for a slot whose in-flight work was lost (pipeline
        reset): recompute-requeue with generated tokens folded into the
        prompt, bounded by ``max_requeues``, then fail with the cause.
        Caller must have zeroed the slot's inflight counters and released
        any deferred frees first."""
        s = self._slots[slot_idx]
        assert s is not None
        self.allocator.free(s.blocks)
        self._slots[slot_idx] = None
        s.retired = True
        req = s.req
        if s.cancel_requested or req.requeues >= self.ecfg.max_requeues:
            # No caller left to retry for (cancelled / deadline-aborted)
            # or the requeue budget is spent: finish now with the cause.
            # Fold reconciled tokens into the prompt first so the error
            # result still carries the partial output.
            if s.generated:
                req.prompt_ids = req.prompt_ids + s.generated
            if s.cancel_requested:
                self._fail_request(req, s.abort_cause or "cancelled")
            else:
                self._fail_request(
                    req, f"{cause} (gave up after {req.requeues} requeues)")
            return
        req.requeues += 1
        self.requeues += 1
        consumed = len(s.generated)
        if consumed:
            req.prompt_ids = req.prompt_ids + s.generated
            req.sampling = dataclasses.replace(
                req.sampling,
                max_tokens=max(1, req.sampling.max_tokens - consumed))
        self._cap_request(req)
        self._pending.appendleft(req)
        t_now = time.monotonic()
        self._span("engine.requeue", t_now, t_now, req, status="error",
                   cause=cause[:200], requeues=req.requeues)
        self._flight.note("requeue", request_id=req.request_id,
                          cause=cause, requeues=req.requeues)

    def _reset_pipeline(self, cause: str,
                        extra_calls: tuple = ()) -> None:
        """Drop every in-flight call and recover the engine to a clean,
        serving state after a stuck or failed dispatch.

        Device-side page/token-buffer contents are suspect after a lost
        call (later dispatches in the chain consumed the failed call's
        donated buffers), so every live slot recovers by recompute: its
        reconciled tokens fold into the prompt and it re-queues (bounded
        by ``max_requeues``).  Shared prefix pages are dropped for the
        same reason.  The allocator's free count returns to its idle
        baseline — nothing leaks across a reset."""
        # Failure edge: snapshot the span ring + recent events to a flight
        # artifact BEFORE recovery mutates slot state (watchdog fires land
        # here), so the postmortem shows the pipeline as it wedged.
        self._flight.note("pipeline_reset", cause=cause,
                          inflight=len(self._inflight) + len(extra_calls),
                          watchdog_trips=self.watchdog_trips)
        self._flight.dump("pipeline_reset", extra={"cause": cause})
        calls = list(extra_calls) + list(self._inflight)
        self._inflight.clear()
        for call in calls:
            if call.kind in ("decode", "spec"):
                for _, s, _steps in call.lanes:
                    s.inflight_decode = 0
            elif call.kind == "chunk":
                for s in call.touched:
                    s.inflight_chunks = 0
        # No in-flight call references retired pages anymore.
        for _, blocks in self._deferred_frees:
            self.allocator.free(blocks)
        self._deferred_frees.clear()
        # Cached prefix pages may hold partial writes from the lost calls.
        # Deliberately NOT spilled to the host tier first — suspect pages
        # must never be demoted (a poisoned spill would resurface as wrong
        # KV on restore); already-spilled entries are untouched and stay
        # restorable after the reset.
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            s.inflight_decode = 0
            s.inflight_chunks = 0
            self._requeue_or_fail(i, cause)

    # -- admission ------------------------------------------------------

    def _bucket(self, n: int) -> int:
        """Smallest prefill bucket covering ``n`` tokens.

        ``n`` must not exceed the largest bucket — longer prompts go through
        chunked prefill, never silent clamping.
        """
        return prefill_bucket_for(n, self.ecfg.prefill_buckets)

    def _free_slots(self) -> list[int]:
        return [i for i, s in enumerate(self._slots) if s is None]

    def _lane_count(self, n: int) -> int:
        """Smallest power-of-two lane count covering ``n`` (capped at
        ``max_prefills_per_step``).  Padded lanes cost real FLOPs — a
        2-candidate round padded to 8 lanes dispatches 4x the needed
        prefill compute — while the pow-2 ladder keeps the compile cache
        at log2(max) entries instead of one per batch size."""
        P = 1
        while P < n:
            P <<= 1
        return min(P, self.ecfg.max_prefills_per_step)

    def _tokens_to_device(self, tokens: np.ndarray):
        """Token batch -> device, sharded over the mesh ``seq`` axis when
        sequence-parallel prefill is active (see __init__)."""
        t = jnp.asarray(tokens)
        if self._tok_sharding is not None:
            t = jax.device_put(t, self._tok_sharding)
        return t

    def _fail_request(self, req: GenerationRequest, msg: str) -> None:
        now = time.monotonic()
        result = GenerationResult(
            request_id=req.request_id,
            token_ids=req.prompt_ids[req.orig_prompt_len:]
            if req.orig_prompt_len >= 0 else [],
            finish_reason="error",
            ttft_s=0.0,
            latency_s=now - req.submit_time,
            error=msg,
        )
        self._results[req.request_id] = result
        self.hist_e2e.observe(result.latency_s, req.slo_class,
                              self._trace_id(req))
        self._end_request_span(req, "error", finish_reason="error",
                               error=msg[:200])
        if self.token_sink is not None:
            self.token_sink(req.request_id, [], result)

    def _emit(self, req: GenerationRequest, toks: list[int]) -> None:
        if self.token_sink is not None and toks:
            self.token_sink(req.request_id, toks, None)

    def _lane_buffers(self, P: int, bucket: int, table_width: int = 0):
        """Host-side lane arrays shared by the admission and chunk-round
        dispatch paths: (tokens, start, lengths, tables, idx, temp, topk,
        topp).  ``idx`` defaults to max_slots so padding / non-final lanes
        scatter their sampled token out of range (dropped).

        ``table_width`` (0 = full ``max_blocks_per_seq``) narrows the block
        table passed to the chunked program: its paged-attention gather
        materializes ``table_width * block_size`` keys per lane per layer
        regardless of real context, so a round early in a long prompt
        would otherwise pay the full-capacity gather (measured on v5e 8B
        W8A8: [4,512] chunk rounds run 221 ms at 2048 gathered keys vs
        171 ms at 1024 — ~25 ms per extra 512 keys)."""
        ec = self.ecfg
        W = table_width or ec.max_blocks_per_seq
        return (np.zeros((P, bucket), np.int32),
                np.zeros((P,), np.int32),
                np.zeros((P,), np.int32),
                np.zeros((P, W), np.int32),
                np.full((P,), ec.max_slots, np.int32),
                np.zeros((P,), np.float32),
                np.zeros((P,), np.int32),
                np.ones((P,), np.float32))

    def _table_width(self, max_tokens_covered: int) -> int:
        """Block-table width bucket for a chunked dispatch: enough blocks
        for the deepest lane's context, rounded up to 32 blocks so compile
        variants stay bounded (<= max_blocks_per_seq/32 widths)."""
        need = (max_tokens_covered + self.ecfg.block_size - 1) \
            // self.ecfg.block_size
        return min(self.ecfg.max_blocks_per_seq, (need + 31) // 32 * 32)

    def _write_hist(self, entries: list[tuple[int, GenerationRequest]]) -> None:
        """Load prompt tokens into the speculation history rows of freshly
        occupied slots (one batched scatter).  Prompts longer than the
        window keep their head — matches past the window just stop
        proposing, which degrades acceptance, never correctness."""
        if self._hist is None or not entries:
            return
        H = self._hist.shape[1]
        # Fixed row counts (1 or the admission lane max) keep the compile
        # cache at two entries; padding rows carry idx == max_slots (drop).
        P = 1 if len(entries) == 1 else self.ecfg.max_prefills_per_step
        rows = np.full((P, H), -1, np.int32)
        idx = np.full((P,), self.ecfg.max_slots, np.int32)
        for j, (slot_idx, req) in enumerate(entries):
            L = min(len(req.prompt_ids), H)
            rows[j, :L] = req.prompt_ids[:L]
            idx[j] = slot_idx
        self._hist = self._hist_place(
            self._hist, jnp.asarray(rows), jnp.asarray(idx))

    def _ensure_free(self, num_tokens: int) -> bool:
        """Make room for ``num_tokens`` of new blocks, evicting LRU prefix
        cache entries if needed.  Eviction drops the cache's reference; a
        block only returns to the free list when no live slot shares it."""
        while not self.allocator.can_alloc(num_tokens):
            if not self._evict_prefix_lru():
                return False
        return True

    # -- host KV tier (spill / restore, serving/kv_tier.py) --------------

    def _evict_prefix_lru(self) -> bool:
        """Pressured prefix-cache eviction, demoting to the host tier.

        With a :class:`HostKVTier` attached, the LRU victim's page rows are
        fetched off-device and stored under its chain digest BEFORE the
        device-side eviction — the next prompt that would have hit it
        rehydrates (``_try_restore``) instead of re-prefilling.  The spill
        is strictly best-effort: any failure degrades to the historical
        drop (the supervisor's replay machinery re-prefills on demand)."""
        pc = self.prefix_cache
        if pc is None:
            return False
        tier = self.host_kv_tier
        if tier is not None:
            peek = pc.peek_lru()
            if peek is not None:
                digest, blocks = peek
                # The victim's namespace follows it to the host tier (the
                # digest is already tenant-seeded; the tag drives the
                # tier's per-tenant byte accounting + max-share cap).
                victim_tenant = pc.peek_lru_tenant() or DEFAULT_TENANT
                t_spill = time.monotonic()
                try:
                    tier.put(digest, self._fetch_rows(blocks),
                             tenant=victim_tenant)
                except Exception as exc:  # noqa: BLE001 — spill must never block eviction
                    logger.warning("KV spill failed (%s); dropping entry",
                                   exc)
                else:
                    # Cache-maintenance work has no owning request; spans
                    # land under the engine's synthetic maintenance root.
                    if (self._maint_ctx is not None
                            and self._maint_ctx.sampled):
                        self._tracer.record(
                            "engine.kv_spill", t_spill, time.monotonic(),
                            self._maint_ctx, attrs={"blocks": len(blocks)})
                    self._flight.note("kv_spill", blocks=len(blocks))
        return pc.evict_lru()

    def _fetch_rows(self, blocks: list[int]) -> SpilledPrefix:
        """Materialize the page rows of ``blocks`` on the host (one gather
        per pytree leaf; syncs on the dispatch chain, which is exactly the
        price of demotion).  Under a mesh the fancy-index gather yields the
        GLOBAL fused-lane rows — page ids are global, so a spilled entry is
        mesh-shape-portable."""
        idx = np.asarray(blocks, np.int64)
        pages = self.pages
        quant = pages.quantized
        layers: list[tuple[np.ndarray, ...]] = []
        for li in range(len(pages.k)):
            leaf = (pages.k[li], pages.v[li])
            if quant:
                leaf += (pages.k_scale[li], pages.v_scale[li])
            layers.append(tuple(np.asarray(a[idx]) for a in leaf))
        return SpilledPrefix(n_blocks=len(blocks), layers=layers)

    def _write_rows(self, blocks: list[int], layers: list[tuple]) -> None:
        """Scatter host rows back into the device pool at ``blocks``,
        rebinding every page leaf through a donated jitted update so the
        pool keeps its treedef, shapes, and sharding (zero recompiles of
        the decode programs).  Rows are padded to a power-of-two count with
        the out-of-range index ``num_blocks`` (mode="drop") — never index
        0, whose null block must stay zero."""
        k = len(blocks)
        P = 1
        while P < k:
            P <<= 1
        idx = np.full((P,), self.ecfg.num_blocks, np.int32)
        idx[:k] = blocks
        idx_dev = jnp.asarray(idx)

        def write(leaf, rows):
            key = (P, np.dtype(leaf.dtype).name)
            prog = self._tier_write_cache.get(key)
            if prog is None:
                prog = jax.jit(
                    lambda lf, r, ix: lf.at[ix].set(
                        r.astype(lf.dtype), mode="drop"),
                    donate_argnums=(0,))
                self._tier_write_cache[key] = prog
            padded = np.zeros((P,) + rows.shape[1:], rows.dtype)
            padded[:k] = rows
            return prog(leaf, jnp.asarray(padded), idx_dev)

        pages = self.pages
        quant = pages.quantized
        new_k, new_v = list(pages.k), list(pages.v)
        new_ks, new_vs = list(pages.k_scale), list(pages.v_scale)
        for li, leaf_rows in enumerate(layers):
            new_k[li] = write(pages.k[li], leaf_rows[0])
            new_v[li] = write(pages.v[li], leaf_rows[1])
            if quant:
                new_ks[li] = write(pages.k_scale[li], leaf_rows[2])
                new_vs[li] = write(pages.v_scale[li], leaf_rows[3])
        self.pages = llama.KVPages(k=new_k, v=new_v,
                                   k_scale=new_ks if quant else (),
                                   v_scale=new_vs if quant else ())

    def _try_restore(self, prompt_ids: list[int], shared: list[int],
                     shared_toks: int, *,
                     tenant: str = DEFAULT_TENANT) -> tuple[list[int], int]:
        """Host-tier lookup behind a device prefix-cache miss (or a
        shorter-than-spilled hit): rehydrate the longest spilled prefix of
        ``prompt_ids`` into freshly allocated blocks, re-register it, and
        return the caller-owned span exactly as ``PrefixCache.lookup``
        would have.  Any failure returns the inputs unchanged — a lost
        spill is just a miss (replay/re-prefill fallback)."""
        tier = self.host_kv_tier
        pc = self.prefix_cache
        if tier is None or pc is None or len(tier) == 0:
            return shared, shared_toks
        bs = self.ecfg.block_size
        n = shareable_blocks(len(prompt_ids), bs)
        have = shared_toks // bs
        if n <= have:
            return shared, shared_toks
        digests = pc.digest_chain(prompt_ids, n, tenant=tenant)
        for k in range(n, have, -1):
            dg = digests[k - 1]
            entry = tier.peek(dg)
            if entry is None or entry.n_blocks != k:
                continue
            if not self._ensure_free(k * bs):
                return shared, shared_toks
            try:
                blocks = self.allocator.alloc(k * bs)
            except OutOfBlocks:
                return shared, shared_toks
            entry = tier.take(dg)
            if entry is None:  # raced away between peek and take
                self.allocator.free(blocks)
                return shared, shared_toks
            try:
                self._write_rows(blocks, entry.layers)
            except Exception as exc:  # noqa: BLE001 — failed restore degrades to a miss
                logger.warning("KV restore failed (%s); falling back to "
                               "re-prefill", exc)
                self.allocator.free(blocks)
                return shared, shared_toks
            # Re-publish for every prefix length.  shareable_blocks
            # guarantees len(prompt_ids) > k*bs, so the +1 slice below is
            # always in range; the extra token only satisfies the
            # shareable-span rule (digests cover whole blocks).
            pc.register(prompt_ids[:k * bs + 1], blocks, tenant=tenant)
            if shared:
                self.allocator.free(shared)
            return blocks, k * bs
        return shared, shared_toks

    # -- cross-replica prefix migration (kv_tier rung 3) -----------------

    def _kv_geometry(self) -> dict:
        """The geometry contract a migration blob must match exactly — a
        mismatched receiver must refuse the install, never write pages."""
        cfg, ec = self.cfg, self.ecfg
        return {
            "model": cfg.name,
            "layers": cfg.num_layers,
            "kv_heads": cfg.num_kv_heads,
            "head_dim": cfg.head_dim_,
            "block_size": ec.block_size,
            "kv_quant": self.kv_quant,
            "page_dtype": np.dtype(self.pages.k[0].dtype).name,
        }

    def export_prefix(self, prompt_ids: list[int], *,
                      tenant: str = DEFAULT_TENANT) -> Optional[bytes]:
        """Frame the longest cached prefix of ``prompt_ids`` (within
        ``tenant``'s namespace) for a replica-to-replica transfer (the
        fleet page-fetch endpoint).  Returns None on a miss.  The blob's
        META carries the tenant, so the receiver can refuse a namespace
        mismatch before touching pages.  The lookup's increfs pin the
        blocks for the duration of the device fetch, then release —
        export never changes cache contents."""
        pc = self.prefix_cache
        if pc is None:
            return None
        shared, shared_toks = pc.lookup(prompt_ids, tenant=tenant)
        if not shared:
            return None
        try:
            entry = self._fetch_rows(shared)
            meta = dict(
                self._kv_geometry(),
                n_blocks=len(shared),
                tokens=[int(t) for t in prompt_ids[:shared_toks]],
                tenant=tenant)
            return pack_prefix_blob(
                meta, [a for leaf in entry.layers for a in leaf])
        finally:
            self.allocator.free(shared)

    def install_prefix(self, blob: bytes, *,
                       expected_tenant: str | None = None) -> str:
        """Install a migrated prefix blob into the local pool and prefix
        cache (under the blob's own tenant namespace).  Returns an outcome
        string: ``"installed"`` (pages written and registered),
        ``"cached"`` (already resident — no work), ``"incompatible"``
        (geometry contract mismatch), ``"tenant_mismatch"`` (the caller
        expected a different namespace than the blob header claims — the
        pages are refused unseen), or ``"nospace"`` (pool pressure won).
        Framing/CRC damage raises :class:`~..serving.kv_tier.BlobError` —
        the caller treats a torn transfer as a miss, never a partial
        install."""
        meta, raw = unpack_prefix_blob(blob)
        geo = self._kv_geometry()
        if any(meta.get(key) != geo[key] for key in geo):
            return "incompatible"
        # Blobs packed before tenancy landed carry no tenant header and
        # install into the default namespace (back-compat).
        try:
            blob_tenant = normalize_tenant(
                meta.get("tenant"), default=DEFAULT_TENANT)
        except ValueError:
            return "incompatible"
        if expected_tenant is not None and blob_tenant != expected_tenant:
            return "tenant_mismatch"
        pc = self.prefix_cache
        cfg, ec = self.cfg, self.ecfg
        bs = ec.block_size
        tokens = [int(t) for t in meta.get("tokens", ())]
        k = int(meta.get("n_blocks", 0))
        leaves = 4 if self.kv_quant else 2
        if (pc is None or k <= 0 or len(tokens) != k * bs
                or len(raw) != cfg.num_layers * leaves):
            return "incompatible"
        # The +1 probe/register token never enters a digest (whole blocks
        # only); it just satisfies the shareable-span rule.
        probe = tokens + [0]
        shared, st = pc.lookup(probe, tenant=blob_tenant)
        if shared:
            self.allocator.free(shared)
            if st >= k * bs:
                return "cached"
        F = cfg.num_kv_heads * cfg.head_dim_
        pdtype = np.dtype(self.pages.k[0].dtype)
        layers: list[tuple] = []
        it = iter(raw)
        try:
            for _ in range(cfg.num_layers):
                leaf = (np.frombuffer(next(it), pdtype).reshape(k, bs, F),
                        np.frombuffer(next(it), pdtype).reshape(k, bs, F))
                if self.kv_quant:
                    leaf += (np.frombuffer(next(it), np.float32)
                             .reshape(k, bs, cfg.num_kv_heads),
                             np.frombuffer(next(it), np.float32)
                             .reshape(k, bs, cfg.num_kv_heads))
                layers.append(leaf)
        except ValueError as e:
            raise BlobError(f"ARRAY record does not match geometry: {e}") from e
        if not self._ensure_free(k * bs):
            return "nospace"
        try:
            blocks = self.allocator.alloc(k * bs)
        except OutOfBlocks:
            return "nospace"
        try:
            self._write_rows(blocks, layers)
        except Exception:
            self.allocator.free(blocks)
            raise
        pc.register(probe, blocks, tenant=blob_tenant)
        # The cache entries hold their own references now; dropping the
        # alloc-time ref leaves the pages owned by the cache alone (LRU
        # evictable, host-spillable) exactly like a locally prefilled span.
        self.allocator.free(blocks)
        return "installed"

    def kv_tier_stats(self) -> dict:
        """Tier byte accounting + spill/restore counters for the exporter
        (``kv_tier_bytes{tier}`` etc.) and the fleet registry.  Device
        bytes are the GLOBAL pool (tp=1 view — per-chip slices divide by
        the mesh's model degree, see ``page_slice_bytes``)."""
        cfg, ec = self.cfg, self.ecfg
        pdtype = np.dtype(self.pages.k[0].dtype)
        page_b = page_slice_bytes(
            cfg.num_kv_heads, cfg.head_dim_, ec.block_size, pdtype.itemsize,
            scale_bytes=4 if self.kv_quant else 0)
        out = {
            "kv_quant": self.kv_quant,
            "page_dtype": pdtype.name,
            "device_bytes": cfg.num_layers * ec.num_blocks * page_b,
            "host_bytes": 0,
            "host_entries": 0,
            "spills": 0,
            "restores": 0,
            "host_lost": 0,
        }
        if self.host_kv_tier is not None:
            s = self.host_kv_tier.stats()
            out.update(host_bytes=s["bytes"], host_entries=s["entries"],
                       spills=s["spills"], restores=s["restores"],
                       host_lost=s["lost"],
                       host_tenant_bytes=s["tenant_bytes"])
        # Per-tenant resident-block fairness accounting (exporter
        # ``tenant_kv_blocks`` + the bench's monopoly probe).
        if self.prefix_cache is not None:
            out["tenant_blocks"] = self.prefix_cache.blocks_by_tenant()
        return out

    def _pending_prefix_gain(
        self, cand: list[int], publishers: list[list[int]],
    ) -> int:
        """Tokens of ``cand``'s prefix that become cache-sharable once the
        ``publishers`` prompts register their pages (block-aligned, capped
        at both prompts' shareable spans — kv_cache.shareable_blocks)."""
        bs = self.ecfg.block_size
        cand_blocks = shareable_blocks(len(cand), bs)
        if cand_blocks <= 0:
            return 0
        best = 0
        for other in publishers:
            if cand[:bs] != other[:bs]:
                continue
            # Whole-block slice compares (C-speed) — only full blocks are
            # ever sharable, so per-token resolution buys nothing.
            nb = min(shareable_blocks(len(other), bs), cand_blocks)
            if nb <= 0:
                continue
            k = 1
            while k < nb and cand[k * bs:(k + 1) * bs] == other[k * bs:(k + 1) * bs]:
                k += 1
            best = max(best, k * bs)
        return best

    def _admit_round(self) -> bool:
        """Dispatch one batched prefill+sample call for up to
        ``max_prefills_per_step`` pending prompts.  Returns True if anything
        was dispatched.

        Each candidate first consults the prefix cache; a hit turns its
        prefill into a suffix-only chunked ingestion over the shared pages.
        Rounds where every lane is a miss keep the dense prefill path (no
        page gather); any hit switches the round to the chunked program.

        Cold-burst dedup, two rules sharing one economic gate (the
        published span must cover at least half the candidate's remaining
        prefill work):

        * a candidate sharing a prefix with a *dense lane admitted this
          round* (pages publish at dispatch) is held back exactly one
          round — 100 simultaneous same-evidence diagnosis queries
          prefill their shared prefix once, not max_prefills_per_step
          times;
        * a *chunk-path* candidate (suffix wider than the largest bucket)
          sharing a prefix with a slot still streaming its chunks waits
          until that publisher's final chunk registers the pages — chunk
          rounds advance every step regardless of admissions, so the wait
          is bounded and the candidate then admits suffix-only.  Short
          candidates never wait on a streaming publisher (their own
          prefill costs at most one bucket).
        """
        ec = self.ecfg
        top = ec.prefill_buckets[-1]
        free = self._free_slots()
        admitted_long = 0
        deferred: list[GenerationRequest] = []
        round_prompts: list[list[int]] = []
        # Prompts whose pages will register when their streaming prefill
        # completes: live chunk-path slots + this round's long admissions.
        publishing: list[list[int]] = (
            [s.req.prompt_ids for s in self._slots
             if s is not None and s.prefilling and not s.retired
             and not s.cancel_requested]
            if self.prefix_cache is not None else [])
        # Deferral work per round is bounded: past this many held-back
        # candidates the scan stops (the rest stay pending and hit the
        # cache next round) — a 10k-deep cold queue must not stall the
        # scheduler thread inside one admission round.
        defer_budget = 4 * ec.max_prefills_per_step
        # Entries: (slot_idx, req, blocks, shared_toks)
        batch: list[tuple[int, GenerationRequest, list[int], int]] = []
        while len(batch) < ec.max_prefills_per_step and self._pending and free:
            if len(deferred) >= defer_budget:
                # Stop the scan, not just the deferring: candidates past
                # the budget stay pending (and will hit the cache next
                # round) instead of being admitted into a redundant
                # prefix recompute.
                break
            req = self._pending[0]
            if time.monotonic() > self._deadline_of(req, queued=True):
                self._pending.popleft()
                self.deadline_expired += 1
                self._fail_request(
                    req, f"deadline exceeded after "
                         f"{time.monotonic() - req.submit_time:.2f}s in queue")
                continue
            L = len(req.prompt_ids)
            if L + 1 > self.capacity_tokens:
                # Defensive: submit() caps requests, so this only catches
                # internal misuse; fail loudly instead of livelocking.
                self._pending.popleft()
                self._fail_request(
                    req, f"prompt of {L} tokens exceeds capacity "
                         f"{self.capacity_tokens}")
                continue
            shared: list[int] = []
            shared_toks = 0
            if self.prefix_cache is not None:
                shared, shared_toks = self.prefix_cache.lookup(
                    req.prompt_ids, tenant=req.tenant)
                if self.host_kv_tier is not None:
                    # A spilled entry longer than the device hit rehydrates
                    # here, overlapped with the rest of admission prep —
                    # the scatter is async; the prefill that consumes the
                    # pages queues behind it on the dispatch chain.
                    t_res = time.monotonic()
                    pre_toks = shared_toks
                    shared, shared_toks = self._try_restore(
                        req.prompt_ids, shared, shared_toks,
                        tenant=req.tenant)
                    if shared_toks > pre_toks:
                        self._span("engine.kv_restore", t_res,
                                   time.monotonic(), req,
                                   tokens=shared_toks - pre_toks)
                        self._flight.note(
                            "kv_restore", request_id=req.request_id,
                            tokens=shared_toks - pre_toks)
                suffix = L - shared_toks

                def worth(gain: int) -> bool:
                    # The one economic gate both rules share: the published
                    # span must beat the current hit AND cover at least
                    # half the prefill work still ahead of this candidate.
                    return (gain > shared_toks
                            and 2 * (gain - shared_toks) >= suffix)

                defer = False
                if not req.prefix_deferred and round_prompts:
                    defer = worth(self._pending_prefix_gain(
                        req.prompt_ids, round_prompts))
                if not defer and suffix > top and publishing:
                    # Chunk-path candidate: wait for a streaming publisher
                    # (re-evaluated each round; no flag — the wait ends
                    # when the publisher's final chunk registers, or
                    # immediately if it is preempted or cancelled).
                    defer = worth(self._pending_prefix_gain(
                        req.prompt_ids, publishing))
                if defer:
                    if shared:
                        self.allocator.free(shared)
                    if not req.prefix_deferred:
                        # Counts requests ever deferred, not rounds held —
                        # a chunk-path candidate may wait several rounds
                        # on one streaming publisher.
                        req.prefix_deferred = True
                        self.prefix_deferrals += 1
                    self._pending.popleft()
                    deferred.append(req)
                    continue
            if not self._ensure_free(L + 1 - shared_toks):
                if shared:
                    self.allocator.free(shared)
                break
            self._pending.popleft()
            if self.prefix_cache is not None:
                # Stats count *admissions* (a deferred request's retried
                # lookups must not double-count).
                if shared_toks > 0:
                    self.prefix_cache.hits += 1
                else:
                    self.prefix_cache.misses += 1
            if req.orig_prompt_len < 0:
                req.orig_prompt_len = L
            try:
                blocks = shared + self.allocator.alloc(L + 1 - shared_toks)
            except OutOfBlocks:
                # can_alloc said yes but alloc still failed (injected
                # exhaustion, or a racing sharer): push back, end the scan.
                if shared:
                    self.allocator.free(shared)
                self._pending.appendleft(req)
                break
            self._note_admission_wait(req)
            self._clamp_for_brownout(req)
            if L - shared_toks > top:
                # Long suffix: occupy a slot in *prefilling* state — its
                # chunks stream one batched round per engine step
                # (_dispatch_prefill_chunks), so decode and short-prompt
                # admissions interleave instead of stalling behind a
                # serial chunk loop.
                slot = _Slot(req, blocks)
                slot.ctx_len = L
                slot.prefill_pos = shared_toks
                slot.prefilling = True
                slot_idx = free.pop(0)
                self._slots[slot_idx] = slot
                self._write_hist([(slot_idx, req)])
                admitted_long += 1
                if self.prefix_cache is not None:
                    publishing.append(req.prompt_ids)
                continue
            batch.append((free.pop(0), req, blocks, shared_toks))
            round_prompts.append(req.prompt_ids)
        if deferred:
            # Back to the queue head in original order: next round's
            # lookups hit the pages this round's dispatch publishes.
            self._pending.extendleft(reversed(deferred))
        if not batch:
            return admitted_long > 0

        P = self._lane_count(len(batch))
        any_shared = any(st > 0 for _, _, _, st in batch)
        bucket = self._bucket(
            max(len(r.prompt_ids) - st for _, r, _, st in batch))
        # The chunked program (taken when any lane shares a cached prefix)
        # gathers table_width * block_size keys per lane; narrow it to the
        # deepest prompt.  The dense program never gathers — full width
        # there avoids extra compile shapes.
        W = (self._table_width(max(len(r.prompt_ids) for _, r, _, _ in batch))
             if any_shared else 0)
        (tokens, start, lengths, tables, idx,
         temp, topk, topp) = self._lane_buffers(P, bucket, W)
        fstate = np.zeros((P,), np.int32)
        for j, (slot_idx, req, blocks, st) in enumerate(batch):
            L = len(req.prompt_ids)
            if req.orig_prompt_len < 0:
                req.orig_prompt_len = L
            tokens[j, : L - st] = req.prompt_ids[st:]
            start[j] = st
            lengths[j] = L - st
            # blocks may cover L+1 tokens (the first decode write); the
            # prefill only reads/writes positions < L, so truncating to the
            # narrowed width is safe — decode uses its own full table.
            nb = min(len(blocks), tables.shape[1])
            tables[j, :nb] = blocks[:nb]
            idx[j] = slot_idx
            sp = req.sampling
            temp[j], topk[j], topp[j] = sp.temperature, sp.top_k, sp.top_p
            if sp.constrained:
                fstate[j] = self._fsm_entry(req)

        all_greedy = all(r.sampling.temperature <= 0.0 for _, r, _, _ in batch)
        # Any constrained lane forces the FSM program family (sampled-shape,
        # masked logits); free lanes ride along at state 0, and greedy lanes
        # stay exact via argmax-of-masked inside the shared sampler.
        constrained = any(r.sampling.constrained for _, r, _, _ in batch)
        fnext = None
        try:
            self._faults.maybe_raise("prefill_dispatch")
            if not any_shared:
                if constrained:
                    self._rng, sub = jax.random.split(self._rng)
                    first, fnext, self.pages = self._prefill_sample_fsm(
                        self.params, self._tokens_to_device(tokens), jnp.asarray(lengths),
                        self.pages, jnp.asarray(tables), jnp.asarray(fstate),
                        self._fsm_trans, jnp.asarray(temp),
                        jnp.asarray(topk), jnp.asarray(topp), sub,
                    )
                elif all_greedy:
                    first, self.pages = self._prefill_greedy(
                        self.params, self._tokens_to_device(tokens), jnp.asarray(lengths),
                        self.pages, jnp.asarray(tables),
                    )
                else:
                    self._rng, sub = jax.random.split(self._rng)
                    first, self.pages = self._prefill_sample(
                        self.params, self._tokens_to_device(tokens), jnp.asarray(lengths),
                        self.pages, jnp.asarray(tables), jnp.asarray(temp),
                        jnp.asarray(topk), jnp.asarray(topp), sub,
                    )
            else:
                if constrained:
                    self._rng, sub = jax.random.split(self._rng)
                    first, fnext, self.pages = self._prefill_chunk_sample_fsm(
                        self.params, self._tokens_to_device(tokens), jnp.asarray(start),
                        jnp.asarray(lengths), self.pages, jnp.asarray(tables),
                        jnp.asarray(fstate), self._fsm_trans,
                        jnp.asarray(temp), jnp.asarray(topk),
                        jnp.asarray(topp), sub,
                    )
                elif all_greedy:
                    first, self.pages = self._prefill_chunk_greedy(
                        self.params, self._tokens_to_device(tokens), jnp.asarray(start),
                        jnp.asarray(lengths), self.pages, jnp.asarray(tables),
                    )
                else:
                    self._rng, sub = jax.random.split(self._rng)
                    first, self.pages = self._prefill_chunk_sample(
                        self.params, self._tokens_to_device(tokens), jnp.asarray(start),
                        jnp.asarray(lengths), self.pages, jnp.asarray(tables),
                        jnp.asarray(temp), jnp.asarray(topk),
                        jnp.asarray(topp), sub,
                    )
        except Exception as exc:
            # Host state is still pre-dispatch (no slot occupied, no pages
            # registered): release this round's pages and requeue the
            # candidates — bounded, so a deterministic dispatch failure
            # eventually surfaces to callers instead of spinning.
            self._record_dispatch_failure(exc)
            requeue: list[GenerationRequest] = []
            for _, req, blocks, _ in batch:
                self.allocator.free(blocks)
                if req.requeues >= self.ecfg.max_requeues:
                    self._fail_request(
                        req, f"prefill dispatch failed: {exc} "
                             f"(gave up after {req.requeues} requeues)")
                else:
                    req.requeues += 1
                    self.requeues += 1
                    requeue.append(req)
            self._pending.extendleft(reversed(requeue))
            return admitted_long > 0
        self._record_dispatch_ok()
        self.prefill_bucket_rounds[bucket] = (
            self.prefill_bucket_rounds.get(bucket, 0) + 1)
        if self.prefix_cache is not None:
            for slot_idx, req, blocks, st in batch:
                self.prefix_cache.register(req.prompt_ids, blocks,
                                           tenant=req.tenant)
        self._finish_admit_dispatch(
            first, [(s, r, b) for s, r, b, _ in batch], idx, fsm_next=fnext,
            span_attrs={"bucket": bucket, "lanes": len(batch),
                        "shared": any_shared})
        return True

    def _dispatch_prefill_chunks(self) -> bool:
        """One batched chunk round for slots in prefilling state.

        Lanes are ordered depth-first (fewest remaining tokens first, then
        submit order): finishing a few lanes completely beats advancing all
        of them one chunk — p50 TTFT is completion-order-sensitive while
        total work is fixed.  Each lane ingests its next ``<= top`` tokens
        via the per-lane-start chunked program; lanes whose chunk is final
        sample their first token in the same call (admit semantics at
        reconcile), non-final lanes drop theirs.  One round per engine
        step, so decode dispatches interleave between rounds.
        """
        ec = self.ecfg
        top = ec.prefill_buckets[-1]
        cands = [(i, s) for i, s in enumerate(self._slots)
                 if s is not None and s.prefilling and not s.retired
                 and not s.cancel_requested]
        if not cands:
            return False
        cands.sort(key=lambda t: (len(t[1].req.prompt_ids)
                                  - t[1].prefill_pos,
                                  t[1].req.submit_time))
        cands = cands[:ec.max_prefills_per_step]

        P = self._lane_count(len(cands))
        bucket = self._bucket(min(top, max(
            len(s.req.prompt_ids) - s.prefill_pos for _, s in cands)))
        # Deadline-aware round sizing: queued interactive work shrinks the
        # round so its admission dispatch isn't head-of-line blocked behind
        # a full-bucket chunk.  Total chunk work is unchanged — the long
        # prompt just takes more, shorter rounds while the queue holds
        # interactive requests.
        icb = self.ecfg.interactive_chunk_bucket
        if icb > 0 and any(r.slo_class == "interactive"
                           for r in self._pending):
            small = self._bucket(min(icb, top))
            if small < bucket:
                bucket = small
                self.chunk_shrinks += 1
        self.last_chunk_bucket = bucket
        # Narrow the gathered table to the deepest lane's post-round
        # context: early rounds of a long prompt attend to a fraction of
        # capacity, and the gather cost scales with table width.
        W = self._table_width(max(
            s.prefill_pos + min(bucket, len(s.req.prompt_ids)
                                - s.prefill_pos) for _, s in cands))
        (tokens, start, lengths, tables, idx,
         temp, topk, topp) = self._lane_buffers(P, bucket, W)
        fstate = np.zeros((P,), np.int32)
        lanes: list[tuple] = []
        touched: list[_Slot] = []
        final_greedy = True
        final_constrained = False
        # (slot, chunk_len, became_final) — enough to roll every slot
        # mutation back if the dispatch itself fails.
        muts: list[tuple[_Slot, int, bool]] = []
        to_register: list[_Slot] = []
        for j, (i, s) in enumerate(cands):
            L = len(s.req.prompt_ids)
            n = min(bucket, L - s.prefill_pos)
            tokens[j, :n] = s.req.prompt_ids[s.prefill_pos:s.prefill_pos + n]
            start[j] = s.prefill_pos
            lengths[j] = n
            nb = min(len(s.blocks), tables.shape[1])
            tables[j, :nb] = s.blocks[:nb]
            s.prefill_pos += n
            s.inflight_chunks += 1
            touched.append(s)
            became_final = False
            if s.prefill_pos >= L:
                # Final chunk: its last-token logits produce the first
                # generated token; pages for the whole prompt are now in
                # the dispatch chain, so the prefix becomes publishable
                # (registered below, only after the dispatch succeeds).
                s.prefilling = False
                became_final = True
                sp = s.req.sampling
                temp[j], topk[j], topp[j] = sp.temperature, sp.top_k, sp.top_p
                final_greedy = final_greedy and sp.temperature <= 0.0
                if sp.constrained:
                    final_constrained = True
                    fstate[j] = self._fsm_entry(s.req)
                idx[j] = i
                lanes.append((j, i, s.req))
                if self.prefix_cache is not None:
                    to_register.append(s)
            muts.append((s, n, became_final))

        fnext = None
        try:
            self._faults.maybe_raise("prefill_dispatch")
            if final_constrained:
                # Only final lanes sample, so only they consult the FSM;
                # non-final lanes stay at state 0 and drop their token (and
                # state) via the out-of-range idx scatter.
                self._rng, sub = jax.random.split(self._rng)
                first, fnext, self.pages = self._prefill_chunk_sample_fsm(
                    self.params, self._tokens_to_device(tokens), jnp.asarray(start),
                    jnp.asarray(lengths), self.pages, jnp.asarray(tables),
                    jnp.asarray(fstate), self._fsm_trans, jnp.asarray(temp),
                    jnp.asarray(topk), jnp.asarray(topp), sub,
                )
            elif final_greedy:
                first, self.pages = self._prefill_chunk_greedy(
                    self.params, self._tokens_to_device(tokens), jnp.asarray(start),
                    jnp.asarray(lengths), self.pages, jnp.asarray(tables),
                )
            else:
                self._rng, sub = jax.random.split(self._rng)
                first, self.pages = self._prefill_chunk_sample(
                    self.params, self._tokens_to_device(tokens), jnp.asarray(start),
                    jnp.asarray(lengths), self.pages, jnp.asarray(tables),
                    jnp.asarray(temp), jnp.asarray(topk), jnp.asarray(topp),
                    sub,
                )
        except Exception as exc:
            # Nothing reached the device: rewind this round's slot state
            # so the next step re-dispatches the same chunks.
            for s, n, became_final in muts:
                s.prefill_pos -= n
                s.inflight_chunks -= 1
                if became_final:
                    s.prefilling = True
            self._record_dispatch_failure(exc)
            return False
        self._record_dispatch_ok()
        self.prefill_bucket_rounds[bucket] = (
            self.prefill_bucket_rounds.get(bucket, 0) + 1)
        for s in to_register:
            self.prefix_cache.register(s.req.prompt_ids, s.blocks,
                                       tenant=s.req.tenant)
        self.prefills += len(lanes)
        self._queue_inflight("chunk", first, idx, lanes, touched,
                             fsm_next=fnext,
                             span_attrs={"bucket": bucket,
                                         "lanes": len(cands)})
        return True

    def _queue_inflight(self, kind: str, first, idx, lanes,
                        touched=(), fsm_next=None, span_attrs=None) -> None:
        """Shared dispatch tail: place sampled tokens into the device token
        buffer, start the async host copy, and queue the reconcile entry."""
        self._tok_state = self._place_tokens(
            self._tok_state, first, jnp.asarray(idx))
        if self._fsm_trans is not None:
            # With a grammar installed, every admission (re)writes its
            # lanes' FSM states: the post-first-token state for constrained
            # lanes, zero for free lanes — which also clears stale state
            # left by a previous constrained occupant of a reused slot.
            # Same ordering argument as _tok_state: the scatter is enqueued
            # after the producing call and before any consuming decode.
            self._fsm_state = self._place_fsm(
                self._fsm_state,
                fsm_next if fsm_next is not None else jnp.zeros_like(first),
                jnp.asarray(idx))
        try:
            first.copy_to_host_async()
        except AttributeError:  # non-jax array (tests with stub impls)
            pass
        self._inflight.append(_Inflight(
            kind=kind, call_id=self._next_call_id, arr=first,
            lanes=list(lanes), touched=list(touched),
            t0=time.monotonic(), span_attrs=span_attrs or {}))
        self._next_call_id += 1

    def _finish_admit_dispatch(self, first, batch, idx,
                               fsm_next=None, span_attrs=None) -> None:
        """Admission tail: occupy slots, then queue via the shared path."""
        lanes = []
        for slot_idx, req, blocks in batch:
            slot = _Slot(req, blocks)
            slot.ctx_len = len(req.prompt_ids)
            self._slots[slot_idx] = slot
            lanes.append((slot_idx, req))
        self.prefills += len(batch)
        self._write_hist(lanes)
        self._queue_inflight("admit", first, idx, lanes, fsm_next=fsm_next,
                             span_attrs=span_attrs)

    # -- decode ---------------------------------------------------------

    def _decode_program(self, n_steps: int, sampled: bool,
                        bounded: bool = False, constrained: bool = False):
        """Build (and cache) the fused K-step decode program.

        The scan carries (token, ctx, done, pages[, rng]) on device: each
        iteration feeds the previous step's sampled token back in without a
        host round-trip, EOS and per-lane budget exhaustion flip lanes to the
        masked state (writes -> null block), and the emitted [K, B] token
        matrix uses -1 for steps where a lane was not active.  Returns
        (toks [K, B], final token state [B], pages).

        ``bounded`` (static, sampled programs only): sample from the top
        ``sample_topk_cap`` logits per step instead of rank-sorting the
        full vocab — distribution-exact when every sampling lane has
        0 < top_k <= cap, which _dispatch_decode verifies per call.

        ``constrained`` (static, sampled programs only): the scan also
        carries the per-lane grammar FSM state — each step masks logits by
        the lane's allowed-token row before the shared sampler and advances
        the state by the sampled token.  Lanes at state 0 (FREE) are
        untouched, so one constrained program serves mixed batches; the
        transition table is a runtime argument (no recompile per grammar).
        """
        key = (n_steps, sampled, bounded, constrained)
        prog = self._decode_cache.get(key)
        if prog is not None:
            return prog

        cfg = self.cfg
        attn_impl = self._attn_impl
        k_cap = self.ecfg.sample_topk_cap
        overlap_step = self._overlap_step

        def _step_core(params, tokens, ctx, act, pages, tables):
            ctx_eff = jnp.where(act, ctx, 0)
            if overlap_step is not None:
                # Hand-staged TP schedule (parallel/overlap.py): same
                # calling convention minus attn_impl, which the builder
                # resolved from self.decode_path at engine construction.
                logits, pages = overlap_step(
                    params, tokens, ctx_eff, pages, tables)
            else:
                logits, pages = llama.decode_step(
                    params, cfg, tokens, ctx_eff, pages, tables,
                    attn_impl=attn_impl,
                )
            return logits, pages

        if sampled and constrained:
            def fn(params, tok_state, fsm_state, ctx, remaining, pages,
                   tables, ftrans, temp, topk, topp, rng, eos):
                active0 = ctx > 0

                def body(carry, i):
                    tokens, fstate, ctx, done, rng, pages = carry
                    act = active0 & ~done & (i < remaining)
                    logits, pages = _step_core(
                        params, tokens, ctx, act, pages, tables)
                    logits = fsm_mask_logits(logits, fstate, ftrans)
                    rng, sub = jax.random.split(rng)
                    if bounded:
                        nxt = sample_tokens_bounded(
                            sub, logits, temperature=temp, top_k=topk,
                            top_p=topp, k_cap=k_cap)
                    else:
                        nxt = sample_tokens(sub, logits, temperature=temp,
                                            top_k=topk, top_p=topp)
                    nxt = jnp.where(act, nxt, tokens)
                    fstate = jnp.where(
                        act, fsm_advance(fstate, ftrans, nxt), fstate)
                    done = done | (act & (nxt == eos))
                    ctx = jnp.where(act, ctx + 1, ctx)
                    out = jnp.where(act, nxt, -1)
                    return (nxt, fstate, ctx, done, rng, pages), out

                done0 = jnp.zeros_like(active0)
                (tok_state, fsm_state, _, _, _, pages), toks = jax.lax.scan(
                    body, (tok_state, fsm_state, ctx, done0, rng, pages),
                    jnp.arange(n_steps, dtype=jnp.int32))
                return toks, tok_state, fsm_state, pages

            prog = jax.jit(fn, donate_argnums=(1, 2, 5))
        elif sampled:
            def fn(params, tok_state, ctx, remaining, pages, tables,
                   temp, topk, topp, rng, eos):
                active0 = ctx > 0

                def body(carry, i):
                    tokens, ctx, done, rng, pages = carry
                    act = active0 & ~done & (i < remaining)
                    logits, pages = _step_core(
                        params, tokens, ctx, act, pages, tables)
                    rng, sub = jax.random.split(rng)
                    if bounded:
                        nxt = sample_tokens_bounded(
                            sub, logits, temperature=temp, top_k=topk,
                            top_p=topp, k_cap=k_cap)
                    else:
                        nxt = sample_tokens(sub, logits, temperature=temp,
                                            top_k=topk, top_p=topp)
                    nxt = jnp.where(act, nxt, tokens)
                    done = done | (act & (nxt == eos))
                    ctx = jnp.where(act, ctx + 1, ctx)
                    out = jnp.where(act, nxt, -1)
                    return (nxt, ctx, done, rng, pages), out

                done0 = jnp.zeros_like(active0)
                (tok_state, _, _, _, pages), toks = jax.lax.scan(
                    body, (tok_state, ctx, done0, rng, pages),
                    jnp.arange(n_steps, dtype=jnp.int32))
                return toks, tok_state, pages

            prog = jax.jit(fn, donate_argnums=(1, 4))
        else:
            def fn(params, tok_state, ctx, remaining, pages, tables, eos):
                active0 = ctx > 0

                def body(carry, i):
                    tokens, ctx, done, pages = carry
                    act = active0 & ~done & (i < remaining)
                    logits, pages = _step_core(
                        params, tokens, ctx, act, pages, tables)
                    nxt = greedy_tokens(logits)
                    nxt = jnp.where(act, nxt, tokens)
                    done = done | (act & (nxt == eos))
                    ctx = jnp.where(act, ctx + 1, ctx)
                    out = jnp.where(act, nxt, -1)
                    return (nxt, ctx, done, pages), out

                done0 = jnp.zeros_like(active0)
                (tok_state, _, _, pages), toks = jax.lax.scan(
                    body, (tok_state, ctx, done0, pages),
                    jnp.arange(n_steps, dtype=jnp.int32))
                return toks, tok_state, pages

            prog = jax.jit(fn, donate_argnums=(1, 4))
        self._decode_cache[key] = prog
        return prog

    def profile_decode_phases(self, reps: int = 3) -> dict[str, float]:
        """Attribute the fused decode step: attention vs sampling cost.

        Runs the warm compiled decode programs on synthetic full-batch
        state (all ``max_slots`` lanes live) and differences timings:

          * long-context minus short-context greedy -> ``decode_attn_ms``
            (only paged attention scales with context length; the dense
            matmuls and dispatch overhead are ctx-independent), and
          * sampled minus greedy at short context -> ``decode_sample_ms``.

        The programs append garbage rows into ``self.pages`` as a side
        effect, so this must only run while the engine is IDLE — bench
        calls it before serving traffic; it is never triggered by a
        /metrics scrape.  Populates ``self.decode_attn_ms`` /
        ``self.decode_sample_ms`` (exported as gauges) and returns all
        four figures.
        """
        if self._inflight or any(s is not None for s in self._slots):
            raise RuntimeError(
                "profile_decode_phases() requires an idle engine "
                "(it clobbers KV pages)")
        ec = self.ecfg
        K = ec.decode_steps_per_iter
        B = ec.max_slots
        width = ec.max_blocks_per_seq
        # One shared table row (blocks 1..width): lanes alias the same
        # pages, which is fine for timing — traffic per lane is identical
        # to distinct pages and HBM reads don't conflict.
        nblk = min(width, ec.num_blocks - 1)
        row = np.zeros((1, width), np.int32)
        row[0, :nblk] = np.arange(1, 1 + nblk, dtype=np.int32)
        dtbl = jnp.asarray(np.tile(row, (B, 1)))
        ctx_hi = max(nblk * ec.block_size - K - 1, 1)
        ctx_lo = 1

        cap = ec.sample_topk_cap
        remaining = jnp.full((B,), 10 ** 6, jnp.int32)
        eos = jnp.asarray(-1, jnp.int32)

        def run(prog, ctx_val: int, sampled: bool) -> float:
            ctx = jnp.full((B,), ctx_val, jnp.int32)
            tok = jnp.zeros((B,), jnp.int32)
            if sampled:
                extras = (jnp.full((B,), 0.7, jnp.float32),
                          jnp.full((B,), max(min(cap, 8), 1), jnp.int32),
                          jnp.full((B,), 0.9, jnp.float32),
                          jax.random.PRNGKey(0), eos)
            else:
                extras = (eos,)
            # Warm (compile) call, then timed reps.  tok_state and pages
            # are donated — thread both through every call.
            _, tok, self.pages = prog(self.params, tok, ctx, remaining,
                                      self.pages, dtbl, *extras)
            tok.block_until_ready()
            t0 = time.monotonic()
            for _ in range(reps):
                _, tok, self.pages = prog(self.params, tok, ctx, remaining,
                                          self.pages, dtbl, *extras)
            tok.block_until_ready()
            return (time.monotonic() - t0) / (reps * K) * 1e3

        greedy_prog = self._decode_program(K, sampled=False)
        sampled_prog = self._decode_program(K, sampled=True,
                                            bounded=cap > 0)
        t_lo = run(greedy_prog, ctx_lo, sampled=False)
        t_hi = run(greedy_prog, ctx_hi, sampled=False)
        t_samp = run(sampled_prog, ctx_lo, sampled=True)
        self.decode_attn_ms = max(t_hi - t_lo, 0.0)
        self.decode_sample_ms = max(t_samp - t_lo, 0.0)
        self.decode_collective_share = self._estimate_collective_share(t_lo)
        return {
            "decode_step_ms_short_ctx": t_lo,
            "decode_step_ms_long_ctx": t_hi,
            "decode_attn_ms": self.decode_attn_ms,
            "decode_sample_ms": self.decode_sample_ms,
            "decode_collective_share": self.decode_collective_share,
        }

    def mesh_axes(self) -> dict[str, int]:
        """{axis: size} of the serving mesh ({} off-mesh) — the exporter's
        ``mesh_axes`` topology gauge."""
        return dict(self.mesh.shape) if self.mesh is not None else {}

    def _estimate_collective_share(self, step_ms: float) -> float:
        """Per-step ICI time share of the TP decode step (byte model).

        Row-parallel o/down projections each psum a [B, hidden] activation
        per layer; a ring all-reduce moves ``2*(tp-1)/tp`` of the payload
        over each chip's links.  Dividing that wire time (at the chip's
        aggregate ICI bandwidth) by the *measured* step time gives the
        share the dashboard shows next to ``decode_attn_ms``.  It is an
        estimate — collectives overlap compute on real meshes — and on the
        forced-host CPU mesh the step time itself is a dryrun stand-in.
        """
        ici_ms = self._ring_ici_ms()
        if ici_ms <= 0.0 or step_ms <= 0.0:
            return 0.0
        return min(1.0, ici_ms / step_ms)

    def _ring_ici_ms(self) -> float:
        """Per-step wire time of the TP decode collectives (byte model,
        ms): row-parallel o/down each move ``2*(tp-1)/tp`` of a
        [max_slots, hidden] activation over each chip's ICI links per
        layer — the same bytes whether staged as one ring all-reduce
        (GSPMD) or as a reduce-scatter + all-gather pair (overlap path).
        0.0 off-mesh / TP=1."""
        if self.mesh is None:
            return 0.0
        tp = self.mesh.shape.get("model", 1)
        if tp <= 1:
            return 0.0
        from k8s_llm_monitor_tpu.parallel.mesh import ici_bandwidth_gbs

        cfg = self.cfg
        act_bytes = 4 if cfg.dtype == "float32" else 2
        payload = self.ecfg.max_slots * cfg.hidden_size * act_bytes
        per_chip_bytes = (2 * cfg.num_layers          # o-proj + down-proj
                          * 2.0 * (tp - 1) / tp * payload)
        kind = self.mesh.devices.flat[0].device_kind
        return per_chip_bytes / (ici_bandwidth_gbs(kind) * 1e9) * 1e3

    def estimate_hidden_share(self, step_ms_on: float | None = None,
                              step_ms_off: float | None = None) -> float:
        """``decode_collective_hidden_share``: fraction of the per-step
        ring wire time the overlap schedule hides under compute.

        On TPU, with measured overlap-on and overlap-off step times, the
        hidden share is the observed saving against the byte model:
        ``(off - on) / ring_ici_ms``, clamped to [0, 1].

        Off-TPU (the forced-host dev mesh), interpreter step times are
        meaningless, so the dryrun falls back to the analytic window
        model: a reduce-scatter/all-gather half is hidden up to the time
        the next column-parallel matmuls spend streaming their weight
        shard HBM->VMEM (decode is weight-streaming bound).  Per layer
        that window is the per-chip column weight bytes over HBM
        bandwidth; the wire is the per-layer share of ``_ring_ici_ms``.
        Both the measured and analytic figures land in
        ``self.decode_collective_hidden_share`` for /metrics.
        """
        share = 0.0
        ici_ms = self._ring_ici_ms()
        if ici_ms <= 0.0 or not self.tp_overlap:
            self.decode_collective_hidden_share = 0.0
            return 0.0
        on_tpu = jax.default_backend() == "tpu"
        if (on_tpu and step_ms_on is not None and step_ms_off is not None
                and step_ms_off > 0.0):
            share = max(0.0, min(1.0, (step_ms_off - step_ms_on) / ici_ms))
        else:
            from k8s_llm_monitor_tpu.parallel.mesh import hbm_bandwidth_gbs

            cfg = self.cfg
            tp = self.mesh.shape.get("model", 1)
            # int8 weights stream 1 byte/element; float params their dtype.
            layer0 = self.params["layers"][0]
            wbytes = (1 if "kernel_q" in layer0["q"]
                      else (4 if cfg.dtype == "float32" else 2))
            D = cfg.head_dim_
            col_weights = (cfg.hidden_size * cfg.num_heads * D       # q
                           + 2 * cfg.hidden_size * cfg.num_kv_heads * D
                           + 2 * cfg.hidden_size * cfg.intermediate_size)
            stream_ms = (col_weights * wbytes / tp
                         / (hbm_bandwidth_gbs(
                             self.mesh.devices.flat[0].device_kind) * 1e9)
                         * 1e3)
            wire_ms = ici_ms / (2 * cfg.num_layers)   # one RS/AG pair
            share = min(1.0, stream_ms / wire_ms) if wire_ms > 0 else 0.0
        self.decode_collective_hidden_share = share
        return share

    @staticmethod
    def _spec_class(lanes) -> str:
        """Request class for adaptive speculation: greedy and sampled
        traffic accept at very different rates (diagnosis queries quote
        verbatim under greedy; sampled lanes diverge from the draft), so
        their kill-switches are tracked separately.  A mixed batch is
        scored as its most divergent member."""
        return ("greedy"
                if all(s.req.sampling.temperature <= 0.0 for _, s in lanes)
                else "sampled")

    @property
    def _spec_ema(self) -> Optional[float]:
        """Back-compat scalar view of the per-class acceptance EMAs: the
        best class (a single healthy class keeps the scalar above the
        floor, mirroring the pre-class behavior for one-class traffic)."""
        snap = self._spec_accept.snapshot()
        return max(snap.values()) if snap else None

    def spec_accept_ema(self) -> dict:
        """{request class: accepted-tokens-per-lane-round EMA} for the
        exporter's ``spec_accept_ema`` gauge."""
        return self._spec_accept.snapshot()

    def _spec_program(self, k: int, rounds: int, sampled: bool,
                      filtered: bool = False):
        """Build (and cache) the fused speculative-decode program.

        Each scanned round, entirely on device: write the current token into
        the history row, propose ``k`` draft tokens by n-gram lookup
        (serving/spec.py), verify all ``k+1`` positions in one forward
        (llama.verify_step), accept a draft prefix plus the model's
        correction/bonus token, and advance ctx by the accepted count.
        Rejected positions' K/V stays beyond context_lens — masked, then
        overwritten — so there is no rollback.

        ``sampled=False``: argmax acceptance, bit-identical to the
        sequential greedy path.  ``sampled=True``: the delta-draft
        speculative-sampling rule (spec.accept_sampled) against the same
        temperature/top-k/top-p-filtered distribution sequential decode
        samples from, with greedy lanes handled in the same call.

        Returns (toks [rounds*(k+1), B] with -1 padding, tok_state, pages,
        hist, stats [2] = [verify rounds run, lane-rounds run]).
        """
        key = ("spec", k, rounds, sampled, filtered)
        prog = self._decode_cache.get(key)
        if prog is not None:
            return prog

        cfg = self.cfg
        H = self._hist.shape[1]

        def fn(params, tok_state, ctx, quota, pages, tables, hist, temp,
               topk, topp, rng, eos):
            active0 = ctx > 0
            B = tok_state.shape[0]
            lane = jnp.arange(B, dtype=jnp.int32)

            def body(carry, _):
                tok, ctx, quota, done, rng, pages, hist = carry
                act = active0 & ~done & (quota > 0)
                # Current token enters history at its own position (writes
                # at/after H, or by inactive lanes, are dropped).
                wcol = jnp.where(act & (ctx < H), ctx, H)
                hist = hist.at[lane, wcol].set(tok, mode="drop")
                drafts = propose_drafts(hist, ctx, tok, k)
                toks_in = jnp.concatenate([tok[:, None], drafts], axis=1)
                lengths = jnp.where(act, k + 1, 0).astype(jnp.int32)
                logits, pages = llama.verify_step(
                    params, cfg, toks_in, ctx, lengths, pages, tables,
                    attn_impl=self._verify_impl)
                if sampled:
                    rng, sub = jax.random.split(rng)
                    # `filtered` is a static program property: batches with
                    # no top-k/top-p lane skip the full-vocab rank sort
                    # inside accept_sampled (plain softmax, same dist).
                    emit, out = accept_sampled(
                        sub, logits, drafts, quota, act, eos, temp,
                        top_k=topk if filtered else None,
                        top_p=topp if filtered else None)
                else:
                    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    emit, out = accept_greedy(greedy, drafts, quota, act, eos)
                # Accepted tokens extend the history at ctx+1+i.  Padding
                # (-1) columns are redirected to H and dropped.
                cols = (ctx[:, None] + 1
                        + jnp.arange(k + 1, dtype=jnp.int32)[None, :])
                cols = jnp.where((out >= 0) & (cols < H), cols, H)
                hist = hist.at[lane[:, None], cols].set(out, mode="drop")
                last = jnp.take_along_axis(
                    out, jnp.maximum(emit - 1, 0)[:, None], axis=1)[:, 0]
                tok = jnp.where(act & (emit > 0), last, tok)
                # out's -1 padding must not match an unset eos_id of -1.
                done = done | (act & jnp.any((out == eos) & (out >= 0), 1))
                ctx = ctx + jnp.where(act, emit, 0)
                quota = quota - jnp.where(act, emit, 0)
                # Stats row: [rounds that ran a forward, lane-rounds] — the
                # latter divides spec_tokens into true per-lane acceptance.
                stats = jnp.stack([jnp.any(act).astype(jnp.int32),
                                   jnp.sum(act.astype(jnp.int32))])
                return (tok, ctx, quota, done, rng, pages, hist), (out, stats)

            done0 = jnp.zeros_like(active0)
            carry, (outs, stats) = jax.lax.scan(
                body, (tok_state, ctx, quota, done0, rng, pages, hist),
                None, length=rounds)
            tok_state, _, _, _, _, pages, hist = carry
            # [R, B, k+1] -> [R*(k+1), B]: chronological per lane, matching
            # the reconcile contract of the fused decode program.
            toks = jnp.transpose(outs, (0, 2, 1)).reshape(rounds * (k + 1), B)
            return toks, tok_state, pages, hist, jnp.sum(stats, axis=0)

        prog = jax.jit(fn, donate_argnums=(1, 4, 6))
        self._decode_cache[key] = prog
        return prog

    def _decode_lanes(self) -> list[tuple[int, "_Slot"]]:
        """Slots eligible for a decode dispatch right now.  Recomputed after
        any reconcile/preemption point that can retire or admit slots."""
        return [(i, s) for i, s in enumerate(self._slots)
                if s is not None and not s.retired and not s.prefilling
                and s.remaining_pred > 0 and not s.cancel_requested]

    def _dispatch_decode(self) -> bool:
        """Dispatch one fused decode call over lanes with predicted budget.
        Returns True if a call was dispatched."""
        ec = self.ecfg
        B = ec.max_slots

        # Retire cancelled lanes that have fully settled; exclude the rest
        # from new dispatches (their in-flight steps drain via reconcile).
        # A cancelled slot still mid-prefill (prefilling) never reaches the
        # admit reconcile that clears pending_admit, so it settles once its
        # chunk calls drain.
        for i, s in enumerate(self._slots):
            if (s is not None and s.cancel_requested
                    and s.inflight_decode == 0 and s.inflight_chunks == 0
                    and (s.prefilling or not s.pending_admit)):
                self._retire(i)

        lanes = self._decode_lanes()
        if not lanes:
            return False

        if any(c.kind == "spec" for c in self._inflight):
            # A spec call's emission is data-dependent, so ctx_pred for its
            # lanes is an upper bound while it is in flight.  ANY follow-up
            # decode dispatch (spec or not — a sampled admission can flip
            # the batch to the fused path) must wait for reconciled ctx, or
            # it would run lanes at inflated positions whose attention
            # window covers rejected-draft KV.
            self._reconcile_all()
            lanes = self._decode_lanes()
            if not lanes:
                return False

        # Every sampling mode speculates: greedy by argmax match, sampled
        # by the delta-draft rule against the same filtered distribution
        # sequential decode samples from (spec.accept_sampled).  Whether a
        # given dispatch speculates is ADAPTIVE: below the measured
        # acceptance threshold the fused pipelined path wins, so spec runs
        # only as a periodic probe until acceptance recovers.  Grammar-
        # constrained lanes force spec off: the verify pass samples from
        # unmasked positions, so accepted drafts could violate the grammar.
        spec = ec.spec_k > 0 and not any(
            s.req.sampling.constrained for _, s in lanes)
        if spec and self._brownout_level() >= 1:
            # DEGRADED or worse: a verify forward costs more than a fused
            # step and serializes the pipeline — the brownout ladder sheds
            # the speculative gamble before it sheds any request.
            spec = False
        if spec:
            spec = self._spec_accept.should_draft(self._spec_class(lanes))
        if spec:
            # Emission per spec call is data-dependent (1..k+1 per round),
            # so a dispatch-ahead call would run with an overestimated ctx
            # and read unmasked garbage.  Drain the pipeline first: spec
            # trades dispatch-ahead depth for multi-token verify rounds.
            if self._inflight:
                self._reconcile_all()
                lanes = self._decode_lanes()
                if not lanes:
                    return False
            # Per-lane quota: the most a call can emit if every round
            # accepts the full draft.
            K = ec.spec_rounds_per_iter * (ec.spec_k + 1)
        else:
            kmax = min(ec.decode_steps_per_iter,
                       max(s.remaining_pred for _, s in lanes))
            K = 1 << (kmax.bit_length() - 1)

        # Ensure pages for each lane's next min(K, remaining) KV writes.  On
        # pressure, drain in-flight work (so preemption sees reconciled
        # state) and evict the lowest-class, youngest active slot so the
        # oldest protected work always makes progress; the victim may be
        # the failing lane itself, evicting itself.
        for i, s in sorted(lanes, key=lambda t: t[1].req.submit_time):
            if self._slots[i] is not s or s.retired:
                continue  # evicted/retired during the pressure loop below
            steps_i = max(1, min(K, s.remaining_pred))
            while True:
                try:
                    self.allocator.extend(s.blocks, s.ctx_pred + steps_i)
                    break
                except OutOfBlocks:
                    # Cheapest relief first: demote cached prefixes nobody
                    # is actively using to the host tier (or drop them)
                    # before draining/preempting live work.
                    if self._evict_prefix_lru():
                        continue
                    self._reconcile_all()
                    if self._slots[i] is not s or s.retired:
                        break
                    try:
                        self.allocator.extend(s.blocks, s.ctx_pred + steps_i)
                        break
                    except OutOfBlocks:
                        victim = self._eviction_victim()
                        if victim < 0:
                            victim = i  # only cancelled lanes left: self-evict
                        try:
                            self._faults.maybe_raise("lane_eviction")
                        except FaultError as exc:
                            # Mid-eviction failure: fall back to evicting
                            # the requesting lane itself — always safe
                            # (recompute-requeue) and never leaves an
                            # unextended lane in the dispatch.
                            self._record_dispatch_failure(exc)
                            victim = i
                        self._preempt(victim)
                        if victim == i:
                            break

        lanes = self._decode_lanes()
        if not lanes:
            return False

        ctx = np.zeros((B,), np.int32)
        steps_arr = np.zeros((B,), np.int32)
        table = np.zeros((B, ec.max_blocks_per_seq), np.int32)
        temp = np.zeros((B,), np.float32)
        topk = np.zeros((B,), np.int32)
        topp = np.ones((B,), np.float32)
        meta = []
        for i, s in lanes:
            steps_i = min(K, s.remaining_pred)
            ctx[i] = s.ctx_pred
            steps_arr[i] = steps_i
            table[i, : len(s.blocks)] = s.blocks
            sp = s.req.sampling
            temp[i], topk[i], topp[i] = sp.temperature, sp.top_k, sp.top_p
            s.inflight_decode += steps_i
            # Keep the slot object: by reconcile time the index may host a
            # different request (zombie lane whose slot was reused).
            meta.append((i, s, steps_i))

        eos = jnp.asarray(self.eos_id, jnp.int32)
        all_greedy = all(s.req.sampling.temperature <= 0.0 for _, s in lanes)
        # Recomputed from the final lane set (preemption above may have
        # evicted the constrained lane): any constrained lane selects the
        # FSM program; its free co-lanes run masked-by-nothing at state 0.
        constrained = (self._fsm_trans is not None and any(
            s.req.sampling.constrained for _, s in lanes))
        try:
            self._faults.maybe_raise("decode_dispatch")
            payload, kind = self._dispatch_decode_call(
                spec and not constrained, all_greedy, lanes, K, ctx,
                steps_arr, table, temp, topk, topp, eos,
                constrained=constrained)
        except Exception as exc:
            # Nothing reached the device: undo the in-flight accounting so
            # the same lanes re-dispatch next step (ctx_pred derives from
            # inflight_decode, so it rewinds with it).
            for _, s, steps_i in meta:
                s.inflight_decode -= steps_i
            self._record_dispatch_failure(exc)
            return False
        self._record_dispatch_ok()
        if self._faults.should_fire("decode_stuck"):
            payload = _StuckPayload(payload)
        self._inflight.append(_Inflight(
            kind=kind, call_id=self._next_call_id, arr=payload, lanes=meta,
            t0=time.monotonic(),
            span_attrs={"steps": K, "lanes": len(lanes),
                        "constrained": constrained}))
        self._next_call_id += 1
        return True

    def _dispatch_decode_call(self, spec: bool, all_greedy: bool, lanes,
                              K: int, ctx, steps_arr, table, temp, topk,
                              topp, eos, constrained: bool = False):
        """The device-call half of :meth:`_dispatch_decode`, split out so
        the dispatch fault/rollback boundary wraps exactly the program
        call.  Returns ``(payload, kind)``."""
        ec = self.ecfg
        if constrained:
            # Grammar-masked fused decode: always the sampled program family
            # (greedy lanes take argmax-of-masked inside the sampler), FSM
            # state threaded through the scan carry and the device-resident
            # [max_slots] buffer, exactly like _tok_state.
            cap = ec.sample_topk_cap
            bounded = cap > 0 and all(
                0 < s.req.sampling.top_k <= cap
                for _, s in lanes if s.req.sampling.temperature > 0.0)
            prog = self._decode_program(K, sampled=True, bounded=bounded,
                                        constrained=True)
            self._rng, sub = jax.random.split(self._rng)
            toks, self._tok_state, self._fsm_state, self.pages = prog(
                self.params, self._tok_state, self._fsm_state,
                jnp.asarray(ctx), jnp.asarray(steps_arr), self.pages,
                jnp.asarray(table), self._fsm_trans, jnp.asarray(temp),
                jnp.asarray(topk), jnp.asarray(topp), sub, eos,
            )
            payload: Any = toks
            kind = "decode"
            self.steps += K
            try:
                toks.copy_to_host_async()
            except AttributeError:
                pass
            return payload, kind
        if spec:
            # Filters only matter on lanes that actually sample: a greedy
            # lane carrying top_p (a common client default) must not force
            # the filtered program variant (extra compile + per-round
            # full-vocab sorts the argmax rule never reads).
            any_filtered = any(
                s.req.sampling.temperature > 0.0
                and (s.req.sampling.top_k > 0 or s.req.sampling.top_p < 1.0)
                for _, s in lanes)
            prog = self._spec_program(ec.spec_k, ec.spec_rounds_per_iter,
                                      sampled=not all_greedy,
                                      filtered=any_filtered and not all_greedy)
            self._rng, sub = jax.random.split(self._rng)
            toks, self._tok_state, self.pages, self._hist, nver = prog(
                self.params, self._tok_state, jnp.asarray(ctx),
                jnp.asarray(steps_arr), self.pages, jnp.asarray(table),
                self._hist, jnp.asarray(temp), jnp.asarray(topk),
                jnp.asarray(topp), sub, eos,
            )
            payload: Any = (toks, nver)
            kind = "spec"
        elif all_greedy:
            prog = self._decode_program(K, sampled=False)
            toks, self._tok_state, self.pages = prog(
                self.params, self._tok_state, jnp.asarray(ctx),
                jnp.asarray(steps_arr), self.pages, jnp.asarray(table), eos,
            )
            payload = toks
            kind = "decode"
            self.steps += K
        else:
            # Bounded top-k sampling is exact only when every lane that
            # actually samples keeps at most sample_topk_cap tokens.
            cap = ec.sample_topk_cap
            bounded = cap > 0 and all(
                0 < s.req.sampling.top_k <= cap
                for _, s in lanes if s.req.sampling.temperature > 0.0)
            prog = self._decode_program(K, sampled=True, bounded=bounded)
            self._rng, sub = jax.random.split(self._rng)
            toks, self._tok_state, self.pages = prog(
                self.params, self._tok_state, jnp.asarray(ctx),
                jnp.asarray(steps_arr), self.pages, jnp.asarray(table),
                jnp.asarray(temp), jnp.asarray(topk), jnp.asarray(topp),
                sub, eos,
            )
            payload = toks
            kind = "decode"
            self.steps += K
        try:
            toks.copy_to_host_async()
        except AttributeError:
            pass
        return payload, kind

    # -- reconciliation -------------------------------------------------

    def _reconcile_one(self) -> None:
        call = self._inflight.popleft()
        budget = self.ecfg.dispatch_timeout_s
        if budget > 0 and not self._call_ready(call):
            # Watchdog: poll readiness instead of blocking in np.asarray —
            # a wedged device call must trip recovery, not hang the loop.
            t0 = time.monotonic()
            while not self._call_ready(call):
                if time.monotonic() - t0 >= budget:
                    self.watchdog_trips += 1
                    if self.health is not None:
                        self.health.record_watchdog_trip()
                    self._reset_pipeline(
                        f"dispatch watchdog: {call.kind} call not ready "
                        f"after {budget:.2f}s", extra_calls=(call,))
                    return
                time.sleep(0.002)
        if self._faults.should_fire("slow_host_callback"):
            time.sleep(self._faults.delay_s("slow_host_callback"))
        try:
            self._apply_call(call)
        except Exception as exc:
            # A failed host conversion (device error surfacing, injected
            # stuck payload with the watchdog off) poisons the donated
            # buffer chain: reset and recompute.
            self._record_dispatch_failure(exc)
            self._reset_pipeline(
                f"reconcile of {call.kind} call failed: {exc}",
                extra_calls=(call,))
            return
        # Release deferred frees that no in-flight call references anymore.
        if self._deferred_frees:
            still = []
            for after_id, blocks in self._deferred_frees:
                if after_id <= call.call_id:
                    self.allocator.free(blocks)
                else:
                    still.append((after_id, blocks))
            self._deferred_frees = still

    def _apply_call(self, call: _Inflight) -> None:
        """Convert one dispatched call's payload and apply it to slots
        (token emission, retirement, chunk/decode accounting)."""
        gap_t0 = time.monotonic()
        if call.kind == "spec":
            toks, stats = call.arr
            arr = np.asarray(toks)
            ran, lane_rounds = (int(x) for x in np.asarray(stats))
            self.spec_verify_steps += ran
            self.spec_lane_rounds += lane_rounds
            self.steps += ran
            if lane_rounds:
                # Per-class acceptance EMA drives the adaptive spec/fused
                # choice; the class is derived from the slots this call
                # actually ran (meta holds the slot objects, so reuse of
                # the lane index after dispatch cannot misattribute).
                self._spec_accept.update(
                    self._spec_class((i, s) for i, s, _ in call.lanes),
                    int(np.sum(arr >= 0)), lane_rounds)
        else:
            arr = np.asarray(call.arr)
        if call.kind in ("decode", "spec"):
            # Host time spent blocked on this device call: ~0 whenever
            # dispatch-ahead (or the ready-drain in step()) hid the device
            # latency.  EMA so /metrics shows the steady-state gap.
            gap_ms = (time.monotonic() - gap_t0) * 1e3
            self.decode_host_gap_ms = (
                gap_ms if self.decode_host_gap_ms == 0.0
                else 0.9 * self.decode_host_gap_ms + 0.1 * gap_ms)
        if call.kind in ("admit", "chunk"):
            now = time.monotonic()
            # Per-prefill-call wall time (dispatch -> reconcile), the
            # prefill twin of decode_host_gap_ms: an EMA across admission
            # and chunk rounds, surfaced as engine_prefill_attn_ms.
            pf_ms = max(0.0, now - call.t0) * 1e3
            self.prefill_attn_ms = (
                pf_ms if self.prefill_attn_ms == 0.0
                else 0.9 * self.prefill_attn_ms + 0.1 * pf_ms)
            for s in call.touched:           # chunk calls: drain refcounts
                s.inflight_chunks -= 1
            rows = (enumerate(call.lanes) if call.kind == "admit"
                    else ((row, (slot_idx, req))
                          for row, slot_idx, req in call.lanes))
            span_name = ("engine.prefill" if call.kind == "admit"
                         else "engine.prefill_chunk")
            for j, (slot_idx, req) in rows:
                s = self._slots[slot_idx]
                if s is None or s.req is not req:
                    continue  # preempted before reconcile
                tok = int(arr[j])
                s.pending_admit = False
                s.generated.append(tok)
                if req.first_token_time == 0.0:
                    req.first_token_time = now
                    self._observe_ttft(now - req.submit_time, req.slo_class,
                                       trace_id=self._trace_id(req))
                s.first_token_time = req.first_token_time
                self._span(span_name, call.t0, now, req,
                           constrained=req.sampling.constrained,
                           **call.span_attrs)
                self._emit(req, [tok])
                if self._is_finished(s) or s.cancel_requested:
                    self._retire(slot_idx)
        else:
            now = time.monotonic()
            span_name = ("engine.spec_decode" if call.kind == "spec"
                         else "engine.decode")
            # Satellite: the analytic collective share from the last
            # profile_decode_phases() run rides on every decode segment.
            coll = self.decode_collective_share
            for slot_idx, s, steps_i in call.lanes:
                if self._slots[slot_idx] is not s or s.retired:
                    continue  # lane EOSed in an earlier call; discard zombies
                new = [int(t) for t in arr[:, slot_idx] if t >= 0]
                s.inflight_decode -= steps_i
                if call.kind == "spec":
                    self.spec_tokens += len(new)
                if steps_i > 0:
                    self.hist_decode_step.observe(
                        max(0.0, now - call.t0) / steps_i,
                        s.req.slo_class, self._trace_id(s.req))
                attrs = {"steps": steps_i, "emitted": len(new)}
                if coll > 0.0:
                    attrs["collective_share"] = coll
                if call.kind == "spec":
                    attrs["rounds"] = self.ecfg.spec_rounds_per_iter
                self._span(span_name, call.t0, now, s.req, **attrs)
                if not new:
                    continue
                s.ctx_len += len(new)
                s.generated.extend(new)
                self._emit(s.req, new)
                if self._is_finished(s) or (s.cancel_requested
                                            and s.inflight_decode == 0):
                    self._retire(slot_idx)

    def _observe_ttft(self, ttft_s: float,
                      slo_class: str = DEFAULT_CLASS,
                      trace_id: str = "") -> None:
        self.hist_ttft.observe(ttft_s, slo_class, trace_id)
        for i, le in enumerate(self.ttft_buckets):
            if ttft_s <= le:
                self.ttft_counts[i] += 1
                break
        else:
            self.ttft_counts[-1] += 1
        self.ttft_sum += ttft_s
        self.ttft_count += 1
        prev = self.ttft_ema_by_class.get(slo_class)
        self.ttft_ema_by_class[slo_class] = (
            ttft_s if prev is None else 0.9 * prev + 0.1 * ttft_s)

    def _is_finished(self, s: _Slot) -> bool:
        return bool(s.generated) and (
            s.generated[-1] == self.eos_id
            or len(s.generated) >= s.req.sampling.max_tokens)

    def _retire(self, slot_idx: int) -> None:
        s = self._slots[slot_idx]
        assert s is not None
        now = time.monotonic()
        # Tokens generated before a preemption live in the folded prompt tail.
        toks = s.req.prompt_ids[s.req.orig_prompt_len:] + s.generated
        reason = "eos" if toks and toks[-1] == self.eos_id else "length"
        if reason == "eos":
            toks = toks[:-1]
        error = ""
        if s.abort_cause:
            # Deadline-aborted (or otherwise force-failed) slot: the result
            # carries the cause and whatever tokens were already streamed.
            reason, error = "error", s.abort_cause
        result = GenerationResult(
            request_id=s.req.request_id,
            token_ids=toks,
            finish_reason=reason,
            error=error,
            # A slot cancelled mid-prefill retires with no first token.
            ttft_s=(s.first_token_time - s.req.submit_time
                    if s.first_token_time > 0.0 else 0.0),
            latency_s=now - s.req.submit_time,
        )
        self._results[s.req.request_id] = result
        self.hist_e2e.observe(result.latency_s, s.req.slo_class,
                              self._trace_id(s.req))
        self._end_request_span(
            s.req, "error" if reason == "error" else "ok",
            finish_reason=reason, tokens=len(toks),
            ttft_s=round(result.ttft_s, 6))
        if self.token_sink is not None:
            self.token_sink(s.req.request_id, [], result)
        if self._inflight:
            # In-flight calls may still write into these pages (zombie
            # steps); free only after the newest dispatched call reconciles.
            self._deferred_frees.append(
                (self._next_call_id - 1, s.blocks))
        else:
            self.allocator.free(s.blocks)
        s.retired = True
        self._slots[slot_idx] = None

    def _preempt(self, slot_idx: int) -> None:
        """Evict a slot, folding generated tokens into a new prompt.

        Only called on reconciled state (_dispatch_decode drains in-flight
        work before preempting), so ``generated`` is complete."""
        s = self._slots[slot_idx]
        assert (s is not None and s.inflight_decode == 0
                and s.inflight_chunks == 0)
        self.allocator.free(s.blocks)
        self._slots[slot_idx] = None
        s.retired = True
        req = s.req
        # Already-sampled tokens become prompt; budget shrinks accordingly.
        consumed = len(s.generated)
        req.prompt_ids = req.prompt_ids + s.generated
        req.sampling = dataclasses.replace(
            req.sampling, max_tokens=max(1, req.sampling.max_tokens - consumed)
        )
        self._cap_request(req)  # re-apply the submit-time capacity cap
        self._pending.appendleft(req)
        self.preemptions += 1
        self.preemptions_by_class[req.slo_class] = (
            self.preemptions_by_class.get(req.slo_class, 0) + 1)
        t_now = time.monotonic()
        self._span("engine.preempt", t_now, t_now, req,
                   tokens_folded=consumed)
        self._flight.note("preempt", request_id=req.request_id,
                          slo_class=req.slo_class, tokens_folded=consumed)
