"""Host-side paged KV-cache block allocator.

The device-side page arrays live in models/llama.py (KVPages); this class
owns the free list and per-sequence block accounting.  Block id 0 is the
null block — masked lanes in prefill/decode scatter there — so it is never
handed out.

Deliberately simple (free-list LIFO, no copy-on-write / prefix sharing yet);
the continuous-batching engine calls alloc/extend/free on request admission,
block-boundary crossings, and completion.
"""

from __future__ import annotations


class OutOfBlocks(Exception):
    pass


class BlockAllocator:
    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))  # pop -> 1,2,...

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_for(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    def can_alloc(self, num_tokens: int) -> bool:
        return self.blocks_for(num_tokens) <= len(self._free)

    def alloc(self, num_tokens: int) -> list[int]:
        n = self.blocks_for(num_tokens)
        if n > len(self._free):
            raise OutOfBlocks(f"need {n} blocks, {len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def extend(self, blocks: list[int], new_len: int) -> None:
        """Grow ``blocks`` in place to cover ``new_len`` tokens."""
        need = self.blocks_for(new_len) - len(blocks)
        if need <= 0:
            return
        if need > len(self._free):
            raise OutOfBlocks(f"need {need} more blocks, {len(self._free)} free")
        for _ in range(need):
            blocks.append(self._free.pop())

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if b == 0:
                raise ValueError("attempt to free the null block")
            self._free.append(b)
        blocks.clear()
