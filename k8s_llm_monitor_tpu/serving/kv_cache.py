"""Host-side paged KV-cache block management: refcounted allocator + prefix
cache.

The device-side page arrays live in models/llama.py (KVPages); these classes
own the free list, per-block reference counts, and the prompt-prefix reuse
map.  Block id 0 is the null block — masked lanes in prefill/decode scatter
there — so it is never handed out.

Prefix sharing design (TPU-first, no copy-on-write needed):

  * Only *full* blocks covered entirely by a prompt are ever shared
    (``n = len(prompt) // block_size`` blocks, capped so at least one prompt
    token always remains unshared).  KV content of such a block is a pure
    function of the token prefix (absolute-position RoPE), so equal prefixes
    mean equal pages.
  * A sequence's writes always start at its first unshared position, which
    by construction lands in a privately-owned block — shared blocks are
    read-only for their entire lifetime, so reference counting alone is
    sound; there is no "first divergent write" to copy on.
  * The cache is an LRU over chain-hash keys: ``h_k = sha256(h_{k-1} ||
    block_k_token_bytes)``.  SHA-256 chaining makes the key itself the
    collision guard (Python's tuple hash is deterministic and adversarially
    constructible; a collision here would hand one request another's KV
    pages), and keeps registration O(L) — no per-entry token copies.
    Lookup walks the query's chain from the longest prefix down, so a hit
    reuses the longest cached prefix; eviction decrefs, and blocks still
    referenced by live slots survive.  LRU order lives in dict insertion
    order (touch = pop + reinsert), so eviction is O(1).
  * The chain seed is the caller's *tenant* namespace digest
    (``resilience.tenancy.tenant_seed``), not ``b""`` — two tenants hashing
    identical token prefixes produce disjoint digest chains, so a
    cross-tenant prefix hit is structurally impossible (the privacy
    invariant docs/resilience.md "Tenancy & quotas" states, and
    graftcheck's ``tenant-namespace`` rule gates at every call site).
    Eviction is fairness-aware: when one tenant's resident blocks exceed
    ``max_tenant_share`` of the cached total (and another tenant is
    present), pressure evicts *that tenant's* LRU entry first, so no
    tenant can monopolize the device pool.

Every diagnosis query shares the system preamble + evidence prefix
(monitor/analysis.py builds them), so at 100 concurrent the prefix is
prefilled once instead of 100 times — the reference has no inference at all
to cache (its LLM layer is config keys, reference
internal/config/config.go:141-145); this is a north-star obligation
(SURVEY.md §7 hard parts #1/#2).

Mesh invariant — page ids are GLOBAL:

  Under tensor parallelism the device-side page pool is sharded on the KV
  *head* dimension (parallel/sharding.py ``SpecLayout.kv_pages``), never on
  the page dimension.  Every chip therefore holds rows for *all*
  ``num_blocks`` pages — each row just covers that chip's 1/tp slice of the
  fused ``kv_heads * head_dim`` lane dim.  That is what lets everything in
  THIS module stay mesh-agnostic: one BlockAllocator free list, one
  PrefixCache, one page-table namespace serve every chip, block id ``b``
  names the same logical page on chip 0 and chip 7, and prefix-cache hits
  transfer across mesh shapes.  Nothing here may ever divide ``num_blocks``
  by the mesh size; capacity planning divides *bytes per page* instead
  (``page_slice_bytes``).
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.resilience.tenancy import DEFAULT_TENANT, tenant_seed


def shareable_blocks(n_tokens: int, block_size: int) -> int:
    """Full blocks of a prompt that may be published for prefix reuse,
    leaving >= 1 unshared token (the final prompt token must run through
    prefill to produce the first-token logits).  The single source of
    truth for the shareable-span rule — PrefixCache.lookup/register and
    the engine's admission deferral gate must agree on it exactly."""
    return min(n_tokens // block_size, (n_tokens - 1) // block_size)


def page_slice_bytes(num_kv_heads: int, head_dim: int, block_size: int,
                     dtype_bytes: int, tp: int = 1,
                     scale_bytes: int = 0) -> int:
    """Bytes ONE chip holds for ONE logical KV page (K + V) under
    head-dimension sharding.

    With ``tp`` dividing ``num_kv_heads`` each chip stores a
    ``kv_heads/tp`` slice of every page; otherwise the pool is replicated
    (parallel/sharding.py ``SpecLayout.kv_pages``) and every chip pays the
    full page.  Fit preflight multiplies this by ``num_blocks`` — the
    page-id namespace itself never shrinks with the mesh (global-ids
    invariant above).

    ``scale_bytes`` accounts for quantized pools: a per-token-per-head
    dequant scale array rides each of K and V (models/llama.py KVPages
    ``k_scale``/``v_scale``, f32 so scale_bytes=4), sharded on the same
    head boundaries as the pages themselves (``SpecLayout.kv_scales``)."""
    sharded = 1 < tp <= num_kv_heads and num_kv_heads % tp == 0
    heads = num_kv_heads // tp if sharded else num_kv_heads
    return (2 * block_size * heads * head_dim * dtype_bytes
            + 2 * block_size * heads * scale_bytes)


class OutOfBlocks(Exception):
    pass


class BlockAllocator:
    """Free-list allocator with per-block reference counts.

    ``alloc``/``extend`` hand out blocks at refcount 1; ``incref`` adds
    sharers; ``free`` decrements and returns a block to the free list only
    when its count reaches zero.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))  # pop -> 1,2,...
        self._refs: dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_for(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    def can_alloc(self, num_tokens: int) -> bool:
        return self.blocks_for(num_tokens) <= len(self._free)

    def alloc(self, num_tokens: int) -> list[int]:
        n = self.blocks_for(num_tokens)
        if get_injector().should_fire("alloc_exhaustion"):
            raise OutOfBlocks(
                f"injected exhaustion: need {n} blocks (fault point)")
        if n > len(self._free):
            raise OutOfBlocks(f"need {n} blocks, {len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def extend(self, blocks: list[int], new_len: int) -> None:
        """Grow ``blocks`` in place to cover ``new_len`` tokens."""
        need = self.blocks_for(new_len) - len(blocks)
        if need <= 0:
            return
        if get_injector().should_fire("alloc_exhaustion"):
            raise OutOfBlocks(
                f"injected exhaustion: need {need} more blocks (fault point)")
        if need > len(self._free):
            raise OutOfBlocks(f"need {need} more blocks, {len(self._free)} free")
        for _ in range(need):
            b = self._free.pop()
            self._refs[b] = 1
            blocks.append(b)

    def incref(self, blocks: list[int]) -> None:
        for b in blocks:
            if b == 0:
                raise ValueError("cannot share the null block")
            self._refs[b] += 1

    def ref_count(self, block: int) -> int:
        return self._refs.get(block, 0)

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if b == 0:
                raise ValueError("attempt to free the null block")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)
        blocks.clear()


@dataclasses.dataclass
class _PrefixEntry:
    blocks: tuple[int, ...]     # cache-owned refs (one per block)
    tenant: str = DEFAULT_TENANT  # namespace owner (fairness accounting)


class PrefixCache:
    """LRU map from token-prefix chain digests to shared KV blocks.

    All entries' blocks carry one cache-owned reference; ``lookup`` increfs
    the reused span for the caller, ``evict_lru`` releases the cache's own
    reference (live slots keep their pages).

    ``hits``/``misses`` are maintained by the engine at admission time (a
    lookup retried for a deferred request must not double-count).
    """

    def __init__(self, allocator: BlockAllocator, max_entries: int = 512,
                 max_tenant_share: float = 1.0):
        self.allocator = allocator
        self.max_entries = max_entries
        # Fairness cap: once >1 tenant is resident, a tenant holding more
        # than this fraction of the cached blocks becomes the preferred
        # eviction victim (1.0 = no cap).
        self.max_tenant_share = float(max_tenant_share)
        # Insertion-ordered: first key is always the LRU entry (touch =
        # pop + reinsert), so eviction never scans.
        self._entries: dict[bytes, _PrefixEntry] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _chain_digests(self, prompt_ids: list[int], n_blocks: int,
                       tenant: str) -> list[bytes]:
        """SHA-256 chain over block token bytes, seeded by the tenant's
        namespace digest: collision-proof AND tenant-disjoint keys, O(L)."""
        bs = self.allocator.block_size
        digests = []
        h = tenant_seed(tenant)
        for k in range(n_blocks):
            block = np.asarray(prompt_ids[k * bs:(k + 1) * bs], np.int64)
            h = hashlib.sha256(h + block.tobytes()).digest()
            digests.append(h)
        return digests

    def _shareable_blocks(self, prompt_ids: list[int]) -> int:
        return shareable_blocks(len(prompt_ids), self.allocator.block_size)

    def digest_chain(self, prompt_ids: list[int], n_blocks: int, *,
                     tenant: str) -> list[bytes]:
        """Public digest access: the host spill tier (serving/kv_tier.py)
        and the fleet migration path key their entries by the SAME chain
        digests lookup walks, so a demoted or migrated prefix is found by
        the identical probe that would have hit it on-device.  ``tenant``
        is keyword-required on purpose: every key derivation must name its
        namespace (graftcheck's ``tenant-namespace`` rule enforces it)."""
        return self._chain_digests(prompt_ids, n_blocks, tenant)

    def _touch(self, key: bytes, entry: _PrefixEntry) -> None:
        del self._entries[key]
        self._entries[key] = entry

    def lookup(self, prompt_ids: list[int], *,
               tenant: str) -> tuple[list[int], int]:
        """Longest cached prefix of ``prompt_ids`` in ``tenant``'s
        namespace (digests of other tenants can never match: the chains
        are seeded differently).

        Returns (shared block ids increfed for the caller, tokens covered).
        The caller owns one reference per returned block and must release
        it through ``BlockAllocator.free`` eventually.
        """
        n = self._shareable_blocks(prompt_ids)
        if n <= 0 or not self._entries:
            return [], 0
        digests = self._chain_digests(prompt_ids, n, tenant)
        for k in range(n, 0, -1):
            entry = self._entries.get(digests[k - 1])
            if entry is not None and len(entry.blocks) >= k:
                self._touch(digests[k - 1], entry)
                shared = list(entry.blocks[:k])
                self.allocator.incref(shared)
                return shared, k * self.allocator.block_size
        return [], 0

    def register(self, prompt_ids: list[int], blocks: list[int], *,
                 tenant: str) -> None:
        """Publish a prompt's full blocks for reuse (after its prefill has
        been dispatched — page contents are ordered by device data flow).

        One entry is stored per prefix length (a flattened trie), so a later
        prompt diverging mid-way still reuses the longest common span.  Each
        entry owns references on its own span; block i is held by every
        entry covering it and returns to the pool when all are evicted."""
        n = self._shareable_blocks(prompt_ids)
        if n <= 0:
            return
        digests = self._chain_digests(prompt_ids, n, tenant)
        for k in range(n, 0, -1):
            key = digests[k - 1]
            entry = self._entries.get(key)
            if entry is not None:
                self._touch(key, entry)
                continue
            while len(self._entries) >= self.max_entries:
                if not self.evict_lru():
                    return
            shared = blocks[:k]
            self.allocator.incref(shared)
            self._entries[key] = _PrefixEntry(tuple(shared), tenant)
        # Fairness cap: if this registration pushed the tenant over its
        # share (and someone else is resident), the tenant pays with its
        # OWN oldest entries — never another tenant's.
        while self._overshare_tenant() == tenant:
            if not self._evict_key(self._tenant_lru_key(tenant)):
                break

    def evictable_blocks(self) -> int:
        """Blocks an eviction sweep could return to the free list right
        now: those whose every reference is cache-owned (live slots pin
        theirs, and a pinned block survives eviction — ``free`` only
        decrefs).  One entry per prefix length means a block is covered by
        several entries; it is evictable iff its allocator refcount equals
        that coverage.  Tier-aware admission
        (engine.admission_headroom_tokens) counts these as capacity the
        spill path can deliver without losing cache content."""
        coverage: dict[int, int] = {}
        for entry in self._entries.values():
            for b in entry.blocks:
                coverage[b] = coverage.get(b, 0) + 1
        return sum(1 for b, n in coverage.items()
                   if self.allocator.ref_count(b) == n)

    def blocks_by_tenant(self) -> dict[str, int]:
        """Distinct resident blocks per tenant (tenant namespaces are
        disjoint, so the counts never double-book a block) — the fairness
        accounting behind the max-share cap and ``tenant_kv_blocks``."""
        per: dict[str, set[int]] = {}
        for entry in self._entries.values():
            per.setdefault(entry.tenant, set()).update(entry.blocks)
        return {t: len(s) for t, s in per.items()}

    def _overshare_tenant(self) -> str | None:
        """The tenant currently over its max-share cap (worst offender),
        or None.  Only meaningful with >= 2 resident tenants: a sole
        tenant using the whole cache victimizes nobody."""
        if self.max_tenant_share >= 1.0:
            return None
        per = self.blocks_by_tenant()
        if len(per) < 2:
            return None
        total = sum(per.values())
        if total <= 0:
            return None
        worst = max(per, key=lambda t: per[t])
        if per[worst] > self.max_tenant_share * total:
            return worst
        return None

    def _tenant_lru_key(self, tenant: str) -> bytes | None:
        """The oldest entry belonging to ``tenant`` (insertion order)."""
        for key, entry in self._entries.items():
            if entry.tenant == tenant:
                return key
        return None

    def _victim_key(self) -> bytes | None:
        """The entry the next eviction should take: an over-share tenant's
        own LRU when the fairness cap is tripped, the global LRU otherwise.
        ``peek_lru`` and ``evict_lru`` both route through this so the
        engine's spill-then-evict sequence stays coherent."""
        if not self._entries:
            return None
        offender = self._overshare_tenant()
        if offender is not None:
            key = self._tenant_lru_key(offender)
            if key is not None:
                return key
        return next(iter(self._entries))

    def _evict_key(self, key: bytes | None) -> bool:
        if key is None:
            return False
        entry = self._entries.pop(key)
        self.allocator.free(list(entry.blocks))
        self.evictions += 1
        return True

    def peek_lru(self) -> tuple[bytes, list[int]] | None:
        """The next eviction victim's (chain digest, block ids) without
        evicting or touching refcounts — the engine's host-spill wrapper
        reads the victim's pages off-device *before* calling ``evict_lru``
        so a pressured eviction demotes to the host tier instead of
        dropping."""
        key = self._victim_key()
        if key is None:
            return None
        return key, list(self._entries[key].blocks)

    def peek_lru_tenant(self) -> str | None:
        """Namespace owner of the next eviction victim (the spill wrapper
        tags the host-tier entry with it)."""
        key = self._victim_key()
        return self._entries[key].tenant if key is not None else None

    def evict_lru(self) -> bool:
        """Drop the next victim entry (the over-share tenant's LRU when the
        fairness cap is tripped, else the global LRU), releasing the
        cache's block references.  Returns False when the cache is empty."""
        return self._evict_key(self._victim_key())

    def clear(self) -> None:
        while self.evict_lru():
            pass
