"""Concurrent front-end for the inference engine.

``InferenceEngine`` is single-threaded by design (one thread owns device
state); ``EngineService`` wraps it in a background step-loop thread plus a
thread-safe submit API, so N concurrent callers (e.g. the HTTP server's
request threads) share prefill batches and decode steps instead of
serializing whole generations.  This is the concurrency layer the north-star
SLO needs: 100 concurrent diagnosis queries share the continuous batch
(BASELINE.md config #4).

Per-request ``RequestHandle``s deliver tokens as the engine fetches them from
device (streaming seam for SSE in monitor/server.py) and a final
``GenerationResult``.
"""

from __future__ import annotations

import atexit
import dataclasses
import itertools
import logging
import queue
import threading
import time
from typing import Callable, Iterator, Optional

from k8s_llm_monitor_tpu.devtools.lockcheck import guarded_by, make_lock
from k8s_llm_monitor_tpu.observability.flight import get_flight_recorder
from k8s_llm_monitor_tpu.observability.tracing import Tracer, get_tracer
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.resilience.faults import get_injector
from k8s_llm_monitor_tpu.resilience.health import HealthMonitor
from k8s_llm_monitor_tpu.resilience.retry import Backoff
from k8s_llm_monitor_tpu.resilience.slo import (
    DEFAULT_CLASS,
    BrownoutController,
    normalize_slo_class,
)
from k8s_llm_monitor_tpu.resilience.tenancy import (
    DEFAULT_TENANT,
    TenantGovernor,
    normalize_tenant,
)
from k8s_llm_monitor_tpu.serving.engine import (
    GenerationRequest,
    GenerationResult,
    InferenceEngine,
    SamplingParams,
)

__all__ = [
    "EngineService",
    "OverloadedError",  # re-export: defined in resilience/errors.py
    "RequestHandle",
]

logger = logging.getLogger("serving.service")


class RequestHandle:
    """Ticket for one in-flight generation.

    ``stream()`` yields token ids as they are generated (EOS excluded);
    ``result()`` blocks for the final GenerationResult.  Both may be used on
    the same handle from different threads.
    """

    def __init__(self, request_id: str, eos_id: int, cancel_fn=None):
        self.request_id = request_id
        self._eos_id = eos_id
        self._tokens: "queue.Queue[Optional[int]]" = queue.Queue()
        self._done = threading.Event()
        self._result: Optional[GenerationResult] = None
        self._cancel_fn = cancel_fn
        # Tokens delivered by a previous engine incarnation (supervisor
        # replay): already streamed to the caller, prepended to the final
        # result so token_ids stays the complete output.
        self._replay_prefix: list[int] = []

    def cancel(self) -> None:
        """Ask the engine to stop generating (client went away).  The final
        result still arrives (finish_reason per whatever completed)."""
        if self._cancel_fn is not None and not self._done.is_set():
            self._cancel_fn(self.request_id)

    # -- engine side ----------------------------------------------------

    def _push(self, toks: list[int], result: Optional[GenerationResult]) -> None:
        for t in toks:
            if t != self._eos_id:
                self._tokens.put(t)
        if result is not None:
            if self._replay_prefix:
                result = dataclasses.replace(
                    result,
                    token_ids=self._replay_prefix + list(result.token_ids))
            self._result = result
            self._done.set()
            self._tokens.put(None)  # stream sentinel

    # -- caller side ----------------------------------------------------

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield generated token ids until completion (EOS not yielded).

        ``timeout`` bounds the wait for each *next* token; on expiry a
        TimeoutError is raised (matching ``result()``'s contract)."""
        while True:
            try:
                tok = self._tokens.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"generation {self.request_id}: no token within "
                    f"{timeout}s") from None
            if tok is None:
                return
            yield tok

    def poll_token(self, timeout: Optional[float] = None) -> Optional[int]:
        """Single-step variant of ``stream()``: the next token id, or None
        once the stream has ended (idempotent — the end sentinel is re-armed
        so callers racing several handles may poll past it).  Raises
        TimeoutError when nothing arrives within ``timeout``; the fleet
        router uses that to multiplex a hedged pair of handles from one
        thread."""
        try:
            tok = self._tokens.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"generation {self.request_id}: no token within "
                f"{timeout}s") from None
        if tok is None:
            self._tokens.put(None)
            return None
        return tok

    def result(self, timeout: Optional[float] = None) -> GenerationResult:
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(
                f"generation {self.request_id} not done within {timeout}s")
        assert self._result is not None
        return self._result

    @property
    def done(self) -> bool:
        return self._done.is_set()


@guarded_by("_handles_lock", "_draining", "_dead", "shed_count",
            "shed_count_by_class", "_shed_streaks")
class EngineService:
    """Background step-loop over an ``InferenceEngine`` with thread-safe
    submission.  The loop thread is the only toucher of engine state; callers
    talk through a submission queue and per-request handles.

    Lifecycle hooks (serving/supervisor.py): ``on_death`` is called instead
    of failing the handles when the step loop dies, so a supervisor can
    rebuild the engine and replay the survivors; ``observer`` sees every
    (request_id, toks, result) delivery *before* the handle does, which is
    where the request journal checkpoints progress.
    """

    def __init__(self, engine: InferenceEngine,
                 health: HealthMonitor | None = None,
                 on_death: Callable[[str], None] | None = None,
                 brownout: BrownoutController | None = None,
                 governor: TenantGovernor | None = None):
        self.engine = engine
        # Per-tenant admission + quota accountant (resilience/tenancy.py).
        # Owned by the supervisor on single-replica roles so reservations
        # survive engine rebuilds; replicas behind a FleetRouter get None —
        # the router charges once per logical request, and a replica-level
        # governor would double-charge hedges and failover replays.
        self.governor = governor
        engine.token_sink = self._sink
        # One health monitor per service: the engine reports dispatch
        # failures / watchdog trips into it, submit() reports shed/admit,
        # and /health + /readyz read it.
        self.health = health or HealthMonitor()
        engine.health = self.health
        # Brownout ladder over the health state (resilience/slo.py): the
        # engine consults the level for spec-decode gating and batch
        # max_tokens clamping; the fleet/router tiers read it from stats.
        self.brownout = brownout or BrownoutController(self.health.state)
        engine.brownout = self.brownout.level
        self.on_death = on_death
        self.observer: Callable[
            [str, list[int], Optional[GenerationResult]], None] | None = None
        self._faults = get_injector()
        self._submissions: "queue.Queue[GenerationRequest]" = queue.Queue()
        self._cancels: "queue.Queue[str]" = queue.Queue()
        # Control-plane calls executed ON the step thread (the engine's
        # only legal toucher): prefix export/install for the fleet
        # migration path, tier stats snapshots.  Each item is
        # (fn, reply_queue); the reply carries ("ok", value) or
        # ("err", exc) back to the blocked caller.
        self._calls: "queue.Queue[tuple[Callable, queue.Queue]]" = (
            queue.Queue())
        self._cancelled: set[str] = set()
        self._handles: dict[str, RequestHandle] = {}
        self._ids = itertools.count()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._draining = False
        self.shed_count = 0
        self.shed_count_by_class: dict[str, int] = {}
        # Consecutive sheds per SLO class -> per-class Retry-After hints:
        # a shed batch caller backs off on the batch streak while the
        # interactive lane's hint stays at the base delay.
        self._shed_streaks: dict[str, int] = {}
        self._shed_backoff = Backoff(base_s=1.0, cap_s=8.0, jitter=0.0)
        self._dead: str | None = None  # set when the step loop dies
        # Step-loop liveness beat: refreshed every iteration; a stale beat
        # with work pending means the loop is wedged inside a dispatch
        # (supervisor's rebuild trigger alongside _dead).
        self.last_heartbeat = time.monotonic()
        # Created last: lockcheck's guarded_by treats writes before the
        # lock exists as construction, not races.
        self._handles_lock = make_lock("service.handles")
        self._thread = threading.Thread(
            target=self._run, name="engine-service", daemon=True)
        self._thread.start()
        # Interpreter shutdown kills daemon threads wherever they stand; a
        # step loop torn down inside an XLA call aborts the whole process
        # ("FATAL: exception not rethrown").  atexit runs before daemon
        # teardown, so stop the loop first — hosts that call stop()
        # themselves just make this a no-op.
        atexit.register(self.stop)

    # -- submission -----------------------------------------------------

    def _record_shed(self, slo_class: str = DEFAULT_CLASS,
                     request_id: str = "", reason: str = "",
                     trace_ctx=None, tenant: str = "") -> float:
        """Bump shed counters; returns a Retry-After hint that backs off
        with consecutive sheds *of this class* (reset by the class's next
        successful admit) — overloaded batch lanes escalate their hint
        without inflating the interactive lane's.  Also records the shed
        decision as an instant span and a flight-recorder event so a
        refusal shows up in the request's timeline."""
        with self._handles_lock:
            self.shed_count += 1
            self.shed_count_by_class[slo_class] = (
                self.shed_count_by_class.get(slo_class, 0) + 1)
            self._shed_streaks[slo_class] = (
                self._shed_streaks.get(slo_class, 0) + 1)
            streak = self._shed_streaks[slo_class]
        self.health.record_shed()
        if tenant and self.governor is not None:
            self.governor.note_shed(tenant)
        now = time.monotonic()
        get_tracer().record(
            "service.shed", now, now, trace_ctx, status="error",
            attrs={"request_id": request_id, "class": slo_class,
                   "reason": reason, "tenant": tenant})
        get_flight_recorder().note(
            "shed", request_id=request_id, slo_class=slo_class,
            reason=reason, tenant=tenant)
        return self._shed_backoff.delay(min(streak - 1, 4))

    def submit(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams | None = None,
        request_id: str | None = None,
        deadline_s: float = 0.0,
        force: bool = False,
        handle: RequestHandle | None = None,
        slo_class: str = DEFAULT_CLASS,
        tenant: str = DEFAULT_TENANT,
    ) -> RequestHandle:
        """Admit a generation request.

        ``force`` bypasses drain/shed/quota checks (supervisor replay: the
        request was already accepted once and must not be refused — or
        re-charged — on its way back in).  ``handle`` re-installs an
        existing RequestHandle under the same request id so a replayed
        request keeps streaming to the original caller with no token gap.
        ``slo_class`` orders admission, shedding, and eviction
        (resilience/slo.py); ``tenant`` is the quota/namespace owner
        (resilience/tenancy.py) — quota refusals raise a tenant-tagged
        OverloadedError *before* the SLO shed check, so an over-quota
        tenant's traffic never reaches the queue and cannot push a
        within-quota tenant into shedding.
        """
        slo_class = normalize_slo_class(slo_class)
        tenant = normalize_tenant(tenant)
        sampling = sampling or SamplingParams()
        # The id exists BEFORE any shed decision so every 429/503 body
        # carries it — a refused request is joinable with traces and
        # journal records even though it never reached the engine.
        if request_id is None:
            request_id = f"svc-{next(self._ids)}"
        # Trace context: join the caller's trace (HTTP handler thread set
        # it from ``traceparent``) or start a fresh one; the request's own
        # span is a child so engine phase spans nest under it.  None when
        # sampling is fully off — the engine then skips all span work.
        tracer = get_tracer()
        parent_ctx = tracer.current() or tracer.new_trace()
        trace_ctx = Tracer.child(parent_ctx) if parent_ctx is not None else None
        tracer.bind(request_id, trace_ctx)
        with self._handles_lock:
            dead = self._dead
            draining = self._draining
        if dead is not None:
            raise RuntimeError(f"engine service is dead: {dead}")
        if not force:
            if draining or self._stop.is_set():
                # Not retriable *here* — this replica is going away; the
                # client should retry against another replica.
                hint = self._record_shed(slo_class, request_id, "draining",
                                         trace_ctx, tenant)
                raise OverloadedError("draining", retriable=False,
                                      retry_after_s=hint,
                                      slo_class=slo_class,
                                      request_id=request_id,
                                      tenant=tenant)
            # Quota gate FIRST: over-quota work is refused before it can
            # occupy queue slots that would push should_shed() into
            # refusing a within-quota tenant.  Raises a tenant-tagged
            # OverloadedError (HTTP 429 + Retry-After) and reserves
            # max_tokens on success.
            if self.governor is not None:
                self.governor.admit(
                    tenant, request_id,
                    max_tokens=sampling.max_tokens,
                    prompt_bytes=len(prompt_ids) * 4,
                    slo_class=slo_class)
            # Prompt + first sampled token is the KV footprint admission
            # must eventually place (engine._admit_round allocates L+1) —
            # the tier-aware capacity clause checks it against headroom.
            reason = self.engine.should_shed(
                slo_class, need_tokens=len(prompt_ids) + 1)
            if reason:
                if self.governor is not None:
                    # SLO shed after a successful quota reservation:
                    # release the token reservation (nothing was
                    # generated) but keep the request-rate charge — a
                    # shed retry storm still counts against the tenant.
                    self.governor.settle(request_id)
                hint = self._record_shed(slo_class, request_id, reason,
                                         trace_ctx, tenant)
                raise OverloadedError(
                    reason,
                    queue_depth=self.engine.queue_depth,
                    queue_tokens=self.engine.queue_tokens,
                    retry_after_s=hint,
                    slo_class=slo_class,
                    request_id=request_id,
                    tenant=tenant)
        self.health.record_admit()
        with self._handles_lock:
            self._shed_streaks.pop(slo_class, None)
        if handle is None:
            handle = RequestHandle(request_id, self.engine.eos_id,
                                   cancel_fn=self._request_cancel)
        else:
            handle._eos_id = self.engine.eos_id
            handle._cancel_fn = self._request_cancel
        # Kept on the handle so _fail_all can close the request span when
        # the engine dies before retiring it (no orphan parents in the
        # trace even across a replica kill).
        handle.trace = trace_ctx
        with self._handles_lock:
            self._handles[request_id] = handle
        self._submissions.put(GenerationRequest(
            request_id=request_id,
            prompt_ids=list(prompt_ids),
            sampling=sampling,
            deadline_s=deadline_s,
            slo_class=slo_class,
            tenant=tenant,
            trace=trace_ctx,
        ))
        self._wake.set()
        return handle

    def submit_text(self, prompt: str,
                    sampling: SamplingParams | None = None) -> RequestHandle:
        tok = self.engine.tokenizer
        assert tok is not None, "engine has no tokenizer"
        return self.submit(tok.encode(prompt), sampling)

    def generate_text(self, prompt: str,
                      sampling: SamplingParams | None = None,
                      timeout: Optional[float] = None) -> str:
        """Submit and block for the decoded completion."""
        res = self.submit_text(prompt, sampling).result(timeout=timeout)
        if res.finish_reason == "error":
            raise RuntimeError(f"generation failed: {res.error}")
        tok = self.engine.tokenizer
        return tok.decode(res.token_ids)

    def _request_cancel(self, request_id: str) -> None:
        self._cancels.put(request_id)
        self._wake.set()

    # -- control plane ---------------------------------------------------

    def call(self, fn: Callable[[InferenceEngine], object],
             timeout: float = 30.0):
        """Run ``fn(engine)`` on the step-loop thread and return its value.

        The step thread is the sole toucher of engine/device state, so
        anything that reads or writes the KV pool outside the generate
        path — prefix export for the migration endpoint, host-tier
        installs, tier stats — must funnel through here rather than
        calling the engine from an HTTP thread.  Exceptions raised by
        ``fn`` propagate to the caller; the step loop survives them."""
        with self._handles_lock:
            dead = self._dead
        if dead is not None:
            raise RuntimeError(f"engine service is dead: {dead}")
        reply: "queue.Queue[tuple[str, object]]" = queue.Queue(maxsize=1)
        self._calls.put((fn, reply))
        self._wake.set()
        try:
            kind, value = reply.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"engine call not serviced within {timeout}s") from None
        if kind == "err":
            raise value  # type: ignore[misc]
        return value

    def _drain_calls(self) -> None:
        while True:
            try:
                fn, reply = self._calls.get_nowait()
            except queue.Empty:
                return
            try:
                out = ("ok", fn(self.engine))
            except Exception as exc:  # noqa: BLE001 — caller's exception
                out = ("err", exc)
            try:
                reply.put_nowait(out)
            except queue.Full:  # caller timed out and left; drop it
                pass

    # -- drain / shutdown -----------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting new work (submit() sheds with ``draining``) and
        wait for queued + inflight requests to finish and their streams to
        flush.  Returns True when fully drained within ``timeout``."""
        with self._handles_lock:
            self._draining = True
        self.health.set_draining(True)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._handles_lock:
                idle = not self._handles
            if (idle and self._submissions.empty()
                    and not self.engine.has_work):
                return True
            time.sleep(0.01)
        return False

    def stop(self, timeout: float = 10.0, drain_s: float = 0.0) -> None:
        """Stop the step loop.  ``drain_s > 0`` first drains gracefully
        (finish inflight, flush streams); any handle still unresolved when
        the loop exits is failed so no client blocks forever."""
        with self._handles_lock:
            self._draining = True  # no admission races the shutdown
            dead = self._dead
        if drain_s > 0 and dead is None:
            self.drain(timeout=drain_s)
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout)
        atexit.unregister(self.stop)
        with self._handles_lock:
            dead = self._dead
        if dead is None:
            self._fail_all("service stopped")
            self._fail_calls("service stopped")

    # -- loop -----------------------------------------------------------

    def _fail_handle(self, request_id: str, msg: str) -> None:
        result = GenerationResult(
            request_id=request_id, token_ids=[], finish_reason="error",
            ttft_s=0.0, latency_s=0.0, error=msg,
        )
        if self.governor is not None:
            # Failed before/without generating: settle refunds whatever
            # the reservation still holds beyond tokens already streamed.
            self.governor.settle(request_id)
        # Terminal outcome: the observer (journal) must tombstone it so a
        # restart doesn't resurrect an invalid/cancelled request.
        if self.observer is not None:
            try:
                self.observer(request_id, [], result)
            except Exception:  # noqa: BLE001 — observer must not kill the loop
                logger.exception("observer failed for %s", request_id)
        with self._handles_lock:
            handle = self._handles.pop(request_id, None)
        if handle is not None:
            handle._push([], result)

    def _drain_submissions(self) -> None:
        # Cancels first: a cancel aimed at a request still sitting in the
        # submission queue (never admitted to the engine) must release the
        # caller immediately, not after a full generation.
        while True:
            try:
                self._cancelled.add(self._cancels.get_nowait())
            except queue.Empty:
                break
        while True:
            try:
                req = self._submissions.get_nowait()
            except queue.Empty:
                break
            if req.request_id in self._cancelled:
                self._cancelled.discard(req.request_id)
                self._fail_handle(req.request_id, "cancelled before admission")
                continue
            try:
                self.engine.submit(req)
            except ValueError as exc:
                # Invalid request (empty prompt, bad sampling): fail its
                # handle instead of killing the step loop.
                self._fail_handle(req.request_id, str(exc))
        for rid in list(self._cancelled):
            # Unknown ids (already finished, duplicate cancel) are dropped;
            # the handle has already resolved either way.
            self.engine.cancel(rid)
            self._cancelled.discard(rid)

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                self.last_heartbeat = time.monotonic()
                self._faults.maybe_raise("step_loop_crash")
                self._drain_submissions()
                self._drain_calls()
                if self.engine.has_work:
                    self.engine.step()
                else:
                    # Idle: sleep until a submission arrives.
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
        except Exception as exc:  # engine is corrupt — fail or hand off
            msg = f"engine step failed: {exc!r}"
            with self._handles_lock:
                self._dead = msg
            self._fail_calls(msg)
            self.health.set_dead(msg)
            if self.on_death is not None:
                # A supervisor owns recovery: keep the handles alive so
                # their requests can be replayed on the rebuilt engine.
                # Exit quietly — the exception IS handled (by the rebuild),
                # so don't trip thread-excepthook noise.
                try:
                    self.on_death(msg)
                except Exception:  # noqa: BLE001 — dying thread, best effort
                    logger.exception("on_death callback failed")
                logger.warning("step loop dead, awaiting supervisor: %s", msg)
            else:
                self._fail_all(msg)
                raise

    def _fail_calls(self, msg: str) -> None:
        # Control calls that raced the death of the loop error out
        # immediately instead of blocking their callers until timeout.
        while True:
            try:
                _fn, reply = self._calls.get_nowait()
            except queue.Empty:
                return
            try:
                reply.put_nowait(
                    ("err", RuntimeError(f"engine service is dead: {msg}")))
            except queue.Full:
                pass

    def _fail_all(self, msg: str) -> None:
        # Failure edge: dump the flight recorder (span ring + recent
        # engine events) so the mass-failure has a postmortem timeline.
        # A clean stop with nothing in flight is not a failure — skip the
        # artifact so routine shutdowns don't litter the flight dir.
        with self._handles_lock:
            had_work = bool(self._handles)
        if had_work or not self._submissions.empty():
            get_flight_recorder().dump("fail_all", extra={"msg": msg})
        # Drain submissions that raced the death of the loop so their
        # handles fail instead of hanging until timeout.
        while True:
            try:
                self._submissions.get_nowait()
            except queue.Empty:
                break
        with self._handles_lock:
            handles = list(self._handles.values())
            self._handles.clear()
        now = time.monotonic()
        for h in handles:
            if self.governor is not None:
                # Terminal failure (no supervisor to replay): settle so
                # the tenant is only charged for tokens actually streamed.
                self.governor.settle(h.request_id)
            # The engine died before retiring this request, so its
            # "engine.request" span (the parent of any phase spans already
            # recorded) would never be emitted — close it here so the
            # trace has no orphan parents.
            ctx = getattr(h, "trace", None)
            if ctx is not None:
                get_tracer().record(
                    "engine.request", now, now, ctx, status="error",
                    span_id=ctx.span_id, parent_id=ctx.parent_id,
                    attrs={"request_id": h.request_id, "error": msg[:200]})
            h._push([], GenerationResult(
                request_id=h.request_id, token_ids=[], finish_reason="error",
                ttft_s=0.0, latency_s=0.0, error=msg,
            ))

    def _sink(self, request_id: str, toks: list[int],
              result: Optional[GenerationResult]) -> None:
        # Observer first, and outside the handles lock: the journal must
        # checkpoint tokens BEFORE they reach the caller (a token streamed
        # but never journaled would be re-generated on replay — a
        # duplicate), and the observer takes the supervisor's lock (lock
        # order: supervisor -> service, never the reverse).
        if self.observer is not None:
            try:
                self.observer(request_id, toks, result)
            except Exception:  # noqa: BLE001 — observer must not kill the loop
                logger.exception("observer failed for %s", request_id)
        # Quota accounting mirrors the journal's view: tokens are charged
        # as emitted (delivered once, here) and the reservation settles on
        # the terminal result — refunding reserved-but-ungenerated tokens.
        if self.governor is not None:
            if toks:
                self.governor.note_delivered(request_id, len(toks))
            if result is not None:
                self.governor.settle(request_id)
        with self._handles_lock:
            handle = self._handles.get(request_id)
            if result is not None:
                self._handles.pop(request_id, None)
        if handle is not None:
            handle._push(toks, result)
        if result is not None:
            # Results are delivered through handles; drop the engine's copy.
            self.engine.poll(request_id)

    def detach_handles(self) -> dict[str, RequestHandle]:
        """Hand every live handle to the supervisor (rebuild path): the
        dying service must not fail them — they will be re-attached to the
        replacement service via ``submit(handle=...)``."""
        with self._handles_lock:
            handles = dict(self._handles)
            self._handles.clear()
        return handles
